"""Zero-nvcc build for apex-tpu.

The reference (shawnwang18/apex ``setup.py :: ext_modules``) gates ~25 CUDA
extensions behind flags like ``--cpp_ext --cuda_ext --fmha``.  Here the compute
path is Pallas (JIT, no compile step); the only native code is an optional
plain-C++ host extension (``apex_tpu/csrc``) providing flat-buffer pack/unpack
parity with the reference's ``apex_C`` (csrc/flatten_unflatten.cpp).  Build it
with ``APEX_TPU_CPP_EXT=1 pip install .``; everything degrades gracefully to
pure Python/NumPy when absent.  North star: ``pip install .`` succeeds with
zero nvcc — there is no CUDA anywhere in this build.
"""
import os
from setuptools import setup, Extension

ext_modules = []
if os.environ.get("APEX_TPU_CPP_EXT", "0") == "1":
    ext_modules.append(
        Extension(
            "apex_tpu._apex_C",
            sources=["apex_tpu/csrc/flatten_unflatten.c"],
            extra_compile_args=["-O3"],
        )
    )
    ext_modules.append(
        Extension(
            "apex_tpu._gds_C",
            sources=["apex_tpu/csrc/async_io.c"],
            extra_compile_args=["-O3"],
        )
    )

setup(ext_modules=ext_modules)
