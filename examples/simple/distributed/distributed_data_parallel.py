"""Minimal data-parallel training over a device mesh (reference:
``examples/simple/distributed/distributed_data_parallel.py`` — the
smallest end-to-end DDP example: wrap the model, train, verify ranks
agree).

Mesh-native translation of the reference's ``torch.distributed.launch``
two-process recipe: ONE process, a 1-D ``data`` mesh over all local
devices, the per-device batch sharded by ``shard_map``, gradients averaged
by ``DistributedDataParallel.reduce_gradients`` (bucketed psum), and a
SyncBatchNorm layer whose batch statistics are computed over the GLOBAL
batch via the same mesh axis.

Run (any machine — 8 virtual devices on CPU):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python distributed_data_parallel.py
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import apex_tpu._jax_compat  # noqa: F401  (grafts jax.shard_map on old jax)

from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel, SyncBatchNorm

STEPS, LR, BATCH_PER_RANK, DIM, CLASSES = 20, 0.05, 8, 16, 4


def main():
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("data",))
    ndev = len(devices)
    print(f"mesh: {ndev} x {devices[0].device_kind}")

    bn = SyncBatchNorm(num_features=DIM)   # psum-Welford stats over "data"
    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(DIM, CLASSES) * 0.1, jnp.float32)
    bn_vars = bn.init(jax.random.key(0),
                      jnp.zeros((BATCH_PER_RANK, DIM)))
    params = {"w": w, "bn": bn_vars["params"]}
    ddp = DistributedDataParallel()

    # learnable synthetic task: label is recoverable from the features
    y = rng.randint(0, CLASSES, size=BATCH_PER_RANK * ndev)
    x = rng.randn(BATCH_PER_RANK * ndev, DIM).astype(np.float32) * 0.5
    x[np.arange(x.shape[0]), y % DIM] += 2.0
    x, y = jnp.asarray(x), jnp.asarray(y)

    def loss_fn(params, batch_stats, x, y):
        h, _ = bn.apply({"params": params["bn"],
                         "batch_stats": batch_stats},
                        x, mutable=["batch_stats"])
        logits = h @ params["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

    @functools.partial(jax.jit, donate_argnums=(0,))
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False)
    def train_step(params, batch_stats, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch_stats,
                                                  x, y)
        grads = ddp.reduce_gradients(grads)   # psum-mean over "data"
        params = jax.tree.map(lambda p, g: p - LR * g, params, grads)
        return jax.lax.pmean(loss, "data"), params

    losses = []
    batch_stats = bn_vars["batch_stats"]
    for step in range(STEPS):
        loss, params = train_step(params, batch_stats, x, y)
        losses.append(float(loss))
        if step % 5 == 0:
            print(f"step {step:3d} loss {losses[-1]:.4f}")
    print(f"final loss {losses[-1]:.4f} (first {losses[0]:.4f})")
    return losses


if __name__ == "__main__":
    main()
