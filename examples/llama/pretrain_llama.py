"""LLaMA-family pretraining over a tp x dp mesh (beyond-parity model:
``apex_tpu.models.LlamaModel`` — RMSNorm + RoPE + GQA + SwiGLU on the
same TP layers the GPT flagship uses).

The loop shows the decoder recipe composed with the parallel stack:
  * tensor parallelism inside attention (GQA kv shards) and SwiGLU,
  * data parallelism with psum gradient reduction,
  * fused Adam over the raveled per-rank parameters.

Synthetic data is next-token-predictable (cyclic sequences), so the
loss falls fast and the smoke test can assert learning.  Runs anywhere
(``--platform cpu`` uses the jax config path — on axon machines the
plugin overrides the ``JAX_PLATFORMS`` env var):

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python pretrain_llama.py --tp 2 --dp 2 --platform cpu
"""
from __future__ import annotations

import argparse
import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

sys.path.insert(0, __file__.rsplit("/", 3)[0])   # repo root on sys.path

from apex_tpu.ops.fused_update import fused_adam_flat
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import LlamaConfig, llama_model_provider
from apex_tpu.transformer.testing.standalone_llama import (
    reduce_llama_grads,
)
from apex_tpu.utils import tree_ravel


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="mesh LLaMA pretrain")
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=2)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--batch", type=int, default=4, help="per-dp-rank")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--platform", type=str, default=None)
    return p.parse_args(argv)


def cyclic_batch(rng, args, dp):
    """[dp, batch, seq] sequences with t[i+1] = t[i]+1 mod V."""
    starts = rng.integers(0, args.vocab, size=(dp, args.batch, 1))
    toks = (starts + np.arange(args.seq)[None, None, :]) % args.vocab
    return jnp.asarray(toks, jnp.int32)


def main(argv=None):
    args = parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    parallel_state.destroy_model_parallel()
    # dp is inferred as n_devices // tp — restrict the mesh to tp*dp
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        devices=jax.devices()[:args.tp * args.dp])
    mesh = parallel_state.get_mesh()
    cfg = LlamaConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        num_kv_heads=args.kv_heads, max_seq_length=args.seq)
    model = llama_model_provider(cfg)
    rng = np.random.default_rng(args.seed)

    def train(stream):
        """One rank's whole run: init, then a scan over the iteration
        stream (my dp shard of it).  Per-rank state — the sharded param
        tree raveled to one fused-Adam flat buffer — never crosses the
        shard_map boundary, so no per-leaf specs are needed."""
        params = model.init(jax.random.PRNGKey(args.seed + 1),
                            stream[0, 0])
        flat0, unravel = tree_ravel(params)
        master = flat0.astype(jnp.float32)

        def loss_fn(tree, tokens):
            labels = jnp.roll(tokens, -1, axis=1)
            return model.apply(tree, tokens, labels)

        def body(state, tokens):
            master, m, v, n = state
            tree = unravel(master.astype(flat0.dtype))
            loss, g_tree = jax.value_and_grad(loss_fn)(tree, tokens[0])
            # replicated-kv (MQA/GQA with kv_heads % tp != 0) wgrads
            # are per-rank partials — psum them over the tensor axis
            g_tree = reduce_llama_grads(g_tree, cfg)
            g = tree_ravel(g_tree)[0]
            g = jax.lax.pmean(g, parallel_state.DATA_AXIS)
            loss = jax.lax.pmean(loss, parallel_state.DATA_AXIS)
            p2, m2, v2 = fused_adam_flat(
                master, g.astype(jnp.float32), m, v, lr=args.lr,
                beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
                step=n + 1)
            return (p2, m2, v2, n + 1), loss

        state = (master, jnp.zeros_like(master), jnp.zeros_like(master),
                 jnp.zeros((), jnp.int32))
        _, losses = jax.lax.scan(body, state, stream)
        return losses

    stream = jnp.stack([cyclic_batch(rng, args, args.dp)
                        for _ in range(args.iters)])   # [it, dp, b, s]
    losses = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        train, mesh=mesh,
        in_specs=(P(None, parallel_state.DATA_AXIS),),
        out_specs=P()))(stream)
    losses = np.asarray(losses)
    for i in range(0, args.iters, max(1, args.iters // 4)):
        print(f"iter {i:3d}  loss {losses[i]:.4f}", flush=True)
    first, last = float(losses[0]), float(losses[-1])
    print(f"loss {first:.4f} -> {last:.4f}")
    return first, last


if __name__ == "__main__":
    main()
