"""LLaMA-family pretraining over a tp x dp mesh (beyond-parity model:
``apex_tpu.models.LlamaModel`` — RMSNorm + RoPE + GQA + SwiGLU on the
same TP layers the GPT flagship uses).

The loop shows the decoder recipe composed with the parallel stack:
  * tensor parallelism inside attention (GQA kv shards) and SwiGLU,
  * data parallelism with psum gradient reduction,
  * flat-native fused Adam (``optimizers.functional``): the fp32 flat
    master is the differentiation variable, so autodiff produces flat
    grads and the step has no pytree repacking.

Synthetic data is next-token-predictable (cyclic sequences), so the
loss falls fast and the smoke test can assert learning.  Runs anywhere
(``--platform cpu`` uses the jax config path — on axon machines the
plugin overrides the ``JAX_PLATFORMS`` env var):

  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python pretrain_llama.py --tp 2 --dp 2 --platform cpu
"""
from __future__ import annotations

import argparse
import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# abspath first: with a relative __main__.__file__ (plain
# `python pretrain_llama.py`) slicing path components off the raw value
# would compute a bogus repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))               # repo root on sys.path

from apex_tpu import train_step
from apex_tpu.optimizers import functional
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import LlamaConfig, llama_model_provider


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="mesh LLaMA pretrain")
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=2)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--batch", type=int, default=4, help="per-dp-rank")
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--xent-chunk", type=int, default=None,
                   help="token-chunk size for the fused LM-head+CE "
                        "(no [tokens, vocab/tp] logits transient). "
                        "Default reads APEX_TPU_XENT_CHUNK; 0 = unfused")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO: shard the fused-Adam master/moments 1/dp "
                        "over the data axis (reduce-scatter grads, "
                        "all-gather params; numerics match the dense "
                        "run)")
    p.add_argument("--platform", type=str, default=None)
    return p.parse_args(argv)


def cyclic_batch(rng, args, dp):
    """[dp, batch, seq] sequences with t[i+1] = t[i]+1 mod V."""
    starts = rng.integers(0, args.vocab, size=(dp, args.batch, 1))
    toks = (starts + np.arange(args.seq)[None, None, :]) % args.vocab
    return jnp.asarray(toks, jnp.int32)


def main(argv=None):
    args = parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    parallel_state.destroy_model_parallel()
    if args.tp * args.dp > len(jax.devices()):
        # a short mesh would shrink the data axis under the ZeRO step's
        # /dp mean (and the TP shards) — refuse rather than train wrong
        raise SystemExit(
            f"tp={args.tp} x dp={args.dp} needs {args.tp * args.dp} "
            f"devices, have {len(jax.devices())}")
    # dp is inferred as n_devices // tp — restrict the mesh to tp*dp
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        devices=jax.devices()[:args.tp * args.dp])
    mesh = parallel_state.get_mesh()
    cfg = LlamaConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        num_kv_heads=args.kv_heads, max_seq_length=args.seq,
        # None falls through to APEX_TPU_XENT_CHUNK inside the model
        fused_head_xent=args.xent_chunk)
    model = llama_model_provider(cfg)
    tx = functional.fused_adam(lr=args.lr, betas=(0.9, 0.999), eps=1e-8,
                               weight_decay=0.0)
    rng = np.random.default_rng(args.seed)
    # replicated-kv (MQA/GQA with kv_heads % tp != 0): each rank
    # backpropagates only its OWN q-heads' contribution to the shared
    # kv_proj weights — the true grad is the psum over the tensor axis
    # (same contract as ``standalone_llama.reduce_llama_grads``, applied
    # here to flat-grad slices so the step stays re-ravel-free)
    need_kv_psum = args.tp > 1 and cfg.kv_heads % args.tp != 0
    if args.zero and need_kv_psum:
        # the kv fixup indexes FULL-grad offsets; under ZeRO the grads
        # arrive pre-scattered as shards, so those offsets don't apply
        raise SystemExit(
            "--zero requires kv_heads % tp == 0 (the replicated-kv "
            "psum fixup operates on full-grad offsets, which do not "
            "exist in the reduce-scattered shard)")

    def train(stream):
        """One rank's whole run: init, then a scan over the iteration
        stream (my dp shard of it).  Per-rank state — the sharded param
        tree flattened into one functional fused-Adam FlatState — never
        crosses the shard_map boundary, so no per-leaf specs are needed.
        The fp32 flat master is the differentiation variable: autodiff
        produces flat grads, no per-step grad re-ravel exists."""
        params = model.init(jax.random.PRNGKey(args.seed + 1),
                            stream[0, 0])
        if args.zero:
            # ZeRO: the fp32 master SHARD is the differentiation
            # variable — the zero step all-gathers params into the
            # forward and autodiff's transpose reduce-scatters the flat
            # grads; per-rank optimizer state is 1/dp of the dense run
            zstep = train_step.make_train_step(
                lambda tree, tokens: model.apply(
                    tree, tokens[0], jnp.roll(tokens[0], -1, axis=1)),
                tx, zero=True)
            st0 = train_step.init_train_state(
                tx, params, shard=(parallel_state.DATA_AXIS, args.dp))
            _, losses = jax.lax.scan(zstep, st0, stream)
            return losses
        st0 = tx.init(params)
        kv_slices = [(off, size) for key, (off, size, _)
                     in train_step.leaf_offsets(params).items()
                     if "kv_proj" in key]

        def body(st, tokens):
            def flat_loss(flat):
                tree = st.unravel(flat.astype(st.flat_dtype))
                labels = jnp.roll(tokens[0], -1, axis=1)
                return model.apply(tree, tokens[0], labels)

            loss, g = jax.value_and_grad(flat_loss)(st.master)
            if need_kv_psum:
                for off, size in kv_slices:
                    leaf = jax.lax.dynamic_slice_in_dim(g, off, size)
                    leaf = jax.lax.psum(leaf, parallel_state.TENSOR_AXIS)
                    g = jax.lax.dynamic_update_slice_in_dim(
                        g, leaf, off, 0)
            g = jax.lax.pmean(g, parallel_state.DATA_AXIS)
            loss = jax.lax.pmean(loss, parallel_state.DATA_AXIS)
            return tx.update(st, g), loss

        _, losses = jax.lax.scan(body, st0, stream)
        return losses

    stream = jnp.stack([cyclic_batch(rng, args, args.dp)
                        for _ in range(args.iters)])   # [it, dp, b, s]
    losses = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        train, mesh=mesh,
        in_specs=(P(None, parallel_state.DATA_AXIS),),
        out_specs=P()))(stream)
    losses = np.asarray(losses)
    for i in range(0, args.iters, max(1, args.iters // 4)):
        print(f"iter {i:3d}  loss {losses[i]:.4f}", flush=True)
    first, last = float(losses[0]), float(losses[-1])
    print(f"loss {first:.4f} -> {last:.4f}")
    return first, last


if __name__ == "__main__":
    main()
