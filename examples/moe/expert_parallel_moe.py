"""Minimal expert-parallel MoE training over a device mesh.

Beyond reference parity (the reference has no MoE — SURVEY.md §2.4);
this is the EP sibling of
``examples/simple/distributed/distributed_data_parallel.py``: the
smallest end-to-end recipe showing the pieces a Megatron MoE user needs —

* ``initialize_model_parallel(expert_model_parallel_size_=...)`` carving
  the ``expert`` axis out of data parallelism,
* :class:`~apex_tpu.transformer.moe.MoELayer` dispatching tokens through
  an ``all_to_all`` over that axis,
* the SPLIT gradient reduction: dense params (router + head) average
  over ``("data", "expert")`` while each expert shard averages over
  ``data`` only — ``reduce_moe_grads`` does both,
* the router's load-balancing aux loss keeping experts alive.

Run (any machine — 8 virtual devices on CPU):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python expert_parallel_moe.py
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

import apex_tpu._jax_compat  # noqa: F401  (grafts jax.shard_map on old jax)

from jax import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoELayer, reduce_moe_grads

STEPS, LR = 80, 0.1
TOKENS_PER_RANK, HIDDEN, FFN, EXPERTS, TOP_K = 16, 16, 32, 4, 2
AUX_COEFF = 0.01


def main(expert_parallel_size: int = 2):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        expert_model_parallel_size_=expert_parallel_size)
    # the ep>1 parallel_state is this example's, not the process's:
    # leaving it initialized (even on a failure partway through) makes
    # every later axis_name=None reduction resolve to ('data', 'expert')
    # and fail in callers running their own mesh
    try:
        return _train(expert_parallel_size)
    finally:
        parallel_state.destroy_model_parallel()


def _train(expert_parallel_size):
    mesh = parallel_state.get_mesh()
    ep = expert_parallel_size
    dp = mesh.shape["data"]
    print(f"mesh: data={dp} x expert={ep} "
          f"({mesh.devices.size} x {mesh.devices.flat[0].device_kind})")

    moe = MoELayer(num_experts=EXPERTS, hidden_size=HIDDEN,
                   ffn_hidden_size=FFN, top_k=TOP_K,
                   expert_parallel_size=ep)

    # learnable synthetic task: the target is a fixed rotation of the
    # input, recoverable only if tokens actually reach working experts
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(dp * ep * TOKENS_PER_RANK, HIDDEN),
                    jnp.float32)
    rot = jnp.asarray(np.linalg.qr(rng.randn(HIDDEN, HIDDEN))[0],
                      jnp.float32)
    y = x @ rot

    def loss_fn(params, x, y):
        out, aux = moe.apply(params, x)
        mse = jnp.mean((out - y) ** 2)
        return mse + AUX_COEFF * aux["load_balancing_loss"], mse

    # Param placement: expert shards live distributed along the 'expert'
    # axis (dim 0 of each [E_local, ...] leaf stacks to the global E);
    # the router is replicated.  The spec tree expresses exactly that.
    import jax.tree_util as jtu

    struct = jax.eval_shape(
        # same layer config with ep=1: identical tree STRUCTURE, and an
        # ep>1 init would need axis_index (shard_map-only)
        lambda: moe.clone(expert_parallel_size=1).init(
            jax.random.key(0), jnp.zeros((4, HIDDEN), jnp.float32)))
    param_specs = jtu.tree_map_with_path(
        lambda path, _: P("expert") if any(
            isinstance(p, jtu.DictKey) and p.key == "experts"
            for p in path) else P(),
        struct)

    @functools.partial(jax.jit, donate_argnums=(0,))
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(param_specs, P(("data", "expert")),
                  P(("data", "expert"))),
        out_specs=(P(), param_specs), check_vma=False)
    def train_step(params, x, y):
        (_, mse), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, x, y)
        # router averages over (data, expert); expert shards over data
        grads = reduce_moe_grads(grads)
        params = jax.tree.map(lambda p, g: p - LR * g, params, grads)
        return jax.lax.pmean(mse, ("data", "expert")), params

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(("data", "expert")),),
        out_specs=param_specs, check_vma=False)
    def init_params(x):
        return moe.init(jax.random.key(0), x)

    params = init_params(x)
    losses = []
    for step in range(STEPS):
        loss, params = train_step(params, x, y)
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step:3d} mse {losses[-1]:.4f}")
    print(f"final mse {losses[-1]:.4f} (first {losses[0]:.4f})")
    return losses


if __name__ == "__main__":
    main()
