"""BERT MLM pretraining loop: standalone BERT + flat-native FusedLAMB +
dynamic loss scaling (BASELINE config 2's model/optimizer pairing — the
reference's BERT-large phase-1 recipe is amp O2 + FusedLAMB; here bf16
params with fp32 LAMB masters and the jit-carried scaler play that role).

Flat-native structure (matching the gpt example's one-program shape):
the whole run is ONE jitted ``lax.scan`` over pre-staged batches, built
by :func:`apex_tpu.train_step.train_loop` — the fp32 flat LAMB master is
the differentiation variable, so autodiff produces flat grads (no
per-step grad re-ravel), and the scaler's ``found_inf`` feeds the update
kernel's ``noop_flag`` in-program (no host sync anywhere in the step).

Synthetic MLM data (recoverable signal: masked positions' labels are a
deterministic function of their neighbors) so the smoke path needs no
corpus.  Scale the config up and shard the batch over a mesh for the real
thing; the model supports TP/SP via ``parallel_state``.

Run:  python pretrain_bert.py --iters 20
"""
from __future__ import annotations

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))               # repo root on sys.path

from apex_tpu import train_step
from apex_tpu.optimizers import functional
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import BertConfig, bert_model_provider


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="BERT MLM pretrain (apex_tpu)")
    p.add_argument("--hidden", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--iters", type=int, default=40)
    p.add_argument("--lr", type=float, default=5e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dropout", type=float, default=0.0,
                   help="hidden + attention dropout (the reference BERT "
                        "recipe uses 0.1; attention dropout runs "
                        "IN-KERNEL on the softmax probabilities). The "
                        "toy default stays 0 so the smoke run converges "
                        "in tens of steps")
    p.add_argument("--loss-scale", type=str, default="dynamic")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO dp-sharded optimizer state over a 'data' "
                        "mesh: the fp32 LAMB master + moments shard "
                        "1/dp per device, grads reduce-scatter, params "
                        "all-gather — same numerics as the dense run "
                        "(the dryrun 'zero' leg asserts it)")
    p.add_argument("--dp", type=int, default=None,
                   help="data-parallel width for --zero (default: all "
                        "local devices)")
    p.add_argument("--numerics", action="store_true",
                   help="drive the run host-side through "
                        "instrumented_train_loop(numerics=True): the "
                        "step gains in-program grad/param-norm + "
                        "update-ratio probes and the overflow autopsy "
                        "names any parameter leaf whose grads go "
                        "nonfinite (same ONE donated executable; "
                        "APEX_TPU_TELEMETRY=<dir> writes the JSONL + "
                        "Prometheus artifacts). Not combinable with "
                        "--zero here (the scanned zero run stays one "
                        "opaque executable)")
    p.add_argument("--platform", type=str, default=None,
                   help="force a jax platform (e.g. cpu); the axon TPU "
                        "plugin ignores JAX_PLATFORMS, so this calls "
                        "jax.config.update before any device query")
    args = p.parse_args(argv)
    if args.zero and args.numerics:
        p.error("--numerics drives a host-side step loop; the --zero "
                "run here is one scanned executable — run them "
                "separately")
    return args


def synthetic_mlm_batch(rng, args):
    """Masked-LM batches with a position-determined target (masked
    position ``p``'s label is ``(7*p + 13) % vocab``): solvable from the
    position embeddings alone, so the smoke run converges in tens of
    steps at toy scale, and every batch is FRESH — a falling loss means
    the model generalizes, not memorizes.  Swap in a real tokenized
    corpus (15% random masking, labels = original tokens) to pretrain for
    real; the training loop is identical."""
    tokens = rng.randint(4, args.vocab, size=(args.batch_size, args.seq))
    labels = np.full_like(tokens, -100)           # ignored positions
    n_mask = max(1, int(0.15 * args.seq))
    for i in range(args.batch_size):
        pos = rng.choice(np.arange(1, args.seq), size=n_mask,
                         replace=False)
        labels[i, pos] = (7 * pos + 13) % args.vocab
        tokens[i, pos] = 3                         # [MASK] id
    return jnp.asarray(tokens), jnp.asarray(labels)


def main(argv=None):
    args = parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = BertConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        max_seq_length=args.seq, hidden_dropout=args.dropout,
        attention_dropout=args.dropout, params_dtype=jnp.bfloat16)
    model = bert_model_provider(cfg, add_binary_head=False)

    rng = np.random.RandomState(args.seed)
    tokens0, labels0 = synthetic_mlm_batch(rng, args)
    params = model.init(jax.random.PRNGKey(args.seed), tokens0,
                        lm_labels=labels0)

    # vocab_parallel_cross_entropy has no ignore_index: weight the loss
    # to the masked positions via loss_mask (attention stays FULL — the
    # model must see the unmasked neighbors to solve the task)
    train_mode = args.dropout > 0.0

    def masked_lm_loss(params, tokens, labels, **apply_kw):
        valid = labels >= 0
        safe = jnp.where(valid, labels, 0)
        loss, _ = model.apply(params, tokens, lm_labels=safe,
                              loss_mask=valid.astype(jnp.int32),
                              **apply_kw)
        return loss

    def loss_fn(params, batch):
        apply_kw = (dict(deterministic=False,
                         rngs={"dropout": batch["key"]})
                    if train_mode else {})
        return masked_lm_loss(params, batch["tokens"], batch["labels"],
                              **apply_kw)

    # flat-native FusedLAMB: fp32 flat master of the bf16 params (the O2
    # regime) IS the differentiation variable; loss scaling, overflow
    # detection, and the noop-predicated update all run in-program
    tx = functional.fused_lamb(lr=args.lr, weight_decay=0.01,
                               max_grad_norm=1.0)
    loss_scale = (args.loss_scale if args.loss_scale == "dynamic"
                  else float(args.loss_scale))
    dp = args.dp or len(jax.devices())
    if args.zero:
        if dp > len(jax.devices()):
            # a short mesh would psum_scatter over fewer ranks than the
            # /dp mean assumes — silently wrong gradients, so refuse
            raise SystemExit(f"--zero: --dp {dp} exceeds the "
                             f"{len(jax.devices())} available devices")
        if args.batch_size % dp:
            raise SystemExit(f"--zero: batch size {args.batch_size} "
                             f"must divide over dp={dp}")
        # GLOBAL-view sharded state built outside; shard_map slices each
        # rank's 1/dp window via the returned spec tree
        state, state_specs = train_step.init_zero_train_state(
            tx, params, "data", dp, loss_scale=loss_scale)
    else:
        state = train_step.init_train_state(tx, params,
                                            loss_scale=loss_scale)

    heldout = synthetic_mlm_batch(rng, args)   # never trained on
    # all batches staged on-device up front: the whole run is one jitted
    # lax.scan (the gpt example's structure), so there is no per-step
    # host round-trip for a prefetcher to hide.  NOTE memory is
    # O(iters): for corpus-scale runs, chunk the stream and call the
    # jitted loop once per chunk (the carried TrainState composes)
    toks, labs = zip(*[synthetic_mlm_batch(rng, args)
                       for _ in range(args.iters)])
    batches = {"tokens": jnp.stack(toks), "labels": jnp.stack(labs)}
    if train_mode:
        dropout_root = jax.random.PRNGKey(args.seed + 1)
        batches["key"] = jax.vmap(
            lambda i: jax.random.fold_in(dropout_root, i))(
                jnp.arange(args.iters))
    if args.zero:
        # ZeRO run: the scan body is the zero step (psum_scatter'd bf16
        # grads -> local fused LAMB on the master shard -> all-gather'd
        # bf16 params into the next forward), the whole run still ONE
        # donated executable; the batch shards over the mesh's data
        # axis, so this IS data-parallel training, with optimizer state
        # 1/dp per device
        import functools
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))
        zstep = train_step.make_train_step(loss_fn, tx, zero=True)
        batch_specs = {"tokens": P(None, "data"),
                       "labels": P(None, "data")}
        if train_mode:
            batch_specs["key"] = P()
        run = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            lambda st, bs: jax.lax.scan(zstep, st, bs), mesh=mesh,
            in_specs=(state_specs, batch_specs),
            out_specs=(state_specs, P())), donate_argnums=(0,))
    elif args.numerics:
        # ISSUE 11: host-driven loop so the numerics probes have
        # somewhere to land between steps — same step math (parity
        # pinned by tests/L1/test_numerics_train_step.py), grad/param
        # norms + overflow autopsy resolved one step late
        run = train_step.instrumented_train_loop(
            loss_fn, tx, tokens_per_batch=args.batch_size * args.seq,
            numerics=True)
    else:
        run = train_step.train_loop(loss_fn, tx)
    state, losses = run(state, batches)
    losses = [float(l) for l in np.asarray(losses)]
    for it in range(0, args.iters, 5):
        print(f"iter {it:3d} loss {losses[it]:.4f}")
    # held-out eval is ALWAYS deterministic (dropout off), so the number
    # is comparable across dropout settings; one eager call on the
    # materialized params (the checkpoint/eval boundary) — a second jit
    # compile would never amortize
    final_params = state.params()
    heldout_loss = float(masked_lm_loss(final_params, heldout[0],
                                        heldout[1]))
    print(f"final loss {losses[-1]:.4f} (first {losses[0]:.4f}) "
          f"held-out {heldout_loss:.4f} "
          f"scale {float(state.scaler.loss_scale):.0f}")
    if args.numerics:                  # parse_args forbids it with --zero
        acc = run.telemetry.numerics
        fmt = lambda v: "—" if v is None else f"{v:.4g}"  # noqa: E731
        print(f"numerics: grad_norm {fmt(acc.grad_norm.value())} "
              f"param_norm {fmt(acc.param_norm.value())} "
              f"update_ratio {fmt(acc.update_ratio.value())} "
              f"backoffs {int(acc.backoffs.total())} "
              f"nonfinite_elems {int(acc.nonfinite_elems.total())}")
    return losses, heldout_loss


if __name__ == "__main__":
    main()
