"""Fleet front-door demo (ISSUE 19): three replicas, one submit().

Builds N tiny paged GPT engines with the host tier armed, wires them
under one :class:`~apex_tpu.fleet.FleetRouter`, and serves a skewed
tenant mix (each tenant re-sends its own long shared prefix with fresh
tails) through BOTH routing arms at equal aggregate HBM:

* ``round_robin`` stripes blindly, so every replica re-prefills every
  tenant's prefix into its own pool — duplicated pages, cold tails;
* ``prefix_affinity`` probes each replica's ACTUAL prefix tree
  (read-only ``peek_match`` + the swap-aware admission cost) and sends
  each tenant home, spilling off deep queues so affinity never starves
  a replica.

Prints per-arm hit rates, mean TTFT, the per-replica routing split,
and the three-level conservation law, then prices the fleet with the
capacity simulator (measured capture profile when one exists —
``unavailable:`` provenance is printed, never fabricated).

Runs anywhere::

    JAX_PLATFORMS=cpu python examples/fleet_serve.py
"""
from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))                # repo root on sys.path

from apex_tpu.fleet import (CAPACITY_DRIFT_TOLERANCE, build_fleet,
                            profile_from_captures, required_replicas)
from apex_tpu.inference import InferenceEngine
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu fleet demo")
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--waves", type=int, default=6)
    p.add_argument("--max-new-tokens", type=int, default=4)
    p.add_argument("--slo-ttft-us", type=float, default=20000.0,
                   help="TTFT p99 target the capacity sim prices")
    return p.parse_args(argv)


def build_engines(n):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return [InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                            page_size=8, num_pages=16,
                            host_tier_bytes=1 << 20)
            for _ in range(n)]


def serve_arm(policy, engines, prefixes, args):
    """One routing arm over FRESH schedulers (shared warm engines)."""
    fleet = build_fleet(engines, policy=policy)
    n_tenants = len(prefixes)
    for w in range(args.waves):
        for j in range(n_tenants):
            t = (w + j) % n_tenants           # rotate submission order
            prompt = prefixes[t] + [(w * 7 + t) % 64,
                                    (w * 11 + t + 1) % 64]
            fleet.submit(prompt, max_new_tokens=args.max_new_tokens,
                         tenant=f"tenant{t}")
        fleet.run()
    law = fleet.conservation()
    hits = sum(int(r.telemetry.prefix_hits.total())
               for r in fleet.replicas)
    served = sum(c["finished"] for c in law["replicas"])
    ttft_sum = sum(float(r.telemetry.ttft.sum())
                   for r in fleet.replicas) * 1e6
    ttft_n = sum(int(r.telemetry.ttft.count())
                 for r in fleet.replicas)
    split = [int(fleet.telemetry.routed.value(replica=str(i)) or 0)
             for i in range(len(engines))]
    return {"policy": policy, "hit_rate": hits / max(1, served),
            "ttft_us": ttft_sum / max(1, ttft_n), "split": split,
            "spills": int(fleet.telemetry.affinity_spills.total()),
            "holds": law["holds"]}


def main(argv=None):
    args = parse_args(argv)
    engines = build_engines(args.replicas)
    # one shared prefix per tenant, one more tenant than replicas so
    # the mix never tiles evenly (the skew affinity has to chase)
    prefixes = [
        [int(t) for t in (np.arange(16, dtype=np.int64) * (j + 3) + j)
         % 64]
        for j in range(args.replicas + 1)]

    # warm every program both arms dispatch (cold bucket, decode,
    # suffix chunk) so the first arm is not billed for the compiles
    from apex_tpu.inference import SlotScheduler
    for eng in engines:
        warm = SlotScheduler(eng)
        for tail in ((63, 62), (61, 60)):
            warm.submit(prefixes[0] + list(tail),
                        max_new_tokens=args.max_new_tokens)
            warm.run()

    print(f"{args.replicas} replicas x 2 slots, "
          f"{len(prefixes)} tenants, {args.waves} waves")
    for policy in ("round_robin", "prefix_affinity"):
        arm = serve_arm(policy, engines, prefixes, args)
        print(f"  {arm['policy']:16s} hit_rate={arm['hit_rate']:.3f} "
              f"ttft={arm['ttft_us']:8.0f}us "
              f"split={arm['split']} spills={arm['spills']} "
              f"conservation={'ok' if arm['holds'] else 'BROKEN'}")

    prof = profile_from_captures()
    req = required_replicas(
        prof, slots=2, slo_ttft_us=args.slo_ttft_us, n_requests=128,
        interarrival_us=1000.0, prompt_tokens=64, decode_tokens=4,
        seed=19)
    print(f"capacity sim ({req['provenance']}, drift tolerance "
          f"{CAPACITY_DRIFT_TOLERANCE}x): "
          f"replicas for TTFT p99 <= {args.slo_ttft_us:.0f}us -> "
          f"{req['replicas']}")


if __name__ == "__main__":
    main()
