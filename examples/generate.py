"""Text generation demo: the inference engine end to end.

Builds a small standalone GPT or LLaMA (optionally trained for a few
quick steps on cyclic synthetic data so greedy decoding has structure to
reproduce), then serves a batch of prompts through the full stack —
prefill into cache slots, continuous-batching decode, greedy or
temperature/top-k sampling — and prints the generated token streams plus
prefill/decode throughput.

Runs anywhere::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generate.py --model llama --kv-heads 2

With ``--train-steps N`` the demo first trains next-token prediction on
cyclic sequences (tok[i+1] = (tok[i] + 1) % vocab), so the generated
continuations visibly count upward — a one-glance correctness check.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))                # repo root on sys.path

from apex_tpu import observability as obs
from apex_tpu.inference import InferenceEngine, SamplingConfig, \
    SlotScheduler
from apex_tpu.optimizers import functional
from apex_tpu import train_step
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu generation demo")
    p.add_argument("--model", choices=("gpt", "llama"), default="gpt")
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="llama only: < heads for GQA, 1 for MQA")
    p.add_argument("--max-seq", type=int, default=128)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--page-size", type=int, default=None,
                   help="serve from a paged KV pool with this page "
                        "size (tokens, power of two) instead of the "
                        "dense slot cache")
    p.add_argument("--num-pages", type=int, default=None,
                   help="paged pool size (default: dense-equivalent "
                        "slots * max_seq / page_size)")
    p.add_argument("--straggler-demo", action="store_true",
                   help="serve a straggler-shaped workload through the "
                        "slot cache and a paged pool of the SAME KV "
                        "HBM and report how many requests each admits "
                        "concurrently")
    p.add_argument("--spec-k", type=int, default=None,
                   help="speculative decoding: drafted tokens per "
                        "decode round via the n-gram prompt-lookup "
                        "drafter (None reads APEX_TPU_SPEC_K; 0 off)")
    p.add_argument("--decode-fusion", default=None,
                   help="fused transformer-block decode: 0/1/auto "
                        "(paged engines; None reads "
                        "APEX_TPU_DECODE_FUSION)")
    p.add_argument("--prompts", type=int, default=6)
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--train-steps", type=int, default=150,
                   help="0 = serve random weights")
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def build_model(args):
    if args.model == "gpt":
        cfg = GPTConfig(
            vocab_size=args.vocab, hidden_size=args.hidden,
            num_layers=args.layers, num_attention_heads=args.heads,
            max_seq_length=args.max_seq, hidden_dropout=0.0,
            attention_dropout=0.0)
        return cfg, gpt_model_provider(cfg)
    cfg = LlamaConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.layers, num_attention_heads=args.heads,
        num_kv_heads=args.kv_heads, max_seq_length=args.max_seq)
    return cfg, llama_model_provider(cfg)


def quick_train(model, params, args):
    """A few flat-native fused-Adam steps on cyclic next-token data."""
    rng = np.random.RandomState(args.seed)
    seq = 32

    def loss_fn(p, batch):
        return model.apply(p, batch["tokens"], batch["labels"])

    tx = functional.fused_adam(lr=1e-2)
    state = train_step.init_train_state(tx, params)
    run = train_step.train_loop(loss_fn, tx)
    starts = rng.randint(0, args.vocab, size=(args.train_steps, 8, 1))
    tokens = (starts + np.arange(seq)[None, None, :]) % args.vocab
    batches = {"tokens": jnp.asarray(tokens, jnp.int32),
               "labels": jnp.asarray(np.roll(tokens, -1, axis=2),
                                     jnp.int32)}
    state, losses = run(state, batches)
    print(f"trained {args.train_steps} steps: loss "
          f"{float(losses[0]):.3f} -> {float(losses[-1]):.3f}")
    # the checkpoint boundary the engine consumes: bf16 export off the
    # fp32 flat master
    return state


def straggler_demo(args, cfg, params, sampling):
    """Admission capacity at EQUAL KV HBM, slot cache vs paged pool.

    The workload one 128K-context user inflicts on a serving fleet,
    shrunk to demo scale: the dense cache must provision every slot for
    ``max_seq``, so a fixed HBM budget buys only ``budget_slots``
    concurrent requests no matter how short they are.  The paged engine
    spends the SAME bytes on a page pool and admits by free pages — the
    short requests each pin only their own few pages, so many more run
    concurrently (``SlotScheduler.peak_active`` is the observable)."""
    from apex_tpu.inference import SlotScheduler

    budget_slots = 2                  # dense slots the HBM budget buys
    page_size = args.page_size or 16
    rng = np.random.RandomState(args.seed + 2)
    n_req = args.prompts
    short = max(4, args.max_seq // 8)   # mean_seq << max_seq
    prompts = [list(rng.randint(0, args.vocab, size=rng.randint(2, short)))
               for _ in range(n_req)]
    new_toks = 4

    def run(engine):
        sched = SlotScheduler(engine)
        for p in prompts:
            sched.submit(p, max_new_tokens=new_toks)
        sched.run()
        return sched.peak_active, engine.cache_hbm_bytes()

    dense = InferenceEngine(args.model, cfg, params, slots=budget_slots,
                            max_seq=args.max_seq, dtype=jnp.bfloat16,
                            sampling=sampling, seed=args.seed)
    # same HBM: the pool gets exactly the dense cache's pages
    num_pages = budget_slots * args.max_seq // page_size - 1  # -1: trash
    paged = InferenceEngine(args.model, cfg, params, slots=n_req,
                            max_seq=args.max_seq, page_size=page_size,
                            num_pages=num_pages, dtype=jnp.bfloat16,
                            sampling=sampling, seed=args.seed)
    d_peak, d_bytes = run(dense)
    p_peak, p_bytes = run(paged)
    print(f"straggler demo ({n_req} short requests <= {short} tokens, "
          f"max_seq {args.max_seq}):")
    print(f"  slot cache: {d_bytes} B KV HBM -> {d_peak} concurrent "
          f"(capped by {budget_slots} max_seq-deep slots)")
    print(f"  paged pool: {p_bytes} B KV HBM -> {p_peak} concurrent "
          f"(admitted by free {page_size}-token pages)")
    assert p_peak > d_peak, "paged admission should beat the slot cache"


def main(argv=None):
    args = parse_args(argv)
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg, model = build_model(args)
    params = model.init(jax.random.PRNGKey(args.seed),
                        jnp.zeros((1, 8), jnp.int32))

    sampling = SamplingConfig(temperature=args.temperature,
                              top_k=args.top_k)
    if args.straggler_demo:
        straggler_demo(args, cfg, params, sampling)
        return
    paged_kw = {}
    if args.page_size is not None or args.num_pages is not None:
        paged_kw = dict(page_size=args.page_size,
                        num_pages=args.num_pages)
    if args.spec_k is not None:
        paged_kw["spec_k"] = args.spec_k
    if args.decode_fusion is not None:
        paged_kw["decode_fusion"] = args.decode_fusion
    if args.train_steps:
        state = quick_train(model, params, args)
        engine = InferenceEngine.from_train_state(
            args.model, cfg, state, slots=args.slots,
            max_seq=args.max_seq, sampling=sampling, seed=args.seed,
            **paged_kw)
    else:
        engine = InferenceEngine(args.model, cfg, params,
                                 slots=args.slots, max_seq=args.max_seq,
                                 dtype=jnp.bfloat16, sampling=sampling,
                                 seed=args.seed, **paged_kw)

    rng = np.random.RandomState(args.seed + 1)
    prompts = []
    for _ in range(args.prompts):
        start = rng.randint(0, args.vocab)
        n = rng.randint(4, 12)
        prompts.append([(start + i) % args.vocab for i in range(n)])

    # serve through the scheduler explicitly (what engine.generate
    # wraps) so its telemetry is in hand; APEX_TPU_PROFILE_DIR=<dir>
    # drops a jax.profiler trace of the serve, APEX_TPU_TELEMETRY=<dir>
    # writes the JSONL event log + Prometheus file alongside
    sched = SlotScheduler(engine)
    t0 = time.perf_counter()
    with obs.profile_capture(tag="generate",
                             registry=sched.telemetry.registry):
        uids = [sched.submit(p, max_new_tokens=args.max_new_tokens)
                for p in prompts]
        out = sched.run()
    dt = time.perf_counter() - t0
    outs = [out[u] for u in uids]
    n_new = sum(len(o) for o in outs)
    for p, o in zip(prompts, outs):
        print(f"  prompt {p} -> {o}")
    print(f"{n_new} tokens in {dt:.2f}s "
          f"({n_new / dt:.1f} tok/s incl. compile)")
    print(f"telemetry: {json.dumps(sched.telemetry.summary())}")
    # SLO accounting (ISSUE 13): armed by APEX_TPU_SLO_TTFT_US /
    # APEX_TPU_SLO_DECODE_US; the scheduler closed one window per wave
    if sched.slo.specs:
        print(f"slo: {json.dumps(sched.slo.summary())}")
    if sched.telemetry.tracer.enabled():
        print("traces: APEX_TPU_TRACE armed — render a waterfall with "
              "`python -m apex_tpu.observability.report <telemetry "
              f"dir> --trace <uid>` (uids 0..{len(uids) - 1})")
    if args.train_steps and args.temperature == 0.0:
        want = [[(p[-1] + 1 + i) % args.vocab
                 for i in range(len(o))] for p, o in zip(prompts, outs)]
        hits = sum(o == w for o, w in zip(outs, want))
        print(f"cyclic continuation reproduced on {hits}/{len(outs)} "
              f"prompts")


if __name__ == "__main__":
    main()
