"""GPT pretraining over a tp x pp x dp device mesh — the flagship
`apex.transformer`-style driver (reference: the Megatron driver pattern
the reference's transformer README documents: ``initialize_model_parallel``
-> ``setup_microbatch_calculator`` -> ``get_forward_backward_func`` ->
schedule + grad reductions + optimizer).

Everything the parallel stack offers in one loop:
  * tensor parallelism inside each transformer layer (TP matmul shards),
  * 1F1B pipeline parallelism over the layer stack (bounded activations),
  * data parallelism with bucketed psum gradient reduction,
  * TIED input/output embeddings across the first/last stage with the
    masked-psum embedding-group reduction,
  * one flat-native fused Adam update (``optimizers.functional``) over
    the per-rank FlatState carried through the scan.

Synthetic data is next-token-predictable (cyclic sequences), so the loss
falls fast and the smoke test can assert learning.  Runs anywhere:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python pretrain_gpt.py --tp 2 --pp 2
"""
from __future__ import annotations

import argparse
import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))               # repo root on sys.path

from apex_tpu.ops.fused_lm_xent import (fused_lm_head_cross_entropy,
                                        xent_chunk_default)
from apex_tpu.optimizers import functional
from apex_tpu.parallel.distributed import flat_allreduce
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    embedding_grads_all_reduce,
    get_forward_backward_func,
    get_num_microbatches,
)
from apex_tpu.transformer.pipeline_parallel.utils import (
    _reconfigure_microbatch_calculator,
)
from apex_tpu.transformer.testing import GPTConfig
from apex_tpu.transformer.testing.standalone_gpt import (
    ParallelTransformerLayer,
)
from apex_tpu.utils import tree_ravel


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="mesh GPT pretrain (apex_tpu)")
    p.add_argument("--tp", type=int, default=2)
    p.add_argument("--pp", type=int, default=2)
    p.add_argument("--vpp", type=int, default=1,
                   help="virtual pipeline chunks per rank (interleaved "
                        "1F1B when > 1)")
    p.add_argument("--hidden", type=int, default=32)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--seq", type=int, default=16)
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--micro-batch-size", type=int, default=2)
    p.add_argument("--global-batch-size", type=int, default=16)
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--dropout", type=float, default=0.0,
                   help="hidden + attention dropout through the pipeline "
                        "(per-microbatch keys ride the batch pytree; the "
                        "attention part runs IN-KERNEL on the softmax "
                        "probabilities). Toy default 0 so the smoke run "
                        "converges fast")
    p.add_argument("--xent-chunk", type=int, default=None,
                   help="token-chunk size for the fused LM-head+CE "
                        "(the [tokens, vocab] logits never materialize; "
                        "backward re-projects per chunk). Default reads "
                        "APEX_TPU_XENT_CHUNK; 0 = unfused dense logits")
    p.add_argument("--zero", action="store_true",
                   help="ZeRO over the data axis: the flat fused-Adam "
                        "master/moments shard 1/dp per rank; the dp "
                        "grad all-reduce becomes reduce-scatter and "
                        "the per-step params materialize via "
                        "all-gather (numerics match the dense run)")
    p.add_argument("--platform", type=str, default=None,
                   help="force a jax platform (e.g. cpu)")
    return p.parse_args(argv)


def cyclic_batch(rng, args, n_micro, dp):
    """[n_micro, dp*micro_bs, seq] sequences with t[i+1] = t[i]+1 mod V —
    next-token prediction a 1-layer-per-stage model learns in a few
    dozen steps."""
    starts = rng.randint(0, args.vocab,
                         size=(n_micro, dp * args.micro_batch_size, 1))
    ramp = np.arange(args.seq)[None, None, :]
    tokens = (starts + ramp) % args.vocab
    labels = (tokens + 1) % args.vocab
    return jnp.asarray(tokens), jnp.asarray(labels)


def main(argv=None):
    args = parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    n_dev = len(jax.devices())
    dp = n_dev // (args.tp * args.pp)
    assert dp >= 1, f"need tp*pp <= {n_dev} devices"
    if args.vpp > 1 and args.pp <= 1:
        raise SystemExit(
            "--vpp > 1 requires --pp > 1 (virtual chunks interleave "
            "across pipeline ranks; with one rank there is nothing to "
            "interleave)")

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=args.tp,
        pipeline_model_parallel_size_=args.pp)
    mesh = parallel_state.get_mesh()
    # _reconfigure_* (vs setup_*) so repeated runs in one process work —
    # same helper the reference's tests use
    _reconfigure_microbatch_calculator(
        rank=0, rampup_batch_size=None,
        global_batch_size=args.global_batch_size,
        micro_batch_size=args.micro_batch_size,
        data_parallel_size=dp)
    n_micro = get_num_microbatches()
    fwd_bwd = get_forward_backward_func(
        virtual_pipeline_model_parallel_size=args.vpp,
        pipeline_model_parallel_size=args.pp)
    print(f"mesh: tp={args.tp} pp={args.pp} dp={dp} vpp={args.vpp} "
          f"micro-batches/step={n_micro} executor={fwd_bwd.__name__}")

    train_mode = args.dropout > 0.0
    cfg = GPTConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        num_layers=args.pp * args.vpp,
        num_attention_heads=args.heads, max_seq_length=args.seq,
        hidden_dropout=args.dropout, attention_dropout=args.dropout)
    layer = ParallelTransformerLayer(cfg, causal=True)
    tx = functional.fused_adam(lr=args.lr, betas=(0.9, 0.999), eps=1e-8,
                               weight_decay=0.0)

    def stage_fn(params, x, mb):
        # injection at VIRTUAL stage 0 only: rank 0 AND chunk 0 (the
        # chunk identity is a param leaf precisely so the interleaved
        # executor's per-chunk param slicing selects it)
        stage = jax.lax.axis_index("pipe") if args.pp > 1 else 0
        emb = jnp.take(params["embed"], mb["tokens"], axis=0)  # [b,s,h]
        emb = emb.transpose(1, 0, 2)                           # [s,b,h]
        inject = (stage == 0) & (params["chunk_id"] < 0.5)
        x = jnp.where(inject, emb, x)
        if not train_mode:
            return layer.apply(params["layer"], x, None, True)
        # dropout under pipelining (schedules.py contract): the
        # per-microbatch key rides the batch, the (stage, chunk) fold
        # decorrelates virtual stages, and the layer itself folds the
        # TP rank for its in-kernel attention dropout
        key = jax.random.fold_in(
            jax.random.fold_in(mb["key"], stage),
            params["chunk_id"].astype(jnp.int32))
        return layer.apply(params["layer"], x, None, False,
                           rngs={"dropout": key})

    xent_chunk = (args.xent_chunk if args.xent_chunk is not None
                  else xent_chunk_default())

    def loss_fn(y, mb, params):
        # TIED head: logits through the same embedding table (3-arg loss
        # contract so the head weight gets gradients)
        if xent_chunk and xent_chunk > 0:
            # fused chunked head+CE: the [s*b, vocab] logits never
            # materialize (forward scans token chunks; backward
            # re-projects each chunk and accumulates d_embed in the
            # scan carry)
            return fused_lm_head_cross_entropy(
                y, params["embed"], mb["labels"].T,
                token_chunk=xent_chunk).mean()
        logits = jnp.einsum("sbh,vh->sbv", y, params["embed"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, mb["labels"].T[..., None], axis=-1))

    def input_fn(mb):
        return jnp.zeros((args.seq, args.micro_batch_size, args.hidden))

    def body(all_batches):
        """Whole training run inside ONE shard_map: per-rank TP-sharded
        layer init (axis_index-folded keys), then lax.scan over steps —
        the sharded optimizer state never crosses the jit boundary."""
        x0 = jnp.zeros((args.seq, args.micro_batch_size, args.hidden),
                       dtype=jnp.float32)
        pipe_rank = jax.lax.axis_index("pipe") if args.pp > 1 else 0
        embed0 = jax.random.normal(            # replicated tied embedding
            jax.random.PRNGKey(args.seed + 1),
            (args.vocab, args.hidden)) * 0.02

        def chunk_params(chunk):
            key = jax.random.fold_in(jax.random.fold_in(
                jax.random.PRNGKey(args.seed), pipe_rank), chunk)
            return {
                "embed": embed0,
                "layer": layer.init(key, x0, None, True),
                "chunk_id": jnp.float32(chunk),
            }

        if args.vpp > 1:
            # leading [v] chunk dim; chunk c on rank r = virtual stage
            # c*pp + r (the interleaved executor's layout)
            params = jax.tree.map(lambda *xs: jnp.stack(xs),
                                  *[chunk_params(c)
                                    for c in range(args.vpp)])
        else:
            params = chunk_params(0)
        # flat-native functional Adam: ONE ravel at init; the scan
        # carries the FlatState, params rematerialize per step as
        # unravel slices that fuse into the forward.  Under --zero the
        # state is the local 1/dp shard and st.params() all-gathers.
        opt0 = tx.init(params,
                       shard=("data", dp) if args.zero else None)

        def one_step(carry, xs):
            st = carry
            step, batch = xs
            params = st.params()
            loss, grads = fwd_bwd(
                stage_fn, loss_fn, params, batch,
                num_microbatches=n_micro, input_fn=input_fn,
                virtual_pipeline_model_parallel_size=args.vpp)
            # tied-embedding reconciliation (first+last stage group
            # psum); with vpp the chunk contributions (lookup in chunk 0,
            # head in chunk v-1) sum first, and every replica receives
            # the reconciled total so they update in lockstep
            g_embed = grads["embed"]
            if args.vpp > 1:
                total = embedding_grads_all_reduce(g_embed.sum(axis=0))
                g_embed = jnp.broadcast_to(total, g_embed.shape)
            else:
                g_embed = embedding_grads_all_reduce(g_embed)
            grads["embed"] = g_embed
            if args.zero:
                # ZeRO-2: the dp all-reduce becomes ONE reduce-scatter
                # into my master shard's window (+ the dp mean)
                flat_g, _ = tree_ravel(grads)
                return tx.update(
                    st, functional.shard_flat_grads(flat_g, st)), loss
            if dp > 1:
                grads = flat_allreduce(grads, axis_name="data")
                grads = jax.tree.map(lambda g: g / dp, grads)
            # the pipeline executor produces grads per-leaf, so ONE
            # ravel per step remains here; the params side needs none
            flat_g, _ = tree_ravel(grads)
            return tx.update(st, flat_g), loss

        steps = jnp.arange(args.iters)
        _, losses = jax.lax.scan(
            one_step, opt0, (steps, all_batches))
        # fwd_bwd psums the loss over 'pipe' only; average the dp shards
        # so the reported metric is the GLOBAL-batch loss (and the P()
        # out-spec's replication claim actually holds)
        return jax.lax.pmean(losses, "data")

    batch_specs = {"tokens": P(None, None, "data"),
                   "labels": P(None, None, "data")}
    if train_mode:
        batch_specs["key"] = P()         # keys are replicated, not sharded
    run = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(batch_specs,),
        out_specs=P()))

    rng = np.random.RandomState(args.seed)
    toks, labs = zip(*[cyclic_batch(rng, args, n_micro, dp)
                       for _ in range(args.iters)])
    all_batches = {"tokens": jnp.stack(toks), "labels": jnp.stack(labs)}
    if train_mode:
        # one key per (step, microbatch), sliced by the executors like
        # any other batch leaf
        all_batches["key"] = jax.vmap(jax.vmap(jax.random.PRNGKey))(
            (args.seed + jnp.arange(args.iters * n_micro,
                                    dtype=jnp.uint32))
            .reshape(args.iters, n_micro))
    losses = [float(l) for l in np.asarray(run(all_batches))]
    for it in range(0, args.iters, 5):
        print(f"iter {it:3d} loss {losses[it]:.4f}")
    print(f"final loss {losses[-1]:.4f} (first {losses[0]:.4f})")
    return losses


if __name__ == "__main__":
    main()
