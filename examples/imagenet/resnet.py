"""Self-contained torch ResNet (the dev image has no torchvision).

Mirrors torchvision's ResNet v1 exactly (the model
``examples/imagenet/main_amp.py`` in the reference pulls from
``torchvision.models``): conv-bn stem, four bottleneck/basic stages,
average pool, fc.
"""
from __future__ import annotations

import torch
import torch.nn as nn

__all__ = ["resnet18", "resnet50"]


def _conv3(cin, cout, stride=1):
    return nn.Conv2d(cin, cout, 3, stride=stride, padding=1, bias=False)


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = _conv3(cin, planes, stride)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = _conv3(planes, planes)
        self.bn2 = nn.BatchNorm2d(planes)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + idt)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = _conv3(planes, planes, stride)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + idt)


class ResNet(nn.Module):
    def __init__(self, block, layers, num_classes=1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias=False),
                nn.BatchNorm2d(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        layers += [block(self.inplanes, planes) for _ in range(1, blocks)]
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


def resnet18(num_classes=1000):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes)


def resnet50(num_classes=1000):
    return ResNet(Bottleneck, [3, 4, 6, 3], num_classes)
