"""ImageNet training entry point (reference:
``examples/imagenet/main_amp.py`` — the canonical end-to-end Apex example:
``amp.initialize`` + ``amp.scale_loss`` around a ResNet training loop).

Differences from the reference, by environment design:
* model comes from the local ``resnet.py`` (no torchvision in the image);
* ``--synthetic`` trains on generated data so the smoke path (BASELINE
  config 0: ResNet-50, ``--opt-level O0``, CPU, loss decreases) needs no
  dataset on disk;  with a data dir the standard ImageFolder pipeline is
  used when torchvision is available;
* O2/O3 cast to bfloat16 (TPU-native half) rather than float16.

Run:  python main_amp.py --synthetic -b 8 --iters 20 --opt-level O0
"""
from __future__ import annotations

import argparse
import sys
import time

import torch
import torch.nn as nn

sys.path.insert(0, __file__.rsplit("/", 3)[0])   # repo root on sys.path

from apex_tpu import amp
from examples.imagenet.resnet import resnet18, resnet50


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="PyTorch ImageNet training with apex_tpu.amp")
    p.add_argument("data", nargs="?", default=None,
                   help="path to dataset (omit with --synthetic)")
    p.add_argument("--arch", "-a", default="resnet50",
                   choices=["resnet18", "resnet50"])
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("-b", "--batch-size", type=int, default=8)
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=1e-4)
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--opt-level", type=str, default="O0")
    p.add_argument("--loss-scale", type=str, default=None)
    p.add_argument("--keep-batchnorm-fp32", type=str, default=None)
    p.add_argument("--synthetic", action="store_true",
                   help="generated data (no dataset needed)")
    p.add_argument("--iters", type=int, default=None,
                   help="cap steps per epoch (smoke tests)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--seed", type=int, default=0)
    return p.parse_args(argv)


def synthetic_loader(args):
    """Deterministic fake-data batches with learnable signal: the label is
    recoverable from the image so the loss can actually decrease."""
    g = torch.Generator().manual_seed(args.seed)
    n_batches = args.iters or 10
    batches = []
    for _ in range(n_batches):
        target = torch.randint(0, args.num_classes, (args.batch_size,),
                               generator=g)
        images = torch.randn(args.batch_size, 3, args.image_size,
                             args.image_size, generator=g) * 0.1
        # plant a class-dependent mean so the task is learnable
        images += (target.float() / args.num_classes
                   ).view(-1, 1, 1, 1)
        batches.append((images, target))
    return batches


def main(argv=None, return_state=False):
    """Train; returns the per-iteration loss trace, plus (with
    ``return_state=True``) the final fp32 parameter vectors — the hooks the
    cross-run comparison tier uses to assert O0/O1/O2/O3 runs track each
    other (reference: ``tests/L1/common/compare.py``)."""
    args = parse_args(argv)
    torch.manual_seed(args.seed)

    model = {"resnet18": resnet18, "resnet50": resnet50}[args.arch](
        num_classes=args.num_classes)
    criterion = nn.CrossEntropyLoss()
    optimizer = torch.optim.SGD(model.parameters(), args.lr,
                                momentum=args.momentum,
                                weight_decay=args.weight_decay)

    loss_scale = args.loss_scale
    if loss_scale is not None and loss_scale != "dynamic":
        loss_scale = float(loss_scale)
    keep_bn = args.keep_batchnorm_fp32
    if isinstance(keep_bn, str):
        keep_bn = {"True": True, "False": False}.get(keep_bn, None)

    model, optimizer = amp.initialize(
        model, optimizer, opt_level=args.opt_level,
        keep_batchnorm_fp32=keep_bn, loss_scale=loss_scale)

    if args.synthetic or args.data is None:
        loader = synthetic_loader(args)
    else:  # pragma: no cover - needs torchvision + dataset on disk
        import torchvision.datasets as datasets
        import torchvision.transforms as transforms
        ds = datasets.ImageFolder(
            args.data,
            transforms.Compose([
                transforms.RandomResizedCrop(args.image_size),
                transforms.ToTensor(),
            ]))
        loader = torch.utils.data.DataLoader(
            ds, batch_size=args.batch_size, shuffle=True)

    losses = []
    model.train()
    for epoch in range(args.epochs):
        t0 = time.time()
        for i, (images, target) in enumerate(loader):
            if args.iters is not None and i >= args.iters:
                break
            output = model(images)
            loss = criterion(output.float(), target)
            optimizer.zero_grad()
            with amp.scale_loss(loss, optimizer) as scaled_loss:
                scaled_loss.backward()
            optimizer.step()
            losses.append(loss.item())
            if i % args.print_freq == 0:
                print(f"Epoch {epoch} [{i}] loss {loss.item():.4f} "
                      f"({(i + 1) / (time.time() - t0):.2f} it/s)")
    print(f"final loss {losses[-1]:.4f} (first {losses[0]:.4f})")
    if return_state:
        state = [p.detach().float().cpu().numpy()
                 for p in model.parameters()]
        return losses, state
    return losses


if __name__ == "__main__":
    main()
