"""On-chip chunked-fused-LM-head+CE experiment queue for the next
healthy tunnel window (r9, ISSUE 9): fused-vs-unfused A/Bs on the
``xent_fused`` leg plus the flagship GPT train leg with the fused head
on, so every capture carries the measured wall time NEXT TO the APX215
peak-live model stamps (``xent_fused_peak_live_bytes`` /
``xent_unfused_peak_live_bytes``) and the knob provenance
(``xent_chunk`` / ``xent_vocab_chunk``) — the modeled memory win and
the measured recompute cost land in the same artifact.

Same discipline as ``r8_overlap_experiments.py``: every experiment
drives a REAL ``bench.py`` leg in its own subprocess, results are
rewritten after EVERY experiment, and re-runs resume.

What these answer:

1. Chunk sweep at the flagship head shape (8192 x 1024 x 51200, where
   the unfused bf16 logits alone are 800 MiB fwd + the softmax
   residual bwd): where does the per-chunk dispatch/recompute overhead
   cross the HBM-traffic win — on TPU the fused path should WIN wall
   time too once the unfused logits spill (the CPU dryrun can only
   show the memory model, its fused leg pays the scan overhead at toy
   shapes).
2. Vocab-chunked inner scan (online logsumexp) at chunk=512: does the
   [C, Vc] transient shrink cost measurable time vs the [C, V] one.
3. The end-to-end flagship: the GPT main leg at a seq/batch that the
   unfused head cannot fit (the config whose logits exceed the HBM
   budget) with ``xent_chunk=512`` — the capture that demonstrates
   training a config the dense path cannot reach.

Usage:  python bench_captures/r9_xent_fused_experiments.py [--quick]
Writes: bench_captures/r9_xent_fused_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r9_xent_fused_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # chunk sweep on the dedicated A/B leg (each row re-times the
    # unfused twin so the pair shares a session)
    ("xent_c256", ["--leg", "xent_fused", "--override",
                   "xent_chunk=256"], 900),
    ("xent_c512", ["--leg", "xent_fused", "--override",
                   "xent_chunk=512"], 900),
    ("xent_c1024", ["--leg", "xent_fused", "--override",
                    "xent_chunk=1024"], 900),
    # vocab-chunked inner scan at the sweep's winner-so-far (6400
    # divides the leg's 51200 vocab — a power of two would not)
    ("xent_c512_vc6400", ["--leg", "xent_fused", "--override",
                          "xent_chunk=512", "--override",
                          "xent_vocab_chunk=6400"], 900),
    # end-to-end flagship GPT train leg, fused head on (the unfused
    # run of the same leg is every committed r1-r8 capture)
    ("gpt_fused_head", ["--leg", "main", "--override",
                        "xent_chunk=512"], 2400),
    # the memory-headline config: batch x seq pushed to where the
    # UNFUSED [tokens, vocab] logits alone exceed single-chip HBM
    # (16 x 2048 x 51200 fp32 logits = 6.4 GiB) — trains only fused
    ("gpt_fused_head_big", ["--leg", "main", "--override",
                            "xent_chunk=512", "--override", "batch=16",
                            "--override", "seq=2048"], 2400),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *args],
            capture_output=True, text=True, timeout=timeout, cwd=str(REPO))
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {json.dumps(results[key])[:200]}", flush=True)
    clean = all(
        results.get(k) and not ({"_error", "_timeout"} & set(results[k]))
        for k, _, _ in EXPERIMENTS)
    if not quick and clean:
        print("ALL_COMPLETE", flush=True)


if __name__ == "__main__":
    main()
