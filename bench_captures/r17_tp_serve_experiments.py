"""On-chip tensor-parallel serving experiment queue for the next
healthy multi-chip tunnel window (r17, ISSUE 17): paged infer-leg runs
through the engine's tp-sharded shard_map executables that land the
sharded-vs-single-chip per-token decode latency next to the comm-model
stamps (``exposed_comm_model_us`` / ``overlap_step_time_model_us``) and
the per-rank HBM accounting (``infer_hbm_cache_bytes_tp``) in the same
capture as the knob provenance (``infer_serve_tp``).

Same discipline as ``r15_fused_spec_experiments.py``: every experiment
drives a REAL ``bench.py`` leg in its own subprocess, results are
rewritten after EVERY experiment, and re-runs resume.

What these answer:

1. Decode scaling: the CPU dryrun can only show the capture shape and
   the comm-model estimate (host-device collectives are loopback — the
   measured step there is meaningless); on chips,
   ``infer_decode_token_us_tp`` vs ``infer_decode_token_us`` is the
   real ~1/tp compute-scaling check, with ``exposed_comm_model_us``
   separating the modeled exposed-psum tax from the compute win.
2. HBM headroom: ``infer_hbm_cache_bytes_tp`` (per RANK) at the
   flagship shape vs one chip's HBM — the capacity case for serving a
   model that cannot fit a single chip (the acceptance criterion's
   arithmetic, measured).
3. Fusion under sharding: the fused-block A/B rides the same leg
   (``APEX_TPU_DECODE_FUSION=1``) with the 1/tp weight shard resident
   — the ``fused_vmem_model_bytes`` stamp prices the sharded envelope,
   so the fusion cap's predicted move UP under tp is checked against
   the observed win at hidden sizes the unsharded kernel cannot fuse.

Usage:  python bench_captures/r17_tp_serve_experiments.py [--quick]
Writes: bench_captures/r17_tp_serve_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r17_tp_serve_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # single-chip baseline at the flagship paged shape, for the A-leg
    ("infer_paged_tp1", ["--leg", "infer", "--override", "paged=1"],
     1200),
    # the tentpole: sharded decode at tp=2 and tp=4 (same shape — the
    # infer_decode_token_us_tp vs baseline ratio is the scaling curve)
    ("infer_paged_tp2", ["--leg", "infer", "--override", "paged=1",
                         "--override", "tp=2"], 1500),
    ("infer_paged_tp4", ["--leg", "infer", "--override", "paged=1",
                         "--override", "tp=4"], 1500),
    # longer sequences: more pages per request => the sharded pool's
    # per-rank capacity win grows while decode stays page-streamed
    ("infer_tp2_seq2048", ["--leg", "infer", "--override", "paged=1",
                           "--override", "tp=2",
                           "--override", "seq=2048"], 1800),
    # fused-block decode under sharding: the 1/tp-resident kernel at a
    # hidden size near the unsharded fusion cap (PERF.md round-16's
    # ~2048 crossover — sharded, the static model says it fuses)
    ("infer_tp2_fused", ["--leg", "infer", "--override", "paged=1",
                         "--override", "tp=2",
                         "env:APEX_TPU_DECODE_FUSION=1"], 1500),
    # knob-path provenance: the SAME tp=2 leg armed via the env knob
    # instead of the override (serve_tp precedence: override > env)
    ("infer_tp2_env_knob", ["--leg", "infer", "--override", "paged=1",
                            "env:APEX_TPU_SERVE_TP=2"], 1500),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    import os
    env, cleaned = None, []
    for a in args:
        if a.startswith("env:"):
            env = dict(env or os.environ)
            name, _, val = a[4:].partition("=")
            env[name] = val
        else:
            cleaned.append(a)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *cleaned],
            capture_output=True, text=True, timeout=timeout,
            cwd=str(REPO), env=env)
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {'ERROR ' + res['_error'] if '_error' in res else 'ok'}",
              flush=True)
    print(f"results: {OUT}")


if __name__ == "__main__":
    main()
