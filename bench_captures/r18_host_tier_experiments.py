"""On-chip tiered-KV serving experiment queue for the next healthy
tunnel window (r18, ISSUE 18): paged infer-leg runs that land the
hot-but-evicted TTFT (swap-in uploads from the host tier) next to the
cold-prefill and warm-hit TTFTs in the same capture as the effective
tier knobs (``infer_host_tier_bytes`` / ``infer_swap_batch_pages``)
and the swap traffic counters (``infer_swap_in_pages`` /
``infer_swap_out_pages`` / ``infer_prefix_host_hits``).

Same discipline as ``r17_tp_serve_experiments.py``: every experiment
drives a REAL ``bench.py`` leg in its own subprocess, results are
rewritten after EVERY experiment, and re-runs resume.

What these answer:

1. Swap-in vs recompute: the CPU dryrun already shows
   ``infer_prefix_hot_evicted_ttft_us`` under the cold TTFT in
   interpret mode; on chips the gap is the real PCIe-upload-vs-prefill
   race — the acceptance criterion's arithmetic, measured.  The
   warm-hit TTFT bounds it from below (HBM-resident pages cost no
   upload at all).
2. Batch sizing: the swap copy programs are fixed-width (one
   executable per direction), so ``APEX_TPU_SWAP_BATCH_PAGES`` trades
   dispatch count against padding waste — the 4/8/16 sweep finds the
   knee at real host-link bandwidth.
3. Sharded swap invariance: under tp=2 each rank offloads its own
   1/tp kv-head shard and the host books stay replicated — the tier
   stamps must match the tp=1 run page-for-page while
   ``measured_tp_rank_step_skew`` (profiler armed, deferred tp trace
   ingest) reports the measured straggler ratio next to APX217's
   HLO-analysis estimate (ROADMAP item 1 leftover).
4. Longer prefixes: seq=2048 multiplies pages per prefix, so the
   swap batch pipelining (uploads overlapped with chunked prefill of
   the tail) has real work to hide — the chunked-prefill knob rides
   the same leg.

Usage:  python bench_captures/r18_host_tier_experiments.py [--quick]
Writes: bench_captures/r18_host_tier_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r18_host_tier_experiments_out.json"
PROF = REPO / "bench_captures" / "r18_profiles"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # the tentpole at the flagship paged shape: hot-but-evicted TTFT
    # vs cold prefill vs warm hit, default 64 MiB budget / batch 8
    ("infer_tier_default", ["--leg", "infer", "--override", "paged=1"],
     1200),
    # env-knob provenance: the SAME leg with the budget armed via
    # APEX_TPU_HOST_KV_TIER_BYTES (precedence: override > env > 64MiB)
    ("infer_tier_env_knob", ["--leg", "infer", "--override", "paged=1",
                             "env:APEX_TPU_HOST_KV_TIER_BYTES=134217728"],
     1200),
    # swap-batch sweep: dispatch count vs padding waste at real
    # host-link bandwidth (8 is the shipped default)
    ("infer_tier_batch4", ["--leg", "infer", "--override", "paged=1",
                           "env:APEX_TPU_SWAP_BATCH_PAGES=4"], 1200),
    ("infer_tier_batch16", ["--leg", "infer", "--override", "paged=1",
                            "env:APEX_TPU_SWAP_BATCH_PAGES=16"], 1200),
    # sharded swap invariance + the measured straggler skew: tp=2 with
    # the profiler armed — the deferred tp trace ingest stamps
    # measured_tp_rank_step_skew / measured_tp_step_us next to
    # exposed_comm_model_us in the same capture
    ("infer_tier_tp2_skew", ["--leg", "infer", "--override", "paged=1",
                             "--override", "tp=2",
                             f"env:APEX_TPU_PROFILE_DIR={PROF}"], 1800),
    # longer prefixes: more pages per swap, real overlap to hide
    ("infer_tier_seq2048", ["--leg", "infer", "--override", "paged=1",
                            "--override", "seq=2048"], 1800),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    import os
    env, cleaned = None, []
    for a in args:
        if a.startswith("env:"):
            env = dict(env or os.environ)
            name, _, val = a[4:].partition("=")
            env[name] = val
        else:
            cleaned.append(a)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *cleaned],
            capture_output=True, text=True, timeout=timeout,
            cwd=str(REPO), env=env)
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {'ERROR ' + res['_error'] if '_error' in res else 'ok'}",
              flush=True)
    print(f"results: {OUT}")


if __name__ == "__main__":
    main()
