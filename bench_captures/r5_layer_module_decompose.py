"""Time the real layer modules fwd+bwd at the north-star shape:
ParallelTransformerLayer, ParallelAttention, ParallelMLP, FusedLayerNorm.
Scratch diagnostic."""
import json
import time

import jax
import jax.flatten_util
import jax.numpy as jnp


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def bench_module(model, params, x, iters, r, extra=()):
    flat, unravel = jax.flatten_util.ravel_pytree(params)

    def loss(fp, x):
        out = model.apply(unravel(fp), x, *extra)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    @jax.jit
    def loop(fp, x):
        def body(c, _):
            l, gs = jax.value_and_grad(loss, argnums=(0, 1))(
                fp, x + jnp.asarray(c, x.dtype) * 1e-30)
            bump = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in gs)
            return c + bump * 1e-30 + l * 0, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    return round(timed(loop, (flat, x), iters, r) * 1e6, 1)


def main():
    from apex_tpu.normalization import FusedLayerNorm
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig
    from apex_tpu.transformer.testing.standalone_gpt import (
        ParallelAttention, ParallelMLP, ParallelTransformerLayer)

    r = rtt()
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    iters = 50
    b, s = 32, 128
    cfg = BertConfig(max_seq_length=s, hidden_dropout=0.0,
                     attention_dropout=0.0,
                     params_dtype=jnp.bfloat16).gpt_cfg()
    x = jax.random.normal(jax.random.PRNGKey(0), (s, b, cfg.hidden_size),
                          jnp.bfloat16)
    out = {}

    layer = ParallelTransformerLayer(cfg, causal=False)
    p = layer.init(jax.random.PRNGKey(1), x)
    out["layer_us"] = bench_module(layer, p, x, iters, r)
    print("layer", out["layer_us"], flush=True)

    attn = ParallelAttention(cfg, causal=False)
    p = attn.init(jax.random.PRNGKey(1), x)
    out["attention_us"] = bench_module(attn, p, x, iters, r)
    print("attention", out["attention_us"], flush=True)

    mlp = ParallelMLP(cfg)
    p = mlp.init(jax.random.PRNGKey(1), x)
    out["mlp_us"] = bench_module(mlp, p, x, iters, r)
    print("mlp", out["mlp_us"], flush=True)

    ln = FusedLayerNorm(normalized_shape=cfg.hidden_size)
    p = ln.init(jax.random.PRNGKey(1), x)
    out["ln_us"] = bench_module(ln, p, x, iters, r)
    print("ln", out["ln_us"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
