"""On-chip fleet front-door experiment queue for the next healthy
tunnel window (r19, ISSUE 19): fleet-leg runs that land the
prefix_affinity vs round_robin A/B (``fleet_affinity_hit_rate`` /
``fleet_affinity_ttft_us`` against the ``fleet_round_robin_*``
control, equal aggregate HBM by construction) next to the capacity
simulator's calibration block (``fleet_capacity_pred_ttft_us`` /
``fleet_capacity_measured_ttft_us`` / ``fleet_capacity_drift_ratio``)
and the effective knob stamps (``fleet_replicas`` / ``fleet_policy``).

Same discipline as ``r18_host_tier_experiments.py``: every experiment
drives a REAL ``bench.py`` leg in its own subprocess, results are
rewritten after EVERY experiment, and re-runs resume.

What these answer:

1. Affinity vs striping at real prefill cost: the CPU dryrun already
   shows affinity winning both axes in interpret mode; on chips the
   gap is real prefill FLOPs saved vs pages re-materialized — the
   acceptance criterion's arithmetic, measured.
2. Scale in replicas: 2 -> 4 replicas with the SAME per-replica pool
   stresses the coprime prefix rotation harder (5 prefixes over 4
   replicas) — affinity's win should widen as round_robin duplicates
   each prefix across more pools.
3. Policy knob provenance: the SAME leg with the policy armed via
   APEX_TPU_FLEET_POLICY (stamped as ``fleet_policy``) and the
   replica count via APEX_TPU_FLEET_REPLICAS (stamped as
   ``fleet_replicas``) — env vs override precedence on chip.
4. Capacity drift at real service times: the queued-calibration
   drift ratio re-measured where prefill/decode latencies are real —
   the watch trends ``fleet_capacity_drift_ratio`` downward from
   whatever this window achieves (tolerance envelope 2.0).
5. Longer prefixes: seq=2048 multiplies pages per prefix, so
   affinity's page-reuse advantage and round_robin's duplication cost
   both scale up — the contrast at serving-realistic prefix sizes.

Usage:  python bench_captures/r19_fleet_experiments.py [--quick]
Writes: bench_captures/r19_fleet_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r19_fleet_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # the tentpole A/B at the flagship shape: 2 replicas, default knobs
    ("fleet_default", ["--leg", "fleet"], 1800),
    # replica scale: 4 replicas x the same pool, 5 rotating prefixes
    ("fleet_replicas4", ["--leg", "fleet", "--override", "replicas=4"],
     2400),
    # env-knob provenance: the SAME leg armed via the env registry's
    # knobs (precedence: override > env > defaults)
    ("fleet_env_knobs", ["--leg", "fleet",
                         "env:APEX_TPU_FLEET_REPLICAS=2",
                         "env:APEX_TPU_FLEET_POLICY=prefix_affinity"],
     1800),
    # longer prefixes: more pages per prefix, bigger reuse stakes
    ("fleet_seq2048", ["--leg", "fleet", "--override", "seq=2048",
                       "--override", "prefix_len=1024"], 2400),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    import os
    env, cleaned = None, []
    for a in args:
        if a.startswith("env:"):
            env = dict(env or os.environ)
            name, _, val = a[4:].partition("=")
            env[name] = val
        else:
            cleaned.append(a)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *cleaned],
            capture_output=True, text=True, timeout=timeout,
            cwd=str(REPO), env=env)
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {'ERROR ' + res['_error'] if '_error' in res else 'ok'}",
              flush=True)
    print(f"results: {OUT}")


if __name__ == "__main__":
    main()
