"""Time BERT-layer components fwd+bwd in isolation at the north-star
shape (b=32, s=128, h=1024, heads=16).  Scratch diagnostic."""
import json
import time

import jax
import jax.numpy as jnp


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def bench_grad(f, args, iters, r):
    """us per fwd+bwd of f(*args) (sum-of-squares loss, full grads)."""
    def loss(*a):
        return jnp.sum(f(*a).astype(jnp.float32) ** 2)

    @jax.jit
    def loop(args):
        def body(c, _):
            a0 = args[0] + jnp.asarray(c, args[0].dtype) * 1e-30
            gs = jax.grad(loss, argnums=tuple(range(len(args))))(
                a0, *args[1:])
            bump = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in gs)
            return c + bump * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    return round(timed(loop, (args,), iters, r) * 1e6, 1)


def main():
    from apex_tpu.ops.attention import flash_attention, mha_reference
    from apex_tpu.ops.layer_norm import layer_norm
    r = rtt()
    iters = 100
    out = {}
    b, s, h, nh, d = 32, 128, 1024, 16, 64
    key = jax.random.PRNGKey(0)

    q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (b, nh, s, d),
                                 jnp.bfloat16) for i in range(3))
    out["flash_us"] = bench_grad(
        lambda q, k, v: flash_attention(q, k, v, causal=False), (q, k, v),
        iters, r)
    print("flash", out["flash_us"], flush=True)
    out["mha_ref_us"] = bench_grad(
        lambda q, k, v: mha_reference(q, k, v, causal=False), (q, k, v),
        iters, r)
    print("mha_ref", out["mha_ref_us"], flush=True)

    x = jax.random.normal(key, (s * b, h), jnp.bfloat16)
    gam = jnp.ones((h,), jnp.float32)
    bet = jnp.zeros((h,), jnp.float32)
    out["fused_ln_us"] = bench_grad(
        lambda x, g, b_: layer_norm(x, g, b_), (x, gam, bet), iters, r)
    print("fused_ln", out["fused_ln_us"], flush=True)

    def jnp_ln(x, g, b_):
        x32 = x.astype(jnp.float32)
        mu = x32.mean(-1, keepdims=True)
        var = x32.var(-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + 1e-5) * g + b_).astype(
            x.dtype)
    out["jnp_ln_us"] = bench_grad(jnp_ln, (x, gam, bet), iters, r)
    print("jnp_ln", out["jnp_ln_us"], flush=True)

    # the layer's three matmuls, fused into one fn (qkv, out-proj, mlp x2)
    wqkv = jax.random.normal(jax.random.PRNGKey(4), (h, 3 * h),
                             jnp.bfloat16) * 0.02
    wo = jax.random.normal(jax.random.PRNGKey(5), (h, h), jnp.bfloat16) * .02
    w1 = jax.random.normal(jax.random.PRNGKey(6), (h, 4 * h),
                           jnp.bfloat16) * 0.02
    w2 = jax.random.normal(jax.random.PRNGKey(7), (4 * h, h),
                           jnp.bfloat16) * 0.02

    def matmuls(x, wqkv, wo, w1, w2):
        a = x @ wqkv
        bqv = a[:, :h] @ wo
        c = jax.nn.gelu(x @ w1)
        return bqv + c @ w2
    out["matmuls_us"] = bench_grad(matmuls, (x, wqkv, wo, w1, w2), iters, r)
    print("matmuls", out["matmuls_us"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
