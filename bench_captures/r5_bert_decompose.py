"""Decompose the BERT north-star leg's step time on-chip.

Times, each in its own scan program (same harness as bench.py):
  fwd-only, fwd+bwd, lamb-only.  (The full step is the bench.py bert
  leg itself — run ``python bench.py --inner tpu --leg bert``.)
Prints one JSON line.  Scratch diagnostic — not a bench artifact.
"""
import json
import time

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def main():
    from apex_tpu.optimizers.fused_lamb import _lamb_step
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig, bert_model_provider

    r = rtt()
    cfg = BertConfig(max_seq_length=128, hidden_dropout=0.0,
                     attention_dropout=0.0, params_dtype=jnp.bfloat16)
    batch, seq, iters = 32, 128, 4
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    model = bert_model_provider(cfg, add_binary_head=False)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size)
    types = jnp.zeros((batch, seq), jnp.int32)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens, types,
                        lm_labels=labels)
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = flat.astype(jnp.float32)
    sizes = tuple(int(np.prod(l.shape)) if l.ndim else 1
                  for l in jax.tree.leaves(params))
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    out = {"n_params": int(flat.size), "n_leaves": len(sizes)}

    def loss_fn(fp):
        loss, _ = model.apply(unravel(fp), tokens, types, lm_labels=labels)
        return loss

    # 1. forward only
    @jax.jit
    def fwd_loop(fp):
        def body(c, _):
            return c + loss_fn(fp + c * 1e-30), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["fwd_ms"] = round(timed(fwd_loop, (flat,), iters, r) * 1e3, 2)
    print("fwd", out["fwd_ms"], flush=True)

    # 2. fwd + bwd
    @jax.jit
    def fb_loop(fp):
        def body(c, _):
            l, g = jax.value_and_grad(loss_fn)(fp + c * 1e-30)
            return c + l + jnp.sum(g[:1]), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["fwd_bwd_ms"] = round(timed(fb_loop, (flat,), iters, r) * 1e3, 2)
    print("fwd_bwd", out["fwd_bwd_ms"], flush=True)

    # 3. lamb only (state carried)
    g = jnp.ones_like(flat) * 1e-4

    @jax.jit
    def lamb_loop(state, g):
        def body(state, _):
            fp, m, v = state
            return _lamb_step(
                fp, m, v, g, jnp.float32(1), jnp.float32(1e-4),
                jnp.float32(0.9), jnp.float32(0.999), jnp.float32(1e-6),
                jnp.float32(0.01), jnp.float32(1.0), jnp.float32(0),
                jnp.float32(1.0), bias_correction=True, offsets=offsets,
                sizes=sizes, use_nvlamb=False), None
        state, _ = jax.lax.scan(body, state, None, length=iters)
        return jax.tree.map(lambda x: jnp.sum(x[:1]), state)
    state = (flat, jnp.zeros_like(flat), jnp.zeros_like(flat))
    out["lamb_ms"] = round(timed(lamb_loop, (state, g), iters, r) * 1e3, 2)
    print("lamb", out["lamb_ms"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
