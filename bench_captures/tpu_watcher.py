"""Detached TPU-tunnel watcher for round 4.

The axon tunnel has died late in ALL prior rounds (VERDICT r3 "do this" #2:
capture early, commit immediately).  This watcher probes the backend in a
disposable subprocess every PROBE_INTERVAL seconds; the moment the chip
answers, it runs the full ``bench.py`` capture, saves the raw JSON line to
``bench_captures/r4_watch_capture_<n>.json``, and keeps watching (later
captures are upgrades — bench.py itself picks its own best numbers).

Run detached:  nohup python bench_captures/tpu_watcher.py >> bench_captures/watcher.log 2>&1 &
"""
from __future__ import annotations

import datetime
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
CAPDIR = REPO / "bench_captures"
PROBE_TIMEOUT = 90
BENCH_TIMEOUT = 1800
PROBE_INTERVAL = 240

PROBE_SRC = """
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
x = jax.numpy.ones((256, 256))
print("PROBE_OK", float((x @ x).sum()))
"""


def log(msg: str) -> None:
    print(f"[{datetime.datetime.utcnow().isoformat()}] {msg}", flush=True)


def probe() -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # a cpu override would fail the assert
    try:
        r = subprocess.run(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT, env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def run_capture(n: int) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # bench manages its own backend choice
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT, env=env,
            cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        log("bench.py timed out")
        return False
    line = None
    for cand in reversed(r.stdout.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            line = cand
            break
    if line is None:
        log(f"no JSON line (rc={r.returncode}); stderr tail: {r.stderr[-400:]}")
        return False
    try:
        payload = json.loads(line)
    except json.JSONDecodeError:
        log("JSON parse failed")
        return False
    backend = (payload.get("extras") or {}).get("backend")
    out = CAPDIR / f"r4_watch_capture_{n:03d}.json"
    out.write_text(line + "\n")
    log(f"capture saved to {out.name} backend={backend} "
        f"value={payload.get('value')} vs_baseline={payload.get('vs_baseline')}")
    if backend == "tpu":
        # commit immediately: the tunnel has died late in every round —
        # an uncommitted on-chip capture is one session crash from lost
        extras = payload.get("extras") or {}
        msg = (f"r4 on-chip capture: {payload.get('value')} tokens/s, "
               f"mfu {extras.get('mfu')}, bert_mfu {extras.get('bert_mfu')}")
        r = subprocess.run(["git", "-C", str(REPO), "add", str(out)],
                           capture_output=True, text=True)
        r2 = subprocess.run(
            ["git", "-C", str(REPO), "commit", "-m", msg,
             "-m", "No-Verification-Needed: committing a measurement "
                   "artifact, no source change"],
            capture_output=True, text=True)
        log(f"git commit rc={r.returncode}/{r2.returncode}: "
            f"{(r2.stdout or r2.stderr)[-160:]}")
    return backend == "tpu"


def main() -> None:
    # resume numbering after a restart — never clobber a saved capture
    # (numeric sort: lexicographic mis-orders once indices pass the pad)
    indices = sorted(int(f.stem.rsplit("_", 1)[1])
                     for f in CAPDIR.glob("r4_watch_capture_*.json"))
    n = indices[-1] if indices else 0
    log(f"watcher started (next capture index {n + 1})")
    bert_done = False
    while True:
        if probe():
            if not bert_done:
                # the north-star leg FIRST: a brief tunnel window must
                # not be eaten by the 20+ min main-leg compile before
                # the >=50%-MFU BERT number is captured
                log("probe OK — running quick bert leg first")
                try:
                    r = subprocess.run(
                        [sys.executable,
                         str(CAPDIR / "r4_experiments.py"), "--quick"],
                        capture_output=True, text=True, timeout=1000,
                        cwd=str(REPO))
                    log(f"bert leg rc={r.returncode}: "
                        f"{(r.stdout or '').strip().splitlines()[-1:]}"
                    )
                    outf = CAPDIR / "r4_experiments_out.json"
                    if outf.exists() and "bert_mfu" in outf.read_text():
                        bert_done = True
                        subprocess.run(["git", "-C", str(REPO), "add",
                                        str(outf)], capture_output=True)
                        subprocess.run(
                            ["git", "-C", str(REPO), "commit", "-m",
                             "r4 on-chip bert leg capture",
                             "-m", "No-Verification-Needed: measurement "
                                   "artifact, no source change"],
                            capture_output=True)
                except subprocess.TimeoutExpired:
                    log("bert leg timed out")
            log("running full bench capture")
            n += 1
            ok = run_capture(n)
            log(f"capture {'TPU-green' if ok else 'degraded'}; sleeping 1200s")
            time.sleep(1200)
        else:
            log("probe failed (tunnel dead/wedged)")
            time.sleep(PROBE_INTERVAL)


if __name__ == "__main__":
    main()
