"""Detached TPU-tunnel watcher (round 5, hardened per r4 verdict Weak #2).

The axon tunnel has died mid-session in ALL prior rounds.  This watcher
probes the backend in a disposable subprocess every PROBE_INTERVAL
seconds; the moment the chip answers it

1. runs the quick BERT north-star leg (``r4_experiments.py --quick``)
   first — a brief window must not be eaten by the main-leg compile,
2. runs the full ``bench.py`` capture and saves the JSON line to
   ``bench_captures/r5_watch_capture_<n>.json``,
3. on a TPU-green capture, ALSO writes ``BENCH_r05.json`` at the repo
   root so the driver artifact has on-chip provenance the moment the
   first capture lands (r4 verdict Missing #2), and commits everything.

Hardening vs the r4 version:
- a pid lockfile (``watcher.lock``) prevents two instances racing the
  same capture numbering; stale locks (dead pid) are reclaimed,
- capture files are written via temp+rename and the index is re-scanned
  immediately before each write, tolerating a concurrent writer,
- the capture/commit path is factored into pure-ish functions exercised
  by ``tests/L1/test_watcher.py`` with a stubbed runner.

Run detached:
  nohup python bench_captures/tpu_watcher.py >> bench_captures/watcher.log 2>&1 &
"""
from __future__ import annotations

import datetime
import fcntl
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
CAPDIR = REPO / "bench_captures"
LOCKFILE = CAPDIR / "watcher.lock"
ROUND = "r5"
PROBE_TIMEOUT = 90
#: outer ceiling > the SUM of bench.py's per-leg timeouts (8900 s incl.
#: the main-leg retry) — same rule as the experiments runner: the outer
#: kill must never truncate a capture the inner per-leg timeouts would
#: have completed degraded
BENCH_TIMEOUT = 9600
PROBE_INTERVAL = 240

PROBE_SRC = """
import jax
assert jax.default_backend() == "tpu", jax.default_backend()
x = jax.numpy.ones((256, 256))
print("PROBE_OK", float((x @ x).sum()))
"""


def log(msg: str) -> None:
    print(f"[{datetime.datetime.utcnow().isoformat()}] {msg}", flush=True)


_lock_fd = None  # held open for the watcher's lifetime


def acquire_lock() -> bool:
    """flock the lockfile (no TOCTOU window; the kernel releases the
    lock automatically when the holder dies, so no stale-pid logic)."""
    global _lock_fd
    fd = os.open(LOCKFILE, os.O_CREAT | os.O_WRONLY)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(fd)
        return False
    os.ftruncate(fd, 0)
    os.write(fd, str(os.getpid()).encode())  # diagnostic only
    _lock_fd = fd
    return True


def release_lock() -> None:
    global _lock_fd
    if _lock_fd is not None:
        try:
            os.close(_lock_fd)  # drops the flock
            LOCKFILE.unlink()
        except OSError:
            pass
        _lock_fd = None


def next_capture_path() -> pathlib.Path:
    """Concurrent-writer-safe: re-scan indices at call time across ALL
    round prefixes (r4 leftovers included) and claim the next slot with
    O_EXCL so two scanners can never agree on the same file."""
    while True:
        indices = [0]
        for f in CAPDIR.glob("r?_watch_capture_*.json"):
            try:
                indices.append(int(f.stem.rsplit("_", 1)[1]))
            except ValueError:
                continue
        n = max(indices) + 1
        path = CAPDIR / f"{ROUND}_watch_capture_{n:03d}.json"
        try:
            os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return path
        except FileExistsError:
            continue  # concurrent writer claimed n — rescan


def probe(runner=subprocess.run) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # a cpu override would fail the assert
    try:
        r = runner(
            [sys.executable, "-c", PROBE_SRC],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT, env=env,
        )
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "PROBE_OK" in r.stdout


def extract_json_line(stdout: str):
    """Last {...} line of bench.py output, parsed; None if absent/bad."""
    for cand in reversed(stdout.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                return None
    return None


def save_and_commit(payload: dict, runner=subprocess.run) -> bool:
    """Persist one bench payload; on TPU provenance also refresh
    BENCH_r05.json at the repo root and git-commit both.  Returns
    whether the capture was TPU-green."""
    line = json.dumps(payload)
    out = next_capture_path()
    tmp = out.with_suffix(".tmp")
    tmp.write_text(line + "\n")
    os.replace(tmp, out)  # atomic: readers never see a partial file
    backend = (payload.get("extras") or {}).get("backend")
    log(f"capture saved to {out.name} backend={backend} "
        f"value={payload.get('value')} vs_baseline={payload.get('vs_baseline')}")
    if backend != "tpu":
        return False
    bench_artifact = REPO / "BENCH_r05.json"
    btmp = bench_artifact.with_suffix(".json.tmp")
    btmp.write_text(line + "\n")
    os.replace(btmp, bench_artifact)
    extras = payload.get("extras") or {}
    msg = (f"{ROUND} on-chip capture: {payload.get('value')} tokens/s, "
           f"mfu {extras.get('mfu')}, bert_mfu {extras.get('bert_mfu')}")
    _commit_artifacts([out, bench_artifact], msg, runner=runner)
    return True


def run_capture(runner=subprocess.run) -> bool:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # bench manages its own backend choice
    try:
        r = runner(
            [sys.executable, str(REPO / "bench.py")],
            capture_output=True, text=True, timeout=BENCH_TIMEOUT, env=env,
            cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        log("bench.py timed out")
        return False
    payload = extract_json_line(r.stdout)
    if payload is None:
        log(f"no JSON line (rc={r.returncode}); stderr tail: {r.stderr[-400:]}")
        return False
    return save_and_commit(payload, runner=runner)


def run_experiments(quick: bool, runner=subprocess.run) -> bool:
    """Drive r5_experiments.py (bench.py legs with overrides).  quick =
    the BERT north-star leg only — first, so a brief window can't miss
    it.  Commits the incrementally-written results file either way.

    Success means the run's own goal was met: quick = the bert capture
    landed; full = EVERY experiment is clean (r5_experiments prints
    ALL_COMPLETE; its resume logic retries _error/_timeout entries in
    later windows, so a partial batch must NOT be marked done here)."""
    args = [sys.executable, str(CAPDIR / "r5_experiments.py")] + (
        ["--quick"] if quick else [])
    stdout = ""
    try:
        # full-batch ceiling > the sum of the inner per-experiment
        # timeouts (24600 s after the r5 additions) so the outer kill
        # never truncates a batch the inner timeouts would have
        # completed; results are flushed per-experiment either way
        r = runner(args, capture_output=True, text=True,
                   timeout=1400 if quick else 26000, cwd=str(REPO))
        stdout = r.stdout or ""
        log(f"experiments ({'quick' if quick else 'full'}) "
            f"rc={r.returncode}: {stdout.strip().splitlines()[-1:]}")
    except subprocess.TimeoutExpired:
        log("experiments timed out (partial results kept)")
    outf = CAPDIR / "r5_experiments_out.json"
    captured = outf.exists() and "bert_mfu" in outf.read_text()
    if captured:
        _commit_artifacts([outf], f"{ROUND} on-chip experiment captures",
                          runner=runner)
    return captured if quick else "ALL_COMPLETE" in stdout


#: diagnostic scripts run once after the experiment batch completes —
#: each prints JSON/op tables; stdout is committed alongside the
#: captures so an unattended window still yields the decomposition data
DIAGNOSTICS = [
    ("op_probes", "r5_op_probes.py", 1800),
    ("profile_bert", "r5_profile_bert.py", 1200),
]


def _commit_artifacts(paths, msg, runner=subprocess.run) -> None:
    """Shared add+commit for measurement artifacts (no-op when empty)."""
    if not paths:
        return
    runner(["git", "-C", str(REPO), "add", *map(str, paths)],
           capture_output=True, text=True)
    r = runner(
        ["git", "-C", str(REPO), "commit", "-m", msg,
         "-m", "No-Verification-Needed: committing a measurement "
               "artifact, no source change"],
        capture_output=True, text=True)
    log(f"git commit rc={r.returncode}: "
        f"{((r.stdout or r.stderr) or '')[-160:]}")


def run_diagnostics(runner=subprocess.run) -> bool:
    """Run each diagnostic script, save stdout+stderr, commit.  True
    only when every script exited 0 (a crashed or timed-out script is
    stamped _FAIL/_TIMEOUT — not _DONE — so it reruns next window)."""
    all_ok = True
    touched = []
    for key, script, timeout in DIAGNOSTICS:
        outf = CAPDIR / f"r5_diag_{key}.txt"
        if outf.exists() and outf.read_text().strip().endswith("_DONE"):
            continue
        try:
            r = runner([sys.executable, str(CAPDIR / script)],
                       capture_output=True, text=True, timeout=timeout,
                       cwd=str(REPO))
            body = (r.stdout or "") + \
                (f"\n--- stderr ---\n{r.stderr}" if r.stderr else "")
            outf.write_text(
                body + ("\n_DONE" if r.returncode == 0 else "\n_FAIL"))
            log(f"diagnostic {key} rc={r.returncode}")
            if r.returncode != 0:
                all_ok = False
        except subprocess.TimeoutExpired as e:
            def _s(x):
                return x if isinstance(x, str) else (x or b"").decode()
            outf.write_text(_s(e.stdout) +
                            (f"\n--- stderr ---\n{_s(e.stderr)}"
                             if e.stderr else "") + "\n_TIMEOUT")
            log(f"diagnostic {key} timed out (partial kept)")
            all_ok = False
        touched.append(outf)
    _commit_artifacts(touched, f"{ROUND} on-chip diagnostic outputs",
                      runner=runner)
    return all_ok


def main() -> None:
    if not acquire_lock():
        log(f"another watcher holds {LOCKFILE.name}; exiting")
        return
    log(f"watcher started (round {ROUND}, pid {os.getpid()})")
    bert_done = False
    experiments_done = False
    diagnostics_done = False
    try:
        while True:
            # one bad iteration (ENOSPC, git hiccup, transient OSError)
            # must not end the vigil — the whole point is to survive
            # unattended until the tunnel comes back
            try:
                if probe():
                    if not bert_done:
                        log("probe OK — running quick bert leg first")
                        bert_done = run_experiments(quick=True)
                    log("running full bench capture")
                    ok = run_capture()
                    log(f"capture {'TPU-green' if ok else 'degraded'}")
                    if ok and not experiments_done:
                        log("running full experiment batch")
                        experiments_done = run_experiments(quick=False)
                    if experiments_done and not diagnostics_done:
                        log("running diagnostics (op probes + profile)")
                        diagnostics_done = run_diagnostics()
                    time.sleep(1200)
                else:
                    log("probe failed (tunnel dead/wedged)")
                    time.sleep(PROBE_INTERVAL)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001
                log(f"iteration error ({type(e).__name__}: {e}); "
                    "sleeping and continuing")
                time.sleep(PROBE_INTERVAL)
    finally:
        release_lock()


if __name__ == "__main__":
    main()
