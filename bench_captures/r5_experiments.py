"""On-chip experiment runner for the next healthy tunnel window (r5).

Every experiment drives a REAL ``bench.py`` leg in its own subprocess
(``--inner tpu --leg X --override k=v``), so the measured code is the
measured code — no templated model-setup duplicates that can drift from
the bench legs (r4 verdict weak #7; this file replaces
``r4_experiments.py``'s 5.8 kB of inline source snippets).

Open questions it answers, in priority order (a wedge mid-batch keeps
everything already written; the EXPERIMENTS table below is the
authoritative order):

1. ``--quick``: the BERT north-star leg alone (BASELINE north_star,
   >=50% MFU target) — first, so a brief window can't miss it.
2. The cheap bert-leg design A/Bs that set library defaults:
   split-state (tree fwd/bwd + flat master), embedding grad via
   matmul, batch 48.
3. GPT flagship main leg at batch 8/16/24, split-state, emb-matmul —
   bigger GEMM M dims vs the committed batch-8 number.
4. BERT batch 16 and batch 64 + remat.
5. Flash attention block 512 vs 1024 (the r3 block choice re-validated
   under base-2 softmax).
6. The MoE leg (its E-sweep + onehot/gather crossover is built in).

Usage:  python bench_captures/r5_experiments.py [--quick]
Writes: bench_captures/r5_experiments_out.json (one JSON object per
key), rewritten after EVERY experiment so a later wedge loses nothing.
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r5_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
# Ordered by information-per-chip-second: the cheap bert-leg A/Bs that
# decide library defaults come before the 2400 s GPT sweeps, so a short
# tunnel window still answers the design questions.
EXPERIMENTS = [
    ("bert", ["--leg", "bert"], 1200),
    # two-buffer state (tree fwd/bwd + flat master) vs differentiating
    # through unravel — the leading candidate for the ~40 ms in-model
    # overhead (PERF.md round-5 §3)
    ("bert_split_state", ["--leg", "bert", "--override",
                          "split_state=1"], 900),
    # embedding-table grad: one-hot MXU matmul vs XLA scatter-add
    ("bert_emb_matmul_grad", ["--leg", "bert", "--override",
                              "emb_matmul_grad=1"], 900),
    # batch 48 projected ~13 GB — the largest no-remat fit
    ("bert_batch48", ["--leg", "bert", "--override", "batch=48"], 1200),
    ("gpt_batch8", ["--leg", "main"], 2400),
    ("gpt_split_state", ["--leg", "main", "--override",
                         "split_state=1"], 2400),
    ("gpt_batch16", ["--leg", "main", "--override", "batch=16"], 2400),
    ("gpt_batch24", ["--leg", "main", "--override", "batch=24"], 2400),
    ("gpt_emb_matmul_grad", ["--leg", "main", "--override",
                             "emb_matmul_grad=1"], 2400),
    ("bert_batch16", ["--leg", "bert", "--override", "batch=16"], 900),
    # batch 64 without remat OOMs (measured r5: 16.44 G vs 15.75 G HBM);
    # two ways to fit: bf16 CE residuals (~1 GB back, no recompute) or
    # remat (costs ~+fwd FLOPs — only wins if the bigger GEMMs beat the
    # recompute)
    ("bert_batch64_ce_half", ["--leg", "bert", "--override", "batch=64",
                              "--override", "ce_half=1"], 1200),
    ("bert_batch64_remat", ["--leg", "bert", "--override", "batch=64",
                            "--override", "remat=1"], 1200),
    # the beyond-parity llama decoder's measured MFU
    ("llama", ["--leg", "llama"], 1500),
    ("attn_block1024", ["--leg", "attn"], 900),
    ("attn_block512", ["--leg", "attn", "--override", "block_q=512",
                       "--override", "block_k=512"], 900),
    ("moe", ["--leg", "moe"], 1800),
]


def last_json_line(text: str):
    """Newest parseable JSON object line; skips unparseable lines (a
    timeout kill can truncate the final line mid-write — an earlier
    complete line, e.g. the moe leg's pre-sweep flush, still counts)."""
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *args],
            capture_output=True, text=True, timeout=timeout, cwd=str(REPO))
    except subprocess.TimeoutExpired as e:
        # salvage any JSON the leg printed before wedging (the moe leg
        # flushes its base result before the sweep for exactly this)
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        # partial salvage (_timeout) retries too: the whole point of
        # e.g. the moe experiment is the sweep a wedge cut short
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        # never let a worse retry overwrite salvaged data
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {json.dumps(results[key])[:200]}", flush=True)
    clean = all(
        results.get(k) and not ({"_error", "_timeout"} & set(results[k]))
        for k, _, _ in EXPERIMENTS)
    if not quick and clean:
        print("ALL_COMPLETE", flush=True)


if __name__ == "__main__":
    main()
