"""Op-level A/B probes for the remaining BERT north-star suspects.
Run on a healthy tunnel:  python bench_captures/r5_op_probes.py

1. CE target gather: take_along_axis vs one-hot reduction
   ([4096, 30592] fp32 — the MLM loss inner op).
2. Embedding table grad: XLA scatter-add vs one-hot MXU matmul
   ([4096] ids -> [30592, 1024] bf16 table).
3. Megatron layout transposes: [s,b,n,d] -> [b,n,s,d] relayout at the
   BERT shape (the per-layer q/k/v + output round trip).
4. Flat-master plumbing: 297-leaf unravel (fp32 slice+cast+reshape) and
   grad re-ravel (cast+concat) at BERT-large size.
Prints one JSON line.  Scratch diagnostic.
"""
import json
import time

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed_us(loop, args, iters, r, reps=3):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    per = [(s - r) / iters for s in samples]
    best, med = per[0], per[len(per) // 2]
    if best < 0.25 * med:
        best = med
    return round(best * 1e6, 1)


def scan_loop(fn, n_args, iters):
    """Jitted scan harness: perturbs arg0 by the carry, folds all
    outputs' full sums into the carry (nothing sliceable away)."""

    @jax.jit
    def loop(*args):
        def body(c, _):
            a0 = args[0] + jnp.asarray(c, args[0].dtype) * 1e-30
            outs = fn(a0, *args[1:n_args])
            bump = sum(jnp.sum(o.astype(jnp.float32)) * 1e-30
                       for o in jax.tree.leaves(outs)
                       if hasattr(o, "astype"))
            return c + bump, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    return loop


def main():
    r = rtt()
    out = {}
    rows, vocab, h = 4096, 30592, 1024
    iters = 40

    # 1. CE target gather
    logits = jax.random.normal(jax.random.PRNGKey(0), (rows, vocab),
                               jnp.float32)
    tgt = jax.random.randint(jax.random.PRNGKey(1), (rows,), 0, vocab)

    def gather_taa(logits, tgt):
        return jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]

    def gather_onehot(logits, tgt):
        return jnp.sum(
            logits * jax.nn.one_hot(tgt, vocab, dtype=logits.dtype), -1)

    out["ce_take_along_axis_us"] = timed_us(
        scan_loop(gather_taa, 2, iters), (logits, tgt), iters, r)
    print("ce_taa", out["ce_take_along_axis_us"], flush=True)
    out["ce_onehot_us"] = timed_us(
        scan_loop(gather_onehot, 2, iters), (logits, tgt), iters, r)
    print("ce_onehot", out["ce_onehot_us"], flush=True)

    # 2. embedding table grad
    table = jax.random.normal(jax.random.PRNGKey(2), (vocab, h),
                              jnp.bfloat16)
    dy = jax.random.normal(jax.random.PRNGKey(3), (rows, h), jnp.bfloat16)

    def emb_scatter(table, tgt, dy):
        def f(w):
            return jnp.sum(jnp.take(w, tgt, axis=0).astype(jnp.float32)
                           * dy.astype(jnp.float32))
        return jax.grad(f)(table)

    def emb_onehot(table, tgt, dy):
        onehot = jax.nn.one_hot(tgt, vocab, dtype=dy.dtype)
        return jax.lax.dot_general(onehot, dy, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    out["emb_scatter_us"] = timed_us(
        scan_loop(lambda t, tg, d: emb_scatter(t, tg, d), 3, iters),
        (table, tgt, dy), iters, r)
    print("emb_scatter", out["emb_scatter_us"], flush=True)
    out["emb_onehot_us"] = timed_us(
        scan_loop(lambda t, tg, d: emb_onehot(t, tg, d), 3, iters),
        (table, tgt, dy), iters, r)
    print("emb_onehot", out["emb_onehot_us"], flush=True)

    # 3. layout transposes at the BERT per-layer shape
    s, b, nh, d = 128, 32, 16, 64
    x = jax.random.normal(jax.random.PRNGKey(4), (s, b, nh, d),
                          jnp.bfloat16)

    def roundtrip(x):
        y = x.transpose(1, 2, 0, 3)           # [b, n, s, d]
        return y.transpose(2, 0, 1, 3)        # back

    out["transpose_roundtrip_us"] = timed_us(
        scan_loop(roundtrip, 1, iters), (x,), iters, r)
    print("transpose", out["transpose_roundtrip_us"], flush=True)

    # 3b. LayerNorm fwd+bwd at the BERT per-layer shape: Pallas kernel
    # vs plain-XLA LN (grad through both; the layer runs ~50 LN
    # kernel-pairs per step so fixed overheads multiply)
    from apex_tpu.ops.layer_norm import layer_norm, layer_norm_reference
    xln = jax.random.normal(jax.random.PRNGKey(5), (s * b, h), jnp.bfloat16)
    gam = jnp.ones((h,), jnp.float32)
    bet = jnp.zeros((h,), jnp.float32)

    def ln_grad(impl):
        def f(x, g_, b_):
            def loss(x, g_, b_):
                return jnp.sum(impl(x, g_, b_).astype(jnp.float32) ** 2)
            return jax.grad(loss, argnums=(0, 1, 2))(x, g_, b_)
        return f

    out["ln_fused_us"] = timed_us(
        scan_loop(ln_grad(layer_norm), 3, iters), (xln, gam, bet),
        iters, r)
    print("ln_fused", out["ln_fused_us"], flush=True)
    out["ln_xla_us"] = timed_us(
        scan_loop(ln_grad(layer_norm_reference), 3, iters),
        (xln, gam, bet), iters, r)
    print("ln_xla", out["ln_xla_us"], flush=True)

    # 4. flat-master unravel + grad ravel at BERT-large size
    n_leaves = 297
    sizes = [31_254_528] + [1024 * 1024] * 96 + [4 * 1024 * 1024] * 48 + \
        [1024] * 151
    sizes.append(334_822_400 - sum(sizes))
    tree = {f"w{i}": jnp.zeros((sz,), jnp.bfloat16)
            for i, sz in enumerate(sizes)}
    flat, unravel = jax.flatten_util.ravel_pytree(tree)
    flat32 = flat.astype(jnp.float32)
    it2 = 8

    def unravel_fn(fp):
        return unravel(fp)

    out["unravel_us"] = timed_us(
        scan_loop(unravel_fn, 1, it2), (flat32,), it2, r)
    print("unravel", out["unravel_us"], flush=True)

    def ravel_fn(fp):
        t = unravel(fp)
        g, _ = jax.flatten_util.ravel_pytree(t)
        return g.astype(jnp.float32)

    out["unravel_plus_ravel_us"] = timed_us(
        scan_loop(ravel_fn, 1, it2), (flat32,), it2, r)
    print("unravel+ravel", out["unravel_plus_ravel_us"], flush=True)

    # 4b. the GRAD of unravel — the flat-master pattern differentiates
    # through it, whose transpose is a 297-way pad+add chain over the
    # full flat buffer; if XLA doesn't fuse that into one pass, this is
    # the in-model overhead the isolated layers don't show
    def unravel_grad_fn(fp):
        def loss(fp):
            t = unravel(fp)
            return sum(jnp.sum(x.astype(jnp.float32) ** 2)
                       for x in jax.tree.leaves(t))
        return jax.grad(loss)(fp)

    out["unravel_grad_us"] = timed_us(
        scan_loop(unravel_grad_fn, 1, it2), (flat32,), it2, r)
    print("unravel_grad", out["unravel_grad_us"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
