"""Decompose _lamb_step cost on-chip: phase1 kernel vs per-leaf norms vs
repeat broadcast.  Scratch diagnostic."""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def main():
    from apex_tpu.ops.fused_update import fused_lamb_phase1_flat

    r = rtt()
    iters = 4
    n = 334_822_400
    # BERT-large-ish leaf structure: 297 leaves, one 31M embedding,
    # many 1M/4M matrices, many 1024 biases
    rng = np.random.default_rng(0)
    sizes = [31_254_528] + [1024 * 1024] * 96 + [4 * 1024 * 1024] * 48 + \
        [1024] * 151
    sizes.append(n - sum(sizes))
    assert sizes[-1] > 0
    sizes = tuple(sizes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    out = {"n_leaves": len(sizes)}

    p = jnp.ones((n,), jnp.float32)
    g = jnp.full((n,), 1e-4, jnp.float32)

    # 1. phase1 kernel alone (state carried)
    @jax.jit
    def ph1_loop(state, g):
        def body(state, _):
            p, m, v = state
            m2, v2, u = fused_lamb_phase1_flat(
                p, g, m, v, beta1=jnp.float32(0.9), beta2=jnp.float32(0.999),
                eps=jnp.float32(1e-6), weight_decay=jnp.float32(0.01),
                step=jnp.float32(1), bias_correction=True,
                grad_scale=jnp.float32(1.0), grad_averaging=True)
            return (p - 1e-9 * u, m2, v2), None
        state, _ = jax.lax.scan(body, state, None, length=iters)
        return jax.tree.map(lambda x: jnp.sum(x[:1]), state)
    st = (p, jnp.zeros_like(p), jnp.zeros_like(p))
    out["phase1_ms"] = round(timed(ph1_loop, (st, g), iters, r) * 1e3, 2)
    print("phase1", out["phase1_ms"], flush=True)

    # 2. per-leaf sq-norms via static slices (the suspect)
    def sq_norms_slices(flat):
        return jnp.stack([
            jnp.sum(jnp.square(jax.lax.dynamic_slice_in_dim(flat, o, s)))
            for o, s in zip(offsets, sizes)])

    @jax.jit
    def norms_loop(p):
        def body(c, _):
            nrm = sq_norms_slices(p + c * 1e-30)
            return c + jnp.sum(nrm[:1]), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["norms_slices_ms"] = round(timed(norms_loop, (p,), iters, r) * 1e3, 2)
    print("norms_slices", out["norms_slices_ms"], flush=True)

    # 3. per-leaf sq-norms via segment_sum over a precomputed id vector
    # (seg_ids passed as an ARG — closure capture inlines 1.3 GB of HLO
    # constant and the tunnel 413s)
    seg_ids = jnp.asarray(np.repeat(np.arange(len(sizes)), sizes), jnp.int32)

    @jax.jit
    def seg_loop(p, seg_ids):
        def body(c, _):
            nrm = jax.ops.segment_sum(jnp.square(p + c * 1e-30), seg_ids,
                                      num_segments=len(sizes))
            return c + jnp.sum(nrm[:1]), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["norms_segsum_ms"] = round(
        timed(seg_loop, (p, seg_ids), iters, r) * 1e3, 2)
    print("norms_segsum", out["norms_segsum_ms"], flush=True)

    # 4. repeat broadcast alone
    ratio = jnp.ones((len(sizes),), jnp.float32)
    sz = jnp.asarray(sizes)

    @jax.jit
    def rep_loop(ratio):
        def body(c, _):
            scale = jnp.repeat(ratio + c * 1e-30, sz, total_repeat_length=n)
            return c + jnp.sum(scale[:1]), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["repeat_ms"] = round(timed(rep_loop, (ratio,), iters, r) * 1e3, 2)
    print("repeat", out["repeat_ms"], flush=True)

    # 5. gather broadcast: scale = ratio[seg_ids]
    @jax.jit
    def gat_loop(ratio, seg_ids):
        def body(c, _):
            scale = (ratio + c * 1e-30)[seg_ids]
            return c + jnp.sum(scale[:1]), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["gather_ms"] = round(
        timed(gat_loop, (ratio, seg_ids), iters, r) * 1e3, 2)
    print("gather", out["gather_ms"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
