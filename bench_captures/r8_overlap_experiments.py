"""On-chip comm/compute-overlap experiment queue for the next healthy
tunnel window (r8, ISSUE 7): overlap=0|1 A/Bs on the zero and TP legs,
so every capture carries the measured step time NEXT TO the comm
model's ``overlap_step_time_model_us`` / ``sequential_step_time_model_us``
stamps (and ``zero_prefetch`` / ``tp_overlap_chunks`` provenance) —
the modeled win and the measured win land in the same artifact.

Same discipline as ``r6_zero_experiments.py``: every experiment drives
a REAL ``bench.py`` leg in its own subprocess, results are rewritten
after EVERY experiment, and re-runs resume.

What these answer:

1. dp=1 single-chip controls: the overlapped zero step's PROGRAM-SHAPE
   cost (per-span gathers are no-ops at dp=1 but the decomposed
   program still compiles differently) — any delta here is
   restructuring overhead, not communication, and bounds what a
   multi-chip window can attribute to overlap.
2. The first multi-chip window flips ``zero_dp=N`` on rows 1–4 and
   reads the overlap win directly: (zero@dp=N, overlap=0) vs
   (zero@dp=N, overlap=1) at identical comm bytes (APX215-pinned).
3. TP leg fused-vs-ring on a 2-chip tensor axis (skipped cleanly on a
   single-chip session — the leg stubs itself).

Usage:  python bench_captures/r8_overlap_experiments.py [--quick]
Writes: bench_captures/r8_overlap_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r8_overlap_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # zero overlap A/B on the flagship GPT leg (dp defaults to the
    # session's device count: 1 on a single-chip tunnel = shape
    # control, N on the first multi-chip window = the real A/B)
    ("gpt_zero_seq", ["--leg", "main", "--override", "zero=1",
                      "--override", "overlap=0"], 2400),
    ("gpt_zero_overlap", ["--leg", "main", "--override", "zero=1",
                          "--override", "overlap=1"], 2400),
    # BERT north-star shape, same A/B (LAMB path: the per-leaf trust
    # ratios exercise the span-aware leaf machinery on chip)
    ("bert_zero_seq", ["--leg", "bert", "--override", "zero=1",
                       "--override", "overlap=0"], 1200),
    ("bert_zero_overlap", ["--leg", "bert", "--override", "zero=1",
                           "--override", "overlap=1"], 1200),
    # prefetch-depth sweep at the GPT shape (spans = 4 / 16 vs the
    # default 8): where does the per-span dispatch overhead cross the
    # hiding win
    ("gpt_zero_overlap_p4", ["--leg", "main", "--override", "zero=1",
                             "--override", "overlap=1",
                             "--override", "prefetch=4"], 2400),
    ("gpt_zero_overlap_p16", ["--leg", "main", "--override", "zero=1",
                              "--override", "overlap=1",
                              "--override", "prefetch=16"], 2400),
    # TP ring A/B (needs >= 2 devices; single-chip sessions record the
    # skip stub, costing seconds)
    ("tp_fused", ["--leg", "tp"], 900),
    ("tp_ring_c4", ["--leg", "tp", "--override", "overlap=1"], 900),
    ("tp_ring_c8", ["--leg", "tp", "--override", "overlap=1",
                    "--override", "overlap_chunks=8"], 900),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *args],
            capture_output=True, text=True, timeout=timeout, cwd=str(REPO))
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {json.dumps(results[key])[:200]}", flush=True)
    clean = all(
        results.get(k) and not ({"_error", "_timeout"} & set(results[k]))
        for k, _, _ in EXPERIMENTS)
    if not quick and clean:
        print("ALL_COMPLETE", flush=True)


if __name__ == "__main__":
    main()
