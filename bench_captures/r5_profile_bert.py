"""Capture a device trace of the BERT north-star step and print the top
ops by self time.  Run when the tunnel is healthy:

  python bench_captures/r5_profile_bert.py [--leg gpt]

Writes the raw xplane under bench_captures/profile/ and prints a
ranked op table (via tensorboard_plugin_profile's converter when it can
parse the trace; falls back to listing the xplane event names).
"""
import glob
import json
import os
import sys

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np

PROFDIR = os.path.join(os.path.dirname(__file__), "profile")


def build_bert_step():
    from apex_tpu.optimizers.fused_lamb import _lamb_step
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig, bert_model_provider

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = BertConfig(max_seq_length=128, hidden_dropout=0.0,
                     attention_dropout=0.0, params_dtype=jnp.bfloat16)
    batch, seq = 32, 128
    model = bert_model_provider(cfg, add_binary_head=False)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size)
    types = jnp.zeros((batch, seq), jnp.int32)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens, types,
                        lm_labels=labels)
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = flat.astype(jnp.float32)
    sizes = tuple(int(np.prod(l.shape)) if l.ndim else 1
                  for l in jax.tree.leaves(params))
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))

    @jax.jit
    def step(state):
        fp, m, v = state

        def loss_fn(fp):
            loss, _ = model.apply(unravel(fp), tokens, types,
                                  lm_labels=labels)
            return loss

        _, g = jax.value_and_grad(loss_fn)(fp)
        return _lamb_step(
            fp, m, v, g, jnp.float32(1), jnp.float32(1e-4),
            jnp.float32(0.9), jnp.float32(0.999), jnp.float32(1e-6),
            jnp.float32(0.01), jnp.float32(1.0), jnp.float32(0),
            jnp.float32(1.0), bias_correction=True, offsets=offsets,
            sizes=sizes, use_nvlamb=False)

    state = (flat, jnp.zeros_like(flat), jnp.zeros_like(flat))
    return step, state


def main():
    os.makedirs(PROFDIR, exist_ok=True)
    step, state = build_bert_step()
    # warm/compile outside the trace
    state = step(state)
    jax.block_until_ready(state)
    with jax.profiler.trace(PROFDIR):
        for _ in range(3):
            state = step(state)
        jax.block_until_ready(state)
    print("trace captured under", PROFDIR, flush=True)

    pbs = sorted(glob.glob(os.path.join(
        PROFDIR, "**", "*.xplane.pb"), recursive=True))
    if not pbs:
        print("no xplane.pb found — device tracing unsupported?")
        return
    latest = pbs[-1]
    print("xplane:", latest, flush=True)
    try:
        from tensorboard_plugin_profile.convert import raw_to_tool_data
        data, _ = raw_to_tool_data.xspace_to_tool_data(
            [latest], "framework_op_stats", params={})
        out = os.path.join(PROFDIR, "op_stats.json")
        with open(out, "w") as f:
            f.write(data if isinstance(data, str) else data.decode())
        print("op stats written to", out)
        try:
            rows = json.loads(data if isinstance(data, str)
                              else data.decode())
            print(json.dumps(rows[:2], indent=1)[:2000])
        except Exception:  # noqa: BLE001 — format varies by version
            pass
    except Exception as e:  # noqa: BLE001
        print(f"converter failed ({type(e).__name__}: {e}); raw xplane "
              f"kept for manual inspection")


if __name__ == "__main__":
    main()
