"""Probe the repeat-free per-leaf broadcast + norms on-chip.
Scratch diagnostic."""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def main():
    r = rtt()
    iters = 4
    n = 334_822_400
    sizes = [31_254_528] + [1024 * 1024] * 96 + [4 * 1024 * 1024] * 48 + \
        [1024] * 151
    sizes.append(n - sum(sizes))
    sizes = tuple(sizes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes[:-1]))
    out = {}

    p = jnp.ones((n,), jnp.float32)
    u = jnp.full((n,), 1e-4, jnp.float32)
    ratio = jnp.ones((len(sizes),), jnp.float32)

    # A. full trust-ratio apply via concat of per-leaf broadcasts
    @jax.jit
    def apply_loop(p, u, ratio):
        def body(p, _):
            pieces = [
                jax.lax.dynamic_slice_in_dim(p, o, s)
                - 1e-4 * ratio[i] * jax.lax.dynamic_slice_in_dim(u, o, s)
                for i, (o, s) in enumerate(zip(offsets, sizes))]
            return jnp.concatenate(pieces), None
        p, _ = jax.lax.scan(body, p, None, length=iters)
        return jnp.sum(p[:1])
    out["apply_concat_ms"] = round(
        timed(apply_loop, (p, u, ratio), iters, r) * 1e3, 2)
    print("apply_concat", out["apply_concat_ms"], flush=True)

    # B. scale vector built via concat of broadcast_to, then vector math
    @jax.jit
    def scale_loop(p, u, ratio):
        def body(p, _):
            scale = jnp.concatenate([
                jnp.broadcast_to(ratio[i], (s,))
                for i, s in enumerate(sizes)])
            return p - 1e-4 * scale * u, None
        p, _ = jax.lax.scan(body, p, None, length=iters)
        return jnp.sum(p[:1])
    out["scale_concat_ms"] = round(
        timed(scale_loop, (p, u, ratio), iters, r) * 1e3, 2)
    print("scale_concat", out["scale_concat_ms"], flush=True)

    # C. per-leaf sq-norms via static slices, ALL used (stacked)
    @jax.jit
    def norms_loop(p):
        def body(c, _):
            x = p + c * 1e-30
            nrm = jnp.stack([
                jnp.sum(jnp.square(jax.lax.dynamic_slice_in_dim(x, o, s)))
                for o, s in zip(offsets, sizes)])
            return c + jnp.sum(nrm) * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["norms_all_ms"] = round(timed(norms_loop, (p,), iters, r) * 1e3, 2)
    print("norms_all", out["norms_all_ms"], flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
