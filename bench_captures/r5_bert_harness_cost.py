"""Full BERT fwd+bwd: native-pytree params vs flat-fp32-master unravel.
Isolates the cost of the master-vector indirection.  Scratch.
Run one variant at a time: MODE=tree|flat."""
import json
import os
import time

import jax
import jax.flatten_util
import jax.numpy as jnp


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def main():
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig, bert_model_provider

    r = rtt()
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    batch, seq, iters = 32, 128, 4
    cfg = BertConfig(max_seq_length=seq, hidden_dropout=0.0,
                     attention_dropout=0.0, params_dtype=jnp.bfloat16)
    model = bert_model_provider(cfg, add_binary_head=False)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size)
    types = jnp.zeros((batch, seq), jnp.int32)
    labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(1), tokens, types,
                        lm_labels=labels)
    out = {}

    mode = os.environ.get("MODE", "tree")

    def loss_tree(p):
        loss, _ = model.apply(p, tokens, types, lm_labels=labels)
        return loss

    @jax.jit
    def tree_loop(params):
        def body(c, _):
            bump = jax.tree.map(
                lambda x: x * (1 + jnp.asarray(c, x.dtype) * 1e-30), params)
            l, g = jax.value_and_grad(loss_tree)(bump)
            gn = sum(jnp.sum(x.astype(jnp.float32) ** 2)
                     for x in jax.tree.leaves(g))
            return c + l * 0 + gn * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    if mode == "tree":
        out["tree_fwd_bwd_ms"] = round(
            timed(tree_loop, (params,), iters, r) * 1e3, 2)
        print(json.dumps(out), flush=True)
        return

    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = flat.astype(jnp.float32)

    def loss_flat(fp):
        return loss_tree(unravel(fp))

    @jax.jit
    def flat_loop(fp):
        def body(c, _):
            l, g = jax.value_and_grad(loss_flat)(fp + c * 1e-30)
            return c + l * 0 + jnp.sum(g * g) * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    out["flat_fwd_bwd_ms"] = round(
        timed(flat_loop, (flat,), iters, r) * 1e3, 2)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
