"""On-chip experiment runner for the next healthy tunnel window (r4).

The watcher's standard capture records the official numbers; this script
answers the open tuning questions in one go, each in its own subprocess
(a wedge kills one experiment, not the batch):

1. GPT flagship main leg at batch 8 vs 16 vs 24 — bigger GEMM M dims
   may lift MFU past the exp2 savings alone.
2. Flash attention fwd+bwd at the flagship shape with block 512 vs 1024
   — re-validate the r3 block choice under the base-2 kernels.
3. The bert leg (north-star config) — standalone, so a partial window
   still captures it.

Usage:  python bench_captures/r4_experiments.py [--quick]
Writes: bench_captures/r4_experiments_out.json (one JSON object per key)
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r4_experiments_out.json"

SNIPPETS = {
    "gpt_batch_sweep": """
import json, time
import jax, jax.numpy as jnp, jax.flatten_util
import sys; sys.path.insert(0, {repo!r})
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider
from apex_tpu.ops.fused_update import fused_adam_flat

assert jax.default_backend() in ("tpu", "axon")
cfg = GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=8,
                num_attention_heads=16, max_seq_length=1024,
                hidden_dropout=0.0, attention_dropout=0.0,
                params_dtype=jnp.bfloat16)
parallel_state.destroy_model_parallel()
parallel_state.initialize_model_parallel(1)
model = gpt_model_provider(cfg)
res = {{}}
for batch in (8, 16, 24):
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, 1024), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = flat.astype(jnp.float32)

    def step(state, _):
        fp, m, v = state
        loss, g = jax.value_and_grad(
            lambda fp: model.apply(unravel(fp), tokens, labels))(fp)
        return fused_adam_flat(fp, g.astype(jnp.float32), m, v, lr=1e-4,
                               beta1=0.9, beta2=0.999, eps=1e-8,
                               weight_decay=0.0, step=1), None

    @jax.jit
    def loop(state):
        state, _ = jax.lax.scan(step, state, None, length=8)
        return jax.tree.map(lambda x: jnp.sum(x[:1]) if x.ndim else x,
                            state)

    state = (flat, jnp.zeros_like(flat), jnp.zeros_like(flat))
    jax.device_get(loop(state))
    best = 1e9
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(loop(state))
        best = min(best, time.perf_counter() - t0)
    sec = best / 8
    tps = batch * 1024 / sec
    n = int(flat.size)
    mfu = tps * (6 * n + 6 * 8 * 1024 * 1024) / 197e12
    res[str(batch)] = {{"sec_per_step": round(sec, 5),
                        "tokens_per_s": round(tps, 1),
                        "mfu": round(mfu, 4)}}
print("RESULT" + json.dumps(res))
""",
    "attn_block_ab": """
import json, time
import jax, jax.numpy as jnp
import sys; sys.path.insert(0, {repo!r})
from apex_tpu.ops.attention import flash_attention

assert jax.default_backend() in ("tpu", "axon")
b, h, s, d = 8, 16, 1024, 64
ks = jax.random.split(jax.random.PRNGKey(0), 3)
q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16) for kk in ks)
res = {{}}
for blk in (512, 1024):
    def fb(q, k, v):
        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=blk,
                block_k=blk).astype(jnp.float32))
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    @jax.jit
    def loop(q, k, v):
        def body(c, _):
            dq, dk, dv = fb(q + c * 1e-30, k, v)
            return c + jnp.sum(dq.ravel()[:1].astype(jnp.float32)), None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=20)
        return c

    jax.device_get(loop(q, k, v))
    best = 1e9
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(loop(q, k, v))
        best = min(best, time.perf_counter() - t0)
    res[str(blk)] = {{"fwd_bwd_us": round(best / 20 * 1e6, 1)}}
print("RESULT" + json.dumps(res))
""",
    "bert_leg": """
import json, sys; sys.path.insert(0, {repo!r})
import bench
bench._bench_micro_leg("bert", force_cpu=False)
""",
}


def run(name: str, code: str, timeout: int):
    try:
        r = subprocess.run([sys.executable, "-c", code.format(repo=str(REPO))],
                           capture_output=True, text=True, timeout=timeout,
                           cwd=str(REPO))
    except subprocess.TimeoutExpired:
        return {"error": f"timed out after {timeout}s"}
    for line in reversed((r.stdout or "").strip().splitlines()):
        if line.startswith("RESULT"):
            return json.loads(line[len("RESULT"):])
        try:
            obj = json.loads(line)
            if isinstance(obj, dict):
                return obj
        except json.JSONDecodeError:
            continue
    return {"error": f"rc={r.returncode}; stderr tail: {r.stderr[-300:]}"}


def main():
    quick = "--quick" in sys.argv
    out = {}
    for name, timeout in (("bert_leg", 900), ("gpt_batch_sweep", 1200),
                          ("attn_block_ab", 700)):
        if quick and name != "bert_leg":
            continue
        print(f"=== {name} ===", flush=True)
        out[name] = run(name, SNIPPETS[name], timeout)
        print(json.dumps({name: out[name]}), flush=True)
        OUT.write_text(json.dumps(out, indent=1) + "\n")
    print("wrote", OUT)


if __name__ == "__main__":
    main()
