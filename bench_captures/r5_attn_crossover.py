"""Measure flash-kernel vs XLA-attention crossover over seq length at
fixed tokens (b*s = 4096, h=16, d=64) and at fixed batch.  Scratch."""
import json
import time

import jax
import jax.numpy as jnp


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def bench_grad(f, args, iters, r):
    def loss(*a):
        return jnp.sum(f(*a).astype(jnp.float32) ** 2)

    @jax.jit
    def loop(args):
        def body(c, _):
            a0 = args[0] + jnp.asarray(c, args[0].dtype) * 1e-30
            gs = jax.grad(loss, argnums=(0, 1, 2))(a0, *args[1:])
            bump = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in gs)
            return c + bump * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    return round(timed(loop, (args,), iters, r) * 1e6, 1)


def main():
    from apex_tpu.ops.attention import flash_attention, mha_reference
    r = rtt()
    rows = []
    for s, batch, iters in ((128, 32, 100), (256, 16, 60), (512, 8, 40),
                            (1024, 4, 20), (2048, 4, 10)):
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i),
                                     (batch, 16, s, 64), jnp.bfloat16)
                   for i in range(3))
        for causal in (False, True):
            fl = bench_grad(lambda q, k, v, c=causal: flash_attention(
                q, k, v, causal=c), (q, k, v), iters, r)
            rf = bench_grad(lambda q, k, v, c=causal: mha_reference(
                q, k, v, causal=c), (q, k, v), iters, r)
            rows.append({"s": s, "b": batch, "causal": causal,
                         "flash_us": fl, "ref_us": rf})
            print(rows[-1], flush=True)
    print(json.dumps(rows), flush=True)


if __name__ == "__main__":
    main()
