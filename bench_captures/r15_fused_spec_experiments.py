"""On-chip fused-block decode + speculative decoding experiment queue
for the next healthy tunnel window (r15, ISSUE 15): paged infer-leg
A/Bs that land the fused-vs-unfused per-token decode latency and the
speculation rates (base / prompt-lookup / replay-ceiling, acceptance
rate, effective-vs-floor tokens/s) in the same capture as the knob
provenance stamps (``infer_decode_fusion`` / ``infer_fusion_min_pages``
/ ``infer_spec_k``).

Same discipline as ``r9_xent_fused_experiments.py``: every experiment
drives a REAL ``bench.py`` leg in its own subprocess, results are
rewritten after EVERY experiment, and re-runs resume.

What these answer:

1. Fused-block crossover: the CPU dryrun can only show the capture
   shape (interpret-mode Pallas is meaningless for wall time); on
   chip, the fused kernel's win should GROW with the virtual window
   (pages streamed once through one kernel with weights resident vs
   per-op dispatches re-reading weights per sublayer).  The seq sweep
   brackets where ``APEX_TPU_FUSION_MIN_PAGES`` should sit — today's
   8 is PROVISIONAL.
2. Speculation k sweep: effective tokens/s vs k at the flagship shape
   — more drafts amortize more dispatch but the verify slab's compute
   grows and acceptance decays with depth; the replay-ceiling stamp
   separates machinery overhead from draft quality.
3. The acceptance criterion: greedy speculation >= 1.5x effective
   tokens/s on the repeated-structure workload (the
   ``infer_spec_oracle_tokens_per_s`` vs ``infer_spec_base_tokens_
   per_s`` pair, with ``infer_spec_effective_tokens_per_s`` as the
   realistic prompt-lookup number).

Usage:  python bench_captures/r15_fused_spec_experiments.py [--quick]
Writes: bench_captures/r15_fused_spec_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r15_fused_spec_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # the flagship paged shape: fused A/B + speculation legs ride the
    # standard infer leg (seq 1024 => 16 pages/slot, auto would fuse)
    ("infer_paged_base", ["--leg", "infer", "--override", "paged=1"],
     1200),
    # window sweep for the fusion crossover (pages/slot = seq/64)
    ("infer_seq512", ["--leg", "infer", "--override", "paged=1",
                      "--override", "seq=512"], 1200),
    ("infer_seq2048", ["--leg", "infer", "--override", "paged=1",
                       "--override", "seq=2048"], 1500),
    # speculation depth sweep at the flagship shape
    ("infer_spec_k2", ["--leg", "infer", "--override", "paged=1",
                       "--override", "spec_k=2"], 1200),
    ("infer_spec_k8", ["--leg", "infer", "--override", "paged=1",
                       "--override", "spec_k=8"], 1200),
    # fused decode UNDER the serve path too: the whole leg with the
    # engine-level knob armed (env: marker = environment variable for
    # the subprocess, not a bench override), so the serve TTFT/decode
    # stamps and the speculation wave all ride the fused executable
    ("infer_fusion_on", ["--leg", "infer", "--override", "paged=1",
                         "env:APEX_TPU_DECODE_FUSION=1"], 1200),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    import os
    env, cleaned = None, []
    for a in args:
        if a.startswith("env:"):
            env = dict(env or os.environ)
            name, _, val = a[4:].partition("=")
            env[name] = val
        else:
            cleaned.append(a)
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *cleaned],
            capture_output=True, text=True, timeout=timeout,
            cwd=str(REPO), env=env)
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {'ERROR ' + res['_error'] if '_error' in res else 'ok'}",
              flush=True)
    print(f"results: {OUT}")


if __name__ == "__main__":
    main()
