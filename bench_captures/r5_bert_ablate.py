"""Ablate the BERT fwd+bwd on-chip: head-only (L=0) vs full (L=24),
plus a no-head variant (mean of final hidden).  Scratch diagnostic."""
import json
import time

import jax
import jax.flatten_util
import jax.numpy as jnp


def rtt():
    triv = jax.jit(lambda x: x + 1.0)
    jax.device_get(triv(jnp.float32(0)))
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(triv(jnp.float32(1)))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(loop, args, iters, r):
    jax.device_get(loop(*args))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        jax.device_get(loop(*args))
        samples.append(time.perf_counter() - t0)
    return (min(samples) - r) / iters


def fwd_bwd_ms(model, params, tokens, types, labels, iters, r):
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    flat = flat.astype(jnp.float32)

    def loss_fn(fp):
        out = model.apply(unravel(fp), tokens, types, lm_labels=labels)
        loss = out[0] if isinstance(out, tuple) else out
        return loss

    @jax.jit
    def loop(fp):
        def body(c, _):
            l, g = jax.value_and_grad(loss_fn)(fp + c * 1e-30)
            # full grad feeds the carry via its global norm: nothing for
            # XLA to slice away
            return c + l * 0 + jnp.sum(g * g) * 1e-30, None
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=iters)
        return c
    return round(timed(loop, (flat,), iters, r) * 1e3, 2)


def main():
    from apex_tpu.transformer import parallel_state
    from apex_tpu.transformer.testing import BertConfig, bert_model_provider

    r = rtt()
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    batch, seq, iters = 32, 128, 4
    out = {}

    def data(cfg):
        tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                    cfg.vocab_size)
        types = jnp.zeros((batch, seq), jnp.int32)
        labels = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                    cfg.vocab_size)
        return tokens, types, labels

    for tag, nl in (("head_only_L0", 0), ("full_L24", 24)):
        cfg = BertConfig(max_seq_length=128, num_layers=nl,
                         hidden_dropout=0.0, attention_dropout=0.0,
                         params_dtype=jnp.bfloat16)
        model = bert_model_provider(cfg, add_binary_head=False)
        tokens, types, labels = data(cfg)
        params = model.init(jax.random.PRNGKey(1), tokens, types,
                            lm_labels=labels)
        out[tag] = fwd_bwd_ms(model, params, tokens, types, labels,
                              iters, r)
        print(tag, out[tag], flush=True)

    out["per_layer_ms"] = round((out["full_L24"] - out["head_only_L0"]) / 24,
                                3)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
