"""On-chip ZeRO experiment queue for the next healthy tunnel window
(r6, ISSUE 3): the batch-48/64 BERT ZeRO captures plus zero-overhead
A/Bs on the flagship legs.

Same discipline as ``r5_experiments.py``: every experiment drives a
REAL ``bench.py`` leg in its own subprocess (``--inner tpu --leg X
--override k=v``) so the measured code is the shipped code, results
are rewritten after EVERY experiment (a wedge mid-batch keeps
everything already captured), and re-runs resume.

What these answer:

1. ``zero=1`` at the committed batch-32 BERT shape — the pure program-
   shape overhead of the zero step on ONE chip (dp=1: psum_scatter /
   all_gather are no-ops, so any delta is the restructured program,
   not communication).  This is the control for every later multi-chip
   number.
2. batch 48 (the largest no-remat HBM fit, VERDICT r5) and batch 64
   (+remat / +bf16-CE-residuals) under zero — the memory lever the
   north-star MFU push is gated on.  NOTE on one chip dp=1 ZeRO frees
   no memory (the shard IS the buffer); these rows pin the throughput
   side so the first multi-chip window (``--override zero_dp=N``) can
   read off the memory win against a known-speed baseline.
3. The same A/B on the GPT main leg and the llama leg.

Usage:  python bench_captures/r6_zero_experiments.py [--quick]
Writes: bench_captures/r6_zero_experiments_out.json
"""
from __future__ import annotations

import json
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
OUT = REPO / "bench_captures" / "r6_zero_experiments_out.json"

# (key, bench.py args, timeout_s); --quick runs only the first row.
EXPERIMENTS = [
    # dp=1 zero-overhead control at the committed north-star shape
    ("bert_zero_b32", ["--leg", "bert", "--override", "zero=1"], 1200),
    ("bert_zero_b48", ["--leg", "bert", "--override", "zero=1",
                       "--override", "batch=48"], 1200),
    ("bert_zero_b64_remat", ["--leg", "bert", "--override", "zero=1",
                             "--override", "batch=64",
                             "--override", "remat=1"], 1200),
    ("bert_zero_b64_ce_half", ["--leg", "bert", "--override", "zero=1",
                               "--override", "batch=64",
                               "--override", "ce_half=1"], 1200),
    # non-zero twins for any shape not already in r5_experiments_out
    ("bert_b48", ["--leg", "bert", "--override", "batch=48"], 1200),
    ("gpt_zero_b8", ["--leg", "main", "--override", "zero=1"], 2400),
    ("llama_zero", ["--leg", "llama", "--override", "zero=1"], 1500),
]


def last_json_line(text: str):
    for cand in reversed(text.strip().splitlines()):
        cand = cand.strip()
        if cand.startswith("{") and cand.endswith("}"):
            try:
                return json.loads(cand)
            except json.JSONDecodeError:
                continue
    return None


def run_experiment(key, args, timeout):
    try:
        r = subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--inner", "tpu",
             *args],
            capture_output=True, text=True, timeout=timeout, cwd=str(REPO))
    except subprocess.TimeoutExpired as e:
        payload = last_json_line((e.stdout or b"").decode()
                                 if isinstance(e.stdout, bytes)
                                 else (e.stdout or ""))
        return dict(payload, _timeout=True) if payload else {
            "_error": f"timeout after {timeout}s"}
    payload = last_json_line(r.stdout)
    if payload is None:
        return {"_error": f"rc={r.returncode}; no JSON; "
                          f"stderr tail: {r.stderr[-300:]}"}
    return payload


def main() -> None:
    quick = "--quick" in sys.argv
    results = {}
    if OUT.exists():              # resume: keep earlier window's answers
        try:
            results = json.loads(OUT.read_text())
        except json.JSONDecodeError:
            results = {}
    todo = EXPERIMENTS[:1] if quick else EXPERIMENTS
    for key, args, timeout in todo:
        prev = results.get(key)
        if prev and not ({"_error", "_timeout"} & set(prev)):
            print(f"{key}: already captured, skipping", flush=True)
            continue
        print(f"{key}: running bench.py {' '.join(args)}", flush=True)
        res = run_experiment(key, args, timeout)
        if prev and ({"_error", "_timeout"} & set(res)) and len(res) <= \
                len(prev):
            print(f"{key}: retry no better, keeping previous", flush=True)
            continue
        results[key] = res
        OUT.write_text(json.dumps(results, indent=1) + "\n")
        print(f"{key}: {json.dumps(results[key])[:200]}", flush=True)
    clean = all(
        results.get(k) and not ({"_error", "_timeout"} & set(results[k]))
        for k, _, _ in EXPERIMENTS)
    if not quick and clean:
        print("ALL_COMPLETE", flush=True)


if __name__ == "__main__":
    main()
