"""Test harness config: force an 8-device CPU mesh.

Mirrors the reference's multi-process-on-one-host distributed test pattern
(``apex/transformer/testing/distributed_test_base.py``): we get N logical
devices on a single host so TP/PP/DP logic is exercised without hardware.

Note: the axon TPU plugin force-registers itself via sitecustomize and
overrides JAX_PLATFORMS, so we must flip jax.config *after* import (verified:
env-var routes are ignored in this image).
"""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
