"""Test harness config: force an 8-device CPU mesh.

Mirrors the reference's multi-process-on-one-host distributed test pattern
(``apex/transformer/testing/distributed_test_base.py``): we get N logical
devices on a single host so TP/PP/DP logic is exercised without hardware.

Note: the axon TPU plugin force-registers itself via sitecustomize and
overrides JAX_PLATFORMS, so we must flip jax.config *after* import (verified:
env-var routes are ignored in this image).
"""
import os

# jax<0.5 has no "jax_num_cpu_devices" config option; the XLA flag is the
# portable route and is still honoured because the backend initialises
# lazily (first device query), which has not happened at conftest import.
# REPLACE any inherited count (a driver exporting its own value would
# otherwise silently shrink every mesh in the suite).
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
_flags.append("--xla_force_host_platform_device_count=8")
os.environ["XLA_FLAGS"] = " ".join(_flags)

import jax
import pytest

import apex_tpu._jax_compat  # noqa: F401  (tests call jax.shard_map directly)

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # jax<0.5: covered by XLA_FLAGS above


# --- fast/slow lanes --------------------------------------------------------
# The default lane must fit a CI/driver budget (<300 s on the single-core
# box; the full suite takes ~19 min).  Tests that measured >~5 s are
# marked slow HERE, centrally, so the split is auditable and editable in
# one place; `pytest -m "slow or not slow"` runs everything.  Entries are
# nodeid prefixes (parametrized variants inherit the mark).
SLOW = {
    # llama fixture (new in r5): train/TP/remat legs measured 9-18 s
    "tests/L1/test_pretrain_llama.py::test_pretrain_llama_tp2_dp2_trains",
    "tests/L1/test_pretrain_llama.py::test_pretrain_llama_mqa_tp2",
    # r6 re-lane (VERDICT r5 weak #4): the three unlaned >5 s tests that
    # pushed the fast lane past its 300 s budget
    "tests/L0/run_transformer/test_llama_minimal.py::test_gqa_variants_finite",
    "tests/L0/run_transformer/test_llama_minimal.py::test_mqa_under_tp_replicated_kv",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_forward_only",
    "tests/L0/run_transformer/test_llama_minimal.py::test_mqa_tp_kv_grad_reduction_keeps_ranks_consistent",
    "tests/L0/run_transformer/test_llama_minimal.py::test_tp2_trains_under_shard_map",
    "tests/L0/run_transformer/test_llama_minimal.py::test_tp2_matches_tp1_exactly",
    "tests/L0/run_transformer/test_llama_minimal.py::test_remat_matches_baseline",
    "tests/L0/run_transformer/test_llama_minimal.py::test_loss_reasonable_and_trains",
    # r9 fused LM-head+CE model swaps: ~10 s each (two-model compile
    # per variant); the fast lane keeps the tp=2 sentinels (GPT tied
    # head + LLaMA GQA untied head — the two backward contracts)
    "tests/L0/run_transformer/test_fused_lm_xent.py::TestModelSwap::test_gpt_tied_head[1]",
    "tests/L0/run_transformer/test_fused_lm_xent.py::TestModelSwap::test_llama_untied_head_mha_gqa[1-4]",
    "tests/L0/run_transformer/test_fused_lm_xent.py::TestModelSwap::test_llama_untied_head_mha_gqa[1-2]",
    # r5 re-lane: measured >5 s in the 2026-07-31 durations run
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::test_scan_layers_dropout_trains",
    "tests/L0/run_transformer/test_moe.py::test_gather_dispatch_matches_onehot",
    "tests/L1/test_main_amp.py::test_static_loss_scale_runs",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_1f1b_stage_fn_sees_correct_microbatch",
    "tests/distributed/test_ddp_race_condition.py::test_matches_full_batch_single_device",
    # two-OS-process jax.distributed smoke (ISSUE 3 satellite): spawns
    # subprocesses, each paying a cold jax import (~10 s)
    "tests/distributed/test_multiprocess_cpu.py::test_two_process_distributed_init_and_kv_exchange",
    # full ZeRO dryrun leg in a subprocess (4 combos x jit, ~60 s); the
    # fast lane covers the same path via tests/L1/test_zero_train_step.py
    "tests/L1/test_zero_dryrun_leg.py::test_zero_leg_all_combos_green",
    # inference engine parity (ISSUE 4): multi-layer/multi-variant
    # prefill+decode-vs-full-forward runs measured 6-15 s each (every
    # layer compiles its Pallas kernels in interpret mode); the fast
    # lane keeps the 1-layer GQA sentinel
    # (test_llama_gqa_one_layer_greedy_fast) plus the kv-cache/decode-
    # attention/sampling/scheduler coverage
    # paged engine (ISSUE 6): multi-layer / dual-engine parity runs
    # measured 5-12 s; the fast lane keeps the 1-layer paged GQA
    # sentinel (test_llama_gqa_one_layer_paged_greedy_fast) plus the
    # admission-by-pages, truncation-reason and compile-count coverage
    "tests/L0/run_inference/test_paged_engine.py::test_paged_generate_equals_dense_generate",
    "tests/L0/run_inference/test_paged_engine.py::test_paged_kernel_path_engine_matches_dense",
    "tests/L0/run_inference/test_paged_engine.py::test_out_of_pages_is_backpressure_not_failure",
    "tests/L0/run_inference/test_engine_parity.py::test_gpt_greedy_decode_matches_full_forward",
    "tests/L0/run_inference/test_engine_parity.py::test_gpt_bf16_params_greedy_matches",
    "tests/L0/run_inference/test_engine_parity.py::test_llama_gqa_greedy_decode_matches_full_forward",
    "tests/L0/run_inference/test_engine_parity.py::test_llama_mqa_greedy_decode_matches_full_forward",
    "tests/L0/run_inference/test_engine_parity.py::test_decode_logits_match_full_forward_logits",
    "tests/L0/run_inference/test_engine_parity.py::test_continuous_batching_is_slot_invariant",
    "tests/L0/run_inference/test_engine_parity.py::test_bert_encode_only_path",
    "tests/L0/run_inference/test_weight_export.py::test_contrib_dp4_state_dict_equals_dense_export",
    # fused-block decode + speculative decoding (ISSUE 15): the
    # free-running dual-wave and the heavier layout variants measured
    # 6-12 s; the fast lane keeps the GQA step-locked fused sentinel,
    # the GPT fused-logits sentinel, both paged spec-parity sentinels
    # and the replay-drafter acceptance-criterion pin
    # tensor-parallel serving (ISSUE 17): the full parity matrix and
    # the scheduler-churn invariance run 5-10 s each (two engines per
    # variant, every tp mesh compiles its own shard_map executables);
    # the fast lane keeps the tp=2 GPT parity + per-rank-HBM sentinel
    # (test_gpt_tp2_parity_and_per_rank_hbm_fast) plus the contract and
    # env-knob coverage
    "tests/L0/run_inference/test_tp_serving.py::test_gpt_tp_matrix",
    "tests/L0/run_inference/test_tp_serving.py::test_llama_kv_replication_tp_matrix",
    "tests/L0/run_inference/test_tp_serving.py::test_spec_verify_tp2_parity",
    "tests/L0/run_inference/test_tp_serving.py::test_allocator_prefix_churn_invariant_and_zero_compiles_under_tp",
    "tests/L0/run_inference/test_fused_block.py::test_fused_gpt_matches_unfused_greedy",
    "tests/L0/run_inference/test_fused_block.py::test_fused_llama_tracks_unfused_step_locked[mha]",
    "tests/L0/run_inference/test_speculative.py::test_engine_drafter_self_draft_full_acceptance",
    "tests/L0/run_attention/test_attention_dropout.py::test_block_independent_and_large_bh",
    "tests/L0/run_contrib/test_parity_shims.py::TestFMHA::test_p_dropout_wired_and_needs_seed",
    "tests/L0/run_attention/test_attention_dropout.py::test_forward_matches_masked_oracle",
    "tests/L0/run_contrib/test_contrib.py::TestMultiheadAttn::test_self_attn_padding_mask",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_interleaved_requires_divisible_microbatches",
    "tests/L0/run_transformer/test_moe.py::test_sinkhorn_router_survives_huge_logits",
    "tests/L0/run_attention/test_flash_attention.py::test_mask_grads_match_oracle",
    "tests/L0/run_attention/test_attention_dropout.py::test_drop_fraction_and_rescale",
    "tests/L0/run_attention/test_flash_attention.py::test_fused_backward_masked_padded",
    "tests/L0/run_amp/test_amp.py::TestEndToEndTraining::test_o2_loss_decreases",
    "tests/L0/run_attention/test_ring_attention.py::test_grads_match_full_attention",
    "tests/L0/run_contrib/test_contrib_tier2.py::TestBottleneck::test_bottleneck_runs",
    "tests/L0/run_contrib/test_contrib_tier2.py::TestTransducer::test_loss_grad_finite_and_descends",
    "tests/L0/run_parallel/test_determinism.py::test_grad_reduction_bitwise_stable_across_bucketing",
    "tests/L0/run_parallel/test_sync_batchnorm.py::test_synced_stats_match_global_batch",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestBertMinimal::test_loss_with_padding_mask",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_loss_reasonable_tp1",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_remat_matches_baseline",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_sequence_parallel_matches_non_sp",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_trains_single_device",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_trains_with_dropout",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_tp2_dropout_decorrelates_ranks",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_sp_hidden_dropout_per_rank_masks",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::test_context_parallel_matches_cp1",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::test_scan_layers_matches_loop",
    "tests/L0/run_transformer/test_layers.py::test_sequence_parallel_column_row",
    "tests/L0/run_transformer/test_moe.py::test_1f1b_with_expert_parallel_moe_stage",
    "tests/L0/run_transformer/test_moe.py::test_gpt_moe_scan_layers_keeps_aux_losses",
    "tests/L0/run_transformer/test_moe.py::test_gpt_moe_tp_sp_trains_in_shard_map",
    "tests/L0/run_transformer/test_moe.py::test_gpt_with_moe_ffn",
    "tests/L0/run_transformer/test_moe.py::test_interleaved_with_expert_parallel_moe_stage",
    "tests/L0/run_transformer/test_moe.py::test_moe_ep1_matches_dense_reference",
    "tests/L0/run_transformer/test_moe.py::test_moe_ep4_matches_dense_per_shard",
    "tests/L0/run_transformer/test_moe.py::test_moe_grads_flow",
    "tests/L0/run_transformer/test_moe.py::test_moe_sinkhorn_router_end_to_end",
    "tests/L0/run_transformer/test_moe.py::test_moe_tp_ep_matches_dense_per_shard",
    "tests/L0/run_transformer/test_moe.py::test_moe_tp_ep_sp_matches_dense_per_shard",
    "tests/L0/run_transformer/test_moe.py::test_moe_tp_grads_match_dense",
    "tests/L0/run_transformer/test_moe.py::test_reduce_moe_grads_expert_scale_matches_dense",
    "tests/L0/run_transformer/test_moe.py::test_reduce_moe_grads_spans_context_axis",
    "tests/L0/run_transformer/test_moe.py::test_reduce_moe_grads_syncs_router_replicas",
    "tests/L0/run_transformer/test_moe.py::test_routing_statistics",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_1f1b_composes_with_remat",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_1f1b_memory_bounded_in_microbatches",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_interleaved_matches_reference",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_interleaved_memory_bounded_in_microbatches",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_interleaved_stage_fn_sees_correct_microbatch",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_no_pipelining_matches_reference",
    "tests/L0/run_transformer/test_pipeline_trace_cost.py::test_1f1b_trace_cost_bounded_with_gpt_stage",
    "tests/L0/run_transformer/test_pipeline_trace_cost.py::test_interleaved_trace_cost_bounded_with_gpt_stage",
    "tests/L0/run_transformer/test_tied_embedding_pp.py::test_tied_embedding_grads_match_oracle",
    "tests/L1/test_bert_pretrain.py::test_bert_pretrain_generalizes",
    "tests/L1/test_bert_pretrain.py::test_bert_pretrain_with_dropout_learns",
    "tests/L1/test_config5_topology.py::test_tp8_pp4_equivalence_32dev",
    "tests/L1/test_cross_run_compare.py::test_opt_level_tracks_o0",
    "tests/L1/test_cross_run_compare.py::test_same_level_rerun_is_deterministic",
    "tests/L1/test_main_amp.py::test_baseline_config0_resnet50_o0",
    "tests/L1/test_main_amp.py::test_loss_decreases",
    "tests/L1/test_moe_example.py::test_moe_example_trains",
    "tests/L1/test_pretrain_gpt.py::test_gpt_pretrain_learns",
    "tests/L1/test_pretrain_gpt.py::test_gpt_pretrain_learns_interleaved",
    "tests/L1/test_pretrain_gpt.py::test_gpt_pretrain_learns_with_dropout",
    "tests/distributed/test_amp_master_params.py::test_master_flow_matches_fp32_reference",
    "tests/distributed/test_amp_master_params.py::test_master_params_stay_synced_across_ranks",
    "tests/distributed/test_ddp_race_condition.py::test_every_bucketing_matches_fused",
    # second tier (~4.5-13 s each); heavier variants of coverage the fast
    # lane keeps via their smaller siblings
    "tests/L0/run_contrib/test_contrib_tier2.py::TestBottleneck::test_spatial_matches_unsharded",
    "tests/L0/run_contrib/test_contrib_tier2.py::TestTransducer::test_joint_shape_and_relu",
    "tests/L0/run_contrib/test_contrib_tier2.py::TestTransducer::test_loss_matches_bruteforce",
    "tests/L0/run_contrib/test_parity_shims.py::TestFMHA::test_packed_varlen_matches_dense",
    "tests/L0/run_contrib/test_parity_shims.py::test_checkpoint_resume_identical",
    "tests/L0/run_contrib/test_parity_shims.py::TestConvBiasReLU::test_conv_bias_relu",
    "tests/L0/run_contrib/test_distributed_optimizers.py::test_dist_adam_matches_fused_adam",
    "tests/L0/run_optimizers/test_fused_optimizer.py::TestEmptyBuffers::test_odd_sizes_match_reference",
    "tests/L0/run_fused_layer_norm/test_fused_layer_norm.py::test_rms_norm_grads",
    "tests/L0/run_fused_layer_norm/test_fused_layer_norm.py::test_layer_norm_grads",
    "tests/L0/run_fused_layer_norm/test_fused_layer_norm.py::test_layer_norm_forward[True-float32-shape4]",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_1f1b_matches_reference",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_1f1b_with_per_microbatch_dropout_matches_reference",
    "tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py::test_interleaved_forward_only",
    "tests/L0/run_parallel/test_ddp.py::TestSyncBatchNorm::test_stats_match_full_batch",
    "tests/L0/run_parallel/test_ddp.py::TestDDP::test_bucketing_matches_single_psum",
    "tests/L0/run_parallel/test_ddp.py::TestDDP::test_ddp_grad_correctness_vs_single_process",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestGPTMinimal::test_tp4_loss_finite_and_scaled",
    "tests/L0/run_transformer/test_gpt_bert_minimal.py::TestBertMinimal::test_tp4_runs",
    "tests/L0/run_transformer/test_fused_rope.py::test_cached_matches_uncached",
    "tests/L0/run_attention/test_ulysses_attention.py::test_grads_match_full_attention",
    "tests/L0/run_attention/test_attention_dropout.py::test_split_backward_matches_fused",
    "tests/L0/run_attention/test_attention_dropout.py::test_ring_dropout_matches_unsharded",
    "tests/L0/run_attention/test_attention_dropout.py::test_masked_plus_dropout_matches_oracle",
    "tests/L0/run_attention/test_attention_dropout.py::test_ulysses_dropout_reproducible_and_finite",
    "tests/L0/run_attention/test_attention_dropout.py::test_backward_regenerates_identical_mask",
    "tests/L0/run_attention/test_attention_dropout.py::test_deterministic_and_seed_sensitive",
    "tests/L0/run_attention/test_attention_dropout.py::test_padded_shape_with_dropout",
    "tests/L0/run_attention/test_ring_attention.py::test_causal_outlier_grads_finite",
    "tests/L0/run_attention/test_flash_attention.py::test_padded_shape_grads_match_oracle",
    "tests/L0/run_attention/test_flash_attention.py::test_fused_and_split_backward_agree",
    "tests/L0/run_contrib/test_contrib.py::TestMultiheadAttn::test_self_attn_impls_match",
    "tests/L0/run_contrib/test_contrib.py::TestMultiheadAttn::test_self_attn_norm_add",
}


def pytest_collection_modifyitems(config, items):
    # a test named EXPLICITLY on the command line must run even in the
    # default lane — otherwise `pytest <file>::<slow_test>` silently
    # collects nothing under the addopts -m filter
    explicit = {a.split("[", 1)[0].replace("\\", "/")
                for a in config.invocation_params.args if "::" in a}
    hits = set()
    for item in items:
        base = item.nodeid.split("[", 1)[0]
        if base in explicit:
            continue
        # exact (parametrized) nodeids override; base names mark all
        # variants
        if base in SLOW:
            hits.add(base)
            item.add_marker(pytest.mark.slow)
        elif item.nodeid in SLOW:
            hits.add(item.nodeid)
            item.add_marker(pytest.mark.slow)
    # guard against silent rot: a renamed/moved slow test would drop back
    # into the fast lane while its stale entry matches nothing
    if not explicit and len(items) > 300:
        stale = SLOW - hits
        if stale:
            import warnings
            warnings.warn(
                f"tests/conftest.py SLOW entries matched no collected "
                f"test (renamed/moved?): {sorted(stale)}")


# --- fast-lane duration budget ---------------------------------------------
# The default lane must stay <300 s total (driver/CI budget; it ran 278 s
# at r4's 385 tests).  Enforced here, not by convention: any single
# fast-lane test that takes >6 s on this box belongs in SLOW above —
# the per-test ceiling keeps the lane's headroom from eroding one test
# at a time while staying robust to overall box speed.
_FAST_TEST_CEILING_S = 6.0
_overlong = []


def pytest_runtest_logreport(report):
    if report.when == "call" and report.duration > _FAST_TEST_CEILING_S \
            and not any(m == "slow" for m in report.keywords):
        _overlong.append((report.nodeid, report.duration))


def pytest_sessionfinish(session, exitstatus):
    # only police full-lane runs; single-test invocations and the slow
    # lane are exempt (explicit selection bypasses the marker filter)
    if session.testscollected > 300 and _overlong:
        lines = "\n".join(f"  {nid}: {dur:.1f}s" for nid, dur in _overlong)
        import warnings
        warnings.warn(
            f"fast-lane tests exceeded the {_FAST_TEST_CEILING_S:.0f}s "
            f"per-test ceiling — add them to tests/conftest.py SLOW:\n"
            f"{lines}")
