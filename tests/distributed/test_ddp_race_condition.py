"""DDP reduction-ordering correctness (reference:
``tests/distributed/DDP/ddp_race_condition_test.py`` — the bucketed
allreduce must produce correct gradients even when parameters become ready
out of order or produce no gradient at all on some iterations).

The torch reference races autograd-hook firing order against bucket
flushes; under jit there is no asynchrony to race, but the property it
protects — bucket assembly must not misalign gradients when some params
have zero/absent grads or when bucket boundaries fall mid-tensor — is
exactly testable: every bucketing config must agree with the single fused
psum bit-for-bit, across a multi-step training loop.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import DistributedDataParallel

STEPS = 4


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def _params():
    rng = np.random.RandomState(0)
    # deliberately awkward sizes so small buckets split mid-tensor
    return {
        "w1": jnp.asarray(rng.randn(7, 13), jnp.float32),
        "w2": jnp.asarray(rng.randn(13, 5), jnp.float32),
        "unused": jnp.asarray(rng.randn(3, 3), jnp.float32),
        "b": jnp.zeros((5,), jnp.float32),
    }


def _loss(p, x, y, step):
    h = jnp.tanh(x @ p["w1"])
    pred = h @ p["w2"] + p["b"]
    loss = jnp.mean((pred - y) ** 2)
    # "unused" contributes only on even steps -> its grad is exactly zero
    # on odd steps (the reference's param-with-no-grad race case)
    gate = (step % 2 == 0).astype(jnp.float32)
    return loss + gate * 1e-3 * jnp.sum(p["unused"] ** 2)


def _train(ddp, params, X, Y, mesh):
    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=P(), check_vma=False)
    def run(params, x, y):
        def body(params, step):
            g = jax.grad(_loss)(params, x, y, step)
            g = ddp.reduce_gradients(g)
            return jax.tree.map(lambda p, gg: p - 0.05 * gg, params, g), None
        params, _ = jax.lax.scan(body, params, jnp.arange(STEPS))
        return params
    return jax.tree.map(np.asarray, run(params, X, Y))


@pytest.mark.parametrize("message_size", [1, 64, 333, 10_000_000])
def test_every_bucketing_matches_fused(message_size):
    mesh = _mesh()
    ndev = len(jax.devices())
    rng = np.random.RandomState(1)
    params = _params()
    X = jnp.asarray(rng.randn(4 * ndev, 7), jnp.float32)
    Y = jnp.asarray(rng.randn(4 * ndev, 5), jnp.float32)

    fused = _train(DistributedDataParallel(delay_allreduce=True),
                   params, X, Y, mesh)
    bucketed = _train(DistributedDataParallel(message_size=message_size),
                      params, X, Y, mesh)
    for name in params:
        np.testing.assert_array_equal(fused[name], bucketed[name])


def test_matches_full_batch_single_device():
    """End-to-end: sharded-batch DDP training == full-batch single-device
    training (grads average exactly)."""
    mesh = _mesh()
    ndev = len(jax.devices())
    rng = np.random.RandomState(2)
    params = _params()
    X = jnp.asarray(rng.randn(4 * ndev, 7), jnp.float32)
    Y = jnp.asarray(rng.randn(4 * ndev, 5), jnp.float32)

    got = _train(DistributedDataParallel(message_size=128),
                 params, X, Y, mesh)

    ref = params
    for step in range(STEPS):
        g = jax.grad(_loss)(ref, X, Y, jnp.asarray(step))
        ref = jax.tree.map(lambda p, gg: p - 0.05 * gg, ref, g)
    for name in params:
        np.testing.assert_allclose(got[name], np.asarray(ref[name]),
                                   rtol=2e-5, atol=1e-6)
