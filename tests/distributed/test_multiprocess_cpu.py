"""Two-process ``jax.distributed`` smoke test (slow lane).

Executable evidence for the multi-process story MIGRATION.md documents
(VERDICT missing #4): the recipe is one SPMD process per host plus
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)`` — this test actually runs it, as two OS processes on the
CPU backend, and asserts the coordination service forms, the global
device view is consistent (``device_count == 2 x local``,
``process_index``/``process_count`` correct), and a payload round-trips
through the coordination-service KV store in both directions.

Cross-process collectives are not implemented by this image's CPU
backend (the worker pins the exact error so a jax upgrade that adds
them flips the marker to MULTIPROC-COLLECTIVES-OK); on TPU pods the
identical init path serves real collectives over ICI/DCN.
"""
import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_multiprocess_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distributed_init_and_kv_exchange():
    nproc = 2
    port = _free_port()
    env = dict(os.environ)
    # each worker gets ONE cpu device: the 2x-local global view is then
    # unambiguous (2 devices total, one per process)
    env["XLA_FLAGS"] = " ".join(
        [f for f in env.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
        + ["--xla_force_host_platform_device_count=1"])
    procs = [subprocess.Popen(
        [sys.executable, WORKER, str(rank), str(nproc), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"worker {rank} rc={p.returncode}:\n{out[-2000:]}")
        assert f"MULTIPROC-OK {rank}" in out, out[-2000:]
        assert (f"MULTIPROC-COLLECTIVES-OK {rank}" in out
                or f"MULTIPROC-COLLECTIVES-UNSUPPORTED {rank}" in out), \
            out[-2000:]
