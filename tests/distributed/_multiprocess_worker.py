"""Worker for the two-process ``jax.distributed`` smoke test.

Run as ``python _multiprocess_worker.py <process_id> <num_processes>
<coordinator_port>``.  Validates the multi-host recipe MIGRATION.md
documents (one process per host + ``jax.distributed.initialize``):

* the coordination service forms (rank 0 serves, others connect);
* every process sees the GLOBAL device count = num_processes x local;
* ``jax.process_index()`` matches the assigned rank;
* a value round-trips through the coordination-service KV store in
  both directions (each process publishes, then blocking-reads its
  peer's key) — cross-process coordination, not just a lucky init.

Cross-process *collectives* are exercised only when the backend
supports them: this image's jax/XLA CPU backend reports
"Multiprocess computations aren't implemented on the CPU backend", so
the collective leg degrades to asserting exactly that error (a real
TPU pod runs the same init path with working collectives).  Prints
``MULTIPROC-OK <rank>`` on success; any assertion kills the process
and the parent test fails on the exit code.
"""
import sys

import jax


def main() -> None:
    pid, nproc, port = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc, process_id=pid)

    local = jax.local_device_count()
    assert jax.device_count() == nproc * local, (
        jax.device_count(), nproc, local)
    assert jax.process_index() == pid, (jax.process_index(), pid)
    assert jax.process_count() == nproc, jax.process_count()

    from jax._src import distributed
    client = distributed.global_state.client
    client.key_value_set(f"smoke/{pid}", f"payload-from-{pid}")
    for peer in range(nproc):
        if peer == pid:
            continue
        got = client.blocking_key_value_get(f"smoke/{peer}", 30_000)
        assert got == f"payload-from-{peer}", (peer, got)

    # collective leg: works on backends with multi-process support
    # (TPU pods); on this CPU backend it must fail with the KNOWN
    # not-implemented error, not hang or crash differently
    import jax.numpy as jnp
    try:
        from jax.experimental import multihost_utils
        vals = multihost_utils.process_allgather(jnp.float32(pid + 1))
        assert sorted(float(v) for v in vals) == [
            float(r + 1) for r in range(nproc)], vals
        print(f"MULTIPROC-COLLECTIVES-OK {pid}", flush=True)
    except Exception as e:  # noqa: BLE001 — asserting the exact mode
        assert "Multiprocess computations aren't implemented" in str(e), e
        print(f"MULTIPROC-COLLECTIVES-UNSUPPORTED {pid}", flush=True)

    print(f"MULTIPROC-OK {pid}", flush=True)


if __name__ == "__main__":
    main()
