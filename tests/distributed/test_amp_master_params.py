"""Mixed-precision master weights under data-parallel training (reference:
``tests/distributed/amp_master_params/amp_master_params.py`` — after DDP
training steps, every rank's fp32 master params must be identical, and the
half-precision model params must equal the masters cast down).

Mesh-native analog of the reference's two-process NCCL run: an 8-device
CPU mesh shards the batch over the ``data`` axis; each rank computes bf16
grads, DDP-psums them, copies onto fp32 masters (``model_grads_to_master_
grads``), steps the masters, and writes back down (``master_params_to_
model_params``) — the O2-style flow.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.fp16_utils import (
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
)
from apex_tpu.parallel import DistributedDataParallel

STEPS, LR = 3, 0.05


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def test_master_params_stay_synced_across_ranks():
    mesh = _mesh()
    ndev = len(jax.devices())
    rng = np.random.RandomState(0)
    params32 = {"w": jnp.asarray(rng.randn(16, 4), jnp.float32),
                "b": jnp.zeros((4,), jnp.float32)}
    model_params = network_to_half(params32)
    _, master_params = prep_param_lists(model_params)
    X = jnp.asarray(rng.randn(8 * ndev, 16), jnp.float32)
    Y = jnp.asarray(rng.randn(8 * ndev, 4), jnp.float32)
    ddp = DistributedDataParallel()

    def loss_fn(mp, x, y):
        pred = x.astype(jnp.bfloat16) @ mp["w"] + mp["b"]
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P(), P("data"), P("data")),
        out_specs=(P("data"), P("data")), check_vma=False)
    def train(model_params, master_params, x, y):
        for _ in range(STEPS):
            g = jax.grad(loss_fn)(model_params, x, y)
            g = ddp.reduce_gradients(g)            # bf16 psum-mean
            g32 = model_grads_to_master_grads(g)
            master_params = jax.tree.map(
                lambda m, gg: m - LR * gg, master_params, g32)
            model_params = master_params_to_model_params(
                model_params, master_params)
        # stack per-rank copies so the host can check cross-rank equality
        return (jax.tree.map(lambda p: p[None], model_params),
                jax.tree.map(lambda p: p[None], master_params))

    model_out, master_out = train(model_params, master_params, X, Y)

    for name in ("w", "b"):
        model_ranks = np.asarray(
            model_out[name].astype(jnp.float32))
        master_ranks = np.asarray(master_out[name])
        # 1. every rank holds bit-identical masters (the reference's
        #    "python -c compare master0/master1" check)
        for r in range(1, model_ranks.shape[0]):
            np.testing.assert_array_equal(master_ranks[r], master_ranks[0])
            np.testing.assert_array_equal(model_ranks[r], model_ranks[0])
        # 2. model params == masters cast to bf16 (master->model contract)
        np.testing.assert_array_equal(
            model_ranks[0],
            np.asarray(master_ranks[0].astype(np.float32)
                       ).astype(jnp.bfloat16).astype(np.float32))
        # 3. masters really moved (test isn't vacuous)
        assert not np.allclose(master_ranks[0],
                               np.asarray(params32[name]))


def test_master_flow_matches_fp32_reference():
    """With grads computed in bf16 but accumulated/stepped in fp32
    masters, the trajectory must track a pure-fp32 run (loose bf16
    tolerance) — the property that makes O2 trainable at all."""
    mesh = _mesh()
    ndev = len(jax.devices())
    rng = np.random.RandomState(1)
    params32 = {"w": jnp.asarray(rng.randn(16, 4) * 0.1, jnp.float32)}
    X = jnp.asarray(rng.randn(8 * ndev, 16), jnp.float32)
    Y = jnp.asarray(rng.randn(8 * ndev, 4), jnp.float32)
    ddp = DistributedDataParallel()

    def bf16_loss(mp, x, y):
        pred = x.astype(jnp.bfloat16) @ mp["w"]
        return jnp.mean((pred.astype(jnp.float32) - y) ** 2)

    def fp32_loss(p, x, y):
        return jnp.mean((x @ p["w"] - y) ** 2)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P("data"), P("data")),
        out_specs=P(), check_vma=False)
    def train_mixed(master, x, y):
        model = network_to_half(master)
        for _ in range(STEPS):
            g = jax.grad(bf16_loss)(model, x, y)
            g = ddp.reduce_gradients(g)
            master = jax.tree.map(
                lambda m, gg: m - LR * gg,
                master, model_grads_to_master_grads(g))
            model = master_params_to_model_params(model, master)
        return master

    got = np.asarray(train_mixed(params32, X, Y)["w"])

    ref = params32
    for _ in range(STEPS):
        ref = jax.tree.map(lambda p, g: p - LR * g, ref,
                           jax.grad(fp32_loss)(ref, X, Y))
    np.testing.assert_allclose(got, np.asarray(ref["w"]),
                               atol=0.02, rtol=0.05)
