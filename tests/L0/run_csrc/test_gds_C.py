"""GDS I/O shim tests (reference: ``apex/contrib/gpu_direct_storage`` over
cuFile): Python-fallback roundtrip always; native GIL-releasing path when
the ``_gds_C`` extension is built (APEX_TPU_CPP_EXT=1)."""
import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu.contrib import gpu_direct_storage as gds


def test_roundtrip(tmp_path):
    f = str(tmp_path / "blob.bin")
    x = jnp.asarray(np.random.RandomState(0).randn(128, 16),
                    jnp.float32)
    gds.save_data(x, f)
    y = gds.load_data(jnp.zeros_like(x), f)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_offsets_never_truncate(tmp_path):
    f = str(tmp_path / "blob.bin")
    a = jnp.arange(8.0)
    b = jnp.arange(8.0) + 100
    gds.save_data(a, f, offset=0)
    gds.save_data(b, f, offset=a.nbytes)
    # rewriting the front must not clobber the tail
    gds.save_data(a * 2, f, offset=0)
    back = gds.load_data(jnp.zeros((16,)), f)
    np.testing.assert_array_equal(
        np.asarray(back),
        np.concatenate([np.asarray(a) * 2, np.asarray(b)]))


def test_short_read_raises(tmp_path):
    """Same EOFError contract on both the native and fallback paths."""
    f = str(tmp_path / "short.bin")
    gds.save_data(jnp.ones((4,), jnp.float32), f)
    with pytest.raises(EOFError):
        gds.load_data(jnp.zeros((100,), jnp.float32), f)


def test_async_roundtrip(tmp_path):
    f = str(tmp_path / "blob.bin")
    x = jnp.ones((64,), jnp.float32) * 3
    gds.save_data_async(x, f).result()
    y = gds.load_data_async(jnp.zeros_like(x), f).result()
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


@pytest.mark.skipif(not gds.HAVE_GDS_C,
                    reason="C extension not built (APEX_TPU_CPP_EXT=1)")
class TestNative:
    def test_native_read_write_raw(self, tmp_path):
        from apex_tpu import _gds_C
        f = str(tmp_path / "raw.bin")
        data = np.arange(1000, dtype=np.float64)
        n = _gds_C.write_from(f, memoryview(data).cast("B"), 16)
        assert n == data.nbytes
        out = np.empty_like(data)
        n = _gds_C.read_into(f, memoryview(out).cast("B"), 16)
        assert n == data.nbytes
        np.testing.assert_array_equal(out, data)

    def test_missing_file_oserror(self, tmp_path):
        from apex_tpu import _gds_C
        buf = np.zeros(4, np.uint8)
        with pytest.raises(OSError):
            _gds_C.read_into(str(tmp_path / "nope"),
                             memoryview(buf).cast("B"), 0)

    def test_concurrent_readers_overlap(self, tmp_path):
        """The point of the GIL-releasing loop: N readers make progress
        concurrently (smoke: all futures complete with correct data)."""
        f = str(tmp_path / "big.bin")
        x = jnp.asarray(np.random.RandomState(1).randn(1 << 18),
                        jnp.float32)
        gds.save_data(x, f)
        futs = [gds.load_data_async(jnp.zeros_like(x), f)
                for _ in range(8)]
        for fut in futs:
            np.testing.assert_array_equal(np.asarray(fut.result()),
                                          np.asarray(x))
