"""apex_C flatten/unflatten parity tests (reference: the DDP bucket
pack/unpack contract of csrc/flatten_unflatten.cpp)."""
import numpy as np
import pytest

from apex_tpu import apex_C


def test_torch_roundtrip():
    torch = pytest.importorskip("torch")
    ts = [torch.randn(3, 4), torch.randn(7), torch.randn(2, 2, 2)]
    flat = apex_C.flatten(ts)
    assert flat.shape == (3 * 4 + 7 + 8,)
    outs = apex_C.unflatten(flat, ts)
    for o, t in zip(outs, ts):
        assert o.shape == t.shape
        np.testing.assert_allclose(o.numpy(), t.numpy())


def test_torch_matches_torch_utils():
    torch = pytest.importorskip("torch")
    from torch._utils import _flatten_dense_tensors
    ts = [torch.arange(6, dtype=torch.float32).reshape(2, 3),
          torch.ones(5)]
    np.testing.assert_allclose(
        apex_C.flatten(ts).numpy(),
        _flatten_dense_tensors(tuple(ts)).numpy())


def test_jax_roundtrip():
    import jax.numpy as jnp
    ts = [jnp.arange(6.0).reshape(2, 3), jnp.ones((5,))]
    flat = apex_C.flatten(ts)
    assert flat.shape == (11,)
    outs = apex_C.unflatten(flat, ts)
    for o, t in zip(outs, ts):
        np.testing.assert_allclose(np.asarray(o), np.asarray(t))


@pytest.mark.skipif(not apex_C.HAVE_CPP_EXT,
                    reason="C extension not built (APEX_TPU_CPP_EXT=1)")
def test_cpp_ext_raw_buffers():
    from apex_tpu import _apex_C
    a = np.arange(5, dtype=np.float32)
    b = np.arange(3, dtype=np.float32) + 10
    packed = _apex_C.flatten([a, b])
    got = np.frombuffer(bytes(packed), dtype=np.float32)
    np.testing.assert_allclose(got, np.concatenate([a, b]))
    # flatten_into a preallocated buffer
    dst = np.zeros(8, dtype=np.float32)
    n = _apex_C.flatten_into([a, b], dst)
    assert n == 32
    np.testing.assert_allclose(dst, np.concatenate([a, b]))
