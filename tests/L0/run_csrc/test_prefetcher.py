"""DevicePrefetcher (reference: ``examples/imagenet/main_amp.py ::
data_prefetcher`` — side-stream H2D overlap, rebuilt as an async
device_put pipeline)."""
import queue

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.utils import DevicePrefetcher


def test_order_and_values_preserved():
    batches = [(np.full((4,), i, np.float32), {"y": np.int32(i)})
               for i in range(10)]
    out = list(DevicePrefetcher(iter(batches), depth=3))
    assert len(out) == 10
    for i, (x, d) in enumerate(out):
        assert isinstance(x, jax.Array)
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
        assert int(d["y"]) == i


def test_torch_tensors_bridge_to_device():
    batches = [(torch.full((2, 3), float(i)), torch.tensor([i]))
               for i in range(4)]
    out = list(DevicePrefetcher(iter(batches)))
    for i, (x, y) in enumerate(out):
        assert isinstance(x, jax.Array) and isinstance(y, jax.Array)
        assert float(x[0, 0]) == float(i)


def test_feeds_jit_consumer():
    @jax.jit
    def f(x):
        return jnp.sum(x * 2)

    total = sum(float(f(x)) for x in DevicePrefetcher(
        (np.ones((8,), np.float32) * i for i in range(5))))
    assert total == 2 * 8 * (0 + 1 + 2 + 3 + 4)


def test_source_exception_propagates_in_order():
    def gen():
        yield np.zeros(2, np.float32)
        raise RuntimeError("loader died")

    pf = DevicePrefetcher(gen())
    next(pf)
    with pytest.raises(RuntimeError, match="loader died"):
        next(pf)


def test_empty_iterator():
    assert list(DevicePrefetcher(iter(()))) == []


def test_sharding_places_batches():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:4]).reshape(4),
                             ("data",))
    sh = NamedSharding(mesh, P("data"))
    out = list(DevicePrefetcher(
        (np.arange(8, dtype=np.float32) + i for i in range(3)),
        sharding=sh))
    for x in out:
        assert x.sharding == sh


def test_close_releases_blocked_worker():
    def endless():
        i = 0
        while True:
            yield np.float32(i)
            i += 1

    pf = DevicePrefetcher(endless(), depth=1)
    next(pf)
    pf.close()
    pf._thread.join(timeout=5)
    assert not pf._thread.is_alive()


def test_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        DevicePrefetcher(iter(()), depth=0)


def test_terminal_states_keep_raising_stopiteration():
    # exhausted: must not hang on a queue the dead worker won't refill
    pf = DevicePrefetcher(iter([np.float32(1)]))
    assert len(list(pf)) == 1
    with pytest.raises(StopIteration):
        next(pf)
    assert list(pf) == []
    # closed mid-stream: same contract
    pf2 = DevicePrefetcher(iter([np.float32(1), np.float32(2)]), depth=1)
    next(pf2)
    pf2.close()
    with pytest.raises(StopIteration):
        next(pf2)
