"""Parity-shim batch: fmha packed varlen, conv_bias_relu, peer_memory,
cudnn_gbn, nccl shims, models re-export, FusedMixedPrecisionLamb,
metrics, checkpoint resume-identical, testing harness (arguments,
global_vars, distributed base).
"""
import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.fmha import fmha_packed
from apex_tpu.ops.attention import mha_reference


class TestFMHA:
    def test_packed_varlen_matches_dense(self):
        h, d = 2, 64
        lens = [96, 128]
        total = sum(lens)
        cu = jnp.array([0, 96, 224], jnp.int32)
        qkv = jax.random.normal(jax.random.PRNGKey(0), (total, 3, h, d))
        out = fmha_packed(qkv, cu, max_s=128)
        # oracle: per-sequence dense attention
        off = 0
        for L in lens:
            seg = qkv[off:off + L]                       # [L,3,h,d]
            q, k, v = (seg[:, i].transpose(1, 0, 2)[None] for i in range(3))
            ref = mha_reference(q, k, v)[0].transpose(1, 0, 2)  # [L,h,d]
            np.testing.assert_allclose(out[off:off + L], ref,
                                       atol=2e-5, rtol=2e-5)
            off += L

    def test_p_dropout_wired_and_needs_seed(self):
        import pytest
        h, d = 2, 64
        cu = jnp.array([0, 96, 224], jnp.int32)
        qkv = jax.random.normal(jax.random.PRNGKey(0), (224, 3, h, d))
        with pytest.raises(ValueError, match="dropout_seed"):
            fmha_packed(qkv, cu, max_s=128, p_dropout=0.1)
        a = fmha_packed(qkv, cu, max_s=128, p_dropout=0.1, dropout_seed=5)
        b = fmha_packed(qkv, cu, max_s=128, p_dropout=0.1, dropout_seed=5)
        c = fmha_packed(qkv, cu, max_s=128)
        assert bool(jnp.all(a == b))         # deterministic per seed
        assert bool(jnp.any(a != c))         # dropout actually engaged
        # eval mode ignores dropout like the reference
        e = fmha_packed(qkv, cu, max_s=128, p_dropout=0.1,
                        is_training=False)
        assert bool(jnp.all(e == c))


class TestConvBiasReLU:
    def test_conv_bias_relu(self):
        from apex_tpu.contrib.conv_bias_relu import ConvBias, ConvBiasReLU
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 3))
        w = jax.random.normal(jax.random.PRNGKey(1), (3, 3, 3, 6)) * 0.1
        b = jnp.ones((6,)) * 0.05
        y = ConvBiasReLU.apply(x, w, b, 1, 1)
        y2 = ConvBias.apply(x, w, b, 1, 1)
        assert y.shape == (2, 8, 8, 6)
        np.testing.assert_allclose(np.asarray(y),
                                   np.maximum(np.asarray(y2), 0), atol=1e-6)


class TestPeerMemory:
    def test_halo_exchanger_shim(self):
        from apex_tpu.contrib.peer_memory import (
            PeerHaloExchanger1d,
            PeerMemoryPool,
        )
        from jax.sharding import Mesh
        pool = PeerMemoryPool(1 << 20, 1 << 20, None)   # accepted, unused
        hx = PeerHaloExchanger1d(peer_pool=pool, half_halo=1)
        mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 4))

        def body(xs):
            return hx(xs)

        y = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P(None, "data"),),
            out_specs=P(None, "data")))(x)
        assert y.shape == (2, 24, 4, 4)    # 4 + 2*1 halo rows per shard


def test_nccl_allocator_absorbed():
    from apex_tpu.contrib import nccl_allocator
    nccl_allocator.init()
    with nccl_allocator.nccl_mem():
        pass


def test_openfold_triton_tombstone():
    from apex_tpu.contrib import openfold_triton
    with pytest.raises(NotImplementedError):
        openfold_triton.AttnTri


def test_models_reexport():
    from apex_tpu import models
    assert models.GPTConfig().hidden_size > 0
    assert callable(models.gpt_model_provider)


def test_fused_mixed_precision_lamb():
    from apex_tpu.optimizers.fused_mixed_precision_lamb import (
        FusedMixedPrecisionLamb,
    )
    params = {"w": jnp.ones((8, 8), jnp.bfloat16)}
    opt = FusedMixedPrecisionLamb(params, lr=1e-2, step=5)
    assert opt.step_count == 5
    g = {"w": jnp.ones((8, 8), jnp.bfloat16) * 0.1}
    new = opt.step(g)
    assert new["w"].dtype == jnp.bfloat16
    assert float(jnp.mean(new["w"])) < 1.0


def test_metrics():
    from apex_tpu.utils.metrics import Metrics, named_scope, trace_annotation
    m = Metrics()
    m.step(); m.step()
    m.gauge("loss_scale", 65536.0)
    m.count("overflows")
    snap = m.snapshot()
    assert snap["steps"] == 2 and snap["loss_scale"] == 65536.0
    assert "steps_per_sec" in snap
    assert isinstance(m.json_line(), str)
    with named_scope("test"):
        _ = jnp.ones(()) + 1


def test_checkpoint_resume_identical(tmp_path):
    """SURVEY §5 contract: resume ⇒ identical continuation."""
    from apex_tpu.checkpoint import load_checkpoint, save_checkpoint
    from apex_tpu.optimizers import FusedAdam

    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (33,))}
    g = {"w": jnp.full((33,), 0.3)}
    opt = FusedAdam(params, lr=1e-2)
    p1 = opt.step(g)
    ckpt = {"params": p1, "opt": opt.state_dict()}
    save_checkpoint(str(tmp_path / "ck"), ckpt)
    # continue original
    p2a = opt.step(g)
    # resume from checkpoint in a FRESH optimizer
    restored = load_checkpoint(str(tmp_path / "ck"), like=ckpt)
    opt2 = FusedAdam(jax.tree.map(jnp.asarray, restored["params"]),
                     lr=1e-2)
    opt2.load_state_dict(jax.tree.map(jnp.asarray, restored["opt"]))
    p2b = opt2.step(g)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, atol=0),
                 p2a, p2b)


class TestTestingHarness:
    def test_arguments_parse(self):
        from apex_tpu.transformer.testing.arguments import (
            core_transformer_config_from_args,
            parse_args,
        )
        a = parse_args(args=["--hidden-size", "128",
                             "--num-attention-heads", "8",
                             "--tensor-model-parallel-size", "2"])
        assert a.hidden_size == 128 and a.ffn_hidden_size == 512
        assert a.world_size == 2
        cfg = core_transformer_config_from_args(a)
        assert cfg.hidden_size == 128

    def test_global_vars_lifecycle(self):
        from apex_tpu.transformer.testing import global_vars as gv
        gv.destroy_global_vars()
        gv.set_global_variables(args=["--global-batch-size", "16",
                                      "--micro-batch-size", "2"])
        assert gv.get_args().global_batch_size == 16
        assert gv.get_num_microbatches() == 8
        assert gv.get_current_global_batch_size() == 16
        gv.update_num_microbatches(100, consistency_check=False)
        gv.destroy_global_vars()

    def test_distributed_test_base(self):
        from apex_tpu.transformer.testing.distributed_test_base import (
            NcclDistributedTestBase,
        )

        class T(NcclDistributedTestBase):
            TENSOR_MODEL_PARALLEL_SIZE = 4

            def runTest(self):
                out = self.run_sharded(
                    lambda: jax.lax.psum(jnp.ones(()), "tensor"))
                assert float(out) == 4.0

        t = T()
        t.setUp()
        try:
            t.runTest()
        finally:
            t.tearDown()
