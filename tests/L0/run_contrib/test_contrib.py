"""Contrib tier-1 tests, mirroring ``apex/contrib/test/``:
xentropy kernel vs reference, clip_grad vs manual, multihead_attn runs +
norm-add variant, MLP/FusedDense numerics.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.mlp import MLP
from apex_tpu.ops.xentropy import (
    softmax_cross_entropy_loss,
    xentropy_reference,
)


class TestXentropy:
    """Reference: apex/contrib/test/xentropy/test_label_smoothing.py."""

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    @pytest.mark.parametrize("n,v", [(128, 512), (96, 1000), (256, 8192)])
    def test_forward_matches_reference(self, smoothing, n, v):
        logits = jax.random.normal(jax.random.PRNGKey(0), (n, v)) * 3
        labels = jax.random.randint(jax.random.PRNGKey(1), (n,), 0, v)
        out = softmax_cross_entropy_loss(logits, labels,
                                         smoothing=smoothing)
        ref = xentropy_reference(logits, labels, smoothing)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_grads_match_reference(self, smoothing):
        n, v = 64, 1024
        logits = jax.random.normal(jax.random.PRNGKey(2), (n, v)) * 2
        labels = jax.random.randint(jax.random.PRNGKey(3), (n,), 0, v)

        gk = jax.grad(lambda l: softmax_cross_entropy_loss(
            l, labels, smoothing=smoothing).sum())(logits)
        gr = jax.grad(lambda l: xentropy_reference(
            l, labels, smoothing).sum())(logits)
        np.testing.assert_allclose(gk, gr, rtol=1e-4, atol=1e-5)

    def test_padding_idx_zeroes_loss_and_grad(self):
        n, v = 32, 256
        logits = jax.random.normal(jax.random.PRNGKey(4), (n, v))
        labels = jax.random.randint(jax.random.PRNGKey(5), (n,), 0, v)
        labels = labels.at[::4].set(-100)
        loss = softmax_cross_entropy_loss(logits, labels)
        assert np.all(np.asarray(loss[::4]) == 0.0)
        g = jax.grad(lambda l: softmax_cross_entropy_loss(
            l, labels).sum())(logits)
        assert np.all(np.asarray(g[::4]) == 0.0)
        assert np.any(np.asarray(g[1::4]) != 0.0)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_backward_scatter_matches_onehot_bitwise(self, smoothing):
        """ISSUE 9 satellite: run_bwd's subtract-at-index (scatter-add
        of -(1-s) at safe_labels) is BITWISE the old explicit-one_hot
        formula — ``p + (-(1-s))`` is IEEE ``p - (1-s)`` at the label
        column and untouched columns keep ``p`` exactly — while never
        materializing the second fp32 [tokens, vocab] buffer."""
        n, v = 48, 512
        logits = jax.random.normal(jax.random.PRNGKey(10), (n, v)) * 2
        labels = jax.random.randint(jax.random.PRNGKey(11), (n,), 0, v)
        labels = labels.at[::6].set(-100)
        dloss = jax.random.normal(jax.random.PRNGKey(12), (n,))

        _, vjp = jax.vjp(lambda l: softmax_cross_entropy_loss(
            l, labels, smoothing=smoothing), logits)
        (got,) = vjp(dloss)

        # the pre-ISSUE-9 formula, verbatim
        pad = labels == -100
        safe = jnp.where(pad, 0, labels)
        x = logits.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(x, axis=-1)
        p = jnp.exp(x - lse[:, None])
        onehot = jax.nn.one_hot(safe, v, dtype=jnp.float32)
        ref = p - (1.0 - smoothing) * onehot - smoothing / v
        ref = ref * jnp.where(pad, 0.0, dloss)[:, None]
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_class_shim(self):
        logits = jax.random.normal(jax.random.PRNGKey(6), (16, 128))
        labels = jax.random.randint(jax.random.PRNGKey(7), (16,), 0, 128)
        out = SoftmaxCrossEntropyLoss.apply(logits, labels, 0.1)
        ref = xentropy_reference(logits, labels, 0.1)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_batched_shape(self):
        b, s, v = 4, 32, 512
        logits = jax.random.normal(jax.random.PRNGKey(8), (b, s, v))
        labels = jax.random.randint(jax.random.PRNGKey(9), (b, s), 0, v)
        out = softmax_cross_entropy_loss(logits, labels)
        assert out.shape == (b, s)
        ref = xentropy_reference(logits.reshape(-1, v), labels.reshape(-1))
        np.testing.assert_allclose(out.reshape(-1), ref, rtol=1e-5,
                                   atol=1e-5)


class TestClipGrad:
    """Reference: apex/contrib/test/clip_grad/."""

    def test_clips_to_max_norm(self):
        grads = {"a": jnp.ones((1000,)) * 3.0, "b": jnp.ones((17,)) * -2.0}
        clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
        flat = jnp.concatenate([clipped["a"], clipped["b"]])
        expected_norm = float(jnp.sqrt(1000 * 9.0 + 17 * 4.0))
        np.testing.assert_allclose(float(norm), expected_norm, rtol=1e-5)
        np.testing.assert_allclose(
            float(jnp.linalg.norm(flat)), 1.0, rtol=1e-3)

    def test_no_clip_below_max(self):
        grads = {"a": jnp.full((10,), 1e-3)}
        clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
        np.testing.assert_allclose(clipped["a"], grads["a"], rtol=1e-6)

    def test_inf_norm(self):
        grads = {"a": jnp.array([1.0, -5.0, 2.0])}
        _, norm = clip_grad_norm_(grads, 1.0, norm_type=float("inf"))
        assert float(norm) == 5.0


class TestMultiheadAttn:
    """Reference: apex/contrib/test/multihead_attn/."""

    @pytest.mark.parametrize("impl", ["fast", "default"])
    def test_self_attn_impls_match(self, impl):
        s, b, h, nh = 128, 2, 64, 4
        x = jax.random.normal(jax.random.PRNGKey(0), (s, b, h))
        m = SelfMultiheadAttn(h, nh, impl=impl)
        params = m.init(jax.random.PRNGKey(1), x, is_training=False)
        out, _ = m.apply(params, x, is_training=False)
        assert out.shape == (s, b, h)
        # fast and default produce the same numbers (kernel == oracle)
        m2 = SelfMultiheadAttn(
            h, nh, impl="default" if impl == "fast" else "fast")
        out2, _ = m2.apply(params, x, is_training=False)
        np.testing.assert_allclose(out, out2, rtol=2e-4, atol=2e-5)

    def test_self_attn_norm_add(self):
        s, b, h = 64, 2, 64
        x = jax.random.normal(jax.random.PRNGKey(2), (s, b, h))
        m = SelfMultiheadAttn(h, 4, include_norm_add=True)
        params = m.init(jax.random.PRNGKey(3), x, is_training=False)
        out, _ = m.apply(params, x, is_training=False)
        # residual path present: zeroing the out_proj weight leaves x
        zeroed = jax.tree.map(jnp.zeros_like, params)
        out0, _ = m.apply(zeroed, x, is_training=False)
        np.testing.assert_allclose(out0, x, atol=1e-6)

    def test_self_attn_padding_mask(self):
        s, b, h = 64, 2, 64
        x = jax.random.normal(jax.random.PRNGKey(4), (s, b, h))
        pad = jnp.zeros((b, s), bool).at[:, s // 2:].set(True)
        m = SelfMultiheadAttn(h, 4)
        params = m.init(jax.random.PRNGKey(5), x, is_training=False)
        out_m, _ = m.apply(params, x, key_padding_mask=pad,
                           is_training=False)
        # masked keys don't affect output rows: perturb padded positions
        x2 = x.at[s // 2:].add(10.0)
        out_m2, _ = m.apply(params, x2, key_padding_mask=pad,
                            is_training=False)
        np.testing.assert_allclose(out_m[:s // 2], out_m2[:s // 2],
                                   atol=1e-4)

    def test_encdec_attn(self):
        sq, sk, b, h = 32, 64, 2, 64
        q = jax.random.normal(jax.random.PRNGKey(6), (sq, b, h))
        kv = jax.random.normal(jax.random.PRNGKey(7), (sk, b, h))
        m = EncdecMultiheadAttn(h, 4)
        params = m.init(jax.random.PRNGKey(8), q, kv, is_training=False)
        out, _ = m.apply(params, q, kv, is_training=False)
        assert out.shape == (sq, b, h)


class TestMLPDense:
    """Reference: tests/L0/run_mlp/test_mlp.py."""

    def test_mlp_matches_manual(self):
        sizes = [16, 32, 8]
        m = MLP(sizes)
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 16))
        params = m.init(jax.random.PRNGKey(1), x)
        out = m.apply(params, x)
        h = x
        for i in range(2):
            p = params["params"][f"layer_{i}"]
            h = jax.nn.relu(h @ p["kernel"] + p["bias"])
        np.testing.assert_allclose(out, h, rtol=1e-6)

    def test_fused_dense_gelu_dense(self):
        m = FusedDenseGeluDense(16, 64, 16)
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 16))
        params = m.init(jax.random.PRNGKey(3), x)
        out = m.apply(params, x)
        p = params["params"]
        ref = jax.nn.gelu(
            x @ p["dense1"]["kernel"] + p["dense1"]["bias"]) \
            @ p["dense2"]["kernel"] + p["dense2"]["bias"]
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_fused_dense(self):
        m = FusedDense(8, 24)
        x = jax.random.normal(jax.random.PRNGKey(4), (3, 8))
        params = m.init(jax.random.PRNGKey(5), x)
        assert m.apply(params, x).shape == (3, 24)
