"""ZeRO optimizer equivalence (reference:
``apex/contrib/test/optimizers/test_dist_adam.py`` — DistributedFusedAdam
must match FusedAdam stepped on replicated grads).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam

DP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:DP]), ("data",))


def _params(key):
    k1, k2 = jax.random.split(key)
    return {"w": jax.random.normal(k1, (37, 13)),
            "b": jax.random.normal(k2, (13,))}


def test_dist_adam_matches_fused_adam():
    params = _params(jax.random.PRNGKey(0))
    # per-rank grads: average over DP must equal the replicated grad
    grads_per_rank = jax.random.normal(
        jax.random.PRNGKey(1), (DP, 37 * 13 + 13))
    opt = DistributedFusedAdam(DP, lr=1e-2, weight_decay=0.01)
    mesh = _mesh()

    def body(grank):
        state = opt.init_state(params)
        flat = grank[0]
        g = {"w": flat[:37 * 13].reshape(37, 13), "b": flat[37 * 13:]}
        new_params, state = opt.step(state, g)
        new_params, state = opt.step(state, g)
        return new_params

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("data"),), out_specs=P()))(
        grads_per_rank)

    # oracle: FusedAdam on the mean grad, two steps
    gmean = jnp.mean(grads_per_rank, axis=0)
    g = {"w": gmean[:37 * 13].reshape(37, 13), "b": gmean[37 * 13:]}
    ref_opt = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    p1 = ref_opt.step(g)
    p2 = ref_opt.step(g)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        out, p2)


def test_dist_adam_dp1_no_mesh():
    params = _params(jax.random.PRNGKey(2))
    g = jax.tree.map(jnp.ones_like, params)
    opt = DistributedFusedAdam(1, lr=1e-3)
    state = opt.init_state(params)
    new_params, state = opt.step(state, g)
    ref = FusedAdam(params, lr=1e-3).step(g)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
        new_params, ref)


def test_dist_lamb_runs_and_descends():
    params = _params(jax.random.PRNGKey(3))
    mesh = _mesh()
    opt = DistributedFusedLAMB(DP, lr=1e-2)

    def loss_fn(p):
        return jnp.sum(p["w"] ** 2) + jnp.sum(p["b"] ** 2)

    def body():
        state = opt.init_state(params)
        p = params
        for _ in range(3):
            g = jax.grad(loss_fn)(p)
            p, state = opt.step(state, g)
        return loss_fn(p)

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(), out_specs=P()))()
    assert float(out) < float(loss_fn(params))


@pytest.mark.parametrize("dp", [1, 2, 4])
def test_dist_lamb_matches_fused_lamb(dp):
    """Per-tensor trust ratios across shards must EQUAL the non-ZeRO
    FusedLAMB (reference: distributed_fused_lamb.py computes exact
    per-tensor norms with multi_tensor_l2norm + group allreduce)."""
    from apex_tpu.optimizers import FusedLAMB

    params = _params(jax.random.PRNGKey(5))
    nflat = 37 * 13 + 13
    grads_per_rank = jax.random.normal(
        jax.random.PRNGKey(6), (dp, nflat)) * 0.05
    opt = DistributedFusedLAMB(dp, lr=1e-2, weight_decay=0.01,
                               max_grad_norm=1.0)

    def unflat(flat):
        return {"w": flat[:37 * 13].reshape(37, 13), "b": flat[37 * 13:]}

    def body(grank):
        state = opt.init_state(params)
        g = unflat(grank[0] if dp > 1 else grank.reshape(-1))
        new_params, state = opt.step(state, g)
        new_params, state = opt.step(state, g)
        return new_params

    if dp > 1:
        mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))
        out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P("data"),), out_specs=P()))(
            grads_per_rank)
    else:
        out = jax.jit(body)(grads_per_rank)

    gmean = jnp.mean(grads_per_rank, axis=0)
    ref_opt = FusedLAMB(params, lr=1e-2, weight_decay=0.01,
                        max_grad_norm=1.0)
    ref_opt.step(unflat(gmean))
    ref = ref_opt.step(unflat(gmean))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        out, ref)


def test_dist_adam_overflow_skip():
    params = _params(jax.random.PRNGKey(4))
    g = jax.tree.map(jnp.ones_like, params)
    opt = DistributedFusedAdam(1, lr=1e-3)
    state = opt.init_state(params)
    new_params, state2 = opt.step(state, g, noop_flag=1.0)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=0),
        new_params, params)
    # moments untouched too
    np.testing.assert_allclose(state2["exp_avg"], state["exp_avg"], atol=0)


def test_dist_adam_preserves_bf16_dtypes():
    params = {"w": jnp.ones((37, 13), jnp.bfloat16),
              "b": jnp.zeros((13,), jnp.bfloat16)}
    g = jax.tree.map(lambda x: jnp.full_like(x, 0.1), params)
    opt = DistributedFusedAdam(1, lr=1e-3)
    state = opt.init_state(params)
    new_params, _ = opt.step(state, g)
    assert new_params["w"].dtype == jnp.bfloat16
    assert new_params["b"].dtype == jnp.bfloat16


def test_dist_lamb_large_dp_fallback_matches_switch(monkeypatch):
    """The bounded-compile global-buffer path (dp > _SWITCH_MAX_DP) must
    produce the same params as the lax.switch static-span path.  The
    span machinery lives in ``optimizers.base`` since the ZeRO rewire
    (the contrib classes are shells over the sharded functional core),
    so the threshold is patched there."""
    import apex_tpu.optimizers.base as co

    params = _params(jax.random.PRNGKey(9))
    nflat = 37 * 13 + 13
    grads_per_rank = jax.random.normal(
        jax.random.PRNGKey(10), (DP, nflat)) * 0.05
    mesh = _mesh()

    def unflat(flat):
        return {"w": flat[:37 * 13].reshape(37, 13), "b": flat[37 * 13:]}

    def run():
        opt = DistributedFusedLAMB(DP, lr=1e-2, weight_decay=0.01,
                                   max_grad_norm=1.0)

        def body(grank):
            state = opt.init_state(params)
            g = unflat(grank[0])
            new_params, state = opt.step(state, g)
            new_params, state = opt.step(state, g)
            return new_params

        return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P("data"),), out_specs=P()))(
            grads_per_rank)

    via_switch = run()
    monkeypatch.setattr(co, "_SWITCH_MAX_DP", 1)   # force the fallback
    via_global = run()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6,
                                                atol=1e-7),
        via_global, via_switch)
