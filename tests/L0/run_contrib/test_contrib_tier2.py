"""Contrib tier-2 tests: group_norm, groupbn, focal_loss, index_mul_2d,
ASP sparsity, transducer, spatial bottleneck halo exchange.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
from apex_tpu.contrib.focal_loss import focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.sparsity import ASP, compute_sparse_masks, mask_2to4_1d
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    transducer_joint,
    transducer_loss,
)


class TestGroupNorm:
    def test_matches_manual(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
        out = group_norm_nhwc(x, num_groups=2)
        # manual per-group normalize
        xg = x.reshape(2, 4, 4, 2, 4)
        m = xg.mean(axis=(1, 2, 4), keepdims=True)
        v = xg.var(axis=(1, 2, 4), keepdims=True)
        ref = ((xg - m) / np.sqrt(v + 1e-5)).reshape(2, 4, 4, 8)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_module_affine_and_silu(self):
        m = GroupNorm(num_groups=4, num_channels=16, act="silu")
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 3, 16))
        params = m.init(jax.random.PRNGKey(2), x)
        y = m.apply(params, x)
        base = group_norm_nhwc(x, 4)
        np.testing.assert_allclose(y, base * jax.nn.sigmoid(base),
                                   atol=1e-5)


class TestGroupBN:
    def test_fused_add_relu(self):
        m = BatchNorm2d_NHWC(8, fuse_relu=True, bn_group=1)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
        z = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 4, 8))
        vars_ = m.init(jax.random.PRNGKey(2), x, z)
        y, _ = m.apply(vars_, x, z, mutable=["batch_stats"])
        assert float(jnp.min(y)) >= 0.0   # relu applied
        assert y.shape == x.shape


class TestFocalLoss:
    def test_reduces_easy_example_weight(self):
        # well-classified anchors (target logit +5, others -5) get
        # down-weighted by (1-p_t)^gamma vs the gamma=0 (plain BCE) case
        targets = jnp.zeros((4,), jnp.int32)
        logits = jnp.full((4, 2), -5.0).at[:, 0].set(5.0)
        loss_focal = focal_loss(logits, targets, 4.0, 2, gamma=2.0)
        loss_bce = focal_loss(logits, targets, 4.0, 2, gamma=0.0)
        assert float(loss_focal) < 0.01 * float(loss_bce)

    def test_ignore_index(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (4, 3))
        t_all = jnp.array([0, 1, -1, 2])
        t_ign = jnp.array([0, 1, -1, -2])
        l_all = focal_loss(logits, t_all, 1.0, 3)
        l_ign = focal_loss(logits, t_ign, 1.0, 3)
        assert float(l_ign) != float(l_all)   # last anchor dropped

    def test_grad_finite(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (8, 4)) * 10
        t = jnp.array([0, 1, 2, 3, -1, -1, -2, 0])
        g = jax.grad(lambda x: focal_loss(x, t, 4.0, 4))(logits)
        assert np.all(np.isfinite(g))


def test_index_mul_2d():
    in1 = jax.random.normal(jax.random.PRNGKey(0), (10, 8))
    in2 = jax.random.normal(jax.random.PRNGKey(1), (5, 8))
    idx = jnp.array([0, 3, 3, 9, 1])
    out = index_mul_2d(in1, in2, idx)
    np.testing.assert_allclose(out, np.asarray(in1)[np.asarray(idx)]
                               * np.asarray(in2), rtol=1e-6)
    # scatter-add backward through the gather
    g = jax.grad(lambda a: index_mul_2d(a, in2, idx).sum())(in1)
    assert float(g[3].sum()) != 0.0   # row 3 used twice


class TestASP:
    def test_mask_2to4(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (8, 16))
        m = mask_2to4_1d(w)
        # exactly 2 of every 4 kept
        groups = np.asarray(m).reshape(8, 4, 4)
        np.testing.assert_array_equal(groups.sum(-1), 2)
        # kept entries are the largest magnitudes per group
        wg = np.abs(np.asarray(w)).reshape(8, 4, 4)
        kept = np.sort(wg * groups, axis=-1)[..., 2:]
        np.testing.assert_allclose(
            kept, np.sort(wg, axis=-1)[..., 2:], rtol=1e-6)

    def test_compute_masks_skips_bias_and_norm(self):
        params = {"dense": {"kernel": jnp.ones((4, 8)),
                            "bias": jnp.ones((8,))},
                  "layernorm": {"scale": jnp.ones((8,))}}
        masks = compute_sparse_masks(params)
        np.testing.assert_array_equal(masks["dense"]["bias"], 1.0)
        np.testing.assert_array_equal(masks["layernorm"]["scale"], 1.0)
        assert float(masks["dense"]["kernel"].mean()) == 0.5

    def test_prune_roundtrip(self):
        params = {"w": jax.random.normal(jax.random.PRNGKey(1), (4, 8))}
        pruned = ASP.prune_trained_model(params)
        assert float((np.asarray(pruned["w"]) == 0).mean()) == 0.5


class TestPermutationSearch:
    """reference: apex/contrib/sparsity/permutation_search_kernels —
    channel permutation must RAISE the magnitude kept by 2:4 pruning."""

    def _adversarial(self, key, rows=32, cols=64):
        # columns sorted by magnitude scale: groups of 4 hold similar-sized
        # columns, so identity 2:4 must drop large entries — permutation
        # can pair big with small columns and keep much more
        scales = jnp.linspace(1.0, 20.0, cols)
        w = jax.random.normal(key, (rows, cols)) * scales[None, :]
        return w

    def test_efficacy_improves(self):
        from apex_tpu.contrib.sparsity import (
            search_for_good_permutation, sparsity_efficacy)
        w = self._adversarial(jax.random.PRNGKey(0))
        perm = search_for_good_permutation(w, iters=60)
        base = float(sparsity_efficacy(w))
        permuted = float(sparsity_efficacy(w[:, perm]))
        assert permuted > base + 0.01, (base, permuted)

    def test_perm_is_valid_and_deterministic(self):
        from apex_tpu.contrib.sparsity import search_for_good_permutation
        w = self._adversarial(jax.random.PRNGKey(1))
        p1 = np.asarray(search_for_good_permutation(
            w, iters=20, key=jax.random.PRNGKey(7)))
        p2 = np.asarray(search_for_good_permutation(
            w, iters=20, key=jax.random.PRNGKey(7)))
        np.testing.assert_array_equal(p1, p2)
        np.testing.assert_array_equal(np.sort(p1), np.arange(w.shape[1]))

    def test_never_worse_than_identity(self):
        from apex_tpu.contrib.sparsity import (
            search_for_good_permutation, sparsity_efficacy)
        # already-uniform matrix: nothing to gain, must not lose
        w = jax.random.normal(jax.random.PRNGKey(2), (16, 32))
        perm = search_for_good_permutation(w, iters=30)
        assert float(sparsity_efficacy(w[:, perm])) >= \
            float(sparsity_efficacy(w)) - 1e-6

    def test_alias(self):
        from apex_tpu.contrib.sparsity import (
            accelerated_search_for_good_permutation)
        w = self._adversarial(jax.random.PRNGKey(3), rows=8, cols=16)
        perm = accelerated_search_for_good_permutation(w, iters=5)
        assert perm.shape == (16,)


class TestTransducer:
    def test_joint_shape_and_relu(self):
        f = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 8))
        g = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 8))
        h = transducer_joint(f, g, relu=True)
        assert h.shape == (2, 5, 3, 8)
        assert float(jnp.min(h)) >= 0.0
        np.testing.assert_allclose(
            TransducerJoint(relu=True)(f, g), h)

    def test_loss_matches_bruteforce(self):
        """Exact check vs explicit DP over all alignment paths."""
        b, t, u, v = 1, 3, 2, 4
        key = jax.random.PRNGKey(2)
        logits = jax.random.normal(key, (b, t, u + 1, v))
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        labels = jnp.array([[1, 2]])
        f_len = jnp.array([t])
        y_len = jnp.array([u])
        loss = transducer_loss(log_probs, labels, f_len, y_len,
                               blank_idx=0)
        # brute force alpha DP in numpy
        lp = np.asarray(log_probs)[0]
        lab = [1, 2]
        import math
        alpha = np.full((t, u + 1), -np.inf)
        alpha[0, 0] = 0.0
        for uu in range(1, u + 1):
            alpha[0, uu] = alpha[0, uu - 1] + lp[0, uu - 1, lab[uu - 1]]
        for tt in range(1, t):
            for uu in range(u + 1):
                a = alpha[tt - 1, uu] + lp[tt - 1, uu, 0]
                if uu > 0:
                    bterm = alpha[tt, uu - 1] + lp[tt, uu - 1, lab[uu - 1]]
                    a = np.logaddexp(a, bterm)
                alpha[tt, uu] = a
        ref = -(alpha[t - 1, u] + lp[t - 1, u, 0])
        np.testing.assert_allclose(float(loss[0]), ref, rtol=1e-5)

    def test_loss_grad_finite_and_descends(self):
        b, t, u, v = 2, 6, 3, 8
        logits = jax.random.normal(jax.random.PRNGKey(3), (b, t, u + 1, v))
        labels = jnp.array([[1, 2, 3], [4, 5, 6]])
        f_len = jnp.array([t, t - 1])
        y_len = jnp.array([u, u - 1])

        def loss_fn(lg):
            lp = jax.nn.log_softmax(lg, axis=-1)
            return transducer_loss(lp, labels, f_len, y_len).sum()

        l0 = loss_fn(logits)
        g = jax.grad(loss_fn)(logits)
        assert np.all(np.isfinite(g))
        l1 = loss_fn(logits - 0.1 * g)
        assert float(l1) < float(l0)


class TestBottleneck:
    def test_bottleneck_runs(self):
        m = Bottleneck(16, 4, 16)
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16))
        vars_ = m.init(jax.random.PRNGKey(1), x)
        y, _ = m.apply(vars_, x, mutable=["batch_stats"])
        assert y.shape == x.shape

    def test_spatial_matches_unsharded(self):
        """Halo-exchanged sharded conv == unsharded conv (eval-mode BN so
        per-shard stats don't differ)."""
        n_dev = 4
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
        c = 8
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, 8, c))
        sp = SpatialBottleneck(c, 4, c, axis_name="data",
                               use_running_average=True)
        sp0 = SpatialBottleneck(c, 4, c, axis_name=None,
                                use_running_average=True)
        params = sp0.init(jax.random.PRNGKey(3), x[:, :4])

        def body(xs):
            return sp.apply(params, xs)

        spec = P(None, "data", None, None)
        y_sharded = jax.jit(functools.partial(
            jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(spec,), out_specs=spec))(x)

        # unsharded oracle: same params, zero-halo (SAME-padding) pass
        y_full = jax.jit(lambda xs: sp0.apply(params, xs))(x)
        np.testing.assert_allclose(y_sharded, y_full, atol=1e-4,
                                   rtol=1e-4)
