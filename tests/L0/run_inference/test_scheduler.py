"""Host-side continuous batching: slot allocation, retire/readmit,
EOS/budget cuts — with the device shapes pinned fixed."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


@pytest.fixture(scope="module")
def engine():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)


def test_more_requests_than_slots_all_complete(engine):
    sched = SlotScheduler(engine)
    uids = [sched.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(5)]
    out = sched.run()
    assert sorted(out) == sorted(uids)
    assert all(len(v) == 3 for v in out.values())


def test_token_budget_and_eos_cut(engine):
    # every output token is in [0, 32); eos_id=999 never fires
    sched = SlotScheduler(engine)
    u1 = sched.submit([1, 2], max_new_tokens=4, eos_id=999)
    out = sched.run()
    assert len(out[u1]) == 4
    # eos_id set to the first generated token -> single-token output
    first = out[u1][0]
    sched2 = SlotScheduler(engine)
    u2 = sched2.submit([1, 2], max_new_tokens=4, eos_id=int(first))
    out2 = sched2.run()
    assert out2[u2] == [first]


def test_validates_prompts(engine):
    sched = SlotScheduler(engine)
    with pytest.raises(ValueError, match="empty"):
        sched.submit([])
    with pytest.raises(ValueError, match="max_seq"):
        sched.submit(list(range(65)))


def test_slot_capacity_guard(engine):
    """A request whose decode would overrun max_seq is cut at capacity
    instead of writing past the cache."""
    sched = SlotScheduler(engine)
    u = sched.submit(list(np.arange(60) % 32), max_new_tokens=50)
    out = sched.run()
    # 60-token prompt in a 64-deep slot: 1 prefill token + 4 decode
    # writes (positions 60..63), then capacity retires the request
    assert len(out[u]) == 5


def _fresh_telemetry():
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry
    return ServeTelemetry(MetricsRegistry())


def test_lifecycle_conservation(engine):
    """submitted == finished + active + rejected at every boundary the
    host can observe (ISSUE 8 satellite) — no request is ever lost or
    double-counted by the telemetry lifecycle."""
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    for i in range(5):
        sched.submit([1 + i, 2, 3], max_new_tokens=3)
    for bad in ([], list(range(65))):        # rejected at validation
        with pytest.raises(ValueError):
            sched.submit(bad)
    c = tel.conservation()
    assert c == {"submitted": 7, "finished": 0, "rejected": 2,
                 "active": 5}
    sched.run()
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    assert c == {"submitted": 7, "finished": 5, "rejected": 2,
                 "active": 0}


def test_peak_active_and_finish_reasons_surface_through_telemetry(engine):
    """The PR 6 internals (`peak_active`, `finish_reasons`) are now
    first-class metrics: the gauge/counters mirror the attributes
    existing callers keep reading."""
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    for i in range(3):
        sched.submit([1 + i, 2], max_new_tokens=2)
    # one request EOS-cuts on its first token (vocab is 32: token 999
    # never appears, so pick one from a probe run)
    probe = SlotScheduler(engine, telemetry=_fresh_telemetry())
    up = probe.submit([9, 2], max_new_tokens=2)
    first = probe.run()[up][0]
    sched.submit([9, 2], max_new_tokens=2, eos_id=int(first))
    sched.run()
    assert tel.peak_active.value() == sched.peak_active
    assert sched.peak_active == 2            # engine has 2 slots
    # finish_reasons is {uid: reason}; the counter mirrors its tallies
    import collections
    tallies = collections.Counter(sched.finish_reasons.values())
    for reason, n in tallies.items():
        assert tel.finished.value(reason=reason) == n, reason
    assert int(tel.finished.total()) == len(sched.finish_reasons) == 4
    assert tel.finished.value(reason="eos") >= 1
    # token accounting: every token handed back is counted
    assert int(tel.tokens_generated.total()) == 3 * 2 + 1


def test_ttft_histogram_counts_every_admitted_request(engine):
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    n = 5
    for i in range(n):
        sched.submit([1 + i, 2, 3], max_new_tokens=2)
    sched.run()
    assert tel.ttft.count() == n
    assert tel.prefill_seconds.count() == n
    # latencies are physical: positive, and TTFT >= its prefill bracket
    assert tel.ttft.sum() > 0
    assert tel.decode_token_seconds.count() == \
        int(tel.decode_steps.total()) > 0


def test_span_conservation_every_trace_closes_terminal(engine):
    """ISSUE 13 satellite: with tracing armed, every admitted trace
    closes with exactly ONE terminal span (`retired` carrying the
    finish reason) — asserted alongside the lifecycle conservation
    law; nothing dangles at the wave boundary."""
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry
    from apex_tpu.observability.spans import TERMINAL_SPANS

    reg = MetricsRegistry()
    events = []

    class _Sink:
        def event(self, obj):
            events.append(obj)

    reg.add_sink(_Sink())
    tel = ServeTelemetry(reg, trace=1)
    sched = SlotScheduler(engine, telemetry=tel)
    uids = [sched.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(5)]
    sched.run()
    # lifecycle conservation (the existing law) ...
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    # ... and span conservation beside it
    sc = tel.tracer.conservation()
    assert sc["started"] == sc["admitted"] == sc["closed"] == 5
    assert sc["closed_by_span"] == {"retired": 5}
    assert sc["dangling"] == [] and sc["live"] == 0
    assert sc["orphan_terminals"] == []
    # exactly one terminal span per uid in the stream, reason from
    # finish_reasons
    for uid in uids:
        terminals = [e for e in events if e["kind"] == "trace_span"
                     and e["uid"] == uid
                     and e["span"] in TERMINAL_SPANS]
        assert len(terminals) == 1, uid
        assert terminals[0]["detail"] == sched.finish_reasons[uid]


def test_overload_sheds_lowest_priority_first(engine):
    """ISSUE 13 satellite: a seeded overload — more queued work than
    the slots drain — flips the shedding advisory, and the scheduler
    rejects the LOWEST effective-priority request first (reason
    "shed", no results entry, trace closed with a `rejected` terminal,
    conservation intact)."""
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry
    from apex_tpu.observability.slo import OverloadDetector, SLOTracker

    reg = MetricsRegistry()
    events = []

    class _Sink:
        def event(self, obj):
            events.append(obj)

    reg.add_sink(_Sink())
    tel = ServeTelemetry(reg, trace=1)
    slo = SLOTracker(reg, (), detector=OverloadDetector(window=2,
                                                        queue_high=2))
    sched = SlotScheduler(engine, telemetry=tel, slo=slo,
                          shed_on_overload=True)
    low = None
    for i in range(6):
        pr = -5 if i == 3 else 0        # uid 3 is the shed victim
        uid = sched.submit([1 + i, 2, 3], max_new_tokens=4,
                           tenant="low" if i == 3 else "default",
                           priority=pr)
        if i == 3:
            low = uid
    out = sched.run()
    sheds = [uid for uid, r in sched.finish_reasons.items()
             if r == "shed"]
    assert sheds, "the seeded overload never flipped the advisory"
    # lowest effective priority went first
    shed_events = [e for e in events if e["kind"] == "request_shed"]
    assert shed_events[0]["uid"] == low
    assert shed_events[0]["tenant"] == "low"
    assert low not in out
    # every non-shed request completed in full
    for uid in range(6):
        if uid not in sheds:
            assert len(out[uid]) == 4, uid
    # counters: shed rides the rejected side of the conservation law
    assert int(tel.rejected.value(reason="shed")) == len(sheds)
    assert int(tel.shed.total()) == len(sheds)
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    assert c["active"] == 0
    # the advisory was observable while it held
    assert any(e["kind"] == "overload" and e["overloaded"]
               for e in events)
    # shed traces closed with the `rejected` terminal — no dangles
    sc = tel.tracer.conservation()
    assert sc["closed_by_span"]["rejected"] == len(sheds)
    assert sc["dangling"] == []


def test_decode_shape_is_fixed_across_admits(engine):
    """The continuous-batching property: a full wave of admits/retires
    compiles NO new decode programs after the first step."""
    sched = SlotScheduler(engine)
    for i in range(3):
        sched.submit([1 + i, 2, 3], max_new_tokens=3)
    sched.run()                              # warm every executable
    events = []
    # snapshot listeners so teardown restores instead of leaking ours
    from jax._src import monitoring as _mon
    saved = {attr: list(getattr(_mon, attr))
             for attr in dir(_mon)
             if attr.endswith("_listeners")
             and isinstance(getattr(_mon, attr), list)}
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    try:
        sched2 = SlotScheduler(engine)
        for i in range(4):
            sched2.submit([2 + i, 3, 4], max_new_tokens=3)
        out = sched2.run()
    finally:
        for attr, listeners in saved.items():
            getattr(_mon, attr)[:] = listeners
    assert all(len(v) == 3 for v in out.values())
    assert not any("compile_requests" in e for e in events)
