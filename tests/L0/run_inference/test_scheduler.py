"""Host-side continuous batching: slot allocation, retire/readmit,
EOS/budget cuts — with the device shapes pinned fixed."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


@pytest.fixture(scope="module")
def engine():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)


def test_more_requests_than_slots_all_complete(engine):
    sched = SlotScheduler(engine)
    uids = [sched.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(5)]
    out = sched.run()
    assert sorted(out) == sorted(uids)
    assert all(len(v) == 3 for v in out.values())


def test_token_budget_and_eos_cut(engine):
    # every output token is in [0, 32); eos_id=999 never fires
    sched = SlotScheduler(engine)
    u1 = sched.submit([1, 2], max_new_tokens=4, eos_id=999)
    out = sched.run()
    assert len(out[u1]) == 4
    # eos_id set to the first generated token -> single-token output
    first = out[u1][0]
    sched2 = SlotScheduler(engine)
    u2 = sched2.submit([1, 2], max_new_tokens=4, eos_id=int(first))
    out2 = sched2.run()
    assert out2[u2] == [first]


def test_validates_prompts(engine):
    sched = SlotScheduler(engine)
    with pytest.raises(ValueError, match="empty"):
        sched.submit([])
    with pytest.raises(ValueError, match="max_seq"):
        sched.submit(list(range(65)))


def test_slot_capacity_guard(engine):
    """A request whose decode would overrun max_seq is cut at capacity
    instead of writing past the cache."""
    sched = SlotScheduler(engine)
    u = sched.submit(list(np.arange(60) % 32), max_new_tokens=50)
    out = sched.run()
    # 60-token prompt in a 64-deep slot: 1 prefill token + 4 decode
    # writes (positions 60..63), then capacity retires the request
    assert len(out[u]) == 5


def _fresh_telemetry():
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry
    return ServeTelemetry(MetricsRegistry())


def test_lifecycle_conservation(engine):
    """submitted == finished + active + rejected at every boundary the
    host can observe (ISSUE 8 satellite) — no request is ever lost or
    double-counted by the telemetry lifecycle."""
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    for i in range(5):
        sched.submit([1 + i, 2, 3], max_new_tokens=3)
    for bad in ([], list(range(65))):        # rejected at validation
        with pytest.raises(ValueError):
            sched.submit(bad)
    c = tel.conservation()
    assert c == {"submitted": 7, "finished": 0, "rejected": 2,
                 "active": 5}
    sched.run()
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    assert c == {"submitted": 7, "finished": 5, "rejected": 2,
                 "active": 0}


def test_peak_active_and_finish_reasons_surface_through_telemetry(engine):
    """The PR 6 internals (`peak_active`, `finish_reasons`) are now
    first-class metrics: the gauge/counters mirror the attributes
    existing callers keep reading."""
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    for i in range(3):
        sched.submit([1 + i, 2], max_new_tokens=2)
    # one request EOS-cuts on its first token (vocab is 32: token 999
    # never appears, so pick one from a probe run)
    probe = SlotScheduler(engine, telemetry=_fresh_telemetry())
    up = probe.submit([9, 2], max_new_tokens=2)
    first = probe.run()[up][0]
    sched.submit([9, 2], max_new_tokens=2, eos_id=int(first))
    sched.run()
    assert tel.peak_active.value() == sched.peak_active
    assert sched.peak_active == 2            # engine has 2 slots
    # finish_reasons is {uid: reason}; the counter mirrors its tallies
    import collections
    tallies = collections.Counter(sched.finish_reasons.values())
    for reason, n in tallies.items():
        assert tel.finished.value(reason=reason) == n, reason
    assert int(tel.finished.total()) == len(sched.finish_reasons) == 4
    assert tel.finished.value(reason="eos") >= 1
    # token accounting: every token handed back is counted
    assert int(tel.tokens_generated.total()) == 3 * 2 + 1


def test_ttft_histogram_counts_every_admitted_request(engine):
    tel = _fresh_telemetry()
    sched = SlotScheduler(engine, telemetry=tel)
    n = 5
    for i in range(n):
        sched.submit([1 + i, 2, 3], max_new_tokens=2)
    sched.run()
    assert tel.ttft.count() == n
    assert tel.prefill_seconds.count() == n
    # latencies are physical: positive, and TTFT >= its prefill bracket
    assert tel.ttft.sum() > 0
    assert tel.decode_token_seconds.count() == \
        int(tel.decode_steps.total()) > 0


def test_decode_shape_is_fixed_across_admits(engine):
    """The continuous-batching property: a full wave of admits/retires
    compiles NO new decode programs after the first step."""
    sched = SlotScheduler(engine)
    for i in range(3):
        sched.submit([1 + i, 2, 3], max_new_tokens=3)
    sched.run()                              # warm every executable
    events = []
    # snapshot listeners so teardown restores instead of leaking ours
    from jax._src import monitoring as _mon
    saved = {attr: list(getattr(_mon, attr))
             for attr in dir(_mon)
             if attr.endswith("_listeners")
             and isinstance(getattr(_mon, attr), list)}
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    try:
        sched2 = SlotScheduler(engine)
        for i in range(4):
            sched2.submit([2 + i, 3, 4], max_new_tokens=3)
        out = sched2.run()
    finally:
        for attr, listeners in saved.items():
            getattr(_mon, attr)[:] = listeners
    assert all(len(v) == 3 for v in out.values())
    assert not any("compile_requests" in e for e in events)
