"""Host-side radix prefix cache (ISSUE 12): page-granular trie
matching, partial-tail / LCP boundary coverage, refcount pinning, and
LRU leaf eviction under backpressure."""
import numpy as np
import pytest

from apex_tpu.inference.kv_cache import PageAllocator
from apex_tpu.inference.prefix_cache import PrefixCache

PS = 4


def _setup(pages=12, min_hit=None):
    al = PageAllocator(pages, PS, 8)
    return al, PrefixCache(al, min_hit_tokens=min_hit)


def _toks(*vals):
    return list(vals)


def test_insert_pins_pages_and_match_returns_them_in_order():
    al, pc = _setup()
    pages = al.acquire(3)                  # a request's prompt pages
    prompt = list(range(10))               # 2 full pages + 2-token tail
    new = pc.insert(prompt, pages)
    assert new == 3 and pc.pinned_pages == 3
    # the cache holds its own reference: releasing the request's refs
    # keeps every cached page live
    al.release(pages)
    assert al.live_pages == 3 and al.free_pages == 9
    c, got = pc.match(prompt)
    assert c == 10 and got == pages


def test_match_walks_longest_prefix_and_reports_partial_lcp():
    al, pc = _setup()
    pages = al.acquire(3)
    pc.insert(list(range(10)), pages)      # [0..9]
    # full-page walk only: diverges inside page 2
    c, got = pc.match(list(range(8)) + [99, 98, 97])
    assert c == 8 and got == pages[:2]
    # partial tail [8, 9]: lcp 1 against [8, 55] adds sub-page coverage
    c, got = pc.match(list(range(8)) + [8, 55])
    assert c == 9 and got == pages[:3]
    # divergence in the FIRST page with lcp below min_hit_tokens: miss
    c, got = pc.match([0, 1, 77, 66])
    assert (c, got) == (0, [])


def test_min_hit_tokens_suppresses_subpage_accidental_overlap():
    al, pc = _setup()                      # min hit = PS
    pages = al.acquire(2)
    pc.insert(list(range(PS)), pages[:1])
    c, got = pc.match([0, 1, 2, 99])       # 3-token overlap < PS
    assert (c, got) == (0, [])
    al2, pc2 = _setup(min_hit=1)
    p2 = al2.acquire(1)
    pc2.insert(list(range(PS)), p2)
    c, got = pc2.match([0, 1, 2, 99])
    assert c == 3 and got == p2


def test_insert_dedupes_existing_edges():
    al, pc = _setup()
    a = al.acquire(2)
    assert pc.insert(list(range(8)), a) == 2
    # identical prompt prefilled again with private pages: no new pins
    b = al.acquire(2)
    assert pc.insert(list(range(8)), b) == 0
    assert pc.pinned_pages == 2
    c, got = pc.match(list(range(8)))
    assert got == a                        # the original stays indexed
    al.release(b)


def test_insert_extends_cached_prefix_radix_style():
    al, pc = _setup()
    a = al.acquire(1)
    pc.insert(list(range(4)), a)
    b = al.acquire(2)                      # same first page + new tail
    new = pc.insert(list(range(8)) + [42], [a[0]] + b)
    assert new == 2                        # only the extension pinned
    c, got = pc.match(list(range(8)) + [42, 7])
    assert c == 9 and got == [a[0]] + b


def test_evict_lru_releases_leaves_first_until_pages_free():
    al, pc = _setup(pages=6)
    a = al.acquire(2)
    pc.insert(list(range(8)), a)           # chain: a0 -> a1 (leaf)
    b = al.acquire(2)
    pc.insert([50, 51, 52, 53] + [60, 61, 62, 63], b)
    al.release(a)
    al.release(b)                          # only the cache pins now
    pc.match(list(range(8)))               # touch chain A (fresher)
    assert al.free_pages == 2
    freed = pc.evict_lru(1)
    assert freed >= 1
    # chain B's leaf went first (least recently matched)
    c, got = pc.match([50, 51, 52, 53, 60, 61, 62, 63])
    assert c == 4                          # b1 evicted, b0 kept
    c, got = pc.match(list(range(8)))
    assert c == 8                          # chain A untouched
    # interior pages are never evicted before their subtree
    freed = pc.evict_lru(10)               # drain everything evictable
    assert pc.pinned_pages == 0
    assert al.free_pages == 6


def test_evicting_shared_page_does_not_free_it_under_a_live_owner():
    """The silent-overwrite hazard, cache edition: eviction only drops
    the cache's OWN reference — a page a live request still maps stays
    out of the free list until that request releases it."""
    al, pc = _setup(pages=4)
    a = al.acquire(1)
    pc.insert(list(range(4)), a)           # rc(a0) = 2 (request+cache)
    free_before = al.free_pages
    freed = pc.evict_lru(1)
    assert freed == 0                      # released, NOT freed
    assert pc.pinned_pages == 0
    assert al.refcount(a[0]) == 1          # the request's ref survives
    assert al.free_pages == free_before
    al.release(a)
    assert al.free_pages == 4


def test_matched_pages_pinned_before_eviction_cannot_be_reissued():
    """Regression (review finding): the scheduler pins matched pages
    (share) BEFORE eviction/acquire — so even an eviction sweep that
    drains the whole cache cannot free a matched page into the LIFO
    free list where the very next acquire would re-issue it as a
    private page (one physical page mapped twice into one row)."""
    al, pc = _setup(pages=6)
    a = al.acquire(3)
    pc.insert(list(range(10)), a)
    al.release(a)                          # cache is the sole owner
    c, matched = pc.match(list(range(10)))
    assert matched == a
    al.share(matched)                      # the _reservation pin
    pc.evict_lru(100)                      # drain everything evictable
    assert pc.pinned_pages == 0
    got = al.acquire(al.free_pages)        # whatever actually freed
    assert not set(got) & set(matched), (got, matched)
    for p in matched:
        assert al.refcount(p) == 1         # still the request's


def test_insert_validates_page_coverage():
    al, pc = _setup()
    with pytest.raises(ValueError, match="cannot back"):
        pc.insert(list(range(9)), al.acquire(2))


def test_clear_releases_everything():
    al, pc = _setup()
    a = al.acquire(3)
    pc.insert(list(range(10)), a)
    al.release(a)
    pc.clear()
    assert pc.pinned_pages == 0 and al.free_pages == 12
