"""Checkpoint -> inference round-trip (ISSUE 4 satellite): a contrib
``state_dict`` written at dp=4 loads into engine weights identical to a
dense (dp=1) export, and a ZeRO-sharded FlatState exports the same
params as its dense twin."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.inference import InferenceEngine
from apex_tpu.optimizers import functional as fopt
from apex_tpu.optimizers.functional import export_params
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

DP = 4


def _gpt():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=32,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, model, params


def _grads_like(params, seed=1):
    flat, unravel = jax.flatten_util.ravel_pytree(params)
    g = jax.random.normal(jax.random.PRNGKey(seed), flat.shape,
                          flat.dtype) * 1e-2
    return unravel(g)


def _train_contrib(params, grads, dp, n_steps=2):
    """n_steps of DistributedFusedAdam at the given dp; returns the
    optimizer and the GLOBAL-view state (state_dict-ready)."""
    opt = DistributedFusedAdam(dp, lr=1e-2, weight_decay=0.01)
    if dp == 1:
        state = opt.init_state(params)
        for _ in range(n_steps):
            _, state = opt.step(state, grads)
        return opt, state
    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))

    def body():
        state = opt.init_state(params)
        for _ in range(n_steps):
            _, state = opt.step(state, grads)
        return state

    specs = {"step": P(), "master": P("data"), "exp_avg": P("data"),
             "exp_avg_sq": P("data")}
    state = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(), out_specs=specs))()
    return opt, state


def test_contrib_dp4_state_dict_equals_dense_export():
    """The satellite's literal claim: dp=4 checkpoint -> engine weights
    identical (bitwise) to the dp=1 export."""
    cfg, model, params = _gpt()
    grads = _grads_like(params)
    opt4, state4 = _train_contrib(params, grads, DP)
    opt1, state1 = _train_contrib(params, grads, 1)
    sd4, sd1 = opt4.state_dict(state4), opt1.state_dict(state1)
    # same training trajectory: masters agree to fp tolerance...
    np.testing.assert_allclose(sd4["master"], sd1["master"],
                               rtol=1e-6, atol=1e-7)
    # ...and the EXPORT path is bitwise-identical given equal masters:
    # run both state_dicts through the engine weight boundary
    e4 = export_params(sd4["master"], params, dtype=jnp.bfloat16)
    e1 = export_params(sd1["master"], params, dtype=jnp.bfloat16)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)), e4, e1)

    # and both restore straight into a working engine with equal output
    eng4 = InferenceEngine.from_state_dict("gpt", cfg, sd4, params,
                                           slots=1, max_seq=32)
    eng1 = InferenceEngine.from_state_dict("gpt", cfg, sd1, params,
                                           slots=1, max_seq=32)
    prompt = [3, 1, 4, 1, 5]
    assert eng4.generate([prompt], max_new_tokens=4) == \
        eng1.generate([prompt], max_new_tokens=4)


def test_export_params_layout_and_padding():
    _, _, params = _gpt()
    flat, _ = jax.flatten_util.ravel_pytree(params)
    # ZeRO padding on the tail must be sliced off
    padded = jnp.concatenate([flat, jnp.zeros((13,), flat.dtype)])
    tree = export_params(padded, params)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), tree, params)
    # bf16 export casts floating leaves only
    tree16 = export_params(padded, params, dtype=jnp.bfloat16)
    for leaf in jax.tree.leaves(tree16):
        assert leaf.dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="SHARD"):
        export_params(flat[:100], params)


def test_flat_state_params_dtype_export():
    """``FlatState.params(dtype=...)`` — the TrainState -> engine
    boundary — casts floating leaves and leaves values = master."""
    cfg, model, params = _gpt()
    tx = fopt.fused_adam(lr=1e-2)
    state = tx.init(params)
    out = state.params(dtype=jnp.bfloat16)
    for leaf in jax.tree.leaves(out):
        assert leaf.dtype == jnp.bfloat16
    ref = state.params()
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        rtol=1e-2, atol=1e-2), out, ref)
    # the engine classmethod accepts the TrainState shape end to end
    from apex_tpu import train_step
    ts = train_step.init_train_state(tx, params)
    eng = InferenceEngine.from_train_state("gpt", cfg, ts, slots=1,
                                           max_seq=32)
    toks = eng.generate([[1, 2, 3]], max_new_tokens=3)[0]
    assert len(toks) == 3


def test_zero_sharded_flat_state_exports_like_dense():
    """A dp-sharded FlatState (ZeRO) all-gathers into the same exported
    params as the dense state — the 'checkpoint at any dp' property at
    the FlatState level."""
    _, _, params = _gpt()
    tx = fopt.fused_adam(lr=1e-2)
    dense = tx.init(params)
    mesh = Mesh(np.array(jax.devices()[:DP]), ("data",))

    def body():
        st = tx.init(params, shard=("data", DP))
        return st.master

    shards = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(), out_specs=P("data")))()
    sharded = tx.init(params, shard=("data", DP, 0)).replace(
        master=shards)          # global view, shard layout stamped
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a, np.float32), np.asarray(b, np.float32)),
        sharded.params(dtype=jnp.bfloat16),
        dense.params(dtype=jnp.bfloat16))
