"""Ragged paged decode attention vs the dense decode path: the XLA
gather fallback must be numerically identical to the dense cache's
decode, the Pallas kernel must match within fp tolerance on ragged
batches (straggler + shorts) across MHA/GQA/MQA, and the crossover
knob must dispatch like the dense machinery it mirrors."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import decode_attention
from apex_tpu.ops.paged_attention import (
    _PAGED_XLA_MAX_PAGES,
    paged_decode_attention,
    paged_xla_max_pages,
)


def _paged_twin(slots, h, kvh, ps, mpps, lengths, d=16, seed=0):
    """(q, dense k/v, paged pool k/v + scrambled page table, lengths):
    the SAME cache contents laid out both ways, with dead pool pages
    holding garbage so masking bugs can't hide."""
    rng = np.random.RandomState(seed)
    max_seq = ps * mpps
    n_pages = slots * mpps
    q = rng.randn(slots, h, d).astype(np.float32)
    k = rng.randn(slots, kvh, max_seq, d).astype(np.float32)
    v = rng.randn(slots, kvh, max_seq, d).astype(np.float32)
    pool_k = rng.randn(n_pages + 1, kvh, ps, d).astype(np.float32)
    pool_v = rng.randn(n_pages + 1, kvh, ps, d).astype(np.float32)
    perm = rng.permutation(n_pages)       # non-contiguous assignment
    pt = np.empty((slots, mpps), np.int32)
    i = 0
    for s in range(slots):
        for j in range(mpps):
            pid = perm[i]
            i += 1
            pt[s, j] = pid
            pool_k[pid] = k[s, :, j * ps:(j + 1) * ps, :]
            pool_v[pid] = v[s, :, j * ps:(j + 1) * ps, :]
    return (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(pt),
            jnp.asarray(lengths, jnp.int32))


RAGGED = [32, 0, 1, 7, 8, 9]              # straggler + shorts around ps


@pytest.mark.parametrize("h,kvh", [(4, 4), (8, 2), (4, 1)])  # MHA/GQA/MQA
def test_xla_path_is_bitwise_the_dense_decode(h, kvh):
    q, k, v, pk, pv, pt, ln = _paged_twin(6, h, kvh, 8, 4, RAGGED)
    dense = decode_attention(q, k, v, ln, use_kernel=False)
    paged = paged_decode_attention(q, pk, pv, pt, ln, use_kernel=False)
    # the gathered window IS the dense window: identical ops, identical
    # bits — the paged memory model changes storage, not math
    np.testing.assert_array_equal(np.asarray(paged), np.asarray(dense))


@pytest.mark.parametrize("h,kvh", [(4, 4), (8, 2), (4, 1)])
def test_kernel_matches_dense_on_ragged_batch(h, kvh):
    q, k, v, pk, pv, pt, ln = _paged_twin(6, h, kvh, 8, 4, RAGGED)
    dense = decode_attention(q, k, v, ln, use_kernel=False)
    kern = paged_decode_attention(q, pk, pv, pt, ln, use_kernel=True)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(dense),
                               rtol=2e-5, atol=2e-5)


def test_kernel_bf16_matches_dense_bf16():
    q, k, v, pk, pv, pt, ln = _paged_twin(4, 8, 2, 8, 3, [24, 5, 0, 13])
    bf = jnp.bfloat16
    dense = decode_attention(q.astype(bf), k.astype(bf), v.astype(bf),
                             ln, use_kernel=False)
    kern = paged_decode_attention(q.astype(bf), pk.astype(bf),
                                  pv.astype(bf), pt, ln, use_kernel=True)
    np.testing.assert_allclose(
        np.asarray(kern, np.float32), np.asarray(dense, np.float32),
        rtol=2e-2, atol=2e-2)


def test_zero_length_slots_emit_zeros_finite():
    q, k, v, pk, pv, pt, ln = _paged_twin(3, 4, 2, 4, 3, [0, 5, 0])
    for use_kernel in (False, True):
        out = np.asarray(paged_decode_attention(q, pk, pv, pt, ln,
                                                use_kernel=use_kernel))
        assert np.all(out[0] == 0) and np.all(out[2] == 0)
        assert np.all(np.isfinite(out))


def test_four_dim_q_round_trips():
    q, k, v, pk, pv, pt, ln = _paged_twin(3, 4, 2, 4, 3, [5, 3, 1])
    out3 = paged_decode_attention(q, pk, pv, pt, ln)
    out4 = paged_decode_attention(q[:, :, None, :], pk, pv, pt, ln)
    assert out4.shape == (3, 4, 1, 16)
    np.testing.assert_array_equal(np.asarray(out4[:, :, 0]),
                                  np.asarray(out3))


def test_crossover_knob(monkeypatch):
    assert paged_xla_max_pages() == _PAGED_XLA_MAX_PAGES
    assert paged_xla_max_pages(8) == 8                 # kwarg wins
    monkeypatch.setenv("APEX_TPU_PAGED_XLA_MAX_PAGES", "3")
    assert paged_xla_max_pages() == 3
    assert paged_xla_max_pages(7) == 7
    monkeypatch.setenv("APEX_TPU_PAGED_XLA_MAX_PAGES", "bogus")
    with pytest.raises(ValueError, match="must be an int"):
        paged_xla_max_pages()


def test_auto_dispatch_selects_kernel_above_crossover(monkeypatch):
    """The traced program contains a pallas_call exactly when the page
    count exceeds the effective crossover — the knob really steers."""
    q, k, v, pk, pv, pt, ln = _paged_twin(3, 4, 2, 4, 3, [5, 3, 1])

    def has_pallas(xla_max_pages):
        jaxpr = jax.make_jaxpr(
            lambda *a: paged_decode_attention(
                *a, xla_max_pages=xla_max_pages))(q, pk, pv, pt, ln)
        return "pallas_call" in str(jaxpr)

    assert not has_pallas(3)          # mpps == 3 <= 3: XLA gather path
    assert has_pallas(2)              # mpps > 2: kernel path
    assert has_pallas(0)              # 0 forces the kernel
    monkeypatch.setenv("APEX_TPU_PAGED_XLA_MAX_PAGES", "0")
    assert has_pallas(None)           # env steers the auto dispatch


def test_validates_shapes():
    q, k, v, pk, pv, pt, ln = _paged_twin(3, 4, 2, 4, 3, [5, 3, 1])
    with pytest.raises(ValueError, match="q_len == 1"):
        paged_decode_attention(jnp.zeros((3, 4, 2, 16)), pk, pv, pt, ln)
    with pytest.raises(ValueError, match="equal-shaped"):
        paged_decode_attention(q, pk, pv[:, :, :2], pt, ln)
    with pytest.raises(ValueError, match="must divide"):
        bad = jnp.zeros((5, 3, 4, 16))      # 3 kv heads !| 4 q heads
        paged_decode_attention(q, bad, bad, pt, ln)
    with pytest.raises(ValueError, match="page_table"):
        paged_decode_attention(q, pk, pv, pt[:2], ln)
    with pytest.raises(ValueError, match="lengths"):
        paged_decode_attention(q, pk, pv, pt, ln[:2])
