"""Fused transformer-block decode (ISSUE 15): the one-kernel-per-layer
lowering serves the SAME greedy tokens as the per-op path, the fused
weight layout is an exact re-slicing of the model tree, and the
dispatch knob resolves statically with the documented precedence."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.inference import models as inf_models
from apex_tpu.ops.paged_attention import (
    decode_fusion,
    fusion_min_pages,
    resolve_decode_fusion,
)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


def _gpt(layers=1):
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, params


def _llama(kvh):
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=1,
                      num_attention_heads=4, num_kv_heads=kvh,
                      max_seq_length=64)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, params


def _wave(kind, cfg, params, **engine_kw):
    eng = InferenceEngine(kind, cfg, params, slots=2, max_seq=64,
                          page_size=8, num_pages=24, **engine_kw)
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry
    sched = SlotScheduler(eng, telemetry=ServeTelemetry(MetricsRegistry()))
    prompts = [list((np.arange(9) * 5 + i) % cfg.vocab_size)
               for i in range(3)]
    uids = [sched.submit(p, max_new_tokens=8) for p in prompts]
    out = sched.run()
    return [out[u] for u in uids]


def test_fused_gpt_matches_unfused_greedy():
    cfg, params = _gpt()
    assert _wave("gpt", cfg, params) == \
        _wave("gpt", cfg, params, decode_fusion="1")


@pytest.mark.parametrize("kvh", [4, 2, 1], ids=["mha", "gqa", "mqa"])
def test_fused_llama_tracks_unfused_step_locked(kvh):
    """Step-locked fused-vs-unfused parity on the LLaMA layouts: the
    SAME token stream through both lowerings, logits within the fused
    kernel's fp32-residual tolerance, argmax identical except at
    genuine near-ties (free-running greedy streams can diverge at a
    tie on random toy weights — that is the tolerance contract, not a
    bug; bitwise belongs to the fusion-off path)."""
    from apex_tpu.inference.engine import make_decode_fn
    from apex_tpu.inference.sampling import SamplingConfig

    cfg, params = _llama(kvh)
    eng = InferenceEngine("llama", cfg, params, slots=2, max_seq=64,
                          page_size=8, num_pages=24)
    alloc = eng.new_allocator()
    cache_a, cache_b = eng.init_cache(), eng.init_cache()
    prompt = list((np.arange(9) * 5) % 64)
    for slot in range(2):
        pages = alloc.acquire(alloc.pages_needed(len(prompt) + 8))
        cache_a, tok, _ = eng.prefill(cache_a, prompt, slot, pages=pages)
        cache_b, _, _ = eng.prefill(cache_b, prompt, slot, pages=pages)
    fused = inf_models.fused_layer_params("llama", cfg, params)
    unfused_fn = jax.jit(make_decode_fn("llama", cfg, SamplingConfig()),
                         donate_argnums=(0,))
    fused_fn = jax.jit(
        make_decode_fn("llama", cfg, SamplingConfig(), fused=True),
        donate_argnums=(0,))
    toks = np.asarray([int(tok), int(tok)], np.int32)
    key = jax.random.PRNGKey(0)
    active = np.ones((2,), bool)
    for step in range(4):
        cache_a, ta, la, _ = unfused_fn(cache_a, params, toks, active,
                                        key, jnp.int32(step))
        cache_b, _, lb, _ = fused_fn(cache_b, (params, fused), toks,
                                     active, key, jnp.int32(step))
        la, lb = np.asarray(la), np.asarray(lb)
        np.testing.assert_allclose(la, lb, rtol=0, atol=0.15)
        for s in range(2):
            top2 = np.sort(la[s])[-2:]
            if top2[1] - top2[0] > 0.3:         # not a near-tie
                assert la[s].argmax() == lb[s].argmax()
        toks = np.asarray(ta)          # lock both paths to one stream


def test_fused_layer_params_is_exact_reslicing():
    """The fused layout must reproduce the model path's projections
    EXACTLY (same dots over the same reduction order): q/k/v from the
    deinterleaved planes equal the interleaved qkv's split, for both
    weight conventions."""
    cfg, params = _gpt(layers=1)
    p = params["params"]["layer_0"]["self_attention"]["query_key_value"]
    blk = inf_models.fused_layer_params("gpt", cfg, params)[0]
    x = jax.random.normal(jax.random.PRNGKey(3), (5, cfg.hidden_size))
    qkv = (x @ p["weight"].T + p["bias"]).reshape(
        5, cfg.num_attention_heads, 3 * 16)
    q_ref, k_ref, v_ref = jnp.split(qkv, 3, axis=-1)
    np.testing.assert_array_equal(
        np.asarray(x @ blk["wq"] + blk["bq"]),
        np.asarray(q_ref.reshape(5, -1)))
    np.testing.assert_array_equal(
        np.asarray(x @ blk["wk"] + blk["bk"]),
        np.asarray(k_ref.reshape(5, -1)))
    np.testing.assert_array_equal(
        np.asarray(x @ blk["wv"] + blk["bv"]),
        np.asarray(v_ref.reshape(5, -1)))

    cfg2, params2 = _llama(2)
    att = params2["params"]["layer_0"]["attention"]
    blk2 = inf_models.fused_layer_params("llama", cfg2, params2)[0]
    x2 = jax.random.normal(jax.random.PRNGKey(4), (5, cfg2.hidden_size))
    kv = (x2 @ att["kv_proj"]["weight"].T).reshape(5, 2, 2 * 8)
    k2, v2 = jnp.split(kv, 2, axis=-1)
    np.testing.assert_array_equal(np.asarray(x2 @ blk2["wk"]),
                                  np.asarray(k2.reshape(5, -1)))
    np.testing.assert_array_equal(np.asarray(x2 @ blk2["wv"]),
                                  np.asarray(v2.reshape(5, -1)))


def test_fused_decode_logits_close_to_unfused():
    """Beyond greedy-token equality: the fused kernel's logits track
    the per-op path within bf16-accumulation tolerance at every step
    (the residual chain stays fp32 in-kernel, so exact bitwise is NOT
    expected — the XLA fallback owns bitwise)."""
    from apex_tpu.inference.engine import make_decode_fn
    from apex_tpu.inference.sampling import SamplingConfig

    cfg, params = _gpt()
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                          page_size=8, num_pages=24)
    alloc = eng.new_allocator()
    cache_a = eng.init_cache()
    cache_b = eng.init_cache()
    prompt = list((np.arange(9) * 5) % 64)
    for slot in range(2):
        # one reservation serves BOTH caches: identical page rows in
        # two independent pools make the twin states comparable
        pages = alloc.acquire(alloc.pages_needed(len(prompt) + 8))
        cache_a, tok, _ = eng.prefill(cache_a, prompt, slot, pages=pages)
        cache_b, _, _ = eng.prefill(cache_b, prompt, slot, pages=pages)
    fused = inf_models.fused_layer_params("gpt", cfg, params)
    unfused_fn = jax.jit(make_decode_fn("gpt", cfg, SamplingConfig()),
                         donate_argnums=(0,))
    fused_fn = jax.jit(
        make_decode_fn("gpt", cfg, SamplingConfig(), fused=True),
        donate_argnums=(0,))
    toks = np.asarray([int(tok), int(tok)], np.int32)
    key = jax.random.PRNGKey(0)
    active = np.ones((2,), bool)
    ta, tb = toks, toks
    for step in range(4):
        cache_a, ta, la, _ = unfused_fn(cache_a, params, ta, active,
                                        key, jnp.int32(step))
        cache_b, tb, lb, _ = fused_fn(cache_b, (params, fused), tb,
                                      active, key, jnp.int32(step))
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   rtol=0, atol=0.15)
        np.testing.assert_array_equal(np.asarray(ta), np.asarray(tb))


def test_decode_fusion_knob_resolution(monkeypatch):
    monkeypatch.delenv("APEX_TPU_DECODE_FUSION", raising=False)
    assert decode_fusion() == "0"
    monkeypatch.setenv("APEX_TPU_DECODE_FUSION", "auto")
    assert decode_fusion() == "auto"
    assert decode_fusion("1") == "1"            # kwarg beats env
    with pytest.raises(ValueError):
        decode_fusion("maybe")
    monkeypatch.setenv("APEX_TPU_FUSION_MIN_PAGES", "4")
    assert fusion_min_pages() == 4
    assert fusion_min_pages(16) == 16
    # auto: paged window length against the crossover
    assert resolve_decode_fusion("auto", paged=True, max_pages=4)
    assert not resolve_decode_fusion("auto", paged=True, max_pages=3)
    assert not resolve_decode_fusion("auto", paged=False)
    assert not resolve_decode_fusion("0", paged=True, max_pages=99)
    with pytest.raises(ValueError):
        resolve_decode_fusion("1", paged=False)


def test_fusion_requires_paged_engine():
    cfg, params = _gpt(layers=1)
    with pytest.raises(ValueError):
        InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                        decode_fusion="1")
