"""Paged engine acceptance (ISSUE 6): the paged cache serves the SAME
tokens as the dense slot cache and the full-sequence forward, decode
stays ONE executable across admits/retires, the scheduler admits by
free pages (more concurrent short requests than the equal-HBM slot
cache can hold), and capacity truncation is surfaced with a reason
code instead of silently clamped."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


def _tiny_gpt(max_seq=64, layers=1):
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=layers,
                    num_attention_heads=2, max_seq_length=max_seq,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, model, params


def _reference_greedy(model, params, prompt, n_new):
    total = len(prompt) + n_new
    toks = list(prompt)
    apply = jax.jit(model.apply)
    for _ in range(n_new):
        padded = np.zeros((1, total), np.int32)
        padded[0, :len(toks)] = toks
        logits = apply(params, jnp.asarray(padded))
        toks.append(int(jnp.argmax(logits[len(toks) - 1, 0]
                                   .astype(jnp.float32))))
    return toks[len(prompt):]


def test_llama_gqa_one_layer_paged_greedy_fast():
    """Fast-lane paged parity sentinel: smallest config walking the
    full paged GQA decode path (page-table gather, RoPE at position,
    grouped pool) — the paged twin of the dense sentinel."""
    cfg = LlamaConfig(vocab_size=32, hidden_size=16, num_layers=1,
                      num_attention_heads=4, num_kv_heads=2,
                      max_seq_length=16)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    engine = InferenceEngine("llama", cfg, params, slots=1, max_seq=16,
                             page_size=4)
    prompt = [3, 1, 4, 1]
    ref = _reference_greedy(model, params, prompt, 3)
    got = engine.generate([prompt], max_new_tokens=3)[0]
    assert got == ref


def test_paged_generate_equals_dense_generate():
    """The paged memory model changes storage, not tokens: identical
    streams from both caches, with the paged pool backpressured below
    dense-equivalent capacity so page reuse is actually exercised."""
    cfg, model, params = _tiny_gpt(max_seq=64, layers=2)
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, 64, size=n)) for n in (4, 9, 3, 7, 5)]
    dense = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)
    paged = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                            page_size=16, num_pages=4)
    out_d = dense.generate(prompts, max_new_tokens=5)
    out_p = paged.generate(prompts, max_new_tokens=5)
    assert out_d == out_p


def test_paged_kernel_path_engine_matches_dense():
    """paged_attn_max_pages=0 pins the Pallas kernel inside the decode
    executable; greedy streams still match the dense engine."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    rng = np.random.RandomState(5)
    prompts = [list(rng.randint(0, 64, size=n)) for n in (6, 3)]
    dense = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)
    kern = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                           page_size=16, paged_attn_max_pages=0)
    assert dense.generate(prompts, max_new_tokens=5) == \
        kern.generate(prompts, max_new_tokens=5)


def test_admission_by_pages_beats_equal_hbm_slot_cache():
    """ISSUE 6 acceptance: with page_size * num_pages < slots *
    max_seq, the paged scheduler admits MORE concurrent short requests
    than the slot cache could hold at the same KV HBM."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, 64, size=4)) for _ in range(6)]

    def peak(engine):
        sched = SlotScheduler(engine)
        for p in prompts:
            sched.submit(p, max_new_tokens=3)
        sched.run()
        return sched.peak_active, engine.cache_hbm_bytes()

    # HBM budget: a 2-slot dense cache
    dense = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)
    # same budget as a pool: 2 * 64 / 8 - 1 trash-equivalent pages,
    # slots are now cheap metadata
    paged = InferenceEngine("gpt", cfg, params, slots=len(prompts),
                            max_seq=64, page_size=8, num_pages=15)
    d_peak, d_bytes = peak(dense)
    p_peak, p_bytes = peak(paged)
    assert p_bytes <= d_bytes                  # no extra HBM spent
    assert paged.page_size * paged.num_pages < paged.slots * paged.max_seq
    assert d_peak <= dense.slots
    assert p_peak > d_peak, (p_peak, d_peak)   # the whole point


def test_out_of_pages_is_backpressure_not_failure():
    """A pool too small for the whole wave still completes every
    request — admission waits for reclaimed pages (FIFO), it never
    fails mid-decode or drops a request."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    rng = np.random.RandomState(9)
    # prompt + 4 new tokens lands in (16, 32]: 2 pages per request
    prompts = [list(rng.randint(0, 64, size=n)) for n in (13, 20, 14, 17)]
    # 2 pages of 16: at most ONE request's reservation at a time
    paged = InferenceEngine("gpt", cfg, params, slots=4, max_seq=64,
                            page_size=16, num_pages=2)
    dense = InferenceEngine("gpt", cfg, params, slots=4, max_seq=64)
    sched = SlotScheduler(paged)
    uids = [sched.submit(p, max_new_tokens=4) for p in prompts]
    out = sched.run()
    assert sorted(out) == sorted(uids)
    assert sched.peak_active == 1              # serialized by the pool
    assert [out[u] for u in uids] == \
        dense.generate(prompts, max_new_tokens=4)


def test_prefill_rejects_undersized_reservation():
    """Regression (review finding): a reservation that can't hold the
    prompt must fail loudly, not park the prompt tail in the trash
    page."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                          page_size=16)
    alloc = eng.new_allocator()
    cache = eng.init_cache()
    with pytest.raises(ValueError, match="trash page"):
        eng.prefill(cache, list(range(2, 20)), 0, pages=alloc.acquire(1))


def test_request_larger_than_pool_fails_fast_at_submit():
    """A request no empty pool could cover is rejected at submit(),
    before any earlier request's work could be done and discarded."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    paged = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                            page_size=16, num_pages=1)
    sched = SlotScheduler(paged)
    with pytest.raises(ValueError, match="grow num_pages"):
        sched.submit(list(range(2, 20)), max_new_tokens=4)  # 2 pages
    # BERT never has a cache — paged kwargs are rejected up front
    from apex_tpu.transformer.testing import BertConfig
    bcfg = BertConfig(vocab_size=32, hidden_size=32, num_layers=1,
                      num_attention_heads=2, max_seq_length=16,
                      hidden_dropout=0.0, attention_dropout=0.0)
    with pytest.raises(ValueError, match="encode-only"):
        InferenceEngine("bert", bcfg, {}, page_size=16)


def test_truncation_reason_codes():
    """A request whose prompt + budget overruns its capacity retires
    with reason "truncated" (tokens stop, loudly); budget and EOS cuts
    record their own codes."""
    cfg, model, params = _tiny_gpt(max_seq=32)
    paged = InferenceEngine("gpt", cfg, params, slots=2, max_seq=32,
                            page_size=8)
    sched = SlotScheduler(paged)
    rng = np.random.RandomState(11)
    u_trunc = sched.submit(list(rng.randint(0, 64, size=28)),
                           max_new_tokens=50)   # 28 + 50 >> max_seq 32
    u_len = sched.submit(list(rng.randint(0, 64, size=4)),
                         max_new_tokens=3)
    out = sched.run()
    assert sched.finish_reasons[u_trunc] == "truncated"
    # capacity = max_seq = 32: 28 prompt + 5 generated - 1 hits the cap
    assert len(out[u_trunc]) == 5
    assert sched.finish_reasons[u_len] == "length"
    assert len(out[u_len]) == 3
    # EOS cut records "eos"
    sched2 = SlotScheduler(paged)
    u = sched2.submit([1, 2, 3], max_new_tokens=4)
    first = sched2.run()[u][0]
    sched3 = SlotScheduler(paged)
    u2 = sched3.submit([1, 2, 3], max_new_tokens=4, eos_id=int(first))
    assert sched3.run()[u2] == [first]
    assert sched3.finish_reasons[u2] == "eos"


def test_paged_decode_is_one_executable_across_admits_and_retires():
    """ISSUE 6 acceptance: decode compile count stays 1 across N steps
    WITH admits/retires (page-table churn) in between — the page table
    is a traced operand, so reassigning pages never recompiles."""
    cfg, model, params = _tiny_gpt(max_seq=64)
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                          page_size=16)
    alloc = eng.new_allocator()

    events = []
    from jax._src import monitoring as _mon
    saved = {attr: list(getattr(_mon, attr))
             for attr in dir(_mon)
             if attr.endswith("_listeners")
             and isinstance(getattr(_mon, attr), list)}
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    try:
        cache = eng.init_cache()
        pages0 = alloc.acquire(2)
        cache, _, _ = eng.prefill(cache, [1, 2, 3], 0, pages=pages0)
        last = np.zeros((2,), np.int32)
        active = np.array([True, False])
        cache, toks, _, _ = eng.decode(cache, last, active)   # warm up
        jax.block_until_ready(cache)
        jax.clear_caches()
        events.clear()
        # interleave: decode / retire+admit into the other slot (fresh
        # pages, same bucket) / decode / admit again / decode
        cache, toks, _, _ = eng.decode(cache, last, active)
        alloc.release(pages0)
        pages1 = alloc.acquire(2)
        cache, _, _ = eng.prefill(cache, [4, 5], 1, pages=pages1)
        active = np.array([False, True])
        cache, toks, _, _ = eng.decode(cache, last, active)
        pages2 = alloc.acquire(2)
        cache, _, _ = eng.prefill(cache, [6, 7, 8], 0, pages=pages2)
        active = np.array([True, True])
        for _ in range(3):
            cache, toks, _, _ = eng.decode(cache, last, active)
        jax.block_until_ready(cache)
        decode_compiles = sum(1 for e in events
                              if "compile_requests" in e)
        # one decode recompile (cleared cache) + one prefill bucket;
        # the admits/retires between steps must add NOTHING
        assert decode_compiles <= 2, decode_compiles
    finally:
        for attr, listeners in saved.items():
            getattr(_mon, attr)[:] = listeners
