"""Slot KV cache semantics: insert/append/advance/evict as pure donated
updates over one statically shaped buffer pair."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import kv_cache

SLOTS, LAYERS, KVH, MAXSEQ, D = 3, 2, 2, 16, 8


def _cache(dtype=jnp.float32):
    return kv_cache.init_cache(SLOTS, LAYERS, KVH, MAXSEQ, D, dtype=dtype)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape),
                       jnp.float32)


def test_init_shape_and_dtype():
    c = _cache(jnp.bfloat16)
    assert c.k.shape == (SLOTS, LAYERS, KVH, MAXSEQ, D)
    assert c.k.dtype == jnp.bfloat16 and c.v.dtype == jnp.bfloat16
    assert c.lengths.dtype == jnp.int32
    assert (c.slots, c.layers, c.kv_heads, c.max_seq, c.head_dim) == \
        (SLOTS, LAYERS, KVH, MAXSEQ, D)
    assert np.all(np.asarray(c.lengths) == 0)


def test_insert_places_slab_and_sets_length():
    c = _cache()
    k = _rand((LAYERS, KVH, 5, D), 1)
    v = _rand((LAYERS, KVH, 5, D), 2)
    c = kv_cache.insert(c, 1, k, v, 4)          # padded to 5, 4 real
    np.testing.assert_array_equal(np.asarray(c.k[1, :, :, :5]),
                                  np.asarray(k))
    np.testing.assert_array_equal(np.asarray(c.v[1, :, :, :5]),
                                  np.asarray(v))
    assert np.asarray(c.lengths).tolist() == [0, 4, 0]
    # other slots untouched
    assert np.all(np.asarray(c.k[0]) == 0) and np.all(
        np.asarray(c.k[2]) == 0)


def test_insert_validates():
    c = _cache()
    with pytest.raises(ValueError, match="prefill k/v"):
        kv_cache.insert(c, 0, _rand((LAYERS, KVH + 1, 4, D)),
                        _rand((LAYERS, KVH + 1, 4, D)), 4)
    with pytest.raises(ValueError, match="max_seq"):
        kv_cache.insert(c, 0, _rand((LAYERS, KVH, MAXSEQ + 1, D)),
                        _rand((LAYERS, KVH, MAXSEQ + 1, D)), 4)


def test_append_writes_at_each_slots_own_length():
    c = _cache()
    c = kv_cache.insert(c, 0, _rand((LAYERS, KVH, 3, D), 1),
                        _rand((LAYERS, KVH, 3, D), 2), 3)
    c = kv_cache.insert(c, 2, _rand((LAYERS, KVH, 6, D), 3),
                        _rand((LAYERS, KVH, 6, D), 4), 6)
    k_tok = _rand((SLOTS, KVH, D), 5)
    v_tok = _rand((SLOTS, KVH, D), 6)
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, k_tok, v_tok)
    # token rows landed at positions (3, 0, 6) per slot, in EVERY layer
    for slot, pos in ((0, 3), (1, 0), (2, 6)):
        want_k = np.broadcast_to(np.asarray(k_tok[slot]),
                                 (LAYERS, KVH, D))
        want_v = np.broadcast_to(np.asarray(v_tok[slot]),
                                 (LAYERS, KVH, D))
        np.testing.assert_array_equal(np.asarray(c.k[slot, :, :, pos]),
                                      want_k)
        np.testing.assert_array_equal(np.asarray(c.v[slot, :, :, pos]),
                                      want_v)
    # lengths only move via advance, and only for active slots
    c, trunc = kv_cache.advance(c, jnp.asarray([True, False, True]))
    assert np.asarray(c.lengths).tolist() == [4, 0, 7]
    assert not np.asarray(trunc).any()


def test_append_validates():
    c = _cache()
    with pytest.raises(ValueError, match="token k/v"):
        kv_cache.append_layer(c, 0, _rand((SLOTS, KVH, D + 1)),
                              _rand((SLOTS, KVH, D + 1)))


def test_evict_zeroes_length_only():
    c = _cache()
    k = _rand((LAYERS, KVH, 4, D), 1)
    c = kv_cache.insert(c, 1, k, k, 4)
    c = kv_cache.evict(c, 1)
    assert np.asarray(c.lengths).tolist() == [0, 0, 0]
    # data untouched (masked by length; next insert overwrites)
    np.testing.assert_array_equal(np.asarray(c.k[1, :, :, :4]),
                                  np.asarray(k))


def test_updates_are_donation_safe():
    """The whole insert+append+advance chain jits with the cache donated
    — the serving property: one allocation for the engine's lifetime."""

    def step(c, k_slab, k_tok):
        c = kv_cache.insert(c, 0, k_slab, k_slab, 4)
        for layer in range(LAYERS):
            c = kv_cache.append_layer(c, layer, k_tok, k_tok)
        return kv_cache.advance(c, jnp.ones((SLOTS,), bool))[0]

    c = _cache()
    kbuf = c.k
    slab = _rand((LAYERS, KVH, 4, D), 1)
    tok = _rand((SLOTS, KVH, D), 2)
    c2 = jax.jit(step, donate_argnums=(0,))(c, slab, tok)
    jax.block_until_ready(c2)
    assert kbuf.is_deleted()                 # buffer actually reused
    assert np.asarray(c2.lengths).tolist() == [5, 1, 1]


def test_cache_is_scan_carryable():
    """Treedef stable across updates: a KVCache is a valid lax.scan
    carry (the bench/decode-loop shape)."""

    def body(c, tok):
        for layer in range(LAYERS):
            c = kv_cache.append_layer(c, layer, tok, tok)
        return (kv_cache.advance(c, jnp.ones((SLOTS,), bool))[0],
                c.lengths)

    toks = _rand((4, SLOTS, KVH, D), 7)
    c, hist = jax.lax.scan(body, _cache(), toks)
    assert np.asarray(c.lengths).tolist() == [4, 4, 4]
    assert hist.shape == (4, SLOTS)
