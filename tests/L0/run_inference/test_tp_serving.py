"""Tensor-parallel serving acceptance (ISSUE 17): the tp-sharded
engine (param mirrors column/row-partitioned, paged pool sharded over
kv heads, page table replicated host-side) serves the SAME per-slot
tokens as the single-chip engine across GPT and LLaMA GQA/MQA, the
fused-block and speculative paths shard the same way, per-rank HBM is
1/tp (the capacity case for a model that cannot fit one chip), and the
host-side allocator/prefix-cache machinery is INVARIANT under tp —
conservation law unchanged, hit/COW churn adds zero compiles.

All meshes are forced host devices (tests/conftest.py pins 8)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.inference.sampling import SamplingConfig
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


def _gpt(hidden=64, heads=4, layers=2, vocab=128, max_seq=128):
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_attention_heads=heads,
                    max_seq_length=max_seq, hidden_dropout=0.0,
                    attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, params


def _llama(kvh, heads=4):
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                      num_attention_heads=heads, num_kv_heads=kvh,
                      max_seq_length=128)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return cfg, params


def _serve(kind, cfg, params, tp, fusion="0", spec_k=0):
    """Prefill slot 0, decode 4 steps with a half-active batch, and
    (spec_k) verify one slab — the per-slot outputs a tp-sharded
    engine must reproduce bit-for-tokens vs single-chip."""
    eng = InferenceEngine(kind, cfg, params, slots=2, paged=True,
                          page_size=16, num_pages=12,
                          sampling=SamplingConfig(), spec_k=spec_k,
                          decode_fusion=fusion, tp=tp)
    cache = eng.init_cache()
    alloc = eng.new_allocator()
    pages = alloc.acquire(4)
    cache, tok, logits = eng.prefill(cache, list(range(1, 11)), 0,
                                     pages=pages)
    toks = [int(tok)]
    last = np.array([int(tok), 0], np.int32)
    active = np.array([True, False])
    for _ in range(4):
        cache, nt, _, _ = eng.decode(cache, last, active)
        toks.append(int(np.asarray(nt)[0]))
        last = np.asarray(nt)
    spec = None
    if spec_k:
        slab = np.zeros((2, spec_k + 1), np.int32)
        slab[0, 0] = toks[-1]
        cache, vt, n_emit, _ = eng.verify(cache, slab, active)
        spec = (np.asarray(vt)[0].tolist(), int(np.asarray(n_emit)[0]))
    return toks, np.asarray(logits), spec, eng


def _assert_parity(base, got, tol=1e-4):
    assert base[0] == got[0], (base[0], got[0])
    assert base[2] == got[2], (base[2], got[2])
    assert float(np.max(np.abs(base[1] - got[1]))) < tol


# -- parity: sharded vs single-chip ------------------------------------------

def test_gpt_tp2_parity_and_per_rank_hbm_fast():
    """Fast-lane sentinel: GPT paged tp=2 serves the same tokens (and
    prefill logits) as single-chip, AND the HBM acceptance arithmetic
    holds — per-rank pool bytes are 1/tp, the sharded param mirrors
    hold 1/tp of every partitioned leaf, so a model+cache footprint
    that exceeds one chip's budget fits each rank of a tp=2 mesh."""
    cfg, params = _gpt(hidden=32, heads=2, layers=1, vocab=64,
                       max_seq=64)
    base = _serve("gpt", cfg, params, 1)
    got = _serve("gpt", cfg, params, 2)
    _assert_parity(base, got)

    eng1, eng2 = base[3], got[3]
    # the paged pool: cache_hbm_bytes reports PER-RANK bytes (the
    # number serving capacity prices against under sharding)
    assert eng2.cache_hbm_bytes() * 2 == eng1.cache_hbm_bytes()
    # the pool leaves really are kv-head-sharded on device: each
    # rank's addressable shard holds kv_heads_pool/tp heads
    kvh_pool = eng2.tp_dims["kv_heads_pool"]
    cache2 = eng2.init_cache()
    shard = cache2.k.addressable_shards[0].data
    assert shard.shape[2] == kvh_pool // 2
    assert cache2.k.shape[2] == kvh_pool

    def rank0_bytes(tree):
        return sum(x.addressable_shards[0].data.nbytes
                   for x in jax.tree_util.tree_leaves(tree))

    def total_bytes(tree):
        return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree))

    full = total_bytes(eng1.params) + eng1.cache_hbm_bytes()
    rank = rank0_bytes(eng2.params) + eng2.cache_hbm_bytes()
    # the acceptance shape: pick any per-chip budget between the
    # per-rank and the unsharded footprint — single-chip cannot hold
    # it, each tp=2 rank can (embed/lm-head/qkv/mlp all sharded; only
    # norms/biases replicate, so the split is well under 3/4)
    assert rank < 0.75 * full, (rank, full)


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("fusion", ["0", "1"])
def test_gpt_tp_matrix(tp, fusion):
    """GPT paged parity over tp in {2,4} x per-op/fused decode (the
    fused path takes the 1/tp weight shard with the out-proj psum
    OUTSIDE the kernel)."""
    cfg, params = _gpt()
    _assert_parity(_serve("gpt", cfg, params, 1, fusion),
                   _serve("gpt", cfg, params, tp, fusion))


@pytest.mark.parametrize("tp", [2, 4])
@pytest.mark.parametrize("kvh", [4, 2, 1])
def test_llama_kv_replication_tp_matrix(kvh, tp):
    """LLaMA MHA/GQA/MQA parity under tp: kv heads shard when tp
    divides them and REPLICATE below tp (tp=4 over kvh=2 carries each
    kv head twice; MQA replicates its one head tp ways) — the
    kv-expansion scheme the pool's [kv_heads_pool] dimension encodes."""
    cfg, params = _llama(kvh)
    _assert_parity(_serve("llama", cfg, params, 1),
                   _serve("llama", cfg, params, tp))


@pytest.mark.parametrize("kind", ["gpt", "llama"])
def test_spec_verify_tp2_parity(kind):
    """The spec-decode verify slab scores identically on the sharded
    engine (same tokens emitted, same n_emit)."""
    cfg, params = _gpt() if kind == "gpt" else _llama(2)
    _assert_parity(_serve(kind, cfg, params, 1, spec_k=2),
                   _serve(kind, cfg, params, 2, spec_k=2))


# -- engine contract ---------------------------------------------------------

def test_tp_requires_paged_generative():
    cfg, params = _gpt()
    with pytest.raises(ValueError, match="PAGED"):
        InferenceEngine("gpt", cfg, params, slots=2, max_seq=64, tp=2)
    with pytest.raises(ValueError):
        InferenceEngine("gpt", cfg, params, slots=2, paged=True,
                        page_size=16, num_pages=8, tp=0)
    # tp must divide heads (4 heads / tp=3 has no whole-head shard)
    with pytest.raises(ValueError):
        InferenceEngine("gpt", cfg, params, slots=2, paged=True,
                        page_size=16, num_pages=8, tp=3)


def test_serve_tp_env_knob(monkeypatch):
    """APEX_TPU_SERVE_TP semantics: unset/0 -> 1, explicit engine tp
    wins over the env, garbage raises."""
    from apex_tpu.inference.engine import serve_tp
    monkeypatch.delenv("APEX_TPU_SERVE_TP", raising=False)
    assert serve_tp() == 1
    monkeypatch.setenv("APEX_TPU_SERVE_TP", "0")
    assert serve_tp() == 1
    monkeypatch.setenv("APEX_TPU_SERVE_TP", "2")
    assert serve_tp() == 2
    cfg, params = _gpt(hidden=32, heads=2, layers=1, vocab=64,
                       max_seq=32)
    # explicit tp=1 beats the env's 2 (no mesh is built at all)
    eng = InferenceEngine("gpt", cfg, params, slots=1, paged=True,
                          page_size=16, num_pages=4, tp=1)
    assert eng.tp == 1 and eng.mesh is None
    monkeypatch.setenv("APEX_TPU_SERVE_TP", "banana")
    with pytest.raises(ValueError, match="APEX_TPU_SERVE_TP"):
        serve_tp()
    monkeypatch.setenv("APEX_TPU_SERVE_TP", "-2")
    with pytest.raises(ValueError):
        serve_tp()


# -- host-side machinery invariance under tp ---------------------------------

def test_allocator_prefix_churn_invariant_and_zero_compiles_under_tp():
    """The page table/allocator stay host-side and REPLICATED under
    sharding, so admission, prefix sharing and COW are the SAME
    machinery: a shared-prefix burst on a tp=2 engine reproduces the
    single-chip engine's hit/COW/sharing counters, the allocator's
    conservation law balances after the waves, and the churn adds ZERO
    compiles to the warm sharded executables."""
    from apex_tpu.observability import MetricsRegistry, ServeTelemetry

    prefix = list(range(1, 33))                       # two full pages
    burst = [prefix + [40 + i, 50 + i] for i in range(2)]

    def churn(tp):
        cfg, params = _gpt()
        eng = InferenceEngine("gpt", cfg, params, slots=2, paged=True,
                              page_size=16, num_pages=12,
                              sampling=SamplingConfig(), tp=tp)
        # warm every executable the churn touches on ONE scheduler
        # (the prefix cache is per-scheduler): wave 1 the cold
        # full-prompt bucket, wave 2 the hit path's suffix bucket +
        # the COW copy program, wave 3 the dual-concurrent admission
        w = SlotScheduler(eng,
                          telemetry=ServeTelemetry(MetricsRegistry()))
        w.submit(list(burst[0]), max_new_tokens=2)
        w.run()
        w.submit(list(burst[0]), max_new_tokens=2)
        w.run()
        for p in burst:
            w.submit(list(p), max_new_tokens=2)
        w.run()
        tel = ServeTelemetry(MetricsRegistry())
        sched = SlotScheduler(eng, telemetry=tel)
        events = []
        from jax._src import monitoring as _mon
        saved = {attr: list(getattr(_mon, attr))
                 for attr in dir(_mon)
                 if attr.endswith("_listeners")
                 and isinstance(getattr(_mon, attr), list)}
        jax.monitoring.register_event_listener(
            lambda name, **kw: events.append(name))
        try:
            sched.submit(list(burst[0]), max_new_tokens=2)  # seed
            sched.run()
            for p in burst:                                 # hit wave
                sched.submit(list(p), max_new_tokens=2)
            sched.run()
        finally:
            for attr, listeners in saved.items():
                getattr(_mon, attr)[:] = listeners
        compiles = sum(1 for e in events if "compile_requests" in e)
        s = tel.summary()
        alloc = sched.alloc
        assert alloc.free_pages + alloc.live_pages == eng.num_pages
        return (compiles, s.get("prefix_hit_tokens", 0),
                int(tel.prefix_hits.total()), s.get("cow_copies", 0),
                alloc.free_pages)

    base, sharded = churn(1), churn(2)
    assert sharded[0] == 0, f"tp churn compiled {sharded[0]} programs"
    # identical to the single-chip run: zero compiles AND the same
    # hit/COW/free-page books (the machinery is the same host code)
    assert sharded == base, (base, sharded)
