"""Tiered KV memory (ISSUE 18): the host-DRAM page tier under the
paged pool — copy-program semantics, the byte-budgeted host store, the
prefix cache's two-state edges (offload / resurrection / host-LRU),
and the scheduler's swap-in-before-prefill path, at tp=1 and tp=2.

The conservation laws walked here every step:

* allocator: ``distinct live + free == num_pages``
* ownership: ``weighted_live == sum(holder refs) + prefix pinned``
* tier mirror: ``prefix.host_pages == store.pages``
* disjoint tiers: no page id both HBM-pinned by the cache and
  host-resident
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler, kv_cache
from apex_tpu.inference.prefix_cache import PrefixCache
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

LAYERS, KVH, PS, D, SLOTS, MPPS, PAGES = 2, 2, 4, 8, 3, 4, 6


def _cache(dtype=jnp.float32):
    return kv_cache.init_paged_cache(PAGES, LAYERS, KVH, PS, D,
                                     slots=SLOTS,
                                     max_pages_per_slot=MPPS,
                                     dtype=dtype)


def _fill(c, seed=0):
    rng = np.random.RandomState(seed)
    shape = (PAGES + 1, LAYERS, KVH, PS, D)
    return c.replace(k=jnp.asarray(rng.randn(*shape), c.k.dtype),
                     v=jnp.asarray(rng.randn(*shape), c.v.dtype))


# --------------------------------------------------------------------------
# the two copy programs
# --------------------------------------------------------------------------

def test_extract_restore_roundtrip_moves_pages():
    c = _fill(_cache())
    k0, v0 = np.asarray(c.k), np.asarray(c.v)
    ks, vs = kv_cache.extract_pages(c, jnp.asarray([4, 1], jnp.int32))
    np.testing.assert_array_equal(np.asarray(ks), k0[[4, 1]])
    np.testing.assert_array_equal(np.asarray(vs), v0[[4, 1]])
    # restore the slabs at DIFFERENT pages: content lands there bitwise
    c2 = kv_cache.restore_pages(c, jnp.asarray([0, 3], jnp.int32),
                                ks, vs)
    np.testing.assert_array_equal(np.asarray(c2.k[0]), k0[4])
    np.testing.assert_array_equal(np.asarray(c2.k[3]), k0[1])
    np.testing.assert_array_equal(np.asarray(c2.v[3]), v0[1])
    # untouched pages stay bitwise
    np.testing.assert_array_equal(np.asarray(c2.k[2]), k0[2])


def test_extract_pads_with_trash_restore_drops_oob():
    """The fixed-width batch contract: extract's padding lanes read the
    trash page (in-bounds garbage the host slices off), restore's
    padding lanes carry an out-of-bounds id and DROP — neither padding
    direction can touch live data."""
    c = _fill(_cache())
    k0 = np.asarray(c.k)
    ks, _ = kv_cache.extract_pages(
        c, jnp.asarray([2, PAGES, PAGES], jnp.int32))   # trash-padded
    np.testing.assert_array_equal(np.asarray(ks)[0], k0[2])
    # restore with OOB sentinel ids: whole cache stays bitwise
    slab = jnp.zeros((2, LAYERS, KVH, PS, D), c.k.dtype)
    oob = jnp.asarray([PAGES + 1, PAGES + 1], jnp.int32)
    c2 = kv_cache.restore_pages(c, oob, slab, slab)
    np.testing.assert_array_equal(np.asarray(c2.k), k0)


def test_restore_pages_is_donation_safe():
    def step(c, ids, ks, vs):
        return kv_cache.restore_pages(c, ids, ks, vs)

    c = _fill(_cache())
    kbuf = c.k
    slab = jnp.ones((1, LAYERS, KVH, PS, D), c.k.dtype)
    c2 = jax.jit(step, donate_argnums=(0,))(
        c, jnp.asarray([1], jnp.int32), slab, slab)
    jax.block_until_ready(c2)
    assert kbuf.is_deleted()


def test_copy_program_validation():
    c = _cache()
    with pytest.raises(ValueError, match="rank-1"):
        kv_cache.extract_pages(c, jnp.zeros((2, 2), jnp.int32))
    bad = jnp.zeros((2, LAYERS, KVH, PS + 1, D), c.k.dtype)
    with pytest.raises(ValueError, match="slab"):
        kv_cache.restore_pages(c, jnp.asarray([0, 1], jnp.int32),
                               bad, bad)


# --------------------------------------------------------------------------
# the host store's byte ledger
# --------------------------------------------------------------------------

def test_host_store_budget_and_handles():
    st = kv_cache.HostPageStore(3 * 128, 128)
    assert st.fits(3) and not st.fits(4)
    a = st.put(np.ones(2), np.ones(2))
    b = st.put(np.zeros(2), np.zeros(2))
    assert (st.pages, st.bytes_used) == (2, 256)
    st.put(None, None)
    with pytest.raises(ValueError, match="over budget"):
        st.put(None, None)                   # caller makes room FIRST
    k, _ = st.get(a)
    np.testing.assert_array_equal(k, np.ones(2))
    assert st.pop(b) is not None
    assert st.pop(b) is None                 # second pop: race-tolerant
    with pytest.raises(KeyError):
        st.get(b)
    assert st.pages == 2


def test_host_store_validation():
    with pytest.raises(ValueError):
        kv_cache.HostPageStore(-1, 128)
    with pytest.raises(ValueError):
        kv_cache.HostPageStore(0, 0)


def test_default_swap_batch_pages_env(monkeypatch):
    monkeypatch.delenv("APEX_TPU_SWAP_BATCH_PAGES", raising=False)
    assert kv_cache.default_swap_batch_pages() == 8
    monkeypatch.setenv("APEX_TPU_SWAP_BATCH_PAGES", "4")
    assert kv_cache.default_swap_batch_pages() == 4
    monkeypatch.setenv("APEX_TPU_SWAP_BATCH_PAGES", "0")
    with pytest.raises(ValueError):
        kv_cache.default_swap_batch_pages()


# --------------------------------------------------------------------------
# prefix-cache two-state edges (books only: fake offload)
# --------------------------------------------------------------------------

def _tiered(total=8, budget_pages=8):
    al = kv_cache.PageAllocator(total, PS, MPPS)
    st = kv_cache.HostPageStore(budget_pages * 128, 128)
    pc = PrefixCache(al, host_store=st,
                     offload=lambda ids: [st.put(i, i) for i in ids])
    return al, st, pc


def _books_ok(al, st, pc, holders=()):
    assert al.live_pages + al.free_pages == al.num_pages
    held = sum(len(ids) for ids in holders)
    assert al.weighted_live() == held + pc.pinned_pages
    assert pc.host_pages == st.pages
    # walk the tree: HBM pages distinct and counted; tiers disjoint
    hbm, host = [], []

    def walk(node):
        for e in node.partials.values():
            hbm.append(e.page)
        for e in node.children.values():
            (host if e.page is None else hbm).append(
                e.host if e.page is None else e.page)
            walk(e.child)

    walk(pc._root)
    assert len(hbm) == len(set(hbm)) == pc.pinned_pages
    assert len(host) == pc.host_pages


def test_evict_offloads_full_pages_and_discards_partials():
    al, st, pc = _tiered()
    toks = list(range(2 * PS + 2))               # 2 full pages + tail
    ids = al.acquire(3)
    pc.insert(toks, ids)
    al.release(ids)                              # request retires
    freed = pc.evict_lru(al.num_pages)
    assert freed == 3
    assert pc.host_pages == st.pages == 2        # partial discarded
    assert pc.swapped_out == 2 and pc.pinned_pages == 0
    _books_ok(al, st, pc)
    # match_tiered reports the host ordinals; match() truncates to 0
    c, pages, host = pc.match_tiered(toks)
    assert c == 2 * PS and pages == [-1, -1]
    assert [j for j, _ in host] == [0, 1]
    assert pc.match(toks) == (0, [])


def test_insert_resurrects_host_edges():
    al, st, pc = _tiered()
    toks = list(range(2 * PS))
    ids = al.acquire(2)
    pc.insert(toks, ids)
    al.release(ids)
    pc.evict_lru(al.num_pages)
    assert pc.host_pages == 2
    # a new request recomputed/swapped the same prefix into fresh pages
    fresh = al.acquire(2)
    new = pc.insert(toks, fresh)
    assert new == 2 and pc.host_pages == 0 and st.pages == 0
    c, pages, host = pc.match_tiered(toks)
    assert c == 2 * PS and pages == list(fresh) and host == []
    al.release(fresh)
    _books_ok(al, st, pc)


def test_host_budget_evicts_lru_leaves_then_trims():
    """A host budget of 2 pages holding a 3-page offload: the LRU host
    leaf drops to make room, and victims that still don't fit are
    discarded (oldest first) exactly as before the tier existed."""
    al, st, pc = _tiered(total=8, budget_pages=2)
    a = al.acquire(2)
    pc.insert(list(range(2 * PS)), a)
    al.release(a)
    pc.evict_lru(al.num_pages)                   # 2 pages parked
    assert st.pages == 2 and not st.fits(1)
    b = al.acquire(3)
    pc.insert([100 + t for t in range(3 * PS)], b)
    al.release(b)
    pc.evict_lru(al.num_pages)
    # room for 2 of the 3 new victims: host LRU dropped the old leaf
    # chain entirely (leaf-first), the oldest new victim was trimmed
    assert st.pages == 2 == pc.host_pages
    assert pc.host_evictions >= 1
    _books_ok(al, st, pc)


def test_tier_invariant_below_host_all_host():
    """Eviction drains a chain bottom-up (an interior edge is
    evictable only once its subtree holds no HBM pages), so a host
    edge never sits above an HBM edge and the host LRU always finds a
    true leaf to drop."""
    al, st, pc = _tiered()
    ids = al.acquire(3)
    pc.insert(list(range(3 * PS)), ids)
    al.release(ids)

    def check(node, above_host):
        for e in node.children.values():
            if above_host:
                assert e.page is None
            check(e.child, above_host or e.page is None)

    # one page at a time: the leaf goes host first, then its parent,
    # then the root edge — the invariant holds at every partial state
    for want_host in (1, 2, 3):
        assert pc.evict_lru(1) == 1
        assert pc.host_pages == want_host
        check(pc._root, False)
        _books_ok(al, st, pc)
    assert pc.pinned_pages == 0 and al.free_pages == al.num_pages


def test_clear_drops_both_tiers():
    al, st, pc = _tiered()
    ids = al.acquire(3)
    pc.insert(list(range(2 * PS + 1)), ids)
    al.release(ids)
    pc.evict_lru(1)
    pc.clear()
    assert (pc.pinned_pages, pc.host_pages, st.pages) == (0, 0, 0)
    assert al.free_pages == al.num_pages


def test_churn_sweep_conserves_across_tiers():
    """The ISSUE 12 200-step fragmentation sweep extended with
    eviction-to-host and swap-back (ISSUE 18 satellite): interleaved
    admissions (tiered matching, positional assembly, resurrection),
    retires, backpressure evictions, and a small host budget forcing
    host-LRU drops — every conservation law checked at EVERY step."""
    total = 8
    al, st, pc = _tiered(total=total, budget_pages=4)
    held = {}
    rng = np.random.RandomState(7)
    protos = [list(range(40, 40 + 3 * PS)),
              list(range(80, 80 + 2 * PS))]
    uid = 0
    for step in range(200):
        r = rng.rand()
        if held and (r < 0.35 or al.free_pages == 0):
            al.release(held.pop(list(held)[rng.randint(len(held))]))
        elif r < 0.75:
            toks = protos[rng.randint(2)][:int(rng.randint(PS, 3 * PS))]
            toks = toks + [int(t) for t in rng.randint(0, 30, 3)]
            covered, mpages, host = pc.match_tiered(toks)
            n_cov = -(-covered // PS)
            mpages, host = mpages[:n_cov], [h for h in host
                                            if h[0] < n_cov]
            host_map = dict(host)
            shared = [mpages[j] for j in range(covered // PS)
                      if j not in host_map]
            need = -(-len(toks) // PS)
            priv = al.acquire(need - len(shared))
            if priv is None:
                pc.evict_lru(need - len(shared))
                continue
            for _, h in host:
                st.get(h)                        # slabs still there
            al.share(shared)
            q, row = list(priv), []
            for j in range(need):
                if j < covered // PS and j not in host_map:
                    row.append(mpages[j])
                else:
                    row.append(q.pop(0))
            pc.insert(toks, row)
            held[uid] = row
            uid += 1
        else:
            pc.evict_lru(int(rng.randint(1, 3)))
        _books_ok(al, st, pc, holders=held.values())
    for ids in held.values():
        al.release(ids)
    pc.evict_lru(al.num_pages)
    _books_ok(al, st, pc)
    assert al.free_pages == total
    assert pc.swapped_out > 0 and pc.host_evictions > 0


# --------------------------------------------------------------------------
# engine wiring
# --------------------------------------------------------------------------

def _engine(tp=None, **kw):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                           page_size=8, num_pages=16,
                           cache_dtype=jnp.float32, tp=tp, **kw)


def _tel():
    return ServeTelemetry(MetricsRegistry())


PREFIX = list((np.arange(24) * 7 + 3) % 64)       # 3 full pages


def test_engine_rejects_tier_on_dense_and_bad_values():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                        host_tier_bytes=1 << 20)
    with pytest.raises(ValueError):
        _engine(host_tier_bytes=-1)
    with pytest.raises(ValueError):
        _engine(host_tier_bytes=1 << 20, swap_batch_pages=0)
    eng = _engine()                               # default: tier off
    assert eng.host_tier_bytes == 0
    tel = _tel()
    sched = SlotScheduler(eng, telemetry=tel)
    assert sched.host_store is None


def test_swap_batch_dispatch_counts_and_page_host_bytes():
    eng = _engine(host_tier_bytes=1 << 20, swap_batch_pages=2)
    # page_host_bytes is the GLOBAL page footprint: 2 buffers x layers
    # x kv_heads x page_size x head_dim x itemsize
    assert eng.page_host_bytes() == 2 * 1 * 2 * 8 * 16 * 4
    cache = eng.init_cache()
    ids = list(range(5))                          # 5 pages, batch 2
    # the dispatch counters live on the GLOBAL registry: measure the
    # deltas so earlier swap traffic in the process cannot skew them
    out0 = int(eng._swap_out_dispatches.total())
    in0 = int(eng._swap_in_dispatches.total())
    k, v = eng.swap_out_pages(cache, ids)
    assert k.shape == (5, 1, 2, 8, 16)
    reg = eng._swap_out_dispatches
    assert int(reg.total()) - out0 == 3           # ceil(5/2) batches
    cache = eng.swap_in_pages(cache, ids, k, v)
    assert int(eng._swap_in_dispatches.total()) - in0 == 3


@pytest.mark.parametrize("tp", [None, 2])
def test_hit_after_eviction_swaps_in_instead_of_recompute(tp):
    """The tentpole end-to-end at tp=1 and tp=2: outputs after
    evict->swap-out->hit->swap-in are bitwise the cold run's, the hit
    is served by uploads (swap counters move, prefix_host_hits fires),
    and every cross-tier book balances after each wave."""
    eng = _engine(tp=tp, host_tier_bytes=1 << 20)
    tel = _tel()
    sched = SlotScheduler(eng, telemetry=tel)

    def books():
        al = sched.alloc
        assert al.live_pages + al.free_pages == al.num_pages
        assert al.weighted_live() == sched.prefix.pinned_pages
        assert sched.prefix.host_pages == sched.host_store.pages

    u0 = sched.submit(PREFIX + [9], max_new_tokens=4)
    ref = sched.run()[u0]
    books()
    freed = sched.prefix.evict_lru(eng.num_pages)
    assert freed == 4 and sched.prefix.host_pages == 3
    assert int(tel.swap_out_pages.total()) == 3
    books()
    u1 = sched.submit(PREFIX + [9], max_new_tokens=4)
    out = sched.run()[u1]
    assert out == ref
    assert int(tel.swap_in_pages.total()) == 3
    assert int(tel.prefix_host_hits.total()) == 1
    assert sched.prefix.host_pages == 0 == sched.host_store.pages
    books()
    # dispatch counters moved under the fixed-width batch contract
    assert int(eng._swap_in_dispatches.total()) >= 1
    assert int(eng._swap_out_dispatches.total()) >= 1


def test_boundary_subpage_match_on_host_edge():
    """A hit whose boundary falls INSIDE a host-resident page: the
    swapped-in copy is request-private (no COW needed), the columns
    past the boundary are masked by prefill_from — outputs match a
    cold scheduler bitwise."""
    eng = _engine(host_tier_bytes=1 << 20)
    long = list((np.arange(32) * 5 + 1) % 64)     # 4 full pages
    probe = long[:28] + [7]                       # boundary at 28

    cold = SlotScheduler(eng, telemetry=_tel(), prefix_cache=False)
    uc = cold.submit(probe, max_new_tokens=4)
    ref = cold.run()[uc]

    tel = _tel()
    sched = SlotScheduler(eng, telemetry=tel)
    sched.submit(long, max_new_tokens=2)
    sched.run()
    sched.prefix.evict_lru(eng.num_pages)
    assert sched.prefix.host_pages == 4
    u = sched.submit(probe, max_new_tokens=4)
    out = sched.run()[u]
    assert out == ref
    assert int(tel.swap_in_pages.total()) == 4    # 3 full + boundary
    assert int(tel.prefix_host_hits.total()) == 1


@pytest.mark.parametrize("tp", [None, 2])
def test_scheduler_churn_waves_conserve(tp):
    """Multi-wave churn through the real engine at both widths:
    admissions, eviction-to-host between waves, swap-back hits, host
    books replicated under tp — conservation after every wave."""
    eng = _engine(tp=tp, host_tier_bytes=1 << 20)
    tel = _tel()
    sched = SlotScheduler(eng, telemetry=tel)
    rng = np.random.RandomState(3)
    outs = {}
    for wave in range(4):
        for j in range(3):
            tail = [int(t) for t in rng.randint(0, 64, 2)]
            sched.submit(PREFIX + tail, max_new_tokens=2)
        outs.update(sched.run())
        al = sched.alloc
        assert al.live_pages + al.free_pages == al.num_pages
        assert al.weighted_live() == sched.prefix.pinned_pages
        assert sched.prefix.host_pages == sched.host_store.pages
        if wave % 2 == 0:
            sched.prefix.evict_lru(eng.num_pages)
            assert sched.prefix.host_pages == sched.host_store.pages
    assert len(outs) == 12
    assert int(tel.swap_in_pages.total()) > 0
    assert int(tel.swap_out_pages.total()) > 0
