"""Shared-prefix serving + SLO-aware scheduling (ISSUE 12 acceptance):

1. N concurrent requests extending one cached prefix hold ONE physical
   copy of the prefix's pages (+ per-request suffix pages) —
   conservation-checked in the allocator mid-flight;
2. sharing changes pages, never tokens: hit streams equal cold streams;
3. an exact-repeat prompt (full-cover hit) COWs its boundary page and
   reproduces the original stream bitwise;
4. retiring one of two prefix-sharing requests leaves the survivor's
   decode output bitwise unchanged (release, never free);
5. chunked prefill interleaves decode steps between chunks (bounded
   consecutive prefill chunks) with goodput conservation intact;
6. priority admission + per-tenant fairness order the queue.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


@pytest.fixture(scope="module")
def engine():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    # f32 cache: the bitwise assertions compare cached-prefix reads
    # against in-program recomputation
    return InferenceEngine("gpt", cfg, params, slots=3, max_seq=64,
                           page_size=8, num_pages=21,
                           cache_dtype=jnp.float32)


def _tel():
    return ServeTelemetry(MetricsRegistry())


PREFIX = list((np.arange(24) * 7 + 3) % 64)          # 3 full pages


def test_sharing_holds_one_prefix_copy_conservation(engine):
    """The capacity multiplier, machine-checked: with 3 concurrent
    requests over a 3-page prefix, the allocator holds the prefix ONCE
    (distinct live pages) while the refcount-weighted view counts every
    owner — and the books balance at every observation point."""
    tel = _tel()
    sched = SlotScheduler(engine, telemetry=tel)
    seed = sched.submit(PREFIX + [1], max_new_tokens=2)
    sched.run()

    c0, ppages = sched.prefix.match(PREFIX)
    assert c0 == 24 and len(ppages) == 3     # the cached prefix pages
    snaps = []
    orig = engine.decode

    def spy(*a, **kw):
        al = sched.alloc
        snaps.append((al.live_pages, al.weighted_live(),
                      al.shared_pages(), al.free_pages,
                      tuple(al.refcount(p) for p in ppages)))
        return orig(*a, **kw)

    engine.decode = spy
    try:
        uids = [sched.submit(PREFIX + [10 + i], max_new_tokens=2)
                for i in range(3)]
        out = sched.run()
    finally:
        engine.decode = orig
    assert sorted(out) == sorted(uids)
    assert int(tel.prefix_hits.total()) == 3
    # every snapshot balances: distinct live + free == pool
    for live, weighted, shared, free_p, _ in snaps:
        assert live + free_p == engine.num_pages
    # at the first decode all 3 hits are in flight: each prefix page is
    # held ONCE physically but by four owners (cache + 3 requests) —
    # cold, 3 requests would have pinned 3 distinct copies
    live, weighted, shared, _, rcs = snaps[0]
    assert rcs == (4, 4, 4)
    assert shared >= 3                       # the prefix's pages
    assert weighted - live >= 3 * 3          # >= 3 extra owners x 3 pages
    assert int(tel.prefix_hit_tokens.total()) == 3 * 24


def test_hit_streams_equal_cold_streams(engine):
    """Sharing is a memory-model change, not a math change."""
    prompts = [PREFIX + [10 + i] for i in range(3)]
    shared = SlotScheduler(engine, telemetry=_tel())
    shared.submit(PREFIX + [1], max_new_tokens=2)
    shared.run()                             # seed the cache
    us = [shared.submit(p, max_new_tokens=4) for p in prompts]
    out_s = shared.run()
    cold = SlotScheduler(engine, telemetry=_tel(), prefix_cache=False)
    uc = [cold.submit(p, max_new_tokens=4) for p in prompts]
    out_c = cold.run()
    assert [out_s[u] for u in us] == [out_c[u] for u in uc]


def test_exact_repeat_cow_reproduces_stream_bitwise(engine):
    """A fully-cached prompt shares every page, COWs the boundary page
    (its decode appends would otherwise write a page other owners still
    map), re-prefills ONLY the last token — and emits the exact stream
    the cold run emitted."""
    tel = _tel()
    sched = SlotScheduler(engine, telemetry=tel)
    u0 = sched.submit(PREFIX + [1, 2], max_new_tokens=4)
    out0 = sched.run()
    cows0 = int(tel.cow_copies.total())
    u1 = sched.submit(PREFIX + [1, 2], max_new_tokens=4)
    out1 = sched.run()
    assert out1[u1] == out0[u0]
    assert int(tel.cow_copies.total()) == cows0 + 1
    # the hit prefilled only the uncached tail: 26-token prompt,
    # 25 tokens covered
    assert int(tel.prefix_hit_tokens.total()) >= 25


def test_retire_releases_survivor_decode_bitwise_unchanged(engine):
    """ISSUE 12 satellite: retiring one of two prefix-sharing requests
    must only RELEASE its references.  A third request admitted into
    the freed pages afterwards must not perturb the survivor — its
    remaining decode output is bitwise identical to an undisturbed
    run."""
    def run(with_churn):
        sched = SlotScheduler(engine, telemetry=_tel())
        sched.submit(PREFIX + [1], max_new_tokens=2)
        sched.run()                          # seed
        survivor = sched.submit(PREFIX + [2], max_new_tokens=10)
        if with_churn:
            # sharer retires after 2 tokens; its release must not free
            # the shared prefix pages under the survivor
            sched.submit(PREFIX + [3], max_new_tokens=2)
            # filler (distinct prompt) reuses whatever pages actually
            # freed — if a shared page leaked into the free list, the
            # filler's prefill overwrites the survivor's prefix
            sched.submit(list((np.arange(20) * 5 + 1) % 64),
                         max_new_tokens=4)
        out = sched.run()
        return out[survivor]

    assert run(with_churn=True) == run(with_churn=False)


def test_chunked_prefill_interleaves_decode_steps(engine):
    """SLO path (ISSUE 12 satellite): a long prompt admitted behind a
    decoding stream prefills in chunks with decode steps interleaved —
    max consecutive prefill dispatches stays at max_chunks_per_pass —
    and the lifecycle conservation law survives chunked admission."""
    tel = _tel()
    sched = SlotScheduler(engine, telemetry=tel, prefix_cache=False,
                          prefill_chunk=16, max_chunks_per_pass=1)
    trace = []
    orig_p, orig_d = engine.prefill, engine.decode

    def spy_p(*a, **kw):
        trace.append("P")
        return orig_p(*a, **kw)

    def spy_d(*a, **kw):
        trace.append("D")
        return orig_d(*a, **kw)

    engine.prefill, engine.decode = spy_p, spy_d
    try:
        u_short = sched.submit([5, 6, 7], max_new_tokens=8)
        u_long = sched.submit(list((np.arange(40) + 2) % 64),
                              max_new_tokens=2)
        out = sched.run()
    finally:
        engine.prefill, engine.decode = orig_p, orig_d
    # every request completed, reasons recorded, books balanced
    assert len(out[u_short]) == 8 and len(out[u_long]) == 2
    assert sched.finish_reasons[u_short] == "length"
    assert sched.finish_reasons[u_long] == "length"
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    assert c == {"submitted": 2, "finished": 2, "rejected": 0,
                 "active": 0}
    # the 40-token prompt split into ceil(40/16) = 3 chunks
    assert int(tel.prefill_chunks.total()) == 3
    # bounded interleaving: once decoding starts, never two prefill
    # dispatches back to back
    first_d = trace.index("D")
    run_len, worst = 0, 0
    for ev in trace[first_d:]:
        run_len = run_len + 1 if ev == "P" else 0
        worst = max(worst, run_len)
    assert worst <= 1, trace


def test_chunked_prefill_streams_match_monolithic(engine):
    prompts = [list((np.arange(n) + 3) % 64) for n in (40, 25, 7)]
    mono = SlotScheduler(engine, telemetry=_tel(), prefix_cache=False)
    um = [mono.submit(p, max_new_tokens=4) for p in prompts]
    out_m = mono.run()
    chunked = SlotScheduler(engine, telemetry=_tel(),
                            prefix_cache=False, prefill_chunk=16)
    uc = [chunked.submit(p, max_new_tokens=4) for p in prompts]
    out_c = chunked.run()
    assert [out_m[u] for u in um] == [out_c[u] for u in uc]


def test_priority_admission_and_tenant_fairness(engine):
    """Highest effective priority first; ties round-robin across
    tenants by least-recent admission; FIFO last.  finish order on a
    1-slot drain IS admission order (serialized)."""
    cfg = engine.cfg
    model_params = engine.params
    one = InferenceEngine("gpt", cfg, model_params, slots=1, max_seq=64,
                          page_size=8, num_pages=8)
    tel = _tel()
    sched = SlotScheduler(one, telemetry=tel, prefix_cache=False,
                          tenant_priority={"vip": 10})
    ua1 = sched.submit([1, 2], max_new_tokens=1, tenant="a")
    ua2 = sched.submit([2, 3], max_new_tokens=1, tenant="a")
    ub1 = sched.submit([3, 4], max_new_tokens=1, tenant="b")
    uv = sched.submit([4, 5], max_new_tokens=1, tenant="vip")
    out = sched.run()
    order = list(out)                        # insertion = finish order
    # vip's override wins outright; then a (FIFO), then b (fairness:
    # a was just admitted), then a again
    assert order == [uv, ua1, ub1, ua2]
    assert tel.tenant_admitted.value(tenant="vip") == 1
    assert tel.tenant_admitted.value(tenant="a") == 2
    # rejected submissions are tenant-attributed too
    with pytest.raises(ValueError):
        sched.submit([], tenant="a")
    assert tel.tenant_rejected.value(tenant="a") == 1


def test_llama_gqa_hit_streams_equal_cold_streams():
    """The grouped-query path: suffix rows score the pre-broadcast
    per-kv-head window exactly as the cold flash path scores its
    broadcast — streams match across the memory models."""
    from apex_tpu.transformer.testing import (LlamaConfig,
                                              llama_model_provider)
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=2,
                      num_attention_heads=4, num_kv_heads=2,
                      max_seq_length=64)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    eng = InferenceEngine("llama", cfg, params, slots=3, max_seq=64,
                          page_size=8, num_pages=21,
                          cache_dtype=jnp.float32)
    prefix = list((np.arange(24) * 11 + 5) % 64)
    prompts = [prefix + [10 + i] for i in range(3)]
    tel = _tel()
    shared = SlotScheduler(eng, telemetry=tel)
    shared.submit(prefix + [1], max_new_tokens=2)
    shared.run()
    us = [shared.submit(p, max_new_tokens=5) for p in prompts]
    out_s = shared.run()
    cold = SlotScheduler(eng, telemetry=_tel(), prefix_cache=False)
    uc = [cold.submit(p, max_new_tokens=5) for p in prompts]
    out_c = cold.run()
    assert [out_s[u] for u in us] == [out_c[u] for u in uc]
    assert int(tel.prefix_hits.total()) == 3


def test_prefix_cache_eviction_under_backpressure(engine):
    """A pool mostly pinned by the prefix cache still admits new cold
    requests: LRU leaves are evicted to free pages instead of
    deadlocking on backpressure."""
    cfg = engine.cfg
    small = InferenceEngine("gpt", cfg, engine.params, slots=2,
                            max_seq=64, page_size=8, num_pages=6)
    tel = _tel()
    sched = SlotScheduler(small, telemetry=tel)
    sched.submit(list((np.arange(24) + 9) % 64), max_new_tokens=2)
    sched.run()                              # cache pins ~4 pages
    assert sched.prefix.pinned_pages >= 3
    # a distinct prompt needing most of the pool: must evict, not hang
    u = sched.submit(list((np.arange(30) * 3 + 1) % 64),
                     max_new_tokens=4)
    out = sched.run()
    assert len(out[u]) == 4
    assert int(tel.prefix_evictions.total()) >= 1
    al = sched.alloc
    assert al.live_pages + al.free_pages == small.num_pages
