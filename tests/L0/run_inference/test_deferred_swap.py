"""Deferred host-tier eviction drains (ISSUE 19 satellite).

Eviction-side device->host page copies no longer stall the admission
path: ``swap_out_pages(defer=True)`` dispatches the batched gathers
into fresh output buffers and hands back a :class:`PendingSwapOut`;
the blocking ``device_get``\\ s run at the next wave boundary (the
scheduler's ``drain_pending_swaps``) or lazily on the first host-tier
hit against one of the parked handles — whichever comes first.

Pinned here:

1. ``HostPageStore.put_deferred`` books bytes EAGERLY and stays as
   strict as an eager ``put`` (over-budget raises, nothing parked).
2. ``get``/``pop`` on a deferred handle force resolution exactly once
   (the placeholder is replaced by the materialized slabs).
3. ``PendingSwapOut.resolve`` is idempotent: one fetch, the device
   batches are freed, every later call returns the cached slabs.
4. ``swap_out_pages(defer=True)`` returns byte-identical slabs to the
   eager path — deferral changes WHEN the copy lands, never WHAT.
5. The scheduler drains every pending batch at the wave boundary and
   the tier books (allocator conservation, host mirror) balance
   through an evict -> hit round trip that rides the deferred path.
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.inference.engine import PendingSwapOut
from apex_tpu.inference.kv_cache import HostPageStore, _DeferredSlab
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

PREFIX = [int(t) for t in (np.arange(16) * 5 + 2) % 64]


class _FakePending:
    """Stands in for PendingSwapOut in the pure-store tests: resolves
    to deterministic per-row slabs and counts forced resolutions."""

    def __init__(self, n, row_shape=(1, 2, 8, 4)):
        self.calls = 0
        self._n, self._row = n, row_shape
        self._cached = None

    def resolve(self):
        self.calls += 1
        if self._cached is None:
            size = int(np.prod(self._row))
            k = np.arange(self._n * size, dtype=np.float32).reshape(
                (self._n,) + self._row)
            self._cached = (k, k + 1000.0)
        return self._cached


def _store(pages=4, page_bytes=256):
    return HostPageStore(capacity_bytes=pages * page_bytes,
                         page_bytes=page_bytes)


def test_put_deferred_books_bytes_eagerly_and_strictly():
    store = _store(pages=4)
    pending = _FakePending(3)
    handles = store.put_deferred(3, pending)
    assert len(handles) == 3
    # bytes booked the moment the placeholders park — the drain WILL
    # land, so the budget must not discover it late
    assert store.pages == 3
    assert store.bytes_used == 3 * store.page_bytes
    assert pending.calls == 0
    # strict like put(): one more page fits, two do not — and the
    # over-budget attempt parks NOTHING (no partial booking)
    with pytest.raises(ValueError):
        store.put_deferred(2, _FakePending(2))
    assert store.pages == 3
    store.put_deferred(1, _FakePending(1))
    assert not store.fits(1)


def test_get_materializes_lazily_exactly_once():
    store = _store()
    pending = _FakePending(2)
    h0, h1 = store.put_deferred(2, pending)
    assert pending.calls == 0
    k, v = store.get(h1)
    assert pending.calls == 1
    # index selects this page's row out of the stacked batch
    want_k, want_v = pending.resolve()
    np.testing.assert_array_equal(k, want_k[1])
    np.testing.assert_array_equal(v, want_v[1])
    # the placeholder was REPLACED by the materialized slabs: a second
    # get serves the copy without touching the pending drain
    assert not isinstance(store._slabs[h1], _DeferredSlab)
    calls_before = pending.calls
    k2, _ = store.get(h1)
    assert pending.calls == calls_before
    np.testing.assert_array_equal(k2, k)


def test_pop_materializes_and_releases_bytes():
    store = _store()
    pending = _FakePending(1)
    (h,) = store.put_deferred(1, pending)
    k, v = store.pop(h)
    assert pending.calls >= 1
    assert k.shape[0] == 1 or k.ndim >= 1
    assert store.pages == 0
    assert store.bytes_used == 0
    assert store.pop(h) is None


def test_pending_swap_out_resolve_is_idempotent_and_frees_batches():
    k1 = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    v1 = k1 + 100.0
    k2 = k1 + 200.0
    p = PendingSwapOut([(k1, v1, 2), (k2, v1, 3)])
    k, v = p.resolve()
    # valid-row trim then concat: 2 + 3 rows
    assert k.shape == (5, 4) and v.shape == (5, 4)
    np.testing.assert_array_equal(k[:2], np.asarray(k1)[:2])
    np.testing.assert_array_equal(k[2:], np.asarray(k2)[:3])
    # idempotent: the device batches are freed, the fetched slabs are
    # cached — every later resolve returns the SAME objects
    assert p._batches is None
    assert p.resolve() is p.resolve()
    assert p.resolve()[0] is k


def _sched():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                          page_size=8, num_pages=16,
                          host_tier_bytes=1 << 20)
    return SlotScheduler(eng,
                         telemetry=ServeTelemetry(MetricsRegistry()))


def test_deferred_swap_out_matches_eager_bit_for_bit():
    sched = _sched()
    sched.submit(PREFIX + [1, 2], max_new_tokens=3)
    sched.run()
    ids = [0, 1, 2]
    k_e, v_e = sched.engine.swap_out_pages(sched.cache, ids)
    pending = sched.engine.swap_out_pages(sched.cache, ids, defer=True)
    assert isinstance(pending, PendingSwapOut)
    k_d, v_d = pending.resolve()
    np.testing.assert_array_equal(k_d, k_e)
    np.testing.assert_array_equal(v_d, v_e)


def test_scheduler_drains_pending_swaps_at_wave_boundary():
    sched = _sched()
    eng = sched.engine
    sched.submit(PREFIX + [1, 2], max_new_tokens=3)
    sched.run()
    # run() ends on a drained boundary
    assert sched._pending_swaps == []

    # evict to host: the offload dispatches but does NOT fetch — the
    # store holds deferred placeholders, the scheduler a pending batch
    assert sched.prefix.evict_lru(eng.num_pages) > 0
    assert len(sched._pending_swaps) >= 1
    assert sched.host_store.pages > 0
    assert any(isinstance(s, _DeferredSlab)
               for s in sched.host_store._slabs.values())

    # wave boundary forces the stragglers, exactly once
    forced = sched.drain_pending_swaps()
    assert forced >= 1
    assert sched._pending_swaps == []
    assert sched.drain_pending_swaps() == 0

    # a hit against the swapped-out prefix rides the deferred slabs
    # through swap-in and the books still balance
    sched.submit(PREFIX + [9], max_new_tokens=3)
    out = sched.run()
    assert all(len(v) == 3 for v in out.values())
    assert sched._pending_swaps == []
    tel = sched.telemetry
    assert int(tel.swap_out_pages.total()) >= 2
    assert int(tel.swap_in_pages.total()) >= 2
    al = sched.alloc
    assert al.live_pages + al.free_pages == al.num_pages
    assert sched.prefix.host_pages == sched.host_store.pages


def test_hit_before_drain_resolves_lazily_and_boundary_catches_rest():
    sched = _sched()
    eng = sched.engine
    sched.submit(PREFIX + [1, 2], max_new_tokens=3)
    sched.run()
    assert sched.prefix.evict_lru(eng.num_pages) > 0
    assert len(sched._pending_swaps) >= 1
    # the hit wave swaps the prefix back in BEFORE any explicit drain:
    # the host store materializes the placeholders lazily, and the
    # wave boundary clears the (already-resolved) pending list
    sched.submit(PREFIX + [9], max_new_tokens=3)
    out = sched.run()
    assert all(len(v) == 3 for v in out.values())
    assert sched._pending_swaps == []
    assert int(sched.telemetry.prefix_host_hits.total()) >= 1
    al = sched.alloc
    assert al.live_pages + al.free_pages == al.num_pages
