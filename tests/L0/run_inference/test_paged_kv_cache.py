"""Paged KV pool semantics (ISSUE 6): page-table-threaded donated
mutations, trash-page overflow containment, truncation surfacing, and
the host-side page allocator's no-leak bookkeeping."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import kv_cache

LAYERS, KVH, PS, D, SLOTS, MPPS, PAGES = 2, 2, 4, 8, 3, 4, 6


def _cache(dtype=jnp.float32, **kw):
    return kv_cache.init_paged_cache(PAGES, LAYERS, KVH, PS, D,
                                     slots=SLOTS, max_pages_per_slot=MPPS,
                                     dtype=dtype, **kw)


def _rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape),
                       jnp.float32)


def _row(ids):
    return kv_cache.page_row(ids, MPPS, PAGES)


def test_init_geometry_and_trash_page():
    c = _cache(jnp.bfloat16)
    # pool carries PAGES allocatable pages + 1 trash page
    assert c.k.shape == (PAGES + 1, LAYERS, KVH, PS, D)
    assert c.k.dtype == jnp.bfloat16 and c.v.dtype == jnp.bfloat16
    assert (c.pages, c.null_page, c.alloc_pages) == (PAGES + 1, PAGES,
                                                     PAGES)
    assert (c.slots, c.max_pages_per_slot, c.page_size) == (SLOTS, MPPS,
                                                            PS)
    assert c.max_seq == MPPS * PS
    # empty: every table entry parks on the trash page, nothing owned
    assert np.all(np.asarray(c.page_table) == PAGES)
    assert np.all(np.asarray(c.lengths) == 0)
    assert np.all(np.asarray(c.capacity) == 0)


def test_insert_pages_places_slabs_and_derives_capacity():
    c = _cache()
    k = _rand((LAYERS, KVH, 2 * PS, D), 1)
    v = _rand((LAYERS, KVH, 2 * PS, D), 2)
    ids = [4, 1]                      # deliberately non-contiguous
    c = kv_cache.insert_pages(c, 1, k, v, 5, _row(ids))
    # slab pages landed at the assigned physical pages, in order
    np.testing.assert_array_equal(np.asarray(c.k[4]),
                                  np.asarray(k[:, :, :PS]))
    np.testing.assert_array_equal(np.asarray(c.k[1]),
                                  np.asarray(k[:, :, PS:]))
    np.testing.assert_array_equal(np.asarray(c.v[4]),
                                  np.asarray(v[:, :, :PS]))
    # table row = assigned pages padded with the trash page
    assert np.asarray(c.page_table[1]).tolist() == [4, 1, PAGES, PAGES]
    # capacity derived in-program from the owned-page count
    assert np.asarray(c.lengths).tolist() == [0, 5, 0]
    assert np.asarray(c.capacity).tolist() == [0, 2 * PS, 0]
    # other slots' rows untouched
    assert np.all(np.asarray(c.page_table[0]) == PAGES)


def test_bucket_overhang_spills_into_trash_page():
    """A prefill bucket larger than the reservation writes its dead
    padding pages into the trash page, not into anyone's data."""
    c = _cache()
    victim = _rand((LAYERS, KVH, PS, D), 3)
    c = kv_cache.insert_pages(c, 0, victim, victim, PS, _row([2]))
    # slot 1 inserts a 3-page slab but owns only 1 page: pages 1-2 of
    # the slab overhang into the trash page
    k = _rand((LAYERS, KVH, 3 * PS, D), 4)
    c = kv_cache.insert_pages(c, 1, k, k, 3, _row([5]))
    np.testing.assert_array_equal(np.asarray(c.k[2]), np.asarray(victim))
    np.testing.assert_array_equal(np.asarray(c.k[5]),
                                  np.asarray(k[:, :, :PS]))
    assert np.asarray(c.capacity).tolist() == [PS, PS, 0]


def test_append_crosses_page_boundary():
    c = _cache()
    k = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 0, k, k, PS - 1, _row([0, 3]))
    tok1 = _rand((SLOTS, KVH, D), 5)
    tok2 = _rand((SLOTS, KVH, D), 6)
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, tok1, tok1)
    c, _ = kv_cache.advance(c, jnp.asarray([True, False, False]))
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, tok2, tok2)
    c, _ = kv_cache.advance(c, jnp.asarray([True, False, False]))
    # token 1 filled the last row of page 0; token 2 opened page 3
    np.testing.assert_array_equal(
        np.asarray(c.k[0, :, :, PS - 1]),
        np.broadcast_to(np.asarray(tok1[0]), (LAYERS, KVH, D)))
    np.testing.assert_array_equal(
        np.asarray(c.k[3, :, :, 0]),
        np.broadcast_to(np.asarray(tok2[0]), (LAYERS, KVH, D)))
    assert np.asarray(c.lengths)[0] == PS + 1


def test_advance_truncates_at_capacity_and_protects_pages():
    c = _cache()
    k = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 0, k, k, PS - 1, _row([2]))  # cap PS
    tok = _rand((SLOTS, KVH, D), 7)
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, tok, tok)
    c, trunc = kv_cache.advance(c, jnp.asarray([True, False, False]))
    assert np.asarray(trunc).tolist() == [False, False, False]
    assert np.asarray(c.lengths)[0] == PS
    # at capacity: the append clamps into the trash page, advance
    # reports truncation, the owned page keeps its data
    page2 = np.asarray(c.k[2]).copy()
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, tok * 9, tok * 9)
    c, trunc = kv_cache.advance(c, jnp.asarray([True, False, False]))
    assert np.asarray(trunc).tolist() == [True, False, False]
    assert np.asarray(c.lengths)[0] == PS            # clamped
    np.testing.assert_array_equal(np.asarray(c.k[2]), page2)


def test_evict_zeroes_metadata_and_reparks_page_row():
    c = _cache()
    k = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 1, k, k, 3, _row([0]))
    c = kv_cache.evict(c, 1)
    assert np.asarray(c.lengths).tolist() == [0, 0, 0]
    assert np.asarray(c.capacity).tolist() == [0, 0, 0]
    # the row re-parks on the trash page so the idle slot's future
    # appends cannot chase the freed page into its next owner
    assert np.all(np.asarray(c.page_table[1]) == c.null_page)
    # data untouched (masked; the allocator reclaims page 0 host-side)
    np.testing.assert_array_equal(np.asarray(c.k[0]), np.asarray(k))


def test_retired_slot_append_cannot_corrupt_reassigned_page():
    """Regression (review finding): slot 0 is retired and its page is
    reassigned to slot 1; slot 0's still-running masked decode appends
    must land in the trash page, not in slot 1's new data."""
    c = _cache()
    a = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 0, a, a, 2, _row([3]))
    c = kv_cache.evict(c, 0)                 # retire; page 3 reclaimed
    b = _rand((LAYERS, KVH, PS, D), 2)
    c = kv_cache.insert_pages(c, 1, b, b, 3, _row([3]))  # reassigned
    tok = jnp.full((SLOTS, KVH, D), 7.0)
    for layer in range(LAYERS):
        c = kv_cache.append_layer(c, layer, tok, tok)
    c, _ = kv_cache.advance(c, jnp.asarray([True, True, False]))
    got = np.asarray(c.k[3])
    want = np.asarray(b).copy()
    want[:, :, 3] = 7.0                      # slot 1's own append only
    np.testing.assert_array_equal(got, want)


def test_advance_does_not_flag_empty_active_slots_truncated():
    """Regression (review finding): an active-but-never-admitted paged
    slot (capacity 0) is empty, not a truncated stream."""
    c = _cache()
    k = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 0, k, k, 1, _row([0]))
    c, trunc = kv_cache.advance(c, jnp.ones((SLOTS,), bool))
    assert np.asarray(trunc).tolist() == [False, False, False]
    assert np.asarray(c.lengths).tolist() == [2, 0, 0]


def test_insert_validates():
    c = _cache()
    good = _rand((LAYERS, KVH, PS, D))
    with pytest.raises(ValueError, match="prefill k/v"):
        kv_cache.insert_pages(c, 0, _rand((LAYERS, KVH + 1, PS, D)),
                              _rand((LAYERS, KVH + 1, PS, D)), 3,
                              _row([0]))
    with pytest.raises(ValueError, match="multiple of page_size"):
        kv_cache.insert_pages(c, 0, _rand((LAYERS, KVH, PS + 1, D)),
                              _rand((LAYERS, KVH, PS + 1, D)), 3,
                              _row([0]))
    with pytest.raises(ValueError, match="page row"):
        kv_cache.insert_pages(c, 0, good, good, 3,
                              np.zeros((MPPS + 1,), np.int32))
    with pytest.raises(ValueError, match="exceed max_pages_per_slot"):
        kv_cache.page_row(list(range(MPPS + 1)), MPPS, PAGES)


def test_updates_are_donation_safe():
    """insert+append+advance jit with the pool donated — one
    allocation for the engine's lifetime, like the dense cache."""

    def step(c, slab, tok, row):
        c = kv_cache.insert_pages(c, 0, slab, slab, 3, row)
        for layer in range(LAYERS):
            c = kv_cache.append_layer(c, layer, tok, tok)
        c, _ = kv_cache.advance(c, jnp.ones((SLOTS,), bool))
        return c

    c = _cache()
    kbuf, tbuf = c.k, c.page_table
    slab = _rand((LAYERS, KVH, PS, D), 1)
    tok = _rand((SLOTS, KVH, D), 2)
    c2 = jax.jit(step, donate_argnums=(0,))(c, slab, tok,
                                            jnp.asarray(_row([0, 1])))
    jax.block_until_ready(c2)
    assert kbuf.is_deleted() and tbuf.is_deleted()
    # slots 1/2 own no pages (capacity 0): advance holds them at 0 —
    # un-admitted slots can't drift, unlike the dense cache's clamp
    assert np.asarray(c2.lengths).tolist() == [4, 0, 0]


def test_pool_is_scan_carryable():
    def body(c, tok):
        for layer in range(LAYERS):
            c = kv_cache.append_layer(c, layer, tok, tok)
        c, _ = kv_cache.advance(c, jnp.ones((SLOTS,), bool))
        return c, c.lengths

    c = _cache()
    slab = _rand((LAYERS, KVH, PS, D), 1)
    c = kv_cache.insert_pages(c, 0, slab, slab, 0, _row([0, 1]))
    c = kv_cache.insert_pages(c, 1, slab, slab, 0, _row([2]))
    c = kv_cache.insert_pages(c, 2, slab, slab, 0, _row([3]))
    toks = _rand((4, SLOTS, KVH, D), 7)
    c, hist = jax.lax.scan(body, c, toks)
    assert np.asarray(c.lengths).tolist() == [4, 4, 4]
    assert hist.shape == (4, SLOTS)


# --------------------------------------------------------------------------
# token-granular suffix insert + copy-on-write (ISSUE 12)
# --------------------------------------------------------------------------

def test_insert_tokens_cold_matches_slab_insert():
    """start=0 insert_tokens places exactly what insert_pages places —
    the cold path is the slab path at token granularity."""
    k = _rand((LAYERS, KVH, 2 * PS, D), 1)
    v = _rand((LAYERS, KVH, 2 * PS, D), 2)
    a = kv_cache.insert_pages(_cache(), 1, k, v, 5, _row([4, 1]))
    b = kv_cache.insert_tokens(_cache(), 1, k, v, 5, _row([4, 1]), 0)
    np.testing.assert_array_equal(np.asarray(a.k), np.asarray(b.k))
    np.testing.assert_array_equal(np.asarray(a.v), np.asarray(b.v))
    np.testing.assert_array_equal(np.asarray(a.page_table),
                                  np.asarray(b.page_table))
    assert np.asarray(b.lengths).tolist() == [0, 5, 0]
    assert np.asarray(b.capacity).tolist() == [0, 2 * PS, 0]


def test_insert_tokens_mid_page_preserves_earlier_rows():
    """An unaligned suffix insert (a prefix-cache hit resuming mid-page
    after its boundary COW) writes rows [start % PS, ...) of the
    boundary page and leaves the copied prefix rows below untouched."""
    c = _cache()
    base = _rand((LAYERS, KVH, PS, D), 3)
    c = kv_cache.insert_pages(c, 0, base, base, PS, _row([2]))
    before = np.asarray(c.k[2]).copy()
    # resume at start = PS - 2: the slab's first rows land at offsets
    # PS-2, PS-1 of page 2, then roll into page 5
    slab = _rand((LAYERS, KVH, PS, D), 4)
    start = PS - 2
    c = kv_cache.insert_tokens(c, 0, slab, slab, start + PS,
                               _row([2, 5]), start)
    got = np.asarray(c.k[2])
    np.testing.assert_array_equal(got[:, :, :PS - 2],
                                  before[:, :, :PS - 2])   # kept
    np.testing.assert_array_equal(got[:, :, PS - 2:],
                                  np.asarray(slab[:, :, :2]))
    np.testing.assert_array_equal(np.asarray(c.k[5])[:, :, :PS - 2],
                                  np.asarray(slab[:, :, 2:PS]))
    assert np.asarray(c.lengths)[0] == start + PS
    assert np.asarray(c.capacity)[0] == 2 * PS


def test_insert_tokens_overhang_spills_into_trash_page():
    """Bucket positions beyond the reservation clamp into the trash
    page, exactly like the slab insert's overhang."""
    c = _cache()
    victim = _rand((LAYERS, KVH, PS, D), 5)
    c = kv_cache.insert_pages(c, 0, victim, victim, PS, _row([2]))
    slab = _rand((LAYERS, KVH, 3 * PS, D), 6)
    c = kv_cache.insert_tokens(c, 1, slab, slab, 3, _row([5]), 0)
    np.testing.assert_array_equal(np.asarray(c.k[2]), np.asarray(victim))
    np.testing.assert_array_equal(np.asarray(c.k[5]),
                                  np.asarray(slab[:, :, :PS]))
    assert np.asarray(c.capacity).tolist() == [PS, PS, 0]


def test_insert_tokens_full_window_overhang_is_dropped_not_clamped():
    """Regression (review finding): when the slab overhangs past the
    END of the virtual window (a prompt filling the whole per-slot
    window, e.g. an exact-repeat hit at max_seq), the overhang rows are
    DROPPED — clamping them onto the last owned position would clobber
    the real last token's KV with padding garbage."""
    c = _cache()
    base = _rand((LAYERS, KVH, MPPS * PS, D), 9)
    full_row = _row([0, 1, 2, 3])
    c = kv_cache.insert_pages(c, 0, base, base, MPPS * PS, full_row)
    # re-insert the LAST position only, with a bucket overhanging the
    # window end: positions MPPS*PS .. beyond must vanish
    slab = _rand((LAYERS, KVH, PS, D), 10)
    c = kv_cache.insert_tokens(c, 0, slab, slab, MPPS * PS, full_row,
                               MPPS * PS - 1)
    got = np.asarray(c.k[3])
    np.testing.assert_array_equal(got[:, :, PS - 1],
                                  np.asarray(slab)[:, :, 0])  # real row
    np.testing.assert_array_equal(got[:, :, :PS - 1],
                                  np.asarray(base)[:, :, -PS:-1])
    # the other owned pages are untouched by the dropped overhang
    np.testing.assert_array_equal(np.asarray(c.k[0]),
                                  np.asarray(base)[:, :, :PS])


def test_cow_page_copies_rows_and_isolates_writers():
    """cow_page duplicates a physical page; the copy's owner can then
    be written without perturbing the original — the write barrier
    behind shared-boundary-page admission."""
    c = _cache()
    base = _rand((LAYERS, KVH, PS, D), 7)
    c = kv_cache.insert_pages(c, 0, base, base, PS - 1, _row([3]))
    c = kv_cache.cow_page(c, 3, 0)
    np.testing.assert_array_equal(np.asarray(c.k[0]), np.asarray(c.k[3]))
    np.testing.assert_array_equal(np.asarray(c.v[0]), np.asarray(c.v[3]))
    # slot 1 maps the COPY and overwrites its tail; page 3 is untouched
    slab = _rand((LAYERS, KVH, PS, D), 8)
    c = kv_cache.insert_tokens(c, 1, slab, slab, PS, _row([0]), PS - 1)
    np.testing.assert_array_equal(np.asarray(c.k[3]), np.asarray(base))
    got = np.asarray(c.k[0])
    np.testing.assert_array_equal(got[:, :, :PS - 1],
                                  np.asarray(base)[:, :, :PS - 1])
    np.testing.assert_array_equal(got[:, :, PS - 1],
                                  np.asarray(slab)[:, :, 0])


def test_cow_page_is_donation_safe():
    def step(c):
        return kv_cache.cow_page(c, jnp.int32(1), jnp.int32(0))

    c = _cache()
    kbuf = c.k
    c2 = jax.jit(step, donate_argnums=(0,))(c)
    jax.block_until_ready(c2)
    assert kbuf.is_deleted()


# --------------------------------------------------------------------------
# host-side page allocator
# --------------------------------------------------------------------------

def test_allocator_acquire_release_reuse():
    al = kv_cache.PageAllocator(4, PS, MPPS)
    a = al.acquire(2)
    b = al.acquire(2)
    assert sorted(a + b) == [0, 1, 2, 3]
    assert al.acquire(1) is None          # exhausted -> backpressure
    al.release(a)
    c = al.acquire(2)
    assert sorted(c) == sorted(a)         # released pages come back
    assert al.free_pages == 0


def test_allocator_share_refcounts_and_last_owner_frees():
    """The ISSUE 12 sharing contract: share() adds one owner per call,
    release() drops one, and the page reaches the free list exactly
    when its LAST owner lets go — N sharers of one page pin ONE page."""
    al = kv_cache.PageAllocator(4, PS, MPPS)
    [pid] = al.acquire(1)
    al.share([pid])                       # second owner
    al.share([pid])                       # third owner
    assert al.refcount(pid) == 3
    assert (al.live_pages, al.free_pages) == (1, 3)   # ONE page pinned
    assert al.weighted_live() == 3        # ...by three owners
    assert al.shared_pages() == 1
    al.release([pid])
    al.release([pid])
    assert al.refcount(pid) == 1          # survivors keep it alive
    assert al.free_pages == 3
    al.release([pid])                     # last owner
    assert al.refcount(pid) == 0
    assert al.free_pages == 4
    with pytest.raises(ValueError, match="not outstanding"):
        al.share([pid])                   # sharing a freed page raises


def test_allocator_interleaved_retire_admit_leaks_nothing():
    """200-step fragmentation sweep WITH prefix sharing and COW
    (ISSUE 12 satellite): interleaved acquire/share/release of uneven
    requests — where a 'hit' takes extra references on a random live
    holder's leading pages and a 'COW' acquires a private copy page —
    returns the pool to fully-free.  At every step: no page is issued
    twice concurrently, distinct live + free == total (conservation),
    and the refcount-weighted live count equals the sum of every
    holder's page list."""
    total = 8
    al = kv_cache.PageAllocator(total, PS, MPPS)
    held = {}                              # uid -> list of page refs
    rng = np.random.RandomState(0)
    uid = 0
    for _ in range(200):
        r = rng.rand()
        if held and (r < 0.4 or al.free_pages == 0):
            k = list(held)[rng.randint(len(held))]
            al.release(held.pop(k))        # retire: release EVERY ref
        elif held and r < 0.6:
            # prefix hit: share a random holder's leading pages, then
            # acquire a private tail (suffix + COW boundary copy)
            src = held[list(held)[rng.randint(len(held))]]
            n_share = int(rng.randint(1, len(src) + 1))
            shared = src[:n_share]
            priv = al.acquire(int(rng.randint(1, 3)))
            if priv is not None:
                al.share(shared)
                held[uid] = list(shared) + priv
                uid += 1
        else:
            got = al.acquire(int(rng.randint(1, 4)))
            if got is not None:
                held[uid] = got
                uid += 1
        for ids in held.values():          # no double issue WITHIN one
            assert len(ids) == len(set(ids))
        live = {p for ids in held.values() for p in ids}
        assert len(live) == al.live_pages
        assert al.live_pages + al.free_pages == total   # conservation
        weighted = sum(len(ids) for ids in held.values())
        assert al.weighted_live() == weighted
    for ids in held.values():
        al.release(ids)
    assert al.free_pages == total
    assert al.live_pages == 0 and al.weighted_live() == 0


def test_allocator_eviction_returns_all_pages_and_rejects_double_release():
    al = kv_cache.PageAllocator(6, PS, MPPS)
    ids = al.acquire(3)
    al.release(ids)                       # retire returns EVERY page
    assert al.free_pages == 6
    with pytest.raises(ValueError, match="not outstanding"):
        al.release(ids)                   # double release, loudly
    with pytest.raises(ValueError, match="not outstanding"):
        al.release([99])                  # foreign page likewise


def test_allocator_pages_needed_rounds_and_clamps():
    al = kv_cache.PageAllocator(8, 4, 3)
    assert al.pages_needed(1) == 1
    assert al.pages_needed(4) == 1
    assert al.pages_needed(5) == 2
    assert al.pages_needed(400) == 3      # clamped to the table width
