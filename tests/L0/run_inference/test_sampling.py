"""Sampling: greedy/temperature/top-k semantics + key discipline."""
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.inference.sampling import (
    SamplingConfig,
    greedy,
    sample_token,
)


def _logits(seed=0, rows=4, vocab=32):
    return jnp.asarray(np.random.RandomState(seed).randn(rows, vocab),
                       jnp.float32)


def test_greedy_is_argmax():
    lg = _logits()
    toks = greedy(lg)
    assert toks.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.argmax(np.asarray(lg), axis=-1))


def test_default_config_is_greedy_and_ignores_key():
    cfg = SamplingConfig()
    assert cfg.is_greedy
    lg = _logits()
    a = sample_token(lg, jax.random.PRNGKey(0), cfg)
    b = sample_token(lg, jax.random.PRNGKey(99), cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(greedy(lg)))


def test_sampled_deterministic_per_key_and_key_sensitive():
    cfg = SamplingConfig(temperature=1.0)
    lg = _logits(rows=64)
    k = jax.random.PRNGKey(1)
    a = sample_token(lg, k, cfg)
    b = sample_token(lg, k, cfg)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = sample_token(lg, jax.random.PRNGKey(2), cfg)
    assert np.any(np.asarray(a) != np.asarray(c))


def test_top_k_restricts_support():
    cfg = SamplingConfig(temperature=1.0, top_k=3)
    lg = _logits(rows=16, vocab=32)
    top3 = np.argsort(np.asarray(lg), axis=-1)[:, -3:]
    for i in range(50):
        toks = np.asarray(sample_token(
            lg, jax.random.PRNGKey(i), cfg))
        for row, t in enumerate(toks):
            assert t in top3[row], (row, t)


def test_low_temperature_approaches_greedy():
    cfg = SamplingConfig(temperature=1e-4)
    lg = _logits()
    toks = sample_token(lg, jax.random.PRNGKey(0), cfg)
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(greedy(lg)))


def test_config_is_static_and_hashable():
    # jit closure requirement: the config must hash (frozen dataclass)
    assert hash(SamplingConfig(temperature=0.7, top_k=5)) is not None
    assert SamplingConfig() == SamplingConfig(temperature=0.0, top_k=0)


def test_config_rejects_nonsense():
    import pytest
    with pytest.raises(ValueError, match="temperature"):
        SamplingConfig(temperature=-0.7)   # would invert the dist
    with pytest.raises(ValueError, match="top_k"):
        SamplingConfig(temperature=1.0, top_k=-1)
