"""Speculative decoding (ISSUE 15): the verify step emits EXACTLY the
target's greedy stream regardless of draft quality (correctness never
depends on the drafter), accept/reject is a pure length rollback on
the paged cache, the slab writes respect the bounded-damage
discipline, and the drafters honor their contracts."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import (
    EngineDrafter,
    InferenceEngine,
    NGramDrafter,
    ReplayDrafter,
    SlotScheduler,
)
from apex_tpu.inference import kv_cache
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


def _gpt():
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, params


def _llama_gqa():
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=1,
                      num_attention_heads=4, num_kv_heads=2,
                      max_seq_length=64)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return cfg, params


def _serve(kind, cfg, params, prompts, mnt=8, drafter=None, **kw):
    eng = InferenceEngine(kind, cfg, params, slots=2, max_seq=64, **kw)
    tel = ServeTelemetry(MetricsRegistry())
    sched = SlotScheduler(eng, telemetry=tel, drafter=drafter)
    uids = [sched.submit(p, max_new_tokens=mnt) for p in prompts]
    out = sched.run()
    return [out[u] for u in uids], tel


_PAGED = dict(page_size=8, num_pages=24)


@pytest.mark.parametrize("kind", ["gpt", "llama"])
def test_spec_stream_equals_plain_greedy_paged(kind):
    cfg, params = _gpt() if kind == "gpt" else _llama_gqa()
    prompts = [list((np.arange(10) * 3 + i) % 64) for i in range(3)]
    base, _ = _serve(kind, cfg, params, prompts, **_PAGED)
    spec, tel = _serve(kind, cfg, params, prompts, spec_k=3, **_PAGED)
    assert base == spec
    assert int(tel.spec_verify_steps.total()) > 0
    assert int(tel.recompiles.total()) == 0
    # conservation: every emitted token reached a request
    assert int(tel.spec_emitted.total()) == \
        int(tel.tokens_generated.total()) - len(prompts)


def test_spec_stream_equals_plain_greedy_dense():
    """The verify slab machinery is layout-agnostic: the dense slot
    cache rolls back by the same length reset."""
    cfg, params = _gpt()
    prompts = [list((np.arange(10) * 3 + i) % 64) for i in range(3)]
    base, _ = _serve("gpt", cfg, params, prompts)
    spec, _ = _serve("gpt", cfg, params, prompts, spec_k=4)
    assert base == spec


def test_poisoned_drafts_still_emit_target_stream():
    """A drafter that lies (scripted garbage) costs speculation upside
    only: every round rejects and emits the bonus token — the stream
    is still the target's greedy stream, at acceptance 0."""
    cfg, params = _gpt()
    prompts = [list((np.arange(10) * 3) % 64)]
    base, _ = _serve("gpt", cfg, params, prompts, **_PAGED)
    poisoned = ReplayDrafter({tuple(prompts[0]): [63] * 16})
    # a lying script would collide with real greedy tokens only if 63
    # were ever emitted — make sure it is not
    assert 63 not in base[0]
    spec, tel = _serve("gpt", cfg, params, prompts, spec_k=3,
                       drafter=poisoned, **_PAGED)
    assert spec == base
    assert int(tel.spec_accepted.total()) == 0
    assert int(tel.spec_emitted.total()) == len(base[0]) - 1


def test_replay_drafter_reaches_full_acceptance():
    cfg, params = _llama_gqa()
    prompts = [list((np.arange(9) * 5 + i) % 64) for i in range(2)]
    base, _ = _serve("llama", cfg, params, prompts, **_PAGED)
    script = {tuple(p): toks for p, toks in zip(prompts, base)}
    spec, tel = _serve("llama", cfg, params, prompts, spec_k=4,
                       drafter=ReplayDrafter(script), **_PAGED)
    assert spec == base
    drafted = int(tel.spec_drafted.total())
    accepted = int(tel.spec_accepted.total())
    # the script IS the continuation: only the final short round can
    # reject (pad drafts past the budget), so acceptance is near 1 and
    # the 8-token budget needs at most ceil(7 / 5) verify rounds/slot
    assert accepted / drafted >= 0.5
    assert int(tel.spec_verify_steps.total()) <= 2
    # the >= 1.5x effective-tokens-per-step criterion, counted exactly:
    # emitted tokens per slot-step vs the 1-token decode baseline
    emitted = int(tel.spec_emitted.total())
    slot_steps = drafted // 4
    assert emitted / slot_steps >= 1.5


def test_verify_rollback_lengths_and_pages():
    """Direct engine.verify: accepted count advances lengths by
    n_emit, rejected rows stay dead-by-mask, and the page table is
    untouched (rollback releases nothing device-side)."""
    cfg, params = _gpt()
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                          spec_k=3, **_PAGED)
    alloc = eng.new_allocator()
    cache = eng.init_cache()
    prompt = list((np.arange(10) * 3) % 64)
    toks = []
    for slot in range(2):
        pages = alloc.acquire(alloc.pages_needed(len(prompt) + 8))
        cache, tok, _ = eng.prefill(cache, prompt, slot, pages=pages)
        toks.append(int(tok))
    table_before = np.asarray(cache.page_table).copy()
    len_before = np.asarray(cache.lengths).copy()
    # garbage drafts: everything rejects, n_emit == 1 everywhere
    slab = np.zeros((2, 4), np.int32)
    slab[:, 0] = toks
    slab[:, 1:] = 63
    cache, out, n_emit, truncated = eng.verify(cache, slab)
    n_emit = np.asarray(n_emit)
    out = np.asarray(out)
    assert not np.asarray(truncated).any()
    np.testing.assert_array_equal(np.asarray(cache.page_table),
                                  table_before)
    np.testing.assert_array_equal(np.asarray(cache.lengths),
                                  len_before + n_emit)
    # full-acceptance round: feed the emitted tokens back as drafts
    slab2 = np.zeros((2, 4), np.int32)
    slab2[:, 0] = out[:, 0]
    cache2 = eng.init_cache()
    for slot in range(2):
        # rebuild the same state and verify with the TRUE continuation
        cache2, _, _ = eng.prefill(cache2, prompt, slot,
                                   pages=[int(p) for p in
                                          table_before[slot]
                                          if p != cache.null_page])
    # continuation oracle: greedy decode 3 steps
    base_stream = []
    c, t = cache2, np.asarray(toks, np.int32)
    for _ in range(3):
        c, t, _, _ = eng.decode(c, t)
        base_stream.append(np.asarray(t).copy())
    slab3 = np.zeros((2, 4), np.int32)
    slab3[:, 0] = toks
    for j in range(3):
        slab3[:, 1 + j] = base_stream[j]
    cache3 = eng.init_cache()
    for slot in range(2):
        cache3, _, _ = eng.prefill(cache3, prompt, slot,
                                   pages=[int(p) for p in
                                          table_before[slot]
                                          if p != cache.null_page])
    cache3, out3, n_emit3, _ = eng.verify(cache3, slab3)
    assert (np.asarray(n_emit3) == 4).all()
    for j in range(3):
        np.testing.assert_array_equal(np.asarray(out3)[:, j],
                                      base_stream[j])


def test_append_slab_paged_drops_past_window():
    """Slab rows past the virtual window are DROPPED (never clamped
    onto live rows), and rows inside land at (page, offset) exactly."""
    cache = kv_cache.init_paged_cache(6, 1, 1, 4, 2, slots=1,
                                     max_pages_per_slot=2)
    row = np.asarray([0, 1], np.int32)
    cache = cache.replace(
        page_table=jnp.asarray(row)[None],
        lengths=jnp.asarray([6], jnp.int32),
        capacity=jnp.asarray([8], jnp.int32))
    k = jnp.arange(1 * 1 * 4 * 2, dtype=jnp.float32).reshape(
        1, 1, 4, 2) + 1.0
    before = np.asarray(cache.k).copy()
    cache = kv_cache.append_slab(cache, 0, k, k)
    after = np.asarray(cache.k)
    # positions 6, 7 land in page 1 rows 2, 3; positions 8, 9 are past
    # the 2-page window and vanish (no page may change but 1)
    np.testing.assert_array_equal(after[1, 0, 0, 2], np.asarray(k)[0, 0, 0])
    np.testing.assert_array_equal(after[1, 0, 0, 3], np.asarray(k)[0, 0, 1])
    changed = [p for p in range(6) if not np.array_equal(after[p],
                                                        before[p])]
    assert changed == [1]


def test_advance_by_clamps_and_flags():
    cache = kv_cache.init_paged_cache(6, 1, 1, 4, 2, slots=2,
                                     max_pages_per_slot=2)
    cache = cache.replace(
        lengths=jnp.asarray([6, 0], jnp.int32),
        capacity=jnp.asarray([8, 0], jnp.int32))
    cache, trunc = kv_cache.advance_by(cache, np.asarray([True, True]),
                                       np.asarray([4, 4], np.int32))
    # slot 0 wanted 10 > cap 8: clamped + flagged; slot 1 has capacity
    # 0 (never admitted): clamped to 0, NOT flagged
    np.testing.assert_array_equal(np.asarray(cache.lengths), [8, 0])
    np.testing.assert_array_equal(np.asarray(trunc), [True, False])


def test_set_lengths_rollback():
    cache = kv_cache.init_cache(2, 1, 1, 16, 2)
    cache = cache.replace(lengths=jnp.asarray([9, 4], jnp.int32))
    cache = kv_cache.set_lengths(cache, np.asarray([5, 4], np.int32))
    np.testing.assert_array_equal(np.asarray(cache.lengths), [5, 4])


def test_ngram_drafter_prompt_lookup():
    d = NGramDrafter(max_ngram=3)
    d.begin(0, [1, 2, 3, 4, 1, 2, 3], first_token=4)
    # history ...1,2,3,4,1,2,3,4 — suffix [2,3,4] last occurred at
    # index 1, followed by [1, 2, 3]
    assert d.draft(0, 3) == [1, 2, 3]
    d.observe(0, [9])
    # suffix now ends in 9, never seen before at any ngram length
    assert d.draft(0, 3) == []
    d.retire(0)
    assert d.draft(0, 3) == []


def test_ngram_drafter_min_ngram_refuses_coincidence():
    d = NGramDrafter(max_ngram=3, min_ngram=2)
    d.begin(0, [1, 2, 3], first_token=1)
    # only the single token 1 repeats; min_ngram=2 refuses it
    assert d.draft(0, 2) == []
    d2 = NGramDrafter(max_ngram=3, min_ngram=1)
    d2.begin(0, [1, 2, 3], first_token=1)
    assert d2.draft(0, 2) == [2, 3]


def test_engine_drafter_self_draft_full_acceptance():
    """A draft engine running the SAME weights as the target drafts
    the target's exact stream: acceptance 1.0, and the draft cache's
    rollback keeps it consistent across rounds."""
    cfg, params = _llama_gqa()
    prompts = [list((np.arange(9) * 5 + i) % 64) for i in range(2)]
    base, _ = _serve("llama", cfg, params, prompts, **_PAGED)
    draft = InferenceEngine("llama", cfg, params, slots=2, max_seq=64)
    spec, tel = _serve("llama", cfg, params, prompts, spec_k=3,
                       drafter=EngineDrafter(draft), **_PAGED)
    assert spec == base
    rate = (int(tel.spec_accepted.total())
            / int(tel.spec_drafted.total()))
    assert rate >= 0.7          # only final short rounds reject


def test_engine_drafter_rejects_misconfiguration():
    cfg, params = _gpt()
    paged = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                            **_PAGED)
    with pytest.raises(ValueError):
        EngineDrafter(paged)            # paged draft cache unsupported
    from apex_tpu.inference.sampling import SamplingConfig
    sampled = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                              sampling=SamplingConfig(temperature=0.7))
    with pytest.raises(ValueError):
        EngineDrafter(sampled)


def test_verify_requires_greedy_and_k():
    from apex_tpu.inference.engine import make_verify_fn
    from apex_tpu.inference.sampling import SamplingConfig
    cfg, _ = _gpt()
    with pytest.raises(ValueError):
        make_verify_fn("gpt", cfg, SamplingConfig(), k=0)
    with pytest.raises(ValueError):
        make_verify_fn("gpt", cfg, SamplingConfig(temperature=0.5), k=2)
    cfg2, params = _gpt()
    eng = InferenceEngine("gpt", cfg2, params, slots=2, max_seq=64)
    with pytest.raises(ValueError):
        eng.verify(eng.init_cache(), np.zeros((2, 3), np.int32))


def test_verify_step_histogram_sample_is_per_token():
    """SLO semantics: the decode-latency histogram (which the
    decode_token_p99 objective consumes) must see the EFFECTIVE
    per-token latency for a verify step — step seconds divided by the
    mean tokens emitted per active slot — never the raw multi-token
    step time; the raw wall time lands in the host-side
    spec_step_seconds tally instead (the bench speculation leg's
    clock).  Arming speculation must not read as a latency
    regression."""
    import time

    tel = ServeTelemetry(MetricsRegistry())
    with tel.verify_step(2) as holder:
        time.sleep(0.02)
        holder["tokens"] = 8.0         # 4 tokens per active slot
    assert tel.spec_step_seconds >= 0.02
    assert tel.decode_token_seconds.count() == 1
    # one sample = step_seconds / 4, strictly below the raw step time
    assert tel.decode_token_seconds.sum() <= tel.spec_step_seconds / 2
    assert int(tel.spec_verify_steps.total()) == 1


def test_default_spec_k_env(monkeypatch):
    from apex_tpu.inference.speculative import default_spec_k
    monkeypatch.delenv("APEX_TPU_SPEC_K", raising=False)
    assert default_spec_k() == 0
    monkeypatch.setenv("APEX_TPU_SPEC_K", "4")
    assert default_spec_k() == 4
    monkeypatch.setenv("APEX_TPU_SPEC_K", "-1")
    with pytest.raises(ValueError):
        default_spec_k()
    monkeypatch.setenv("APEX_TPU_SPEC_K", "many")
    with pytest.raises(ValueError):
        default_spec_k()
