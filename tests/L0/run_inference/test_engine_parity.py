"""ISSUE 4 acceptance: prefill + N greedy decode steps reproduce the
full-sequence forward's argmax tokens (and logits within bf16
tolerance) for GPT and LLaMA, including GQA/MQA variants."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import InferenceEngine
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    GPTConfig,
    LlamaConfig,
    gpt_model_provider,
    llama_model_provider,
)

N_NEW = 6
PROMPT_LEN = 5


@pytest.fixture(autouse=True)
def _single_rank():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    yield


def _reference_greedy(model, params, prompt, n_new):
    """Full-sequence forward re-run per token: the O(s^2)-per-token
    oracle the engine must reproduce.  The sequence is padded to its
    final length so the forward compiles ONCE — causal masking makes
    the positions past the live prefix inert, so the logits at the live
    last position are exactly the unpadded run's."""
    total = len(prompt) + n_new
    toks = list(prompt)
    apply = jax.jit(model.apply)
    logits_last = None
    for _ in range(n_new):
        padded = np.zeros((1, total), np.int32)
        padded[0, :len(toks)] = toks
        logits = apply(params, jnp.asarray(padded))  # [total, 1, v]
        logits_last = logits[len(toks) - 1, 0].astype(jnp.float32)
        toks.append(int(jnp.argmax(logits_last)))
    return toks[len(prompt):], logits_last


def _engine_greedy(engine, prompt, n_new, slot=0):
    cache = engine.init_cache()
    cache, tok, first_logits = engine.prefill(cache, prompt, slot)
    got = [int(np.asarray(tok))]
    last = np.zeros((engine.slots,), np.int32)
    active = np.zeros((engine.slots,), bool)
    last[slot], active[slot] = got[-1], True
    logits = None
    for _ in range(n_new - 1):
        cache, toks, logits, _ = engine.decode(cache, last, active)
        got.append(int(np.asarray(toks)[slot]))
        last[slot] = got[-1]
    return got, first_logits, (None if logits is None
                               else np.asarray(logits)[slot])


def _check_parity(kind, cfg, model, logits_tol):
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, PROMPT_LEN), jnp.int32))
    engine = InferenceEngine(kind, cfg, params, slots=2, max_seq=32)
    prompt = list(np.random.RandomState(7).randint(
        0, cfg.vocab_size, size=PROMPT_LEN))
    ref_toks, ref_logits = _reference_greedy(model, params, prompt, N_NEW)
    got_toks, first_logits, _ = _engine_greedy(engine, prompt, N_NEW,
                                               slot=1)
    assert got_toks == ref_toks, (got_toks, ref_toks)
    # prefill logits vs the full forward at the last prompt position
    t = jnp.asarray(np.array(prompt)[None], jnp.int32)
    full = model.apply(params, t)[-1, 0].astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(first_logits), np.asarray(full),
                               rtol=logits_tol, atol=logits_tol)


def test_llama_gqa_one_layer_greedy_fast():
    """Fast-lane parity sentinel: the smallest config that still walks
    the full GQA decode path (grouped cache, RoPE at position, RMSNorm,
    untied head).  The heavier multi-layer GPT/LLaMA/bf16 variants live
    in the slow lane."""
    cfg = LlamaConfig(vocab_size=64, hidden_size=32, num_layers=1,
                      num_attention_heads=4, num_kv_heads=2,
                      max_seq_length=32)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    engine = InferenceEngine("llama", cfg, params, slots=1, max_seq=16)
    prompt = [3, 1, 4, 1]
    ref_toks, _ = _reference_greedy(model, params, prompt, 4)
    got_toks, _, _ = _engine_greedy(engine, prompt, 4)
    assert got_toks == ref_toks


def test_gpt_greedy_decode_matches_full_forward():
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_attention_heads=4, max_seq_length=32,
                    hidden_dropout=0.0, attention_dropout=0.0)
    _check_parity("gpt", cfg, gpt_model_provider(cfg), 1e-4)


def test_gpt_bf16_params_greedy_matches():
    """The serving regime proper: bf16 model params end to end."""
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_attention_heads=4, max_seq_length=32,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    params_dtype=jnp.bfloat16)
    _check_parity("gpt", cfg, gpt_model_provider(cfg), 2e-2)


def test_llama_gqa_greedy_decode_matches_full_forward():
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                      num_attention_heads=4, num_kv_heads=2,
                      max_seq_length=32)
    _check_parity("llama", cfg, llama_model_provider(cfg), 1e-4)


def test_llama_mqa_greedy_decode_matches_full_forward():
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, num_layers=2,
                      num_attention_heads=4, num_kv_heads=1,
                      max_seq_length=32)
    _check_parity("llama", cfg, llama_model_provider(cfg), 1e-4)


def test_decode_logits_match_full_forward_logits():
    """Not only the argmax: the decode-path logits themselves stay
    within bf16-ish tolerance of the full-sequence forward's."""
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_attention_heads=4, max_seq_length=32,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, PROMPT_LEN), jnp.int32))
    engine = InferenceEngine("gpt", cfg, params, slots=1, max_seq=32)
    prompt = list(np.random.RandomState(9).randint(
        0, cfg.vocab_size, size=PROMPT_LEN))
    ref_toks, ref_logits = _reference_greedy(model, params, prompt, N_NEW)
    got_toks, _, last_decode_logits = _engine_greedy(engine, prompt,
                                                     N_NEW)
    assert got_toks == ref_toks
    # ref_logits: full forward at the position predicting token N_NEW;
    # last_decode_logits: the decode step that produced the same token
    np.testing.assert_allclose(last_decode_logits, np.asarray(ref_logits),
                               rtol=2e-2, atol=2e-2)


def test_gqa_cache_is_per_kv_head():
    """The cache must hold kv_heads entries (not query heads): GQA's
    whole serving win."""
    cfg = LlamaConfig(vocab_size=64, hidden_size=64, num_layers=2,
                      num_attention_heads=8, num_kv_heads=2,
                      max_seq_length=32)
    model = llama_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    engine = InferenceEngine("llama", cfg, params, slots=1, max_seq=16)
    cache = engine.init_cache()
    assert cache.kv_heads == 2                       # not 8
    assert cache.k.shape == (1, 2, 2, 16, 8)


def test_bert_encode_only_path():
    """BERT rides along encode-only: one jitted bidirectional forward
    equal to model.apply; the generative surface refuses politely."""
    from apex_tpu.transformer.testing import (BertConfig,
                                              bert_model_provider)
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=1,
                     num_attention_heads=2, max_seq_length=16,
                     hidden_dropout=0.0, attention_dropout=0.0)
    model = bert_model_provider(cfg, add_binary_head=False)
    tokens = jnp.asarray(np.random.RandomState(0).randint(
        0, 64, size=(2, 8)), jnp.int32)
    types = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens, types)
    engine = InferenceEngine("bert", cfg, params)
    got = engine.encode(tokens)
    ref = jax.jit(model.apply)(params, tokens, types)  # same compile path
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
        if a is not None else None, got, ref)
    with pytest.raises(ValueError, match="encode"):
        engine.init_cache()


def test_continuous_batching_is_slot_invariant():
    """Per-request outputs are identical whether requests share 2 slots
    (queueing + slot reuse) or get 5 dedicated slots."""
    cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=2,
                    num_attention_heads=4, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    rng = np.random.RandomState(3)
    prompts = [list(rng.randint(0, 64, size=n)) for n in (4, 7, 3, 5, 9)]
    out2 = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64) \
        .generate(prompts, max_new_tokens=4)
    out5 = InferenceEngine("gpt", cfg, params, slots=5, max_seq=64) \
        .generate(prompts, max_new_tokens=4)
    assert out2 == out5
    assert all(len(o) == 4 for o in out2)
