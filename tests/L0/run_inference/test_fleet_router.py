"""Fleet front door (ISSUE 19): routing policies, the read-only radix
peek, swap-aware admission cost ordering, the router-level
conservation law, the env knob readers, and the discrete-event
capacity simulator's determinism/monotonicity/provenance contracts.
"""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fleet import (CAPACITY_DRIFT_TOLERANCE, FleetRouter,
                            POLICIES, ServiceProfile, build_fleet,
                            default_fleet_policy, drift_ratio,
                            fleet_replicas_from_env,
                            profile_from_captures, required_replicas,
                            simulate)
from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.inference.scheduler import HOST_HIT_TOKEN_COST
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


def _engine(host_tier_bytes=0, num_pages=16):
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                           page_size=8, num_pages=num_pages,
                           host_tier_bytes=host_tier_bytes)


PREFIX = [int(t) for t in (np.arange(16) * 5 + 2) % 64]


# --------------------------------------------------------------------------
# the read-only peek
# --------------------------------------------------------------------------

def test_peek_match_is_read_only():
    """peek_match reports the same coverage as match_tiered WITHOUT
    ticking the LRU clock or touching stamps — the affinity router
    probes every replica per request, and N probes must not perturb
    which edge the next eviction picks."""
    eng = _engine()
    sched = SlotScheduler(eng,
                          telemetry=ServeTelemetry(MetricsRegistry()))
    sched.submit(PREFIX + [1, 2], max_new_tokens=2)
    sched.run()
    pc = sched.prefix
    clock0 = pc._clock
    covered, hbm, host = pc.peek_match(PREFIX + [1, 2])
    for _ in range(10):
        assert pc.peek_match(PREFIX + [1, 2]) == (covered, hbm, host)
    assert pc._clock == clock0
    assert covered >= 16 and hbm >= 2 and host == 0
    # a miss below min_hit_tokens is the (0, 0, 0) triple
    assert pc.peek_match([63, 62, 61]) == (0, 0, 0)


def test_admission_cost_ordering_hbm_host_cold():
    """The satellite's pinned ordering: full-HBM hit < host-tier hit
    < cold, always — the host tier discounts recompute but the swap-in
    upload is not free (HOST_HIT_TOKEN_COST per covered host token)."""
    eng = _engine(host_tier_bytes=1 << 20)
    sched = SlotScheduler(eng,
                          telemetry=ServeTelemetry(MetricsRegistry()))
    prompt = PREFIX + [1, 2]
    sched.submit(prompt, max_new_tokens=2)
    sched.run()
    cold_prompt = [int(t) for t in (np.arange(16) * 7 + 3) % 64] + [1, 2]
    cost_hbm = sched.admission_cost(prompt)
    cost_cold = sched.admission_cost(cold_prompt)
    # evict the prefix to the host tier: same coverage, discounted
    sched.prefix.evict_lru(eng.num_pages)
    sched.drain_pending_swaps()
    assert sched.host_store.pages > 0
    cost_host = sched.admission_cost(prompt)
    assert cost_hbm < cost_host < cost_cold
    # the arithmetic, not just the ordering: eviction offloads the two
    # FULL prefix pages (16 tokens) and discards the partial tail, so
    # the host hit pays the uncovered tail at full price plus the
    # swap-in discount on every host-covered token
    assert cost_cold == pytest.approx(float(len(cold_prompt)))
    assert cost_host == pytest.approx(
        float(len(prompt) - 16) + HOST_HIT_TOKEN_COST * 16)


def test_admission_cost_without_prefix_cache_is_full_price():
    eng = _engine()
    sched = SlotScheduler(eng,
                          telemetry=ServeTelemetry(MetricsRegistry()),
                          prefix_cache=False)
    assert sched.admission_cost(PREFIX) == pytest.approx(16.0)


# --------------------------------------------------------------------------
# routing policies
# --------------------------------------------------------------------------

def test_round_robin_stripes_uids():
    fleet = build_fleet([_engine(), _engine(), _engine()],
                        policy="round_robin")
    for i in range(6):
        uid = fleet.submit(PREFIX + [i, i + 1], max_new_tokens=2)
        assert fleet.placements[uid][0] == i % 3
    fleet.run()
    assert fleet.conservation()["holds"]


def test_least_loaded_prefers_empty_queue():
    fleet = build_fleet([_engine(), _engine()], policy="least_loaded")
    # preload replica 0's queue directly (no run yet)
    fleet.replicas[0].submit(PREFIX + [9, 9], max_new_tokens=2)
    uid = fleet.submit(PREFIX + [1, 2], max_new_tokens=2)
    assert fleet.placements[uid][0] == 1


def test_prefix_affinity_chases_cached_pages():
    """After one seeding wave, every later request sharing the prefix
    routes to the replica whose radix tree holds it — with counters
    and route_decision events to show for it."""
    fleet = build_fleet([_engine(), _engine()],
                        policy="prefix_affinity")
    events = []
    fleet.telemetry.registry.add_sink(
        type("S", (), {"event": lambda self, e: events.append(e),
                       "export": lambda self, fams: None})())
    seed = fleet.submit(PREFIX + [1, 2], max_new_tokens=2)
    fleet.run()
    home = fleet.placements[seed][0]
    for i in range(3, 9, 2):
        uid = fleet.submit(PREFIX + [i, i + 1], max_new_tokens=2)
        assert fleet.placements[uid][0] == home
        fleet.run()
    assert int(fleet.telemetry.affinity_hits.total()) >= 3
    assert int(fleet.telemetry.routed_prefix_tokens.total(
        )) >= 3 * 16
    routes = [e for e in events if e["kind"] == "route_decision"]
    assert routes and all(r["policy"] == "prefix_affinity"
                          for r in routes)
    assert fleet.conservation()["holds"]


def test_affinity_spills_off_deep_queue():
    """The load-aware spill threshold: a preferred replica with a deep
    queue loses the request to the least-loaded one (counted)."""
    fleet = build_fleet([_engine(), _engine()],
                        policy="prefix_affinity", spill_queue_depth=1)
    seed = fleet.submit(PREFIX + [1, 2], max_new_tokens=2)
    fleet.run()
    home = fleet.placements[seed][0]
    # queue one request onto the home replica WITHOUT running, then a
    # prefix-sharing request must spill to the other replica
    fleet.replicas[home].submit(PREFIX + [40, 41], max_new_tokens=2)
    uid = fleet.submit(PREFIX + [3, 4], max_new_tokens=2)
    assert fleet.placements[uid][0] != home
    assert int(fleet.telemetry.affinity_spills.total()) == 1


def test_unknown_policy_rejected():
    with pytest.raises(ValueError):
        FleetRouter([SlotScheduler(
            _engine(), telemetry=ServeTelemetry(MetricsRegistry()))],
            policy="hash_ring")
    with pytest.raises(ValueError):
        build_fleet([])


# --------------------------------------------------------------------------
# env knobs
# --------------------------------------------------------------------------

def test_fleet_env_knob_readers(monkeypatch):
    monkeypatch.delenv("APEX_TPU_FLEET_REPLICAS", raising=False)
    monkeypatch.delenv("APEX_TPU_FLEET_POLICY", raising=False)
    assert fleet_replicas_from_env() == 0
    assert default_fleet_policy() == "prefix_affinity"
    monkeypatch.setenv("APEX_TPU_FLEET_REPLICAS", "4")
    monkeypatch.setenv("APEX_TPU_FLEET_POLICY", "least_loaded")
    assert fleet_replicas_from_env() == 4
    assert default_fleet_policy() == "least_loaded"
    monkeypatch.setenv("APEX_TPU_FLEET_REPLICAS", "-1")
    with pytest.raises(ValueError):
        fleet_replicas_from_env()
    monkeypatch.setenv("APEX_TPU_FLEET_REPLICAS", "two")
    with pytest.raises(ValueError):
        fleet_replicas_from_env()
    monkeypatch.setenv("APEX_TPU_FLEET_POLICY", "hash_ring")
    with pytest.raises(ValueError):
        default_fleet_policy()
    for p in POLICIES:
        monkeypatch.setenv("APEX_TPU_FLEET_POLICY", p)
        assert default_fleet_policy() == p


# --------------------------------------------------------------------------
# capacity simulator
# --------------------------------------------------------------------------

PROF = ServiceProfile(10.0, 100.0, "measured:test")


def test_simulate_is_deterministic():
    kw = dict(replicas=2, slots=2, n_requests=64,
              interarrival_us=500.0, prompt_tokens=32,
              decode_tokens=8, seed=7)
    assert simulate(PROF, **kw) == simulate(PROF, **kw)
    # fixed-spacing arrivals (seed None) are deterministic too
    kw["seed"] = None
    assert simulate(PROF, **kw) == simulate(PROF, **kw)


def test_more_replicas_never_hurt_ttft():
    """Monotonicity: each added replica only removes queue wait."""
    prev = None
    for n in (1, 2, 4, 8):
        r = simulate(PROF, replicas=n, slots=2, n_requests=128,
                     interarrival_us=100.0, prompt_tokens=64,
                     decode_tokens=16, seed=3)
        if prev is not None:
            assert r["ttft_p99_us"] <= prev + 1e-9
        prev = r["ttft_p99_us"]
    # the floor is pure prefill: no queue can make TTFT beat it
    assert prev >= 64 * PROF.prefill_us_per_token - 1e-9


def test_required_replicas_meets_slo_and_degrades():
    ans = required_replicas(PROF, slots=2, slo_ttft_us=2000.0,
                            n_requests=128, interarrival_us=100.0,
                            prompt_tokens=64, decode_tokens=16, seed=3)
    n = ans["replicas"]
    assert n is not None and ans["ttft_p99_us"] <= 2000.0
    if n > 1:
        under = simulate(PROF, replicas=n - 1, slots=2, n_requests=128,
                         interarrival_us=100.0, prompt_tokens=64,
                         decode_tokens=16, seed=3)
        assert under["ttft_p99_us"] > 2000.0
    # an unmeetable SLO (below one request's own prefill) answers None
    floor = 64 * PROF.prefill_us_per_token
    assert required_replicas(PROF, slots=2, slo_ttft_us=floor / 2,
                             prompt_tokens=64)["replicas"] is None


def test_unavailable_profile_refuses_to_price(tmp_path):
    prof = profile_from_captures(tmp_path)        # no captures at all
    assert not prof.available
    assert prof.provenance == "unavailable:no_measured_captures"
    sim = simulate(prof, replicas=2, slots=2)
    assert sim["ttft_p99_us"] is None
    assert sim["provenance"].startswith("unavailable:")
    assert required_replicas(prof, slots=2,
                             slo_ttft_us=1.0)["replicas"] is None


def test_profile_from_captures_newest_round_wins(tmp_path):
    (tmp_path / "r3_old.json").write_text(json.dumps(
        {"infer_prefill_tokens_per_s": 1e5,
         "infer_decode_token_us": 50.0}))
    (tmp_path / "r7_new.json").write_text(json.dumps(
        {"infer_prefill_tokens_per_s": 2e5,
         "infer_decode_token_us": 25.0, "backend": "cpu"}))
    (tmp_path / "r9_partial.json").write_text(json.dumps(
        {"infer_decode_token_us": 10.0}))       # missing prefill: skip
    (tmp_path / "notes.txt").write_text("not a capture")
    prof = profile_from_captures(tmp_path)
    assert prof.provenance == "measured:r7_new.json:cpu"
    assert prof.prefill_us_per_token == pytest.approx(5.0)
    assert prof.decode_us_per_token == pytest.approx(25.0)


def test_drift_ratio_symmetric_and_null_safe():
    assert drift_ratio(100.0, 200.0) == pytest.approx(2.0)
    assert drift_ratio(200.0, 100.0) == pytest.approx(2.0)
    assert drift_ratio(None, 100.0) is None
    assert drift_ratio(100.0, None) is None
    assert drift_ratio(0.0, 100.0) is None
    assert drift_ratio(100.0, -1.0) is None
    assert CAPACITY_DRIFT_TOLERANCE >= 1.0


def test_bad_sim_shapes_rejected():
    with pytest.raises(ValueError):
        simulate(PROF, replicas=0, slots=2)
    with pytest.raises(ValueError):
        simulate(PROF, replicas=2, slots=0)


# --------------------------------------------------------------------------
# hygiene/watch ride-alongs (ISSUE 19 satellite)
# --------------------------------------------------------------------------

def test_fleet_capture_fields_ride_existing_rules():
    """The fleet leg's stamps need no new hygiene or watch rules: the
    per-replica/policy TTFTs are ``*_us`` latencies, and the capacity
    agreement ratio trends lower-is-better by its ``_drift_ratio``
    suffix — pinned here so a rename breaks loudly."""
    from apex_tpu.observability.capture_hygiene import is_us_key
    from apex_tpu.observability.watch import metric_direction
    for key in ("fleet_affinity_ttft_us", "fleet_round_robin_ttft_us",
                "fleet_replica0_ttft_us", "fleet_capacity_pred_ttft_us",
                "fleet_capacity_measured_ttft_us"):
        assert is_us_key(key), key
        assert metric_direction(key) == "lower", key
    assert metric_direction("fleet_capacity_drift_ratio") == "lower"
    # knob/context stamps must NOT read as measurements
    for key in ("fleet_replicas", "fleet_policy", "fleet_slots",
                "fleet_capacity_replicas_needed",
                "fleet_capacity_provenance"):
        assert metric_direction(key) is None, key
