"""Single-token decode attention vs the materializing oracle: length
masking, GQA/MQA grouping, XLA-vs-kernel path parity, crossover knob."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import (
    _DECODE_XLA_MAX_SEQ,
    decode_attention,
    decode_xla_max_seq,
    mha_reference,
)


def _inputs(b=3, h=8, kvh=2, s=64, d=16, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, 1, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, kvh, s, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, kvh, s, d), jnp.float32)
    return q, k, v


def _oracle(q, k, v, lengths):
    b, h = q.shape[:2]
    kvh, s, d = k.shape[1:]
    group = h // kvh
    kb, vb = (jnp.broadcast_to(t[:, :, None], (b, kvh, group, s, d))
              .reshape(b, h, s, d) for t in (k, v))
    mask = (jnp.arange(s)[None, None, None, :]
            >= lengths[:, None, None, None])
    return mha_reference(q, kb, vb, mask=mask)


@pytest.mark.parametrize("kvh", [8, 2, 1])          # MHA / GQA / MQA
def test_matches_oracle_with_length_mask(kvh):
    q, k, v = _inputs(kvh=kvh)
    lengths = jnp.asarray([5, 64, 1], jnp.int32)
    out = decode_attention(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v, lengths)),
                               rtol=1e-5, atol=1e-6)


def test_kernel_path_matches_xla_path():
    q, k, v = _inputs()
    lengths = jnp.asarray([5, 64, 17], jnp.int32)
    xla = decode_attention(q, k, v, lengths, use_kernel=False)
    kern = decode_attention(q, k, v, lengths, use_kernel=True)
    np.testing.assert_allclose(np.asarray(xla), np.asarray(kern),
                               rtol=1e-4, atol=1e-5)


def test_length_zero_slot_emits_zeros():
    q, k, v = _inputs()
    lengths = jnp.asarray([0, 3, 0], jnp.int32)
    out = decode_attention(q, k, v, lengths)
    assert np.all(np.asarray(out[0]) == 0)
    assert np.all(np.asarray(out[2]) == 0)
    assert np.all(np.isfinite(np.asarray(out)))


def test_squeezed_layout_and_bf16():
    q, k, v = _inputs()
    lengths = jnp.asarray([5, 64, 17], jnp.int32)
    out3 = decode_attention(q[:, :, 0].astype(jnp.bfloat16),
                            k.astype(jnp.bfloat16),
                            v.astype(jnp.bfloat16), lengths)
    assert out3.shape == (3, 8, 16) and out3.dtype == jnp.bfloat16
    ref = decode_attention(q, k, v, lengths)[:, :, 0]
    np.testing.assert_allclose(np.asarray(out3, np.float32),
                               np.asarray(ref), rtol=0.1, atol=0.05)


def test_crossover_knob(monkeypatch):
    assert decode_xla_max_seq() == _DECODE_XLA_MAX_SEQ
    assert decode_xla_max_seq(128) == 128          # kwarg wins
    monkeypatch.setenv("APEX_TPU_DECODE_XLA_MAX_SEQ", "99")
    assert decode_xla_max_seq() == 99
    assert decode_xla_max_seq(7) == 7
    monkeypatch.setenv("APEX_TPU_DECODE_XLA_MAX_SEQ", "bogus")
    with pytest.raises(ValueError, match="APEX_TPU_DECODE_XLA_MAX_SEQ"):
        decode_xla_max_seq()
    # auto-dispatch honors the crossover: forcing it below S takes the
    # kernel path and still matches
    q, k, v = _inputs()
    lengths = jnp.asarray([5, 64, 17], jnp.int32)
    monkeypatch.delenv("APEX_TPU_DECODE_XLA_MAX_SEQ")
    out = decode_attention(q, k, v, lengths, xla_max_seq=8)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_oracle(q, k, v, lengths)),
                               rtol=1e-4, atol=1e-5)


def test_validation():
    q, k, v = _inputs()
    lengths = jnp.zeros((3,), jnp.int32)
    with pytest.raises(ValueError, match="q_len == 1"):
        decode_attention(jnp.zeros((3, 8, 2, 16)), k, v, lengths)
    q8, k8, v8 = _inputs(kvh=8)
    with pytest.raises(ValueError, match="kv_heads"):
        decode_attention(q8, k8[:, :3], v8[:, :3], lengths)
    with pytest.raises(ValueError, match="lengths"):
        decode_attention(q, k, v, jnp.zeros((4,), jnp.int32))
    with pytest.raises(ValueError, match="equal-shaped"):
        decode_attention(q, k, v[:, :, :32], lengths)
