"""Seeded-violation tests for the serving control-plane protocol
auditor: each invariant family (APX401–APX407) must actually FIRE on a
deliberately broken component twin — double release, release-before-
extract swap ordering, skipped COW on a shared boundary page, dangling
deferred slab, broken handoff ordering — with a MINIMIZED counterexample
that replays from its repro file to the same finding; and the clean
components must explore violation-free with exactly the pinned
canonical state counts.  The twins subclass the REAL classes and break
one protocol rule each, so these tests double as documentation of what
each invariant guards against."""
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.analysis.protocol_audit import (SCOPES, audit_scope,
                                              check_harness,
                                              replay_repro)
from apex_tpu.analysis.protocol_model import (ProtocolHarness, Scope,
                                              StubEngine, Template,
                                              _tag, random_walk,
                                              replay, write_repro)
from apex_tpu.inference.kv_cache import PageAllocator
from apex_tpu.inference.scheduler import SlotScheduler

REPO_ROOT = Path(__file__).resolve().parents[3]
PIN = REPO_ROOT / ".analysis_protocol.json"


# ---------------------------------------------------------------------------
# broken twins — each violates exactly one protocol rule
# ---------------------------------------------------------------------------

class _DoubleReleaseAllocator(PageAllocator):
    """Release ignores sharing: dropping a slot's reference also drops
    any OTHER holder's reference — the classic double-release.  Pages
    the prefix cache still indexes go back on the free list."""

    def release(self, ids):
        super().release(ids)
        for p in ids:
            if p in self._refs:
                super().release([p])


class _DoubleReleaseEngine(StubEngine):
    def new_allocator(self):
        return _DoubleReleaseAllocator(
            self.num_pages, self.page_size, self.max_pages_per_slot)


class _LazyPendingSwapOut:
    """Snapshots at RESOLVE time instead of dispatch time — the
    release-before-extract ordering bug: pages freed after the
    dispatch can be reacquired and overwritten before the drain."""

    def __init__(self, cache, ids):
        self._cache, self._ids = cache, ids
        self._resolved = None

    @property
    def done(self):
        return self._resolved is not None

    def resolve(self):
        if self._resolved is None:
            k = np.array([[int(self._cache.content[p])]
                          for p in self._ids], np.int64)
            self._resolved = (k, k.copy())
        return self._resolved


class _LazyExtractEngine(StubEngine):
    def swap_out_pages(self, cache, page_ids, defer=False):
        pending = _LazyPendingSwapOut(cache,
                                      [int(p) for p in page_ids])
        self.pending_log.append(pending)
        if defer:
            return pending
        return pending.resolve()


class _SkipCowScheduler(SlotScheduler):
    """Maps the shared boundary page straight into the new row instead
    of privatizing it: the admitted request then writes mid-page into
    a page the original owner (and the cache) still trust."""

    def _reservation(self, req):
        row_ids, capacity, covered, cow_src, swap_plan = \
            super()._reservation(req)
        if cow_src is not None and row_ids is not None:
            dst_ord = covered // self.engine.page_size
            self.alloc.release([row_ids[dst_ord]])
            row_ids[dst_ord] = cow_src
            cow_src = None          # admission skips the copy
        return row_ids, capacity, covered, cow_src, swap_plan


class _NoDrainScheduler(SlotScheduler):
    """drain_pending_swaps is a no-op: deferred device->host drains
    are never resolved, so finish_run closes the wave with the
    dispatch queue still holding unfetched extracts."""

    def drain_pending_swaps(self):
        return 0


# ---------------------------------------------------------------------------
# harness builders
# ---------------------------------------------------------------------------

def _engine_factory(cls):
    return lambda sc: cls(
        slots=sc.slots, num_pages=sc.num_pages,
        page_size=sc.page_size,
        max_pages_per_slot=sc.max_pages_per_slot,
        host_tier_pages=sc.host_tier_pages)


def _twin_checks(tmp_path, scope, build, expect_code, repro_name):
    """Shared twin assertions: the exploration finds a violation
    naming ``expect_code``, the counterexample is 1-minimal, and the
    written repro replays to the same primary finding."""
    res = audit_scope(scope, build=build)
    assert res.violation is not None, \
        f"broken twin explored clean ({res.states} states)"
    vio = res.violation
    assert expect_code in vio.codes, \
        f"expected {expect_code} among {vio.codes}: {vio.messages}"
    assert len(vio.trace) >= 1
    # 1-minimality: no single action can be deleted and still fire
    # the same primary code (shrink ran to fixpoint)
    primary = vio.codes[0]
    for i in range(len(vio.trace)):
        cand = vio.trace[:i] + vio.trace[i + 1:]
        _h, v2 = replay(build, cand, check_harness)
        assert v2 is None or v2.codes[0] != primary, \
            f"trace not minimal: action {i} ({vio.trace[i]}) removable"
    # the repro file replays to the same finding
    repro = tmp_path / repro_name
    write_repro(repro, scope, vio)
    replayed = replay_repro(repro, build=build)
    assert replayed is not None
    assert replayed.codes[0] == primary
    return vio


# ---------------------------------------------------------------------------
# seeded violations
# ---------------------------------------------------------------------------

def test_double_release_names_dangling_refs(tmp_path):
    scope = SCOPES["core"]
    build = lambda: ProtocolHarness(
        scope, engine_factory=_engine_factory(_DoubleReleaseEngine))
    vio = _twin_checks(tmp_path, scope, build, "APX404",
                       "repro_double_release.json")
    # the same bug breaks the weighted books too
    assert any(c in ("APX402", "APX403") for c in vio.codes)


def test_release_before_extract_names_slab_content(tmp_path):
    scope = SCOPES["tiered"]
    build = lambda: ProtocolHarness(
        scope, engine_factory=_engine_factory(_LazyExtractEngine))
    vio = _twin_checks(tmp_path, scope, build, "APX405",
                       "repro_lazy_extract.json")
    assert "does not match its tokens" in " ".join(vio.messages)


def test_skipped_cow_names_row_content(tmp_path):
    scope = SCOPES["core"]
    build = lambda: ProtocolHarness(
        scope, scheduler_factory=_SkipCowScheduler)
    vio = _twin_checks(tmp_path, scope, build, "APX403",
                       "repro_skip_cow.json")
    assert "clobbered" in " ".join(vio.messages)


def test_dangling_deferred_slab_names_wave_boundary(tmp_path):
    scope = SCOPES["tiered"]
    build = lambda: ProtocolHarness(
        scope, scheduler_factory=_NoDrainScheduler)
    _twin_checks(tmp_path, scope, build, "APX407",
                 "repro_no_drain.json")


def test_broken_handoff_ordering_names_wave_boundary(tmp_path):
    scope = SCOPES["fleet"]
    build = lambda: ProtocolHarness(
        scope, abort_transit_on_end_wave=False)
    vio = _twin_checks(tmp_path, scope, build, "APX407",
                       "repro_broken_handoff.json")
    assert "handoff" in " ".join(vio.messages)


# ---------------------------------------------------------------------------
# clean twins: violation-free with the PINNED state counts
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(SCOPES))
def test_clean_scope_matches_pin(name):
    res = audit_scope(SCOPES[name])
    assert res.violation is None, \
        f"{name}: {res.violation and res.violation.messages}"
    assert not res.truncated
    pinned = json.loads(PIN.read_text())["scopes"][name]
    assert res.states == pinned["states"]
    assert res.transitions == pinned["transitions"]


def test_exploration_is_deterministic():
    a = audit_scope(SCOPES["fleet"])
    b = audit_scope(SCOPES["fleet"])
    assert (a.states, a.transitions) == (b.states, b.transitions)


# ---------------------------------------------------------------------------
# slow lane: seeded random long walk one notch above the exhaustive pin
# ---------------------------------------------------------------------------

# Bigger than anything exhaustive exploration can cover: more slots
# than "core", a host tier AND COW sharing in the same scope, a third
# prefix depth (C extends A's (1, 2)), and page pressure (3 slots x 4
# pages > 10 pages forces admission deferral).  Handoff stays out:
# it needs replicas > 1, where the harness caps total submits below
# the router's queue detector threshold — far too few for a long
# walk (the "fleet" scope covers handoff exhaustively instead).
_WALK_SCOPE = Scope(
    name="walk", replicas=1, slots=3, num_pages=10, page_size=2,
    max_pages_per_slot=4, host_tier_pages=3, prefill_chunk=2,
    max_chunks_per_pass=2, shed=True,
    evict_sizes=(1, 2), evict_cap=500,
    templates=(
        Template("A", (1, 2, 3), max_new_tokens=4, cap=500),
        Template("A2", (1, 2, 3, 4), max_new_tokens=3, cap=500),
        Template("B", (5, 6), max_new_tokens=2, tenant="t2", cap=500),
        Template("C", (1, 2, 5, 6, 7), max_new_tokens=2, cap=500),
    ))


@pytest.mark.slow
def test_random_long_walk_above_pinned_scope():
    # With 2000 submits of headroom, submit is enabled at every step,
    # so the walk never runs out of actions: exactly 2000 applied,
    # every invariant checked after each one.
    applied = random_walk(lambda: ProtocolHarness(_WALK_SCOPE),
                          check_harness, steps=2000, seed=20260807)
    assert applied == 2000
