"""The env-knob registry is the single source of truth: the README
"Environment knobs" table must list exactly the registered knobs with
the registered defaults, and every knob the package actually reads
must be registered (the runtime reads are linted by APX108; this test
closes the docs half of the loop)."""
import re
from pathlib import Path

from apex_tpu.analysis.cli import repo_root
from apex_tpu.analysis.env_registry import KNOBS

README = repo_root() / "README.md"

_ROW = re.compile(r"^\|\s*`(APEX_TPU_\w+)`\s*\|\s*`([^`]*)`\s*\|\s*(.+?)\s*\|\s*$",
                  re.MULTILINE)


def _doc_rows():
    text = README.read_text(encoding="utf-8")
    return {m.group(1): (m.group(2), m.group(3))
            for m in _ROW.finditer(text)}


def test_readme_table_matches_registry_exactly():
    rows = _doc_rows()
    assert set(rows) == set(KNOBS), (
        f"README knob table drifted from env_registry: "
        f"doc-only={sorted(set(rows) - set(KNOBS))}, "
        f"registry-only={sorted(set(KNOBS) - set(rows))}")
    for name, knob in KNOBS.items():
        doc_default, _ = rows[name]
        assert doc_default == knob.default, (
            f"{name}: README default {doc_default!r} != registry "
            f"default {knob.default!r}")


def test_every_package_env_read_is_registered():
    """Grep the package for APEX_TPU_* string literals near an environ
    read — each one must be a registered knob (the AST-precise check
    is APX108; this is the belt to its suspenders)."""
    pkg = repo_root()
    pat = re.compile(r"APEX_TPU_[A-Z0-9_]+")
    read = re.compile(r"environ|getenv")
    found = set()
    for path in list((pkg / "apex_tpu").rglob("*.py")) + [pkg / "setup.py"]:
        if "analysis" in path.parts:
            continue  # the analyzer's own docs name placeholder knobs
        text = path.read_text(encoding="utf-8")
        if not read.search(text):
            continue
        for line in text.splitlines():
            if read.search(line) or line.strip().startswith(("_", "ENV")):
                found.update(pat.findall(line))
    unregistered = {k for k in found if k not in KNOBS}
    assert not unregistered, sorted(unregistered)


def test_registry_entries_have_substance():
    for knob in KNOBS.values():
        assert knob.name.startswith("APEX_TPU_")
        assert knob.default != ""
        assert len(knob.effect) > 20
        assert knob.read_by
