"""Per-rule fixture tests: every lint rule has one known-violating and
one clean snippet, asserting the exact rule id fires (and nothing fires
on the clean twin)."""
import pytest

from apex_tpu.analysis import lint_source

# Each entry: rule id -> (firing fixture, clean fixture).  The clean
# twin is the *corrected* version of the same code, so these double as
# documentation of the sanctioned pattern.
FIXTURES = {
    "APX101": (
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    loss = jnp.sum(x)
    return loss.item()
''',
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return jnp.sum(x)

def run(x):
    return step(x).item()   # sync OUTSIDE the jit boundary is fine
''',
    ),
    "APX102": (
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    y = jnp.sum(x)
    print("loss:", y)
    return y
''',
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    y = jnp.sum(x)
    jax.debug.print("loss: {y}", y=y)
    return y
''',
    ),
    "APX103": (
        '''
import jax

def sample(key):
    a = jax.random.normal(key, (4,))
    b = jax.random.uniform(key, (4,))
    return a + b
''',
        '''
import jax

def sample(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.uniform(k2, (4,))
    return a + b
''',
    ),
    "APX104": (
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    s = jnp.sum(x)
    if s > 0:
        return s
    return -s
''',
        '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnums=(1,))
def step(x, causal):
    s = jnp.sum(x)
    if causal:               # static flag — fine
        s = s * 2
    return jnp.where(s > 0, s, -s)
''',
    ),
    "APX105": (
        '''
import jax

@jax.jit
def train_step(state, batch):
    return state, batch
''',
        '''
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    return state, batch
''',
    ),
    "APX106": (
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    acc = jnp.zeros(x.shape)
    return x + acc
''',
        '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    acc = jnp.zeros(x.shape, dtype=x.dtype)
    return x + acc
''',
    ),
    "APX107": (
        '''
import jax

@jax.jit
def reduce_grads(g):
    return jax.lax.psum(g, "data")
''',
        '''
import jax

from apex_tpu.transformer.parallel_state import DATA_AXIS

@jax.jit
def reduce_grads(g):
    return jax.lax.psum(g, DATA_AXIS)
''',
    ),
    "APX108": (
        '''
import os

_ENV = "APEX_TPU_SECRET_TUNING_KNOB"

def crossover():
    return int(os.environ.get(_ENV, "4096"))
''',
        '''
import os

# registered in apex_tpu.analysis.env_registry (and the README table)
_ENV = "APEX_TPU_ATTN_XLA_MAX_SEQ"

def crossover():
    return int(os.environ.get(_ENV, "256"))
''',
    ),
    "APX110": (
        '''
import time

import jax

step = jax.jit(lambda s, b: s + b)

def run(state, batches):
    for batch in batches:
        t0 = time.perf_counter()
        state = step(state, batch)
        dt = time.perf_counter() - t0     # measures DISPATCH, not step
    return state, dt
''',
        '''
import jax

from apex_tpu.observability import StepTimer

step = jax.jit(lambda s, b: s + b)

def run(state, batches):
    timer = StepTimer()                   # dispatch-aware: reports the
    for batch in batches:                 # compile delta, flags recompiles
        with timer.time_step():
            state = step(state, batch)
    return state, timer.last.seconds
''',
    ),
    "APX111": (
        '''
import jax
from jax.experimental import pallas as pl

def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2

def scale(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True,
    )(x)
''',
        '''
import jax
from jax.experimental import pallas as pl

from apex_tpu.utils import interpret_mode

def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2

def scale(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret_mode(),   # the APEX_TPU_INTERPRET knob
    )(x)
''',
    ),
    "APX112": (
        '''
def force_reclaim(scheduler, pages):
    # reach into the allocator's books to "free" pages directly
    for p in pages:
        scheduler.alloc._refs.pop(p, None)
        scheduler.alloc._free.append(p)
    scheduler.prefix._clock = 0
''',
        '''
def force_reclaim(scheduler, n):
    # go through the owner's public transitions; observe via snapshot()
    freed = scheduler.prefix.evict_lru(n)
    return freed, scheduler.alloc.snapshot()
''',
    ),
    "APX109": (
        '''
import jax

from apex_tpu.transformer.parallel_state import PIPE_AXIS

@jax.jit
def sync_embedding_grads(g):
    if jax.process_index() == 0:
        g = jax.lax.psum(g, PIPE_AXIS)
    return g
''',
        '''
import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPE_AXIS

@jax.jit
def sync_embedding_grads(g, member):
    # masked collective EVERY rank enters — no divergent branch
    return jax.lax.psum(jnp.where(member, g, jnp.zeros_like(g)),
                        PIPE_AXIS)
''',
    ),
}


def rules_of(src):
    return {f.rule for f in lint_source(src, "fixture.py")}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fires_on_violation(rule):
    bad, _ = FIXTURES[rule]
    assert rule in rules_of(bad), f"{rule} did not fire on its fixture"


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_quiet_on_clean(rule):
    _, good = FIXTURES[rule]
    fired = rules_of(good)
    assert rule not in fired, f"{rule} fired on the clean fixture: {fired}"


def test_clean_fixtures_fully_clean():
    # the corrected twins must not trip ANY rule, not just their own
    for rule, (_, good) in FIXTURES.items():
        assert rules_of(good) == set(), \
            f"clean fixture for {rule} trips {rules_of(good)}"


# --- engine behaviours ------------------------------------------------------

def test_apx110_ignores_clocks_in_nested_scopes():
    """A clock read inside a nested helper cannot close a timing
    bracket in the enclosing function — no cross-scope false
    positive."""
    src = '''
import time

import jax

step = jax.jit(lambda s, b: s + b)

def run(state, batch):
    t0 = time.perf_counter()        # host timing of non-jit work
    state = step(state, batch)

    def helper():                   # separate scope: not a bracket
        return time.perf_counter()

    return state, helper
'''
    assert "APX110" not in rules_of(src)


def test_syntax_error_is_a_finding():
    fs = lint_source("def broken(:\n", "broken.py")
    assert [f.rule for f in fs] == ["APX000"]


def test_inline_suppression():
    src = FIXTURES["APX102"][0].replace(
        'print("loss:", y)',
        'print("loss:", y)  # apex-lint: disable=APX102')
    assert "APX102" not in rules_of(src)


def test_skip_file_marker():
    src = "# apex-lint: skip-file\n" + FIXTURES["APX101"][0]
    assert lint_source(src, "skipped.py") == []


def test_jit_wrap_form_detected():
    # f = jax.jit(f) after the def, not a decorator
    src = '''
import jax
import jax.numpy as jnp

def step(x):
    return jnp.sum(x).item()

step = jax.jit(step)
'''
    assert "APX101" in rules_of(src)


def test_shard_map_body_is_traced():
    src = '''
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

def run(mesh, x):
    def body(x):
        print("inside", x)
        return x
    return jax.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P())(x)
'''
    assert "APX102" in rules_of(src)


def test_partial_bound_kernel_flags_are_static():
    # functools.partial(kernel, eps, rms) binds static Python values —
    # branching on them inside a pallas kernel is fine
    src = '''
import functools
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _kernel(eps, rms, x_ref, o_ref):
    x = x_ref[...]
    if rms:
        o_ref[...] = x * eps
    else:
        o_ref[...] = x + eps

def norm(x, eps, rms):
    return pl.pallas_call(
        functools.partial(_kernel, eps, rms),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
'''
    assert "APX104" not in rules_of(src)


def test_augassign_does_not_launder_traced_names():
    # acc += 1 keeps acc traced — the target is also an operand
    src = '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    acc = jnp.sum(x)
    acc += 1
    if acc > 0:
        return acc
    return -acc
'''
    assert "APX104" in rules_of(src)


def test_is_none_branch_not_flagged():
    src = '''
import jax
import jax.numpy as jnp

@jax.jit
def step(x, mask):
    if mask is None:
        return jnp.sum(x)
    return jnp.sum(x * mask)
'''
    assert "APX104" not in rules_of(src)


def test_key_reuse_across_loop_iterations():
    src = '''
import jax

def sample(key, n):
    out = []
    for _ in range(n):
        out.append(jax.random.normal(key, (4,)))
    return out
'''
    assert "APX103" in rules_of(src)


def test_key_rebound_in_loop_is_clean():
    src = '''
import jax

def sample(key, n):
    out = []
    for _ in range(n):
        key, sub = jax.random.split(key)
        out.append(jax.random.normal(sub, (4,)))
    return out
'''
    assert "APX103" not in rules_of(src)


def test_key_use_in_disjoint_branches_is_clean():
    src = '''
import jax

def sample(key, flag):
    if flag:
        return jax.random.normal(key, (4,))
    else:
        return jax.random.uniform(key, (4,))
'''
    assert "APX103" not in rules_of(src)


def test_fingerprint_stable_under_line_shift():
    bad, _ = FIXTURES["APX101"]
    f1 = [f for f in lint_source(bad, "m.py") if f.rule == "APX101"]
    f2 = [f for f in lint_source("# pad\n# pad\n" + bad, "m.py")
          if f.rule == "APX101"]
    assert f1 and f2
    assert f1[0].fingerprint == f2[0].fingerprint
    assert f1[0].line != f2[0].line
