"""Seeded-violation tests for the Pallas kernel VMEM auditor: every
check class (APX301–APX305) must actually FIRE on a known-bad kernel
and stay quiet on the corrected twin — the kernel-audit equivalent of
the lint fixture pairs and the SPMD seeded-executable tests."""
import os
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.analysis.pallas_audit import (KernelOpSpec,
                                            audit_kernel_op,
                                            check_kernel_record,
                                            compare_kernel_budget,
                                            extract_kernels,
                                            run_kernel_audit)
from apex_tpu.chip_specs import CHIP_SPECS

V5E = CHIP_SPECS["v5e"]


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _records(fn, *args):
    return extract_kernels(jax.make_jaxpr(fn)(*args))


def _check(rec, meta):
    return check_kernel_record(rec, meta, V5E, "seeded", "<seeded>")


def _rules(findings):
    return [f.rule for f in findings]


def _spec(name, build):
    # a seeded op: no real module behind it, so no PALLAS_AUDIT
    # declarations resolve (meta == {})
    return KernelOpSpec(name, "<seeded>", "tests._no_such_module", build)


# --- APX301: VMEM envelope ---------------------------------------------------

def test_oversized_block_fires_apx301():
    # one whole-array fp32 block of 8192x8192 = 256 MiB > v5e's 128 MiB
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
    (rec,) = _records(fn, jax.ShapeDtypeStruct((8192, 8192),
                                               jnp.float32))
    assert rec.vmem_bytes > V5E.vmem_bytes
    f = _check(rec, {})
    assert "APX301" in _rules(f), _rules(f)


def test_small_block_clean():
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
    (rec,) = _records(fn, jax.ShapeDtypeStruct((128, 128), jnp.float32))
    assert _check(rec, {}) == []


# --- APX302: reduction accumulator must be fp32 ------------------------------

def _scratch_fn(dtype):
    def fn(x):
        return pl.pallas_call(
            _copy_kernel_with_scratch,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=0,
                grid=(2,),
                in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda i: (i, 0)),
                scratch_shapes=[pltpu.VMEM((64, 128), dtype)],
            ),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
    return fn


def _copy_kernel_with_scratch(x_ref, o_ref, acc_ref):
    acc_ref[...] = x_ref[...].astype(acc_ref.dtype)
    o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def test_bf16_accumulator_scratch_fires_apx302():
    (rec,) = _records(_scratch_fn(jnp.bfloat16),
                      jax.ShapeDtypeStruct((128, 128), jnp.bfloat16))
    meta = {rec.kernel: {"reduction": True}}
    f = _check(rec, meta)
    assert "APX302" in _rules(f), _rules(f)


def test_fp32_accumulator_scratch_clean():
    (rec,) = _records(_scratch_fn(jnp.float32),
                      jax.ShapeDtypeStruct((128, 128), jnp.bfloat16))
    meta = {rec.kernel: {"reduction": True}}
    assert _check(rec, meta) == [], _rules(_check(rec, meta))


def test_revisited_bf16_output_block_fires_apx302():
    # constant index map on the OUTPUT: every grid step lands on the
    # same block — a bf16 accumulated output loses the reduction
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((64, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((64, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((64, 128), jnp.bfloat16),
        )(x)
    (rec,) = _records(fn, jax.ShapeDtypeStruct((128, 128),
                                               jnp.bfloat16))
    meta = {rec.kernel: {"reduction": True}}
    assert "APX302" in _rules(_check(rec, meta))
    # the same kernel NOT declared a reduction is quiet
    assert _check(rec, {}) == []


# --- APX303: grid/BlockSpec divisibility -------------------------------------

def _nondividing_fn():
    def fn(x):
        return pl.pallas_call(
            _copy_kernel,
            grid=(2,),
            in_specs=[pl.BlockSpec((48, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((48, 128), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )(x)
    return fn


def test_nondividing_block_fires_apx303():
    # block rows 48 do not divide the 80-row operand: the last step
    # hangs 16 rows past the edge
    (rec,) = _records(_nondividing_fn(),
                      jax.ShapeDtypeStruct((80, 128), jnp.float32))
    f = _check(rec, {})
    assert "APX303" in _rules(f), _rules(f)


def test_masked_tail_declaration_silences_apx303():
    (rec,) = _records(_nondividing_fn(),
                      jax.ShapeDtypeStruct((80, 128), jnp.float32))
    meta = {rec.kernel: {"masked_tail": True}}
    assert _check(rec, meta) == []


def test_dividing_block_clean():
    (rec,) = _records(_nondividing_fn(),
                      jax.ShapeDtypeStruct((96, 128), jnp.float32))
    assert _check(rec, {}) == []


# --- APX304: traced value in a BlockSpec index map ---------------------------

def test_traced_index_map_fires_apx304():
    # the block offset depends on a TRACED operand — jax itself rejects
    # this at trace time; the auditor classifies the failure as APX304
    # rather than a generic APX300
    def build():
        def fn(x, i):
            return pl.pallas_call(
                _copy_kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((64, 128), lambda j: (i, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda j: (j, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), x.dtype),
            )(x)
        return fn, (jax.ShapeDtypeStruct((128, 128), jnp.float32),
                    jax.ShapeDtypeStruct((), jnp.int32))

    f, entry = audit_kernel_op(_spec("seeded_traced_map", build))
    assert entry is None
    assert _rules(f) == ["APX304"], _rules(f)


def test_captured_constant_in_index_map_fires_apx304():
    # a CONCRETE closure capture is rejected by jax the same way
    # ("must not capture constants") — classified APX304, not APX300
    table = jnp.zeros((), jnp.int32)

    def build():
        def fn(x):
            return pl.pallas_call(
                _copy_kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((64, 128),
                                       lambda j: (table, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda j: (j, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), x.dtype),
            )(x)
        return fn, (jax.ShapeDtypeStruct((128, 128), jnp.float32),)

    f, entry = audit_kernel_op(_spec("seeded_const_map", build))
    assert entry is None
    assert _rules(f) == ["APX304"], _rules(f)


def test_record_level_captured_index_map_fires_apx304():
    # the record-level branch (synthetic record: a capture that slipped
    # past the trace-time gate, e.g. a future jax relaxing it)
    from apex_tpu.analysis.pallas_audit import BlockRecord, KernelRecord
    b = BlockRecord(role="in", block_shape=(64, 128),
                    full_shape=(128, 128), dtype="float32",
                    block_bytes=64 * 128 * 4, constant=False,
                    traced_consts=1, nondividing=())
    rec = KernelRecord("_k", (2,), 0, (b,), ())
    assert "APX304" in _rules(_check(rec, {}))


def test_grid_resolved_index_map_clean():
    def build():
        def fn(x):
            return pl.pallas_call(
                _copy_kernel,
                grid=(2,),
                in_specs=[pl.BlockSpec((64, 128), lambda j: (j, 0))],
                out_specs=pl.BlockSpec((64, 128), lambda j: (j, 0)),
                out_shape=jax.ShapeDtypeStruct((128, 128), x.dtype),
            )(x)
        return fn, (jax.ShapeDtypeStruct((128, 128), jnp.float32),)

    f, entry = audit_kernel_op(_spec("seeded_clean_map", build))
    assert f == [], _rules(f)
    assert entry is not None and len(entry["kernels"]) == 1


# --- APX300: trace failure is a finding, not a silent skip -------------------

def test_broken_fixture_fires_apx300():
    def build():
        def fn(x):
            raise TypeError("signature drifted")
        return fn, (jax.ShapeDtypeStruct((8,), jnp.float32),)

    f, entry = audit_kernel_op(_spec("seeded_broken", build))
    assert entry is None
    assert _rules(f) == ["APX300"], _rules(f)


# --- APX305: ledger completeness ---------------------------------------------

def _report(ops):
    return {"version": 1, "chip": "v5e",
            "vmem_capacity_bytes": V5E.vmem_bytes, "ops": ops}


def _entry(vmem=1024):
    return {"kernels": {"_k": {"grid": [1], "vmem_bytes": vmem,
                               "resident_bytes": 0, "scratch_bytes": 0,
                               "prefetch_bytes": 0, "blocks": 2}},
            "max_kernel_vmem_bytes": vmem}


def test_unbudgeted_op_fires_apx305():
    f = compare_kernel_budget(_report({"seeded": _entry()}), _report({}))
    assert _rules(f) == ["APX305"], _rules(f)
    assert "--write-budget" in f[0].message


def test_unbudgeted_kernel_fires_apx305():
    committed = _report({"seeded": _entry()})
    current = _report({"seeded": _entry()})
    current["ops"]["seeded"]["kernels"]["_k2"] = \
        committed["ops"]["seeded"]["kernels"]["_k"]
    f = compare_kernel_budget(current, committed)
    assert _rules(f) == ["APX305"], _rules(f)


def test_budget_growth_fires_apx301():
    committed = _report({"seeded": _entry(vmem=1024)})
    current = _report({"seeded": _entry(vmem=2048)})
    f = compare_kernel_budget(current, committed)
    assert _rules(f) == ["APX301"], _rules(f)
    assert "grew" in f[0].message


def test_matching_budget_clean():
    r = _report({"seeded": _entry()})
    assert compare_kernel_budget(r, r) == []
    # shrinkage is silent too (re-pin consciously, don't fail CI)
    leaner = _report({"seeded": _entry(vmem=512)})
    assert compare_kernel_budget(leaner, r) == []


# --- fast-lane sentinel: the real registry stays extractable -----------------

def test_registered_op_extracts_with_scratch_and_prefetch():
    # fused_block_decode is the load-bearing kernel: scalar-prefetch
    # operands (page table + lengths), fp32 scratch, resident weight
    # blocks — all four model terms must be live in its record
    f, report = run_kernel_audit(ops=["fused_block_decode"])
    assert f == [], _rules(f)
    (entry,) = report["ops"].values()
    (k,) = entry["kernels"].values()
    assert k["prefetch_bytes"] > 0
    assert k["scratch_bytes"] > 0
    assert k["resident_bytes"] > 0
    assert k["vmem_bytes"] >= (k["prefetch_bytes"] + k["scratch_bytes"]
                               + k["resident_bytes"])
