"""Jaxpr auditor: the shipped specs hold, and each invariant fires when
seeded with a violation."""
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.analysis.jaxpr_audit import (
    OpSpec, audit_op, op_specs, run_jaxpr_audit,
)


def test_all_public_ops_pass():
    assert run_jaxpr_audit() == []


def test_covers_at_least_five_ops():
    assert len(op_specs()) >= 5


@pytest.mark.parametrize("name", [s.name for s in op_specs()])
def test_each_op_passes_individually(name):
    assert run_jaxpr_audit([name]) == []


def test_unknown_op_rejected():
    with pytest.raises(ValueError, match="unknown op"):
        run_jaxpr_audit(["definitely_not_an_op"])


# --- seeded violations ------------------------------------------------------

def _spec(fn, args, out_dtypes=None, budget=0, name="seeded"):
    return OpSpec(name, "tests/seeded.py", lambda: (fn, args),
                  out_dtypes, budget)


def test_upcast_violation_fires():
    # an fp32 constant multiplied into a bf16 value: the convert feeds
    # mul (not an accumulator) — exactly the silent-promotion hazard
    def bad(x):
        c = jnp.asarray(1.5, dtype=jnp.float32)
        return (x.astype(jnp.float32) * c).astype(jnp.bfloat16)

    x = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    fs = audit_op(_spec(bad, (x,), budget=0))
    assert [f.rule for f in fs] == ["APX201"]


def test_accumulator_upcast_is_allowed():
    # upcast feeding a reduction is the sanctioned fp32-accumulate
    def good(x):
        return jnp.sum(x.astype(jnp.float32)).astype(jnp.bfloat16)

    x = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    assert audit_op(_spec(good, (x,), budget=0)) == []


def test_host_callback_violation_fires():
    def bad(x):
        jax.debug.print("x0 {v}", v=x[0, 0])
        return x * 2

    x = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    fs = audit_op(_spec(bad, (x,), budget=None))
    assert [f.rule for f in fs] == ["APX202"]


def test_output_dtype_violation_fires():
    def bad(x):
        return x.astype(jnp.float32)   # policy says bf16 out

    x = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    fs = audit_op(_spec(bad, (x,), out_dtypes=("bfloat16",), budget=None))
    assert [f.rule for f in fs] == ["APX203"]


def test_trace_failure_fires():
    def bad(x):
        raise RuntimeError("signature drifted")

    x = jax.ShapeDtypeStruct((8, 128), jnp.bfloat16)
    fs = audit_op(_spec(bad, (x,), budget=None))
    assert [f.rule for f in fs] == ["APX200"]


def test_layer_norm_budget_is_tight():
    # the committed budget equals the measured entry upcasts — one MORE
    # unexplained upcast in the kernel must fail the audit
    spec = next(s for s in op_specs() if s.name == "layer_norm")
    tight = OpSpec(spec.name, spec.path, spec.build, spec.out_dtypes,
                   spec.upcast_budget - 1)
    assert [f.rule for f in audit_op(tight)] == ["APX201"]
