"""Seeded-violation tests for the SPMD soundness auditor: every check
class must actually FIRE on a known-bad executable and stay quiet on
the corrected twin — the auditor equivalent of the lint fixture pairs.
"""
import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.analysis.spmd_audit import (ExecSpec, _audit_exec,
                                          compare_budget, run_spmd_audit)
from apex_tpu.analysis.comm_model import (comm_report, peak_live_bytes,
                                          ring_allreduce_bytes)

shard_map = functools.partial(jax.shard_map, check_vma=False)


def _mesh(n=2, axis="data"):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def _spec(name, fn, args, axes, **kw):
    return ExecSpec(name, "<seeded>", lambda: (fn, args, axes), **kw)


def _rules(findings):
    return [f.rule for f in findings]


# --- APX211: collective on a non-canonical axis -----------------------------

def test_axis_mismatch_fires():
    mesh = _mesh(axis="datum")  # not a parallel_state axis
    fn = shard_map(lambda x: jax.lax.psum(x, "datum"), mesh=mesh,
                   in_specs=(P("datum"),), out_specs=P())
    f, _ = _audit_exec(_spec("seeded_axis", fn,
                             (jnp.ones((8, 4)),), {"datum": 2}))
    assert "APX211" in _rules(f), _rules(f)


def test_canonical_axis_is_clean():
    mesh = _mesh()
    fn = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                   in_specs=(P("data"),), out_specs=P())
    f, _ = _audit_exec(_spec("clean_axis", fn,
                             (jnp.ones((8, 4)),), {"data": 2}))
    assert f == [], _rules(f)


# --- APX212: cond branches with mismatched collective multisets -------------

def test_branch_collective_mismatch_fires():
    mesh = _mesh()

    def body(x, flag):
        return jax.lax.cond(flag > 0,
                            lambda: jax.lax.psum(x, "data"),
                            lambda: x * 2.0)

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"), P()),
                   out_specs=P("data"))
    f, _ = _audit_exec(_spec("seeded_branch", fn,
                             (jnp.ones((8, 4)), jnp.float32(1.0)),
                             {"data": 2}))
    assert "APX212" in _rules(f), _rules(f)


def test_matching_branch_collectives_clean():
    mesh = _mesh()

    def body(x, flag):
        return jax.lax.cond(flag > 0,
                            lambda: jax.lax.psum(x * 2.0, "data"),
                            lambda: jax.lax.psum(x, "data"))

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"), P()),
                   out_specs=P())
    f, _ = _audit_exec(_spec("clean_branch", fn,
                             (jnp.ones((8, 4)), jnp.float32(1.0)),
                             {"data": 2}))
    assert f == [], _rules(f)


# --- APX213: rank-varying control values ------------------------------------

def test_varying_cond_predicate_over_collective_branches_fires():
    mesh = _mesh()

    def body(x):
        # predicate derives from the rank-local shard, branches carry a
        # collective: the classic divergent-entry deadlock
        return jax.lax.cond(jnp.sum(x) > 0,
                            lambda: jax.lax.psum(x, "data"),
                            lambda: jax.lax.psum(x * 2.0, "data"))

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    f, _ = _audit_exec(_spec("seeded_pred", fn,
                             (jnp.ones((8, 4)),), {"data": 2}))
    assert "APX213" in _rules(f), _rules(f)


def test_pmaxed_predicate_is_clean():
    mesh = _mesh()

    def body(x):
        uniform = jax.lax.pmax(jnp.sum(x), "data")
        return jax.lax.cond(uniform > 0,
                            lambda: jax.lax.psum(x, "data"),
                            lambda: jax.lax.psum(x * 2.0, "data"))

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P())
    f, _ = _audit_exec(_spec("clean_pred", fn,
                             (jnp.ones((8, 4)),), {"data": 2}))
    assert f == [], _rules(f)


def test_non_uniform_noop_flag_fires():
    """The PR 3 invariant, seeded broken: found_inf from the LOCAL grad
    shard feeds the fused update kernel without the pmax — each rank
    would skip (or not) alone and the masters diverge."""
    from apex_tpu.ops.fused_update import fused_adam_flat, fused_scale
    mesh = _mesh()
    n = 512

    def body(p, g, m, v):
        g, flag = fused_scale(g, 1.0 / 65536.0)   # rank-local overflow flag
        return fused_adam_flat(p, g, m, v, lr=1e-3, beta1=0.9,
                               beta2=0.999, eps=1e-8, weight_decay=0.0,
                               step=1, noop_flag=flag)

    args = tuple(jnp.ones((n,), jnp.float32) for _ in range(4))
    fn = shard_map(body, mesh=mesh, in_specs=(P(), P("data"), P(), P()),
                   out_specs=(P(), P(), P()))
    f, _ = _audit_exec(_spec("seeded_noop", fn, args, {"data": 2},
                             check_update_uniformity=True))
    assert "APX213" in _rules(f), _rules(f)
    assert any("noop_flag" in x.message or "update kernel" in x.message
               for x in f if x.rule == "APX213")


def test_pmaxed_noop_flag_is_clean():
    from apex_tpu.ops.fused_update import fused_adam_flat, fused_scale
    mesh = _mesh()
    n = 512

    def body(p, g, m, v):
        g, flag = fused_scale(g, 1.0 / 65536.0)
        flag = jax.lax.pmax(flag, "data")          # replica-uniform
        return fused_adam_flat(p, g, m, v, lr=1e-3, beta1=0.9,
                               beta2=0.999, eps=1e-8, weight_decay=0.0,
                               step=1, noop_flag=flag)

    args = tuple(jnp.ones((n,), jnp.float32) for _ in range(4))
    fn = shard_map(body, mesh=mesh, in_specs=(P(), P("data"), P(), P()),
                   out_specs=(P(), P(), P()))
    f, _ = _audit_exec(_spec("clean_noop", fn, args, {"data": 2},
                             check_update_uniformity=True))
    assert f == [], [(x.rule, x.message) for x in f]


# --- APX214: donation verification ------------------------------------------

def test_unaliasable_donation_fires():
    # the donated fp32 buffer comes back bf16: XLA cannot alias it and
    # the old buffer stays live — donation silently defeated
    def step(state, batch):
        return (state * 2.0).astype(jnp.bfloat16), jnp.sum(batch)

    f, _ = _audit_exec(_spec("seeded_alias",
                             step, (jnp.ones((1024,), jnp.float32),
                                    jnp.ones((4,))), {},
                             donate_argnums=(0,)))
    assert "APX214" in _rules(f), _rules(f)
    assert any("matches NO output" in x.message for x in f)


def test_missing_donation_on_matching_buffer_fires():
    def step(state, batch):
        return state * 2.0, jnp.sum(batch)

    f, _ = _audit_exec(_spec("seeded_undonated",
                             step, (jnp.ones((1024,), jnp.float32),
                                    jnp.ones((4,))), {},
                             donate_argnums=(), flag_undonated=True))
    assert "APX214" in _rules(f), _rules(f)
    assert any("undonated" in x.message for x in f)


def test_donated_step_is_clean():
    def step(state, batch):
        return state * 2.0, jnp.sum(batch)

    f, _ = _audit_exec(_spec("clean_donated",
                             step, (jnp.ones((1024,), jnp.float32),
                                    jnp.ones((4,))), {},
                             donate_argnums=(0,), flag_undonated=True))
    assert f == [], [(x.rule, x.message) for x in f]


# --- APX215: budget ratchet --------------------------------------------------

def _compiled(peak_drift=1.5, flops_drift=0.5,
              provenance="xla:cost+memory"):
    return {"provenance": provenance, "flops": 1000,
            "peak_hbm_bytes": 6000, "dot_flops_estimate": 500,
            "dot_flops_drift": flops_drift,
            "peak_live_drift": peak_drift}


def _entry(comm, peak, compiled=None):
    return {"comm_bytes": comm, "by_collective": {"psum@data": comm},
            "collective_counts": {"psum@data": 1},
            "peak_live_bytes": peak, "axes": {"data": 2},
            "compiled": _compiled() if compiled is None else compiled}


def test_budget_growth_fires():
    report = {"version": 1, "executables": {"ddp_allreduce":
                                            _entry(2048, 9000)}}
    committed = {"version": 1, "executables": {"ddp_allreduce":
                                               _entry(1024, 9000)}}
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX215"] and "grew" in f[0].message


def test_peak_growth_fires_and_equal_is_clean():
    committed = {"version": 1, "executables": {"ddp_allreduce":
                                               _entry(1024, 9000)}}
    grown = {"version": 1, "executables": {"ddp_allreduce":
                                           _entry(1024, 9001)}}
    assert _rules(compare_budget(grown, committed)) == ["APX215"]
    same = {"version": 1, "executables": {"ddp_allreduce":
                                          _entry(1024, 9000)}}
    assert compare_budget(same, committed) == []
    # shrinkage is silent (re-pin at leisure)
    small = {"version": 1, "executables": {"ddp_allreduce":
                                           _entry(512, 8000)}}
    assert compare_budget(small, committed) == []


def test_unbudgeted_executable_fires():
    report = {"version": 1, "executables": {"ddp_allreduce":
                                            _entry(1024, 9000)}}
    f = compare_budget(report, {"version": 1, "executables": {}})
    assert _rules(f) == ["APX215"] and "no committed budget" in f[0].message


# --- APX218: compiled-truth attribution + drift ratchet ---------------------

def _budgets(cur_compiled, pinned_compiled):
    report = {"version": 1, "executables": {
        "ddp_allreduce": _entry(1024, 9000, compiled=cur_compiled)}}
    committed = {"version": 1, "executables": {
        "ddp_allreduce": _entry(1024, 9000, compiled=pinned_compiled)}}
    return report, committed


def test_apx218_missing_attribution_fires():
    report = {"version": 1, "executables": {"ddp_allreduce": {
        k: v for k, v in _entry(1024, 9000).items() if k != "compiled"}}}
    committed = {"version": 1, "executables": {"ddp_allreduce":
                                               _entry(1024, 9000)}}
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"]
    assert "no compiled-stats attribution" in f[0].message


def test_apx218_unpinned_compiled_entry_fires():
    report, committed = _budgets(_compiled(), None)
    del committed["executables"]["ddp_allreduce"]["compiled"]
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"]
    assert "no committed compiled-stats entry" in f[0].message


def test_apx218_drift_growth_fires_equal_and_shrunk_clean():
    # peak-live drift moved further from 1 than the pinned band
    report, committed = _budgets(_compiled(peak_drift=2.0),
                                 _compiled(peak_drift=1.5))
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"] and "drifted further" in f[0].message
    # drift on the UNDER side counts the same distance: 1/2 vs 1.5
    report, committed = _budgets(_compiled(peak_drift=0.5),
                                 _compiled(peak_drift=1.5))
    assert _rules(compare_budget(report, committed)) == ["APX218"]
    # identical drift is clean (the bit-for-bit CI case)
    report, committed = _budgets(_compiled(), _compiled())
    assert compare_budget(report, committed) == []
    # drift moving TOWARD 1 is silent improvement
    report, committed = _budgets(_compiled(peak_drift=1.2),
                                 _compiled(peak_drift=1.5))
    assert compare_budget(report, committed) == []


def test_apx218_flops_drift_ratchets_too():
    report, committed = _budgets(_compiled(flops_drift=0.1),
                                 _compiled(flops_drift=0.5))
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"]
    assert "comm_model dot-FLOPs" in f[0].message


def test_apx218_lost_drift_ratio_fires():
    # provenance still full but the analytic estimate degenerated (a
    # Pallas rewrite zeroing jaxpr_dot_flops drops dot_flops_drift):
    # the ratchet must not lose its input silently
    lost = _compiled()
    del lost["dot_flops_drift"]
    report, committed = _budgets(lost, _compiled())
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"] and "vanished" in f[0].message


def test_apx218_degradation_marker_is_explicit_and_accepted():
    # a backend that NEVER had memory stats: pinned and current both
    # carry the marker — clean (the marker IS the attribution)
    marker = {"provenance": "unavailable:no-cost-analysis-on-this-"
                            "backend"}
    report, committed = _budgets(marker, marker)
    assert compare_budget(report, committed) == []


def test_apx218_silent_degradation_fires():
    # pinned full attribution, current suddenly unavailable: the
    # executable STOPPED compiling for stats — that is a regression
    marker = {"provenance": "unavailable:compile-failed:RuntimeError"}
    report, committed = _budgets(marker, _compiled())
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"] and "DEGRADED" in f[0].message


def test_apx218_full_to_cost_only_slide_fires():
    # the sneakier degradation: memory_analysis() lost but cost still
    # reporting — would silently disable the peak-live drift ratchet
    cost_only = {"provenance": "xla:cost-only:memory_analysis-"
                               "unavailable",
                 "flops": 1000, "dot_flops_estimate": 500,
                 "dot_flops_drift": 0.5}
    report, committed = _budgets(cost_only, _compiled())
    f = compare_budget(report, committed)
    assert _rules(f) == ["APX218"] and "DEGRADED" in f[0].message
    # cost-only pinned AND current: no degradation, flops drift still
    # ratchets
    report, committed = _budgets(cost_only, dict(cost_only))
    assert compare_budget(report, committed) == []
    # recovering upward (cost-only pinned, full current) is clean
    report, committed = _budgets(_compiled(), cost_only)
    assert compare_budget(report, committed) == []


def test_audit_entry_always_carries_compiled_attribution():
    """The auditor itself must attribute or mark — a real executable's
    fresh entry carries a compiled block with provenance + drift."""
    mesh = _mesh()
    fn = shard_map(lambda x: jax.lax.psum(x @ x.T, "data"), mesh=mesh,
                   in_specs=(P("data"),), out_specs=P())
    f, entry = _audit_exec(_spec("seeded_compiled", fn,
                                 (jnp.ones((8, 4)),), {"data": 2}))
    assert f == [], _rules(f)
    comp = entry["compiled"]
    assert comp["provenance"].startswith(("xla:", "unavailable:"))
    assert comp["dot_flops_estimate"] > 0
    if comp["provenance"] == "xla:cost+memory":
        assert comp["flops"] > 0 and comp["peak_hbm_bytes"] > 0
        assert comp["peak_live_drift"] > 0


# --- APX216: the ZeRO RS+AG==AR machine check -------------------------------

def test_rs_ag_identity_violation_fires():
    # all-gather with NO reduce-scatter half: the PERF.md round-6
    # regression shape (split-instead-of-reduce-scatter)
    mesh = _mesh()
    fn = shard_map(
        lambda x: jax.lax.all_gather(x, "data", axis=0, tiled=True),
        mesh=mesh, in_specs=(P("data"),), out_specs=P())
    f, entry = _audit_exec(_spec("seeded_identity", fn,
                                 (jnp.ones((512,), jnp.float32),),
                                 {"data": 2}, rs_ag_identity=True))
    assert "APX216" in _rules(f), _rules(f)
    assert entry["rs_ag_equals_ar"] is False


def test_zero_step_satisfies_identity():
    findings, report = run_spmd_audit(execs=["train_step_zero"])
    assert findings == [], [(f.rule, f.message) for f in findings]
    entry = report["executables"]["train_step_zero"]
    assert entry["rs_ag_equals_ar"] is True
    by = entry["by_collective"]
    ag = sum(v for k, v in by.items() if k.startswith("all_gather@"))
    rs = sum(v for k, v in by.items() if k.startswith("reduce_scatter@"))
    # RS + AG == the ring all-reduce of the same flat buffer
    dp = entry["axes"]["data"]
    full_bytes = rs * dp // (dp - 1)
    assert ag + rs == ring_allreduce_bytes(dp, full_bytes)


# --- comm model arithmetic ---------------------------------------------------

def test_comm_report_prices_ring_formulas():
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    n = 4
    payload = 1024 * 4  # [1024] f32

    def body(x):
        a = jax.lax.psum(x, "data")
        b = jax.lax.all_gather(x, "data", axis=0, tiled=True)
        c = jax.lax.psum_scatter(a, "data", scatter_dimension=0,
                                 tiled=True)
        return a, b, c

    fn = shard_map(body, mesh=mesh, in_specs=(P(),),
                   out_specs=(P(), P(None), P("data")))
    closed = jax.make_jaxpr(fn)(jnp.ones((1024,), jnp.float32))
    rep = comm_report(closed, {"data": n})
    by = rep["by_collective"]
    assert by["psum@data"] == 2 * (n - 1) * payload // n
    assert by["all_gather@data"] == (n - 1) * payload
    # psum_scatter traces as reduce_scatter
    rs = by.get("reduce_scatter@data", by.get("psum_scatter@data"))
    assert rs == (n - 1) * payload // n
    assert rep["total_bytes"] == sum(by.values())


def test_peak_live_bytes_tracks_temporaries():
    def small(x):
        return x + 1.0

    def big(x):
        t = jnp.concatenate([x, x, x, x])   # 4x temporary
        return t[: x.shape[0]] + 1.0

    n = 1024
    x = jnp.ones((n,), jnp.float32)
    p_small = peak_live_bytes(jax.make_jaxpr(small)(x).jaxpr)
    p_big = peak_live_bytes(jax.make_jaxpr(big)(x).jaxpr)
    assert p_big >= p_small + 3 * n * 4


# --- APX217: comm/compute overlap on the COMPILED executable ----------------

def _zero_step_fn(prefetch, n_layers=6, d=8):
    """A small ZeRO train step over a 2-rank data mesh, monolithic
    (prefetch=0, the seeded violation) or layered-prefetch."""
    from apex_tpu import train_step
    from apex_tpu.optimizers import functional

    params = {}
    for i in range(n_layers):
        base = np.linspace(-0.3, 0.3, d * d, dtype=np.float32)
        params[f"w{i}"] = jnp.asarray(np.roll(base, i).reshape(d, d))
        params[f"b{i}"] = jnp.asarray(
            np.linspace(-0.01, 0.01, d, dtype=np.float32))

    def loss(p, batch):
        h = batch["x"]
        for i in range(n_layers):
            h = jnp.tanh(h @ p[f"w{i}"] + p[f"b{i}"])
        return jnp.mean((h - batch["y"]) ** 2)

    x = np.linspace(-1.0, 1.0, 8 * d, dtype=np.float32).reshape(8, d)
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(np.tanh(x))}
    tx = functional.fused_adam(lr=1e-2)
    mesh = _mesh()
    state, specs = train_step.init_zero_train_state(
        tx, params, "data", 2, loss_scale="dynamic", prefetch=prefetch)
    step = train_step.make_train_step(loss, tx, zero=True)
    fn = shard_map(step, mesh=mesh, in_specs=(specs, P()),
                   out_specs=(specs, P()))
    return fn, (state, batch)


def _apx217(fn, args, donate=()):
    from apex_tpu.analysis.spmd_audit import _check_async_overlap
    findings = []
    spec = _spec("seeded_overlap", fn, args, {"data": 2},
                 donate_argnums=donate, check_overlap=True)
    _check_async_overlap(spec, fn, args,
                         lambda rule, msg: findings.append((rule, msg)))
    return findings


def test_apx217_monolithic_gather_fires():
    """The deliberately serialized lowering: ONE param all-gather gates
    every layer and ONE reduce-scatter hangs off the whole backward —
    no substantial compute is schedulable during either, and APX217
    says so."""
    fn, args = _zero_step_fn(prefetch=0)
    findings = _apx217(fn, args, donate=(0,))
    assert [r for r, _ in findings] == ["APX217"], findings
    assert "dominant" in findings[0][1]


def test_apx217_prefetched_gather_clean():
    fn, args = _zero_step_fn(prefetch=6)
    assert _apx217(fn, args, donate=(0,)) == []


@pytest.fixture(autouse=True)
def _restore_parallel_state():
    """The seeded TP fixtures initialize a tp=2 topology; leaving it
    behind poisons later suites' audits."""
    yield
    from apex_tpu.transformer import parallel_state
    parallel_state.destroy_model_parallel()


def _tp_col_row_fn(chunks, tokens=4):
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.transformer import tensor_parallel

    ps.destroy_model_parallel()
    ps.initialize_model_parallel(tensor_model_parallel_size_=2)
    mesh = ps.get_mesh()
    col = tensor_parallel.ColumnParallelLinear(
        8, 16, gather_output=False, bias=False, overlap_chunks=chunks)
    row = tensor_parallel.RowParallelLinear(
        16, 8, input_is_parallel=True, bias=False,
        overlap_chunks=chunks)

    def body(x):
        pc = col.init(jax.random.key(0), x)
        h, _ = col.apply(pc, x)
        pr = row.init(jax.random.key(1), h)

        def loss(x):
            h, _ = col.apply(pc, x)
            y, _ = row.apply(pr, h)
            return jnp.mean(y ** 2)

        return jax.value_and_grad(loss)(x)

    fn = shard_map(body, mesh=mesh, in_specs=(P(),),
                   out_specs=(P(), P()))
    x = jnp.asarray(np.linspace(-1, 1, tokens * 8,
                                dtype=np.float32).reshape(tokens, 8))
    return fn, (x,)


def test_apx217_fused_tp_psum_fires():
    """chunks=1 keeps the monolithic matmul-then-psum: only the classic
    wgrad dot can hide under the backward all-reduce (exactly half the
    dominant collectives) — below APX217's strict-majority pipeline
    bar."""
    fn, args = _tp_col_row_fn(chunks=1)
    findings = _apx217(fn, args)
    assert [r for r, _ in findings] == ["APX217"], findings


def test_apx217_chunked_tp_ring_clean():
    fn, args = _tp_col_row_fn(chunks=4)
    assert _apx217(fn, args) == []


_HLO_ASYNC = """HloModule m

ENTRY %main (p0: f32[1024]) -> f32[2048] {
  %p0 = f32[1024]{0} parameter(0)
  %ags = (f32[1024]{0}, f32[2048]{0}) all-gather-start(%p0), dimensions={0}
  @WITNESS@
  %agd = f32[2048]{0} all-gather-done(%ags)
  ROOT %out = f32[2048]{0} add(%agd, %agd)
}
"""


def test_apx217_async_route_requires_substantial_witness():
    """The async (real-TPU) route applies the same witness-size floor
    as the sync route: a scalar bookkeeping op scheduled between
    start and done does not count as hiding the collective, while a
    payload-sized compute op does.  Canned HLO text because the forced
    CPU host devices this suite runs on only produce sync lowerings."""
    from apex_tpu.analysis.spmd_audit import _overlap_findings_from_hlo

    def run(witness):
        findings = []
        _overlap_findings_from_hlo(
            "seeded_async", _HLO_ASYNC.replace("@WITNESS@", witness),
            lambda rule, msg: findings.append((rule, msg)))
        return findings

    serial = run("%wit = f32[] add(%p0, %p0)")
    assert [r for r, _ in serial] == ["APX217"], serial
    assert "async" in serial[0][1]
    assert run("%wit = f32[1024]{0} multiply(%p0, %p0)") == []


_HLO_ASYNC_GENERIC = """HloModule m

%rs_comp (p: f32[2048]) -> f32[1024] {
  %p = f32[2048]{0} parameter(0)
  ROOT %rs = f32[1024]{0} reduce-scatter(%p), dimensions={0}
}

ENTRY %main (p0: f32[2048]) -> f32[1024] {
  %p0 = f32[2048]{0} parameter(0)
  %rss = ((f32[2048]{0}), f32[1024]{0}, u32[]) async-start(%p0), calls=%rs_comp
  @WITNESS@
  %rsu = ((f32[2048]{0}), f32[1024]{0}, u32[]) async-update(%rss)
  %rsd = f32[1024]{0} async-done(%rsu)
  ROOT %out = f32[1024]{0} add(%rsd, %rsd)
}
"""


def test_apx217_generic_async_wrapper_recognized():
    """XLA asyncifies collectives without a dedicated fused opcode
    (reduce-scatter, all-to-all) through GENERIC ``async-start`` /
    ``async-update`` / ``async-done`` wrappers whose ``calls=``
    computation holds the collective — the async route must resolve
    those (NOT fall through to the sync route, which would see zero
    collectives and fire 'nothing to overlap' on a fully pipelined
    executable)."""
    from apex_tpu.analysis.spmd_audit import _overlap_findings_from_hlo

    def run(text):
        findings = []
        _overlap_findings_from_hlo(
            "seeded_generic_async", text,
            lambda rule, msg: findings.append((rule, msg)))
        return findings

    hidden = _HLO_ASYNC_GENERIC.replace(
        "@WITNESS@", "%wit = f32[1024]{0} multiply(%p0, %p0)")
    assert run(hidden) == []
    serial = run(_HLO_ASYNC_GENERIC.replace(
        "@WITNESS@", "%wit = f32[] add(%p0, %p0)"))
    assert [r for r, _ in serial] == ["APX217"], serial
    assert "async" in serial[0][1]


def test_apx217_parses_sigil_less_hlo_dumps():
    """Newer HLO printers drop the ``%`` name sigil; both canned
    modules must parse identically without it (instruction names,
    operand refs, and the calls= resolution all survive)."""
    from apex_tpu.analysis.spmd_audit import _overlap_findings_from_hlo

    def run(text):
        findings = []
        _overlap_findings_from_hlo(
            "seeded_sigil_less", text.replace("%", ""),
            lambda rule, msg: findings.append((rule, msg)))
        return findings

    for module in (_HLO_ASYNC, _HLO_ASYNC_GENERIC):
        hidden = module.replace(
            "@WITNESS@", "%wit = f32[1024]{0} multiply(%p0, %p0)")
        assert run(hidden) == [], module[:40]
        serial = run(module.replace(
            "@WITNESS@", "%wit = f32[] add(%p0, %p0)"))
        assert [r for r, _ in serial] == ["APX217"], serial


# --- overlap-aware step-time model ------------------------------------------

def test_step_time_estimate_overlap_vs_sequential():
    from apex_tpu.analysis.comm_model import step_time_estimate

    mesh = _mesh()
    m = 256

    def body(x, w):
        y = x @ w                                  # 2*m^3 FLOPs
        return jax.lax.psum(y, "data")

    fn = shard_map(body, mesh=mesh, in_specs=(P(), P()), out_specs=P())
    closed = jax.make_jaxpr(fn)(jnp.ones((m, m), jnp.float32),
                                jnp.ones((m, m), jnp.float32))
    est = step_time_estimate(closed, {"data": 2}, tflops=1.0,
                             ici_gbps=1.0)
    assert est["dot_flops"] == 2 * m ** 3
    assert est["comm_bytes"] == 2 * (2 - 1) * (m * m * 4) // 2
    # sequential = sum, overlap = max, exposed = the difference
    assert est["sequential_us"] == pytest.approx(
        est["compute_us"] + est["comm_us"], rel=1e-6)
    assert est["overlap_us"] == pytest.approx(
        max(est["compute_us"], est["comm_us"]), rel=1e-6)
    assert est["exposed_comm_us"] == pytest.approx(
        max(est["comm_us"] - est["compute_us"], 0.0), abs=1e-3)


def test_step_time_estimate_scales_scan_bodies():
    from apex_tpu.analysis.comm_model import step_time_estimate

    m, length = 64, 5

    def body(x):
        def step(c, _):
            return c @ x, None
        c, _ = jax.lax.scan(step, x, None, length=length)
        return c

    closed = jax.make_jaxpr(body)(jnp.ones((m, m), jnp.float32))
    est = step_time_estimate(closed, {})
    assert est["dot_flops"] == length * 2 * m ** 3
