"""DDP / SyncBatchNorm / LARC tests over an 8-device CPU mesh.

Mirrors the reference's ``tests/distributed/DDP`` +
``tests/distributed/synced_batchnorm`` (multi-process-on-one-host pattern →
single-process multi-device mesh, per SURVEY §4).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from apex_tpu.parallel import (
    DistributedDataParallel, LARC, SyncBatchNorm, flat_allreduce)
from apex_tpu.optimizers import FusedSGD


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


class TestDDP:
    @pytest.mark.parametrize("delay_allreduce", [False, True])
    @pytest.mark.parametrize("message_size", [10_000_000, 64])
    def test_reduce_gradients_averages(self, delay_allreduce, message_size):
        mesh = _mesh()
        ddp = DistributedDataParallel(message_size=message_size,
                                      delay_allreduce=delay_allreduce)
        grads = {"w": jnp.arange(8 * 6, dtype=jnp.float32).reshape(8, 6),
                 "b": jnp.ones((8, 2), jnp.float32)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False)
        def reduce(g):
            return ddp.reduce_gradients(g)

        out = reduce(grads)
        expect_w = np.broadcast_to(
            np.asarray(grads["w"]).mean(axis=0, keepdims=True), (8, 6))
        np.testing.assert_allclose(np.asarray(out["w"]), expect_w,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out["b"]), 1.0)

    def test_bucketing_matches_single_psum(self):
        mesh = _mesh()
        grads = {"w": jnp.asarray(
            np.random.RandomState(0).randn(8, 1000), jnp.float32)}

        def run(ddp):
            @functools.partial(
                shard_map, mesh=mesh, in_specs=(P("data"),),
                out_specs=P("data"), check_vma=False)
            def reduce(g):
                return ddp.reduce_gradients(g)
            return np.asarray(reduce(grads)["w"])

        one = run(DistributedDataParallel(delay_allreduce=True))
        bucketed = run(DistributedDataParallel(message_size=512))
        np.testing.assert_allclose(one, bucketed, rtol=1e-6)

    def test_predivide_factor(self):
        mesh = _mesh()
        ddp = DistributedDataParallel(gradient_predivide_factor=4.0)
        grads = {"w": jnp.ones((8, 4), jnp.float32)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False)
        def reduce(g):
            return ddp.reduce_gradients(g)

        # pre-divide by 4, psum (=8), post-multiply by 4/8 -> average = 1
        np.testing.assert_allclose(np.asarray(reduce(grads)["w"]), 1.0,
                                   rtol=1e-6)

    def test_allreduce_always_fp32_with_bf16_grads(self):
        mesh = _mesh()
        ddp = DistributedDataParallel(allreduce_always_fp32=True)
        grads = {"w": jnp.full((8, 4), 0.1, jnp.bfloat16)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False)
        def reduce(g):
            return ddp.reduce_gradients(g)

        out = reduce(grads)
        assert out["w"].dtype == jnp.bfloat16

    def test_flat_allreduce(self):
        mesh = _mesh()
        tree = {"a": jnp.ones((8, 3)), "b": jnp.full((8, 2), 2.0)}

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P("data"),),
            out_specs=P("data"), check_vma=False)
        def reduce(t):
            return flat_allreduce(t)

        out = reduce(tree)
        np.testing.assert_allclose(np.asarray(out["a"]), 8.0)
        np.testing.assert_allclose(np.asarray(out["b"]), 16.0)

    def test_ddp_grad_correctness_vs_single_process(self):
        """The reference's ddp_race_condition_test analog: grads computed
        with per-device batches + DDP reduce == full-batch grads."""
        mesh = _mesh()
        rng = np.random.RandomState(0)
        W = jnp.asarray(rng.randn(6, 3), jnp.float32)
        X = jnp.asarray(rng.randn(16, 6), jnp.float32)
        Y = jnp.asarray(rng.randn(16, 3), jnp.float32)
        ddp = DistributedDataParallel()

        def loss(w, x, y):
            return jnp.mean((x @ w - y) ** 2)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P("data"), P("data")),
            out_specs=P(), check_vma=False)
        def ddp_grads(w, x, y):
            g = jax.grad(loss)(w, x, y)
            return ddp.reduce_gradients(g)

        got = ddp_grads(W, X, Y)
        want = jax.grad(loss)(W, X, Y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)


class TestSyncBatchNorm:
    def test_stats_match_full_batch(self):
        """Two-process BN stat equality vs single-process (reference:
        tests/distributed/synced_batchnorm/unit_test.sh)."""
        mesh = _mesh()
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(16, 4, 4, 8), jnp.float32)  # NHWC
        bn = SyncBatchNorm(num_features=8)
        variables = bn.init(jax.random.key(0), x[:2])

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P("data"), check_vma=False)
        def sync_apply(vars_, xs):
            y, _ = bn.apply(vars_, xs, mutable=["batch_stats"])
            return y

        y_sync = sync_apply(variables, x)

        # oracle: plain full-batch BN
        mean = np.asarray(x).mean(axis=(0, 1, 2))
        var = np.asarray(x).var(axis=(0, 1, 2))
        want = (np.asarray(x) - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(np.asarray(y_sync), want, atol=1e-5)

    def test_running_stats_updated(self):
        x = jnp.asarray(np.random.RandomState(0).randn(8, 5, 5, 4),
                        jnp.float32)
        bn = SyncBatchNorm(num_features=4, axis_name=None)
        variables = bn.init(jax.random.key(0), x)
        _, updated = bn.apply(variables, x, mutable=["batch_stats"])
        rm = np.asarray(updated["batch_stats"]["running_mean"])
        assert not np.allclose(rm, 0.0)
        np.testing.assert_allclose(
            rm, 0.1 * np.asarray(x).mean(axis=(0, 1, 2)), atol=1e-6)

    def test_eval_uses_running_stats(self):
        x = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
        bn = SyncBatchNorm(num_features=4, axis_name=None)
        variables = bn.init(jax.random.key(0), x)
        y = bn.apply(variables, x, use_running_average=True)
        # running stats are (0, 1) at init -> identity modulo eps
        np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-4)

    def test_grads_flow(self):
        x = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
        bn = SyncBatchNorm(num_features=4, axis_name=None)
        variables = bn.init(jax.random.key(0), x)

        def loss(v):
            return jnp.sum(bn.apply(v, x, mutable=["batch_stats"])[0] ** 2)

        g = jax.grad(loss)(variables)
        assert float(jnp.sum(jnp.abs(
            g["params"]["weight"]))) > 0


class TestLARC:
    def test_larc_clips_effective_lr(self):
        params = {"w": jnp.asarray(
            np.random.RandomState(0).randn(32, 16) * 100, jnp.float32)}
        opt = LARC(FusedSGD(params, lr=0.1), trust_coefficient=0.001)
        g = {"w": jnp.asarray(np.random.RandomState(1).randn(32, 16),
                              jnp.float32)}
        out = opt.step(g)
        # LARC multiplier = min(trust*||p||/(||g||), 1); with big ||p|| it
        # would exceed 1 and must be clipped to plain SGD
        plain = FusedSGD(params, lr=0.1).step(g)
        np.testing.assert_allclose(np.asarray(out["w"]),
                                   np.asarray(plain["w"]), rtol=1e-6)

    def test_larc_scales_down(self):
        params = {"w": jnp.asarray(
            np.random.RandomState(0).randn(32, 16) * 0.001, jnp.float32)}
        opt = LARC(FusedSGD(params, lr=0.1), trust_coefficient=0.001)
        g = {"w": jnp.asarray(np.random.RandomState(1).randn(32, 16),
                              jnp.float32)}
        out = opt.step(g)
        plain = FusedSGD(params, lr=0.1).step(g)
        # tiny ||p|| -> multiplier << 1 -> much smaller update
        d_larc = np.abs(np.asarray(out["w"]) - np.asarray(params["w"])).mean()
        d_plain = np.abs(np.asarray(plain["w"]) -
                         np.asarray(params["w"])).mean()
        assert d_larc < d_plain * 0.1

    def test_state_dict_passthrough(self):
        params = {"w": jnp.ones((8, 8))}
        opt = LARC(FusedSGD(params, lr=0.1))
        sd = opt.state_dict()
        opt.load_state_dict(sd)

    @pytest.mark.parametrize("clip", [True, False])
    def test_vs_apex_larc_oracle(self, clip):
        """One step vs a numpy transcription of apex LARC + SGD."""
        rng = np.random.RandomState(0)
        lr, trust, wd = 0.1, 0.02, 0.01
        p0 = rng.randn(16, 8).astype(np.float32)
        g0 = rng.randn(16, 8).astype(np.float32)
        params = {"w": jnp.asarray(p0)}
        opt = LARC(FusedSGD(params, lr=lr, weight_decay=wd),
                   trust_coefficient=trust, clip=clip)
        out = opt.step({"w": jnp.asarray(g0)})

        pn = np.linalg.norm(p0)
        gn = np.linalg.norm(g0)
        adaptive = trust * pn / (gn + wd * pn + 1e-8)
        if clip:
            adaptive = min(adaptive / lr, 1.0)
        g_eff = (g0 + wd * p0) * adaptive   # wd folded, group wd zeroed
        want = p0 - lr * g_eff
        np.testing.assert_allclose(np.asarray(out["w"]), want, atol=1e-5)


class TestSyncBatchNormNumerics:
    def test_large_mean_small_variance(self):
        """E[x²]−mean² would produce NaN here; Welford merge must not."""
        rng = np.random.RandomState(0)
        x = jnp.asarray(1e4 + rng.randn(64, 8).astype(np.float32) * 1e-3)
        bn = SyncBatchNorm(num_features=8, axis_name=None)
        variables = bn.init(jax.random.key(0), x)
        y, _ = bn.apply(variables, x, mutable=["batch_stats"])
        assert np.isfinite(np.asarray(y)).all()
        # normalized output should have ~zero mean, ~unit variance
        assert abs(float(jnp.mean(y))) < 1e-2
