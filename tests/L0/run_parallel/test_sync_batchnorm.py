"""SyncBatchNorm tests.

Mirrors the reference's ``tests/distributed/synced_batchnorm/`` pattern:
stats computed across the data axis must equal single-device stats on the
concatenated batch; plus the torch-module conversion contract
(``apex.parallel.convert_syncbn_model``).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import SyncBatchNorm, convert_syncbn_model


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def test_synced_stats_match_global_batch():
    n_dev, b, h, w, c = 4, 2, 4, 4, 8
    x = jax.random.normal(jax.random.PRNGKey(0), (n_dev * b, h, w, c))
    bn = SyncBatchNorm(num_features=c, axis_name="data")
    vars_ = bn.init(jax.random.PRNGKey(1), x[:b])

    mesh = _mesh(n_dev)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P("data"),),
        out_specs=(P("data"), P()), check_vma=False)
    def run(xs):
        y, new_vars = bn.apply(vars_, xs, mutable=["batch_stats"])
        return y, new_vars["batch_stats"]

    y_sync, stats_sync = run(x)

    # single-device oracle: same module with no axis over the full batch
    bn1 = SyncBatchNorm(num_features=c, axis_name=None)
    y_ref, vars_ref = bn1.apply(vars_, x, mutable=["batch_stats"])
    np.testing.assert_allclose(y_sync, y_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(stats_sync["running_mean"],
                               vars_ref["batch_stats"]["running_mean"],
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(stats_sync["running_var"],
                               vars_ref["batch_stats"]["running_var"],
                               atol=1e-5, rtol=1e-5)


def test_eval_uses_running_stats():
    c = 8
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 4, 4, c))
    bn = SyncBatchNorm(num_features=c, axis_name=None)
    vars_ = bn.init(jax.random.PRNGKey(3), x)
    y = bn.apply(vars_, x, use_running_average=True)
    # fresh stats: mean 0 var 1 -> identity modulo eps and affine init
    np.testing.assert_allclose(y, x / np.sqrt(1 + 1e-5), atol=1e-5)


class TestTorchConversion:
    def test_sync_batchnorm_any_rank(self):
        torch = pytest.importorskip("torch")
        m = torch.nn.Sequential(
            torch.nn.Linear(6, 6),
            torch.nn.SyncBatchNorm(6),
        )
        with torch.no_grad():
            m[1].weight.mul_(2.0).add_(0.5)
            m[1].running_mean.add_(1.0)
        conv = convert_syncbn_model(m)
        # 2D and 3D inputs must both work (SyncBatchNorm accepts 2D-5D;
        # the old BatchNorm2d mapping rejected them)
        conv.train()
        conv(torch.randn(4, 6))
        conv(torch.randn(4, 6, 3).transpose(1, 2).reshape(12, 6))
        assert torch.equal(conv[1].weight, m[1].weight)
        assert conv[1].running_mean is m[1].running_mean

    def test_batchnorm2d_preserved(self):
        torch = pytest.importorskip("torch")
        m = torch.nn.Sequential(torch.nn.BatchNorm2d(3))
        conv = convert_syncbn_model(m)
        y = conv(torch.randn(2, 3, 4, 4))
        assert y.shape == (2, 3, 4, 4)

    def test_flax_module_raises(self):
        with pytest.raises(TypeError):
            convert_syncbn_model(object())
