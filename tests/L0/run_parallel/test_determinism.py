"""Determinism guarantees (SURVEY §5 race-detection analog): identical
seeds must give BITWISE-identical gradients, independent of DDP bucketing
configuration (the reference's race conditions lived exactly in the
bucketed-allreduce path; here the property is compiler-enforced, and this
test pins it)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel.distributed import DistributedDataParallel

DP = 4


def _mesh():
    return Mesh(np.array(jax.devices()[:DP]), ("data",))


def _grads(key):
    ks = jax.random.split(key, 3)
    return {"w1": jax.random.normal(ks[0], (57, 33)),
            "w2": jax.random.normal(ks[1], (129,)),
            "b": jax.random.normal(ks[2], (7, 5, 3))}


@pytest.mark.parametrize("message_size", [1 << 6, 1 << 12, 1 << 30])
def test_grad_reduction_bitwise_stable_across_bucketing(message_size):
    """Different bucket sizes must produce BITWISE identical reduced grads
    (reference analog: allreduce_bucket ordering must not change math)."""
    per_rank = jax.vmap(lambda k: _grads(k))(
        jax.random.split(jax.random.PRNGKey(0), DP))
    ddp = DistributedDataParallel(message_size=message_size)

    def body(g):
        mine = jax.tree.map(lambda x: x[0], g)
        return jax.tree.map(lambda x: x[None], ddp.reduce_gradients(mine))

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=_mesh(), in_specs=(P("data"),), out_specs=P("data")))(
        per_rank)

    # oracle: single giant bucket
    ddp_ref = DistributedDataParallel(message_size=1 << 40)
    ref = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        lambda g: jax.tree.map(
            lambda x: x[None],
            ddp_ref.reduce_gradients(jax.tree.map(lambda x: x[0], g))),
        mesh=_mesh(), in_specs=(P("data"),), out_specs=P("data")))(per_rank)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        out, ref)


def test_same_seed_same_grads_bitwise():
    """Two identical runs (same seed, same data) must produce bitwise
    identical gradients — the functional-purity determinism guarantee."""
    def run():
        key = jax.random.PRNGKey(42)
        w = jax.random.normal(key, (64, 64))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 64))

        def loss(w):
            h = jnp.tanh(x @ w)
            return jnp.sum(jax.nn.softmax(h @ w.T) ** 2)

        return jax.jit(jax.grad(loss))(w)

    g1, g2 = run(), run()
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))


def test_dropout_deterministic_given_seed():
    """Threefry RNG streams: same seed -> bitwise identical dropout mask
    (the RNG-tracker reproducibility contract)."""
    from apex_tpu.transformer.tensor_parallel import random as tp_random

    def masked():
        tp_random.model_parallel_seed(1234)
        with tp_random.get_cuda_rng_tracker().fork() as k:
            return jax.random.bernoulli(k, 0.5, (32,))

    np.testing.assert_array_equal(np.asarray(masked()),
                                  np.asarray(masked()))
