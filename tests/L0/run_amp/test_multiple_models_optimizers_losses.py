"""Multiple models / optimizers / losses under amp (reference:
``tests/L0/run_amp/test_multiple_models_optimizers_losses.py`` — lists to
``amp.initialize`` + per-``loss_id`` scalers)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from apex_tpu import amp  # noqa: E402
from apex_tpu.amp import _amp_state  # noqa: E402


def _fresh_models(n=2, dim=4):
    torch.manual_seed(0)
    models = [nn.Sequential(nn.Linear(dim, dim), nn.ReLU(),
                            nn.Linear(dim, dim)) for _ in range(n)]
    opts = [torch.optim.SGD(m.parameters(), lr=0.05) for m in models]
    return models, opts


@pytest.fixture(autouse=True)
def _teardown_amp():
    yield
    from apex_tpu.amp import amp as amp_mod
    if amp_mod.current_handle() is not None:
        amp_mod.current_handle()._deactivate()
    _amp_state.amp_state.loss_scalers = []
    _amp_state.amp_state.optimizers = []


@pytest.mark.parametrize("opt_level", ["O1", "O2"])
def test_two_models_two_optimizers_two_losses(opt_level):
    models, opts = _fresh_models()
    models, opts = amp.initialize(models, opts, opt_level=opt_level,
                                  num_losses=2, verbosity=0)
    assert isinstance(models, list) and len(models) == 2
    assert isinstance(opts, list) and len(opts) == 2
    assert len(_amp_state.amp_state.loss_scalers) == 2

    x = torch.randn(8, 4)
    before = [p.detach().clone() for m in models for p in m.parameters()]
    for it in range(3):
        for i, (m, o) in enumerate(zip(models, opts)):
            o.zero_grad()
            loss = m(x).pow(2).mean()
            with amp.scale_loss(loss, o, loss_id=i) as scaled:
                scaled.backward()
            o.step()
    after = [p.detach().clone() for m in models for p in m.parameters()]
    for b, a in zip(before, after):
        assert not torch.allclose(b.float(), a.float()), "params frozen"


def test_per_loss_scalers_are_independent():
    models, opts = _fresh_models()
    models, opts = amp.initialize(models, opts, opt_level="O1",
                                  num_losses=2, verbosity=0)
    s0, s1 = _amp_state.amp_state.loss_scalers
    start0, start1 = s0.loss_scale(), s1.loss_scale()

    x = torch.randn(4, 4)
    p0 = [p.detach().clone() for p in models[0].parameters()]
    p1 = [p.detach().clone() for p in models[1].parameters()]

    # loss 0 overflows (scaled by inf factor), loss 1 is clean
    opts[0].zero_grad()
    loss = models[0](x).mean() * float("inf")
    with amp.scale_loss(loss, opts[0], loss_id=0) as scaled:
        scaled.backward()
    opts[0].step()
    for b, p in zip(p0, models[0].parameters()):
        assert torch.equal(b, p.detach()), "overflow step must be skipped"

    opts[1].zero_grad()
    with amp.scale_loss(models[1](x).mean(), opts[1], loss_id=1) as scaled:
        scaled.backward()
    opts[1].step()
    assert any(not torch.equal(b, p.detach())
               for b, p in zip(p1, models[1].parameters())), (
        "clean step must apply")

    assert s0.loss_scale() == start0 / 2.0, "scaler 0 must back off"
    assert s1.loss_scale() == start1, "scaler 1 must be untouched"


def test_one_model_params_split_across_two_optimizers():
    """The reference also covers ONE model whose parameters are split
    across several optimizers: scale_loss([o1, o2]) must unscale both
    partitions exactly once and (on overflow) skip both steps."""
    torch.manual_seed(0)
    model = nn.Sequential(nn.Linear(4, 4), nn.ReLU(), nn.Linear(4, 4))
    o1 = torch.optim.SGD(model[0].parameters(), lr=0.05)
    o2 = torch.optim.SGD(model[2].parameters(), lr=0.05)
    model, opts = amp.initialize(model, [o1, o2], opt_level="O1",
                                 num_losses=1, verbosity=0)
    x = torch.randn(4, 4)

    # clean iteration: one scale_loss over both optimizers; grads must be
    # unscaled exactly once (equal to the plain-loss grads)
    for o in opts:
        o.zero_grad()
    with amp.scale_loss(model(x).pow(2).mean(), opts) as scaled:
        scaled.backward()
    amp_grads = [p.grad.detach().clone().float()
                 for p in model.parameters()]
    for o in opts:
        o.zero_grad()
    model(x).pow(2).mean().backward()
    plain = [p.grad.detach().clone().float() for p in model.parameters()]
    for a, b in zip(amp_grads, plain):
        assert torch.allclose(a, b, rtol=1e-2, atol=1e-3), (a, b)

    # overflow iteration: BOTH optimizers must skip
    before = [p.detach().clone() for p in model.parameters()]
    for o in opts:
        o.zero_grad()
    loss = model(x).mean() * float("inf")
    with amp.scale_loss(loss, opts) as scaled:
        scaled.backward()
    opts[0].step()
    opts[1].step()
    for b, p in zip(before, model.parameters()):
        assert torch.equal(b, p.detach()), (
            "both optimizers must skip on overflow")


def test_two_losses_one_optimizer_requires_delay_unscale():
    """Accumulating two losses into ONE optimizer: the documented
    contract is delay_unscale=True on all but the last scale_loss; a
    second eager unscale would annihilate the first loss's grads, so it
    must raise loudly instead."""
    models, opts = _fresh_models(n=1)
    model, opt = amp.initialize(models[0], opts[0], opt_level="O1",
                                num_losses=2, verbosity=0)
    x = torch.randn(4, 4)

    # correct pattern: delay the first unscale
    opt.zero_grad()
    with amp.scale_loss(model(x).mean(), opt, loss_id=0,
                        delay_unscale=True) as scaled:
        scaled.backward()
    with amp.scale_loss(model(x).pow(2).mean(), opt, loss_id=1) as scaled:
        scaled.backward()
    opt.step()

    # incorrect pattern: two eager unscales -> loud error, not silent
    # gradient corruption
    opt.zero_grad()
    with amp.scale_loss(model(x).mean(), opt, loss_id=0) as scaled:
        scaled.backward()
    with pytest.raises(RuntimeError, match="delay_unscale"):
        with amp.scale_loss(model(x).pow(2).mean(), opt,
                            loss_id=1) as scaled:
            scaled.backward()


def test_delay_unscale_rejects_diverged_scales():
    """If the delayed loss's scaler and the final eager scaler have
    diverged, the accumulated grads would be silently mis-weighted —
    must raise instead."""
    models, opts = _fresh_models(n=1)
    model, opt = amp.initialize(models[0], opts[0], opt_level="O1",
                                num_losses=2, verbosity=0)
    s0, s1 = _amp_state.amp_state.loss_scalers
    s1._scale = s0._scale / 2.0   # simulate a prior backoff on loss 1
    x = torch.randn(4, 4)
    opt.zero_grad()
    with amp.scale_loss(model(x).mean(), opt, loss_id=0,
                        delay_unscale=True) as scaled:
        scaled.backward()
    with pytest.raises(RuntimeError, match="mis-weight"):
        with amp.scale_loss(model(x).pow(2).mean(), opt,
                            loss_id=1) as scaled:
            scaled.backward()
