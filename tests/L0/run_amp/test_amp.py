"""amp tests (reference: ``tests/L0/run_amp`` — opt-level properties,
loss scaling, checkpointing, overflow-skip)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.amp.scaler import (
    DEFAULT_GROWTH_INTERVAL, DEFAULT_INIT_SCALE, init_loss_scale,
    unscale_grads, update_scale)
from apex_tpu.optimizers import FusedAdam


def _params():
    rng = np.random.RandomState(0)
    return {"w": jnp.asarray(rng.randn(64, 32), jnp.float32),
            "b": jnp.asarray(rng.randn(32), jnp.float32)}


class TestOptLevels:
    def test_o0_properties(self):
        p = amp.opt_levels["O0"](amp.Properties())
        assert p.opt_level == "O0"
        assert p.cast_model_type == jnp.float32
        assert p.loss_scale == 1.0
        assert p.master_weights is False

    def test_o1_properties(self):
        p = amp.opt_levels["O1"](amp.Properties())
        assert p.patch_torch_functions is True
        assert p.loss_scale == "dynamic"

    def test_o2_properties(self):
        p = amp.opt_levels["O2"](amp.Properties())
        assert p.cast_model_type == jnp.bfloat16
        assert p.keep_batchnorm_fp32 is True
        assert p.master_weights is True
        assert p.loss_scale == "dynamic"

    def test_o3_properties(self):
        p = amp.opt_levels["O3"](amp.Properties())
        assert p.keep_batchnorm_fp32 is False
        assert p.loss_scale == 1.0

    def test_bad_opt_level(self):
        with pytest.raises(RuntimeError):
            amp.initialize(_params(), None, opt_level="O4")

    def test_override(self):
        params, opt = amp.initialize(
            _params(), FusedAdam(_params()), opt_level="O2",
            loss_scale=128.0)
        assert opt.loss_scaler.loss_scale() == 128.0


class TestInitializeJax:
    def test_o2_casts_params(self):
        params, opt = amp.initialize(_params(), FusedAdam(_params()),
                                     opt_level="O2")
        assert params["w"].dtype == jnp.bfloat16
        assert isinstance(opt, amp.AmpOptimizer)

    def test_o0_keeps_fp32(self):
        params = amp.initialize(_params(), opt_level="O0")
        assert params["w"].dtype == jnp.float32


class TestDynamicScaler:
    def test_init(self):
        s = init_loss_scale("dynamic")
        assert float(s.loss_scale) == DEFAULT_INIT_SCALE

    def test_static(self):
        s = init_loss_scale(512.0)
        assert not s.dynamic
        s2 = update_scale(s.replace(found_inf=jnp.asarray(1.0)))
        assert float(s2.loss_scale) == 512.0  # static never changes

    def test_backoff_on_overflow(self):
        s = init_loss_scale("dynamic")
        s = s.replace(found_inf=jnp.asarray(1.0, jnp.float32))
        s2 = update_scale(s)
        assert float(s2.loss_scale) == DEFAULT_INIT_SCALE * 0.5
        assert int(s2.growth_tracker) == 0

    def test_growth_after_interval(self):
        s = init_loss_scale("dynamic").replace(
            growth_tracker=jnp.asarray(DEFAULT_GROWTH_INTERVAL - 1,
                                       jnp.int32))
        s2 = update_scale(s)
        assert float(s2.loss_scale) == DEFAULT_INIT_SCALE * 2
        assert int(s2.growth_tracker) == 0

    def test_unscale_detects_inf(self):
        s = init_loss_scale("dynamic")
        grads = {"a": jnp.asarray([1.0, jnp.inf]), "b": jnp.ones(3)}
        out, s2 = unscale_grads(grads, s)
        assert float(s2.found_inf) == 1.0

    def test_unscale_divides(self):
        s = init_loss_scale(4.0)
        grads = {"a": jnp.asarray([8.0, 4.0])}
        out, s2 = unscale_grads(grads, s)
        np.testing.assert_allclose(np.asarray(out["a"]), [2.0, 1.0])

    def test_jit_carried(self):
        # scaler state must flow through jit (the TPU-native requirement)
        @jax.jit
        def step(s):
            return update_scale(s.replace(
                found_inf=jnp.asarray(1.0, jnp.float32)))
        s2 = step(init_loss_scale("dynamic"))
        assert float(s2.loss_scale) == DEFAULT_INIT_SCALE * 0.5


class TestAmpOptimizer:
    def test_overflow_skips_step(self):
        params = _params()
        cast, opt = amp.initialize(params, FusedAdam(params, lr=0.1),
                                   opt_level="O2")
        bad = {"w": jnp.full((64, 32), jnp.inf, jnp.float32),
               "b": jnp.ones(32, jnp.float32)}
        out = opt.step(bad)
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(params["w"]))
        assert opt._last_step_skipped
        assert opt.loss_scaler.loss_scale() == DEFAULT_INIT_SCALE * 0.5

    def test_clean_step_applies(self):
        params = _params()
        cast, opt = amp.initialize(params, FusedAdam(params, lr=0.1),
                                   opt_level="O2")
        scale = opt.loss_scaler.loss_scale()
        g = {"w": jnp.ones((64, 32), jnp.float32) * scale,
             "b": jnp.ones(32, jnp.float32) * scale}
        out = opt.step(g)
        assert not np.allclose(np.asarray(out["w"]),
                               np.asarray(params["w"]))
        assert not opt._last_step_skipped

    def test_scale_loss_ctx(self):
        params = _params()
        _, opt = amp.initialize(params, FusedAdam(params), opt_level="O2")
        loss = jnp.asarray(2.0)
        with amp.scale_loss(loss, opt) as scaled:
            assert float(scaled) == 2.0 * opt.loss_scaler.loss_scale()

    def test_state_dict_roundtrip(self):
        params = _params()
        _, opt = amp.initialize(params, FusedAdam(params), opt_level="O2")
        bad = {"w": jnp.full((64, 32), jnp.nan, jnp.float32),
               "b": jnp.ones(32, jnp.float32)}
        opt.step(bad)  # halves scale
        sd = amp.state_dict()
        assert sd["loss_scaler0"]["loss_scale"] == DEFAULT_INIT_SCALE * 0.5
        _, opt2 = amp.initialize(params, FusedAdam(params), opt_level="O2")
        amp.load_state_dict(sd)
        assert opt2.loss_scaler.loss_scale() == DEFAULT_INIT_SCALE * 0.5


class TestEndToEndTraining:
    def test_o2_loss_decreases(self):
        """Linear-regression convergence under O2 (bf16 params, dynamic
        scale) — the minimal analog of the reference L1 cross-product runs."""
        rng = np.random.RandomState(0)
        W_true = rng.randn(16, 4).astype(np.float32)
        X = rng.randn(256, 16).astype(np.float32)
        Y = X @ W_true
        params = {"w": jnp.zeros((16, 4), jnp.float32)}
        cast_params, opt = amp.initialize(params, FusedAdam(params, lr=0.05),
                                          opt_level="O2")

        def loss_fn(p, scale):
            pred = jnp.asarray(X, jnp.bfloat16) @ p["w"].astype(jnp.bfloat16)
            err = (pred.astype(jnp.float32) - Y) ** 2
            return jnp.mean(err) * scale

        grad_fn = jax.jit(jax.grad(loss_fn))
        losses = []
        p = cast_params
        for i in range(60):
            scale = jnp.asarray(opt.loss_scaler.loss_scale(), jnp.float32)
            g = grad_fn(p, scale)
            losses.append(float(loss_fn(p, 1.0)))
            p = opt.step(g)
            p = {"w": p["w"].astype(jnp.bfloat16)}
        assert losses[-1] < losses[0] * 0.1


class TestFP16Utils:
    def test_fp16_optimizer(self):
        from apex_tpu.fp16_utils import FP16_Optimizer
        params = _params()
        opt = FP16_Optimizer(FusedAdam(params, lr=0.01),
                             static_loss_scale=8.0)
        g = {"w": jnp.ones((64, 32)) * 8.0, "b": jnp.ones(32) * 8.0}
        out = opt.step(g)
        assert not np.allclose(np.asarray(out["w"]), np.asarray(params["w"]))
        assert not opt.overflow

    def test_dynamic_overflow(self):
        from apex_tpu.fp16_utils import FP16_Optimizer
        params = _params()
        opt = FP16_Optimizer(FusedAdam(params, lr=0.01),
                             dynamic_loss_scale=True)
        scale0 = opt.loss_scale
        g = {"w": jnp.full((64, 32), jnp.inf), "b": jnp.ones(32)}
        out = opt.step(g)
        assert opt.overflow
        assert opt.loss_scale == scale0 / 2.0
        np.testing.assert_array_equal(np.asarray(out["w"]),
                                      np.asarray(params["w"]))

    def test_network_to_half(self):
        from apex_tpu.fp16_utils import network_to_half
        p = network_to_half(_params())
        assert p["w"].dtype == jnp.bfloat16
