"""O1 patch-list machinery tests (reference:
``tests/L0/run_amp/test_basic_casts.py``, ``test_promotion.py``,
``test_cache.py``)."""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.amp import amp as amp_mod


@pytest.fixture
def handle():
    h = amp_mod.init()
    yield h
    h._deactivate()


# ---- test_basic_casts analogs ---------------------------------------------

def test_mm_runs_half(handle):
    a = torch.randn(4, 4)
    b = torch.randn(4, 4)
    assert torch.mm(a, b).dtype == torch.bfloat16


def test_functional_linear_runs_half(handle):
    x = torch.randn(2, 8)
    w = torch.randn(4, 8)
    assert torch.nn.functional.linear(x, w).dtype == torch.bfloat16


def test_tensor_matmul_runs_half(handle):
    a = torch.randn(4, 4)
    b = torch.randn(4, 4)
    assert (a @ b).dtype == torch.bfloat16


def test_exp_runs_float(handle):
    x = torch.randn(8).to(torch.bfloat16)
    assert torch.exp(x).dtype == torch.float32


def test_softmax_runs_float(handle):
    x = torch.randn(4, 4).to(torch.bfloat16)
    assert torch.softmax(x, dim=-1).dtype == torch.float32


def test_patches_restored_after_deactivate():
    orig_mm = torch.mm
    h = amp_mod.init()
    assert torch.mm is not orig_mm
    h._deactivate()
    assert torch.mm is orig_mm
    a = torch.randn(4, 4)
    assert torch.mm(a, a).dtype == torch.float32


def test_inactive_handle_is_passthrough():
    h = amp_mod.init(enabled=False)
    a = torch.randn(4, 4)
    assert torch.mm(a, a).dtype == torch.float32
    h._deactivate()


# ---- test_promotion analogs -----------------------------------------------

def test_add_promotes_mixed_to_float(handle):
    half = torch.randn(8).to(torch.bfloat16)
    full = torch.randn(8)
    assert torch.add(half, full).dtype == torch.float32
    assert (half + full).dtype == torch.float32


def test_add_same_dtype_untouched(handle):
    half = torch.randn(8).to(torch.bfloat16)
    assert torch.add(half, half).dtype == torch.bfloat16
    full = torch.randn(8)
    assert torch.add(full, full).dtype == torch.float32


def test_cat_promotes_sequence(handle):
    half = torch.randn(4).to(torch.bfloat16)
    full = torch.randn(4)
    assert torch.cat([half, full]).dtype == torch.float32
    assert torch.cat([half, half]).dtype == torch.bfloat16


def test_mul_inplace_promotion(handle):
    half = torch.randn(8).to(torch.bfloat16)
    full = torch.randn(8)
    out = half * full
    assert out.dtype == torch.float32


# ---- test_cache analogs ---------------------------------------------------

def test_weight_cast_is_cached(handle):
    w = torch.randn(4, 4, requires_grad=True)
    x = torch.randn(4, 4)
    y1 = torch.mm(w, x)
    assert len(handle.cache) == 1
    y2 = torch.mm(w, x)
    assert len(handle.cache) == 1          # same weight: one cast
    (y1.float().sum() + y2.float().sum()).backward()
    # both uses flow grads through the SAME cast node back to the leaf
    assert w.grad is not None and w.grad.dtype == torch.float32


def test_cache_cleared_on_scaler_update(handle):
    from apex_tpu.amp._torch_shim import _TorchScaler
    w = torch.randn(4, 4, requires_grad=True)
    torch.mm(w, torch.randn(4, 4))
    assert len(handle.cache) == 1
    _TorchScaler("dynamic").update()
    assert len(handle.cache) == 0


def test_cache_miss_on_recycled_id(handle):
    w = torch.randn(4, 4, requires_grad=True)
    key = id(w)
    handle.cache[key] = (torch.randn(4, 4), torch.randn(4, 4))  # stale alias
    y = torch.mm(w, torch.randn(4, 4))
    assert y.dtype == torch.bfloat16
    assert handle.cache[key][0] is w       # stale entry replaced


def test_activations_not_cached(handle):
    x = torch.randn(4, 4)                  # no requires_grad: activation
    torch.mm(x, x)
    assert len(handle.cache) == 0


# ---- in-place promote semantics (promote_match_arg0) ------------------------

def test_inplace_add_keeps_self_dtype_and_storage(handle):
    """``x += full`` on a bf16 tensor must mutate x's storage in place
    (other args cast to self's dtype), never promote-and-rebind: a
    widest-dtype promote would hand ``+=`` a NEW fp32 tensor and every
    other alias of x would silently stop seeing updates."""
    x = torch.zeros(8, dtype=torch.bfloat16)
    alias = x
    full = torch.ones(8)                   # fp32 operand
    x += full
    assert x.dtype == torch.bfloat16       # self dtype wins (arg0 match)
    assert x is alias                      # same object, mutated in place
    assert torch.all(alias == 1.0)         # alias sees the update


def test_inplace_on_fp32_casts_half_operand(handle):
    a = torch.zeros(8)                     # fp32 self
    a += torch.ones(8, dtype=torch.bfloat16)
    assert a.dtype == torch.float32
    assert torch.all(a == 1.0)


def test_inplace_mul_scalar_passthrough(handle):
    # plain python scalars must not trip the cast machinery (and must not
    # require jax on the torch-only path)
    x = torch.full((4,), 2.0, dtype=torch.bfloat16)
    x *= 3
    assert x.dtype == torch.bfloat16
    assert torch.all(x == 6.0)


def test_wrap_optimizer_clears_cache(handle):
    """Old-style API (init + wrap_optimizer + scale_loss): step() must
    clear the weight-cast cache or forwards keep stale bf16 copies of
    in-place-updated parameters and training silently freezes."""
    w = torch.nn.Parameter(torch.randn(4, 4))
    opt = handle.wrap_optimizer(torch.optim.SGD([w], lr=0.5))
    x = torch.randn(4, 4)
    y1 = torch.mm(w, x)
    assert len(handle.cache) == 1
    cast_before = handle.cache[id(w)][1]
    y1.float().sum().backward()
    opt.step()
    assert len(handle.cache) == 0          # cache cleared by step()
    y2 = torch.mm(w, x)                    # re-cast sees updated weights
    cast_after = handle.cache[id(w)][1]
    assert cast_after is not cast_before
    assert not torch.equal(cast_after, cast_before)


# ---- reference-table parity sweep (einsum / RNN family / promote) ---------
# Each category asserted END TO END through public torch surfaces; the
# remaining intentional-only deltas are documented in
# apex_tpu/amp/lists/__init__.py.

def test_einsum_runs_half(handle):
    a = torch.randn(4, 5)
    b = torch.randn(5, 6)
    out = torch.einsum("ij,jk->ik", a, b)     # equation string untouched
    assert out.dtype == torch.bfloat16
    expect = (a.to(torch.bfloat16) @ b.to(torch.bfloat16))
    assert torch.equal(out, expect)


def test_einsum_weight_cast_is_cached(handle):
    w = torch.randn(4, 4, requires_grad=True)
    x = torch.randn(4, 4)
    torch.einsum("ij,jk->ik", w, x)
    assert len(handle.cache) == 1             # leaf param memoized
    torch.einsum("ij,jk->ik", w, x)
    assert len(handle.cache) == 1


def test_mean_std_var_run_float(handle):
    x = torch.randn(16).to(torch.bfloat16)
    assert torch.mean(x).dtype == torch.float32
    assert torch.std(x).dtype == torch.float32
    assert torch.var(x).dtype == torch.float32
    assert x.mean().dtype == torch.float32    # tensor-method list too
    assert x.std().dtype == torch.float32


def test_lstm_module_runs_half(handle):
    """nn.LSTM dispatches through the patched _VF entry: fp32 module +
    fp32 input run the fused RNN in bf16 end to end."""
    torch.manual_seed(0)
    lstm = torch.nn.LSTM(8, 16, batch_first=True)
    x = torch.randn(2, 5, 8)
    out, (h, c) = lstm(x)
    assert out.dtype == torch.bfloat16
    assert h.dtype == torch.bfloat16 and c.dtype == torch.bfloat16
    # weights are leaf params: the casts are memoized in the handle
    assert len(handle.cache) == len(lstm._flat_weights)


def test_gru_and_rnn_cells_run_half(handle):
    cell = torch.nn.GRUCell(8, 16)
    h = cell(torch.randn(3, 8))
    assert h.dtype == torch.bfloat16
    rnn_cell = torch.nn.RNNCell(8, 16)
    assert rnn_cell(torch.randn(3, 8)).dtype == torch.bfloat16


def test_rnn_patch_restored_on_deactivate():
    import torch.nn.modules.rnn as rnn_mod

    h = amp_mod.init()
    try:
        lstm = torch.nn.LSTM(4, 4, batch_first=True)
        assert lstm(torch.randn(1, 3, 4))[0].dtype == torch.bfloat16
    finally:
        h._deactivate()
    assert not hasattr(rnn_mod._VF.lstm, "_amp_original")
    lstm = torch.nn.LSTM(4, 4, batch_first=True)
    assert lstm(torch.randn(1, 3, 4))[0].dtype == torch.float32


def test_named_inplace_promote_matches_arg0(handle):
    """The as_inplace expansion of the promote list: x.add_(fp32) on a
    bf16 tensor keeps x's dtype and storage (match-arg0, not widest)."""
    x = torch.zeros(8, dtype=torch.bfloat16)
    alias = x
    x.add_(torch.ones(8))                     # fp32 operand cast DOWN
    assert x.dtype == torch.bfloat16
    assert x is alias and torch.all(alias == 1.0)
    y = torch.full((4,), 2.0)                 # fp32 self wins upward too
    y.mul_(torch.full((4,), 3.0, dtype=torch.bfloat16))
    assert y.dtype == torch.float32
    assert torch.all(y == 6.0)
    z = torch.ones(4, dtype=torch.bfloat16)
    z.addcmul_(torch.ones(4), torch.full((4,), 2.0), value=2.0)
    assert z.dtype == torch.bfloat16
    assert torch.all(z == 5.0)


# ---- user decorators / registration (torch + jax) --------------------------

def test_half_function_decorator_torch(handle):
    @amp_mod.half_function
    def f(x):
        return x
    assert f(torch.randn(4)).dtype == torch.bfloat16


def test_half_function_decorator_jax(handle):
    @amp_mod.half_function
    def f(x):
        return x
    assert f(jnp.ones((4,), jnp.float32)).dtype == jnp.bfloat16


def test_float_function_decorator_jax(handle):
    @amp_mod.float_function
    def f(x):
        return x
    assert f(jnp.ones((4,), jnp.bfloat16)).dtype == jnp.float32


def test_promote_function_decorator_jax(handle):
    @amp_mod.promote_function
    def f(a, b):
        return a, b
    a, b = f(jnp.ones((4,), jnp.bfloat16), jnp.ones((4,), jnp.float32))
    assert a.dtype == jnp.float32 and b.dtype == jnp.float32


def test_register_half_function_applied_at_init():
    import types
    m = types.SimpleNamespace(myfn=lambda x: x)
    amp_mod.register_half_function(m, "myfn")
    h = amp_mod.init()
    try:
        assert m.myfn(torch.randn(4)).dtype == torch.bfloat16
    finally:
        h._deactivate()
        amp_mod._USER_REGISTRY.clear()
    assert m.myfn(torch.randn(4)).dtype == torch.float32


def test_decorators_passthrough_when_inactive():
    @amp_mod.half_function
    def f(x):
        return x
    assert f(torch.randn(4)).dtype == torch.float32


def test_o1_initialize_end_to_end():
    """O1 via amp.initialize: patches applied, training decreases loss."""
    from apex_tpu import amp

    torch.manual_seed(0)
    model = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.ReLU(),
                                torch.nn.Linear(32, 4))
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O1")
    try:
        X = torch.randn(64, 16)
        Y = X @ torch.randn(16, 4)
        losses = []
        for _ in range(20):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X).float(), Y)
            with amp.scale_loss(loss, opt) as scaled:
                scaled.backward()
            opt.step()
            losses.append(loss.item())
        assert losses[-1] < losses[0] * 0.7
        # the patched mm really produced bf16 inside the model
        assert torch.mm(torch.randn(2, 2),
                        torch.randn(2, 2)).dtype == torch.bfloat16
    finally:
        if amp_mod.current_handle() is not None:
            amp_mod.current_handle()._deactivate()
