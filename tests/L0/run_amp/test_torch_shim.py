"""torch-CPU shim tests (reference flow: ``examples/imagenet/main_amp.py``)."""
import numpy as np
import pytest
import torch

from apex_tpu import amp


def _mlp():
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Linear(16, 32), torch.nn.ReLU(),
        torch.nn.BatchNorm1d(32), torch.nn.Linear(32, 4))


def _train(model, opt, steps=30):
    torch.manual_seed(1)
    X = torch.randn(128, 16)
    W = torch.randn(16, 4)
    Y = X @ W
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        out = model(X)
        loss = torch.nn.functional.mse_loss(out.float(), Y)
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        losses.append(loss.item())
    return losses


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2"])
def test_loss_decreases(opt_level):
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level=opt_level)
    losses = _train(model, opt)
    assert losses[-1] < losses[0] * 0.7, (opt_level, losses[:3], losses[-3:])


def test_o2_float16_loss_decreases_masters_fp32():
    """The reference's O2 regime is literally fp16 (BERT phase 1 trains
    under it with dynamic scaling); pin the selectable
    ``cast_model_type=float16`` path end to end: model halves are fp16,
    masters stay fp32, and training still converges through the
    scale/unscale loop."""
    import jax.numpy as jnp
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                cast_model_type=jnp.float16)
    assert model[0].weight.dtype == torch.float16
    assert model[2].weight.dtype == torch.float32  # BN kept fp32
    masters = list(amp.master_params(opt))
    assert all(m.dtype == torch.float32 for m in masters)
    losses = _train(model, opt)
    assert losses[-1] < losses[0] * 0.7, (losses[:3], losses[-3:])


def test_cast_model_outputs_honored():
    """Reference contract: cast_model_outputs casts floating outputs to
    the requested dtype regardless of opt level — previously the kwarg
    was silently ignored."""
    from apex_tpu.optimizers import FusedAdam

    for opt_level in ("O1", "O2"):
        model = _mlp()
        opt = FusedAdam(model.parameters(), lr=1e-3)
        model, opt = amp.initialize(model, opt, opt_level=opt_level,
                                    cast_model_outputs=torch.float32)
        out = model(torch.randn(8, 16))
        assert out.dtype == torch.float32, opt_level
        # still trains through the wrapper
        losses = _train(model, opt, steps=10)
        assert np.isfinite(losses).all()


def test_o2_casts_model_keeps_bn_fp32():
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2")
    assert model[0].weight.dtype == torch.bfloat16
    assert model[2].weight.dtype == torch.float32  # BN kept fp32


def test_o2_keep_batchnorm_fp32_string_false():
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                keep_batchnorm_fp32="False")
    assert model[2].weight.dtype == torch.bfloat16


def test_o2_zero_grad_clears_model_grads():
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2")
    X = torch.randn(8, 16)
    loss = model(X).float().pow(2).mean()
    with amp.scale_loss(loss, opt) as scaled:
        scaled.backward()
    opt.step()
    opt.zero_grad()
    for p in model.parameters():
        assert p.grad is None or torch.all(p.grad == 0)


def test_o2_grads_do_not_accumulate_across_steps():
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.0)  # lr=0: params frozen
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                loss_scale=128.0)
    X = torch.randn(8, 16)

    def one_grad():
        opt.zero_grad()
        loss = model(X).float().pow(2).mean()
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        return [p.grad.clone() for p in model.parameters()
                if p.grad is not None]

    g1 = one_grad()
    g2 = one_grad()
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a.float().numpy(), b.float().numpy(),
                                   atol=1e-3)


def test_master_params_iterates_per_param():
    model = _mlp()
    opt = torch.optim.SGD(model.parameters(), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2")
    masters = list(amp.master_params(opt))
    assert len(masters) == len(list(model.parameters()))
    # torch path: clip_grad idiom must work
    torch.nn.utils.clip_grad_norm_(masters, 1.0)


def test_master_params_jax_path_shapes():
    import jax.numpy as jnp
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jnp.ones((4, 8)), "b": jnp.ones(8)}
    _, opt = amp.initialize(params, FusedAdam(params), opt_level="O2")
    masters = list(amp.master_params(opt))
    assert {tuple(m.shape) for m in masters} == {(8,), (4, 8)}
    assert all(m.dtype == jnp.float32 for m in masters)


def test_max_loss_scale_honored():
    import jax.numpy as jnp
    from apex_tpu.amp.scaler import update_scale
    from apex_tpu.optimizers import FusedAdam
    params = {"w": jnp.ones((8, 8))}
    _, opt = amp.initialize(params, FusedAdam(params), opt_level="O2",
                            max_loss_scale=2.0 ** 10)
    s = opt.loss_scalers[0]
    s.state = s.state.replace(
        loss_scale=jnp.asarray(2.0 ** 10, jnp.float32),
        growth_tracker=jnp.asarray(1999, jnp.int32))
    s.update_scale()
    assert s.loss_scale() == 2.0 ** 10
