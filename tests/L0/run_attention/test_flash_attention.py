"""Flash attention kernel vs jnp oracle.

Mirrors the reference's fused-attention tests
(``apex/contrib/test/fmha/test_fmha.py`` — fused vs python reference — and
``tests/L0/run_transformer/test_fused_softmax.py``'s kernel-vs-fallback
equality pattern).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import flash_attention, mha_reference


def _rand(key, *shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype=dtype)


def _qkv(seed, b, h, sq, sk, d, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (_rand(kq, b, h, sq, d, dtype=dtype),
            _rand(kk, b, h, sk, d, dtype=dtype),
            _rand(kv, b, h, sk, d, dtype=dtype))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 128), (256, 256), (128, 256),
                                   (256, 128)])
def test_forward_matches_oracle(causal, sq, sk):
    # causal with sq != sk uses bottom-right diagonal alignment (decode with
    # a KV cache), matching the oracle's tril(k=sk-sq)
    q, k, v = _qkv(0, 2, 4, sq, sk, 64)
    out = flash_attention(q, k, v, causal=causal)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_padding_mask_matches_oracle():
    b, h, s, d = 2, 4, 128, 64
    q, k, v = _qkv(1, b, h, s, s, d)
    # reference convention: True = masked out (scaled_masked_softmax)
    lengths = jnp.array([96, 128])
    mask = (jnp.arange(s)[None, :] >= lengths[:, None])  # [b, sk]
    mask = mask[:, None, None, :]                        # [b, 1, 1, sk]
    mask = jnp.broadcast_to(mask, (b, 1, s, s))
    out = flash_attention(q, k, v, mask=mask)
    ref = mha_reference(q, k, v, mask=mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_oracle(causal):
    b, h, s, d = 1, 2, 128, 64
    q, k, v = _qkv(2, b, h, s, s, d)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=1e-3, rtol=1e-3)


def test_mask_grads_match_oracle():
    b, h, s, d = 2, 2, 128, 64
    q, k, v = _qkv(3, b, h, s, s, d)
    lengths = jnp.array([64, 128])
    mask = jnp.broadcast_to(
        (jnp.arange(s)[None, :] >= lengths[:, None])[:, None, None, :],
        (b, 1, s, s))

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, mask=mask) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, mask=mask) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=1e-3, rtol=1e-3)


def test_bf16_forward_close():
    q, k, v = _qkv(4, 1, 2, 128, 128, 64, dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                        v.astype(jnp.float32), causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32), ref,
                               atol=2e-2, rtol=2e-2)


def test_non_tiling_shape_falls_back():
    q, k, v = _qkv(5, 1, 1, 100, 100, 64)
    out = flash_attention(q, k, v)           # s < 128: single block
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(1000, 1000), (700, 1000)])
def test_non_tiling_long_shape_pads_to_kernel(causal, sq, sk, monkeypatch):
    """s=1000-style shapes must take the PADDED KERNEL path, not the
    O(s²) oracle (old silent fallback).  mha_reference is poisoned to
    prove the kernel ran."""
    import apex_tpu.ops.attention as attn_mod

    q, k, v = _qkv(7, 1, 2, sq, sk, 64)
    ref = mha_reference(q, k, v, causal=causal)

    def _boom(*a, **kw):
        raise AssertionError("oracle fallback taken for padded shape")

    monkeypatch.setattr(attn_mod, "mha_reference", _boom)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_padded_shape_grads_match_oracle():
    b, h, s, d = 1, 2, 384 + 128 + 64, 64   # 576: no 128-multiple divisor
    q, k, v = _qkv(8, b, h, s, s, d)

    def loss_kernel(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=1e-3, rtol=1e-3)


def test_padded_shape_with_mask_matches_oracle():
    b, h, s, d = 2, 2, 700, 64               # 700 > 512, pads to 768
    q, k, v = _qkv(9, b, h, s, s, d)
    lengths = jnp.array([500, 700])
    mask = jnp.broadcast_to(
        (jnp.arange(s)[None, :] >= lengths[:, None])[:, None, None, :],
        (b, 1, s, s))
    out = flash_attention(q, k, v, mask=mask)
    ref = mha_reference(q, k, v, mask=mask)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_sm_scale_respected():
    q, k, v = _qkv(6, 1, 2, 128, 128, 64)
    out = flash_attention(q, k, v, sm_scale=0.05)
    ref = mha_reference(q, k, v, sm_scale=0.05)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 256), (256, 128)])
def test_cross_shape_grads_match_oracle(causal, sq, sk):
    """sq != sk backward (decode/cross-attention): the causal offset
    (bottom-right diagonal alignment) must hold through the fused
    backward's dq accumulator and the dk/dv path.

    vjp with a RANDOM (everywhere-nonzero) cotangent, not grad of
    sum(out^2): a quadratic loss zeroes the cotangent exactly on
    fully-masked rows (out == 0 there), which would let a regression in
    the backward's masked-row guard ship undetected."""
    q, k, v = _qkv(13, 1, 2, sq, sk, 64)
    dout = _rand(jax.random.key(14), 1, 2, sq, 64) + 0.1

    def gl(attn):
        _, vjp = jax.vjp(
            lambda q, k, v: attn(q, k, v, causal=causal), q, k, v)
        return vjp(dout)

    gk = gl(flash_attention)
    gr = gl(mha_reference)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_and_split_backward_agree(causal, monkeypatch):
    """The one-pass fused backward and the split dq/dkv kernels must
    produce identical grads (the VMEM gate picks between them by shape,
    so both paths need coverage at the same shape).  Blocks of 128 on
    s=512 force a REAL 4x4 grid — the fused kernel's multi-block
    machinery (full-sequence dq scratch accumulation across ki, per-ki
    dk/dv reinit, the two finalize predicates, causal block skipping)
    all run multiple times."""
    import apex_tpu.ops.attention as attn_mod

    q, k, v = _qkv(11, 1, 2, 512, 512, 64)

    def grads(q, k, v):
        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=128, block_k=128) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    g_fused = grads(q, k, v)                     # under the 2 MB gate
    monkeypatch.setattr(attn_mod, "_FUSED_BWD_MAX_BYTES", 0)
    g_split = grads(q, k, v)                     # forced two-kernel path
    for a, b_ in zip(g_fused, g_split):
        np.testing.assert_allclose(a, b_, atol=1e-5, rtol=1e-5)


def test_fused_backward_masked_padded(monkeypatch):
    """Fused backward under mask + REAL lane padding matches the oracle
    (s=700 > the 512 fit threshold, so it pads to 768 and the fused
    kernel's valid-window masking is actually exercised)."""
    b, h, s, d = 2, 2, 700, 64                   # pads to 768
    q, k, v = _qkv(12, b, h, s, s, d)
    lengths = jnp.array([500, 700])
    mask = jnp.broadcast_to(
        (jnp.arange(s)[None, :] >= lengths[:, None])[:, None, None, :],
        (b, 1, s, s))

    def loss(attn):
        def f(q, k, v):
            return jnp.sum(attn(q, k, v, mask=mask) ** 2)
        return f

    gk = jax.grad(loss(flash_attention), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gk, gr):
        np.testing.assert_allclose(a, b_, atol=1e-3, rtol=1e-3)


# ---------------------------------------------------------------------------
# XLA short-sequence path (use_kernel=False — on TPU it auto-dispatches at
# padded seq <= _XLA_PATH_MAX_SEQ; forced here so CPU covers it)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 128), (128, 256), (96, 96),
                                   (256, 128)])
def test_xla_path_matches_oracle(causal, sq, sk):
    q, k, v = _qkv(7, 2, 4, sq, sk, 64)
    out = flash_attention(q, k, v, causal=causal, use_kernel=False)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-2, rtol=2e-2)


def test_xla_path_mask_and_grads_match_kernel():
    q, k, v = _qkv(9, 2, 2, 128, 128, 64)
    mask = jax.random.bernoulli(jax.random.PRNGKey(3), 0.2,
                                (2, 1, 128, 128))

    def loss(f):
        def inner(q, k, v):
            return jnp.sum(f(q, k, v) ** 2)
        return jax.grad(inner, argnums=(0, 1, 2))(q, k, v)

    g_x = loss(lambda q, k, v: flash_attention(q, k, v, mask=mask,
                                               use_kernel=False))
    g_k = loss(lambda q, k, v: flash_attention(q, k, v, mask=mask,
                                               use_kernel=True))
    for a, b in zip(g_x, g_k):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-2, rtol=2e-2)


def test_xla_path_fully_masked_rows_zero():
    q, k, v = _qkv(11, 1, 2, 64, 64, 64)
    mask = jnp.zeros((1, 1, 64, 64), bool).at[:, :, 5, :].set(True)
    out = flash_attention(q, k, v, mask=mask, use_kernel=False)
    np.testing.assert_array_equal(np.asarray(out[:, :, 5, :]), 0.0)


def test_xla_path_dropout_stream_matches_kernel():
    q, k, v = _qkv(13, 1, 2, 128, 128, 64)
    a = flash_attention(q, k, v, dropout_rate=0.15, dropout_seed=99,
                        use_kernel=False)
    b = flash_attention(q, k, v, dropout_rate=0.15, dropout_seed=99,
                        use_kernel=True)
    # identical coordinate-hash mask => identical zeros, close values
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=2e-2, rtol=2e-2)
    za = np.isclose(np.asarray(a), 0.0, atol=1e-6)
    zb = np.isclose(np.asarray(b), 0.0, atol=1e-6)
    assert (za == zb).mean() > 0.999


def test_auto_dispatch_predicate(monkeypatch):
    """On TPU backends short seqs take the XLA path, long seqs and
    explicit blocks take the kernel; non-TPU backends always kernel."""
    import apex_tpu.ops.attention as A
    import apex_tpu.utils.common as common
    # on_tpu() is functools.cache'd: pre-warm it with the REAL backend
    # so the monkeypatched default_backend below can't poison it for
    # this test (interpret-mode selection) or later kernel tests
    common.on_tpu()
    calls = {}
    real_xla, real_fwd = A._xla_attention, A._fwd

    def spy_xla(*a, **k):
        calls["xla"] = True
        return real_xla(*a, **k)

    def spy_fwd(*a, **k):
        calls["kernel"] = True
        return real_fwd(*a, **k)

    monkeypatch.setattr(A, "_xla_attention", spy_xla)
    monkeypatch.setattr(A, "_fwd", spy_fwd)
    q, k, v = _qkv(21, 1, 2, 128, 128, 64)

    monkeypatch.setattr(A.jax, "default_backend", lambda: "tpu")
    calls.clear()
    A.flash_attention(q, k, v)
    assert calls == {"xla": True}            # short seq on tpu -> XLA

    calls.clear()
    A.flash_attention(q, k, v, block_q=128, block_k=128)
    assert calls == {"kernel": True}         # explicit blocks -> kernel

    monkeypatch.setattr(A.jax, "default_backend", lambda: "cpu")
    calls.clear()
    A.flash_attention(q, k, v)
    assert calls == {"kernel": True}         # non-tpu backend -> kernel


def _xla_kernel_parity_case(b, h, sq, sk, d, seed, **kw):
    """Assert XLA-path vs kernel parity on loss AND input grads."""
    q, k, v = _qkv(seed + 100, b, h, sq, sk, d)

    def loss(use_kernel):
        def inner(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, use_kernel=use_kernel, **kw) ** 2)
        return jax.value_and_grad(inner, argnums=(0, 1, 2))(q, k, v)

    lx, gx = loss(False)
    lk, gk = loss(True)
    np.testing.assert_allclose(float(lx), float(lk), rtol=2e-3)
    for a, bb in zip(gx, gk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=3e-2, rtol=3e-2)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_xla_kernel_random_parity(seed):
    """Seeded random-config sweep: the XLA path and the kernel must
    agree on outputs AND input grads across shapes, causal, masks, and
    dropout (the dispatch boundary's semantics contract)."""
    rng = np.random.default_rng(seed)
    b = int(rng.integers(1, 3))
    h = int(rng.choice([1, 2, 4]))
    sq = int(rng.choice([64, 96, 128, 192, 256]))
    sk = sq if rng.random() < 0.6 else int(rng.choice([64, 128, 256]))
    d = int(rng.choice([32, 64]))
    causal = bool(rng.random() < 0.5)
    with_mask = bool(rng.random() < 0.5) and not causal
    rate = float(rng.choice([0.0, 0.15]))
    kw = dict(causal=causal)
    if with_mask:
        kw["mask"] = jax.random.bernoulli(
            jax.random.PRNGKey(seed), 0.2, (b, 1, sq, sk))
    if rate:
        kw.update(dropout_rate=rate, dropout_seed=seed * 7 + 1)
    _xla_kernel_parity_case(b, h, sq, sk, d, seed, **kw)


@pytest.mark.parametrize("sq,sk", [(128, 256), (256, 128)])
def test_xla_kernel_rect_causal_parity(sq, sk):
    """Rectangular causal (decode / KV-cache alignment): the XLA path's
    ``cols <= rows + (sk - sq)`` must match the kernel's causal_off in
    both directions, through the backward — the one branch the random
    sweep's seeds never draw."""
    _xla_kernel_parity_case(1, 2, sq, sk, 64, seed=50, causal=True)


def test_xla_max_seq_override_env_and_kwarg(monkeypatch):
    """The kernel/XLA auto-dispatch crossover is tunable without a code
    edit: APEX_TPU_ATTN_XLA_MAX_SEQ env var, overridden in turn by the
    per-call kwarg (VERDICT weak #8 — the 256 default is interpolated,
    not densely measured)."""
    from apex_tpu.ops.attention import (_XLA_PATH_MAX_SEQ,
                                        xla_path_max_seq)

    monkeypatch.delenv("APEX_TPU_ATTN_XLA_MAX_SEQ", raising=False)
    assert xla_path_max_seq() == _XLA_PATH_MAX_SEQ
    monkeypatch.setenv("APEX_TPU_ATTN_XLA_MAX_SEQ", "512")
    assert xla_path_max_seq() == 512
    assert xla_path_max_seq(1024) == 1024      # kwarg beats env
    assert xla_path_max_seq(0) == 0            # 0 disables the XLA path
    monkeypatch.setenv("APEX_TPU_ATTN_XLA_MAX_SEQ", "not-an-int")
    with pytest.raises(ValueError, match="APEX_TPU_ATTN_XLA_MAX_SEQ"):
        xla_path_max_seq()


def test_flash_attention_accepts_xla_max_seq_kwarg():
    """The kwarg threads through flash_attention and does not change
    values (on CPU the kernel path is taken either way; the dispatch
    decision itself is pinned by test_xla_max_seq_override_env_and_kwarg)."""
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 128, 32),
                          jnp.bfloat16)
    base = flash_attention(q, q, q, causal=True)
    via_kwarg = flash_attention(q, q, q, causal=True, xla_max_seq=0)
    np.testing.assert_array_equal(np.asarray(base, np.float32),
                                  np.asarray(via_kwarg, np.float32))
