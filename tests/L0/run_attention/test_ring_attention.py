"""Ring attention (context parallel) vs full-sequence oracle.

The sequence is sharded over the ``context`` mesh axis; the ring result
must equal plain attention on the gathered sequence — forward and grads,
causal and bidirectional.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_reference,
)
from apex_tpu.transformer import parallel_state

CP = 4
B, H, S, D = 1, 2, 512, 64   # S = total sequence; S/CP = 128 per rank


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=CP)
    yield
    parallel_state.destroy_model_parallel()


def _qkv(seed):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, S, D)) for k in ks)


def _run_ring(q, k, v, causal):
    mesh = parallel_state.get_mesh()

    def body(q, k, v):
        return ring_attention(q, k, v, causal=causal)

    # shard the sequence dim (axis 2) over the context axis
    spec = P(None, None, "context", None)
    return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_full_attention(causal):
    q, k, v = _qkv(0)
    out = _run_ring(q, k, v, causal)
    ref = ring_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_full_attention(causal):
    q, k, v = _qkv(1)
    mesh = parallel_state.get_mesh()
    spec = P(None, None, "context", None)

    def ring_loss(q, k, v):
        def body(q, k, v):
            o = ring_attention(q, k, v, causal=causal)
            # local partial sum; psum for the global scalar loss
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2),
                                "context")
        return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=P()))(q, k, v)

    def ref_loss(q, k, v):
        o = ring_attention_reference(q, k, v, causal=causal)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    gk = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gk, gr):
        np.testing.assert_allclose(
            a, b, atol=2e-3, rtol=2e-3,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_cp1_degrades_to_flash():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=1)
    q, k, v = _qkv(2)
    out = ring_attention(q, k, v, causal=True, axis_name=None)
    ref = ring_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_bf16_ring_close_to_fp32_oracle():
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv(3))
    out = _run_ring(q, k, v, causal=True)
    ref = ring_attention_reference(
        q.astype(jnp.float32), k.astype(jnp.float32),
        v.astype(jnp.float32), causal=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(out.astype(jnp.float32), ref,
                               atol=3e-2, rtol=3e-2)


def test_causal_outlier_grads_finite():
    """Regression: invisible shard pairs must be skipped, not masked —
    exp(s - global_lse) on unbounded cross-shard scores overflows."""
    q, k, v = _qkv(4)
    q = q * 30.0   # score outliers
    k = k * 30.0
    mesh = parallel_state.get_mesh()
    spec = P(None, None, "context", None)

    def body(q, k, v):
        o = ring_attention(q, k, v, causal=True)
        return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2), "context")

    loss_fn = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P()))
    g = jax.grad(lambda q, k, v: loss_fn(q, k, v), argnums=(0, 1, 2))(
        q, k, v)
    for a in g:
        assert np.all(np.isfinite(np.asarray(a)))
