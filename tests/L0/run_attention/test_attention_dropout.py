"""In-kernel attention-probability dropout (reference parity:
``apex/contrib/csrc/multihead_attn/philox.h`` — the CUDA kernels drop
softmax *probabilities* inside the fused kernel and regenerate the same
mask in the backward from a counter-based stream).

The TPU kernels use a keyed counter hash over global (bh, row, col)
coordinates (pure int32 ops — identical bits in CPU interpret mode and
on chip), so these tests cover the exact mask generation the chip runs.
``mha_reference`` draws the same mask on the materialized probability
matrix, giving a bit-matched oracle (block-independent: the mask is a
pure function of global coordinates, so every kernel blocking agrees).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.attention import (flash_attention, mha_reference,
                                    _FUSED_BWD_MAX_BYTES)
import apex_tpu.ops.attention as attention_mod

B, H, S, D = 1, 2, 256, 64
BLOCKS = dict(block_q=128, block_k=128)
RATE, SEED = 0.15, 1234


def _qkv(key=0, s=S):
    return jax.random.normal(jax.random.PRNGKey(key), (3, B, H, s, D),
                             jnp.float32)


def _oracle(q, k, v, **kw):
    return mha_reference(q, k, v, dropout_rate=RATE, dropout_seed=SEED,
                         **kw)


def _kernel(q, k, v, **kw):
    return flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=SEED,
                           **BLOCKS, **kw)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_masked_oracle(causal):
    q, k, v = _qkv()
    out = _kernel(q, k, v, causal=causal)
    ref = _oracle(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-6)


def test_backward_regenerates_identical_mask():
    """All three grads must equal the oracle's — only possible if every
    backward kernel redraws the exact forward mask."""
    q, k, v = _qkv(1)

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    gk = jax.grad(loss(_kernel), argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss(_oracle), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gk, go):
        np.testing.assert_allclose(a, b, atol=5e-6, err_msg=f"d{name}")


def test_split_backward_matches_fused(monkeypatch):
    """The split dq/dkv kernels draw the same mask as the fused one-pass
    backward (both derive it from (seed, bh, qi, ki), not grid order)."""
    q, k, v = _qkv(2)

    def g(q, k, v):
        return jnp.sum(jnp.sin(_kernel(q, k, v, causal=True)))

    fused = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    monkeypatch.setattr(attention_mod, "_FUSED_BWD_MAX_BYTES", 0)
    split = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", fused, split):
        np.testing.assert_allclose(a, b, atol=1e-5, err_msg=f"d{name}")


def test_masked_plus_dropout_matches_oracle():
    """Padding mask and prob dropout compose: kernel fwd and all grads
    match the same-mask oracle, and fully-masked rows stay exactly zero
    through the dropout rescale."""
    q, k, v = _qkv(12, s=128)
    # key-padding-style mask with one fully-masked query row per batch
    mask = jnp.zeros((B, 1, 128, 128), bool)
    mask = mask.at[:, :, 7, :].set(True)          # row 7 sees nothing
    mask = mask.at[:, :, :, 100:].set(True)       # keys 100+ padded

    def kfn(q, k, v):
        return flash_attention(q, k, v, mask=mask, dropout_rate=RATE,
                               dropout_seed=SEED, **BLOCKS)

    def ofn(q, k, v):
        return mha_reference(q, k, v, mask=mask, dropout_rate=RATE,
                             dropout_seed=SEED)

    out, ref = kfn(q, k, v), ofn(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-6)
    assert bool(jnp.all(out[:, :, 7] == 0.0))

    def loss(fn):
        return lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v)))

    gk = jax.grad(loss(kfn), argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss(ofn), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gk, go):
        np.testing.assert_allclose(a, b, atol=5e-6, err_msg=f"d{name}")
    assert bool(jnp.all(gk[0][:, :, 7] == 0.0))   # masked row: zero dq


def test_block_independent_and_large_bh():
    """The mask depends on global coordinates only: different kernel
    blockings agree bit-for-bit, and bh >= 3 works (a python-int bh
    once overflowed int32 in the oracle's hash)."""
    q, k, v = jax.random.normal(jax.random.PRNGKey(9), (3, 2, 3, 256, 64),
                                jnp.float32)
    a = flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=SEED,
                        block_q=256, block_k=256)
    b = flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=SEED,
                        block_q=128, block_k=128)
    ref = mha_reference(q, k, v, dropout_rate=RATE, dropout_seed=SEED)
    # same mask, different online-softmax accumulation order: agreement
    # is float-rounding-tight, not bitwise (a dropped entry differing
    # between blockings would show up as O(p/keep) ≈ 1e-2, not 1e-6)
    np.testing.assert_allclose(a, b, atol=2e-6)
    np.testing.assert_allclose(a, ref, atol=2e-6)
    np.testing.assert_allclose(b, ref, atol=2e-6)


def test_deterministic_and_seed_sensitive():
    q, k, v = _qkv(3)
    a = _kernel(q, k, v)
    b = _kernel(q, k, v)
    c = flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=SEED + 1,
                        **BLOCKS)
    assert bool(jnp.all(a == b))
    assert bool(jnp.any(a != c))


def test_rate_zero_identical_to_no_dropout():
    q, k, v = _qkv(4)
    a = flash_attention(q, k, v, **BLOCKS)
    b = flash_attention(q, k, v, dropout_rate=0.0, **BLOCKS)
    assert bool(jnp.all(a == b))


def test_drop_fraction_and_rescale():
    """v = I recovers the dropped probability matrix directly (its first
    D of S columns): entries are either 0 or clean-p/(1-rate); the zero
    fraction tracks rate."""
    s = 128
    q, k, _ = _qkv(5, s=s)
    v = jnp.broadcast_to(jnp.eye(s, D, dtype=jnp.float32), (B, H, s, D))
    pd = flash_attention(q, k, v, dropout_rate=RATE, dropout_seed=SEED,
                         **BLOCKS)
    p_clean = flash_attention(q, k, v, **BLOCKS)
    pd, p_clean = np.asarray(pd), np.asarray(p_clean)
    dropped = pd == 0.0
    frac = dropped.mean()
    assert abs(frac - RATE) < 0.02, frac
    np.testing.assert_allclose(pd[~dropped],
                               p_clean[~dropped] / (1.0 - RATE), rtol=1e-4)


def test_seed_required_and_rate_validated():
    q, k, v = _qkv(6)
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, k, v, dropout_rate=0.1)
    with pytest.raises(ValueError, match="dropout_rate"):
        flash_attention(q, k, v, dropout_rate=1.0, dropout_seed=0)


def test_traced_seed_no_retrace():
    """The seed is a traced operand: stepping it inside jit must reuse
    the compiled kernel (one trace) and still change the mask."""
    q, k, v = _qkv(7)
    traces = []

    @jax.jit
    def f(q, k, v, seed):
        traces.append(1)
        return flash_attention(q, k, v, dropout_rate=RATE,
                               dropout_seed=seed, **BLOCKS)

    a = f(q, k, v, jnp.int32(1))
    b = f(q, k, v, jnp.int32(2))
    assert len(traces) == 1
    assert bool(jnp.any(a != b))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_dropout_matches_unsharded(causal):
    """Context-sharded ring attention with dropout equals the unsharded
    oracle with the same seed: each shard pair offsets the counter hash
    to GLOBAL coordinates, so sharding does not change the mask."""
    import functools
    from jax.sharding import PartitionSpec as P
    from apex_tpu.ops.ring_attention import (ring_attention,
                                             ring_attention_reference)
    from apex_tpu.transformer import parallel_state

    cp, s = 4, 512
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=cp)
    try:
        mesh = parallel_state.get_mesh()
        ks = jax.random.split(jax.random.PRNGKey(11), 3)
        q, k, v = (jax.random.normal(kk, (B, H, s, D)) for kk in ks)

        def g(fn):
            def loss(q, k, v):
                return jnp.sum(jnp.sin(fn(q, k, v).astype(jnp.float32)))
            return jax.value_and_grad(loss, argnums=(0, 1, 2))

        def body(q, k, v):
            val, grads = g(lambda q, k, v: ring_attention(
                q, k, v, causal=causal, dropout_rate=RATE,
                dropout_seed=SEED))(q, k, v)
            return jax.lax.psum(val, "context"), grads

        spec = P(None, None, "context", None)
        val, grads = jax.jit(
            functools.partial(jax.shard_map, check_vma=False)(
                body, mesh=mesh, in_specs=(spec, spec, spec),
                out_specs=(P(), (spec, spec, spec))))(q, k, v)
        ref_val, ref_grads = g(lambda q, k, v: ring_attention_reference(
            q, k, v, causal=causal, dropout_rate=RATE,
            dropout_seed=SEED))(q, k, v)
        np.testing.assert_allclose(float(val), float(ref_val), rtol=1e-5)
        for name, a, b in zip("qkv", grads, ref_grads):
            np.testing.assert_allclose(a, b, atol=2e-5, err_msg=f"d{name}")
    finally:
        parallel_state.destroy_model_parallel()


def test_ulysses_dropout_reproducible_and_finite():
    """Ulysses dropout is rank-decorrelated (documented: not
    dense-matched); it must still be deterministic per seed with finite
    grads under the all-to-all resharding."""
    import functools
    from jax.sharding import PartitionSpec as P
    from apex_tpu.ops.ulysses_attention import ulysses_attention
    from apex_tpu.transformer import parallel_state

    cp, s, h = 2, 256, 4
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=cp)
    try:
        mesh = parallel_state.get_mesh()
        ks = jax.random.split(jax.random.PRNGKey(21), 3)
        q, k, v = (jax.random.normal(kk, (B, h, s, D)) for kk in ks)

        def run(seed):
            def body(q, k, v):
                def loss(q, k, v):
                    return jnp.sum(jnp.sin(ulysses_attention(
                        q, k, v, causal=True, dropout_rate=RATE,
                        dropout_seed=seed)))
                val, grads = jax.value_and_grad(
                    loss, argnums=(0, 1, 2))(q, k, v)
                return jax.lax.psum(val, "context"), grads

            spec = P(None, None, "context", None)
            return jax.jit(
                functools.partial(jax.shard_map, check_vma=False)(
                    body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=(P(), (spec, spec, spec))))(q, k, v)

        v1, g1 = run(SEED)
        v2, g2 = run(SEED)
        v3, _ = run(SEED + 1)
        assert float(v1) == float(v2) and float(v1) != float(v3)
        for a in g1:
            assert bool(jnp.all(jnp.isfinite(a)))
        for a, b in zip(g1, g2):
            assert bool(jnp.all(a == b))
    finally:
        parallel_state.destroy_model_parallel()


def test_padded_shape_with_dropout():
    """Non-lane-multiple sequence: padding + validity window + dropout
    compose; grads stay finite and zero in the padded region."""
    s = 200                      # pads to 256
    q, k, v = _qkv(8, s=s)

    def g(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       dropout_rate=RATE,
                                       dropout_seed=SEED))

    val, grads = jax.value_and_grad(g, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(float(val))
    for a in grads:
        assert bool(jnp.all(jnp.isfinite(a)))
