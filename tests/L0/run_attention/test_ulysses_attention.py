"""Ulysses (all-to-all sequence-parallel) attention vs full-sequence
oracle: the head/sequence resharded result must equal plain attention on
the gathered sequence — forward and grads, causal and bidirectional —
and agree with the ring strategy."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.ring_attention import ring_attention_reference
from apex_tpu.ops.ulysses_attention import ulysses_attention
from apex_tpu.transformer import parallel_state

CP = 4
B, H, S, D = 1, 4, 512, 64   # H % CP == 0; S/CP = 128 per rank


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=CP)
    yield
    parallel_state.destroy_model_parallel()


def _qkv(seed):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, H, S, D)) for k in ks)


SPEC = P(None, None, "context", None)


def _run(q, k, v, causal):
    mesh = parallel_state.get_mesh()

    def body(q, k, v):
        return ulysses_attention(q, k, v, causal=causal)

    return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(SPEC, SPEC, SPEC),
        out_specs=SPEC))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_full_attention(causal):
    q, k, v = _qkv(0)
    out = _run(q, k, v, causal)
    ref = ring_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_full_attention(causal):
    q, k, v = _qkv(1)
    mesh = parallel_state.get_mesh()

    def uly_loss(q, k, v):
        def body(q, k, v):
            o = ulysses_attention(q, k, v, causal=causal)
            return jax.lax.psum(jnp.sum(o.astype(jnp.float32) ** 2),
                                "context")
        return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(SPEC, SPEC, SPEC),
            out_specs=P()))(q, k, v)

    def ref_loss(q, k, v):
        o = ring_attention_reference(q, k, v, causal=causal)
        return jnp.sum(o.astype(jnp.float32) ** 2)

    gu = jax.grad(uly_loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_rejects_indivisible_heads():
    mesh = parallel_state.get_mesh()
    q = jnp.zeros((1, 3, 512, 64))   # 3 heads, cp=4

    def body(q):
        return ulysses_attention(q, q, q)

    with pytest.raises(ValueError, match="divisible"):
        jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(SPEC,), out_specs=SPEC))(q)


def test_cp1_degrades_to_flash():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=1)
    q, k, v = _qkv(2)
    out = ulysses_attention(q, k, v, causal=True)
    ref = ring_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_mismatched_axis_name_fails_loudly():
    """A typo'd/custom axis name inside a real mesh must raise, not
    silently attend within one shard."""
    mesh = parallel_state.get_mesh()
    q = jnp.zeros((1, 4, 512, 64))

    def body(q):
        return ulysses_attention(q, q, q, axis_name="contxt")

    with pytest.raises(Exception, match="contxt"):
        jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(SPEC,), out_specs=SPEC))(q)
