"""Flight recorder (ISSUE 10): the report is a pure function of the
run artifacts — the committed fixture reproduces the committed markdown
byte-for-byte, the JSON view parses, and the prom/percentile helpers
hold on their own."""
import json
import subprocess
import sys
from pathlib import Path

from apex_tpu.observability.report import (build_report,
                                           histogram_quantile, main,
                                           parse_prometheus, percentile,
                                           render_markdown)

FIXTURE = Path(__file__).parent / "fixtures" / "flight_run"


def _fixture_args(extra=()):
    return [str(FIXTURE),
            "--stats", str(FIXTURE / "xla_stats.json"),
            "--budget", str(FIXTURE / "budget.json"), *extra]


def test_golden_markdown_byte_stable(tmp_path, capsys):
    out = tmp_path / "report.md"
    assert main(_fixture_args(["--out", str(out)])) == 0
    capsys.readouterr()
    expected = (FIXTURE / "expected_report.md").read_text(
        encoding="utf-8")
    assert out.read_text(encoding="utf-8") == expected, (
        "the flight-recorder markdown drifted from the committed "
        "golden — if intentional, regenerate expected_report.md with "
        "the report CLI and commit it")


def test_golden_reproduces_twice_identically(capsys):
    main(_fixture_args())
    first = capsys.readouterr().out
    main(_fixture_args())
    second = capsys.readouterr().out
    assert first == second


def test_cli_module_entrypoint(tmp_path):
    """``python -m apex_tpu.observability.report`` — the documented
    invocation — produces the same golden bytes."""
    out = tmp_path / "cli.md"
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.observability.report",
         *_fixture_args(["--out", str(out)])],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    assert out.read_text(encoding="utf-8") == \
        (FIXTURE / "expected_report.md").read_text(encoding="utf-8")


def test_json_view_parses_and_matches_sections(capsys):
    assert main(_fixture_args(["--json"])) == 0
    report = json.loads(capsys.readouterr().out)
    assert set(report) == {"run", "train", "serve",
                           "compiled_attribution"}
    assert report["train"]["steps"] == 6
    assert report["train"]["badput"]["goodput_fraction"] > 0.5
    assert report["serve"]["finish_reasons"] == {"length": 1,
                                                 "truncated": 1}
    attr = report["compiled_attribution"]
    assert attr["train_step_dense"]["provenance"] == "xla:cost+memory"
    # the degraded executable reports NO compiled peak — the marker
    # rides instead of a fabricated number
    assert attr["inference_decode"]["compiled_peak_bytes"] is None
    assert attr["inference_decode"]["provenance"].startswith(
        "xla:cost-only")


def test_degraded_stats_dump_never_pairs_with_ledger_numbers():
    """A degraded dump entry must not have its 'unavailable:' marker
    rendered next to the ledger's numbers (or vice versa): one source
    per row, the better-provenance one wins."""
    budget = {"executables": {"x": {
        "comm_bytes": 0, "peak_live_bytes": 100,
        "compiled": {"provenance": "xla:cost+memory", "flops": 7,
                     "peak_hbm_bytes": 50, "peak_live_drift": 2.0}}}}
    stats = {"executables": {"x": {
        "provenance": "unavailable:no-cost-analysis-on-this-backend"}}}
    row = build_report([], "", stats=stats,
                       budget=budget)["compiled_attribution"]["x"]
    # the committed full-provenance ledger block wins wholesale
    assert row["provenance"] == "xla:cost+memory"
    assert row["compiled_flops"] == 7
    # and a fresh full dump wins over the ledger, with the drift
    # RECOMPUTED against the dump's numbers (the ledger's 2.0 was
    # est/50; carrying it next to the dump's 60 would be inconsistent)
    stats_full = {"executables": {"x": {
        "provenance": "xla:cost+memory", "flops": 9,
        "peak_hbm_bytes": 60}}}
    row = build_report([], "", stats=stats_full,
                       budget=budget)["compiled_attribution"]["x"]
    assert row["compiled_flops"] == 9
    assert row["peak_live_drift"] == round(100 / 60, 4)


def test_report_without_stats_uses_budget_compiled_blocks():
    events = []
    budget = {"executables": {"x": {
        "comm_bytes": 0, "peak_live_bytes": 100,
        "compiled": {"provenance": "xla:cost+memory", "flops": 7,
                     "peak_hbm_bytes": 50, "peak_live_drift": 2.0}}}}
    report = build_report(events, "", budget=budget)
    row = report["compiled_attribution"]["x"]
    assert row["compiled_flops"] == 7
    assert row["peak_live_drift"] == 2.0


def test_prom_parser_roundtrips_own_sink():
    from apex_tpu.observability import MetricsRegistry, render_prometheus
    reg = MetricsRegistry()
    reg.declared("train_steps_total").inc(3)
    reg.declared("serve_requests_finished_total").inc(2, reason="eos")
    h = reg.declared("train_step_seconds")
    for s in (0.01, 0.02, 0.2):
        h.observe(s)
    fams = parse_prometheus(render_prometheus(reg))
    assert fams["train_steps_total"]["type"] == "counter"
    assert ("train_steps_total", {}, 3.0) in \
        fams["train_steps_total"]["samples"]
    assert ("serve_requests_finished_total", {"reason": "eos"}, 2.0) in \
        fams["serve_requests_finished_total"]["samples"]
    # histogram suffixes file under the base family
    series = {s for s, _, _ in fams["train_step_seconds"]["samples"]}
    assert {"train_step_seconds_sum", "train_step_seconds_count"} <= \
        series
    assert histogram_quantile(fams, "train_step_seconds", 0.5) == 0.025


def test_percentile_nearest_rank():
    assert percentile([], 0.5) is None
    assert percentile([3.0], 0.99) == 3.0
    vals = [1.0, 2.0, 3.0, 4.0]
    assert percentile(vals, 0.5) == 2.0
    assert percentile(vals, 0.99) == 4.0


# -- numerics section (ISSUE 11) -------------------------------------------

NUMERICS_FIXTURE = Path(__file__).parent / "fixtures" / \
    "flight_run_numerics"


def test_numerics_golden_markdown_byte_stable(tmp_path, capsys):
    """A run WITH numerics events renders the Numerics section —
    autopsy table, grad-norm percentiles, loss-scale timeline — and
    the committed golden reproduces byte-for-byte."""
    out = tmp_path / "report.md"
    assert main([str(NUMERICS_FIXTURE), "--out", str(out)]) == 0
    capsys.readouterr()
    expected = (NUMERICS_FIXTURE / "expected_report.md").read_text(
        encoding="utf-8")
    got = out.read_text(encoding="utf-8")
    assert got == expected, (
        "the numerics flight-recorder markdown drifted from the "
        "committed golden — if intentional, regenerate "
        "expected_report.md with the report CLI and commit it")
    assert "## Numerics" in got
    assert "overflow autopsy step" in got
    assert "['w1'] (64)" in got


def test_numerics_json_section_shape(capsys):
    assert main([str(NUMERICS_FIXTURE), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    nx = report["numerics"]
    assert nx["observed_steps"] == 8
    assert nx["grad_norm"]["samples"] == 7    # the poisoned step is null
    assert nx["loss_scale_backoffs"] == 1
    assert nx["loss_scale"]["initial"] == 2 * nx["loss_scale"]["final"]
    assert nx["loss_scale"]["changes"] == [[3, 32768.0]]
    [autopsy] = nx["autopsies"]
    assert autopsy["leaves"] == [{"leaf": "['w1']", "nonfinite": 64}]
    assert nx["overflow_leaves"] == {"['w1']": 64.0}


def test_serve_section_renders_speculation_accept_rate():
    """ISSUE 15 satellite: a run whose metrics carry the speculation
    families gets a speculation block in the serve section (verify
    rounds, drafted/accepted/emitted, acceptance rate) — and a run
    WITHOUT them (every pre-PR-15 run dir) renders none, which the
    byte-stable goldens above already pin."""
    from apex_tpu.observability.report import render_markdown
    prom = "\n".join([
        "serve_requests_submitted_total 4",
        "serve_requests_finished_total{reason=\"length\"} 4",
        "serve_spec_verify_steps_total 9",
        "serve_spec_drafted_tokens_total 36",
        "serve_spec_accepted_tokens_total 27",
        "serve_spec_emitted_tokens_total 33",
        "serve_spec_acceptance_rate 0.75",
        "",
    ])
    report = build_report([], prom)
    spec = report["serve"]["speculation"]
    assert spec["verify_steps"] == 9.0
    assert spec["drafted"] == 36.0
    assert spec["accepted"] == 27.0
    assert spec["emitted"] == 33.0
    assert spec["acceptance_rate"] == 0.75
    md = render_markdown(report)
    assert "| speculation | value |" in md
    assert "| acceptance_rate | 0.75 |" in md
    # no verify steps -> no block (the pre-PR-15 predicate)
    bare = build_report([], "serve_requests_submitted_total 4\n")
    assert "speculation" not in bare["serve"]


def test_report_without_numerics_stays_byte_stable(capsys):
    """Back-compat (ISSUE 11 satellite): a pre-PR-11 run dir — the
    ISSUE 10 fixture, committed before numerics existed — renders NO
    Numerics section and still reproduces its committed golden
    byte-for-byte (the section predicate never fires on absent
    signals)."""
    main(_fixture_args())
    got = capsys.readouterr().out
    assert "## Numerics" not in got
    assert "numerics" not in build_report(
        [], (FIXTURE / "metrics.prom").read_text(encoding="utf-8"))
    assert got == (FIXTURE / "expected_report.md").read_text(
        encoding="utf-8")


# -- SLO section + per-request waterfall (ISSUE 13) -------------------------

SLO_FIXTURE = Path(__file__).parent / "fixtures" / "flight_run_slo"


def test_slo_golden_markdown_byte_stable(tmp_path, capsys):
    """A run with tracing + SLO armed renders the SLO section — burn
    rates, budget remaining, violating tenants, shed tallies — and the
    committed golden reproduces byte-for-byte."""
    out = tmp_path / "report.md"
    assert main([str(SLO_FIXTURE), "--out", str(out)]) == 0
    capsys.readouterr()
    got = out.read_text(encoding="utf-8")
    assert got == (SLO_FIXTURE / "expected_report.md").read_text(
        encoding="utf-8"), (
        "the SLO flight-recorder markdown drifted from the committed "
        "golden — if intentional, regenerate expected_report.md with "
        "the report CLI and commit it")
    assert "## SLO" in got
    assert "violating_tenants**: acme" in got


def test_slo_json_section_shape(capsys):
    assert main([str(SLO_FIXTURE), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    slo = report["slo"]
    assert slo["slos"]["ttft_p99"]["burn_rate"] == 50.0
    assert slo["slos"]["ttft_p99"]["budget_remaining"] == 0.0
    assert slo["slos"]["ttft_p99"]["violations"] == 1.0
    assert slo["slos"]["decode_token_p99"]["burn_rate"] == 0.0
    assert slo["violating_tenants"] == ["acme"]
    assert slo["tenant_goodput"] == {"acme": 0.5, "default": 1.0}
    assert slo["shed_requests"] == 1.0
    assert slo["overloaded"] is False and slo["overload_events"] == 2


def test_trace_waterfall_golden(tmp_path, capsys):
    """`report --trace 1`: the per-request waterfall reproduces its
    committed golden byte-for-byte and reads as a lifecycle."""
    out = tmp_path / "trace.md"
    assert main([str(SLO_FIXTURE), "--trace", "1",
                 "--out", str(out)]) == 0
    capsys.readouterr()
    got = out.read_text(encoding="utf-8")
    assert got == (SLO_FIXTURE / "expected_trace.md").read_text(
        encoding="utf-8"), (
        "the trace-waterfall markdown drifted from the committed "
        "golden — if intentional, regenerate expected_trace.md with "
        "`report --trace 1 --out ...` and commit it")
    for span in ("queued", "admitted", "cow_copy", "prefill_chunk",
                 "first_token", "decode", "retired"):
        assert span in got, span
    assert "start=64 tokens=64 bucket=64" in got


def test_trace_json_view(capsys):
    assert main([str(SLO_FIXTURE), "--trace", "1", "--json"]) == 0
    [trace] = json.loads(capsys.readouterr().out)
    assert trace["uid"] == 1 and trace["wave"] == 1
    seqs = [s["seq"] for s in trace["spans"]]
    assert seqs == sorted(seqs)
    terminals = [s for s in trace["spans"]
                 if s["span"] in ("retired", "rejected")]
    assert len(terminals) == 1 and terminals[0]["detail"] == "length"


def test_trace_shed_request_ends_rejected(capsys):
    assert main([str(SLO_FIXTURE), "--trace", "2", "--json"]) == 0
    [trace] = json.loads(capsys.readouterr().out)
    assert [s["span"] for s in trace["spans"]] == ["rejected"]
    assert trace["spans"][0]["detail"] == "shed"


def test_trace_unknown_uid_fails_loudly(capsys):
    assert main([str(SLO_FIXTURE), "--trace", "99"]) == 1
    err = capsys.readouterr().err
    assert "no trace_span events for uid 99" in err


def test_pre_pr13_run_dirs_have_no_slo_section(capsys):
    """Back-compat (acceptance): the ISSUE 10/11 fixtures — committed
    before SLOs existed — render NO SLO section and still reproduce
    their goldens (asserted byte-for-byte by their own tests above)."""
    main(_fixture_args())
    assert "## SLO" not in capsys.readouterr().out
    main([str(NUMERICS_FIXTURE)])
    assert "## SLO" not in capsys.readouterr().out
    assert "slo" not in build_report(
        [], (FIXTURE / "metrics.prom").read_text(encoding="utf-8"))


# -- measured attribution (ISSUE 14) ----------------------------------------

MEASURED_FIXTURE = Path(__file__).parent / "fixtures" / \
    "flight_run_measured"
ALL_PRE_PR14_FIXTURES = (FIXTURE, NUMERICS_FIXTURE, SLO_FIXTURE)


def test_measured_golden_markdown_byte_stable(tmp_path, capsys):
    """A run whose profiler capture was ingested renders the Measured
    attribution section — category/collective tables, skew, the
    model-vs-measured drift — and the committed golden reproduces
    byte-for-byte."""
    out = tmp_path / "report.md"
    assert main([str(MEASURED_FIXTURE), "--out", str(out)]) == 0
    capsys.readouterr()
    got = out.read_text(encoding="utf-8")
    assert got == (MEASURED_FIXTURE / "expected_report.md").read_text(
        encoding="utf-8"), (
        "the measured flight-recorder markdown drifted from the "
        "committed golden — if intentional, regenerate "
        "expected_report.md with the report CLI and commit it")
    assert "## Measured attribution" in got
    assert "measured:trace" in got
    assert "skew.slowest_over_median" in got


def test_measured_json_section_shape(capsys):
    assert main([str(MEASURED_FIXTURE), "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    m = report["measured"]
    assert m["provenance"] == "measured:trace"
    assert m["ranks"] == 2 and m["captures"] == 1
    assert m["window_us"] == 160.0 and m["step_us"] == 80.0
    assert m["exposed_comm_us"] == 30.0
    assert m["model_exposed_comm_us"] == 10.0
    assert m["exposed_comm_drift_ratio"] == 1.5
    assert m["mfu"] == 0.249902
    assert m["categories"]["dot"] == 100.0
    assert m["skew"]["collective_start_spread_us"]["all_gather"] == 12.0


def test_attribution_detail_view_golden(tmp_path, capsys):
    """`report --attribution`: the per-capture detail view reproduces
    its committed golden byte-for-byte."""
    out = tmp_path / "attribution.md"
    assert main([str(MEASURED_FIXTURE), "--attribution",
                 "--out", str(out)]) == 0
    capsys.readouterr()
    got = out.read_text(encoding="utf-8")
    assert got == (MEASURED_FIXTURE /
                   "expected_attribution.md").read_text(
        encoding="utf-8"), (
        "the attribution detail-view markdown drifted from the "
        "committed golden — if intentional, regenerate "
        "expected_attribution.md with `report --attribution --out "
        "...` and commit it")
    for needle in ("| dot | 100 |", "| all_gather | 28 | 1 |",
                   "skew.per_rank_window_us**: 130, 160"):
        assert needle in got, needle


def test_attribution_view_json_and_missing(capsys):
    assert main([str(MEASURED_FIXTURE), "--attribution", "--json"]) == 0
    [ev] = json.loads(capsys.readouterr().out)
    assert ev["kind"] == "attribution"
    assert ev["collectives"]["reduce_scatter"]["time_us"] == 20.0
    # a run with no ingested capture fails loudly, naming the knob
    assert main([str(FIXTURE), "--attribution"]) == 1
    assert "APEX_TPU_PROFILE_DIR" in capsys.readouterr().err


def test_measured_section_prom_fallback():
    """A run whose JSONL was lost but whose prom snapshot survived:
    the measured summary falls back to the trace_* families."""
    from apex_tpu.observability import MetricsRegistry, render_prometheus
    reg = MetricsRegistry()
    reg.declared("trace_window_us").set(160.0)
    reg.declared("trace_mfu").set(0.25)
    reg.declared("trace_category_time_us").set(100.0, category="dot")
    reg.declared("trace_rank_step_skew").set(1.23)
    m = build_report([], render_prometheus(reg))["measured"]
    assert m["captures"] == 0
    assert m["window_us"] == 160.0 and m["mfu"] == 0.25
    assert m["categories"] == {"dot": 100.0}
    assert m["skew"]["slowest_over_median"] == 1.23


def test_degraded_attribution_renders_marker_not_zeros(capsys):
    """The acceptance contract: a run whose armed capture degraded
    renders the unavailable: marker and NO fabricated numbers."""
    events = [{"ts": 1.0, "kind": "attribution",
               "profile_dir": "/tmp/p",
               "provenance": "unavailable:no-trace-files", "ranks": 0,
               "window_us": None, "busy_us": None, "host_gap_us": None,
               "compute_us": None, "exposed_comm_us": None,
               "coverage": None, "steps": None, "step_us": None,
               "mfu": None, "mfu_provenance": None,
               "model_exposed_comm_us": None,
               "exposed_comm_drift_ratio": None, "categories": {},
               "collectives": {}, "skew": None}]
    report = build_report(events, "")
    m = report["measured"]
    assert m["provenance"] == "unavailable:no-trace-files"
    for key in ("window_us", "mfu", "exposed_comm_us", "categories"):
        assert key not in m, key
    md = render_markdown(report)
    assert "unavailable:no-trace-files" in md
    assert "**window_us**" not in md


def test_pre_pr14_run_dirs_render_byte_identically(capsys):
    """Back-compat satellite: every pre-PR-14 golden run dir —
    committed before measured attribution existed — renders NO
    Measured-attribution section and reproduces its committed golden
    byte-for-byte when no trace is present."""
    for fixture in ALL_PRE_PR14_FIXTURES:
        args = _fixture_args() if fixture is FIXTURE else [str(fixture)]
        assert main(args) == 0
        got = capsys.readouterr().out
        assert "## Measured attribution" not in got, fixture.name
        assert got == (fixture / "expected_report.md").read_text(
            encoding="utf-8"), fixture.name
    assert "measured" not in build_report(
        [], (FIXTURE / "metrics.prom").read_text(encoding="utf-8"))


def test_numerics_section_histogram_fallback_from_prom_only():
    """A run whose JSONL was lost but whose prom snapshot survived:
    grad-norm percentiles fall back to bucket resolution from
    train_grad_norm_hist."""
    from apex_tpu.observability import MetricsRegistry, render_prometheus
    reg = MetricsRegistry()
    h = reg.declared("train_grad_norm_hist")
    for v in (0.02, 0.25, 0.26, 0.9):
        h.observe(v)
    reg.declared("train_param_norm").set(3.5)
    report = build_report([], render_prometheus(reg))
    nx = report["numerics"]
    assert nx["grad_norm"]["samples"] == 0
    assert nx["grad_norm"]["p50"] == 0.3      # bucket bound covering 2/4
    assert nx["param_norm"] == 3.5
