"""Metrics registry unit tests: counters/gauges/labels semantics and
the histogram bucketing contract (ISSUE 8 L0 coverage)."""
import pytest

from apex_tpu.observability import (Counter, Gauge, Histogram,
                                    MetricsRegistry)
from apex_tpu.observability import schema


# -- counters / gauges -------------------------------------------------------

def test_counter_accumulates_and_rejects_decrease():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)


def test_counter_labels_are_independent_series():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help", labels=("reason",))
    c.inc(reason="eos")
    c.inc(reason="eos")
    c.inc(reason="length")
    assert c.value(reason="eos") == 2
    assert c.value(reason="length") == 1
    assert c.value(reason="never") == 0
    assert c.total() == 3


def test_label_names_must_match_declaration():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "help", labels=("reason",))
    with pytest.raises(ValueError, match="declared label"):
        c.inc(cause="eos")
    with pytest.raises(ValueError, match="declared label"):
        c.inc()                      # missing the declared label


def test_gauge_set_and_set_max_ratchet():
    reg = MetricsRegistry()
    g = reg.gauge("g", "help")
    assert g.value() is None
    g.set(3)
    g.set(1)
    assert g.value() == 1.0          # plain set overwrites
    g.set_max(5)
    g.set_max(2)
    assert g.value() == 5.0          # ratchet keeps the peak


def test_create_or_get_is_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "help")
    assert reg.counter("x_total") is a
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")


# -- histogram bucketing -----------------------------------------------------

def test_histogram_bucketing_boundaries():
    """A sample lands in the FIRST bucket whose upper bound covers it
    (le semantics: boundary values land in their own bucket), overflow
    goes to +Inf."""
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 1.0, 9.9, 11.0):
        h.observe(v)
    # raw (non-cumulative) landing: [<=0.1]=2 (0.05, 0.1 itself),
    # (0.1,1.0]=2, (1.0,10]=1, +Inf=1
    assert h._values[()]["counts"] == [2, 2, 1, 1]
    # cumulative _bucket{le=} series (what Prometheus exposes)
    assert h.cumulative_counts() == [2, 4, 5, 6]
    assert h.count() == 6
    assert h.sum() == pytest.approx(0.05 + 0.1 + 0.5 + 1.0 + 9.9 + 11.0)


def test_histogram_buckets_sorted_and_required():
    reg = MetricsRegistry()
    h = reg.histogram("h2_seconds", "help", buckets=(1.0, 0.1, 10.0))
    assert h.buckets == (0.1, 1.0, 10.0)     # sorted on construction
    with pytest.raises(ValueError, match="needs buckets"):
        reg.histogram("h3_seconds", "help")


def test_histogram_quantile_is_bucket_resolution():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "help", buckets=(0.001, 0.01, 0.1))
    assert h.quantile(0.5) is None           # empty
    for _ in range(99):
        h.observe(0.005)
    h.observe(0.05)
    assert h.quantile(0.5) == 0.01           # bucket upper bound
    assert h.quantile(0.99) == 0.01
    assert h.quantile(1.0) == 0.1
    h.observe(1e9)                           # +Inf mass
    assert h.quantile(1.0) == 0.1            # reports largest finite


def test_histogram_labeled_series_are_independent():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "help", labels=("leg",),
                      buckets=(1.0,))
    h.observe(0.5, leg="a")
    h.observe(2.0, leg="b")
    assert h.count(leg="a") == 1
    assert h.count(leg="b") == 1
    assert h.cumulative_counts(leg="a") == [1, 1]
    assert h.cumulative_counts(leg="b") == [0, 1]


# -- schema-declared creation (the only production path) ---------------------

def test_declared_creates_from_schema_and_rejects_unknown():
    reg = MetricsRegistry()
    h = reg.declared("serve_ttft_seconds")
    assert isinstance(h, Histogram)
    assert h.buckets == schema.METRIC_SPECS["serve_ttft_seconds"].buckets
    c = reg.declared("serve_requests_finished_total")
    assert isinstance(c, Counter)
    assert c.labels == ("reason",)
    with pytest.raises(KeyError, match="not declared"):
        reg.declared("made_up_metric")


def test_every_declared_family_instantiates():
    """Every spec in the pinned schema constructs the right instrument
    kind — a spec typo cannot lurk until first runtime use."""
    reg = MetricsRegistry()
    kinds = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
    for name, spec in schema.METRIC_SPECS.items():
        inst = reg.declared(name)
        assert isinstance(inst, kinds[spec.kind]), name


def test_emit_event_rejects_undeclared_kind():
    reg = MetricsRegistry()
    with pytest.raises(KeyError, match="not declared"):
        reg.emit_event("made_up_event", x=1)


def test_snapshot_shape():
    reg = MetricsRegistry()
    reg.counter("a_total", "h").inc(2)
    reg.gauge("b", "h").set(7)
    h = reg.histogram("c_seconds", "h", labels=("leg",), buckets=(1.0,))
    h.observe(0.5, leg="x")
    snap = reg.snapshot()
    assert snap["counters"] == {"a_total": 2.0}
    assert snap["gauges"] == {"b": 7.0}
    assert snap["histograms"]["c_seconds{leg=x}"]["count"] == 1
