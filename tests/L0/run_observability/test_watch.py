"""Perf-regression watch (ISSUE 13): the committed fixture pairs —
one where round 2 regresses a leg and the ratchet fires, a clean twin
that passes, and a shuffled-stamp pair where ordering hygiene rejects
the lying capture — plus the comparability/direction unit rules."""
import json
from pathlib import Path

import pytest

from apex_tpu.observability import watch

FIXTURES = Path(__file__).parent / "fixtures"


def _write(dirpath, name, payload):
    (dirpath / name).write_text(json.dumps(payload) + "\n",
                                encoding="utf-8")


# -- the committed self-test fixtures (CI satellite) ------------------------

def test_ratchet_fires_on_committed_regression_fixture(capsys):
    rc = watch.main([str(FIXTURES / "watch_regress")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSED mini_decode_us" in out
    assert "1 regression(s)" in out
    # the throughput leg stayed inside slack — one bad leg, one firing
    res = watch.analyze(str(FIXTURES / "watch_regress"))
    by_metric = {r["metric"]: r for r in res["rows"]}
    assert by_metric["mini_decode_us"]["status"] == "regressed"
    assert by_metric["mini_decode_us"]["ratio"] == pytest.approx(1.3)
    assert by_metric["mini_tokens_per_s"]["status"] == "ok"


def test_clean_twin_passes(capsys):
    rc = watch.main([str(FIXTURES / "watch_clean")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESSED" not in out
    assert "no regressions" in out


def test_shuffled_stamps_reject_the_lying_capture(capsys):
    """The shuffled pair is the REGRESS pair with swapped stamps: r2's
    wall clock precedes r1's, so ordering hygiene rejects r2 before
    trending — the (real) regression inside it must NOT fire, and the
    rejection is loud."""
    rc = watch.main([str(FIXTURES / "watch_shuffled")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REJECTED r2_mini.json" in out
    assert "REGRESSED" not in out
    res = watch.analyze(str(FIXTURES / "watch_shuffled"))
    [rej] = res["rejected"]
    assert rej["capture"] == "r2_mini.json"
    assert "precedes" in rej["reason"]
    # the surviving r1 trends alone
    assert all(r["status"] == "no-prior" for r in res["rows"])


def test_json_output_parses(capsys):
    assert watch.main([str(FIXTURES / "watch_regress"),
                       "--json"]) == 1
    res = json.loads(capsys.readouterr().out)
    assert res["regressions"][0]["metric"] == "mini_decode_us"


def test_slack_is_honored(capsys):
    # at x1.35 slack the 1.3x decode regression is tolerated
    assert watch.main([str(FIXTURES / "watch_regress"),
                       "--slack", "1.35"]) == 0
    capsys.readouterr()


# -- unit rules --------------------------------------------------------------

def test_metric_direction_classifier():
    assert watch.metric_direction("infer_decode_token_us") == "lower"
    assert watch.metric_direction("infer_decode_token_us_median") \
        == "lower"
    assert watch.metric_direction("us_gather") == "lower"
    assert watch.metric_direction("sec_per_step") == "lower"
    assert watch.metric_direction("bert_sec_per_step_median") == "lower"
    assert watch.metric_direction("moe_tokens_per_s") == "higher"
    assert watch.metric_direction(
        "gpt_train_tokens_per_sec_1chip") == "higher"
    assert watch.metric_direction("layernorm_gbps") == "higher"
    assert watch.metric_direction("mfu") == "higher"
    assert watch.metric_direction("mfu_compiled") == "higher"
    assert watch.metric_direction("bert_mfu") == "higher"
    assert watch.metric_direction("adam_roofline") == "higher"
    assert watch.metric_direction("flash_attn_speedup") == "higher"
    # ISSUE 14: the measured-attribution stamps trend too — the
    # model-vs-measured drift ratio is lower-is-better (a widening
    # exposed-comm gap is a regression), measured MFU higher
    assert watch.metric_direction("exposed_comm_drift_ratio") == "lower"
    assert watch.metric_direction("measured_step_us") == "lower"
    assert watch.metric_direction("measured_exposed_comm_us") == "lower"
    assert watch.metric_direction("measured_mfu") == "higher"
    # ISSUE 18: the hot-but-evicted TTFT stamp (swap-in uploads
    # instead of recompute) trends lower-is-better like every latency,
    # and the swap page tallies are workload counts, not measurements
    assert watch.metric_direction("infer_prefix_hot_evicted_ttft_us") \
        == "lower"
    # the measured cross-rank straggler ratio (slowest/median window)
    # is lower-is-better — a widening skew is a regression
    assert watch.metric_direction("measured_tp_rank_step_skew") \
        == "lower"
    # context, not measurements: shapes, knob stamps, SLO targets
    assert watch.metric_direction("infer_shape") is None
    assert watch.metric_direction("xent_chunk") is None
    assert watch.metric_direction("infer_slo_ttft") is None
    assert watch.metric_direction("infer_trace") is None
    assert watch.metric_direction("adam_nelem") is None
    assert watch.metric_direction("infer_swap_batch_pages") is None
    assert watch.metric_direction("infer_host_tier_bytes") is None
    assert watch.metric_direction("infer_swap_in_pages") is None
    assert watch.metric_direction("measured_attribution_provenance") \
        is None


def test_widening_exposed_comm_drift_fails_the_watch(tmp_path):
    """ISSUE 14 acceptance: the measured-vs-model exposed-comm drift
    table trends across captures — a widening gap (overlap the model
    claims but the hardware no longer delivers) fails the watch like
    any latency regression."""
    _write(tmp_path, "r1_a.json",
           {"_leg": "x", "backend": "tpu",
            "measured_attribution_provenance": "measured:trace",
            "measured_step_us": 80.0,
            "exposed_comm_drift_ratio": 1.1})
    _write(tmp_path, "r2_a.json",
           {"_leg": "x", "backend": "tpu",
            "measured_attribution_provenance": "measured:trace",
            "measured_step_us": 82.0,
            "exposed_comm_drift_ratio": 1.6})    # gap widened 1.45x
    res = watch.analyze(str(tmp_path))
    by_metric = {r["metric"]: r for r in res["rows"]}
    assert by_metric["exposed_comm_drift_ratio"]["status"] == "regressed"
    assert by_metric["measured_step_us"]["status"] == "ok"


def test_shape_or_knob_change_starts_a_fresh_series(tmp_path):
    """Same metric, different shape (or knob): no comparison — a
    bigger model measuring slower is not a regression."""
    _write(tmp_path, "r1_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 100.0,
            "mini_shape": [2, 64], "mini_chunk": 8})
    _write(tmp_path, "r2_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 900.0,
            "mini_shape": [2, 1024], "mini_chunk": 8})
    res = watch.analyze(str(tmp_path))
    assert all(r["status"] == "no-prior" for r in res["rows"])
    # knob change isolates the same way
    _write(tmp_path, "r3_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 900.0,
            "mini_shape": [2, 64], "mini_chunk": 64})
    res = watch.analyze(str(tmp_path))
    assert not res["regressions"]


def test_modifier_prefixed_metrics_keep_their_leg_context(tmp_path):
    """`fused_adam_us` belongs to the adam leg even though its first
    token is the modifier: `adam_nelem` must key its comparability, so
    a size change forks the series (review fix)."""
    _write(tmp_path, "r1_a.json",
           {"_leg": "adam", "backend": "tpu", "fused_adam_us": 4300.0,
            "adam_nelem": 100000000})
    _write(tmp_path, "r2_a.json",
           {"_leg": "adam", "backend": "tpu", "fused_adam_us": 430.0,
            "adam_nelem": 1000000})      # 100x smaller problem
    res = watch.analyze(str(tmp_path))
    rows = [r for r in res["rows"] if r["metric"] == "fused_adam_us"]
    assert all(r["status"] == "no-prior" for r in rows)
    # same nelem DOES compare
    ctx1 = watch.context_for({"fused_adam_us": 1.0,
                              "adam_nelem": 5}, "fused_adam_us")
    assert ("adam_nelem", "5") in ctx1


def test_backends_never_compare(tmp_path):
    _write(tmp_path, "r1_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 100.0})
    _write(tmp_path, "r2_a.json",
           {"_leg": "x", "backend": "cpu", "mini_us": 5000.0})
    assert not watch.analyze(str(tmp_path))["regressions"]


def test_best_prior_not_previous(tmp_path):
    """The baseline is the BEST earlier round: a slow r2 must not
    lower the bar for r3."""
    for rnd, us in ((1, 100.0), (2, 140.0), (3, 130.0)):
        _write(tmp_path, f"r{rnd}_a.json",
               {"_leg": "x", "backend": "tpu", "mini_us": us})
    res = watch.analyze(str(tmp_path))
    [row] = res["rows"]
    assert row["best_prior"] == 100.0
    assert row["status"] == "regressed"       # 130 > 100 * 1.15


def test_higher_is_better_direction(tmp_path):
    for rnd, tps in ((1, 1000.0), (2, 800.0)):
        _write(tmp_path, f"r{rnd}_a.json",
               {"_leg": "x", "backend": "tpu",
                "mini_tokens_per_s": tps})
    [row] = watch.analyze(str(tmp_path))["rows"]
    assert row["status"] == "regressed"       # 800 < 1000 / 1.15


def test_scrubbed_values_never_trend(tmp_path):
    """An RTT-collapsed 0.0 µs 'best' must not become the ratchet bar
    (the capture-hygiene rules apply before trending)."""
    _write(tmp_path, "r1_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 0.0})
    _write(tmp_path, "r2_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 120.0})
    [row] = watch.analyze(str(tmp_path))["rows"]
    assert row["status"] == "no-prior"


def test_unstamped_legacy_captures_are_exempt_from_ordering(tmp_path):
    _write(tmp_path, "r1_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 100.0})
    _write(tmp_path, "r2_a.json",
           {"_leg": "x", "backend": "tpu", "mini_us": 101.0,
            "captured_at": "2026-08-01T00:00:00+00:00"})
    res = watch.analyze(str(tmp_path))
    assert res["rejected"] == []
    [row] = res["rows"]
    assert row["status"] == "ok"


def test_full_capture_shape_flattens(tmp_path):
    """Orchestrator captures ({metric, value, extras}) trend their
    headline value under the metric name."""
    for rnd, v in ((1, 100000.0), (2, 50000.0)):
        _write(tmp_path, f"r{rnd}_full.json",
               {"metric": "gpt_train_tokens_per_sec_1chip", "value": v,
                "unit": "tokens/s",
                "extras": {"backend": "tpu", "mfu": 0.4}})
    res = watch.analyze(str(tmp_path))
    by_metric = {r["metric"]: r for r in res["rows"]}
    assert by_metric["gpt_train_tokens_per_sec_1chip"]["status"] \
        == "regressed"


def test_real_bench_captures_load_without_error():
    """The committed history parses end to end (regressions there are
    findings, not failures — PERF.md round 13 records them)."""
    capdir = Path(__file__).parents[3] / "bench_captures"
    res = watch.analyze(str(capdir))
    assert res["captures"] >= 9
    assert res["rejected"] == []
