"""Measured-truth attribution (ISSUE 14): trace ingestion + category
mapping + interval-overlap exposed-comm math + multi-rank skew +
degradation markers + the profile_capture hardening satellite."""
import json
import subprocess
import sys
from pathlib import Path

import pytest

from apex_tpu.observability.attribution import (COVERAGE_TOLERANCE,
                                                attribute,
                                                interval_measure,
                                                merge_intervals, publish,
                                                subtract_intervals)
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.observability.trace_ingest import (PROVENANCE_MEASURED,
                                                 RankTrace, TraceEvent,
                                                 categorize,
                                                 find_trace_files,
                                                 load_profile_dirs,
                                                 parse_trace_file)

GOLDEN_PROFILE = Path(__file__).parent / "fixtures" / "trace_profile"


def _ev(name, cat, start, end):
    return TraceEvent(name=name, category=cat, start_us=float(start),
                      dur_us=float(end - start))


def _rank(events, source="rank.trace.json.gz"):
    return RankTrace(source=source, provenance=PROVENANCE_MEASURED,
                     events=events)


# -- category mapping -------------------------------------------------------

@pytest.mark.parametrize("name,expected", [
    ("dot.6", "dot"),
    ("convolution.2", "dot"),
    ("fusion.123", "fusion"),
    ("loop_fusion.4", "fusion"),
    ("all-gather.3", "collective:all_gather"),
    ("all-gather-start.3", "collective:all_gather"),
    ("all-gather-done.3", "collective:all_gather"),
    ("all-reduce.1", "collective:all_reduce"),
    ("psum.2", "collective:all_reduce"),
    ("reduce-scatter.9", "collective:reduce_scatter"),
    ("collective-permute.1", "collective:ppermute"),
    ("collective-permute-start.1", "collective:ppermute"),
    ("all-to-all.5", "collective:all_to_all"),
    ("copy.8", "copy"),
    ("copy-start.2", "copy"),
    ("infeed.1", "copy"),
    ("outfeed.1", "copy"),
    ("tanh.4.clone", "other"),
    ("reduce.77", "other"),
    ("broadcast.3", "other"),
    ("%dot.5", "dot"),
])
def test_categorize(name, expected):
    assert categorize(name) == expected


def test_wrapper_ops_are_skipped_not_attributed():
    """call/while/conditional wrap their leaves, which are traced
    individually — counting both would attribute the same wall time
    twice."""
    for name in ("call", "while.2", "conditional.1"):
        assert categorize(name) is None


# -- interval arithmetic (the exposed-comm primitive) -----------------------

def test_merge_and_measure():
    assert merge_intervals([]) == []
    merged = merge_intervals([(5, 10), (0, 3), (2, 6), (20, 21),
                              (9, 9)])
    assert merged == [(0, 10), (20, 21)]
    assert interval_measure(merged) == 11


def test_subtract_intervals_exposed_comm_math():
    """Hand-built overlap: collective (50, 70) against compute
    (0, 55) + (60, 100) leaves exactly (55, 60) exposed."""
    coll = merge_intervals([(50, 70)])
    comp = merge_intervals([(0, 55), (60, 100)])
    assert subtract_intervals(coll, comp) == [(55, 60)]
    # fully covered -> nothing; fully exposed -> itself
    assert subtract_intervals([(10, 20)], [(0, 30)]) == []
    assert subtract_intervals([(10, 20)], [(30, 40)]) == [(10, 20)]
    # cover splitting the target twice
    assert subtract_intervals([(0, 10)], [(2, 4), (6, 8)]) == \
        [(0, 2), (4, 6), (8, 10)]


# -- single-rank attribution on hand-built events ---------------------------

def _scenario_rank0():
    return _rank([
        _ev("dot.1", "dot", 0, 40),
        _ev("fusion.2", "fusion", 40, 55),
        _ev("all-gather.3", "collective:all_gather", 50, 70),
        _ev("dot.4", "dot", 60, 100),
        _ev("reduce-scatter.5", "collective:reduce_scatter", 100, 112),
        _ev("copy.6", "copy", 112, 118),
        _ev("tanh.7", "other", 118, 130),
    ], source="rank0.trace.json.gz")


def test_attribute_category_times_and_exposed_comm():
    rec = attribute([_scenario_rank0()])
    assert rec["provenance"] == "measured:trace"
    assert rec["categories"] == {
        "dot": 80.0, "fusion": 15.0, "collective:all_gather": 20.0,
        "collective:reduce_scatter": 12.0, "copy": 6.0, "other": 12.0}
    assert rec["window_us"] == 130.0
    assert rec["busy_us"] == 130.0
    assert rec["host_gap_us"] == 0.0
    # compute = dot + fusion + other union = (0,55)+(60,100)+(118,130)
    assert rec["compute_us"] == 107.0
    # all-gather (50,70): (55,60) exposed; reduce-scatter (100,112):
    # fully exposed -> 5 + 12
    assert rec["exposed_comm_us"] == 17.0
    # attributed category times + host gap sum to the window within the
    # documented tolerance (the acceptance-criterion arithmetic)
    coverage = (sum(rec["categories"].values())
                + rec["host_gap_us"]) / rec["window_us"]
    assert rec["coverage"] == pytest.approx(coverage, abs=1e-3)
    assert abs(coverage - 1.0) <= COVERAGE_TOLERANCE
    assert rec["collectives"]["all_gather"]["count"] == 1
    assert rec["collectives"]["reduce_scatter"]["time_us"] == 12.0
    assert "skew" not in rec          # single rank: no skew block


def test_attribute_steps_mfu_and_model_comparison():
    rec = attribute([_scenario_rank0()], steps=2, flops_per_step=1e9,
                    device_kind="cpu-falls-to-default",
                    model_exposed_comm_us=10.0)
    assert rec["steps"] == 2
    assert rec["step_us"] == 65.0
    assert rec["step_exposed_comm_us"] == 8.5
    # measured MFU = steps * flops / compute seconds / default-chip peak
    from apex_tpu.chip_specs import default_spec
    expect = 2e9 / (107e-6) / (default_spec().bf16_tflops * 1e12)
    assert rec["mfu"] == pytest.approx(expect, abs=1e-4)
    assert rec["mfu_provenance"] == "measured:trace"
    assert rec["model_exposed_comm_us"] == 10.0
    assert rec["exposed_comm_drift_ratio"] == pytest.approx(0.85)


def test_mfu_degrades_with_marker_not_zero():
    rec = attribute([_scenario_rank0()])
    assert "mfu" not in rec
    assert rec["mfu_provenance"] == "unavailable:no-step-count"
    rec = attribute([_scenario_rank0()], steps=4)
    assert rec["mfu_provenance"] == "unavailable:no-compiled-flops"


# -- multi-rank merge + straggler skew --------------------------------------

def _scenario_rank1():
    return _rank([
        _ev("dot.1", "dot", 1000, 1050),
        _ev("fusion.2", "fusion", 1050, 1070),
        _ev("all-gather.3", "collective:all_gather", 1062, 1090),
        _ev("dot.4", "dot", 1080, 1130),
        _ev("reduce-scatter.5", "collective:reduce_scatter", 1130, 1150),
        _ev("tanh.7", "other", 1150, 1160),
    ], source="rank1.trace.json.gz")


def test_two_rank_merge_headline_is_the_straggler():
    rec = attribute([_scenario_rank0(), _scenario_rank1()])
    assert rec["ranks"] == 2
    # rank1's window (160) > rank0's (130): the straggler sets the step
    assert rec["window_us"] == 160.0
    assert rec["compute_us"] == 130.0
    assert rec["exposed_comm_us"] == 30.0
    skew = rec["skew"]
    assert skew["per_rank_window_us"] == [130.0, 160.0]
    assert skew["slowest_rank"] == 1
    assert skew["slowest_over_median"] == pytest.approx(160 / 130,
                                                        abs=1e-4)
    # start spreads are rebased to each rank's first op: all-gather
    # starts at +50 vs +62, reduce-scatter at +100 vs +130
    assert skew["collective_start_spread_us"] == {
        "all_gather": 12.0, "reduce_scatter": 30.0}


def test_mixed_degraded_and_measured_ranks():
    """A degraded rank drops out of the rollup but stays in sources;
    all-degraded ingestion yields the unavailable record with NO
    numeric fields (never zeros)."""
    bad = RankTrace(source="broken", provenance="unavailable:parse-failed")
    rec = attribute([_scenario_rank0(), bad])
    assert rec["ranks"] == 1
    assert rec["sources"] == ["rank0.trace.json.gz", "broken"]
    assert rec["window_us"] == 130.0

    rec = attribute([bad], steps=4, flops_per_step=1e9)
    assert rec["provenance"] == "unavailable:parse-failed"
    assert rec["ranks"] == 0
    for key in ("window_us", "busy_us", "compute_us", "exposed_comm_us",
                "categories", "mfu", "step_us"):
        assert key not in rec, key


# -- golden CPU-captured fixture --------------------------------------------

def test_golden_cpu_trace_parses_measured():
    """The committed (scrubbed) CPU capture: session-dir layout is
    discovered by globbing, op events come from the args.hlo_op
    convention, dot/other categories land, and the attributed times
    sum to the window within the documented tolerance."""
    files = find_trace_files(str(GOLDEN_PROFILE))
    assert len(files) == 1 and files[0].endswith("host0.trace.json.gz")
    [tr] = load_profile_dirs([str(GOLDEN_PROFILE)])
    assert tr.provenance == "measured:trace"
    assert tr.events == sorted(tr.events, key=lambda e: e.start_us)
    cats = {e.category for e in tr.events}
    assert "dot" in cats and "other" in cats
    rec = attribute([tr], steps=3)
    assert rec["provenance"] == "measured:trace"
    assert rec["window_us"] > 0
    assert rec["categories"]["dot"] > rec["categories"]["other"]
    assert abs(rec["coverage"] - 1.0) <= COVERAGE_TOLERANCE
    # single host, no collectives: a MEASURED zero, not a fabricated one
    assert rec["collectives"] == {}
    assert rec["exposed_comm_us"] == 0.0


def test_trace_ingest_cli_on_golden(tmp_path):
    out = tmp_path / "attribution.json"
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.observability.trace_ingest",
         str(GOLDEN_PROFILE), "--steps", "3", "--out", str(out)],
        capture_output=True, text=True, timeout=240)
    assert proc.returncode == 0, proc.stderr
    rec = json.loads(out.read_text(encoding="utf-8"))
    assert rec["provenance"] == "measured:trace"
    assert rec["steps"] == 3


# -- malformed / empty degradation ------------------------------------------

def test_empty_dir_degrades_to_marker(tmp_path):
    [tr] = load_profile_dirs([str(tmp_path)])
    assert tr.provenance == "unavailable:no-trace-files"
    assert tr.events == []


def test_malformed_trace_degrades_to_marker(tmp_path):
    bad = tmp_path / "x.trace.json.gz"
    bad.write_bytes(b"not gzip at all")
    tr = parse_trace_file(str(bad))
    assert tr.provenance.startswith("unavailable:parse-failed:")

    empty = tmp_path / "y.trace.json"
    empty.write_text(json.dumps({"traceEvents": []}), encoding="utf-8")
    assert parse_trace_file(str(empty)).provenance == \
        "unavailable:no-trace-events"

    no_ops = tmp_path / "z.trace.json"
    no_ops.write_text(json.dumps({"traceEvents": [
        {"ph": "X", "name": "python_thing", "ts": 1, "dur": 2,
         "pid": 1, "tid": 1}]}), encoding="utf-8")
    assert parse_trace_file(str(no_ops)).provenance == \
        "unavailable:no-op-events"


def test_host_python_events_are_not_ops(tmp_path):
    """The CPU profiler interleaves thousands of python host events
    with the XLA ops; only hlo_op-carrying (or device-lane) events
    attribute."""
    doc = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 7,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "name": "$builtins isinstance", "ts": 0, "dur": 50,
         "pid": 7, "tid": 1},
        {"ph": "X", "name": "dot.1", "ts": 10, "dur": 5, "pid": 7,
         "tid": 2, "args": {"hlo_op": "dot.1", "hlo_module": "jit_f"}},
    ]}
    p = tmp_path / "t.trace.json"
    p.write_text(json.dumps(doc), encoding="utf-8")
    tr = parse_trace_file(str(p))
    assert [e.name for e in tr.events] == ["dot.1"]


# -- publish: gauges + the attribution event --------------------------------

class _CaptureSink:
    def __init__(self):
        self.events = []

    def event(self, obj):
        self.events.append(obj)


def test_publish_sets_gauges_and_emits_event():
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    rec = attribute([_scenario_rank0(), _scenario_rank1()], steps=2,
                    flops_per_step=1e9, model_exposed_comm_us=10.0)
    publish(rec, profile_dir="/tmp/p", registry=reg)
    assert reg.declared("trace_window_us").value() == 160.0
    assert reg.declared("trace_step_time_us").value() == 80.0
    assert reg.declared("trace_exposed_comm_us").value() == 30.0
    assert reg.declared("trace_category_time_us").value(
        category="dot") == 100.0
    assert reg.declared("trace_category_time_us").value(
        category="host_gap") == 0.0
    assert reg.declared("trace_rank_step_skew").value() == \
        pytest.approx(160 / 130, abs=1e-4)
    assert reg.declared("trace_collective_start_spread_us").value(
        collective="reduce_scatter") == 30.0
    [ev] = sink.events
    assert ev["kind"] == "attribution"
    assert ev["provenance"] == "measured:trace"
    assert ev["categories"]["dot"] == 100.0
    assert ev["skew"]["slowest_rank"] == 1


def test_publish_degraded_record_sets_no_gauges():
    """The degradation contract downstream: an unavailable record
    emits the event (marker + nulls) and touches NO gauge — a
    dashboard reads the marker, never a fabricated zero."""
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    rec = attribute([RankTrace(source="d",
                               provenance="unavailable:no-trace-files")])
    publish(rec, profile_dir="/tmp/none", registry=reg)
    assert reg.declared("trace_window_us").value() is None
    assert reg.declared("trace_mfu").value() is None
    [ev] = sink.events
    assert ev["provenance"] == "unavailable:no-trace-files"
    assert ev["window_us"] is None and ev["mfu"] is None
    assert ev["categories"] == {}


# -- profile_capture hardening (ISSUE 14 satellite) -------------------------

def test_profile_capture_skips_already_populated_dir(tmp_path,
                                                     monkeypatch,
                                                     capsys):
    """An armed dir already holding a trace session degrades to a
    no-op with a profile_skipped event — it must never silently
    shadow the old trace."""
    from apex_tpu.observability.tracing import (profile_capture,
                                                profile_dir_unusable)
    stale = tmp_path / "prof"
    session = stale / "plugins" / "profile" / "2026_01_01_00_00_00"
    session.mkdir(parents=True)
    (session / "host0.trace.json.gz").write_bytes(b"old")
    assert profile_dir_unusable(str(stale)) == "already-populated"
    monkeypatch.setenv("APEX_TPU_PROFILE_DIR", str(stale))
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    with profile_capture(tag="leg", registry=reg) as started:
        assert started is False
    [ev] = sink.events
    assert ev["kind"] == "profile_skipped"
    assert ev["reason"] == "already-populated"
    assert ev["dir"] == str(stale) and ev["tag"] == "leg"
    # the old trace is untouched
    assert (session / "host0.trace.json.gz").read_bytes() == b"old"
    assert "skipped" in capsys.readouterr().err


def test_profile_capture_skips_unwritable_target(tmp_path, monkeypatch):
    """A capture dir that cannot be created (the path is a file)
    degrades the same way instead of raising."""
    from apex_tpu.observability.tracing import (profile_capture,
                                                profile_dir_unusable)
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("i am a file", encoding="utf-8")
    assert profile_dir_unusable(str(blocker)) == "unwritable"
    monkeypatch.setenv("APEX_TPU_PROFILE_DIR", str(blocker))
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    with profile_capture(tag="leg", registry=reg) as started:
        assert started is False
    [ev] = sink.events
    assert ev["kind"] == "profile_skipped"
    assert ev["reason"] == "unwritable"


def test_profile_capture_fresh_dir_still_captures(tmp_path,
                                                  monkeypatch):
    """The hardening must not break the happy path: a fresh dir still
    starts a real capture and drops a parseable trace."""
    import jax
    import jax.numpy as jnp
    from apex_tpu.observability.tracing import profile_capture
    fresh = tmp_path / "prof"
    monkeypatch.setenv("APEX_TPU_PROFILE_DIR", str(fresh))
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    with profile_capture(tag="leg", registry=reg) as started:
        if not started:          # profiler busy elsewhere in-process
            pytest.skip("profiler unavailable in this process")
        x = jnp.ones((64, 64))
        jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    kinds = [e["kind"] for e in sink.events]
    assert kinds == ["profile_start", "profile_stop"]
    assert find_trace_files(str(fresh))
    # and a SECOND armed capture into the now-populated dir skips
    with profile_capture(tag="leg2", registry=reg) as started2:
        assert started2 is False
    assert sink.events[-1]["kind"] == "profile_skipped"
    assert sink.events[-1]["reason"] == "already-populated"


def test_profile_capture_survives_unwritable_telemetry_target(
        tmp_path, monkeypatch, capsys):
    """The never-raises contract holds even when the registry-less
    event path itself fails: an unwritable APEX_TPU_TELEMETRY target
    drops the profile event with a warning instead of crashing the
    bench leg mid-capture."""
    from apex_tpu.observability import reset_global_registry
    from apex_tpu.observability.tracing import profile_capture
    blocker = tmp_path / "tfile"
    blocker.write_text("not a dir", encoding="utf-8")
    monkeypatch.setenv("APEX_TPU_TELEMETRY", str(blocker / "sub"))
    stale = tmp_path / "prof"
    (stale / "plugins" / "profile" / "s").mkdir(parents=True)
    (stale / "plugins" / "profile" / "s" / "x.trace.json.gz"). \
        write_bytes(b"old")
    monkeypatch.setenv("APEX_TPU_PROFILE_DIR", str(stale))
    reset_global_registry()
    try:
        with profile_capture(tag="leg") as started:   # registry=None
            assert started is False
    finally:
        reset_global_registry()
    err = capsys.readouterr().err
    assert "skipped" in err and "dropped" in err


# -- capture-hygiene extension (ISSUE 14 satellite) -------------------------

def test_hygiene_rejects_non_physical_measured_fields():
    from apex_tpu.observability.capture_hygiene import \
        scrub_capture_values
    payload = {
        "measured_mfu": 1.7,                    # > 1.0: not physics
        "mfu": 0.0,                             # RTT-collapse face
        "bert_mfu": -0.2,                       # negative garbage
        "measured_window_us": 5e9,              # > 1 h attributed time
        "measured_compute_us": -5.0,            # negative
        "measured_exposed_comm_us": 0.0,        # collapsed measurement
        "keep_mfu": 0.43,
        "measured_step_us": 81.25,
        "exposed_comm_drift_ratio": 1.5,        # ratio: not us-bounded
    }
    out = scrub_capture_values(payload)
    assert out == {"keep_mfu": 0.43, "measured_step_us": 81.25,
                   "exposed_comm_drift_ratio": 1.5}


def test_committed_capture_history_survives_mfu_rule():
    """The new (0, 1] MFU bound must not scrub any committed capture
    (they are all plausible) — the rule targets future artifacts."""
    from apex_tpu.observability.capture_hygiene import \
        scrub_capture_values
    capdir = Path(__file__).parents[3] / "bench_captures"
    checked = 0
    for path in sorted(capdir.glob("r*_*.json")):
        payload = json.loads(path.read_text(encoding="utf-8"))
        if not isinstance(payload, dict):
            continue

        def _mfu_keys(obj, prefix=""):
            if isinstance(obj, dict):
                for k, v in obj.items():
                    if isinstance(v, (dict, list)):
                        yield from _mfu_keys(v, prefix + k + ".")
                    elif "mfu" in k:
                        yield prefix + k, v
            elif isinstance(obj, list):
                for v in obj:
                    yield from _mfu_keys(v, prefix)

        before = dict(_mfu_keys(payload))
        after = dict(_mfu_keys(scrub_capture_values(payload)))
        assert before == after, path.name
        checked += len(before)
    assert checked > 0        # the history does carry mfu stamps
