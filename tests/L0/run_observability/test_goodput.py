"""MFU + goodput accounting (ISSUE 10): the train badput buckets
conserve the run's wall clock, the MFU gauge prices measured steps
against the armed flops, and the serve counters decompose token work.
Pure host-side — no engine, no device beyond trivial scalars."""
import time

import jax.numpy as jnp
import pytest

from apex_tpu.observability import MetricsRegistry, ServeTelemetry, \
    TrainTelemetry


# -- train: MFU -------------------------------------------------------------

def test_mfu_gauge_prices_measured_steps():
    tel = TrainTelemetry(MetricsRegistry())
    tel.arm_mfu(flops_per_step=1e9, peak_flops=1e12)
    assert tel.model_flops_per_step.value() == 1e9
    for _ in range(3):
        with tel.step():
            time.sleep(0.01)
    mfu = tel.mfu.value()
    assert mfu is not None and 0 < mfu < 1
    # ~1e9 flops in >=10ms against a 1e12 peak => mfu <= ~0.1
    assert mfu == pytest.approx(1e9 / tel._peak_flops
                                / tel._timer.last.seconds, rel=5.0)


def test_mfu_unarmed_publishes_nothing():
    tel = TrainTelemetry(MetricsRegistry())
    with tel.step():
        pass
    assert tel.mfu.value() is None
    assert tel.model_flops_per_step.value() is None


# -- train: badput conservation ---------------------------------------------

def test_badput_buckets_conserve_wall_time():
    tel = TrainTelemetry(MetricsRegistry())
    t0 = time.perf_counter()
    for i in range(4):
        with tel.step():
            time.sleep(0.005)
        tel.observe_device(loss=jnp.float32(float(i)))
    time.sleep(0.02)               # host gap the steps don't cover
    tel.flush()
    wall = time.perf_counter() - t0
    g = tel.goodput()
    assert g["overflow_s"] == 0.0 and g["recompile_s"] == 0.0
    assert g["productive_s"] > 0
    assert g["host_gap_s"] > 0     # the sleep before flush
    # conservation: the four buckets sum to the run wall time
    assert g["wall_s"] == pytest.approx(wall, abs=0.05)
    assert 0 < g["goodput_fraction"] <= 1


def test_overflow_step_lands_in_overflow_bucket():
    tel = TrainTelemetry(MetricsRegistry())
    with tel.step():
        time.sleep(0.002)
    tel.observe_device(found_inf=jnp.asarray(True))
    with tel.step():
        time.sleep(0.002)
    tel.observe_device(found_inf=jnp.asarray(False))
    tel.flush()
    g = tel.goodput()
    assert int(tel.overflow_skips.total()) == 1
    assert g["overflow_s"] > 0
    assert g["productive_s"] > 0
    assert g["overflow_s"] < g["wall_s"]


def test_steps_without_deferred_scalars_settle_productive_at_flush():
    tel = TrainTelemetry(MetricsRegistry())
    for _ in range(3):
        with tel.step():
            pass
    assert tel.productive_seconds.total() == 0.0   # still parked
    tel.flush()
    assert tel.productive_seconds.total() > 0


def test_flush_resets_run_so_two_runs_both_conserve():
    tel = TrainTelemetry(MetricsRegistry())
    for _ in range(2):
        with tel.step():
            time.sleep(0.002)
    tel.flush()
    g1 = tel.goodput()
    time.sleep(0.02)               # inter-run idle: NOT part of any run
    for _ in range(2):
        with tel.step():
            time.sleep(0.002)
    tel.flush()
    g2 = tel.goodput()
    # the inter-run idle gap must not land in any bucket
    assert g2["wall_s"] - g1["wall_s"] < 0.015


# -- serve: token goodput ---------------------------------------------------

def test_prefill_padding_counter():
    tel = ServeTelemetry(MetricsRegistry())
    with tel.prefill_step(prompt_len=33, bucket_len=64):
        pass
    with tel.prefill_step(prompt_len=64, bucket_len=64):
        pass                       # exact fit: no padding
    with tel.prefill_step():
        pass                       # legacy caller: no accounting
    assert int(tel.prefill_pad_tokens.total()) == 31


def test_decode_idle_slot_counter():
    tel = ServeTelemetry(MetricsRegistry())
    with tel.decode_step(3, capacity=8):
        pass
    with tel.decode_step(8, capacity=8):
        pass
    with tel.decode_step(2):
        pass                       # legacy caller: no accounting
    assert int(tel.idle_slot_tokens.total()) == 5


def test_truncation_waste_counter_and_goodput_view():
    tel = ServeTelemetry(MetricsRegistry())
    with tel.prefill_step(prompt_len=10, bucket_len=64):
        pass
    with tel.decode_step(1, capacity=2):
        pass
    tel.request_finished(0, "length", 8)
    tel.request_finished(1, "truncated", 3)
    g = tel.goodput()
    assert g["generated_tokens"] == 11
    assert g["prefill_pad_tokens"] == 54
    assert g["idle_slot_tokens"] == 1
    assert g["truncated_tokens"] == 3
    assert g["goodput_fraction"] == pytest.approx(11 / (11 + 54 + 1))


def test_goodput_empty_is_none_fraction():
    tel = ServeTelemetry(MetricsRegistry())
    assert tel.goodput()["goodput_fraction"] is None
