"""Sink unit tests: Prometheus text-exposition format and the JSONL
event stream — the two surfaces dashboards consume, so the assertions
here are EXACT-text, not shape checks."""
import json

from apex_tpu.observability import (JsonlSink, MetricsRegistry,
                                    PrometheusSink, render_prometheus)


def _small_registry() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("req_total", "requests", labels=("reason",)) \
       .inc(3, reason="eos")
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(0.5)
    h.observe(5.0)
    return reg


def test_prometheus_exposition_exact_text():
    text = render_prometheus(_small_registry())
    assert text == (
        "# HELP depth queue depth\n"
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 3\n'
        'lat_seconds_bucket{le="+Inf"} 4\n'
        "lat_seconds_sum 6.05\n"
        "lat_seconds_count 4\n"
        "# HELP req_total requests\n"
        "# TYPE req_total counter\n"
        'req_total{reason="eos"} 3\n'
    )


def test_prometheus_bucket_series_is_cumulative():
    """_bucket{le=} values are CUMULATIVE (Prometheus semantics), and
    the +Inf bucket equals _count."""
    text = render_prometheus(_small_registry())
    lines = [ln for ln in text.splitlines() if "_bucket" in ln]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts)
    inf = next(ln for ln in lines if 'le="+Inf"' in ln)
    total = next(ln for ln in text.splitlines()
                 if ln.startswith("lat_seconds_count"))
    assert inf.rsplit(" ", 1)[1] == total.rsplit(" ", 1)[1]


def test_prometheus_value_formatting():
    """Integral values print without a decimal point; floats use a
    stable shortest form (no 2.5000000001 artifacts)."""
    reg = MetricsRegistry()
    reg.gauge("a", "h").set(4.0)
    reg.gauge("b", "h").set(0.1 + 0.2)
    text = render_prometheus(reg)
    assert "a 4\n" in text
    assert "b 0.3\n" in text


def test_prometheus_empty_registry_renders_empty():
    assert render_prometheus(MetricsRegistry()) == ""


def test_prometheus_unlabeled_zero_counter_exposes_explicit_zero():
    """The pinned-zero families (serve_recompiles_total) must scrape as
    0, not be absent, so dashboards can alert on them going nonzero."""
    reg = MetricsRegistry()
    reg.counter("recompiles_total", "h")
    reg.counter("labeled_total", "h", labels=("reason",))
    text = render_prometheus(reg)
    assert "recompiles_total 0\n" in text
    # labeled counters can't enumerate unseen label values: headers only
    assert "labeled_total{" not in text


def test_prometheus_sink_atomic_export(tmp_path):
    path = tmp_path / "metrics.prom"
    reg = _small_registry()
    reg.add_sink(PrometheusSink(str(path)))
    reg.export()
    first = path.read_text()
    assert first == render_prometheus(reg)
    reg.counter("req_total").inc(reason="eos")
    reg.export()                       # rewrite, not append
    assert 'req_total{reason="eos"} 4' in path.read_text()
    # no temp-file litter from the atomic-rename dance
    assert [p.name for p in tmp_path.iterdir()] == ["metrics.prom"]


def test_jsonl_sink_appends_schema_shaped_lines(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    reg = MetricsRegistry()
    reg.add_sink(JsonlSink(str(path)))
    reg.emit_event("request_submit", uid=1, prompt_len=4,
                   max_new_tokens=8, queue_depth=1)
    reg.emit_event("request_finish", uid=1, reason="eos", tokens=3,
                   e2e_s=0.25)
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    first, second = (json.loads(ln) for ln in lines)
    assert first["kind"] == "request_submit" and first["uid"] == 1
    assert second["kind"] == "request_finish" and second["reason"] == "eos"
    for obj in (first, second):
        assert isinstance(obj["ts"], float)     # common fields present
