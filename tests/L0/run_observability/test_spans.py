"""Request-scoped tracing (ISSUE 13): span emission order, sampling,
terminal discipline, and the span-conservation books — driven through
ServeTelemetry's host boundaries with a capture sink, no engine."""
import pytest

from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.observability import schema, spans


class _CaptureSink:
    def __init__(self):
        self.events = []

    def event(self, obj):
        self.events.append(obj)


def _telemetry(trace=1):
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    return ServeTelemetry(reg, trace=trace), sink


def _spans(sink, uid=None):
    out = [e for e in sink.events if e["kind"] == "trace_span"]
    if uid is not None:
        out = [e for e in out if e["uid"] == uid]
    return out


def _drive_request(tel, uid, chunks=1, cow=False):
    """One full lifecycle through the telemetry's host boundaries."""
    tel.request_submitted(uid, 8, 4, queue_depth=1)
    tel.request_admitted(uid, slot=0, queue_depth=0, pages=3,
                         prefix_tokens=2)
    if cow:
        tel.cow_copied(uid, slot=0, src=5, dst=9)
    for i in range(chunks):
        with tel.prefill_step(prompt_len=4, bucket_len=64, uid=uid,
                              start_tok=4 * i):
            pass
    tel.first_token(uid)
    tel.request_finished(uid, "length", 4)


def test_full_lifecycle_span_sequence():
    tel, sink = _telemetry()
    tel.begin_wave()
    _drive_request(tel, 0, chunks=2, cow=True)
    evs = _spans(sink, uid=0)
    assert [e["span"] for e in evs] == [
        "queued", "admitted", "cow_copy", "prefill_chunk",
        "prefill_chunk", "first_token", "decode", "retired"]
    # seq is contiguous from 1, every event carries the serving wave
    assert [e["seq"] for e in evs] == list(range(1, len(evs) + 1))
    assert all(e["wave"] == 1 for e in evs)
    # offsets are physical: queued starts the trace, later spans only
    # move forward, durations are non-negative
    assert evs[0]["start_s"] == 0.0
    assert evs[0]["dur_s"] >= 0.0
    starts = [e["start_s"] for e in evs[1:]]
    assert starts == sorted(starts)
    for e in evs:
        if e["dur_s"] is not None:
            assert e["dur_s"] >= 0.0
    # details carry the operator-facing context
    assert "slot=0" in evs[1]["detail"]
    assert "prefix_tokens=2" in evs[1]["detail"]
    assert evs[2]["detail"] == "page 5->9"
    assert "start=4" in evs[4]["detail"] and "bucket=64" in evs[4]["detail"]
    assert evs[6]["detail"] == "tokens=4"
    assert evs[7]["detail"] == "length"
    # decode opens exactly at the first token
    first = next(e for e in evs if e["span"] == "first_token")
    decode = next(e for e in evs if e["span"] == "decode")
    assert decode["start_s"] == pytest.approx(first["start_s"])
    # metric family counted every span
    assert int(tel.tracer.spans.total()) == len(evs)


def test_events_are_schema_shaped():
    tel, sink = _telemetry()
    tel.begin_wave()
    _drive_request(tel, 0)
    declared = schema.EVENT_FIELDS["trace_span"]
    for e in _spans(sink):
        assert set(e) == {"ts", "kind"} | set(declared)
        assert isinstance(e["uid"], int) and isinstance(e["seq"], int)
        assert isinstance(e["wave"], int)
        assert isinstance(e["span"], str)
        assert isinstance(e["start_s"], float)
        assert e["dur_s"] is None or isinstance(e["dur_s"], float)
        assert e["detail"] is None or isinstance(e["detail"], str)


def test_sampling_one_in_n_is_uid_stable():
    tel, sink = _telemetry(trace=2)
    tel.begin_wave()
    for uid in range(4):
        _drive_request(tel, uid)
    assert _spans(sink, uid=0) and _spans(sink, uid=2)
    assert not _spans(sink, uid=1) and not _spans(sink, uid=3)
    c = tel.tracer.conservation()
    assert c["started"] == c["closed"] == 2
    # the untraced uids never register as orphan terminals
    assert c["orphan_terminals"] == []


def test_trace_off_emits_nothing():
    tel, sink = _telemetry(trace=0)
    _drive_request(tel, 0)
    assert _spans(sink) == []
    assert not tel.tracer.enabled()
    assert int(tel.tracer.spans.total()) == 0


def test_env_knob_default(monkeypatch):
    monkeypatch.delenv("APEX_TPU_TRACE", raising=False)
    assert spans.default_trace_sample() == 0
    monkeypatch.setenv("APEX_TPU_TRACE", "3")
    assert spans.default_trace_sample() == 3
    tel, sink = _telemetry(trace=None)        # None -> env
    assert tel.tracer.sample == 3
    monkeypatch.setenv("APEX_TPU_TRACE", "banana")
    with pytest.raises(ValueError, match="APEX_TPU_TRACE"):
        spans.default_trace_sample()
    monkeypatch.setenv("APEX_TPU_TRACE", "-1")
    with pytest.raises(ValueError, match=">= 0"):
        spans.default_trace_sample()


def test_shed_closes_trace_with_rejected_terminal():
    """A queued request shed under overload: the trace closes with a
    `rejected` terminal span — no trace dangles (ISSUE 13 satellite)."""
    tel, sink = _telemetry()
    tel.begin_wave()
    tel.request_submitted(7, 8, 4, queue_depth=1)
    tel.request_shed(7, tenant="acme", queue_depth=0)
    evs = _spans(sink, uid=7)
    assert [e["span"] for e in evs] == ["rejected"]
    assert evs[0]["detail"] == "shed"
    c = tel.tracer.conservation()
    assert c["closed_by_span"] == {"rejected": 1}
    assert c["dangling"] == [] and c["live"] == 0
    # the lifecycle conservation law still balances (shed rides the
    # rejected side, submitted counted once)
    lc = tel.conservation()
    assert lc["submitted"] == lc["finished"] + lc["active"] \
        + lc["rejected"] == 1
    assert int(tel.shed.value(tenant="acme")) == 1


def test_conservation_flags_dangling_and_orphans():
    tel, _ = _telemetry()
    tel.begin_wave()
    tel.request_submitted(0, 4, 2, queue_depth=1)
    tel.request_admitted(0, slot=0, queue_depth=0)
    c = tel.tracer.conservation()
    assert c["dangling"] == [0] and c["live"] == 1
    tel.request_finished(0, "eos", 1)
    c = tel.tracer.conservation()
    assert c["dangling"] == [] and c["started"] == c["closed"] == 1
    # a second terminal for the same uid is an orphan, not a crash
    tel.tracer.request_finished(0, "eos", 1)
    assert tel.tracer.conservation()["orphan_terminals"] == [0]


def test_wave_stamps_the_serving_wave():
    """A request submitted before run() is admitted inside the wave:
    its spans carry the wave that SERVED it."""
    tel, sink = _telemetry()
    tel.request_submitted(0, 4, 2, queue_depth=1)   # pre-wave submit
    tel.begin_wave()
    tel.request_admitted(0, slot=0, queue_depth=0)
    tel.request_finished(0, "length", 2)
    tel.begin_wave()
    tel.request_submitted(1, 4, 2, queue_depth=1)
    tel.request_admitted(1, slot=0, queue_depth=0)
    tel.request_finished(1, "length", 2)
    assert {e["wave"] for e in _spans(sink, uid=0)} == {1}
    assert {e["wave"] for e in _spans(sink, uid=1)} == {2}
    # a request submitted pre-wave but SHED during the wave renders
    # under the wave that shed it, same as the admitted path
    tel.request_submitted(2, 4, 2, queue_depth=1)
    tel.begin_wave()
    tel.request_shed(2)
    assert {e["wave"] for e in _spans(sink, uid=2)} == {3}
