"""DeferredScalarCollector: the one-step-late contract, proven.

The collector exists so telemetry never blocks dispatch: a device
scalar from step N is only materialized after step N+1 has been
ENQUEUED (i.e. dispatched by the caller).  These tests use a probe
whose ``__array__`` records the moment of materialization, so the
contract "poll() never touches arrays from the newest step" is
observed directly, not inferred."""
import numpy as np
import pytest

from apex_tpu.observability import DeferredScalarCollector


class _Probe:
    """Stands in for a device array: materialization (np.asarray ->
    __array__) is observable."""

    def __init__(self, value: float):
        self.value = value
        self.materialized = False

    def __array__(self, dtype=None, copy=None):
        self.materialized = True
        return np.asarray(self.value, dtype=dtype)


def test_poll_resolves_only_strictly_prior_steps():
    col = DeferredScalarCollector()
    p0, p1 = _Probe(1.0), _Probe(2.0)
    col.enqueue(0, loss=p0)
    assert col.poll() == []            # step 0 is the newest: parked
    assert not p0.materialized

    col.enqueue(1, loss=p1)
    resolved = col.poll()
    assert resolved == [(0, {"loss": 1.0})]
    assert p0.materialized             # prior step: read
    assert not p1.materialized         # newest step: NEVER read by poll
    assert col.pending == 1


def test_poll_catches_up_across_many_steps():
    col = DeferredScalarCollector()
    probes = [_Probe(float(i)) for i in range(4)]
    for i, p in enumerate(probes[:3]):
        col.enqueue(i, loss=p)
    col.enqueue(3, loss=probes[3])
    out = col.poll()
    assert [(s, d["loss"]) for s, d in out] == \
        [(0, 0.0), (1, 1.0), (2, 2.0)]
    assert not probes[3].materialized


def test_drain_is_the_blocking_boundary():
    col = DeferredScalarCollector()
    p = _Probe(7.0)
    col.enqueue(0, loss=p)
    assert col.drain() == [(0, {"loss": 7.0})]
    assert p.materialized              # drain DOES block on the newest
    assert col.pending == 0


def test_none_values_dropped_so_optional_signals_pass_through():
    col = DeferredScalarCollector()
    col.enqueue(0, loss=_Probe(1.0), grad_norm=None)
    col.enqueue(1, loss=_Probe(2.0))
    [(_, resolved)] = col.poll()
    assert resolved == {"loss": 1.0}   # no grad_norm key


def test_enqueue_is_forward_only():
    col = DeferredScalarCollector()
    col.enqueue(3, loss=_Probe(1.0))
    with pytest.raises(ValueError, match="forward-only"):
        col.enqueue(2, loss=_Probe(0.0))
    col.enqueue(3, loss=_Probe(2.0))   # same step is fine (re-enqueue)


def test_on_resolve_hook_fires_per_entry():
    seen = []
    col = DeferredScalarCollector(
        on_resolve=lambda step, d: seen.append((step, d)))
    col.enqueue(0, loss=_Probe(1.0))
    col.enqueue(1, loss=_Probe(2.0))
    col.poll()
    assert seen == [(0, {"loss": 1.0})]
    col.drain()
    assert seen == [(0, {"loss": 1.0}), (1, {"loss": 2.0})]


def test_works_on_real_jax_arrays():
    jnp = pytest.importorskip("jax.numpy")
    col = DeferredScalarCollector()
    col.enqueue(0, loss=jnp.float32(1.5), found_inf=jnp.bool_(True))
    col.enqueue(1, loss=jnp.float32(2.5))
    [(step, resolved)] = col.poll()
    assert step == 0
    assert resolved == {"loss": 1.5, "found_inf": 1.0}
