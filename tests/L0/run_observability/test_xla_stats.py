"""Compiled-truth extractor (ISSUE 10): XLA's cost/memory numbers per
executable, with the degradation contract — a backend that cannot
report a number yields an explicit provenance marker and ``None``,
never a fabricated zero."""
import jax
import jax.numpy as jnp
import pytest

from apex_tpu.observability.xla_stats import (
    PROVENANCE_COST_ONLY, PROVENANCE_FULL,
    PROVENANCE_UNAVAILABLE_PREFIX, compile_and_stats,
    stats_from_compiled)


def _matmul(x):
    return jnp.tanh(x @ x)


def test_compile_and_stats_full_provenance():
    x = jnp.ones((32, 32), jnp.float32)
    stats = compile_and_stats(_matmul, (x,))
    assert stats.provenance == PROVENANCE_FULL
    assert not stats.degraded
    # a 32x32x32 matmul is at least 2*32^3 FLOPs
    assert stats.flops >= 2 * 32 ** 3
    assert stats.bytes_accessed > 0
    assert stats.argument_bytes == 32 * 32 * 4
    assert stats.output_bytes == 32 * 32 * 4
    # peak identity: arg + out - alias + temp
    assert stats.peak_hbm_bytes == (
        stats.argument_bytes + stats.output_bytes
        - stats.alias_bytes + stats.temp_bytes)


def test_donation_shows_up_as_alias_bytes():
    x = jnp.ones((64, 64), jnp.float32)
    stats = compile_and_stats(lambda s, g: (s - g, jnp.sum(g)), (x, x),
                              donate_argnums=(0,))
    assert stats.provenance == PROVENANCE_FULL
    assert stats.alias_bytes >= 64 * 64 * 4, \
        "the donated buffer must appear in alias_size_in_bytes"


def test_asdict_drops_none_never_fabricates():
    x = jnp.ones((8, 8), jnp.float32)
    full = compile_and_stats(_matmul, (x,)).asdict()
    assert full["provenance"] == PROVENANCE_FULL
    assert full["flops"] > 0 and full["peak_hbm_bytes"] > 0


class _NoMemCompiled:
    """A compiled artifact whose backend lacks memory_analysis."""

    def __init__(self, real):
        self._real = real

    def cost_analysis(self):
        return self._real.cost_analysis()


class _NothingCompiled:
    """A compiled artifact exposing no analysis at all."""


def test_missing_memory_analysis_degrades_with_marker():
    x = jnp.ones((16, 16), jnp.float32)
    real = jax.jit(_matmul).lower(x).compile()
    stats = stats_from_compiled(_NoMemCompiled(real))
    assert stats.provenance == PROVENANCE_COST_ONLY
    assert stats.degraded
    assert stats.flops > 0                      # cost side still truth
    assert stats.peak_hbm_bytes is None         # NEVER a fabricated 0
    assert stats.temp_bytes is None
    d = stats.asdict()
    assert "peak_hbm_bytes" not in d and "temp_bytes" not in d
    assert d["provenance"] == PROVENANCE_COST_ONLY


def test_partial_cost_model_reports_none_not_zero_bytes():
    """A cost model with flops but no 'bytes accessed' key must yield
    bytes_accessed=None (dropped from the dict), never a fabricated 0."""
    class _FlopsOnly:
        def cost_analysis(self):
            return {"flops": 42.0}

    stats = stats_from_compiled(_FlopsOnly())
    assert stats.flops == 42
    assert stats.bytes_accessed is None
    assert "bytes_accessed" not in stats.asdict()


def test_provenance_rank_ladder():
    from apex_tpu.observability.xla_stats import provenance_rank
    assert provenance_rank(PROVENANCE_FULL) == 2
    assert provenance_rank(PROVENANCE_COST_ONLY) == 1
    assert provenance_rank(PROVENANCE_UNAVAILABLE_PREFIX + "x") == 0


def test_no_cost_analysis_is_unavailable():
    stats = stats_from_compiled(_NothingCompiled())
    assert stats.provenance.startswith(PROVENANCE_UNAVAILABLE_PREFIX)
    assert stats.flops is None and stats.peak_hbm_bytes is None
    assert list(stats.asdict()) == ["provenance"]


def test_raising_memory_analysis_degrades_not_raises():
    x = jnp.ones((16, 16), jnp.float32)
    real = jax.jit(_matmul).lower(x).compile()

    class _Raises:
        def cost_analysis(self):
            return real.cost_analysis()

        def memory_analysis(self):
            raise NotImplementedError("no memory stats on this backend")

    stats = stats_from_compiled(_Raises())
    assert stats.provenance == PROVENANCE_COST_ONLY
    assert stats.peak_hbm_bytes is None


def test_compile_failure_yields_marker_not_exception():
    def broken(x):
        return jax.lax.psum(x, "nonexistent_axis")

    stats = compile_and_stats(broken, (jnp.ones((4,)),))
    assert stats.provenance.startswith(PROVENANCE_UNAVAILABLE_PREFIX)
    assert "compile-failed" in stats.provenance
    assert stats.flops is None


def test_list_and_dict_cost_analysis_both_normalize():
    """Old jax returns cost_analysis() as [dict], modern jax as dict —
    the _jax_compat helper must accept both spellings."""
    from apex_tpu._jax_compat import compiled_cost_analysis

    class _ListStyle:
        def cost_analysis(self):
            return [{"flops": 10.0, "bytes accessed": 20.0}]

    class _DictStyle:
        def cost_analysis(self):
            return {"flops": 10.0, "bytes accessed": 20.0}

    for style in (_ListStyle(), _DictStyle()):
        out = compiled_cost_analysis(style)
        assert out == {"flops": 10.0, "bytes accessed": 20.0}


@pytest.mark.parametrize("exec_name", ["train_step_dense"])
def test_ledger_stats_covers_registered_executable(exec_name):
    from apex_tpu.observability.xla_stats import ledger_stats

    out = ledger_stats([exec_name])
    assert exec_name in out
    entry = out[exec_name]
    assert "provenance" in entry
    # this image's CPU backend reports both analyses
    if entry["provenance"] == PROVENANCE_FULL:
        assert entry["flops"] > 0 and entry["peak_hbm_bytes"] > 0
