"""ISSUE 11 unit level: the in-program probe math against numpy
oracles (dense and sharded/span layouts), the per-leaf nonfinite
attribution, the host-side NumericsAccountant's gauges/counters/
events, and the deferred collector's vector extension."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.amp.scaler import nonfinite_leaf_counts
from apex_tpu.observability import (DeferredScalarCollector, JsonlSink,
                                    MetricsRegistry)
from apex_tpu.observability.numerics import (NUMERICS_EVENT_KINDS,
                                             NUMERICS_METRIC_FAMILIES,
                                             NumericsAccountant,
                                             compute_probes,
                                             flat_leaf_names)
from apex_tpu.observability import schema
from apex_tpu.optimizers import functional
from apex_tpu.optimizers.base import (sharded_leaf_nonfinite_counts,
                                      sharded_leaf_reduce)


def _params():
    return {"b": jnp.asarray(np.linspace(-0.5, 0.5, 4),
                             jnp.float32),
            "w": jnp.asarray(
                np.linspace(-1.0, 1.0, 12).reshape(3, 4),
                jnp.float32)}


# -- in-program probes ------------------------------------------------------

def test_compute_probes_dense_matches_numpy_oracle():
    tx = functional.fused_adam(lr=1e-2)
    params = _params()
    opt = tx.init(params)
    g = np.linspace(-2.0, 2.0, 16).astype(np.float32)
    new = tx.update(opt, jnp.asarray(g))
    probes = compute_probes(opt, new.master, jnp.asarray(g))

    np.testing.assert_allclose(float(probes.grad_sq),
                               float(np.sum(g.astype(np.float64) ** 2)),
                               rtol=1e-6)
    master = np.asarray(opt.master)
    np.testing.assert_allclose(float(probes.param_sq),
                               float(np.sum(master ** 2)), rtol=1e-6)
    delta = np.asarray(new.master) - master
    np.testing.assert_allclose(float(probes.update_sq),
                               float(np.sum(delta ** 2)), rtol=1e-5)
    # leaf order == tree_leaves order (b before w); their sum is the
    # global grad sq-norm
    np.testing.assert_allclose(np.asarray(probes.leaf_grad_sq),
                               [np.sum(g[:4] ** 2), np.sum(g[4:] ** 2)],
                               rtol=1e-6)
    np.testing.assert_allclose(float(jnp.sum(probes.leaf_grad_sq)),
                               float(probes.grad_sq), rtol=1e-6)
    assert np.asarray(probes.leaf_nonfinite).tolist() == [0.0, 0.0]


def test_nonfinite_attribution_names_the_poisoned_leaf():
    g = np.linspace(-1.0, 1.0, 16).astype(np.float32)
    g[5] = np.inf
    g[7] = np.nan
    counts = nonfinite_leaf_counts(jnp.asarray(g), (4, 12))
    assert counts.tolist() == [0.0, 2.0]   # both poisons live in leaf 1
    g[0] = -np.inf
    counts = nonfinite_leaf_counts(jnp.asarray(g), (4, 12))
    assert counts.tolist() == [1.0, 2.0]


@pytest.mark.parametrize("spans", [None, (1, 1)])
def test_sharded_leaf_nonfinite_counts_match_dense(spans):
    """Sharded partial counts summed over ranks == the dense count,
    on both the contiguous-block and the prefetch span layout."""
    from apex_tpu.optimizers.functional import _layout_master
    sizes = (4, 12)
    dp = 2
    g = np.linspace(-1.0, 1.0, 16).astype(np.float32)
    g[2] = np.inf
    g[9] = np.nan
    g[15] = np.inf
    dense = nonfinite_leaf_counts(jnp.asarray(g), sizes)
    laid = _layout_master(jnp.asarray(g), sizes=sizes,
                          spans=spans or (), dp=dp)
    shard_len = int(laid.shape[0]) // dp
    total = np.zeros(2)
    for r in range(dp):
        shard = laid[r * shard_len:(r + 1) * shard_len]
        total += np.asarray(sharded_leaf_nonfinite_counts(
            (shard,), sizes, dp=dp, shard_len=shard_len,
            rank=jnp.int32(r), spans=spans)[0])
    np.testing.assert_array_equal(total, np.asarray(dense))


def test_sharded_leaf_reduce_general_elem_fn():
    """The generalized reduce underlying both sq-norms and nonfinite
    counts: an arbitrary zero-preserving elem_fn sums per leaf."""
    sizes = (3, 5)
    v = jnp.asarray(np.arange(8, dtype=np.float32))
    out = sharded_leaf_reduce((v,), sizes, dp=1, shard_len=8,
                              rank=jnp.int32(0),
                              elem_fn=lambda x: jnp.abs(x))
    np.testing.assert_allclose(np.asarray(out[0]),
                               [0 + 1 + 2, 3 + 4 + 5 + 6 + 7])


def test_flat_leaf_names_are_keystr_paths_without_compute():
    tx = functional.fused_adam(lr=1e-2)
    opt = tx.init(_params())
    assert flat_leaf_names(opt) == ("['b']", "['w']")
    flat_only = tx.init(jnp.zeros((8,), jnp.float32))
    assert flat_leaf_names(flat_only) == ("flat[0]",)


# -- deferred vector extension ---------------------------------------------

def test_deferred_collector_resolves_vectors():
    col = DeferredScalarCollector()
    col.enqueue(0, leaf=jnp.asarray([1.0, 2.0]), scalar=jnp.float32(3.0))
    col.enqueue(1, leaf=jnp.asarray([4.0, 5.0]))
    [(step, resolved)] = col.poll()
    assert step == 0 and resolved["scalar"] == 3.0
    np.testing.assert_array_equal(resolved["leaf"], [1.0, 2.0])


# -- host-side accountant ---------------------------------------------------

def _resolved(grad_sq=4.0, param_sq=9.0, update_sq=0.09,
              leaf_g=(1.0, 3.0), leaf_nf=(0.0, 0.0), loss_scale=None):
    return {"nx_grad_sq": grad_sq, "nx_param_sq": param_sq,
            "nx_update_sq": update_sq,
            "nx_leaf_grad_sq": np.asarray(leaf_g),
            "nx_leaf_nonfinite": np.asarray(leaf_nf),
            **({} if loss_scale is None else {"loss_scale": loss_scale})}


def test_accountant_lands_gauges_and_events(tmp_path):
    reg = MetricsRegistry()
    jsonl = tmp_path / "t.jsonl"
    reg.add_sink(JsonlSink(str(jsonl)))
    acc = NumericsAccountant(reg, ("['b']", "['w']"))
    acc.resolve(0, _resolved(loss_scale=65536.0))
    assert acc.grad_norm.value() == pytest.approx(2.0)
    assert acc.param_norm.value() == pytest.approx(3.0)
    assert acc.update_ratio.value() == pytest.approx(0.1)
    assert acc.grad_norm_hist.count() == 1
    assert acc.leaf_grad_norm.value(leaf="['b']") == pytest.approx(1.0)
    assert acc.leaf_grad_norm.value(leaf="['w']") == pytest.approx(
        np.sqrt(3.0))
    [ev] = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    assert ev["kind"] == "train_numerics" and ev["step"] == 0
    assert ev["grad_norm"] == pytest.approx(2.0)
    assert ev["loss_scale"] == 65536.0
    assert ev["nonfinite_elems"] == 0.0


def test_accountant_autopsy_names_leaves_and_counts(tmp_path):
    reg = MetricsRegistry()
    jsonl = tmp_path / "t.jsonl"
    reg.add_sink(JsonlSink(str(jsonl)))
    acc = NumericsAccountant(reg, ("['b']", "['w']"))
    acc.resolve(3, _resolved(grad_sq=float("inf"),
                             leaf_g=(float("inf"), 1.0),
                             leaf_nf=(5.0, 0.0), loss_scale=32768.0))
    # nonfinite values never land on gauges/histogram
    assert acc.grad_norm.value() is None
    assert acc.grad_norm_hist.count() == 0
    assert acc.leaf_grad_norm.value(leaf="['b']") is None
    assert acc.leaf_grad_norm.value(leaf="['w']") == pytest.approx(1.0)
    # counters attribute per leaf
    assert acc.overflow_leaf.value(leaf="['b']") == 5.0
    assert acc.overflow_leaf.value(leaf="['w']") == 0.0
    assert acc.nonfinite_elems.total() == 5.0
    events = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    [autopsy] = [e for e in events if e["kind"] == "overflow_autopsy"]
    assert autopsy["step"] == 3
    assert autopsy["leaves"] == [{"leaf": "['b']", "nonfinite": 5}]
    assert autopsy["nonfinite_elems"] == 5.0


def test_accountant_tracks_backoffs_and_growths():
    acc = NumericsAccountant(MetricsRegistry(), ("x",))
    for scale in (65536.0, 65536.0, 32768.0, 32768.0, 65536.0):
        acc.observe_scale(scale)
    assert acc.backoffs.total() == 1.0
    assert acc.growths.total() == 1.0


def test_flush_resets_scale_chain_across_runs():
    """Reusing one telemetry across runs (the flush() contract): run
    B's fresh scaler starting above run A's decayed final scale must
    not count as a growth that never happened."""
    from apex_tpu.observability import TrainTelemetry
    import jax.numpy as jnp
    tel = TrainTelemetry(MetricsRegistry())
    acc = tel.arm_numerics(("x",))
    for scale in (65536.0, 16384.0):           # run A decays
        with tel.step():
            pass
        tel.observe_device(loss_scale=jnp.float32(scale))
    tel.flush()                                # run boundary
    with tel.step():
        pass
    tel.observe_device(loss_scale=jnp.float32(65536.0))  # run B fresh
    tel.flush()
    assert acc.backoffs.total() == 1.0         # run A's real backoff
    assert acc.growths.total() == 0.0, \
        "the cross-run scale jump was counted as a growth"


def test_accountant_unsampled_step_is_noop_beyond_scale_tracking():
    """APEX_TPU_NUMERICS_EVERY: an unsampled step resolves with no
    nx_* keys — nothing lands except the loss-scale series."""
    acc = NumericsAccountant(MetricsRegistry(), ("x",), every=2)
    acc.resolve(0, {"loss_scale": 65536.0, "loss": 1.0})
    acc.resolve(1, {"loss_scale": 32768.0, "loss": 1.0})
    assert acc.grad_norm.value() is None
    assert acc.grad_norm_hist.count() == 0
    assert acc.backoffs.total() == 1.0
    assert acc.every == 2


# -- schema guard (tier-1 satellite) ----------------------------------------

def test_every_numerics_family_and_event_is_schema_pinned():
    """The conscious-re-pin guard: every numerics metric family and
    JSONL event kind the mode emits is declared in the schema (and so
    in the committed .telemetry_schema.json, bit-for-bit via
    test_schema_guard)."""
    for fam in NUMERICS_METRIC_FAMILIES:
        assert fam in schema.METRIC_SPECS, fam
    for kind in NUMERICS_EVENT_KINDS:
        assert kind in schema.EVENT_FIELDS, kind
    # the histogram family carries the pinned grad-norm buckets
    assert schema.METRIC_SPECS["train_grad_norm_hist"].buckets == \
        schema.GRAD_NORM_BUCKETS
    # labeled families declare the leaf label (per-leaf attribution)
    assert schema.METRIC_SPECS["train_leaf_grad_norm"].labels == \
        ("leaf",)
    assert schema.METRIC_SPECS["train_overflow_leaf_total"].labels == \
        ("leaf",)
