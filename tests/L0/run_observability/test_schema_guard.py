"""Tier-1 guard pinning the telemetry schema (ISSUE 8 satellite).

Dashboards and log pipelines consume the Prometheus file and the JSONL
stream by FIELD NAME; renaming a family or an event field must be a
conscious act.  Exactly like the SPMD budget-ledger guard
(``tests/L1/test_spmd_audit.py``): the committed
``.telemetry_schema.json`` must match the in-code declarations
bit-for-bit, and re-pinning is an explicit command::

    python -m apex_tpu.observability.schema --write
"""
import json
from pathlib import Path

from apex_tpu.analysis.cli import repo_root
from apex_tpu.observability import schema

REPO = Path(repo_root())


def test_committed_schema_is_current_bit_for_bit():
    committed_text = (REPO / schema.SCHEMA_NAME).read_text(
        encoding="utf-8")
    expected_text = json.dumps(schema.current_schema(), indent=1) + "\n"
    assert committed_text == expected_text, (
        "telemetry schema drifted from the committed "
        f"{schema.SCHEMA_NAME} — if the change is intentional, re-pin "
        "with `python -m apex_tpu.observability.schema --write` and "
        "commit (dashboards parse these names)")


def test_schema_pins_types_and_buckets_not_just_names():
    """The guard covers the full wire contract: kind, labels, buckets
    (histograms), and per-event field TYPES."""
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    assert committed["version"] == schema.SCHEMA_VERSION
    for name, spec in schema.METRIC_SPECS.items():
        entry = committed["prometheus"][name]
        assert entry["type"] == spec.kind, name
        assert tuple(entry["labels"]) == spec.labels, name
        if spec.kind == "histogram":
            assert tuple(entry["buckets"]) == spec.buckets, name
    for kind, fields in schema.EVENT_FIELDS.items():
        assert committed["jsonl"]["events"][kind] == fields, kind
    assert committed["jsonl"]["common"] == schema.COMMON_EVENT_FIELDS


def test_span_and_slo_families_are_pinned():
    """ISSUE 13 satellite: the committed schema re-pin covers every
    family and event the tracing/SLO modules emit — a new span or SLO
    family cannot ship unpinned (the NUMERICS_METRIC_FAMILIES pattern)."""
    from apex_tpu.observability import slo, spans
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    for fam in spans.TRACE_METRIC_FAMILIES + slo.SLO_METRIC_FAMILIES:
        assert fam in committed["prometheus"], fam
        assert fam in schema.METRIC_SPECS, fam
    for kind in spans.TRACE_EVENTS + slo.SLO_EVENTS + ("request_shed",):
        assert kind in committed["jsonl"]["events"], kind
        assert kind in schema.EVENT_FIELDS, kind
    # the scheduler's shed path reaches the shed counter too
    assert "serve_requests_shed_total" in committed["prometheus"]


def test_speculation_families_are_pinned():
    """ISSUE 15 satellite: the committed schema re-pin covers every
    speculation/fused-dispatch family the serve telemetry and engine
    emit — a new family cannot ship unpinned."""
    from apex_tpu.observability import serve
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    for fam in serve.SPEC_METRIC_FAMILIES:
        assert fam in committed["prometheus"], fam
        assert fam in schema.METRIC_SPECS, fam


def test_tier_families_are_pinned():
    """ISSUE 18 satellite: the committed schema re-pin covers every
    host-page-tier family the serve telemetry and engine emit, plus
    the page_swap event — a new tier family cannot ship unpinned."""
    from apex_tpu.observability import serve
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    for fam in serve.TIER_METRIC_FAMILIES:
        assert fam in committed["prometheus"], fam
        assert fam in schema.METRIC_SPECS, fam
    assert "page_swap" in committed["jsonl"]["events"]
    assert "page_swap" in schema.EVENT_FIELDS


def test_fleet_families_are_pinned():
    """ISSUE 19 satellite: the committed schema re-pin covers every
    fleet-router family FleetTelemetry emits, plus the route_decision
    event — a new fleet family cannot ship unpinned (the
    TIER_METRIC_FAMILIES pattern)."""
    from apex_tpu.observability import serve
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    for fam in serve.FLEET_METRIC_FAMILIES:
        assert fam in committed["prometheus"], fam
        assert fam in schema.METRIC_SPECS, fam
    assert "route_decision" in committed["jsonl"]["events"]
    assert "route_decision" in schema.EVENT_FIELDS
    # the per-replica families carry the replica label dashboards
    # group by
    for fam in ("fleet_requests_routed_total",
                "fleet_requests_shed_total",
                "fleet_replica_queue_depth"):
        assert "replica" in schema.METRIC_SPECS[fam].labels, fam


def test_measured_attribution_families_are_pinned():
    """ISSUE 14 satellite: the committed schema re-pin covers every
    family and event the trace-ingestion/attribution layer emits — a
    new measured family cannot ship unpinned."""
    from apex_tpu.observability import attribution, tracing
    committed = json.loads((REPO / schema.SCHEMA_NAME).read_text())
    for fam in attribution.ATTRIBUTION_METRIC_FAMILIES:
        assert fam in committed["prometheus"], fam
        assert fam in schema.METRIC_SPECS, fam
    for kind in attribution.ATTRIBUTION_EVENTS + tracing.PROFILE_EVENTS:
        assert kind in committed["jsonl"]["events"], kind
        assert kind in schema.EVENT_FIELDS, kind
    # the attribution event keeps its nullable measurement fields next
    # to the provenance marker (null is the explicit absence)
    fields = committed["jsonl"]["events"]["attribution"]
    assert fields["provenance"] == "str"
    assert fields["window_us"] == "float|null"
    assert fields["mfu"] == "float|null"


def test_histogram_buckets_are_sorted_positive():
    """Non-physical bucket layouts (unsorted, non-positive bounds) are
    schema bugs — latencies cannot be <= 0."""
    for name, spec in schema.METRIC_SPECS.items():
        if spec.kind != "histogram":
            continue
        assert list(spec.buckets) == sorted(spec.buckets), name
        assert all(b > 0 for b in spec.buckets), name
        assert len(set(spec.buckets)) == len(spec.buckets), name
