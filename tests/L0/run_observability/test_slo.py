"""SLO math (ISSUE 13): burn rate / error budget against hand-computed
windows (empty and 100%-violation windows included), bucket-resolution
threshold semantics, per-tenant goodput floors, and the overload
detector's trend rule."""
import pytest

from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.slo import (OverloadDetector, SLOSpec,
                                        SLOTracker, slo_specs_from_env,
                                        slo_target_us)


class _CaptureSink:
    def __init__(self):
        self.events = []

    def event(self, obj):
        self.events.append(obj)


def _tracker(specs, **kw):
    reg = MetricsRegistry()
    sink = _CaptureSink()
    reg.add_sink(sink)
    return SLOTracker(reg, specs, **kw), reg, sink


TTFT = SLOSpec("ttft_p99", "serve_ttft_seconds", 0.05)  # 50ms @ p99


def test_burn_rate_hand_computed_window():
    """10 good + 1 bad sample against a 1% budget: violation fraction
    1/11, burn rate (1/11)/0.01 ~= 9.09 — budget fully gone."""
    tr, reg, sink = _tracker((TTFT,))
    h = reg.declared("serve_ttft_seconds")
    for _ in range(10):
        h.observe(0.01)
    h.observe(0.2)
    w = tr.observe_window()
    s = w["slos"]["ttft_p99"]
    assert s["samples"] == 11 and s["violations"] == 1
    assert s["fraction"] == pytest.approx(1 / 11)
    assert s["burn_rate"] == pytest.approx((1 / 11) / 0.01)
    # cumulative budget: allowed = 0.01 * 11 = 0.11 violations, spent 1
    assert s["budget_remaining"] == 0.0
    assert tr.burn_rate.value(slo="ttft_p99") == s["burn_rate"]
    assert int(tr.violations.value(slo="ttft_p99")) == 1
    [ev] = [e for e in sink.events if e["kind"] == "slo_violation"]
    assert ev["slo"] == "ttft_p99" and ev["window"] == 1
    assert ev["violations"] == 1 and ev["samples"] == 11
    assert ev["burn_rate"] == pytest.approx((1 / 11) / 0.01, rel=1e-6)
    assert ev["threshold"] == 0.05


def test_empty_window_publishes_nothing():
    tr, reg, sink = _tracker((TTFT,))
    w = tr.observe_window()
    s = w["slos"]["ttft_p99"]
    assert s["samples"] == 0 and s["burn_rate"] is None
    assert tr.burn_rate.value(slo="ttft_p99") is None
    assert [e for e in sink.events if e["kind"] == "slo_violation"] == []
    # a later window only accounts its own delta
    h = reg.declared("serve_ttft_seconds")
    for _ in range(200):
        h.observe(0.01)
    w = tr.observe_window()
    assert w["slos"]["ttft_p99"]["samples"] == 200
    assert w["slos"]["ttft_p99"]["burn_rate"] == 0.0
    # healthy traffic: full budget intact
    assert w["slos"]["ttft_p99"]["budget_remaining"] == 1.0


def test_hundred_percent_violation_window():
    tr, reg, sink = _tracker((TTFT,))
    h = reg.declared("serve_ttft_seconds")
    for _ in range(5):
        h.observe(1.0)
    w = tr.observe_window()
    s = w["slos"]["ttft_p99"]
    assert s["fraction"] == 1.0
    assert s["burn_rate"] == pytest.approx(1.0 / 0.01)   # 100x budget
    assert s["budget_remaining"] == 0.0
    assert int(tr.violations.value(slo="ttft_p99")) == 5


def test_windows_are_deltas_not_cumulative():
    """Window 2 sees only the samples recorded after window 1 — a hot
    first window does not poison a healthy second one."""
    tr, reg, sink = _tracker((SLOSpec("ttft_p99", "serve_ttft_seconds",
                                      0.05, quantile=0.9),))
    h = reg.declared("serve_ttft_seconds")
    h.observe(0.2)                      # window 1: 100% violation
    assert tr.observe_window()["slos"]["ttft_p99"]["burn_rate"] \
        == pytest.approx(10.0)
    for _ in range(9):                  # window 2: all good
        h.observe(0.01)
    w2 = tr.observe_window()["slos"]["ttft_p99"]
    assert w2["samples"] == 9 and w2["violations"] == 0
    assert w2["burn_rate"] == 0.0
    # cumulative budget: 1 violation allowed over 10 samples at q=0.9
    # -> exactly spent
    assert w2["budget_remaining"] == pytest.approx(0.0)


def test_fresh_tracker_on_warm_registry_owns_no_history():
    """A second tracker attached to a registry already holding traffic
    (two schedulers sharing one telemetry) seeds its window baseline
    from the CURRENT histogram state — prior violations are not
    re-counted and no spurious event fires (review fix)."""
    tr, reg, sink = _tracker((TTFT,))
    h = reg.declared("serve_ttft_seconds")
    for _ in range(9):
        h.observe(0.01)
    h.observe(0.2)                      # scheduler 1's violation
    tr.observe_window()
    assert int(tr.violations.value(slo="ttft_p99")) == 1
    tr2 = SLOTracker(reg, (TTFT,))      # scheduler 2, same registry
    w = tr2.observe_window()            # no new traffic
    s = w["slos"]["ttft_p99"]
    assert s["samples"] == 0 and s["violations"] == 0
    assert int(tr2.violations.value(slo="ttft_p99")) == 1   # unchanged
    assert len([e for e in sink.events
                if e["kind"] == "slo_violation"]) == 1


def test_threshold_clamps_to_bucket_resolution():
    """A sample between the clamped bucket bound and the threshold
    counts as a violation — the conservative reading; a sample exactly
    ON a bound covered by the threshold stays good."""
    spec = SLOSpec("ttft_p99", "serve_ttft_seconds", 0.03)
    tr, reg, _ = _tracker((spec,))
    h = reg.declared("serve_ttft_seconds")
    # buckets include 0.025: threshold 0.03 clamps down to 0.025
    h.observe(0.025)                    # on the bound: good
    h.observe(0.028)                    # < threshold but > bound: bad
    w = tr.observe_window()["slos"]["ttft_p99"]
    assert w["violations"] == 1


def test_tenant_goodput_floor_names_violators():
    tr, reg, sink = _tracker((), tenant_goodput_floor=0.9)
    adm = reg.declared("serve_tenant_admitted_total")
    shed = reg.declared("serve_requests_shed_total")
    adm.inc(9, tenant="good")
    adm.inc(1, tenant="acme")
    shed.inc(1, tenant="acme")          # goodput 0.5 < 0.9
    w = tr.observe_window()
    assert w["tenants"] == {"acme": 0.5, "good": 1.0}
    assert tr.violating_tenants == ["acme"]
    assert tr.tenant_goodput.value(tenant="acme") == 0.5
    [ev] = [e for e in sink.events if e["kind"] == "slo_violation"]
    assert ev["slo"] == "tenant_goodput:acme"
    assert ev["fraction"] == 0.5 and ev["threshold"] == 0.9
    assert ev["burn_rate"] is None
    assert "violating_tenants" in tr.summary()


def test_spec_validation():
    with pytest.raises(ValueError, match="threshold"):
        SLOSpec("x", "serve_ttft_seconds", 0.0)
    with pytest.raises(ValueError, match="quantile"):
        SLOSpec("x", "serve_ttft_seconds", 0.1, quantile=1.0)
    with pytest.raises(ValueError, match="histogram"):
        tr, reg, _ = _tracker(
            (SLOSpec("x", "serve_queue_depth", 0.1),))
        tr.observe_window()
    with pytest.raises(ValueError, match="tenant_goodput_floor"):
        _tracker((), tenant_goodput_floor=1.5)


def test_specs_from_env(monkeypatch):
    monkeypatch.delenv("APEX_TPU_SLO_TTFT_US", raising=False)
    monkeypatch.delenv("APEX_TPU_SLO_DECODE_US", raising=False)
    assert slo_specs_from_env() == ()
    monkeypatch.setenv("APEX_TPU_SLO_TTFT_US", "50000")
    monkeypatch.setenv("APEX_TPU_SLO_DECODE_US", "2500")
    ttft, decode = slo_specs_from_env()
    assert ttft.family == "serve_ttft_seconds"
    assert ttft.threshold_s == pytest.approx(0.05)
    assert decode.family == "serve_decode_token_seconds"
    assert decode.threshold_s == pytest.approx(0.0025)
    monkeypatch.setenv("APEX_TPU_SLO_TTFT_US", "fast")
    with pytest.raises(ValueError, match="APEX_TPU_SLO_TTFT_US"):
        slo_target_us("APEX_TPU_SLO_TTFT_US")


# -- overload detector -------------------------------------------------------

def test_overload_detector_sustained_queue_flips():
    det = OverloadDetector(window=3, queue_high=2)
    assert not det.observe(5)           # window not full yet
    assert not det.observe(5)
    assert det.observe(5)               # sustained at >= queue_high
    assert det.observe(6)               # still rising
    assert not det.observe(1)           # drained below high -> clears


def test_overload_detector_draining_queue_is_not_overload():
    det = OverloadDetector(window=3, queue_high=2)
    for depth in (6, 5, 4, 3):
        assert not det.observe(depth)   # decreasing = draining


def test_overload_detector_backpressure_counts_as_pressure():
    det = OverloadDetector(window=3, queue_high=100)
    det.observe(1, backpressure_total=0)
    det.observe(1, backpressure_total=1)
    assert det.observe(1, backpressure_total=2)   # bp grew in-window


def test_overload_detector_recovering_pool_blocks_advisory():
    det = OverloadDetector(window=3, queue_high=2)
    det.observe(5, free_pages=2)
    det.observe(5, free_pages=4)
    assert not det.observe(5, free_pages=8)   # pool recovering
    det2 = OverloadDetector(window=3, queue_high=2)
    det2.observe(5, free_pages=8)
    det2.observe(5, free_pages=4)
    assert det2.observe(5, free_pages=2)      # pool starving


def test_observe_load_emits_events_on_flips_only():
    tr, reg, sink = _tracker((), detector=OverloadDetector(
        window=2, queue_high=2))
    for depth in (4, 4, 4, 0, 0):
        tr.observe_load(queue_depth=depth)
    flips = [e for e in sink.events if e["kind"] == "overload"]
    assert [e["overloaded"] for e in flips] == [True, False]
    assert flips[0]["queue_depth"] == 4
    assert tr.overload_gauge.value() == 0.0
    assert not tr.shedding_advisory()
