"""L0 test runner (reference: ``tests/L0/run_test.py`` — selects test
subdirectories with ``--include`` and runs them as one suite).

The reference drives ``unittest.TestLoader`` over
``run_amp / run_fp16util / run_optimizers / run_fused_layer_norm / ...``;
this repo's suites are pytest files in the same per-area layout, so the
runner shells out to pytest with the selected directories.

Usage::

    python tests/L0/run_test.py                       # every L0 area
    python tests/L0/run_test.py --include run_amp run_optimizers
"""
import argparse
import os
import subprocess
import sys

L0_DIR = os.path.dirname(os.path.abspath(__file__))

TEST_DIRS = sorted(
    d for d in os.listdir(L0_DIR)
    if d.startswith("run_") and os.path.isdir(os.path.join(L0_DIR, d)))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="apex_tpu L0 test runner")
    p.add_argument("--include", nargs="+", default=TEST_DIRS,
                   choices=TEST_DIRS, metavar="DIR",
                   help=f"subset of {TEST_DIRS}")
    p.add_argument("-x", "--exitfirst", action="store_true",
                   help="stop on first failure")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    cmd = [sys.executable, "-m", "pytest", "-q"]
    if args.exitfirst:
        cmd.append("-x")
    cmd += [os.path.join(L0_DIR, d) for d in args.include]
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
