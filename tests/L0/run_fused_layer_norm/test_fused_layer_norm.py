"""Fused LayerNorm/RMSNorm kernel vs jnp-oracle tests.

Mirrors the reference's ``tests/L0/run_fused_layer_norm/test_fused_layer_norm.py``
(fused CUDA kernel vs ``torch.nn.LayerNorm`` within dtype tolerances), here
Pallas-interpret vs pure jnp, including gradients.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops import (
    layer_norm,
    layer_norm_reference,
    rms_norm,
    rms_norm_reference,
)

SHAPES = [(4, 16, 256), (3, 384), (16, 1024)]
ODD_SHAPES = [(4, 65), (2, 3, 100)]  # H % 128 != 0 -> jnp fallback path


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else dict(rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("shape", SHAPES + ODD_SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("affine", [True, False])
def test_layer_norm_forward(shape, dtype, affine):
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, shape, dtype)
    h = shape[-1]
    w = jax.random.normal(k2, (h,), jnp.float32) if affine else None
    b = jax.random.normal(k3, (h,), jnp.float32) if affine else None
    got = layer_norm(x, w, b)
    want = layer_norm_reference(x, w, b)
    assert got.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **_tol(dtype))


@pytest.mark.parametrize("shape", SHAPES + ODD_SHAPES)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rms_norm_forward(shape, dtype):
    key = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, shape, dtype)
    w = jax.random.normal(k2, (shape[-1],), jnp.float32)
    got = rms_norm(x, w)
    want = rms_norm_reference(x, w)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32), **_tol(dtype))


@pytest.mark.parametrize("shape", [(16, 256), (3, 384)])
def test_layer_norm_grads(shape):
    key = jax.random.PRNGKey(2)
    k1, k2, k3 = jax.random.split(key, 3)
    x = jax.random.normal(k1, shape, jnp.float32)
    h = shape[-1]
    w = 1.0 + 0.1 * jax.random.normal(k2, (h,), jnp.float32)
    b = 0.1 * jax.random.normal(k3, (h,), jnp.float32)

    def loss_fused(x, w, b):
        return jnp.sum(jnp.sin(layer_norm(x, w, b)))

    def loss_ref(x, w, b):
        return jnp.sum(jnp.sin(layer_norm_reference(x, w, b)))

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("shape", [(16, 256), (5, 512)])
def test_rms_norm_grads(shape):
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, shape, jnp.float32)
    w = 1.0 + 0.1 * jax.random.normal(k2, (shape[-1],), jnp.float32)

    def loss_fused(x, w):
        return jnp.sum(jnp.cos(rms_norm(x, w)))

    def loss_ref(x, w):
        return jnp.sum(jnp.cos(rms_norm_reference(x, w)))

    g_fused = jax.grad(loss_fused, argnums=(0, 1))(x, w)
    g_ref = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4)


def test_layer_norm_normalized_shape_multi_dim():
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 4, 64))
    w = jnp.ones((4, 64))
    b = jnp.zeros((4, 64))
    got = layer_norm(x, w, b, normalized_shape=(4, 64))
    x2 = x.reshape(6, 256)
    want = layer_norm_reference(x2, w.reshape(-1), b.reshape(-1)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_layer_norm_under_jit():
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 256))
    w = jnp.ones((256,))
    b = jnp.zeros((256,))
    jitted = jax.jit(lambda x: layer_norm(x, w, b))
    np.testing.assert_allclose(np.asarray(jitted(x)),
                               np.asarray(layer_norm_reference(x, w, b)),
                               rtol=1e-5, atol=1e-5)
