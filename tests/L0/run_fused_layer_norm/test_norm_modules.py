"""FusedLayerNorm/FusedRMSNorm module tests (reference:
``tests/L0/run_fused_layer_norm/test_fused_layer_norm.py`` module cases)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (
    FusedLayerNorm, FusedRMSNorm, fused_layer_norm, fused_rms_norm)
from apex_tpu.ops import layer_norm_reference, rms_norm_reference


@pytest.mark.parametrize("hidden", [256, 300])
def test_layer_norm_module(hidden):
    m = FusedLayerNorm(normalized_shape=hidden)
    x = jnp.asarray(np.random.RandomState(0).randn(4, 7, hidden), jnp.float32)
    params = m.init(jax.random.key(0), x)
    y = m.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(layer_norm_reference(x)), atol=1e-5)


def test_layer_norm_module_grads():
    hidden = 256
    m = FusedLayerNorm(normalized_shape=hidden)
    x = jnp.asarray(np.random.RandomState(0).randn(8, hidden), jnp.float32)
    params = m.init(jax.random.key(0), x)

    def loss(p, x):
        return jnp.sum(m.apply(p, x) ** 2)

    g = jax.grad(loss)(params, x)
    ref_g = jax.grad(
        lambda p, x: jnp.sum((layer_norm_reference(
            x, p["params"]["weight"], p["params"]["bias"])) ** 2))(params, x)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-4), g, ref_g)


def test_rms_norm_module():
    hidden = 384
    m = FusedRMSNorm(normalized_shape=hidden)
    x = jnp.asarray(np.random.RandomState(1).randn(5, hidden), jnp.float32)
    params = m.init(jax.random.key(0), x)
    y = m.apply(params, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(rms_norm_reference(x)), atol=1e-5)


def test_no_affine():
    m = FusedLayerNorm(normalized_shape=128, elementwise_affine=False)
    x = jnp.asarray(np.random.RandomState(2).randn(3, 128), jnp.float32)
    params = m.init(jax.random.key(0), x)
    assert not jax.tree_util.tree_leaves(params)  # no params
    y = m.apply(params, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(layer_norm_reference(x)), atol=1e-5)


def test_functional_multidim_normalized_shape():
    x = jnp.asarray(np.random.RandomState(3).randn(6, 4, 128), jnp.float32)
    y = fused_layer_norm(x, (4, 128))
    ref = layer_norm_reference(x.reshape(6, -1)).reshape(x.shape)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)


def test_functional_rms_with_weight():
    x = jnp.asarray(np.random.RandomState(4).randn(6, 256), jnp.float32)
    w = jnp.asarray(np.random.RandomState(5).rand(256), jnp.float32)
    y = fused_rms_norm(x, 256, weight=w)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(rms_norm_reference(x, w)),
                               atol=1e-5)


def test_bf16_input_fp32_params():
    hidden = 256
    m = FusedLayerNorm(normalized_shape=hidden)
    x = jnp.asarray(np.random.RandomState(6).randn(8, hidden), jnp.bfloat16)
    params = m.init(jax.random.key(0), x)
    assert params["params"]["weight"].dtype == jnp.float32
    y = m.apply(params, x)
    assert y.dtype == jnp.bfloat16
