"""1F1B trace-cost budget (SURVEY §7 "hard parts — 1F1B on TPU"): the
bounded-memory executor re-traces the stage vjp inside the scan body
(``jax.vjp`` + ``jax.closure_convert`` per tick half), which is O(1) per
trace but would silently explode compile times if a future change made it
per-microbatch or quadratic.  Pin it: tracing a pp=4 pipeline over a REAL
transformer stage (the standalone GPT layer with TP layers + flash
attention) must stay within a fixed time and jaxpr-size budget.

Measured baseline on the CI CPU mesh: ~0.9 s trace+lower, ~150 KB jaxpr
text; budgets are ~10x that — loose enough for slow CI, tight enough that
an O(num_microbatches) regression (8 extra stage traces) trips it.
"""
import functools
import time

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_pipelining_without_interleaving,
)
from apex_tpu.transformer.testing import GPTConfig
from apex_tpu.transformer.testing.standalone_gpt import (
    ParallelTransformerLayer,
)

PP, HID, SEQ, BS, N_MICRO = 4, 64, 32, 2, 8




@pytest.fixture
def setup():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP)
    yield
    parallel_state.destroy_model_parallel()


def _trace_budget(executor, label, trace_budget_s,
                  jaxpr_budget_bytes, **kw):
    mesh = parallel_state.get_mesh()
    cfg = GPTConfig(vocab_size=128, hidden_size=HID, num_layers=PP,
                    num_attention_heads=4, max_seq_length=SEQ,
                    hidden_dropout=0.0, attention_dropout=0.0)
    layer = ParallelTransformerLayer(cfg, causal=True)
    x0 = jnp.zeros((SEQ, BS, HID))
    params = layer.init(jax.random.PRNGKey(0), x0, None, True)
    batch = {"x": jnp.zeros((N_MICRO, SEQ, BS, HID)),
             "t": jnp.zeros((N_MICRO, SEQ, BS, HID))}

    def stage(p, x, mb):
        return layer.apply(p, x, None, True)

    def loss(y, mb):
        return jnp.mean((y - mb["t"]) ** 2)

    def body(p, b):
        return executor(
            stage, loss, p, b, num_microbatches=N_MICRO,
            input_fn=lambda mb: mb["x"], **kw)

    f = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P())))

    t0 = time.time()
    traced = f.trace(params, batch)
    traced.lower()
    elapsed = time.time() - t0
    assert elapsed < trace_budget_s, (
        f"{label} trace+lower took {elapsed:.1f}s "
        f"(budget {trace_budget_s}s) — did the per-tick vjp rebuild "
        "become per-microbatch?")

    jaxpr_bytes = len(str(traced.jaxpr))
    assert jaxpr_bytes < jaxpr_budget_bytes, (
        f"{label} jaxpr grew to {jaxpr_bytes} bytes "
        f"(budget {jaxpr_budget_bytes}) — residual machinery duplicating "
        "stage compute per microbatch?")


def test_1f1b_trace_cost_bounded_with_gpt_stage(setup):
    # measured ~0.9s / ~150KB; 10x margins trip on an
    # O(num_microbatches) regression (8 extra stage traces)
    _trace_budget(forward_backward_pipelining_without_interleaving,
                  "1F1B", 10.0, 1_500_000)


def test_interleaved_trace_cost_bounded_with_gpt_stage(setup):
    """The interleaved executor traces the stage in 3 phases x 2 halves;
    budget pins that it stays O(1) in num_microbatches."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_pipelining_with_interleaving)

    def chunked(executor):
        def run(stage, loss, p, b, **kws):
            p2 = jax.tree.map(lambda x: jnp.stack([x, x]), p)
            return executor(stage, loss, p2, b,
                            virtual_pipeline_model_parallel_size=2, **kws)
        return run

    # measured ~2.4s / ~0.9MB (3 phases x 2 halves x 2 chunks);
    # same ~8x margin against per-microbatch blowup
    _trace_budget(
        chunked(forward_backward_pipelining_with_interleaving),
        "interleaved", 20.0, 3_000_000)
