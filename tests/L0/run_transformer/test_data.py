"""broadcast_data semantics (reference:
``tests/L0/run_transformer/test_data.py``): all TP ranks must see
TP-rank-0's data even when each rank was fed different arrays."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel.data import broadcast_data

TP = 4


@pytest.fixture
def mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=TP)
    yield parallel_state.get_mesh()
    parallel_state.destroy_model_parallel()


def test_broadcast_data_all_ranks_see_rank0(mesh):
    # per-rank distinct payloads, leading dim = tp rank
    per_rank = {
        "tokens": jnp.arange(TP * 6, dtype=jnp.int32).reshape(TP, 6),
        "labels": 100 + jnp.arange(TP * 6, dtype=jnp.int32).reshape(TP, 6),
    }

    def body(data):
        mine = jax.tree.map(lambda x: x[0], data)
        out = broadcast_data(["tokens", "labels"], mine)
        return jax.tree.map(lambda x: x[None], out)

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("tensor"),), out_specs=P("tensor")))(per_rank)
    # every rank's result equals rank 0's input
    for k in ("tokens", "labels"):
        for r in range(TP):
            np.testing.assert_array_equal(out[k][r], per_rank[k][0])


def test_broadcast_data_dtype_conversion(mesh):
    per_rank = {"x": jnp.arange(TP * 4, dtype=jnp.int64.dtype if hasattr(
        jnp.int64, "dtype") else jnp.int32).reshape(TP, 4)}

    def body(data):
        mine = jax.tree.map(lambda x: x[0], data)
        out = broadcast_data(["x"], mine, datatype=jnp.int32)
        return jax.tree.map(lambda x: x[None], out)

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("tensor"),), out_specs=P("tensor")))(per_rank)
    assert out["x"].dtype == jnp.int32


def test_broadcast_data_tp1_identity():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=1)
    try:
        data = {"a": jnp.arange(5)}
        out = broadcast_data(["a"], data)
        np.testing.assert_array_equal(out["a"], data["a"])
    finally:
        parallel_state.destroy_model_parallel()
