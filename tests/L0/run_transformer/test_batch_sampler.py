"""Batch sampler tests (reference:
``tests/L0/run_transformer/test_batch_sampler.py``)."""
import numpy as np
import pytest

from apex_tpu.transformer.testing import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

TOTAL, MBS, DP = 64, 4, 2


class TestSequentialSampler:
    def test_ranks_partition_each_global_batch(self):
        per_rank = [list(MegatronPretrainingSampler(
            TOTAL, 0, MBS, rank, DP)) for rank in range(DP)]
        # same number of micro-batches on every rank
        assert len({len(b) for b in per_rank}) == 1
        # each global batch = union of the rank slices, covering
        # consecutive indices
        for gb, (b0, b1) in enumerate(zip(*per_rank)):
            merged = b0 + b1
            assert sorted(merged) == list(
                range(gb * MBS * DP, (gb + 1) * MBS * DP))

    def test_resumes_from_consumed_samples(self):
        first = next(iter(MegatronPretrainingSampler(
            TOTAL, 16, MBS, 0, DP)))
        assert first[0] == 16

    def test_drop_last(self):
        # 10 samples, global batch 8 -> 1 full batch, partial dropped
        batches = list(MegatronPretrainingSampler(10, 0, MBS, 0, DP))
        assert len(batches) == 1
        batches = list(MegatronPretrainingSampler(
            10, 0, MBS, 0, DP, drop_last=False))
        assert len(batches) == 2

    def test_validation(self):
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(0, 0, MBS, 0, DP)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 8, MBS, 0, DP)
        with pytest.raises(RuntimeError):
            MegatronPretrainingSampler(8, 0, MBS, 3, DP)


class TestRandomSampler:
    def test_ranks_disjoint_and_shuffled(self):
        per_rank = [list(MegatronPretrainingRandomSampler(
            TOTAL, 0, MBS, rank, DP, seed=7)) for rank in range(DP)]
        flat = [i for b in per_rank for mb in [b] for bb in mb for i in bb]
        assert len(flat) == len(set(flat)), "ranks must not overlap"
        # shuffled: not the sequential order
        seq = [i for b in per_rank[0] for i in b]
        assert seq != sorted(seq)

    def test_same_seed_same_order(self):
        a = list(MegatronPretrainingRandomSampler(
            TOTAL, 0, MBS, 0, DP, seed=3))
        b = list(MegatronPretrainingRandomSampler(
            TOTAL, 0, MBS, 0, DP, seed=3))
        assert a == b
        c = list(MegatronPretrainingRandomSampler(
            TOTAL, 0, MBS, 0, DP, seed=4))
        assert a != c

    def test_epoch_reshuffles(self):
        epoch0 = list(MegatronPretrainingRandomSampler(
            TOTAL, 0, MBS, 0, DP, seed=3))
        epoch1 = list(MegatronPretrainingRandomSampler(
            TOTAL, TOTAL, MBS, 0, DP, seed=3))
        assert epoch0 != epoch1

    def test_micro_batch_size_shape(self):
        for mb in MegatronPretrainingRandomSampler(
                TOTAL, 0, MBS, 1, DP, seed=0):
            assert len(mb) == MBS


def test_partial_batch_split_proportionally():
    """drop_last=False must never hand a rank an empty micro-batch while
    another gets the whole remainder."""
    parts = [list(MegatronPretrainingSampler(
        10, 0, MBS, rank, DP, drop_last=False))[-1] for rank in range(DP)]
    assert sorted(parts[0] + parts[1]) == [8, 9]
    assert all(len(p) >= 1 for p in parts)


def test_random_sampler_rejects_tiny_dataset():
    with pytest.raises(RuntimeError, match="full global batch"):
        MegatronPretrainingRandomSampler(6, 0, MBS, 0, DP)
