"""Topology tests (reference: tests/L0/run_transformer/test_parallel_state.py)."""
import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state


@pytest.fixture(autouse=True)
def _clean():
    parallel_state.destroy_model_parallel()
    yield
    parallel_state.destroy_model_parallel()


def test_initialize_and_sizes():
    assert not parallel_state.model_parallel_is_initialized()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2)
    assert parallel_state.model_parallel_is_initialized()
    assert parallel_state.get_tensor_model_parallel_world_size() == 2
    assert parallel_state.get_pipeline_model_parallel_world_size() == 2
    assert parallel_state.get_data_parallel_world_size() == 2
    assert parallel_state.get_context_parallel_world_size() == 1
    mesh = parallel_state.get_mesh()
    assert mesh.shape["tensor"] == 2 and mesh.shape["pipe"] == 2


def test_invalid_world_size():
    with pytest.raises(RuntimeError):
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=3)


def test_destroy():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    parallel_state.destroy_model_parallel()
    assert not parallel_state.model_parallel_is_initialized()
    with pytest.raises(RuntimeError):
        parallel_state.get_mesh()


def test_ranks_inside_shard_map():
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=4, pipeline_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()

    def body():
        return (parallel_state.get_tensor_model_parallel_rank(),
                parallel_state.get_pipeline_model_parallel_rank(),
                parallel_state.get_tensor_model_parallel_src_rank())

    out_spec = P("pipe", "data", "context", "tensor")
    f = functools.partial(jax.shard_map, check_vma=False)(
        lambda: tuple(x.reshape(1, 1, 1, 1) for x in body()),
        mesh=mesh, in_specs=(), out_specs=out_spec)
    tp_rank, pp_rank, src = jax.jit(f)()
    # tp rank varies along the tensor axis only
    np.testing.assert_array_equal(
        np.asarray(tp_rank)[0, 0, 0], np.arange(4))
    np.testing.assert_array_equal(
        np.asarray(pp_rank)[:, 0, 0, 0], np.arange(2))
    # src rank = my global rank with tp coordinate zeroed -> multiple of tp
    assert np.all(np.asarray(src) % 4 == 0)


def test_first_last_stage_static_when_pp1():
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=1)
    assert parallel_state.is_pipeline_first_stage() is True
    assert parallel_state.is_pipeline_last_stage() is True


def test_virtual_pipeline_bookkeeping():
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2,
        virtual_pipeline_model_parallel_size_=2)
    assert parallel_state.get_virtual_pipeline_model_parallel_world_size() == 2
    parallel_state.set_virtual_pipeline_model_parallel_rank(1)
    assert parallel_state.get_virtual_pipeline_model_parallel_rank() == 1
    # non-zero virtual rank means "not the first model chunk"
    assert parallel_state.is_pipeline_first_stage() is False


def test_group_getters_cover_reference_surface():
    """Reference builds _EMBEDDING/_POSITION_EMBEDDING/_AMAX_REDUCTION
    groups; here groups ARE mesh axis names usable with psum."""
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, pipeline_model_parallel_size_=2)
    assert parallel_state.get_embedding_group() == "pipe"
    assert parallel_state.get_position_embedding_group() == "pipe"
    amax = parallel_state.get_amax_reduction_group()
    assert set(amax) == {"data", "expert", "context", "tensor"}
    # usable as a psum axis spec
    from jax.sharding import PartitionSpec as P
    mesh = parallel_state.get_mesh()

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    def reduce(x):
        return jax.lax.psum(x, amax)

    out = reduce(jnp.ones((8, 2)))
    # psum over data(2) x context(1) x tensor(2) = 4
    np.testing.assert_allclose(np.asarray(out), 4.0)
