"""gradient_accumulation_fusion semantics (reference:
``fused_weight_gradient_mlp_cuda :: wgrad_gemm_accum_fp32`` used by
``LinearWithGradAccumulationAndAsyncCommunication``): with bf16
activations and fp32 master weights, the weight gradient must be computed
with fp32 accumulation and reach the fp32 grad buffer WITHOUT being
rounded through bf16.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.tensor_parallel.layers import (
    _linear_wgrad_fp32,
    linear_with_grad_accumulation_and_async_allreduce,
)

B, S, IN, OUT = 4, 64, 256, 128


def _data(seed=0):
    kx, kw, kd = jax.random.split(jax.random.key(seed), 3)
    x = jax.random.normal(kx, (B, S, IN), jnp.bfloat16)
    w = jax.random.normal(kw, (OUT, IN), jnp.float32) * 0.05
    dy = jax.random.normal(kd, (B, S, OUT), jnp.bfloat16)
    return x, w, dy


def _wgrad(fn, x, w, dy):
    _, vjp = jax.vjp(fn, x, w)
    return vjp(dy)[1]


def test_wgrad_is_fp32_and_not_bf16_rounded():
    x, w, dy = _data()
    dw = _wgrad(_linear_wgrad_fp32, x, w, dy.astype(jnp.bfloat16))
    assert dw.dtype == jnp.float32

    # fp64 oracle of the same contraction
    oracle = np.einsum(
        "bso,bsi->oi",
        np.asarray(dy, np.float64), np.asarray(x, np.float64))
    # what the unfused path produces: the dot emits bf16, then upcasts
    rounded = np.asarray(
        jnp.einsum("bso,bsi->oi", dy, x).astype(jnp.float32), np.float64)

    err_fused = np.abs(np.asarray(dw, np.float64) - oracle).max()
    err_rounded = np.abs(rounded - oracle).max()
    # fp32 MXU accumulation must beat the bf16-quantized wgrad by a wide
    # margin (bf16 has 8 mantissa bits: ~0.4% relative rounding)
    assert err_fused < err_rounded / 8, (err_fused, err_rounded)


def test_forward_matches_unfused():
    x, w, dy = _data(1)
    y_fused = _linear_wgrad_fp32(x, w)
    y_plain = jnp.matmul(x, w.astype(jnp.bfloat16).T)
    np.testing.assert_array_equal(np.asarray(y_fused, np.float32),
                                  np.asarray(y_plain, np.float32))
    assert y_fused.dtype == jnp.bfloat16


def test_dgrad_matches_unfused():
    x, w, dy = _data(2)
    dx_fused = _wgrad(lambda x_, w_: (_linear_wgrad_fp32(x_, w_), None),
                      x, w, (dy, None))
    # compare against input grad of the plain bf16 matmul
    _, vjp = jax.vjp(_linear_wgrad_fp32, x, w)
    dx, _ = vjp(dy)
    _, vjp_plain = jax.vjp(
        lambda x_: jnp.matmul(x_, w.astype(jnp.bfloat16).T), x)
    (dx_plain,) = vjp_plain(dy)
    assert dx.dtype == x.dtype
    np.testing.assert_allclose(np.asarray(dx, np.float32),
                               np.asarray(dx_plain, np.float32),
                               rtol=1e-2, atol=1e-2)


def test_fusion_rejects_non_fp32_weights():
    """bf16 weights would silently round the fp32 wgrad back down (the
    custom_vjp cotangent must match the primal dtype); the reference
    equally hard-requires an fp32 main_grad buffer."""
    x, w, dy = _data(5)
    with pytest.raises(ValueError, match="fp32"):
        linear_with_grad_accumulation_and_async_allreduce(
            x, w.astype(jnp.bfloat16),
            gradient_accumulation_fusion=True, async_grad_allreduce=False)


def test_flag_threads_through_functional_api():
    x, w, dy = _data(3)

    def f(x_, w_):
        return linear_with_grad_accumulation_and_async_allreduce(
            x_, w_, gradient_accumulation_fusion=True,
            async_grad_allreduce=False)

    dw = _wgrad(f, x, w, dy)
    assert dw.dtype == jnp.float32


def test_hlo_emits_fp32_dot_from_bf16_operands():
    """Compiled-HLO evidence: the wgrad dot contracts bf16 operands into an
    f32 result (MXU fp32 accumulation), and the accumulator add runs in
    f32 — there is NO bf16 round-trip between dot and accumulate."""
    x, w, dy = _data(4)
    acc = jnp.zeros((OUT, IN), jnp.float32)

    def step(acc, x, w):
        def loss(w_):
            return jnp.sum(_linear_wgrad_fp32(x, w_).astype(jnp.float32))
        return acc + jax.grad(loss)(w)

    hlo = jax.jit(step).lower(acc, x, w).compile().as_text()
    import re
    # the wgrad dot must emit f32 DIRECTLY (fp32 accumulation), e.g.
    #   %dot = f32[128,256]{1,0} dot(%..., %...)
    assert re.search(r"=\s*f32\[128,256\][^\n]*\bdot\(", hlo), (
        "expected the wgrad dot to be f32-rooted in the compiled HLO")
    # and its result must never round-trip through a bf16[OUT,IN] buffer
    assert not re.search(
        r"=\s*bf16\[128,256\][^\n]*\b(convert|dot)\(", hlo), (
        "wgrad was rounded through bf16 before accumulation")
