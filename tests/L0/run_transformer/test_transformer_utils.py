"""transformer/utils tests (reference:
``tests/L0/run_transformer/test_transformer_utils.py``)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.utils import (
    VocabUtility,
    divide,
    ensure_divisibility,
    split_tensor_along_last_dim,
)


def test_divide():
    assert divide(12, 4) == 3
    with pytest.raises(AssertionError):
        divide(12, 5)


def test_ensure_divisibility():
    ensure_divisibility(8, 2)
    with pytest.raises(AssertionError):
        ensure_divisibility(7, 2)


def test_split_tensor_along_last_dim():
    x = jnp.arange(24.0).reshape(2, 12)
    parts = split_tensor_along_last_dim(x, 3)
    assert len(parts) == 3
    for i, p in enumerate(parts):
        assert p.shape == (2, 4)
        np.testing.assert_allclose(
            np.asarray(p), np.asarray(x)[:, i * 4:(i + 1) * 4])


def test_vocab_utility_ranges():
    # per-partition: rank r of world w owns [r*per, (r+1)*per)
    s, e = VocabUtility.vocab_range_from_per_partition_vocab_size(64, 3, 8)
    assert (s, e) == (192, 256)
    s, e = VocabUtility.vocab_range_from_global_vocab_size(512, 3, 8)
    assert (s, e) == (192, 256)
    # full coverage, no overlap
    spans = [VocabUtility.vocab_range_from_global_vocab_size(512, r, 8)
             for r in range(8)]
    assert spans[0][0] == 0 and spans[-1][1] == 512
    for (a, b), (c, d) in zip(spans, spans[1:]):
        assert b == c
