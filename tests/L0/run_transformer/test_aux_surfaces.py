"""Thin-but-load-bearing auxiliary surfaces that had no direct tests:
contrib nccl_p2p ppermute wrappers, the model-parallel GradScaler's
shared skip decision, the distributed-init no-op path, log_util."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=4)
    yield
    parallel_state.destroy_model_parallel()


def test_left_right_halo_exchange_routes_neighbors():
    from apex_tpu.contrib.nccl_p2p import left_right_halo_exchange

    mesh = parallel_state.get_mesh()
    n = 4
    tops = jnp.arange(n, dtype=jnp.float32).reshape(n, 1) + 100
    btms = jnp.arange(n, dtype=jnp.float32).reshape(n, 1) + 200

    def body(top, btm):
        from_prev, from_next = left_right_halo_exchange(
            top[0], btm[0], "tensor")
        return from_prev[None], from_next[None]

    from_prev, from_next = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P("tensor"), P("tensor")),
            out_specs=(P("tensor"), P("tensor"))))(tops, btms)
    # rank i receives prev's bottom halo and next's top halo
    np.testing.assert_array_equal(
        np.asarray(from_prev).ravel(),
        [200 + (i - 1) % n for i in range(n)])
    np.testing.assert_array_equal(
        np.asarray(from_next).ravel(),
        [100 + (i + 1) % n for i in range(n)])


def test_grad_scaler_shares_skip_decision_across_tp_ranks():
    """One rank's inf must make EVERY tensor rank skip (the reference's
    allreduce-found_inf delta over torch's GradScaler)."""
    from apex_tpu.transformer.amp import GradScaler

    mesh = parallel_state.get_mesh()
    scaler = GradScaler(model_parallel_axes=("tensor",))
    # rank 2's grad shard carries an inf
    grads = jnp.zeros((4, 8), jnp.float32).at[2, 3].set(jnp.inf)

    def body(g):
        state = scaler.init()
        _, state = scaler.unscale_({"w": g[0]}, state)
        return state.found_inf[None]

    found = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("tensor"),),
        out_specs=P("tensor")))(grads)
    assert np.asarray(found).shape == (4,)
    assert np.all(np.asarray(found) > 0), found   # EVERY rank skips


def test_grad_scaler_clean_grads_no_skip():
    from apex_tpu.transformer.amp import GradScaler

    mesh = parallel_state.get_mesh()
    scaler = GradScaler(model_parallel_axes=("tensor",))
    grads = jnp.ones((4, 8), jnp.float32)

    def body(g):
        state = scaler.init()
        ug, state = scaler.unscale_({"w": g[0]}, state)
        return state.found_inf[None], ug["w"][None]

    found, ug = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("tensor"),),
        out_specs=(P("tensor"), P("tensor"))))(grads)
    assert np.all(np.asarray(found) == 0)
    # unscale divides by the initial 2^16 scale
    np.testing.assert_allclose(np.asarray(ug), 1.0 / 2.0 ** 16, rtol=1e-6)


def test_initialize_distributed_backend_single_process_noop():
    from apex_tpu.transformer._ucc_util import (
        HAS_UCC, initialize_distributed_backend)

    assert HAS_UCC is False
    # single-process: returns without touching jax.distributed
    initialize_distributed_backend()
    initialize_distributed_backend(num_processes=1)


def test_log_util_roundtrip():
    import logging

    from apex_tpu.transformer.log_util import (
        get_transformer_logger, set_logging_level)

    pkg = logging.getLogger("apex_tpu.transformer")
    prev = pkg.level
    try:
        logger = get_transformer_logger("test_aux")
        assert logger.name == "apex_tpu.transformer.test_aux"
        set_logging_level(logging.WARNING)
        assert pkg.level == logging.WARNING
        assert logger is get_transformer_logger("test_aux")
    finally:
        pkg.setLevel(prev)   # don't leak a level into the session
