"""End-to-end standalone GPT/BERT (reference:
``tests/L0/run_transformer/test_gpt_minimal.py`` / ``test_bert_minimal.py``
— a real tiny transformer trains under model parallelism; SP is a pure
layout optimization with unchanged numerics).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import (
    BertConfig,
    GPTConfig,
    bert_model_provider,
    gpt_model_provider,
)

VOCAB, HIDDEN, LAYERS, HEADS, SEQ, BATCH = 64, 32, 2, 4, 16, 2


def _gpt_cfg(**kw):
    kw.setdefault("hidden_dropout", 0.0)
    kw.setdefault("attention_dropout", 0.0)
    return GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                     num_layers=LAYERS, num_attention_heads=HEADS,
                     max_seq_length=SEQ, **kw)


def _data(seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (BATCH, SEQ), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    parallel_state.destroy_model_parallel()


class TestGPTMinimal:
    def test_loss_reasonable_tp1(self):
        parallel_state.initialize_model_parallel(1)
        model = gpt_model_provider(_gpt_cfg())
        tokens, labels = _data()
        params = model.init(jax.random.PRNGKey(1), tokens, labels)
        loss = jax.jit(lambda p: model.apply(p, tokens, labels))(params)
        # random init: loss ~ log(vocab)
        assert abs(float(loss) - np.log(VOCAB)) < 1.0

    def test_tp4_loss_finite_and_scaled(self):
        parallel_state.initialize_model_parallel(4)
        mesh = parallel_state.get_mesh()
        model = gpt_model_provider(_gpt_cfg())
        tokens, labels = _data()

        def body(tokens, labels):
            p = model.init(jax.random.PRNGKey(1), tokens, labels)
            return model.apply(p, tokens, labels)

        loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(
                tokens, labels)
        assert np.isfinite(float(loss))
        assert abs(float(loss) - np.log(VOCAB)) < 1.0

    def test_sequence_parallel_matches_non_sp(self):
        # identical params; SP on vs off must give the SAME loss
        tp = 4
        parallel_state.initialize_model_parallel(tp)
        mesh = parallel_state.get_mesh()
        tokens, labels = _data()
        losses = {}
        for sp in (False, True):
            model = gpt_model_provider(_gpt_cfg(sequence_parallel=sp))

            def body(tokens, labels):
                # same seed -> same per-shard params in both runs
                p = model.init(jax.random.PRNGKey(3), tokens, labels)
                return model.apply(p, tokens, labels)

            losses[sp] = float(jax.jit(
                functools.partial(jax.shard_map, check_vma=False)(
                    body, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(
                        tokens, labels))
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=1e-5, atol=1e-6)

    def test_trains_single_device(self):
        parallel_state.initialize_model_parallel(1)
        model = gpt_model_provider(_gpt_cfg())
        tokens, labels = _data()
        params = model.init(jax.random.PRNGKey(1), tokens, labels)
        opt = FusedAdam(params, lr=1e-3)
        lg = jax.jit(jax.value_and_grad(
            lambda p: model.apply(p, tokens, labels)))
        first = None
        for _ in range(8):
            loss, grads = lg(params)
            if first is None:
                first = float(loss)
            params = opt.step(grads)
        assert float(loss) < first, (first, float(loss))

    def test_trains_with_dropout(self):
        """Train-mode path with hidden + in-kernel attention prob
        dropout: learns, and is reproducible per dropout rng."""
        parallel_state.initialize_model_parallel(1)
        model = gpt_model_provider(_gpt_cfg(hidden_dropout=0.1,
                                            attention_dropout=0.1))
        tokens, labels = _data()
        params = model.init({"params": jax.random.PRNGKey(1),
                             "dropout": jax.random.PRNGKey(2)},
                            tokens, labels)
        opt = FusedAdam(params, lr=1e-3)
        lg = jax.jit(lambda p, key: jax.value_and_grad(
            lambda p: model.apply(p, tokens, labels, deterministic=False,
                                  rngs={"dropout": key}))(p))
        first = None
        for i in range(10):
            loss, grads = lg(params, jax.random.PRNGKey(100 + i))
            if first is None:
                first = float(loss)
            params = opt.step(grads)
        assert float(loss) < first, (first, float(loss))
        # same dropout rng -> identical loss; different -> different
        l1, _ = lg(params, jax.random.PRNGKey(7))
        l2, _ = lg(params, jax.random.PRNGKey(7))
        l3, _ = lg(params, jax.random.PRNGKey(8))
        assert float(l1) == float(l2) and float(l1) != float(l3)

    def test_tp2_dropout_decorrelates_ranks(self, monkeypatch):
        """Attention prob dropout under TP: the rank is folded into the
        seed (Megatron's tensor-parallel rng stream).  The regression
        check is a CONTROL run with the fold neutralized (identity) —
        re-correlating the ranks' masks must change the loss, so a
        future edit that drops the fold cannot ship green."""
        import apex_tpu.ops.attention as attn_mod
        parallel_state.initialize_model_parallel(2)
        mesh = parallel_state.get_mesh()
        model = gpt_model_provider(_gpt_cfg(attention_dropout=0.3))
        tokens, labels = _data()

        def body(tokens, labels):
            p = model.init({"params": jax.random.PRNGKey(1),
                            "dropout": jax.random.PRNGKey(2)},
                           tokens, labels)
            return model.apply(p, tokens, labels, deterministic=False,
                               rngs={"dropout": jax.random.PRNGKey(5)})

        def run():
            return float(jax.jit(
                functools.partial(jax.shard_map, check_vma=False)(
                    body, mesh=mesh, in_specs=(P(), P()),
                    out_specs=P()))(tokens, labels))

        folded_a, folded_b = run(), run()
        real_fold = attn_mod.fold_rank_seed
        monkeypatch.setattr(
            attn_mod, "fold_rank_seed",
            lambda seed, axis_name: jnp.asarray(seed, jnp.int32))
        # the model imports the symbol at call time, so the patch takes
        import apex_tpu.transformer.testing.standalone_gpt as gpt_mod
        assert "fold_rank_seed" not in vars(gpt_mod)
        shared = run()
        monkeypatch.setattr(attn_mod, "fold_rank_seed", real_fold)
        assert np.isfinite(folded_a) and folded_a == folded_b
        assert abs(folded_a - np.log(VOCAB)) < 1.5
        assert folded_a != shared, (
            "identity fold did not change the loss — the TP rank fold "
            "is not reaching the kernel")

    def test_sp_hidden_dropout_per_rank_masks(self, monkeypatch):
        """Under sequence parallelism the hidden activations are
        sequence-SHARDED, so hidden-dropout masks must be drawn per TP
        rank (a shared key repeats one pattern across chunks).  Control:
        neutralizing the rank fold must change the loss."""
        import apex_tpu.transformer.testing.standalone_gpt as gpt_mod
        parallel_state.initialize_model_parallel(2)
        mesh = parallel_state.get_mesh()
        model = gpt_model_provider(_gpt_cfg(hidden_dropout=0.4,
                                            sequence_parallel=True))
        tokens, labels = _data()

        def body(tokens, labels):
            p = model.init({"params": jax.random.PRNGKey(1),
                            "dropout": jax.random.PRNGKey(2)},
                           tokens, labels)
            return model.apply(p, tokens, labels, deterministic=False,
                               rngs={"dropout": jax.random.PRNGKey(5)})

        def run():
            return float(jax.jit(
                functools.partial(jax.shard_map, check_vma=False)(
                    body, mesh=mesh, in_specs=(P(), P()),
                    out_specs=P()))(tokens, labels))

        folded = run()
        real = gpt_mod._hidden_dropout_rng
        monkeypatch.setattr(gpt_mod, "_hidden_dropout_rng",
                            lambda mod, sp: mod.make_rng("dropout"))
        shared = run()
        monkeypatch.setattr(gpt_mod, "_hidden_dropout_rng", real)
        assert np.isfinite(folded)
        assert folded != shared, (
            "rank fold not reaching SP hidden dropout")

    def test_remat_matches_baseline(self):
        parallel_state.initialize_model_parallel(1)
        tokens, labels = _data()
        m0 = gpt_model_provider(_gpt_cfg())
        m1 = gpt_model_provider(_gpt_cfg(remat=True))
        p = m0.init(jax.random.PRNGKey(5), tokens, labels)
        l0, g0 = jax.jit(jax.value_and_grad(
            lambda p: m0.apply(p, tokens, labels)))(p)
        l1, g1 = jax.jit(jax.value_and_grad(
            lambda p: m1.apply(p, tokens, labels)))(p)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a, b, rtol=1e-4, atol=1e-5), g0, g1)


class TestBertMinimal:
    def _cfg(self):
        return BertConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                          num_layers=LAYERS, num_attention_heads=HEADS,
                          max_seq_length=SEQ, hidden_dropout=0.0,
                          attention_dropout=0.0)

    def test_loss_with_padding_mask(self):
        parallel_state.initialize_model_parallel(1)
        model = bert_model_provider(self._cfg())
        tokens, labels = _data()
        attn = jnp.ones((BATCH, SEQ), jnp.int32).at[:, SEQ // 2:].set(0)
        tt = jnp.zeros((BATCH, SEQ), jnp.int32)
        params = model.init(jax.random.PRNGKey(1), tokens, tt, attn, labels)
        loss, binary = jax.jit(
            lambda p: model.apply(p, tokens, tt, attn, labels))(params)
        assert np.isfinite(float(loss))
        assert abs(float(loss) - np.log(VOCAB)) < 1.2
        assert binary.shape == (BATCH, 2)

    def test_tp4_runs(self):
        parallel_state.initialize_model_parallel(4)
        mesh = parallel_state.get_mesh()
        model = bert_model_provider(self._cfg(), add_binary_head=False)
        tokens, labels = _data()

        def body(tokens, labels):
            p = model.init(jax.random.PRNGKey(1), tokens,
                           lm_labels=labels)
            loss, _ = model.apply(p, tokens, lm_labels=labels)
            return loss

        loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(
                tokens, labels)
        assert np.isfinite(float(loss))


def test_scan_layers_matches_loop():
    """scan_layers is a compile-time optimization; same architecture, same
    loss when params are transplanted loop->scan layout."""
    from apex_tpu.transformer import parallel_state as ps
    ps.initialize_model_parallel(1)
    tokens, labels = _data()
    m_scan = gpt_model_provider(_gpt_cfg(scan_layers=True))
    p = m_scan.init(jax.random.PRNGKey(9), tokens, labels)
    loss = jax.jit(lambda p: m_scan.apply(p, tokens, labels))(p)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(VOCAB)) < 1.2
    # remat + scan compose
    m_rs = gpt_model_provider(_gpt_cfg(scan_layers=True, remat=True))
    loss2 = jax.jit(lambda p: m_rs.apply(p, tokens, labels))(p)
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)


def test_scan_layers_dropout_trains():
    """Train-mode dropout through the nn.scan layer stack (per-layer rng
    splitting is flax's split_rngs contract): rng-reproducible,
    key-sensitive, finite grads.  This exact composition is what exposed
    the custom_vjp traced-seed closure leak (UnexpectedTracerError under
    scan + grad) that moved mask/seed into custom_vjp arguments."""
    from apex_tpu.transformer import parallel_state as ps
    ps.initialize_model_parallel(1)
    tokens, labels = _data()
    m = gpt_model_provider(_gpt_cfg(scan_layers=True, hidden_dropout=0.2,
                                    attention_dropout=0.2))
    p = m.init({"params": jax.random.PRNGKey(9),
                "dropout": jax.random.PRNGKey(10)}, tokens, labels)

    def loss_with(key):
        return jax.jit(lambda p: m.apply(
            p, tokens, labels, deterministic=False,
            rngs={"dropout": key}))(p)

    a = float(loss_with(jax.random.PRNGKey(3)))
    b = float(loss_with(jax.random.PRNGKey(3)))
    c = float(loss_with(jax.random.PRNGKey(4)))
    assert np.isfinite(a) and a == b and a != c
    g = jax.jit(jax.grad(lambda p: m.apply(
        p, tokens, labels, deterministic=False,
        rngs={"dropout": jax.random.PRNGKey(3)})))(p)
    assert all(bool(jnp.all(jnp.isfinite(x))) for x in jax.tree.leaves(g))


def test_context_parallel_matches_cp1():
    """CP=4 ring-attention GPT loss == CP=1 full-sequence loss with the
    same params (context parallelism is exact)."""
    import functools
    from jax.sharding import PartitionSpec as P

    seq = 64   # 16 tokens per CP rank
    cfg1 = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                     num_layers=LAYERS, num_attention_heads=HEADS,
                     max_seq_length=seq, hidden_dropout=0.0,
                     attention_dropout=0.0)
    cfg_cp = GPTConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                       num_layers=LAYERS, num_attention_heads=HEADS,
                       max_seq_length=seq, hidden_dropout=0.0,
                       attention_dropout=0.0, context_parallel=True)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (BATCH, seq),
                                0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)

    # CP=1 oracle
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    m1 = gpt_model_provider(cfg1)
    params = m1.init(jax.random.PRNGKey(7), tokens, labels)
    loss1 = float(jax.jit(lambda p: m1.apply(p, tokens, labels))(params))
    parallel_state.destroy_model_parallel()

    # CP=4: tokens/labels sharded on the seq dim over the context axis
    parallel_state.initialize_model_parallel(context_parallel_size_=4)
    mesh = parallel_state.get_mesh()
    m_cp = gpt_model_provider(cfg_cp)

    def body(tokens, labels):
        # per-rank mean over the local shard; equal shard sizes -> global
        # mean is the pmean
        loss = m_cp.apply(params, tokens, labels)
        return jax.lax.pmean(loss, "context")

    loss_cp = float(jax.jit(functools.partial(
        jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(None, "context"), P(None, "context")),
        out_specs=P()))(tokens, labels))
    parallel_state.destroy_model_parallel()
    np.testing.assert_allclose(loss_cp, loss1, rtol=2e-5, atol=2e-6)
