"""Tied input/output embedding across pipeline stages (reference:
``allreduce_word_embedding_grads`` over the first+last-stage embedding
group).  The pipelined run with the masked-psum embedding reduction must
match the non-pipelined tied-weights oracle exactly."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    embedding_grads_all_reduce,
    forward_backward_pipelining_without_interleaving,
)

PP = 4
VOCAB, HID = 16, 8
MICRO_BS, N_MICRO, SEQ = 2, 4, 6


def _make(key):
    k1, k2, k3 = jax.random.split(key, 3)
    embed = jax.random.normal(k1, (VOCAB, HID)) * 0.5
    stage_w = jax.random.normal(k2, (PP, HID, HID)) / np.sqrt(HID)
    tokens = jax.random.randint(k3, (N_MICRO, MICRO_BS, SEQ), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=-1)
    return embed, stage_w, tokens, labels


def _stage_body(w, x):
    return x + jax.nn.gelu(x @ w)


def _oracle(embed, stage_w, tokens, labels):
    """Non-pipelined tied-embedding model: embed -> PP stages -> logits
    with embed.T (the tied head)."""
    def loss_fn(embed, stage_w):
        total = 0.0
        for m in range(N_MICRO):
            x = embed[tokens[m]]                      # [bs, seq, hid]
            for s in range(PP):
                x = _stage_body(stage_w[s], x)
            logits = x @ embed.T                      # tied head
            logp = jax.nn.log_softmax(logits, axis=-1)
            total = total + -jnp.mean(
                jnp.take_along_axis(logp, labels[m][..., None],
                                    axis=-1))
        return total / N_MICRO
    return jax.value_and_grad(loss_fn, argnums=(0, 1))(embed, stage_w)


@pytest.fixture
def setup():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP)
    yield
    parallel_state.destroy_model_parallel()


def test_tied_embedding_grads_match_oracle(setup):
    embed, stage_w, tokens, labels = _make(jax.random.PRNGKey(0))
    mesh = parallel_state.get_mesh()
    batch = {"tokens": tokens, "labels": labels}

    def stage_fn(params, x, mb):
        stage = jax.lax.axis_index("pipe")
        # stage 0 consumes the embedding lookup instead of the carried x
        emb = params["embed"][mb["tokens"]]
        x = jnp.where(stage == 0, emb, x)
        return _stage_body(params["w"], x)

    def loss_fn(y, mb, params):
        # tied head: logits through the SAME embedding matrix (3-arg loss
        # contract — closures over params would get zero grads)
        logits = y @ params["embed"].T
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, mb["labels"][..., None], axis=-1))

    # The tied embedding param must reach both stage 0 (lookup) and the
    # last stage (head).  Every rank carries a replica; the masked psum
    # reconciles the two stages' grad contributions.
    def body(embed_rep, stage_w, batch):
        params = {"embed": embed_rep[0], "w": stage_w[0]}
        loss, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, batch,
            num_microbatches=N_MICRO,
            input_fn=lambda mb: jnp.zeros(
                (MICRO_BS, SEQ, HID), jnp.float32))
        # reference: first+last stage allreduce of the embedding grad
        grads["embed"] = embedding_grads_all_reduce(grads["embed"])
        return loss, jax.tree.map(lambda g: g[None], grads)

    embed_rep = jnp.broadcast_to(embed, (PP,) + embed.shape)
    loss, grads = jax.jit(functools.partial(
        jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("pipe"), P("pipe"), P()),
        out_specs=(P(), P("pipe"))))(embed_rep, stage_w, batch)

    ref_loss, (ref_gembed, ref_gw) = _oracle(embed, stage_w, tokens, labels)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    np.testing.assert_allclose(grads["w"], ref_gw, rtol=1e-4, atol=1e-5)
    # after the embedding-group reduction, stage 0's (and the last stage's)
    # embedding grad equals the tied-weights total grad
    np.testing.assert_allclose(grads["embed"][0], ref_gembed,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(grads["embed"][PP - 1], ref_gembed,
                               rtol=1e-4, atol=1e-5)


def test_embedding_grads_all_reduce_masks_middle_stages(setup):
    """Only first+last stages contribute (reference embedding-group
    membership)."""
    mesh = parallel_state.get_mesh()
    per_stage = jnp.arange(PP, dtype=jnp.float32)[:, None] * \
        jnp.ones((1, 5))

    def body(g):
        return embedding_grads_all_reduce(g[0])[None]

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"),), out_specs=P("pipe")))(
        per_stage)
    # sum of stage 0 (=0) and stage PP-1 (=PP-1) only
    np.testing.assert_allclose(out, jnp.full((PP, 5), float(PP - 1)))
