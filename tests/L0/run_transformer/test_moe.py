"""MoE / expert-parallelism tests (beyond reference parity — SURVEY.md
§2.4 marks EP "No"; the rebuild makes it first-class).

Strategy mirrors the TP-layer tests: the sharded (ep>1, all_to_all)
layer must reproduce a dense (ep=1) computation with the reassembled
global expert weights, shard by shard.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.moe import MoELayer, reduce_moe_grads
from apex_tpu.transformer.moe.layer import compute_dispatch_and_combine
from apex_tpu.transformer.moe.router import (load_balancing_loss,
                                             router_z_loss)

E, H, F, K = 4, 8, 16, 2
EP = 4


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(expert_model_parallel_size_=EP)
    yield
    parallel_state.destroy_model_parallel()


def _dense_moe_reference(tokens, params, capacity):
    """Hand computation: gate -> capacity-drop -> per-expert FFN -> sum."""
    w = np.asarray(params["router"]["weight"], np.float32)
    logits = np.asarray(tokens, np.float32) @ w.T
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    idx = np.argsort(-probs, axis=-1)[:, :K]
    gates = np.take_along_axis(probs, idx, axis=-1)
    gates = gates / gates.sum(-1, keepdims=True)
    # GShard slot assignment: k-major priority
    count = np.zeros(E, np.int64)
    kept = np.zeros((tokens.shape[0], E))
    gate_se = np.zeros((tokens.shape[0], E))
    for k in range(K):
        for s in range(tokens.shape[0]):
            e = idx[s, k]
            if count[e] < capacity:
                kept[s, e] = 1.0
                gate_se[s, e] = gates[s, k]
            count[e] += 1
    w1 = np.asarray(params["experts"]["w1"], np.float32)
    w2 = np.asarray(params["experts"]["w2"], np.float32)
    ex = params["experts"]
    b1 = np.asarray(ex["b1"], np.float32) if "b1" in ex else \
        np.zeros((E, 1, w1.shape[-1]), np.float32)
    b2 = np.asarray(ex["b2"], np.float32) if "b2" in ex else \
        np.zeros((E, 1, w2.shape[-1]), np.float32)
    out = np.zeros_like(np.asarray(tokens, np.float32))
    for e in range(E):
        y = np.asarray(jax.nn.gelu(tokens @ w1[e] + b1[e][0]))
        y = y @ w2[e] + b2[e][0]
        out += gate_se[:, e:e + 1] * kept[:, e:e + 1] * y
    return out


def test_dispatch_combine_capacity_drop():
    """Three tokens all choosing expert 0 with capacity 2: the third is
    dropped; slots assigned in token order within a k-slot."""
    gates = jnp.array([[1.0], [1.0], [1.0]])
    idx = jnp.array([[0], [0], [0]])
    dispatch, combine = compute_dispatch_and_combine(gates, idx, E, 2)
    assert dispatch.shape == (3, E, 2)
    np.testing.assert_allclose(dispatch[0, 0], [1, 0])
    np.testing.assert_allclose(dispatch[1, 0], [0, 1])
    np.testing.assert_allclose(dispatch[2, 0], [0, 0])   # dropped
    np.testing.assert_allclose(np.asarray(combine), np.asarray(dispatch))


def test_dispatch_k_major_priority():
    """Top-1 choices win capacity slots over top-2 choices regardless of
    token order (GShard priority)."""
    # token0 picks expert 1 as its SECOND choice; token1 picks it FIRST.
    gates = jnp.array([[0.6, 0.4], [0.9, 0.1]])
    idx = jnp.array([[0, 1], [1, 2]])
    dispatch, _ = compute_dispatch_and_combine(gates, idx, E, 1)
    np.testing.assert_allclose(dispatch[1, 1], [1])      # top-1 kept
    np.testing.assert_allclose(dispatch[0, 1], [0])      # top-2 dropped


def test_moe_ep1_matches_dense_reference():
    tokens = jax.random.normal(jax.random.key(0), (16, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=16)
    params = layer.init(jax.random.key(1), tokens)
    y, aux = layer.apply(params, tokens)
    ref = _dense_moe_reference(tokens, params["params"], capacity=16)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)
    assert np.isfinite(float(aux["load_balancing_loss"]))
    assert np.isfinite(float(aux["z_loss"]))


@pytest.mark.parametrize("mode", ["onehot", "gather"])
def test_moe_ep4_matches_dense_per_shard(mode):
    """The all_to_all machinery: ep=4 sharded layer ≡ dense layer run on
    each shard's tokens with the reassembled global expert weights —
    for BOTH dispatch modes (the [E, C, h] buffer contract feeding the
    all_to_all is mode-independent)."""
    mesh = parallel_state.get_mesh()
    dp = mesh.shape["data"]
    t_local, cap = 8, 8
    tokens = jax.random.normal(jax.random.key(2), (dp * EP * t_local, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=cap, expert_parallel_size=EP,
                     dispatch_mode=mode)

    def body(x):
        params = layer.init(jax.random.key(3), x)
        y, _ = layer.apply(params, x)
        p = params["params"]
        return (y, p["router"]["weight"], p["experts"]["w1"],
                p["experts"]["b1"], p["experts"]["w2"], p["experts"]["b2"])

    y, wr, w1, b1, w2, b2 = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh,
            in_specs=(P(("data", "expert")),),
            out_specs=(P(("data", "expert")), P(), P("expert"), P("expert"),
                       P("expert"), P("expert"))))(tokens)
    global_params = {"router": {"weight": wr},
                     "experts": {"w1": w1, "b1": b1, "w2": w2, "b2": b2}}
    assert w1.shape == (E, H, F)
    # per-expert-rank shards drew INDEPENDENT weights (folded init key)
    e_local = E // EP
    assert not np.allclose(np.asarray(w1[0]), np.asarray(w1[e_local]))
    toks = np.asarray(tokens).reshape(dp * EP, t_local, H)
    ys = np.asarray(y).reshape(dp * EP, t_local, H)
    for shard in range(dp * EP):
        ref = _dense_moe_reference(toks[shard], global_params, capacity=cap)
        np.testing.assert_allclose(ys[shard], ref, rtol=2e-4, atol=2e-4)


def test_routing_statistics():
    """aux carries per-expert load and the dropped-token fraction."""
    n_tok, cap = 16, 32
    tokens = jax.random.normal(jax.random.key(60), (n_tok, H))
    ample = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=cap)
    params = ample.init(jax.random.key(61), tokens)
    _, aux = ample.apply(params, tokens)
    assert aux["expert_load"].shape == (E,)
    np.testing.assert_allclose(float(aux["dropped_fraction"]), 0.0,
                               atol=1e-6)              # capacity ample
    np.testing.assert_allclose(
        float(aux["expert_load"].sum()) * cap, n_tok * K, rtol=1e-6)
    tight = ample.clone(capacity=1)
    _, aux = tight.apply(params, tokens)
    # n_tok x top-K choices into E single slots: the rest are dropped
    np.testing.assert_allclose(float(aux["dropped_fraction"]),
                               1.0 - E / (n_tok * K), rtol=1e-6)
    assert float(aux["expert_load"].max()) <= 1.0


def test_moe_grads_flow():
    tokens = jax.random.normal(jax.random.key(4), (16, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=16)
    params = layer.init(jax.random.key(5), tokens)

    def loss_fn(p):
        y, aux = layer.apply(p, tokens)
        return jnp.sum(y * y) + 0.01 * aux["load_balancing_loss"] \
            + 0.001 * aux["z_loss"]

    grads = jax.grad(loss_fn)(params)["params"]
    for path in (("router", "weight"), ("experts", "w1"),
                 ("experts", "w2")):
        g = grads[path[0]][path[1]]
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).max()) > 0.0


def test_expert_init_per_expert_variance():
    """The stacked [E, h, f] init must give each expert a full 2-D xavier
    draw — declaring the expert dim as batch_axis; folding it into
    fan_in would shrink every expert's std by ~sqrt(E)."""
    from apex_tpu.transformer.moe.experts import expert_init

    e, h, f = 8, 64, 128
    w = np.asarray(expert_init(jax.random.key(0), (e, h, f), jnp.float32))
    want = np.sqrt(2.0 / (h + f))          # xavier fan_avg std
    got = w.reshape(e, -1).std(axis=-1)
    assert np.all(got > 0.8 * want), (got, want)
    assert np.all(got < 1.2 * want), (got, want)


def test_moe_tp_ep_matches_dense_per_shard():
    """TP x EP: each expert's ffn dim shards over the tensor axis; the
    per-rank partial outputs psum to exactly the dense computation with
    the reassembled [E, h, f] weights."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, expert_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    dp, ep, tp = mesh.shape["data"], 2, 2
    t_local, cap = 8, 16
    tokens = jax.random.normal(jax.random.key(8), (dp * ep * t_local, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=cap, expert_parallel_size=ep,
                     tensor_parallel_size=tp)

    def body(x):
        params = layer.init(jax.random.key(9), x)
        y, _ = layer.apply(params, x)
        p = params["params"]
        return y, p["router"]["weight"], p["experts"]["w1"], \
            p["experts"]["w2"]

    y, wr, w1, w2 = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(("data", "expert")),),
        out_specs=(P(("data", "expert")), P(),
                   P("expert", None, "tensor"), P("expert", "tensor"))))(
                       tokens)
    assert w1.shape == (E, H, F) and w2.shape == (E, F, H)
    gp = {"router": {"weight": wr}, "experts": {"w1": w1, "w2": w2}}
    toks = np.asarray(tokens).reshape(dp * ep, t_local, H)
    ys = np.asarray(y).reshape(dp * ep, t_local, H)
    for shard in range(dp * ep):
        ref = _dense_moe_reference(toks[shard], gp, capacity=cap)
        np.testing.assert_allclose(ys[shard], ref, rtol=2e-4, atol=2e-4)


def test_moe_tp_ep_sp_matches_dense_per_shard():
    """TP x EP x SP: input arrives sequence-sharded [s/tp, b, h]; the
    layer gathers, routes the full token set identically on every TP
    rank, and reduce-scatters the psum'd output back to seq shards."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        tensor_model_parallel_size_=2, expert_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    dp, ep, tp = mesh.shape["data"], 2, 2
    s, b, cap = 16, 2, 32
    x = jax.random.normal(jax.random.key(10), (s, dp * ep * b, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=cap, expert_parallel_size=ep,
                     tensor_parallel_size=tp, sequence_parallel=True)

    def body(x):
        params = layer.init(jax.random.key(11), x)
        y, _ = layer.apply(params, x)
        p = params["params"]
        return y, p["router"]["weight"], p["experts"]["w1"], \
            p["experts"]["w2"]

    y, wr, w1, w2 = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("tensor", ("data", "expert")),),
        out_specs=(P("tensor", ("data", "expert")), P(),
                   P("expert", None, "tensor"), P("expert", "tensor"))))(x)
    assert y.shape == x.shape
    gp = {"router": {"weight": wr}, "experts": {"w1": w1, "w2": w2}}
    xs = np.asarray(x).reshape(s, dp * ep, b, H)
    ys = np.asarray(y).reshape(s, dp * ep, b, H)
    for shard in range(dp * ep):
        toks = xs[:, shard].reshape(s * b, H)
        ref = _dense_moe_reference(toks, gp, capacity=cap)
        np.testing.assert_allclose(ys[:, shard].reshape(s * b, H), ref,
                                   rtol=2e-4, atol=2e-4)


def _dense_moe_jnp(wr, w1, w2, tokens, capacity):
    """Differentiable dense (unsharded, bias-free) MoE forward in jnp —
    the grad oracle for the TP-sharded layer."""
    logits = jnp.matmul(tokens.astype(jnp.float32), wr.T)
    probs = jax.nn.softmax(logits, axis=-1)
    gates, idx = jax.lax.top_k(probs, K)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    dispatch, combine = compute_dispatch_and_combine(gates, idx, E, capacity)
    dt = tokens.dtype
    buf = jnp.einsum("sec,sh->ech", dispatch.astype(dt), tokens)
    hidden = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", buf, w1.astype(dt)))
    out = jnp.einsum("ecf,efh->ech", hidden, w2.astype(dt))
    return jnp.einsum("sec,ech->sh", combine.astype(dt), out)


@pytest.mark.parametrize("sp", [False, True])
def test_moe_tp_grads_match_dense(sp):
    """Gradients under TP (+/- SP) must equal the dense oracle's: router
    grad replica-consistent across TP ranks and equal to the dense
    grad; w1/w2 shard grads equal the dense grads' slices; input grad
    equal to the dense input grad (regression: rank-partial router/
    input cotangents desyncing replicas)."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    tp, s_tok, cap = 2, 16, 32
    tokens = jax.random.normal(jax.random.key(12), (s_tok, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=cap, tensor_parallel_size=tp,
                     sequence_parallel=sp)

    def body(x_shard):
        params = layer.init(jax.random.key(13), x_shard)

        def loss_fn(p, x):
            # LOCAL loss only — no psum: under SP each rank's shard
            # cotangent reaches the full output through the scatter's
            # gather-backward, so grads already equal the dense oracle's
            # (a psum here would re-seed the cotangent on every rank and
            # inflate grads by tp)
            y, _ = layer.apply(p, x)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        (gp, gx) = jax.grad(loss_fn, argnums=(0, 1))(params, x_shard)
        gp = gp["params"]
        p = params["params"]
        if sp:  # full input grad for comparison: stack seq shards
            return (gp["router"]["weight"][None], gp["experts"]["w1"],
                    gp["experts"]["w2"], gx,
                    p["router"]["weight"], p["experts"]["w1"],
                    p["experts"]["w2"])
        return (gp["router"]["weight"][None], gp["experts"]["w1"],
                gp["experts"]["w2"], gx[None],
                p["router"]["weight"], p["experts"]["w1"],
                p["experts"]["w2"])

    in_spec = P("tensor") if sp else P()
    gx_spec = P("tensor") if sp else P("tensor", None)
    g_wr, g_w1, g_w2, g_x, wr, w1, w2 = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh,
            in_specs=(in_spec,),
            out_specs=(P("tensor"), P(None, None, "tensor"),
                       P(None, "tensor"), gx_spec, P(),
                       P(None, None, "tensor"), P(None, "tensor"))))(tokens)
    if not sp:
        # router + input grads identical on both TP ranks
        np.testing.assert_allclose(np.asarray(g_wr[0]), np.asarray(g_wr[1]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(g_x[0]), np.asarray(g_x[1]),
                                   rtol=1e-5, atol=1e-6)
        g_wr, g_x = g_wr[0], g_x[0]
    else:
        g_wr = g_wr.reshape(tp, E, H)
        np.testing.assert_allclose(np.asarray(g_wr[0]), np.asarray(g_wr[1]),
                                   rtol=1e-5, atol=1e-6)
        g_wr = g_wr[0]

    def dense_loss(wr, w1, w2, x):
        y = _dense_moe_jnp(wr, w1, w2, x, cap)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    d_wr, d_w1, d_w2, d_x = jax.grad(dense_loss, argnums=(0, 1, 2, 3))(
        wr, w1, w2, tokens)
    np.testing.assert_allclose(np.asarray(g_wr), np.asarray(d_wr),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(g_w1), np.asarray(d_w1),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(g_w2), np.asarray(d_w2),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(g_x), np.asarray(d_x),
                               rtol=2e-4, atol=2e-4)


def test_ddp_axis_resolves_after_init():
    """DDP built BEFORE initialize_model_parallel must still pick up the
    expert axis once the EP mesh exists (regression: construction-time
    resolution froze 'data'); and the context axis joins whenever
    context parallelism is active (dense grads are partial per cp rank)."""
    from apex_tpu.parallel.distributed import DistributedDataParallel

    parallel_state.destroy_model_parallel()
    ddp = DistributedDataParallel()
    assert ddp.axis_name == "data"
    parallel_state.initialize_model_parallel(expert_model_parallel_size_=EP)
    assert set(ddp.axis_name) == {"data", "expert"}
    assert DistributedDataParallel(axis_name="data").axis_name == "data"
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=2)
    assert set(ddp.axis_name) == {"data", "context"}
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        expert_model_parallel_size_=2, context_parallel_size_=2)
    assert set(ddp.axis_name) == {"data", "expert", "context"}


def test_reduce_moe_grads_spans_context_axis():
    """Under context parallelism each cp rank routes a different
    sequence shard through replicated MoE weights, so BOTH router and
    expert grads must average over the context axis too."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(context_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    dp, cp = mesh.shape["data"], 2
    tokens = jax.random.normal(jax.random.key(40), (dp * cp * 8, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=8)

    def body(x):
        params = layer.init(jax.random.key(41), x)

        def loss_fn(p):
            y, _ = layer.apply(p, x)
            return jax.lax.pmean(jnp.sum(y * y), ("data", "context"))

        raw = jax.grad(loss_fn)(params)["params"]
        red = reduce_moe_grads(raw)     # defaults resolve the cp axis
        return (raw["router"]["weight"][None],
                red["router"]["weight"][None],
                red["experts"]["w1"][None])

    raw_g, red_g, red_w1 = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh,
            in_specs=(P(("data", "context")),),
            out_specs=(P(("data", "context")), P(("data", "context")),
                       P(("data", "context")))))(tokens)
    raw_g, red_g = np.asarray(raw_g), np.asarray(red_g)
    assert not np.allclose(raw_g[0], raw_g[1])    # partial per cp rank
    for r in range(1, dp * cp):
        np.testing.assert_allclose(red_g[0], red_g[r], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(red_w1)[0],
                                   np.asarray(red_w1)[r], rtol=1e-6)
    np.testing.assert_allclose(red_g[0], raw_g.mean(axis=0), rtol=1e-5,
                               atol=1e-6)


def test_reduce_moe_grads_syncs_router_replicas():
    """The router is replicated over the expert axis but sees different
    local tokens, so its raw grads diverge per rank; reduce_moe_grads
    must bring every expert rank to the same (averaged) router grad while
    leaving expert grads rank-local."""
    mesh = parallel_state.get_mesh()
    dp = mesh.shape["data"]
    tokens = jax.random.normal(jax.random.key(6), (dp * EP * 8, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=8, expert_parallel_size=EP)

    def body(x):
        params = layer.init(jax.random.key(7), x)

        def loss_fn(p):
            y, _ = layer.apply(p, x)
            return jax.lax.pmean(jnp.sum(y * y), ("data", "expert"))

        raw = jax.grad(loss_fn)(params)["params"]
        red = reduce_moe_grads(raw)
        # leading [1] so out_specs can stack the per-rank values
        return (raw["router"]["weight"][None],
                red["router"]["weight"][None])

    raw_g, red_g = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(("data", "expert")),),
        out_specs=(P(("data", "expert")), P(("data", "expert")))))(tokens)
    raw_g, red_g = np.asarray(raw_g), np.asarray(red_g)
    assert raw_g.shape[0] == dp * EP
    # raw router grads differ between ranks (different local tokens)...
    assert not np.allclose(raw_g[0], raw_g[1])
    # ...reduced ones are identical everywhere and equal the raw mean
    # over BOTH replica axes (data and expert)
    for r in range(1, dp * EP):
        np.testing.assert_allclose(red_g[0], red_g[r], rtol=1e-6)
    np.testing.assert_allclose(red_g[0], raw_g.mean(axis=0), rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize("tight", [False, True])
def test_gather_dispatch_matches_onehot(tight):
    """dispatch_mode='gather' (index form) must reproduce the dense
    one-hot einsum path EXACTLY — same routing, same capacity drops
    (``tight`` forces drops), same output, same grads for tokens,
    router, and experts."""
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cap = 2 if tight else 16
    tokens = jax.random.normal(jax.random.key(80), (24, H))
    kw = dict(num_experts=E, hidden_size=H, ffn_hidden_size=F, top_k=K,
              capacity=cap)
    dense = MoELayer(dispatch_mode="onehot", **kw)
    gather = MoELayer(dispatch_mode="gather", **kw)
    params = dense.init(jax.random.key(81), tokens)   # same param tree

    def loss_fn(layer):
        def f(p, x):
            y, aux = layer.apply(p, x)
            return (jnp.sum(y * y) + 0.01 * aux["load_balancing_loss"],
                    (y, aux))
        return f

    (ld, (yd, auxd)), gd = jax.jit(jax.value_and_grad(
        loss_fn(dense), argnums=(0, 1), has_aux=True))(params, tokens)
    (lg, (yg, auxg)), gg = jax.jit(jax.value_and_grad(
        loss_fn(gather), argnums=(0, 1), has_aux=True))(params, tokens)
    np.testing.assert_allclose(np.asarray(yg), np.asarray(yd),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(lg), float(ld), rtol=1e-6)
    np.testing.assert_allclose(float(auxg["dropped_fraction"]),
                               float(auxd["dropped_fraction"]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(auxg["expert_load"]),
                               np.asarray(auxd["expert_load"]), atol=1e-6)
    if tight:
        assert float(auxd["dropped_fraction"]) > 0.0   # drops exercised
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), gg, gd)


def test_reduce_moe_grads_expert_scale_matches_dense():
    """Expert grads must be gradients of the SAME replica-averaged loss
    as dense grads.  The loss averages over data x expert token shards,
    but an expert weight has replicas only along data — a bare pmean
    over its replica axes normalizes by the smaller count and returns
    ep x the true gradient (expert params would silently train at
    lr*ep).  reduce_moe_grads therefore scales expert leaves by 1/ep:
    red == pmean_data(raw) / ep == psum_data(raw) / (dp*ep).  The dense
    ep=1 replay in ``__graft_entry__.dryrun_multichip`` pins the same
    fact end to end."""
    mesh = parallel_state.get_mesh()
    dp = mesh.shape["data"]
    tokens = jax.random.normal(jax.random.key(70), (dp * EP * 8, H))
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=K, capacity=8, expert_parallel_size=EP)

    def body(x):
        params = layer.init(jax.random.key(71), x)

        def loss_fn(p):
            y, _ = layer.apply(p, x)
            return jax.lax.pmean(jnp.sum(y * y), ("data", "expert"))

        raw = jax.grad(loss_fn)(params)["params"]
        red = reduce_moe_grads(raw)
        return (raw["experts"]["w1"][None], red["experts"]["w1"][None])

    raw_g, red_g = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(("data", "expert")),),
        out_specs=(P(("data", "expert")), P(("data", "expert")))))(tokens)
    raw_g, red_g = np.asarray(raw_g), np.asarray(red_g)
    # rank stacking order under P(("data","expert")) is data-major
    for e in range(EP):
        want = raw_g[[d * EP + e for d in range(dp)]].mean(axis=0) / EP
        for d in range(dp):
            np.testing.assert_allclose(red_g[d * EP + e], want,
                                       rtol=1e-5, atol=1e-7)


def test_gpt_moe_scan_layers_keeps_aux_losses():
    """nn.scan must carry the sown aux losses (regression: missing
    'intermediates' in variable_axes silently dropped them)."""
    from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_attention_heads=2, max_seq_length=8,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    num_moe_experts=4, moe_top_k=2, scan_layers=True)
    model = gpt_model_provider(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    labels = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens, labels)
    loss, inter = model.apply(params, tokens, labels,
                              mutable=["intermediates"])
    flat = jax.tree.leaves(inter["intermediates"])
    assert flat, "scan dropped the sown MoE aux losses"
    # each sown leaf is stacked over the scanned layer axis
    assert all(v.shape[-1] == cfg.num_layers or v.shape[0] == cfg.num_layers
               for v in flat)
    assert np.isfinite(float(loss.mean()))


def test_gpt_with_moe_ffn():
    """GPTConfig(num_moe_experts=...) swaps the dense FFN for the routed
    MoE and sows the aux losses into "intermediates"."""
    from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_attention_heads=2, max_seq_length=8,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    num_moe_experts=4, moe_top_k=2)
    model = gpt_model_provider(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    labels = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), tokens, labels)
    loss, inter = model.apply(params, tokens, labels,
                              mutable=["intermediates"])
    assert np.isfinite(float(loss.mean()))
    flat = jax.tree.leaves(inter["intermediates"])
    assert len(flat) >= 2 * cfg.num_layers   # lb + z loss per layer
    assert all(np.isfinite(float(v)) for v in flat)
    # expert weights exist at the MoE path
    p0 = params["params"]["layer_0"]["mlp"]["experts"]["w1"]
    assert p0.shape == (4, 16, cfg.ffn)


def test_sinkhorn_balances_skewed_routing():
    """Skewed logits drive plain top-1 routing into one expert; routing
    through the sinkhorn-normalized matrix spreads tokens near-evenly
    (the S-BASE/Megatron sinkhorn router's whole point)."""
    from apex_tpu.transformer.moe.router import sinkhorn

    tokens, e = 256, E
    key = jax.random.key(20)
    # every token prefers expert 0 by a wide margin
    logits = jax.random.normal(key, (tokens, e)) * 0.1
    logits = logits.at[:, 0].add(5.0)
    naive_idx = jnp.argmax(logits, axis=-1)
    assert int((naive_idx == 0).sum()) == tokens          # fully collapsed
    balanced = sinkhorn(jnp.exp(logits))
    sk_idx = jnp.argmax(balanced, axis=-1)
    counts = np.bincount(np.asarray(sk_idx), minlength=e)
    assert counts.max() <= 2 * tokens // e, counts        # near-uniform


def test_moe_sinkhorn_router_end_to_end():
    tokens = jax.random.normal(jax.random.key(21), (32, H))
    with pytest.raises(ValueError, match="top_k=1"):
        MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                 top_k=2, capacity=32,
                 load_balancing_type="sinkhorn").init(
                     jax.random.key(22), tokens)
    layer = MoELayer(num_experts=E, hidden_size=H, ffn_hidden_size=F,
                     top_k=1, capacity=32,
                     load_balancing_type="sinkhorn")
    params = layer.init(jax.random.key(22), tokens)
    y, aux = layer.apply(params, tokens)
    assert np.isfinite(np.asarray(y)).all()
    # sinkhorn selection is balanced by construction: no aux loss
    assert float(aux["load_balancing_loss"]) == 0.0

    def loss_fn(p):
        out, _ = layer.apply(p, tokens)
        return jnp.sum(out * out)

    grads = jax.grad(loss_fn)(params)["params"]
    g = grads["router"]["weight"]
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).max()) > 0.0   # gates flow through softmax


def test_sinkhorn_router_survives_huge_logits():
    """Raw exp(logits) overflows fp32 past ~88; the row-max-subtracted
    sinkhorn input must keep routing finite for drifted routers."""
    from apex_tpu.transformer.moe.router import TopKRouter

    x = jax.random.normal(jax.random.key(23), (16, H)) * 1500.0
    router = TopKRouter(num_experts=E, top_k=1,
                        load_balancing_type="sinkhorn")
    params = router.init(jax.random.key(24), x)
    gates, idx, aux = router.apply(params, x)
    logits_scale = float(jnp.abs(
        jnp.matmul(x, params["params"]["weight"].T)).max())
    assert logits_scale > 100.0          # the overflow regime is real
    assert np.isfinite(np.asarray(gates)).all()
    assert (np.asarray(idx) >= 0).all() and (np.asarray(idx) < E).all()


def test_gpt_moe_tp_sp_trains_in_shard_map():
    """Flagship MoE config end to end: GPT with MoE FFNs under tp=2 +
    sequence parallelism inside shard_map — fwd loss finite, grads
    finite, aux losses surfaced through intermediates."""
    from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                    num_attention_heads=2, max_seq_length=8,
                    hidden_dropout=0.0, attention_dropout=0.0,
                    sequence_parallel=True,
                    num_moe_experts=4, moe_top_k=2)
    model = gpt_model_provider(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    labels = jnp.ones((2, 8), jnp.int32)

    def body(tokens, labels):
        params = model.init(jax.random.key(0), tokens, labels)

        def loss_fn(p):
            loss, inter = model.apply(p, tokens, labels,
                                      mutable=["intermediates"])
            lb = sum(jnp.sum(v) for v in
                     jax.tree.leaves(inter["intermediates"]))
            return loss.mean() + 0.01 * lb

        from jax.flatten_util import ravel_pytree
        loss, grads = jax.value_and_grad(loss_fn)(params)
        gflat, _ = ravel_pytree(grads)
        return loss, gflat

    loss, gflat = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(), P("tensor"))))(tokens, labels)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(gflat)).all()


def test_1f1b_with_expert_parallel_moe_stage():
    """PP x EP composition: the 1F1B executor (lax.scan + ppermute over
    the pipe axis) must tolerate a stage whose body performs its own
    all_to_all over the expert axis, and match the non-pipelined
    schedule's loss and grads exactly."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining,
        forward_backward_pipelining_without_interleaving,
    )

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2, expert_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()          # pipe=2, data=2, expert=2
    pp, hid, micro_bs, n_micro = 2, 8, 4, 4
    moe = MoELayer(num_experts=E, hidden_size=hid, ffn_hidden_size=16,
                   top_k=K, capacity=2 * micro_bs,
                   expert_parallel_size=2)
    batch = {
        "x": jax.random.normal(jax.random.key(30),
                               (n_micro, micro_bs, hid)),
        "target": jnp.full((n_micro, micro_bs, hid), 0.1),
    }

    def stage_fn(params, x, mb):
        y, _ = moe.apply(params, x)
        return y

    def loss_fn(y, mb):
        return jnp.mean((y - mb["target"]) ** 2)

    def input_fn(mb):
        return mb["x"]

    def body(batch):
        pipe_r = jax.lax.axis_index("pipe")
        params = moe.init(
            jax.random.fold_in(jax.random.key(31), pipe_r),
            jnp.zeros((micro_bs, hid)))
        l_pipe, g_pipe = forward_backward_pipelining_without_interleaving(
            stage_fn, loss_fn, params, batch,
            num_microbatches=n_micro, input_fn=input_fn)
        # reference: the same stages run sequentially (no pipelining);
        # every pipe rank gets the full stack via all_gather
        allp = jax.lax.all_gather(params, "pipe")

        def full_model_fn(p_all, x, mb):
            for s in range(pp):
                x = stage_fn(jax.tree.map(lambda a, s=s: a[s], p_all),
                             x, mb)
            return x

        l_ref, g_ref = forward_backward_no_pipelining(
            full_model_fn, loss_fn, allp, batch,
            num_microbatches=n_micro, input_fn=input_fn)
        g_ref_mine = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, pipe_r, 0, keepdims=False), g_ref)
        return (l_pipe, l_ref,
                jax.tree.map(lambda g: g[None], g_pipe),
                jax.tree.map(lambda g: g[None], g_ref_mine))

    l_pipe, l_ref, g_pipe, g_ref = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P(),),
            out_specs=(P(), P(), P(("pipe", "expert")),
                       P(("pipe", "expert")))))(batch)
    np.testing.assert_allclose(float(l_pipe), float(l_ref), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_pipe, g_ref)


def test_interleaved_with_expert_parallel_moe_stage():
    """vpp x PP x EP: the interleaved executor's (chunk, microbatch)
    schedule and ring hand-offs must also tolerate all_to_all inside
    every virtual stage, matching the non-pipelined run."""
    from apex_tpu.transformer.pipeline_parallel import (
        forward_backward_no_pipelining,
        forward_backward_pipelining_with_interleaving,
    )

    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=2, expert_model_parallel_size_=2)
    mesh = parallel_state.get_mesh()
    v, pp, hid, micro_bs, n_micro = 2, 2, 8, 4, 4
    moe = MoELayer(num_experts=E, hidden_size=hid, ffn_hidden_size=16,
                   top_k=K, capacity=2 * micro_bs,
                   expert_parallel_size=2)
    batch = {
        "x": jax.random.normal(jax.random.key(50),
                               (n_micro, micro_bs, hid)),
        "target": jnp.full((n_micro, micro_bs, hid), 0.1),
    }

    def stage_fn(params, x, mb):
        y, _ = moe.apply(params, x)
        return y

    def loss_fn(y, mb):
        return jnp.mean((y - mb["target"]) ** 2)

    def input_fn(mb):
        return mb["x"]

    def body(batch):
        pipe_r = jax.lax.axis_index("pipe")
        x0 = jnp.zeros((micro_bs, hid), dtype=jnp.float32)
        # chunk c on rank r is virtual stage c*pp + r; fold the stage id
        # into the init key so every virtual stage draws distinct params
        chunks = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[moe.init(jax.random.fold_in(jax.random.key(51),
                                          c * pp + pipe_r), x0)
              for c in range(v)])
        l_v, g_v = forward_backward_pipelining_with_interleaving(
            stage_fn, loss_fn, chunks, batch,
            num_microbatches=n_micro, input_fn=input_fn,
            virtual_pipeline_model_parallel_size=v)
        allc = jax.lax.all_gather(chunks, "pipe")   # [pp, v, ...]

        def full_model_fn(p_all, x, mb):
            for s in range(v * pp):
                c, r = s // pp, s % pp
                x = stage_fn(jax.tree.map(
                    lambda a, c=c, r=r: a[r, c], p_all), x, mb)
            return x

        l_ref, g_ref = forward_backward_no_pipelining(
            full_model_fn, loss_fn, allc, batch,
            num_microbatches=n_micro, input_fn=input_fn)
        g_ref_mine = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(
                a, pipe_r, 0, keepdims=False), g_ref)
        return (l_v, l_ref,
                jax.tree.map(lambda g: g[None], g_v),
                jax.tree.map(lambda g: g[None], g_ref_mine))

    l_v, l_ref, g_v, g_ref = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P(),),
            out_specs=(P(), P(), P(("pipe", "expert")),
                       P(("pipe", "expert")))))(batch)
    np.testing.assert_allclose(float(l_v), float(l_ref), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_v, g_ref)


def test_aux_losses_uniform_routing():
    """Uniform router probabilities minimize the Switch loss at exactly 1."""
    probs = jnp.full((32, E), 1.0 / E)
    chosen = jnp.zeros((32, E)).at[:, :K].set(1.0)
    assert abs(float(load_balancing_loss(probs, chosen)) - 1.0) < 1e-5
    assert float(router_z_loss(jnp.zeros((32, E)))) >= 0.0


def test_dispatch_mode_auto_policy():
    """``dispatch_mode="auto"`` resolves from the shape: one-hot below
    the pinned Switch-scale threshold, gather at/above it; explicit
    modes pass through untouched.

    The threshold is pinned LITERALLY (not symbolically): 64 is the
    r5-measured on-hot inflection (one-hot step time 3567 us at E=32 ->
    7155 us at E=64 on E-independent expert GEMM work; PERF.md "MoE
    auto-dispatch policy").  Moving it is a policy change that must
    come with new capture data, so this test fails on a silent edit."""
    from apex_tpu.transformer.moe import resolve_dispatch_mode
    from apex_tpu.transformer.moe.layer import _AUTO_GATHER_MIN_E

    assert _AUTO_GATHER_MIN_E == 64        # provenance: r5 one-hot sweep
    assert resolve_dispatch_mode("auto", 8, 256, 64, 64) == "onehot"
    assert resolve_dispatch_mode("auto", 32, 8192, 640, 1024) == "onehot"
    assert resolve_dispatch_mode("auto", 63, 256, 64, 64) == "onehot"
    assert resolve_dispatch_mode("auto", 64, 8192, 320, 1024) == "gather"
    assert resolve_dispatch_mode("auto", 256, 256, 64, 64) == "gather"
    # explicit modes are never second-guessed by the policy
    assert resolve_dispatch_mode("onehot", 512, 256, 64, 64) == "onehot"
    assert resolve_dispatch_mode("gather", 2, 256, 64, 64) == "gather"


def test_dispatch_mode_auto_matches_explicit():
    """An auto layer's forward equals the explicitly-selected mode's,
    on both sides of the threshold (same routing, same drops)."""
    from apex_tpu.transformer.moe.layer import _AUTO_GATHER_MIN_E

    x = jax.random.normal(jax.random.PRNGKey(0), (32, 16))
    for e, expect in ((4, "onehot"), (_AUTO_GATHER_MIN_E, "gather")):
        kw = dict(num_experts=e, hidden_size=16, ffn_hidden_size=32,
                  top_k=2)
        auto = MoELayer(dispatch_mode="auto", **kw)
        explicit = MoELayer(dispatch_mode=expect, **kw)
        p = auto.init(jax.random.PRNGKey(1), x)
        y_auto, _ = auto.apply(p, x)
        y_exp, _ = explicit.apply(p, x)
        np.testing.assert_array_equal(np.asarray(y_auto),
                                      np.asarray(y_exp))


def test_dispatch_mode_invalid_rejected():
    x = jnp.zeros((8, 16))
    layer = MoELayer(num_experts=4, hidden_size=16, ffn_hidden_size=32,
                     dispatch_mode="bogus")
    with pytest.raises(ValueError, match="dispatch_mode"):
        layer.init(jax.random.PRNGKey(0), x)
