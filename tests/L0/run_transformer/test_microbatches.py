"""Microbatch calculator tests (reference:
tests/L0/run_transformer/test_microbatches.py)."""
import pytest

from apex_tpu.transformer.microbatches import (
    ConstantNumMicroBatches,
    RampupBatchsizeNumMicroBatches,
    build_num_microbatches_calculator,
)


def test_constant():
    calc = ConstantNumMicroBatches(
        global_batch_size=32, micro_batch_size=2, data_parallel_size=2)
    assert calc.get() == 8
    assert calc.get_current_global_batch_size() == 32
    calc.update(1000, True)  # no-op
    assert calc.get() == 8


def test_constant_indivisible_raises():
    with pytest.raises(AssertionError):
        ConstantNumMicroBatches(30, 4, 2)


def test_rampup():
    calc = RampupBatchsizeNumMicroBatches(
        start_batch_size=4, batch_size_increment=4, ramup_samples=100,
        global_batch_size=16, micro_batch_size=2, data_parallel_size=1)
    assert calc.get_current_global_batch_size() == 4
    assert calc.get() == 2
    # 3 increments over 100 samples -> 33.3 samples per increment
    calc.update(50, True)
    assert calc.get_current_global_batch_size() == 8
    calc.update(101, True)
    assert calc.get_current_global_batch_size() == 16
    assert calc.get() == 8


def test_builder_dispatch():
    c = build_num_microbatches_calculator(0, None, 16, 2, 1)
    assert isinstance(c, ConstantNumMicroBatches)
    r = build_num_microbatches_calculator(0, [4, 4, 100], 16, 2, 1)
    assert isinstance(r, RampupBatchsizeNumMicroBatches)
