"""Vocab-parallel CE vs local oracle (reference:
tests/L0/run_transformer/test_cross_entropy.py)."""
import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    _local_cross_entropy,
)

TP = 4
VOCAB = 32
BATCH, SEQ = 2, 6


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("label_smoothing", [0.0, 0.1])
def test_matches_local_oracle(label_smoothing):
    key = jax.random.key(0)
    logits = jax.random.normal(key, (BATCH, SEQ, VOCAB), jnp.float32)
    target = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, VOCAB)
    mesh = parallel_state.get_mesh()

    def body(logits, target):
        return vocab_parallel_cross_entropy(
            logits, target, label_smoothing=label_smoothing)

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(None, None, "tensor"), P()),
        out_specs=P()))(logits, target)
    expected = _local_cross_entropy(logits, target, label_smoothing)
    np.testing.assert_allclose(loss, expected, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("label_smoothing", [0.0, 0.1])
def test_gradient_matches_local_oracle(label_smoothing):
    logits = jax.random.normal(jax.random.key(2), (BATCH, SEQ, VOCAB))
    target = jax.random.randint(jax.random.key(3), (BATCH, SEQ), 0, VOCAB)
    mesh = parallel_state.get_mesh()

    def sharded_loss(logits, target):
        return jnp.sum(vocab_parallel_cross_entropy(
            logits, target, label_smoothing=label_smoothing))

    def body(logits, target):
        # psum the scalar so each shard's cotangent is seeded identically
        return jax.grad(lambda l: jax.lax.psum(
            sharded_loss(l, target), "tensor") / TP)(logits)

    g = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(None, None, "tensor"), P()),
        out_specs=P(None, None, "tensor")))(logits, target)
    g_ref = jax.grad(lambda l: jnp.sum(
        _local_cross_entropy(l, target, label_smoothing)))(logits)
    np.testing.assert_allclose(g, g_ref, rtol=2e-5, atol=2e-6)
