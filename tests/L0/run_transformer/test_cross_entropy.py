"""Vocab-parallel CE vs local oracle (reference:
tests/L0/run_transformer/test_cross_entropy.py)."""
import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.tensor_parallel import vocab_parallel_cross_entropy
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    _local_cross_entropy,
)

TP = 4
VOCAB = 32
BATCH, SEQ = 2, 6


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


@pytest.mark.parametrize("label_smoothing", [0.0, 0.1])
def test_matches_local_oracle(label_smoothing):
    key = jax.random.key(0)
    logits = jax.random.normal(key, (BATCH, SEQ, VOCAB), jnp.float32)
    target = jax.random.randint(jax.random.key(1), (BATCH, SEQ), 0, VOCAB)
    mesh = parallel_state.get_mesh()

    def body(logits, target):
        return vocab_parallel_cross_entropy(
            logits, target, label_smoothing=label_smoothing)

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(None, None, "tensor"), P()),
        out_specs=P()))(logits, target)
    expected = _local_cross_entropy(logits, target, label_smoothing)
    np.testing.assert_allclose(loss, expected, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("label_smoothing", [0.0, 0.1])
def test_gradient_matches_local_oracle(label_smoothing):
    logits = jax.random.normal(jax.random.key(2), (BATCH, SEQ, VOCAB))
    target = jax.random.randint(jax.random.key(3), (BATCH, SEQ), 0, VOCAB)
    mesh = parallel_state.get_mesh()

    def sharded_loss(logits, target):
        return jnp.sum(vocab_parallel_cross_entropy(
            logits, target, label_smoothing=label_smoothing))

    def body(logits, target):
        # psum the scalar so each shard's cotangent is seeded identically
        return jax.grad(lambda l: jax.lax.psum(
            sharded_loss(l, target), "tensor") / TP)(logits)

    g = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P(None, None, "tensor"), P()),
        out_specs=P(None, None, "tensor")))(logits, target)
    g_ref = jax.grad(lambda l: jnp.sum(
        _local_cross_entropy(l, target, label_smoothing)))(logits)
    np.testing.assert_allclose(g, g_ref, rtol=2e-5, atol=2e-6)


class TestHalfResiduals:
    """half_residuals=True stores the backward softmax in bf16 (the
    reference xentropy's half-precision bprop): loss must be identical,
    grads within bf16 quantization of the fp32 path — both the sharded
    and the tp==1 local path."""

    def _check(self, tp_body):
        loss32, g32 = tp_body(False)
        loss16, g16 = tp_body(True)
        np.testing.assert_allclose(np.asarray(loss16),
                                   np.asarray(loss32), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g16), np.asarray(g32),
                                   atol=4e-3, rtol=1e-2)
        assert float(np.abs(np.asarray(g16)).sum()) > 0

    def test_local_path(self):
        logits = jax.random.normal(jax.random.PRNGKey(0), (6, 64)) * 3
        target = jax.random.randint(jax.random.PRNGKey(1), (6,), 0, 64)

        def body(half):
            def f(lg):
                return vocab_parallel_cross_entropy(
                    lg, target, half_residuals=half).sum()
            return jax.value_and_grad(f)(logits)

        parallel_state.initialize_model_parallel(1)
        self._check(body)

    def test_local_path_label_smoothing(self):
        logits = jax.random.normal(jax.random.PRNGKey(2), (6, 64)) * 3
        target = jax.random.randint(jax.random.PRNGKey(3), (6,), 0, 64)

        def body(half):
            def f(lg):
                return vocab_parallel_cross_entropy(
                    lg, target, label_smoothing=0.1,
                    half_residuals=half).sum()
            return jax.value_and_grad(f)(logits)

        parallel_state.initialize_model_parallel(1)
        self._check(body)

    def test_sharded_path(self):
        parallel_state.initialize_model_parallel(4)
        mesh = parallel_state.get_mesh()
        vocab = 64
        logits = jax.random.normal(jax.random.PRNGKey(4), (6, vocab)) * 3
        target = jax.random.randint(jax.random.PRNGKey(5), (6,), 0, vocab)

        def body(half):
            def run(logits, target):
                def f(lg):
                    return vocab_parallel_cross_entropy(
                        lg, target, half_residuals=half).sum()
                return jax.value_and_grad(f)(logits)

            loss, g = jax.jit(functools.partial(
                jax.shard_map, check_vma=False)(
                run, mesh=mesh,
                in_specs=(P(None, "tensor"), P()),
                out_specs=(P(), P(None, "tensor"))))(logits, target)
            return loss, g

        self._check(body)
