"""Pipeline schedule equivalence (reference:
tests/L0/run_transformer/test_pipeline_parallel_fwd_bwd.py): every schedule
must produce the same loss and grads as the non-pipelined reference run.

Model: a stack of PP linear+gelu stages; stage params are stacked on a
leading dim sharded over the pipe axis.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    forward_backward_no_pipelining,
    forward_backward_pipelining_without_interleaving,
    forward_backward_pipelining_with_interleaving,
    get_forward_backward_func,
)

PP = 4
HID = 8
MICRO_BS = 2
N_MICRO = 6


def _make_params(key, n_stages):
    ks = jax.random.split(key, n_stages)
    return {
        "w": jnp.stack([
            jax.random.normal(k, (HID, HID)) / np.sqrt(HID) for k in ks]),
        "b": jnp.zeros((n_stages, HID)),
    }


def _stage_fn(params, x, mb):
    return jax.nn.gelu(x @ params["w"] + params["b"])


def _loss_fn(y, mb):
    return jnp.mean((y - mb["target"]) ** 2)


def _input_fn(mb):
    return mb["x"]


def _batch(key):
    return {
        "x": jax.random.normal(key, (N_MICRO, MICRO_BS, HID)),
        "target": jnp.ones((N_MICRO, MICRO_BS, HID)) * 0.1,
    }


def _reference(params, batch):
    """Sequential (non-pipelined) loss+grads over all stages/microbatches."""
    def loss(params):
        total = 0.0
        for m in range(N_MICRO):
            x = batch["x"][m]
            for s in range(PP):
                x = _stage_fn(
                    jax.tree.map(lambda p, s=s: p[s], params), x, None)
            total = total + _loss_fn(x, jax.tree.map(
                lambda v, m=m: v[m], batch))
        return total / N_MICRO
    return jax.value_and_grad(loss)(params)


@pytest.fixture
def setup():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=PP)
    yield
    parallel_state.destroy_model_parallel()


def test_no_pipelining_matches_reference(setup):
    # single joint "stage" covering the full model, no mesh required
    params = _make_params(jax.random.key(0), PP)
    batch = _batch(jax.random.key(1))

    def full_model_fn(params, x, mb):
        for s in range(PP):
            x = _stage_fn(jax.tree.map(lambda p, s=s: p[s], params), x, None)
        return x

    loss, grads = forward_backward_no_pipelining(
        full_model_fn, _loss_fn, params, batch,
        num_microbatches=N_MICRO, input_fn=_input_fn)
    ref_loss, ref_grads = _reference(params, batch)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_1f1b_matches_reference(setup):
    params = _make_params(jax.random.key(0), PP)
    batch = _batch(jax.random.key(1))
    mesh = parallel_state.get_mesh()

    def body(params, batch):
        local = jax.tree.map(lambda p: p[0], params)  # my stage's slice
        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, _loss_fn, local, batch,
            num_microbatches=N_MICRO, input_fn=_input_fn)
        return loss, jax.tree.map(lambda g: g[None], grads)

    loss, grads = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=(P(), P("pipe"))))(params, batch)
    ref_loss, ref_grads = _reference(params, batch)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_1f1b_with_per_microbatch_dropout_matches_reference(setup):
    """Dropout under pipelining: per-microbatch PRNG keys ride the batch
    pytree (``_microbatch`` slices every leaf, so each microbatch — and
    via a stage fold, each stage — draws its own mask).  The 1F1B run
    must still match the dense replay exactly, proving the executors
    route every (stage, microbatch) pair to the right dropout draw."""
    params = _make_params(jax.random.key(0), PP)
    batch = _batch(jax.random.key(1))
    # legacy raw uint32[2] keys so the leaf slices like any array
    batch["key"] = jax.vmap(jax.random.PRNGKey)(
        jnp.arange(N_MICRO, dtype=jnp.uint32))
    mesh = parallel_state.get_mesh()

    def drop_stage(params, x, mb, stage):
        y = jax.nn.gelu(x @ params["w"] + params["b"])
        keep = jax.random.bernoulli(
            jax.random.fold_in(mb["key"], stage), 0.8, y.shape)
        return jnp.where(keep, y / 0.8, 0.0)

    def body(params, batch):
        local = jax.tree.map(lambda p: p[0], params)
        stage_fn = lambda p, x, mb: drop_stage(  # noqa: E731
            p, x, mb, jax.lax.axis_index("pipe"))
        loss, grads = forward_backward_pipelining_without_interleaving(
            stage_fn, _loss_fn, local, batch,
            num_microbatches=N_MICRO, input_fn=_input_fn)
        return loss, jax.tree.map(lambda g: g[None], grads)

    loss, grads = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=(P(), P("pipe"))))(params, batch)

    def ref_loss_fn(params):
        total = 0.0
        for m in range(N_MICRO):
            mb = jax.tree.map(lambda v, m=m: v[m], batch)
            x = mb["x"]
            for s in range(PP):
                x = drop_stage(
                    jax.tree.map(lambda p, s=s: p[s], params), x, mb, s)
            total = total + _loss_fn(x, mb)
        return total / N_MICRO

    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


# interleaving requires num_microbatches % PP == 0 (reference constraint)
N_MICRO_I = 8


def _batch_i(key, n_micro=N_MICRO_I):
    return {
        "x": jax.random.normal(key, (n_micro, MICRO_BS, HID)),
        "target": jnp.ones((n_micro, MICRO_BS, HID)) * 0.1,
    }


def _run_interleaved(v, n_micro=N_MICRO_I, forward_only=False,
                     stage_fn=_stage_fn, extra_batch=None):
    """Run the interleaved executor over v*PP virtual linear stages and
    return (loss, grads-with-virtual-stage-leading-dim, params, batch)."""
    n_stages = v * PP
    params = _make_params(jax.random.key(2), n_stages)
    batch = _batch_i(jax.random.key(3), n_micro)
    if extra_batch:
        batch.update(extra_batch)
    mesh = parallel_state.get_mesh()

    # chunk c on rank r is virtual stage c*PP + r: reorder the stage stack
    # to [v, PP, ...] so shard_map slices the PP dim
    chunked = jax.tree.map(
        lambda p: p.reshape(v, PP, *p.shape[1:]).swapaxes(0, 1), params)

    def body(chunked_params, batch):
        local = jax.tree.map(lambda p: p[0], chunked_params)  # [v, ...]
        loss, grads = forward_backward_pipelining_with_interleaving(
            stage_fn, _loss_fn, local, batch,
            num_microbatches=n_micro, input_fn=_input_fn,
            forward_only=forward_only,
            virtual_pipeline_model_parallel_size=v)
        if forward_only:
            assert grads is None
            grads = jax.tree.map(lambda p: p * 0, local)
        return loss, jax.tree.map(lambda g: g[None], grads)

    loss, grads = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=(P(), P("pipe"))))(chunked, batch)
    # undo the chunk layout: grads come back [PP, v, ...] -> [v*PP, ...]
    grads = jax.tree.map(
        lambda g: g.swapaxes(0, 1).reshape(n_stages, *g.shape[2:]), grads)
    return loss, grads, params, batch


def _interleaved_reference(params, batch, n_stages, n_micro,
                           stage_fn=_stage_fn):
    def ref_loss_fn(params):
        total = 0.0
        for m in range(n_micro):
            x = batch["x"][m]
            mb = jax.tree.map(lambda v_, m=m: v_[m], batch)
            for s in range(n_stages):
                x = stage_fn(
                    jax.tree.map(lambda p, s=s: p[s], params), x, mb)
            total = total + _loss_fn(x, mb)
        return total / n_micro
    return jax.value_and_grad(ref_loss_fn)(params)


@pytest.mark.parametrize("v", [2, 3])
def test_interleaved_matches_reference(setup, v):
    """v virtual chunks x PP stages = v*PP linear stages total."""
    loss, grads, params, batch = _run_interleaved(v)
    ref_loss, ref_grads = _interleaved_reference(
        params, batch, v * PP, N_MICRO_I)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_interleaved_stage_fn_sees_correct_microbatch(setup):
    """Each virtual stage must receive the microbatch ITS activation
    belongs to (per-microbatch conditioning), across chunk hand-offs."""
    def cond_stage_fn(params, x, mb):
        return jax.nn.gelu(x @ params["w"] + params["b"]) + mb["cond"]

    cond = jax.random.normal(jax.random.key(6), (N_MICRO_I, MICRO_BS, HID))
    loss, grads, params, batch = _run_interleaved(
        2, stage_fn=cond_stage_fn, extra_batch={"cond": cond})
    ref_loss, ref_grads = _interleaved_reference(
        params, batch, 2 * PP, N_MICRO_I, stage_fn=cond_stage_fn)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_interleaved_forward_only(setup):
    loss, _, params, batch = _run_interleaved(2, forward_only=True)
    ref_loss, _ = _interleaved_reference(params, batch, 2 * PP, N_MICRO_I)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)


def test_interleaved_requires_divisible_microbatches(setup):
    with pytest.raises(ValueError, match="multiple of the pipeline"):
        _run_interleaved(2, n_micro=6)


def test_interleaved_bubble_shrinks_with_v():
    """The whole point of virtual pipelining: bubble ~ (pp-1)/v.  Cost in
    full-stage fwd+bwd units: warmup/cooldown chunk-ticks run only one of
    (fwd, bwd) so cost 1/(2v) each; steady ticks cost 1/v."""
    from apex_tpu.transformer.pipeline_parallel import interleaved_phase_ticks
    n, pp = 32, 4

    def bubble(v):
        warm, steady, cool = interleaved_phase_ticks(n, pp, v)
        cost = (warm + cool) / (2 * v) + steady / v
        return cost - n  # ideal cost is n

    assert bubble(1) == pytest.approx(pp - 1)
    for v in (2, 4):
        assert bubble(v) == pytest.approx((pp - 1) / v), (
            f"v={v}: bubble {bubble(v)} != {(pp - 1) / v}")
    assert bubble(4) < bubble(2) < bubble(1)


def test_interleaved_memory_bounded_in_microbatches(setup):
    """Interleaved 1F1B's circular residual buffer must keep live
    activation memory O(v*pp), independent of num_microbatches."""
    mesh = parallel_state.get_mesh()
    hid, bs, v = 64, 4, 2

    def temp_bytes(n_micro):
        params = {"w": jnp.zeros((PP, v, hid, hid)),
                  "b": jnp.zeros((PP, v, hid))}
        batch = {"x": jnp.zeros((n_micro, bs, hid)),
                 "target": jnp.zeros((n_micro, bs, hid))}

        def body(params, batch):
            local = jax.tree.map(lambda p: p[0], params)
            loss, grads = forward_backward_pipelining_with_interleaving(
                _stage_fn, _loss_fn, local, batch,
                num_microbatches=n_micro, input_fn=_input_fn,
                virtual_pipeline_model_parallel_size=v)
            return loss, jax.tree.map(lambda g: g[None], grads)

        f = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh,
            in_specs=(P("pipe"), P()), out_specs=(P(), P("pipe"))))
        ma = f.lower(params, batch).compile().memory_analysis()
        if ma is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return ma.temp_size_in_bytes

    small, big = temp_bytes(8), temp_bytes(32)
    assert big <= small * 1.25 + 16384, (
        f"interleaved temp memory grew with num_microbatches: "
        f"{small} -> {big}")


def test_1f1b_stage_fn_sees_correct_microbatch(setup):
    """Regression: at tick t, stage s holds microbatch t-s, so stage_fn must
    receive THAT microbatch's data (e.g. per-microbatch conditioning), not
    microbatch t's."""
    params = _make_params(jax.random.key(4), PP)
    batch = _batch(jax.random.key(5))
    # per-microbatch additive conditioning consumed by every stage
    batch["cond"] = jax.random.normal(jax.random.key(6),
                                      (N_MICRO, MICRO_BS, HID))

    def cond_stage_fn(params, x, mb):
        return jax.nn.gelu(x @ params["w"] + params["b"]) + mb["cond"]

    mesh = parallel_state.get_mesh()

    def body(params, batch):
        local = jax.tree.map(lambda p: p[0], params)
        loss, grads = forward_backward_pipelining_without_interleaving(
            cond_stage_fn, _loss_fn, local, batch,
            num_microbatches=N_MICRO, input_fn=_input_fn)
        return loss, jax.tree.map(lambda g: g[None], grads)

    loss, grads = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"), P()),
        out_specs=(P(), P("pipe"))))(params, batch)

    def ref_loss_fn(params):
        total = 0.0
        for m in range(N_MICRO):
            x = batch["x"][m]
            for s in range(PP):
                x = cond_stage_fn(
                    jax.tree.map(lambda p, s=s: p[s], params), x,
                    jax.tree.map(lambda v, m=m: v[m], batch))
            total = total + _loss_fn(x, jax.tree.map(
                lambda v, m=m: v[m], batch))
        return total / N_MICRO

    ref_loss, ref_grads = jax.value_and_grad(ref_loss_fn)(params)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        grads, ref_grads)


def test_1f1b_memory_bounded_in_microbatches(setup):
    """The 1F1B executor's live-activation memory must be O(pp), NOT
    O(num_microbatches) (reference 1F1B's defining property).  The GPipe
    grad-of-scan path stashes n+pp-1 activation ticks and grows ~linearly;
    1F1B's circular residual buffer must keep temp memory flat."""
    mesh = parallel_state.get_mesh()
    hid, bs = 64, 4

    def temp_bytes(n_micro, use_1f1b):
        params = {"w": jnp.zeros((PP, hid, hid)), "b": jnp.zeros((PP, hid))}
        batch = {"x": jnp.zeros((n_micro, bs, hid)),
                 "target": jnp.zeros((n_micro, bs, hid))}

        def body(params, batch):
            local = jax.tree.map(lambda p: p[0], params)
            loss, grads = forward_backward_pipelining_without_interleaving(
                _stage_fn, _loss_fn, local, batch,
                num_microbatches=n_micro, input_fn=_input_fn,
                use_1f1b=use_1f1b)
            return loss, jax.tree.map(lambda g: g[None], grads)

        f = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh,
            in_specs=(P("pipe"), P()), out_specs=(P(), P("pipe"))))
        ma = f.lower(params, batch).compile().memory_analysis()
        if ma is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return ma.temp_size_in_bytes

    small, big = temp_bytes(4, True), temp_bytes(32, True)
    # flat: allow a small constant slack for scan bookkeeping
    assert big <= small * 1.25 + 16384, (
        f"1F1B temp memory grew with num_microbatches: {small} -> {big}")
    gpipe_small, gpipe_big = temp_bytes(4, False), temp_bytes(32, False)
    assert gpipe_big > gpipe_small * 1.5, (
        "expected the GPipe oracle to grow with num_microbatches "
        f"({gpipe_small} -> {gpipe_big}); memory check is vacuous")


def test_get_forward_backward_func_dispatch(setup):
    assert get_forward_backward_func(pipeline_model_parallel_size=1) is \
        forward_backward_no_pipelining
    assert get_forward_backward_func(pipeline_model_parallel_size=PP) is \
        forward_backward_pipelining_without_interleaving
    assert get_forward_backward_func(
        virtual_pipeline_model_parallel_size=2,
        pipeline_model_parallel_size=PP) is \
        forward_backward_pipelining_with_interleaving


def test_forward_only(setup):
    params = _make_params(jax.random.key(0), PP)
    batch = _batch(jax.random.key(1))
    mesh = parallel_state.get_mesh()

    def body(params, batch):
        local = jax.tree.map(lambda p: p[0], params)
        loss, grads = forward_backward_pipelining_without_interleaving(
            _stage_fn, _loss_fn, local, batch,
            num_microbatches=N_MICRO, input_fn=_input_fn, forward_only=True)
        assert grads is None
        return loss

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"), P()), out_specs=P()))(
        params, batch)
    ref_loss, _ = _reference(params, batch)
    np.testing.assert_allclose(loss, ref_loss, rtol=1e-5)


def test_1f1b_composes_with_remat(setup):
    """The documented rematerialization pattern — wrap stage_fn in
    jax.checkpoint — must (a) produce identical grads through the
    residual-buffer machinery and (b) actually SHRINK the buffered
    residuals (the point of remat: only stage inputs are stashed)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        _residual_layout)

    params = _make_params(jax.random.key(0), PP)
    batch = _batch(jax.random.key(1))
    mesh = parallel_state.get_mesh()
    ckpt_stage = jax.checkpoint(_stage_fn)

    def run(stage):
        def body(p, b):
            local = jax.tree.map(lambda q: q[0], p)
            loss, grads = forward_backward_pipelining_without_interleaving(
                stage, _loss_fn, local, b,
                num_microbatches=N_MICRO, input_fn=_input_fn)
            return loss, jax.tree.map(lambda g: g[None], grads)
        return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(P("pipe"), P()),
            out_specs=(P(), P("pipe"))))(params, batch)

    l_raw, g_raw = run(_stage_fn)
    l_ck, g_ck = run(ckpt_stage)
    np.testing.assert_allclose(l_raw, l_ck, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6,
                                                atol=1e-7),
        g_raw, g_ck)

    def buffered_bytes(stage):
        # closure_convert hoists only TRACERS (the executors always probe
        # inside the traced scan region), so measure under make_jaxpr
        local = jax.tree.map(lambda q: q[0], params)
        captured = {}

        def probe(p, b):
            _, buf_shapes, _ = _residual_layout(stage, _input_fn, p, b)
            captured["bs"] = buf_shapes
            return 0.0

        jax.make_jaxpr(probe)(local, batch)
        return sum(np.prod(s) * np.dtype(d).itemsize
                   for s, d in captured["bs"])

    assert buffered_bytes(ckpt_stage) < buffered_bytes(_stage_fn), (
        "remat did not reduce the circular residual buffer")
