"""FusedScaleMaskSoftmax: fused path vs eager fallback (reference:
tests/L0/run_transformer/test_fused_softmax.py — kernel vs python-fallback
equality)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer.enums import AttnMaskType
from apex_tpu.transformer.functional import (
    FusedScaleMaskSoftmax,
    ScaledMaskedSoftmax,
    ScaledUpperTriangMaskedSoftmax,
)

B, NP, SQ, SK = 2, 4, 16, 16


def _attention_mask_func(scores, mask):
    return jnp.where(mask.astype(bool), -10000.0, scores)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("scale", [None, 0.5])
def test_causal_fused_vs_fallback(dtype, scale):
    x = jax.random.normal(jax.random.key(0), (B, NP, SQ, SK)).astype(dtype)
    m = FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=dtype == jnp.bfloat16,
        attn_mask_type=AttnMaskType.causal,
        scaled_masked_softmax_fusion=True,
        mask_func=_attention_mask_func, softmax_in_fp32=True, scale=scale)
    fused = m.forward_fused_softmax(x, None)
    fallback = m.forward_torch_softmax(x, None)
    np.testing.assert_allclose(
        np.asarray(fused, np.float32), np.asarray(fallback, np.float32),
        rtol=1e-3, atol=1e-3)
    # rows sum to one
    np.testing.assert_allclose(
        np.sum(np.asarray(fused, np.float32), -1), 1.0, rtol=1e-2)


@pytest.mark.parametrize("scale", [None, 2.0])
def test_padding_mask_fused_vs_fallback(scale):
    x = jax.random.normal(jax.random.key(1), (B, NP, SQ, SK))
    mask = jax.random.bernoulli(
        jax.random.key(2), 0.3, (B, 1, SQ, SK))
    m = FusedScaleMaskSoftmax(
        input_in_fp16=False, input_in_bf16=False,
        attn_mask_type=AttnMaskType.padding,
        scaled_masked_softmax_fusion=True,
        mask_func=_attention_mask_func, softmax_in_fp32=True, scale=scale)
    fused = m(x, mask)
    fallback = m.forward_torch_softmax(x, mask)
    np.testing.assert_allclose(fused, fallback, rtol=1e-4, atol=1e-4)


def test_causal_masks_upper_triangle():
    x = jnp.zeros((1, SQ, SK))
    probs = ScaledUpperTriangMaskedSoftmax(x)
    probs = np.asarray(probs)[0]
    for i in range(SQ):
        np.testing.assert_allclose(probs[i, i + 1:], 0.0, atol=1e-7)
        np.testing.assert_allclose(probs[i, :i + 1], 1.0 / (i + 1),
                                   rtol=1e-5)


def test_masked_softmax_disables_masked_positions():
    x = jnp.zeros((1, 1, 2, 4))
    mask = jnp.asarray([[[[True, False, False, True],
                          [False, False, True, True]]]])
    probs = np.asarray(ScaledMaskedSoftmax(x, mask))
    np.testing.assert_allclose(probs[0, 0, 0], [0, 0.5, 0.5, 0], atol=1e-6)
    np.testing.assert_allclose(probs[0, 0, 1], [0.5, 0.5, 0, 0], atol=1e-6)


def test_fp16_bf16_both_raises():
    with pytest.raises(RuntimeError):
        FusedScaleMaskSoftmax(True, True, AttnMaskType.padding, True,
                              None, True, None)
