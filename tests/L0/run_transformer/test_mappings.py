"""Collective-algebra tests (reference: tests/L0/run_transformer/test_mappings.py).

Each mapping is checked for BOTH directions of its contract: forward value
and backward (custom-VJP) value, against the plain-numpy equivalent.
"""
import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel

TP = 4


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


def _run(fn, *args, in_specs, out_specs):
    mesh = parallel_state.get_mesh()
    return jax.jit(functools.partial(jax.shard_map, check_vma=False)(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs))(*args)


FULL = P(None, ("pipe", "data", "context", "tensor"))
SHARD_LAST = P(None, ("pipe", "data", "context", "tensor"))


def test_copy_to_region_fwd_and_bwd():
    x = jnp.arange(8.0).reshape(2, 4)

    def body(x):
        y = tensor_parallel.copy_to_tensor_model_parallel_region(x)
        # grad of sum(y) w.r.t. x should be psum(ones) = TP * ones
        g = jax.grad(lambda x: jnp.sum(
            tensor_parallel.copy_to_tensor_model_parallel_region(x)))(x)
        return y, g

    y, g = _run(body, x, in_specs=(P(),), out_specs=(P(), P()))
    np.testing.assert_allclose(y, x)
    np.testing.assert_allclose(g, TP * np.ones_like(x))


def test_reduce_from_region_fwd_and_bwd():
    x = jnp.ones((2, 4))

    def body(x):
        y = tensor_parallel.reduce_from_tensor_model_parallel_region(x)
        g = jax.grad(lambda x: jnp.sum(
            tensor_parallel.reduce_from_tensor_model_parallel_region(x)))(x)
        return y, g

    y, g = _run(body, x, in_specs=(P(),), out_specs=(P(), P()))
    np.testing.assert_allclose(y, TP * np.ones((2, 4)))
    np.testing.assert_allclose(g, np.ones_like(x))  # identity bwd


def test_scatter_gather_roundtrip():
    x = jnp.arange(2.0 * 8).reshape(2, 8)

    def body(x):
        mine = tensor_parallel.scatter_to_tensor_model_parallel_region(x)
        back = tensor_parallel.gather_from_tensor_model_parallel_region(mine)
        return mine.shape[-1] * jnp.ones(()), back

    width, back = _run(body, x, in_specs=(P(),), out_specs=(P(), P()))
    assert int(width) == 8 // TP
    np.testing.assert_allclose(back, x)


def test_gather_bwd_is_split():
    x = jnp.ones((2, 2 * TP))  # global; local shard is [2, 2]

    def body(x):
        g = jax.grad(lambda x: jnp.sum(
            tensor_parallel.gather_from_tensor_model_parallel_region(x)))(x)
        return g

    g = _run(body, x, in_specs=(P(None, "tensor"),),
             out_specs=P(None, "tensor"))
    # each shard's grad is its slice of ones
    np.testing.assert_allclose(g, np.ones((2, 2 * TP)))


def test_sequence_parallel_gather_reduce_scatter():
    # local seq shard: [s/tp, b]; full seq length 8
    full = jnp.arange(8.0 * 2).reshape(8, 2)

    def body(x):
        gathered = tensor_parallel.gather_from_sequence_parallel_region(x)
        # reduce_scatter of the gathered tensor: sums TP copies then
        # scatters -> TP * my shard
        rs = tensor_parallel.reduce_scatter_to_sequence_parallel_region(
            gathered)
        return gathered, rs

    gathered, rs = _run(body, full,
                        in_specs=(P("tensor"),),
                        out_specs=(P(), P("tensor")))
    np.testing.assert_allclose(gathered, full)
    np.testing.assert_allclose(rs, TP * full)


def test_scatter_to_sequence_parallel_region():
    full = jnp.arange(8.0 * 2).reshape(8, 2)

    def body(x):
        return tensor_parallel.scatter_to_sequence_parallel_region(x)

    mine = _run(body, full, in_specs=(P(),), out_specs=P("tensor"))
    np.testing.assert_allclose(mine, full)


def test_tp1_identity():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=1)
    x = jnp.arange(6.0).reshape(2, 3)
    for fn in (tensor_parallel.copy_to_tensor_model_parallel_region,
               tensor_parallel.reduce_from_tensor_model_parallel_region,
               tensor_parallel.scatter_to_tensor_model_parallel_region,
               tensor_parallel.gather_from_tensor_model_parallel_region,
               tensor_parallel.scatter_to_sequence_parallel_region,
               tensor_parallel.gather_from_sequence_parallel_region,
               tensor_parallel.reduce_scatter_to_sequence_parallel_region):
        np.testing.assert_allclose(fn(x), x)


def test_size1_custom_axis_takes_identity_fast_path():
    """A size-1 axis under ANY name must emit no collectives (the
    reference's world_size==1 early-return, axis-size-based at bind
    time rather than special-cased to the canonical tensor axis)."""
    from jax.sharding import Mesh
    from apex_tpu.transformer.tensor_parallel import mappings

    mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("mp",))

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(),
                       out_specs=P(), check_vma=False)
    def run(x):
        # vma-SAFE ops (elementwise identity at size 1): fast path
        y = mappings.copy_to_tensor_model_parallel_region(x, "mp")
        y = mappings.scatter_to_tensor_model_parallel_region(y, "mp")
        return jax.grad(lambda a: jnp.sum(
            mappings.copy_to_tensor_model_parallel_region(a, "mp") ** 2))(
            x) + y

    x = jnp.ones((4, 4))
    jaxpr = str(jax.make_jaxpr(run)(x))
    assert "psum" not in jaxpr and "all_gather" not in jaxpr, (
        "size-1 axis still emits collectives on vma-safe ops")
    np.testing.assert_allclose(np.asarray(run(x)), 3.0)

    # reduce_from KEEPS its psum (its replicated vma typing under the
    # default check_vma=True is load-bearing; an identity fast path here
    # fails the out_specs=P() replication check at trace time)
    @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(),
                       out_specs=P())
    def run_reduce(x):
        return mappings.reduce_from_tensor_model_parallel_region(x, "mp")

    np.testing.assert_allclose(np.asarray(run_reduce(x)), 1.0)
