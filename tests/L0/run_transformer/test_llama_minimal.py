"""End-to-end standalone LLaMA (beyond-parity model: RMSNorm + RoPE +
GQA + SwiGLU composed from the same op inventory the GPT/BERT fixtures
use; see ``standalone_llama.py``)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import LlamaConfig, llama_model_provider

VOCAB, HIDDEN, LAYERS, HEADS, SEQ, BATCH = 64, 32, 2, 4, 16, 2


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    parallel_state.destroy_model_parallel()


def _cfg(**kw):
    return LlamaConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                       num_layers=LAYERS, num_attention_heads=HEADS,
                       max_seq_length=SEQ, **kw)


def _data(seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (BATCH, SEQ), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


def test_loss_reasonable_and_trains():
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg())
    tokens, labels = _data()
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    lg = jax.jit(jax.value_and_grad(
        lambda p: model.apply(p, tokens, labels)))
    loss0, _ = lg(params)
    assert abs(float(loss0) - np.log(VOCAB)) < 1.0   # random-init CE
    opt = FusedAdam(params, lr=3e-3)
    for _ in range(8):
        loss, grads = lg(params)
        params = opt.step(grads)
    assert float(loss) < float(loss0) - 0.1


@pytest.mark.parametrize("kv_heads", [None, 2, 1])
def test_gqa_variants_finite(kv_heads):
    """MHA (None), grouped (2), and MQA (1) all run and give sane CE."""
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg(num_kv_heads=kv_heads))
    tokens, labels = _data(1)
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    loss = jax.jit(lambda p: model.apply(p, tokens, labels))(params)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(VOCAB)) < 1.0


def test_tp2_matches_tp1():
    """Same per-shard init keys as a dense run is not possible (shard
    init folds the rank), so instead: TP=2 loss is finite, CE-scale, and
    the model TRAINS under shard_map with grads synced by psum."""
    parallel_state.initialize_model_parallel(2)
    mesh = parallel_state.get_mesh()
    model = llama_model_provider(_cfg(num_kv_heads=2))
    tokens, labels = _data(2)

    def body(tokens, labels):
        params = model.init(jax.random.PRNGKey(1), tokens, labels)

        def loss_fn(p):
            return model.apply(p, tokens, labels)

        loss0 = loss_fn(params)
        lr = 3e-3
        for _ in range(6):
            loss, g = jax.value_and_grad(loss_fn)(params)
            params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
        return loss0, loss_fn(params)

    loss0, loss1 = jax.jit(functools.partial(
        jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P())))(
        tokens, labels)
    assert np.isfinite(float(loss0)) and np.isfinite(float(loss1))
    assert abs(float(loss0) - np.log(VOCAB)) < 1.0
    assert float(loss1) < float(loss0) - 0.05


def test_rope_positions_matter():
    """Swapping two tokens must change other positions' logits (RoPE
    encodes order; a bag-of-words bug would pass CE checks)."""
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg())
    tokens, _ = _data(3)
    params = model.init(jax.random.PRNGKey(1), tokens)
    swapped = tokens.at[:, 2].set(tokens[:, 3]).at[:, 3].set(tokens[:, 2])
    la = model.apply(params, tokens)
    lb = model.apply(params, swapped)
    # causal: positions before the swap see identical context
    np.testing.assert_allclose(np.asarray(la[:2]), np.asarray(lb[:2]),
                               atol=1e-5)
    # positions after it must differ
    assert float(jnp.max(jnp.abs(la[5:] - lb[5:]))) > 1e-4


def test_remat_matches_baseline():
    parallel_state.initialize_model_parallel(1)
    tokens, labels = _data(4)
    m1 = llama_model_provider(_cfg())
    params = m1.init(jax.random.PRNGKey(1), tokens, labels)
    m2 = llama_model_provider(_cfg(remat=True))
    l1 = m1.apply(params, tokens, labels)
    l2 = m2.apply(params, tokens, labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: m1.apply(p, tokens, labels))(params)
    g2 = jax.grad(lambda p: m2.apply(p, tokens, labels))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-6), g1, g2)


def test_mqa_under_tp_replicated_kv():
    """tp=2 with a single kv head: the replicated-kv path must produce a
    finite CE-scale loss (each rank gathers its q-heads' shared kv)."""
    parallel_state.initialize_model_parallel(2)
    mesh = parallel_state.get_mesh()
    model = llama_model_provider(_cfg(num_kv_heads=1))
    tokens, labels = _data(5)

    def body(tokens, labels):
        p = model.init(jax.random.PRNGKey(1), tokens, labels)
        return model.apply(p, tokens, labels)

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(
        tokens, labels)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(VOCAB)) < 1.0


def test_config_validation():
    with pytest.raises(ValueError, match="multiple of num_kv_heads"):
        _cfg(num_kv_heads=3)                # 4 heads % 3 != 0
    model = llama_model_provider(_cfg())
    parallel_state.initialize_model_parallel(1)
    long_tokens = jnp.zeros((1, SEQ + 1), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        model.init(jax.random.PRNGKey(0), long_tokens)
