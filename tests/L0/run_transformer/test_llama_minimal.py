"""End-to-end standalone LLaMA (beyond-parity model: RMSNorm + RoPE +
GQA + SwiGLU composed from the same op inventory the GPT/BERT fixtures
use; see ``standalone_llama.py``)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import LlamaConfig, llama_model_provider

VOCAB, HIDDEN, LAYERS, HEADS, SEQ, BATCH = 64, 32, 2, 4, 16, 2


@pytest.fixture(autouse=True)
def _cleanup():
    yield
    parallel_state.destroy_model_parallel()


def _cfg(**kw):
    return LlamaConfig(vocab_size=VOCAB, hidden_size=HIDDEN,
                       num_layers=LAYERS, num_attention_heads=HEADS,
                       max_seq_length=SEQ, **kw)


def _data(seed=0):
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(key, (BATCH, SEQ), 0, VOCAB)
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels


def test_loss_reasonable_and_trains():
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg())
    tokens, labels = _data()
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    lg = jax.jit(jax.value_and_grad(
        lambda p: model.apply(p, tokens, labels)))
    loss0, _ = lg(params)
    assert abs(float(loss0) - np.log(VOCAB)) < 1.0   # random-init CE
    opt = FusedAdam(params, lr=3e-3)
    for _ in range(8):
        loss, grads = lg(params)
        params = opt.step(grads)
    assert float(loss) < float(loss0) - 0.1


@pytest.mark.parametrize("kv_heads", [None, 2, 1])
def test_gqa_variants_finite(kv_heads):
    """MHA (None), grouped (2), and MQA (1) all run and give sane CE."""
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg(num_kv_heads=kv_heads))
    tokens, labels = _data(1)
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    loss = jax.jit(lambda p: model.apply(p, tokens, labels))(params)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(VOCAB)) < 1.0


def _shard_llama_params(params, tp):
    """Hand-shard a TP=1 param tree into per-rank trees stacked on a
    leading [tp] axis (Column/[out,in] and embeddings split dim0, Row
    splits dim1, norm weights replicate)."""
    ROW = ("o_proj", "down_proj")

    def shard(path, leaf):
        names = {getattr(p, "key", None) for p in path}
        if names & {"input_norm", "post_attention_norm", "final_norm"}:
            return jnp.stack([leaf] * tp)
        if names & set(ROW):
            return jnp.stack(jnp.split(leaf, tp, axis=1))
        return jnp.stack(jnp.split(leaf, tp, axis=0))

    return jax.tree_util.tree_map_with_path(shard, params)


def test_tp2_matches_tp1_exactly():
    """Dense (TP=1) init, hand-sharded to TP=2: the sharded loss must
    equal the dense loss — catches shard-to-head misalignment and
    dropped collective partials that a finite-loss smoke test passes."""
    tokens, labels = _data(7)
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg(num_kv_heads=2))
    params = model.init(jax.random.PRNGKey(1), tokens, labels)
    dense_loss = float(model.apply(params, tokens, labels))
    parallel_state.destroy_model_parallel()

    tp = 2
    parallel_state.initialize_model_parallel(tp)
    mesh = parallel_state.get_mesh()
    stacked = _shard_llama_params(params, tp)

    def body(stacked, tokens, labels):
        p = jax.tree.map(lambda x: x[0], stacked)   # my rank's shard
        return model.apply(p, tokens, labels)

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("tensor"), P(), P()),
        out_specs=P()))(stacked, tokens, labels)
    np.testing.assert_allclose(float(loss), dense_loss, rtol=2e-5)


def test_tp2_trains_under_shard_map():
    """TP=2 loss is finite, CE-scale, and the model TRAINS under
    shard_map (grad sync exactness is test_tp2_matches_tp1_exactly's
    job)."""
    parallel_state.initialize_model_parallel(2)
    mesh = parallel_state.get_mesh()
    model = llama_model_provider(_cfg(num_kv_heads=2))
    tokens, labels = _data(2)

    def body(tokens, labels):
        params = model.init(jax.random.PRNGKey(1), tokens, labels)

        def loss_fn(p):
            return model.apply(p, tokens, labels)

        loss0 = loss_fn(params)
        lr = 3e-3
        for _ in range(6):
            loss, g = jax.value_and_grad(loss_fn)(params)
            params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
        return loss0, loss_fn(params)

    loss0, loss1 = jax.jit(functools.partial(
        jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P())))(
        tokens, labels)
    assert np.isfinite(float(loss0)) and np.isfinite(float(loss1))
    assert abs(float(loss0) - np.log(VOCAB)) < 1.0
    assert float(loss1) < float(loss0) - 0.05


def test_rope_positions_matter():
    """Swapping two tokens must change other positions' logits (RoPE
    encodes order; a bag-of-words bug would pass CE checks)."""
    parallel_state.initialize_model_parallel(1)
    model = llama_model_provider(_cfg())
    tokens, _ = _data(3)
    params = model.init(jax.random.PRNGKey(1), tokens)
    swapped = tokens.at[:, 2].set(tokens[:, 3]).at[:, 3].set(tokens[:, 2])
    la = model.apply(params, tokens)
    lb = model.apply(params, swapped)
    # causal: positions before the swap see identical context
    np.testing.assert_allclose(np.asarray(la[:2]), np.asarray(lb[:2]),
                               atol=1e-5)
    # positions after it must differ
    assert float(jnp.max(jnp.abs(la[5:] - lb[5:]))) > 1e-4


def test_remat_matches_baseline():
    parallel_state.initialize_model_parallel(1)
    tokens, labels = _data(4)
    m1 = llama_model_provider(_cfg())
    params = m1.init(jax.random.PRNGKey(1), tokens, labels)
    m2 = llama_model_provider(_cfg(remat=True))
    l1 = m1.apply(params, tokens, labels)
    l2 = m2.apply(params, tokens, labels)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = jax.grad(lambda p: m1.apply(p, tokens, labels))(params)
    g2 = jax.grad(lambda p: m2.apply(p, tokens, labels))(params)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-6), g1, g2)


def test_mqa_under_tp_replicated_kv():
    """tp=2 with a single kv head: the replicated-kv path must produce a
    finite CE-scale loss (each rank gathers its q-heads' shared kv)."""
    parallel_state.initialize_model_parallel(2)
    mesh = parallel_state.get_mesh()
    model = llama_model_provider(_cfg(num_kv_heads=1))
    tokens, labels = _data(5)

    def body(tokens, labels):
        p = model.init(jax.random.PRNGKey(1), tokens, labels)
        return model.apply(p, tokens, labels)

    loss = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(
        tokens, labels)
    assert np.isfinite(float(loss))
    assert abs(float(loss) - np.log(VOCAB)) < 1.0


def test_config_validation():
    with pytest.raises(ValueError, match="multiple of num_kv_heads"):
        _cfg(num_kv_heads=3)                # 4 heads % 3 != 0
    model = llama_model_provider(_cfg())
    parallel_state.initialize_model_parallel(1)
    long_tokens = jnp.zeros((1, SEQ + 1), jnp.int32)
    with pytest.raises(ValueError, match="exceeds"):
        model.init(jax.random.PRNGKey(0), long_tokens)


@pytest.mark.parametrize("reduce_grads", [True, False])
def test_mqa_tp_kv_grad_reduction_keeps_ranks_consistent(reduce_grads):
    """Replicated-kv wgrads are per-rank partials: with
    reduce_llama_grads the kv weights stay bit-identical across tensor
    ranks through updates; without it they drift (the negative control
    proves the reduction is load-bearing)."""
    from apex_tpu.transformer.testing.standalone_llama import (
        reduce_llama_grads,
    )
    parallel_state.initialize_model_parallel(2)
    mesh = parallel_state.get_mesh()
    cfg = _cfg(num_kv_heads=1)
    model = llama_model_provider(cfg)
    tokens, labels = _data(6)

    def body(tokens, labels):
        p = model.init(jax.random.PRNGKey(1), tokens, labels)

        def loss_fn(p):
            return model.apply(p, tokens, labels)

        for _ in range(3):
            _, g = jax.value_and_grad(loss_fn)(p)
            if reduce_grads:
                g = reduce_llama_grads(g, cfg)
            p = jax.tree.map(lambda a, b: a - 3e-3 * b, p, g)
        kv = p["params"]["layer_0"]["attention"]["kv_proj"]["kernel"]
        return kv[None]                      # [1, h, 2*kv*d] per rank

    kv_both = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(), P()),
        out_specs=P("tensor")))(tokens, labels)   # stacked [2, h, ...]
    diff = float(jnp.max(jnp.abs(kv_both[0] - kv_both[1])))
    if reduce_grads:
        assert diff == 0.0, diff
    else:
        assert diff > 1e-7, "negative control: drift expected"
