"""Chunked fused LM-head + cross-entropy (ISSUE 9): grad parity vs the
unfused project-then-CE path — fp32 + bf16, smoothing on/off,
padding_idx rows, token counts not divisible by the chunk, the
vocab-chunked inner scan, the vocab-parallel TP variant, and the
standalone GPT (tied head) / LLaMA (untied GQA head) model swaps.

The acceptance bar is <= 2e-4 loss+grad parity (ISSUE 9); fp32 runs
land ~1e-6 (chunked-sum reorder only) and the assertions pin that
tighter level so regressions surface early.
"""
import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.fused_lm_xent import (
    fused_lm_head_cross_entropy,
    fused_lm_head_vocab_parallel_cross_entropy,
    lm_head_xentropy_reference,
)
from apex_tpu.transformer import parallel_state

shard_map = functools.partial(jax.shard_map, check_vma=False)

TOL = 2e-4          # the ISSUE 9 acceptance ceiling
TOL_F32 = 5e-6      # what fp32 actually achieves (reorder-only)


@pytest.fixture(autouse=True)
def _restore_parallel_state():
    yield
    parallel_state.destroy_model_parallel()


def _fixture(n, h, v, dtype=jnp.float32, pad_every=0, seed=0):
    kh, kw, kl = jax.random.split(jax.random.PRNGKey(seed), 3)
    hid = jax.random.normal(kh, (n, h), dtype)
    w = (jax.random.normal(kw, (v, h), dtype) * 0.3).astype(dtype)
    lab = jax.random.randint(kl, (n,), 0, v)
    if pad_every:
        lab = lab.at[::pad_every].set(-100)
    return hid, w, lab


def _grads(loss_fn, hid, w):
    return jax.value_and_grad(
        lambda hid, w: loss_fn(hid, w).sum(), argnums=(0, 1))(hid, w)


class TestFusedLmXentParity:
    """Op-level fused vs unfused, all the axes the ISSUE names."""

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    @pytest.mark.parametrize("n,chunk", [(64, 16), (37, 8), (5, 8)])
    def test_fp32_loss_and_grads(self, smoothing, n, chunk):
        # 37 % 8 != 0 exercises the internal pad; 5 < 8 the clamp
        hid, w, lab = _fixture(n, 16, 96, pad_every=5)
        l1, (gh1, gw1) = _grads(
            lambda hid, w: fused_lm_head_cross_entropy(
                hid, w, lab, smoothing=smoothing, token_chunk=chunk),
            hid, w)
        l0, (gh0, gw0) = _grads(
            lambda hid, w: lm_head_xentropy_reference(
                hid, w, lab, smoothing=smoothing), hid, w)
        np.testing.assert_allclose(l1, l0, rtol=0, atol=TOL_F32 * n)
        np.testing.assert_allclose(gh1, gh0, rtol=0, atol=TOL_F32)
        np.testing.assert_allclose(gw1, gw0, rtol=0, atol=TOL_F32)

    @pytest.mark.parametrize("vocab_chunk", [32, 48])
    def test_vocab_chunked_inner_scan(self, vocab_chunk):
        hid, w, lab = _fixture(40, 16, 96, pad_every=7)
        l1, (gh1, gw1) = _grads(
            lambda hid, w: fused_lm_head_cross_entropy(
                hid, w, lab, smoothing=0.1, token_chunk=8,
                vocab_chunk=vocab_chunk), hid, w)
        l0, (gh0, gw0) = _grads(
            lambda hid, w: lm_head_xentropy_reference(
                hid, w, lab, smoothing=0.1), hid, w)
        np.testing.assert_allclose(l1, l0, rtol=0, atol=TOL)
        np.testing.assert_allclose(gh1, gh0, rtol=0, atol=TOL)
        np.testing.assert_allclose(gw1, gw0, rtol=0, atol=TOL)

    def test_vocab_chunk_must_divide(self):
        hid, w, lab = _fixture(16, 8, 96)
        with pytest.raises(ValueError, match="divide"):
            fused_lm_head_cross_entropy(hid, w, lab, token_chunk=8,
                                        vocab_chunk=7)

    def test_bf16_within_ulp_scale(self):
        # bf16 parity is rounding-bound (one output-ulp scale), not the
        # fp32 reorder bound; losses compare in fp32
        hid, w, lab = _fixture(64, 32, 128, dtype=jnp.bfloat16)
        l1, (gh1, gw1) = _grads(
            lambda hid, w: fused_lm_head_cross_entropy(
                hid, w, lab, smoothing=0.1, token_chunk=16), hid, w)
        l0, (gh0, gw0) = _grads(
            lambda hid, w: lm_head_xentropy_reference(
                hid, w, lab, smoothing=0.1), hid, w)
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-3)
        np.testing.assert_allclose(
            np.asarray(gh1, np.float32), np.asarray(gh0, np.float32),
            rtol=0, atol=1.6e-2)
        np.testing.assert_allclose(
            np.asarray(gw1, np.float32), np.asarray(gw0, np.float32),
            rtol=0, atol=1.6e-2)

    def test_padding_rows_zero_loss_and_grad(self):
        hid, w, lab = _fixture(32, 16, 64, pad_every=4)
        loss = fused_lm_head_cross_entropy(hid, w, lab, token_chunk=8)
        assert np.all(np.asarray(loss[::4]) == 0.0)
        _, (gh, _) = _grads(
            lambda hid, w: fused_lm_head_cross_entropy(
                hid, w, lab, token_chunk=8), hid, w)
        assert np.all(np.asarray(gh[::4]) == 0.0)
        assert np.any(np.asarray(gh[1::4]) != 0.0)

    def test_chunk_zero_is_the_unfused_path_bitwise(self):
        # the env-knob default (APEX_TPU_XENT_CHUNK=0) must BE the
        # unfused lowering, not a chunked run that happens to agree
        hid, w, lab = _fixture(24, 16, 64)
        out = fused_lm_head_cross_entropy(hid, w, lab, token_chunk=0)
        ref = lm_head_xentropy_reference(hid, w, lab)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_leading_dims_flatten(self):
        hid, w, lab = _fixture(24, 16, 64)
        out2 = fused_lm_head_cross_entropy(
            hid.reshape(4, 6, 16), w, lab.reshape(4, 6), token_chunk=8)
        out1 = fused_lm_head_cross_entropy(hid, w, lab, token_chunk=8)
        assert out2.shape == (4, 6)
        np.testing.assert_array_equal(np.asarray(out2.reshape(-1)),
                                      np.asarray(out1))


class TestVocabParallelFused:
    """The TP variant vs the unfused vocab-parallel head, per rank."""

    def _run(self, tp, fused, grad_input_psum=False, smoothing=0.0):
        from apex_tpu.transformer.tensor_parallel.cross_entropy import (
            vocab_parallel_cross_entropy)
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
        mesh = parallel_state.get_mesh()
        n, h, v = 24, 16, 64
        hid, w, lab = _fixture(n, h, v, seed=3)

        def body(hid, w, lab):
            def loss(hid, w):
                if fused:
                    return fused_lm_head_vocab_parallel_cross_entropy(
                        hid, w, lab, smoothing=smoothing, token_chunk=8,
                        grad_input_psum=grad_input_psum).sum()
                logits = jnp.matmul(hid, w.T)
                if grad_input_psum:
                    from apex_tpu.transformer.tensor_parallel import (
                        mappings)
                    hid = mappings.copy_to_tensor_model_parallel_region(
                        hid)
                    logits = jnp.matmul(hid, w.T)
                return vocab_parallel_cross_entropy(
                    logits.astype(jnp.float32), lab,
                    label_smoothing=smoothing).sum()
            # psum-seeded cotangent pattern from test_cross_entropy
            return jax.value_and_grad(loss, argnums=(0, 1))(hid, w)

        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P("tensor", None), P()),
                       out_specs=(P(), (P(), P("tensor", None))))
        return jax.jit(fn)(hid, w, lab)

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_tp4_matches_unfused_vocab_parallel(self, smoothing):
        l0, (gh0, gw0) = self._run(4, fused=False, smoothing=smoothing)
        l1, (gh1, gw1) = self._run(4, fused=True, smoothing=smoothing)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=0, atol=TOL)
        np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh0),
                                   rtol=0, atol=TOL)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw0),
                                   rtol=0, atol=TOL)

    def test_tp2_grad_input_psum_matches_column_parallel_contract(self):
        # the untied-head contract: dhidden psum'd over the tensor axis
        l0, (gh0, gw0) = self._run(2, fused=False, grad_input_psum=True)
        l1, (gh1, gw1) = self._run(2, fused=True, grad_input_psum=True)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=0, atol=TOL)
        np.testing.assert_allclose(np.asarray(gh1), np.asarray(gh0),
                                   rtol=0, atol=TOL)
        np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw0),
                                   rtol=0, atol=TOL)

    def test_tp2_padding_rows_zero_on_every_rank(self):
        # padding semantics must NOT change between tp=1 (local fused,
        # which zeroes pad rows) and tp>1 — loss 0 and grads 0 for
        # -100 rows on every rank, and non-pad rows untouched
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=2)
        mesh = parallel_state.get_mesh()
        n, h, v = 24, 16, 64
        hid, w, lab = _fixture(n, h, v, seed=5)
        lab_pad = lab.at[::4].set(-100)

        def body(hid, w, lab):
            def loss(hid, w):
                return fused_lm_head_vocab_parallel_cross_entropy(
                    hid, w, lab, token_chunk=8)
            per_tok = loss(hid, w)
            _, (gh, gw) = jax.value_and_grad(
                lambda hid, w: loss(hid, w).sum(),
                argnums=(0, 1))(hid, w)
            return per_tok, gh, gw

        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P("tensor", None), P()),
                       out_specs=(P(), P(), P("tensor", None)))
        per_tok, gh, gw = jax.jit(fn)(hid, w, lab_pad)
        assert np.all(np.asarray(per_tok[::4]) == 0.0)
        assert np.all(np.asarray(gh[::4]) == 0.0)
        assert np.any(np.asarray(gh[1::4]) != 0.0)
        # non-pad rows match the run where the pad rows never existed
        keep = np.arange(n) % 4 != 0
        ref_tok, _, _ = jax.jit(fn)(hid, w, lab)
        np.testing.assert_array_equal(np.asarray(per_tok)[keep],
                                      np.asarray(ref_tok)[keep])

    def test_tp1_degrades_to_local_fused(self):
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(1)
        hid, w, lab = _fixture(24, 16, 64)
        out = fused_lm_head_vocab_parallel_cross_entropy(
            hid, w, lab, token_chunk=8)
        ref = fused_lm_head_cross_entropy(hid, w, lab, token_chunk=8)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestModelSwap:
    """fused_head_xent= on the standalone models: identical param tree,
    <= 2e-4 loss+grad parity vs the unfused configs (MHA tied head and
    GQA untied head), tp=1 and tp=2."""

    def _gpt(self, tp, chunk):
        from apex_tpu.transformer.testing import (GPTConfig,
                                                  gpt_model_provider)
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
        mesh = parallel_state.get_mesh()
        cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                        num_attention_heads=4, max_seq_length=16,
                        hidden_dropout=0.0, attention_dropout=0.0,
                        fused_head_xent=chunk)
        model = gpt_model_provider(cfg)
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 96)
        labs = jnp.roll(toks, -1, axis=1)

        def body(toks, labs):
            p = model.init(jax.random.PRNGKey(1), toks)
            return jax.value_and_grad(
                lambda p: model.apply(p, toks, labs))(p)

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=(P(), P())))(toks, labs)

    @pytest.mark.parametrize("tp", [1, 2])
    def test_gpt_tied_head(self, tp):
        l0, g0 = self._gpt(tp, chunk=0)
        l1, g1 = self._gpt(tp, chunk=8)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=0, atol=TOL)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=TOL)

    def _llama(self, tp, chunk, kv_heads):
        from apex_tpu.transformer.testing.standalone_llama import (
            LlamaConfig, llama_model_provider)
        parallel_state.destroy_model_parallel()
        parallel_state.initialize_model_parallel(
            tensor_model_parallel_size_=tp)
        mesh = parallel_state.get_mesh()
        cfg = LlamaConfig(vocab_size=96, hidden_size=32, num_layers=2,
                          num_attention_heads=4, num_kv_heads=kv_heads,
                          max_seq_length=16)
        ref_model = llama_model_provider(cfg)   # unfused init: the tree
        model = llama_model_provider(
            dataclasses.replace(cfg, fused_head_xent=chunk))
        toks = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 96)
        labs = jnp.roll(toks, -1, axis=1)

        def body(toks, labs):
            # init with the UNFUSED config, apply with the fused one:
            # proves the param trees are interchangeable (checkpoints
            # survive flipping the knob)
            p = ref_model.init(jax.random.PRNGKey(1), toks)
            return jax.value_and_grad(
                lambda p: model.apply(p, toks, labs))(p)

        return jax.jit(shard_map(body, mesh=mesh, in_specs=(P(), P()),
                                 out_specs=(P(), P())))(toks, labs)

    @pytest.mark.parametrize("tp,kv_heads", [(1, 4), (1, 2), (2, 2)])
    def test_llama_untied_head_mha_gqa(self, tp, kv_heads):
        l0, g0 = self._llama(tp, 0, kv_heads)
        l1, g1 = self._llama(tp, 8, kv_heads)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l0),
                                   rtol=0, atol=TOL)
        for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=TOL)


class TestScanCarryAndResiduals:
    """Structural guarantees: no [tokens, vocab] residual crosses the
    custom_vjp boundary, and the op survives jit/scan/donation."""

    def test_no_full_logits_residual_saved(self):
        # trace value_and_grad and liveness-walk it: the peak must sit
        # FAR below the unfused twin's (which materializes logits fwd
        # AND softmax bwd) at a shape where logits dominate
        from apex_tpu.analysis.comm_model import peak_live_bytes
        n, h, v = 256, 16, 2048      # fp32 logits = 2 MiB
        hid, w, lab = _fixture(n, h, v)

        def fb(loss_fn):
            return lambda hid, w: jax.grad(
                lambda hid, w: loss_fn(hid, w).sum(),
                argnums=(0, 1))(hid, w)

        fused = peak_live_bytes(jax.make_jaxpr(
            fb(lambda hid, w: fused_lm_head_cross_entropy(
                hid, w, lab, token_chunk=32)))(hid, w).jaxpr)
        unfused = peak_live_bytes(jax.make_jaxpr(
            fb(lambda hid, w: lm_head_xentropy_reference(
                hid, w, lab)))(hid, w).jaxpr)
        logits_bytes = n * v * 4
        assert fused < unfused / 2, (fused, unfused)
        assert fused < logits_bytes, (fused, logits_bytes)

    def test_jit_scan_donation_safe(self):
        # the fused loss inside a donated scanned train loop: the dw
        # scan carry must not alias donated state wrongly (values match
        # the undonated run)
        n, h, v = 32, 8, 64
        hid, w, lab = _fixture(n, h, v)

        def step(w, _):
            loss, gw = jax.value_and_grad(
                lambda w: fused_lm_head_cross_entropy(
                    hid, w, lab, token_chunk=8).mean())(w)
            return w - 0.1 * gw, loss

        def run(w):
            return jax.lax.scan(step, w, jnp.arange(4))

        w_ref, losses_ref = jax.jit(run)(w)
        w_don, losses_don = jax.jit(run, donate_argnums=(0,))(w)
        np.testing.assert_array_equal(np.asarray(losses_don),
                                      np.asarray(losses_ref))
        np.testing.assert_array_equal(np.asarray(w_don),
                                      np.asarray(w_ref))
