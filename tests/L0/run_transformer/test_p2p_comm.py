"""p2p_communication semantics (reference:
``tests/L0/run_transformer/test_p2p_comm.py``): every wrapper must move
payloads exactly one stage forward/backward along the pipe ring."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p

PP = 4


@pytest.fixture
def mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(pipeline_model_parallel_size_=PP)
    yield parallel_state.get_mesh()
    parallel_state.destroy_model_parallel()


def _run(mesh, fn, payload):
    """Run fn over the pipe mesh; payload has leading stage dim."""
    def body(x):
        out = fn(jax.tree.map(lambda a: a[0], x))
        return jax.tree.map(lambda a: a[None], out)
    return jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"),), out_specs=P("pipe")))(
        payload)


def test_send_forward_recv_forward_rotates_up(mesh):
    payload = jnp.arange(PP, dtype=jnp.float32)[:, None] * jnp.ones((1, 8))
    out = _run(mesh, p2p.send_forward_recv_forward, payload)
    # stage s now holds what stage s-1 sent (ring wrap: stage 0 holds PP-1)
    expect = jnp.roll(jnp.arange(PP, dtype=jnp.float32), 1)
    np.testing.assert_allclose(out[:, 0], expect)


def test_send_backward_recv_backward_rotates_down(mesh):
    payload = jnp.arange(PP, dtype=jnp.float32)[:, None] * jnp.ones((1, 8))
    out = _run(mesh, p2p.send_backward_recv_backward, payload)
    expect = jnp.roll(jnp.arange(PP, dtype=jnp.float32), -1)
    np.testing.assert_allclose(out[:, 0], expect)


def test_individual_halves_match_fused(mesh):
    payload = jax.random.normal(jax.random.PRNGKey(0), (PP, 8))
    fused = _run(mesh, p2p.send_forward_recv_forward, payload)
    send = _run(mesh, p2p.send_forward, payload)
    recv = _run(mesh, p2p.recv_forward, payload)
    np.testing.assert_allclose(send, fused)
    np.testing.assert_allclose(recv, fused)
    fusedb = _run(mesh, p2p.send_backward_recv_backward, payload)
    np.testing.assert_allclose(_run(mesh, p2p.send_backward, payload),
                               fusedb)
    np.testing.assert_allclose(_run(mesh, p2p.recv_backward, payload),
                               fusedb)


def test_steady_state_pair_moves_both_directions(mesh):
    acts = jnp.arange(PP, dtype=jnp.float32)[:, None] * jnp.ones((1, 4))
    grads = 100.0 + jnp.arange(PP, dtype=jnp.float32)[:, None] * \
        jnp.ones((1, 4))

    def body(a, g):
        fa, bg = p2p.send_forward_recv_backward(a[0], g[0])
        return fa[None], bg[None]

    fa, bg = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"), P("pipe")),
        out_specs=(P("pipe"), P("pipe"))))(acts, grads)
    np.testing.assert_allclose(
        fa[:, 0], jnp.roll(jnp.arange(PP, dtype=jnp.float32), 1))
    np.testing.assert_allclose(
        bg[:, 0], jnp.roll(100.0 + jnp.arange(PP, dtype=jnp.float32), -1))


def test_pytree_payloads(mesh):
    payload = {"x": jnp.arange(PP, dtype=jnp.float32)[:, None],
               "y": (jnp.ones((PP, 2)) *
                     jnp.arange(PP, dtype=jnp.float32)[:, None])}
    out = _run(mesh, p2p.send_forward_recv_forward, payload)
    np.testing.assert_allclose(
        out["x"][:, 0], jnp.roll(jnp.arange(PP, dtype=jnp.float32), 1))
    np.testing.assert_allclose(
        out["y"][:, 0], jnp.roll(jnp.arange(PP, dtype=jnp.float32), 1))


def test_roundtrip_is_identity(mesh):
    payload = jax.random.normal(jax.random.PRNGKey(1), (PP, 8))

    def body(x):
        return p2p.send_backward(p2p.send_forward(x[0]))[None]

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("pipe"),), out_specs=P("pipe")))(
        payload)
    np.testing.assert_allclose(out, payload)


def test_tensor_shape_kwargs_accepted(mesh):
    """Parity: reference callers pass tensor_shape/dtype/timers kwargs."""
    payload = jnp.ones((PP, 4))
    out = _run(mesh, functools.partial(
        p2p.send_forward_recv_forward, tensor_shape=(4,),
        override_scatter_gather_tensors_in_pipeline=False,
        dtype_=jnp.float32, timers=None), payload)
    assert out.shape == (PP, 4)
