"""RNG tracker + checkpoint tests (reference:
tests/L0/run_transformer/test_random.py): per-rank streams differ, default
stream is shared, recompute replays dropout identically.
"""
import functools
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel
from apex_tpu.transformer.tensor_parallel import random as tp_random

TP = 4


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    tp_random.model_parallel_seed(123)
    yield
    parallel_state.destroy_model_parallel()


def test_add_duplicate_seed_or_name_raises():
    tracker = tp_random.RNGStatesTracker()
    tracker.add("a", 1)
    with pytest.raises(RuntimeError):
        tracker.add("b", 1)       # duplicate seed
    with pytest.raises(RuntimeError):
        tracker.add("a", 2)       # duplicate name
    with pytest.raises(RuntimeError):
        with tracker.fork("missing"):
            pass


def test_model_parallel_stream_differs_across_ranks():
    mesh = parallel_state.get_mesh()

    def body():
        tracker = tp_random.get_rng_tracker()
        with tracker.fork() as key:
            bits = jax.random.uniform(key, (4,))
        return bits.reshape(1, 4)

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(),
        out_specs=P("tensor")))()
    out = np.asarray(out)  # [TP, 4]
    for i in range(TP):
        for j in range(i + 1, TP):
            assert not np.allclose(out[i], out[j]), (
                "model-parallel dropout streams must differ across TP ranks")


def test_default_stream_shared_across_ranks():
    mesh = parallel_state.get_mesh()

    def body():
        tracker = tp_random.get_rng_tracker()
        with tracker.fork("default") as key:
            bits = jax.random.uniform(key, (4,))
        return bits.reshape(1, 4)

    out = np.asarray(jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(), out_specs=P("tensor")))())
    for i in range(1, TP):
        np.testing.assert_array_equal(out[0], out[i])


def test_checkpoint_recompute_identical_dropout():
    """The property CudaRNGStatesTracker exists to enforce: grads through a
    checkpointed dropout region equal grads through the plain region."""
    tp_random.model_parallel_cuda_manual_seed(7)

    def block(x):
        tracker = tp_random.get_cuda_rng_tracker()
        with tracker.fork("default") as key:
            mask = jax.random.bernoulli(key, 0.5, x.shape)
        return jnp.sum(jnp.where(mask, x, 0.0) * x)

    x = jax.random.normal(jax.random.key(0), (16,))
    g_plain = jax.grad(block)(x)

    tp_random.model_parallel_cuda_manual_seed(7)
    g_ckpt = jax.grad(
        lambda x: tensor_parallel.checkpoint(block, False, x))(x)
    np.testing.assert_allclose(g_plain, g_ckpt)


def test_fork_advances_between_callsites():
    tracker = tp_random.RNGStatesTracker()
    tracker.add("s", 5)
    with tracker.fork("s") as k1, tracker.fork("s") as k2:
        a = jax.random.uniform(k1, (4,))
        b = jax.random.uniform(k2, (4,))
    assert not np.allclose(a, b)
