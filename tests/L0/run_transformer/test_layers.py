"""TP layer tests (reference: tests/L0/run_transformer/test_layers.py):
sharded layers must match a dense (unsharded) computation.
"""
import functools
import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state, tensor_parallel

TP = 4
IN, OUT = 8, 16
BATCH = 3


@pytest.fixture(autouse=True)
def _mesh():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(tensor_model_parallel_size_=TP)
    yield
    parallel_state.destroy_model_parallel()


def test_column_parallel_linear_matches_dense():
    x = jax.random.normal(jax.random.key(0), (BATCH, IN))
    col = tensor_parallel.ColumnParallelLinear(IN, OUT, gather_output=True)
    mesh = parallel_state.get_mesh()

    def body(x):
        params = col.init(jax.random.key(0), x)
        out, _ = col.apply(params, x)
        return out, params["params"]["weight"], params["params"]["bias"]

    out, w_shards, b_shards = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(),),
        out_specs=(P(), P("tensor"), P("tensor"))))(x)
    # reassembled full weight reproduces the sharded forward
    w = np.asarray(w_shards).reshape(OUT, IN)
    b = np.asarray(b_shards).reshape(OUT)
    np.testing.assert_allclose(out, np.asarray(x) @ w.T + b, rtol=1e-5,
                               atol=1e-5)


def test_row_parallel_linear_matches_dense():
    x = jax.random.normal(jax.random.key(1), (BATCH, IN))
    row = tensor_parallel.RowParallelLinear(IN, OUT, input_is_parallel=False)
    mesh = parallel_state.get_mesh()

    def body(x):
        params = row.init(jax.random.key(7), x)
        out, _ = row.apply(params, x)
        return out, params["params"]["weight"]

    out, w_shards = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(),),
        out_specs=(P(), P(None, "tensor"))))(x)
    w = np.asarray(w_shards)  # [OUT, IN] reassembled on in-dim
    np.testing.assert_allclose(out, np.asarray(x) @ w.T, rtol=1e-5,
                               atol=1e-5)


def test_column_row_composition_mlp():
    """Megatron MLP pattern: Column(gather=False) -> Row(input_is_parallel):
    must equal the dense two-layer product with NO intermediate gather."""
    x = jax.random.normal(jax.random.key(2), (BATCH, IN))
    col = tensor_parallel.ColumnParallelLinear(IN, OUT, gather_output=False,
                                               bias=False)
    row = tensor_parallel.RowParallelLinear(OUT, IN, input_is_parallel=True,
                                            bias=False)
    mesh = parallel_state.get_mesh()

    def body(x):
        pc = col.init(jax.random.key(3), x)
        h, _ = col.apply(pc, x)
        pr = row.init(jax.random.key(4), h)
        y, _ = row.apply(pr, h)
        return y, pc["params"]["weight"], pr["params"]["weight"]

    y, wc, wr = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(),),
        out_specs=(P(), P("tensor"), P(None, "tensor"))))(x)
    dense = np.asarray(x) @ np.asarray(wc).T @ np.asarray(wr).T
    np.testing.assert_allclose(y, dense, rtol=1e-5, atol=1e-5)


def test_vocab_parallel_embedding_matches_dense():
    vocab, dim = 16, 8
    tokens = jax.random.randint(jax.random.key(5), (BATCH, 5), 0, vocab)
    emb = tensor_parallel.VocabParallelEmbedding(vocab, dim)
    mesh = parallel_state.get_mesh()

    def body(tokens):
        params = emb.init(jax.random.key(6), tokens)
        return emb.apply(params, tokens), params["params"]["weight"]

    out, table = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(),),
        out_specs=(P(), P("tensor"))))(tokens)
    np.testing.assert_allclose(
        out, np.asarray(table)[np.asarray(tokens)], rtol=1e-6, atol=1e-6)


def test_sequence_parallel_column_row():
    """SP round trip: seq-sharded in -> Column(SP) -> Row(SP) -> seq-sharded
    out equals the dense computation."""
    seq = 8
    x = jax.random.normal(jax.random.key(8), (seq, BATCH, IN))
    col = tensor_parallel.ColumnParallelLinear(
        IN, OUT, gather_output=False, bias=False,
        sequence_parallel_enabled=True)
    row = tensor_parallel.RowParallelLinear(
        OUT, IN, input_is_parallel=True, bias=False,
        sequence_parallel_enabled=True)
    mesh = parallel_state.get_mesh()

    def body(x):
        pc = col.init(jax.random.key(9), x)
        h, _ = col.apply(pc, x)
        pr = row.init(jax.random.key(10), h)
        y, _ = row.apply(pr, h)
        return y, pc["params"]["weight"], pr["params"]["weight"]

    y, wc, wr = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("tensor"),),
        out_specs=(P("tensor"), P("tensor"), P(None, "tensor"))))(x)
    dense = np.asarray(x) @ np.asarray(wc).T @ np.asarray(wr).T
    np.testing.assert_allclose(y, dense, rtol=1e-5, atol=1e-5)


def test_param_attribute_helpers():
    import types
    p = types.SimpleNamespace()
    tensor_parallel.set_tensor_model_parallel_attributes(p, True, 0, 1)
    assert p.tensor_model_parallel and p.partition_dim == 0
    q = types.SimpleNamespace()
    tensor_parallel.copy_tensor_model_parallel_attributes(q, p)
    assert q.tensor_model_parallel
    r = types.SimpleNamespace()
    tensor_parallel.set_defaults_if_not_set_tensor_model_parallel_attributes(r)
    assert r.tensor_model_parallel is False and r.partition_dim == -1


def test_vocab_parallel_embedding_matmul_grad_matches_scatter():
    """grad_via_matmul must reproduce the scatter-add table grad exactly
    (fp32 here; the one-hot MXU contraction and the scatter sum the same
    dy rows per vocab id)."""
    vocab, dim = 16, 8
    tokens = jax.random.randint(jax.random.key(7), (BATCH, 5), 0, vocab)
    mesh = parallel_state.get_mesh()
    grads = {}
    for via_matmul in (False, True):
        emb = tensor_parallel.VocabParallelEmbedding(
            vocab, dim, grad_via_matmul=via_matmul)

        def body(tokens):
            params = emb.init(jax.random.key(6), tokens)

            def loss(p):
                y = emb.apply(p, tokens)
                return jnp.sum(y * (1.0 + jnp.arange(dim)))

            return jax.grad(loss)(params)["params"]["weight"]

        grads[via_matmul] = np.asarray(jax.jit(functools.partial(
            jax.shard_map, check_vma=False)(
                body, mesh=mesh, in_specs=(P(),),
                out_specs=P("tensor")))(tokens))
    np.testing.assert_allclose(grads[True], grads[False],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(grads[True]).sum() > 0      # grads actually flowed
