"""schedules/common machinery + parity shims (reference:
``pipeline_parallel/schedules/common.py``, ``apex/_autocast_utils.py``,
``amp_C.multi_tensor_l2norm_scale``)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import (
    backward_step,
    build_model,
    forward_step,
    listify_model,
)


@pytest.fixture
def pp4():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(
        pipeline_model_parallel_size_=4)
    yield
    parallel_state.destroy_model_parallel()


class TestBuildModel:
    def test_single_chunk(self):
        parallel_state.destroy_model_parallel()
        calls = []

        def provider(pre_process=False, post_process=False):
            calls.append((pre_process, post_process))
            return "model"

        models = build_model(provider)
        assert models == ["model"]
        assert calls == [(True, True)]          # pp=1: both ends
        assert listify_model(models) == ["model"]

    def test_virtual_chunks(self, pp4):
        """v=2: chunk 0 hosts virtual stage 0 (pre), chunk 1 the last
        virtual stage (post); rank masking happens at apply time."""
        calls = []

        def provider(pre_process=False, post_process=False):
            calls.append((pre_process, post_process))
            return len(calls) - 1

        models = build_model(
            provider, virtual_pipeline_model_parallel_size=2)
        assert models == [0, 1]
        assert calls == [(True, False), (False, True)]


class TestForwardBackwardStep:
    def _stage(self, p, x, mb):
        return jnp.tanh(x @ p["w"])

    def test_forward_and_backward_match_vjp(self):
        rng = np.random.RandomState(0)
        p = {"w": jnp.asarray(rng.randn(8, 8), jnp.float32)}
        x = jnp.asarray(rng.randn(4, 8), jnp.float32)
        y = forward_step(self._stage, p, x, None)
        np.testing.assert_allclose(
            np.asarray(y), np.tanh(np.asarray(x) @ np.asarray(p["w"])),
            rtol=1e-6)

        dy = jnp.ones_like(y)
        dx, dp = backward_step(self._stage, p, x, None, dy)
        # oracle via plain grad of sum
        want_dx, want_dp = jax.grad(
            lambda xx, pp: jnp.sum(self._stage(pp, xx, None)),
            argnums=(0, 1))(x, p)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(want_dx),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(dp["w"]),
                                   np.asarray(want_dp["w"]), rtol=1e-5)

    def test_forward_step_collects_loss(self):
        p = {"w": jnp.eye(4)}
        x = jnp.ones((2, 4))
        losses = []
        loss = forward_step(self._stage, p, x, None,
                            loss_fn=lambda y, mb: jnp.sum(y),
                            losses_reduced=losses)
        assert len(losses) == 1 and losses[0] is loss


class TestL2NormScale:
    def test_fused_matches_two_pass(self):
        from apex_tpu.multi_tensor_apply import multi_tensor_l2norm_scale
        rng = np.random.RandomState(1)
        ts = [jnp.asarray(rng.randn(1000), jnp.float32),
              jnp.asarray(rng.randn(77), jnp.float32)]
        outs, gnorm, per, flag = multi_tensor_l2norm_scale(
            0.0, [ts], 0.5, per_tensor=True)
        cat = np.concatenate([np.asarray(t) * 0.5 for t in ts])
        np.testing.assert_allclose(float(gnorm), np.linalg.norm(cat),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[0]),
                                   np.asarray(ts[0]) * 0.5, rtol=1e-6)
        assert per.shape == (2,)
        assert float(flag) == 0.0

    def test_flags_non_finite(self):
        from apex_tpu.multi_tensor_apply import multi_tensor_l2norm_scale
        ts = [jnp.asarray([1.0, jnp.inf, 3.0], jnp.float32)]
        _, _, _, flag = multi_tensor_l2norm_scale(0.0, [ts], 1.0)
        assert float(flag) == 1.0


class TestAutocastUtils:
    def test_cast_only_when_active(self):
        from apex_tpu._autocast_utils import _cast_if_autocast_enabled
        from apex_tpu.amp import amp as amp_mod
        x = jnp.ones((4,), jnp.float32)
        # isolate from any handle an earlier amp test left active
        saved = amp_mod._current_handle
        amp_mod._current_handle = None
        try:
            # inactive: passthrough
            (y,) = _cast_if_autocast_enabled(x)
            assert y.dtype == jnp.float32
            # active handle: fp32 -> bf16, bf16/int/non-array untouched
            handle = amp_mod.AmpHandle()
            amp_mod._current_handle = handle
            a, b, c, d = _cast_if_autocast_enabled(
                x, x.astype(jnp.bfloat16), jnp.arange(3), "s")
            assert a.dtype == jnp.bfloat16
            assert b.dtype == jnp.bfloat16
            assert c.dtype == jnp.int32
            assert d == "s"
        finally:
            amp_mod._current_handle = saved


def test_rnn_compat_probe():
    from apex_tpu.amp import rnn_compat
    assert rnn_compat.has_old_rnns() is False
    # since the O1 list-parity sweep the modern _VF dispatch point IS
    # patched (no longer a no-op): probe it, and exercise the patch
    # through a real handle (end-to-end cast coverage lives in
    # tests/L0/run_amp/test_patch_lists.py)
    assert rnn_compat.has_vf_rnns() is True
    import torch.nn.modules.rnn as rnn_mod

    from apex_tpu.amp import amp as amp_mod
    h = amp_mod.init()
    try:
        assert hasattr(rnn_mod._VF.lstm, "_amp_original")
    finally:
        h._deactivate()
    assert not hasattr(rnn_mod._VF.lstm, "_amp_original")
