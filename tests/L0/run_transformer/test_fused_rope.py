"""RoPE tests (reference: tests/L0/run_transformer/test_fused_rope.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.transformer.functional import (
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_thd,
)

S, B, H, D = 8, 2, 3, 16


def _freqs(s=S, d=D):
    inv = 1.0 / (10000 ** (jnp.arange(0, d, 2) / d))
    f = jnp.outer(jnp.arange(s), inv)
    return jnp.concatenate([f, f], axis=-1).reshape(s, 1, 1, d)


def test_cached_matches_uncached():
    t = jax.random.normal(jax.random.key(0), (S, B, H, D))
    freqs = _freqs()
    out = fused_apply_rotary_pos_emb(t, freqs)
    cached = fused_apply_rotary_pos_emb_cached(
        t, jnp.cos(freqs), jnp.sin(freqs))
    np.testing.assert_allclose(out, cached, rtol=1e-6)


def test_norm_preserved():
    """Rotations preserve pairwise norms."""
    t = jax.random.normal(jax.random.key(1), (S, B, H, D))
    out = fused_apply_rotary_pos_emb(t, _freqs())
    np.testing.assert_allclose(
        jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(t, axis=-1),
        rtol=1e-5)


def test_position_zero_is_identity():
    t = jax.random.normal(jax.random.key(2), (S, B, H, D))
    out = fused_apply_rotary_pos_emb(t, _freqs())
    np.testing.assert_allclose(out[0], t[0], rtol=1e-6, atol=1e-6)


def test_partial_rotation_passthrough():
    t = jax.random.normal(jax.random.key(3), (S, B, H, D))
    freqs = _freqs(d=D // 2)  # rotate only the first half of channels
    out = fused_apply_rotary_pos_emb(t, freqs)
    np.testing.assert_allclose(out[..., D // 2:], t[..., D // 2:])


def test_thd_matches_per_sequence_sbhd():
    """Packed varlen equals applying RoPE per sequence from position 0."""
    lens = [3, 5]
    cu = jnp.asarray([0, 3, 8])
    t = jax.random.normal(jax.random.key(4), (8, H, D))
    freqs = _freqs(s=8).reshape(8, 1, D)
    out = fused_apply_rotary_pos_emb_thd(t, cu, freqs.reshape(8, 1, 1, D))
    # oracle: each sequence restarts positions
    for seq_idx, (start, ln) in enumerate(zip([0, 3], lens)):
        seg = t[start:start + ln].reshape(ln, 1, H, D)
        ref = fused_apply_rotary_pos_emb(
            seg, freqs[:ln].reshape(ln, 1, 1, D))
        np.testing.assert_allclose(
            out[start:start + ln], ref.reshape(ln, H, D), rtol=1e-5,
            atol=1e-6)


def test_grad_flows():
    t = jax.random.normal(jax.random.key(5), (S, B, H, D))
    g = jax.grad(lambda t: jnp.sum(
        fused_apply_rotary_pos_emb(t, _freqs()) ** 2))(t)
    assert np.all(np.isfinite(np.asarray(g)))
