"""Torch-mode fused optimizers (reference canonical flows:
``FusedAdam(model.parameters())`` in imagenet ``main_amp.py``,
``FusedLAMB(...)`` in BERT phase 1).  The public classes must accept
torch parameters, behave as ``torch.optim.Optimizer``s, and match the
upstream-torch / JAX-kernel math they twin."""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD


def _model(seed=0):
    torch.manual_seed(seed)
    return torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 4))


def _clone(model):
    import copy
    return copy.deepcopy(model)


def _run(model, opt, steps=6, seed=1):
    torch.manual_seed(seed)
    X, Y = torch.randn(32, 8), torch.randn(32, 4)
    for _ in range(steps):
        opt.zero_grad()
        torch.nn.functional.mse_loss(model(X), Y).backward()
        opt.step()
    return [p.detach().clone() for p in model.parameters()]


def test_routing_torch_vs_jax():
    m = _model()
    opt = FusedAdam(m.parameters(), lr=1e-3)
    assert isinstance(opt, torch.optim.Optimizer)
    jopt = FusedAdam({"w": jnp.ones((4, 4))}, lr=1e-3)
    assert isinstance(jopt, FusedAdam)
    assert not isinstance(jopt, torch.optim.Optimizer)


def test_generator_params_accepted():
    m = _model()
    opt = FusedLAMB(m.parameters(), lr=1e-3)   # generator consumed once
    assert sum(len(g["params"]) for g in opt.param_groups) == 4


def test_no_torch_impl_raises_cleanly():
    from apex_tpu.optimizers.base import FusedOptimizerBase

    class _NoTwin(FusedOptimizerBase):
        def __init__(self, params):
            super().__init__(params, {})

    m = _model()
    with pytest.raises(TypeError, match="torch-mode"):
        _NoTwin(m.parameters())


def test_fused_novograd_torch_matches_jax():
    """Two steps (the second exercises the per-tensor ||g||2 EMA, the
    first its grad-seeded init) must match the jax class."""
    rng = np.random.default_rng(3)
    shapes = [(5, 4), (4,)]
    params_np = [rng.normal(size=s).astype(np.float32) for s in shapes]
    g1 = [rng.normal(size=s).astype(np.float32) * 0.1 for s in shapes]
    g2 = [rng.normal(size=s).astype(np.float32) * 0.1 for s in shapes]

    tp = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    topt = FusedNovoGrad(tp, lr=1e-2, weight_decay=0.01)
    for grads in (g1, g2):
        for p, g in zip(tp, grads):
            p.grad = torch.tensor(g)
        topt.step()

    jopt = FusedNovoGrad([jnp.asarray(p) for p in params_np], lr=1e-2,
                         weight_decay=0.01)
    jnew = jopt.step([jnp.asarray(g) for g in g1])
    jnew = jopt.step([jnp.asarray(g) for g in g2])
    for t, j in zip(tp, jnew):
        np.testing.assert_allclose(t.detach().numpy(), np.asarray(j),
                                   rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("w_mode", [False, True])
def test_fused_adagrad_torch_matches_jax(w_mode):
    from apex_tpu.optimizers import FusedAdagrad

    rng = np.random.default_rng(4)
    p_np = rng.normal(size=(6, 3)).astype(np.float32)
    g_np = rng.normal(size=(6, 3)).astype(np.float32) * 0.1

    tp = torch.nn.Parameter(torch.tensor(p_np))
    tp.grad = torch.tensor(g_np)
    topt = FusedAdagrad([tp], lr=1e-2, weight_decay=0.01,
                        adagrad_w_mode=w_mode)
    topt.step()

    jopt = FusedAdagrad([jnp.asarray(p_np)], lr=1e-2, weight_decay=0.01,
                        adagrad_w_mode=w_mode)
    jnew = jopt.step([jnp.asarray(g_np)])
    np.testing.assert_allclose(tp.detach().numpy(), np.asarray(jnew[0]),
                               rtol=2e-5, atol=2e-6)


def test_fused_mixed_precision_lamb_routes_with_step_arg():
    from apex_tpu.optimizers import FusedMixedPrecisionLamb

    m = _model()
    # positional `step` arg: inherited routing must not feed it into
    # the LAMB twin's bias_correction slot
    opt = FusedMixedPrecisionLamb(m.parameters(), 1e-3, 5)
    assert isinstance(opt, torch.optim.Optimizer)
    assert opt._initial_step == 5
    _run(m, opt, steps=2)
    assert all("step" in opt.state[p] and opt.state[p]["step"] >= 6
               for g in opt.param_groups for p in g["params"])


def test_fused_adam_matches_torch_adamw():
    ma, mb = _model(), _clone(_model())
    wd = 0.02
    pa = _run(ma, FusedAdam(ma.parameters(), lr=1e-2, weight_decay=wd))
    pb = _run(mb, torch.optim.AdamW(mb.parameters(), lr=1e-2,
                                    weight_decay=wd, eps=1e-8))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-5,
                                   atol=1e-7)


def test_fused_adam_l2_mode_matches_torch_adam():
    ma, mb = _model(), _clone(_model())
    wd = 0.02
    pa = _run(ma, FusedAdam(ma.parameters(), lr=1e-2, weight_decay=wd,
                            adam_w_mode=False))
    pb = _run(mb, torch.optim.Adam(mb.parameters(), lr=1e-2,
                                   weight_decay=wd, eps=1e-8))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-5,
                                   atol=1e-7)


def test_fused_sgd_matches_torch_sgd():
    ma, mb = _model(), _clone(_model())
    pa = _run(ma, FusedSGD(ma.parameters(), lr=0.05, momentum=0.9,
                           weight_decay=0.01))
    pb = _run(mb, torch.optim.SGD(mb.parameters(), lr=0.05, momentum=0.9,
                                  weight_decay=0.01))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-5,
                                   atol=1e-7)


def test_fused_sgd_wd_after_momentum_matches_across_paths():
    """wd_after_momentum changes the decay placement; the JAX kernel
    silently ignored it pre-r5.  Both entry points must honor it (the
    ordering only diverges from step 2 on, so run 3 steps), and the
    flag must actually change the update."""
    from apex_tpu.optimizers import FusedSGD

    rng = np.random.default_rng(4)
    p_np = rng.normal(size=(6, 5)).astype(np.float32)
    grads = [rng.normal(size=(6, 5)).astype(np.float32) for _ in range(3)]
    kw = dict(lr=1e-2, momentum=0.9, weight_decay=0.1)

    def run_torch(wd_after):
        tp = torch.nn.Parameter(torch.tensor(p_np))
        opt = FusedSGD([tp], wd_after_momentum=wd_after, **kw)
        for g in grads:
            tp.grad = torch.tensor(g)
            opt.step()
        return tp.detach().numpy()

    def run_jax(wd_after):
        jp = [jnp.asarray(p_np)]
        opt = FusedSGD(jp, wd_after_momentum=wd_after, **kw)
        for g in grads:
            jp = opt.step([jnp.asarray(g)])
        return np.asarray(jp[0])

    for wd_after in (False, True):
        np.testing.assert_allclose(run_torch(wd_after), run_jax(wd_after),
                                   rtol=2e-5, atol=2e-6)
    assert not np.allclose(run_jax(False), run_jax(True))


def test_fused_sgd_noop_skipped_first_step_is_pure_noop():
    """An amp overflow-skip on step 1 must leave the optimizer exactly
    where it started: the next effective step seeds the momentum buffer
    with d (torch clones into a FRESH buffer), not (1-dampening)*d —
    the step==1 proxy got this wrong when dampening != 0."""
    from apex_tpu.optimizers import FusedSGD

    rng = np.random.default_rng(5)
    p_np = rng.normal(size=(8,)).astype(np.float32)
    g1 = rng.normal(size=(8,)).astype(np.float32)
    g2 = rng.normal(size=(8,)).astype(np.float32)
    kw = dict(lr=1e-2, momentum=0.9, dampening=0.2, weight_decay=0.1)

    skip = FusedSGD([jnp.asarray(p_np)], **kw)
    ps = skip.step([jnp.asarray(g1)], noop_flag=1.0)   # overflow: no-op
    np.testing.assert_array_equal(np.asarray(ps[0]), p_np)
    ps = skip.step([jnp.asarray(g2)])

    fresh = FusedSGD([jnp.asarray(p_np)], **kw)
    pf = fresh.step([jnp.asarray(g2)])
    np.testing.assert_array_equal(np.asarray(ps[0]), np.asarray(pf[0]))


def test_fused_sgd_wd_after_momentum_per_group_torch_path():
    """Per-group wd_after_momentum overrides must reach the torch twin
    too (it treats the flag as a group option, like the jax class)."""
    rng = np.random.default_rng(6)
    p1n = rng.normal(size=(4, 3)).astype(np.float32)
    p2n = rng.normal(size=(3,)).astype(np.float32)
    g1n = rng.normal(size=(4, 3)).astype(np.float32)
    g2n = rng.normal(size=(3,)).astype(np.float32)
    kw = dict(lr=1e-2, momentum=0.9, weight_decay=0.1)
    from apex_tpu.optimizers import FusedSGD

    def run(override):
        p1 = torch.nn.Parameter(torch.tensor(p1n))
        p2 = torch.nn.Parameter(torch.tensor(p2n))
        groups = [{"params": [p1], **override}, {"params": [p2]}]
        opt = FusedSGD(groups, **kw)
        for _ in range(3):
            p1.grad, p2.grad = torch.tensor(g1n), torch.tensor(g2n)
            opt.step()
        return p1.detach().numpy(), p2.detach().numpy()

    base1, base2 = run({})
    ov1, ov2 = run({"wd_after_momentum": True})
    assert not np.allclose(base1, ov1)       # group 1 honors the override
    np.testing.assert_array_equal(base2, ov2)  # group 2 untouched


def test_fused_lamb_torch_matches_jax_kernel():
    """One step of the torch twin must equal the JAX `_lamb_step` kernel
    path on identical params/grads (numpy bridge, default knobs)."""
    rng = np.random.default_rng(0)
    shapes = [(6, 5), (5,), (5, 4), (4,)]
    params_np = [rng.normal(size=s).astype(np.float32) for s in shapes]
    grads_np = [rng.normal(size=s).astype(np.float32) * 0.1 for s in shapes]

    tparams = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    for p, g in zip(tparams, grads_np):
        p.grad = torch.tensor(g)
    topt = FusedLAMB(tparams, lr=1e-2, weight_decay=0.01)
    topt.step()

    jparams = [jnp.asarray(p) for p in params_np]
    jgrads = [jnp.asarray(g) for g in grads_np]
    jopt = FusedLAMB(jparams, lr=1e-2, weight_decay=0.01)
    jnew = jopt.step(jgrads)

    for t, j in zip(tparams, jnew):
        np.testing.assert_allclose(t.detach().numpy(), np.asarray(j),
                                   rtol=2e-5, atol=2e-6)


def test_fused_lamb_global_norm_clip_matches_jax():
    rng = np.random.default_rng(1)
    shapes = [(10, 3), (3,)]
    params_np = [rng.normal(size=s).astype(np.float32) for s in shapes]
    grads_np = [rng.normal(size=s).astype(np.float32) * 5.0 for s in shapes]

    tparams = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
    for p, g in zip(tparams, grads_np):
        p.grad = torch.tensor(g)
    topt = FusedLAMB(tparams, lr=1e-2, max_grad_norm=1.0)
    topt.step()

    jopt = FusedLAMB([jnp.asarray(p) for p in params_np], lr=1e-2,
                     max_grad_norm=1.0)
    jnew = jopt.step([jnp.asarray(g) for g in grads_np])
    for t, j in zip(tparams, jnew):
        np.testing.assert_allclose(t.detach().numpy(), np.asarray(j),
                                   rtol=2e-5, atol=2e-6)


def test_fused_lamb_torch_clips_by_global_norm_across_groups():
    """The reference FusedLAMB computes ONE grad norm across ALL param
    groups (the BERT decay/no-decay split depends on it).  With identical
    hyperparams, a two-group construction must therefore update each
    param exactly as the single-group construction does; a per-group
    clip would scale the two groups differently."""
    rng = np.random.default_rng(3)
    shapes = [(12, 4), (4,), (4, 6), (6,)]
    params_np = [rng.normal(size=s).astype(np.float32) for s in shapes]
    # big grads so the clip actually engages, asymmetric between groups
    grads_np = [rng.normal(size=s).astype(np.float32) * (9.0 if i < 2 else 0.3)
                for i, s in enumerate(shapes)]

    def run(groups):
        tparams = [torch.nn.Parameter(torch.tensor(p)) for p in params_np]
        for p, g in zip(tparams, grads_np):
            p.grad = torch.tensor(g)
        if groups == 1:
            opt = FusedLAMB(tparams, lr=1e-2, weight_decay=0.01,
                            max_grad_norm=1.0)
        else:
            opt = FusedLAMB([{"params": tparams[:2]},
                             {"params": tparams[2:]}],
                            lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
        opt.step()
        return [p.detach().numpy() for p in tparams]

    one, two = run(1), run(2)
    for a, b in zip(one, two):
        np.testing.assert_array_equal(a, b)


def test_fused_lamb_grad_averaging_false_matches_jax():
    """grad_averaging=False (m += g, not (1-b1)*g) must take effect on
    BOTH entry points — the jax path silently dropped the flag pre-r4."""
    rng = np.random.default_rng(2)
    params_np = [rng.normal(size=(4, 3)).astype(np.float32)]
    grads_np = [rng.normal(size=(4, 3)).astype(np.float32) * 0.1]

    # wd != 0 matters: at wd=0 LAMB's trust ratio makes a single step
    # invariant to uniform scalings of the adam direction, so the flag
    # would be invisible on step 1
    tp = [torch.nn.Parameter(torch.tensor(params_np[0]))]
    tp[0].grad = torch.tensor(grads_np[0])
    topt = FusedLAMB(tp, lr=1e-2, weight_decay=0.01, grad_averaging=False)
    topt.step()

    jopt = FusedLAMB([jnp.asarray(params_np[0])], lr=1e-2,
                     weight_decay=0.01, grad_averaging=False)
    jnew = jopt.step([jnp.asarray(grads_np[0])])
    np.testing.assert_allclose(tp[0].detach().numpy(),
                               np.asarray(jnew[0]), rtol=2e-5, atol=2e-6)
    # and the flag actually changes the update
    jopt2 = FusedLAMB([jnp.asarray(params_np[0])], lr=1e-2,
                      weight_decay=0.01, grad_averaging=True)
    jnew2 = jopt2.step([jnp.asarray(grads_np[0])])
    assert not np.allclose(np.asarray(jnew[0]), np.asarray(jnew2[0]))


def test_empty_first_group_still_routes_to_torch():
    m = _model()
    opt = FusedAdam([{"params": []},
                     {"params": list(m.parameters())}], lr=1e-3)
    assert isinstance(opt, torch.optim.Optimizer)


def test_load_state_dict_keeps_fp32_master():
    torch.manual_seed(0)
    p = torch.nn.Parameter(torch.randn(16, 16).bfloat16())
    opt = FusedAdam([p], lr=1e-3)
    p.grad = torch.randn_like(p)
    opt.step()
    sd = opt.state_dict()
    p2 = torch.nn.Parameter(p.detach().clone())
    opt2 = FusedAdam([p2], lr=1e-3)
    p2.grad = torch.randn_like(p2)
    opt2.step()
    opt2.load_state_dict(sd)
    st = opt2.state[p2]
    src = opt.state[p]
    # torch's load casts floating state to the param dtype (bf16) BEFORE
    # any override runs; the override must restore the VALUES from the
    # incoming state_dict, not just upcast the demoted tensors — a
    # dtype-only restore would leave master == bf16-rounded master
    for k in ("master", "exp_avg", "exp_avg_sq"):
        assert st[k].dtype == torch.float32, k
        assert torch.equal(st[k], src[k]), k
    assert not torch.equal(st["master"],
                           st["master"].bfloat16().float()) \
        or torch.equal(src["master"], src["master"].bfloat16().float())


def test_adagrad_sum_stays_fp32_after_load():
    from apex_tpu.optimizers import FusedAdagrad

    p = torch.nn.Parameter(torch.randn(8, 8).bfloat16())
    opt = FusedAdagrad([p], lr=1e-2)
    p.grad = torch.randn_like(p)
    opt.step()
    sd = opt.state_dict()
    p2 = torch.nn.Parameter(p.detach().clone())
    opt2 = FusedAdagrad([p2], lr=1e-2)
    p2.grad = torch.randn_like(p2)
    opt2.step()
    opt2.load_state_dict(sd)
    assert opt2.state[p2]["sum"].dtype == torch.float32


def test_half_params_keep_fp32_masters():
    torch.manual_seed(0)
    p = torch.nn.Parameter(torch.randn(32, 32).bfloat16())
    opt = FusedAdam([p], lr=1e-3)
    for _ in range(3):
        p.grad = torch.randn_like(p)
        opt.step()
    st = opt.state[p]
    assert st["master"].dtype == torch.float32
    assert p.dtype == torch.bfloat16
    np.testing.assert_allclose(st["master"].to(torch.bfloat16).float(),
                               p.detach().float(), atol=1e-2)


def test_amp_o2_with_fused_adam_end_to_end():
    """The reference imagenet flow: amp O2 + FusedAdam(model.parameters())
    + scale_loss/backward/step, unmodified."""
    from apex_tpu import amp
    model = _model()
    opt = FusedAdam(model.parameters(), lr=2e-2)
    model, opt = amp.initialize(model, opt, opt_level="O2")
    torch.manual_seed(1)
    X, Y = torch.randn(64, 8), torch.randn(64, 4)
    losses = []
    for _ in range(40):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(X).float(), Y)
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        losses.append(loss.item())
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_state_dict_roundtrip():
    m = _model()
    opt = FusedAdam(m.parameters(), lr=1e-2)
    _run(m, opt, steps=2)
    sd = opt.state_dict()
    m2 = _clone(_model())
    opt2 = FusedAdam(m2.parameters(), lr=1e-2)
    _run(m2, opt2, steps=2)
    opt2.load_state_dict(sd)
    # states equal after load
    for (k1, v1), (k2, v2) in zip(sorted(opt.state_dict()["state"].items()),
                                  sorted(opt2.state_dict()["state"].items())):
        assert k1 == k2
        np.testing.assert_allclose(v1["exp_avg"].numpy(),
                                   v2["exp_avg"].numpy())
