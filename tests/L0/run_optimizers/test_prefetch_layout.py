"""ZeRO layered-prefetch shard layout (``FlatState.spans``): the
span-wise split of the flat master along leaf boundaries that lets the
zero step's param gather decompose into independent per-span
all-gathers (ISSUE 7 comm/compute overlap).

Covers the pure layout algebra (span grouping, enspan/despan
round-trip, per-rank leaf windows) and the sharded-state semantics
(init slicing, ``params()`` reassembly, ``shard_flat_grads``, the
LAMB/NovoGrad per-leaf machinery over interior padding gaps) — the
step-level on/off parity lives in ``tests/L1/test_overlap.py``.
"""
import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.optimizers import functional
from apex_tpu.optimizers.base import (prefetch_leaf_spans,
                                      sharded_leaf_broadcast,
                                      sharded_leaf_sq_norms)
from apex_tpu.optimizers.functional import (_enspan, prefetch_span_layout)
from apex_tpu.utils import tree_ravel

shard_map = functools.partial(jax.shard_map, check_vma=False)


def _params(seed=0):
    """Deliberately odd leaf sizes: every dp pads, some spans pad
    interior (the layout's hard case)."""
    rng = np.random.RandomState(seed)
    return {
        "w0": jnp.asarray(rng.randn(13, 15) * 0.4, jnp.float32),
        "b0": jnp.asarray(rng.randn(15) * 0.01, jnp.float32),
        "w1": jnp.asarray(rng.randn(15, 11) * 0.4, jnp.float32),
        "b1": jnp.asarray(rng.randn(11) * 0.01, jnp.float32),
        "head": jnp.asarray(rng.randn(3), jnp.float32),
    }


def test_prefetch_span_layout_groups_leaves():
    sizes = (64, 8) * 8                  # 8 homogeneous layers
    spans = prefetch_span_layout(sizes, 8)
    assert sum(spans) == len(sizes)
    assert spans == (2,) * 8             # one (w, b) pair per span
    # k > leaves clamps; k <= 1 stays one span
    assert sum(prefetch_span_layout(sizes, 99)) == len(sizes)
    assert prefetch_span_layout(sizes, 1) == (len(sizes),)


def test_enspan_despan_roundtrip_all_dp():
    params = _params()
    flat, _ = tree_ravel(params)
    sizes = tuple(int(x.size) for x in jax.tree_util.tree_leaves(params))
    for dp in (1, 2, 3, 4):
        for k in (2, 3, 5):
            spans = prefetch_span_layout(sizes, k)
            state = functional.FlatState(
                master=flat, count=jnp.zeros(()), slots={},
                sizes=sizes, shard=("data", dp), spans=spans)
            packed = _enspan(flat, state.span_sizes, state.span_padded,
                             dp)
            assert packed.shape[0] == state.padded_numel
            assert state.padded_numel % dp == 0
            out = state.replace(master=packed)._despan(packed)
            np.testing.assert_array_equal(np.asarray(out),
                                          np.asarray(flat))


def test_prefetch_leaf_spans_cover_exactly_the_leaves():
    sizes = [195, 15, 165, 11, 3]
    for dp in (2, 4):
        for k in (2, 3):
            span_leaves = prefetch_span_layout(sizes, k)
            spans = prefetch_leaf_spans(sizes, span_leaves, dp)
            assert len(spans) == dp
            # every leaf's elements appear exactly once across ranks
            counts = {i: 0 for i in range(len(sizes))}
            for rs in spans:
                for i, lo, hi in rs:
                    assert hi > lo
                    counts[i] += hi - lo
            assert counts == {i: s for i, s in enumerate(sizes)}


def test_sharded_leaf_helpers_match_dense_over_span_layout():
    """Per-leaf sq-norms and scalar broadcast over the span layout
    (interior padding gaps) reassemble to the dense answers."""
    params = _params()
    flat, _ = tree_ravel(params)
    sizes = tuple(int(x.size) for x in jax.tree_util.tree_leaves(params))
    dense_sq = np.asarray([float(jnp.sum(jnp.square(
        jax.lax.dynamic_slice_in_dim(flat, o, s))))
        for o, s in zip(np.cumsum((0,) + sizes[:-1]), sizes)])
    scalars = jnp.arange(1.0, len(sizes) + 1.0, dtype=jnp.float32)

    for dp in (2, 4):
        span_leaves = prefetch_span_layout(sizes, 3)
        spans = prefetch_leaf_spans(sizes, span_leaves, dp)
        state = functional.FlatState(
            master=flat, count=jnp.zeros(()), slots={},
            sizes=sizes, shard=("data", dp), spans=span_leaves)
        packed = np.asarray(_enspan(flat, state.span_sizes,
                                    state.span_padded, dp))
        lt = state.shard_len
        total = np.zeros(len(sizes), np.float32)
        for r in range(dp):
            shard = jnp.asarray(packed[r * lt:(r + 1) * lt])
            sq = sharded_leaf_sq_norms(
                (shard,), sizes, dp=dp, shard_len=lt,
                rank=jnp.int32(r), spans=span_leaves)
            total += np.asarray(sq[0])
            # broadcast: covered positions carry their leaf's scalar,
            # padding gaps the pad value
            bc = np.asarray(sharded_leaf_broadcast(
                scalars, sizes, dp=dp, shard_len=lt,
                rank=jnp.int32(r), pad_value=-1.0, spans=span_leaves))
            expect = np.full((lt,), -1.0, np.float32)
            for i, lo, hi in spans[r]:
                expect[lo:hi] = float(scalars[i])
            np.testing.assert_array_equal(bc, expect)
        np.testing.assert_allclose(total, dense_sq, rtol=1e-6)


def test_sharded_leaf_helpers_large_dp_fallback_matches_switch():
    """Above ``_SWITCH_MAX_DP`` the per-leaf helpers swap the
    lax.switch-over-ranks path for the bounded-compile global-buffer
    path — for the span layout too (the spans override must not
    silently reintroduce the O(dp·n_leaves) switch the guard bounds).
    Both paths must agree exactly."""
    import apex_tpu.optimizers.base as base
    params = _params()
    flat, _ = tree_ravel(params)
    sizes = tuple(int(x.size) for x in jax.tree_util.tree_leaves(params))
    scalars = jnp.arange(1.0, len(sizes) + 1.0, dtype=jnp.float32)
    dp = 4
    span_leaves = prefetch_span_layout(sizes, 3)
    spans = prefetch_leaf_spans(sizes, span_leaves, dp)
    state = functional.FlatState(
        master=flat, count=jnp.zeros(()), slots={},
        sizes=sizes, shard=("data", dp), spans=span_leaves)
    packed = np.asarray(_enspan(flat, state.span_sizes,
                                state.span_padded, dp))
    lt = state.shard_len
    saved = base._SWITCH_MAX_DP
    try:
        for r in range(dp):
            shard = jnp.asarray(packed[r * lt:(r + 1) * lt])
            args = dict(dp=dp, shard_len=lt, rank=jnp.int32(r),
                        spans=span_leaves)
            base._SWITCH_MAX_DP = 32          # switch path
            sq_sw = sharded_leaf_sq_norms((shard,), sizes, **args)
            bc_sw = sharded_leaf_broadcast(scalars, sizes,
                                           pad_value=-1.0, **args)
            base._SWITCH_MAX_DP = 1           # global-buffer fallback
            sq_fb = sharded_leaf_sq_norms((shard,), sizes, **args)
            bc_fb = sharded_leaf_broadcast(scalars, sizes,
                                           pad_value=-1.0, **args)
            np.testing.assert_allclose(np.asarray(sq_fb),
                                       np.asarray(sq_sw), rtol=1e-6)
            np.testing.assert_array_equal(np.asarray(bc_fb),
                                          np.asarray(bc_sw))
            # block layout's fallback keeps agreeing too
            blk = dict(dp=dp, shard_len=lt, rank=jnp.int32(r))
            pad = dp * lt - sum(sizes)
            blk_shard = jnp.asarray(np.concatenate(
                [np.asarray(flat), np.zeros(pad, np.float32)])
                [r * lt:(r + 1) * lt])
            base._SWITCH_MAX_DP = 32
            sq_sw = sharded_leaf_sq_norms((blk_shard,), sizes, **blk)
            bc_sw = sharded_leaf_broadcast(scalars, sizes,
                                           pad_value=-1.0, **blk)
            base._SWITCH_MAX_DP = 1
            sq_fb = sharded_leaf_sq_norms((blk_shard,), sizes, **blk)
            bc_fb = sharded_leaf_broadcast(scalars, sizes,
                                           pad_value=-1.0, **blk)
            np.testing.assert_allclose(np.asarray(sq_fb),
                                       np.asarray(sq_sw), rtol=1e-6)
            np.testing.assert_array_equal(np.asarray(bc_fb),
                                          np.asarray(bc_sw))
    finally:
        base._SWITCH_MAX_DP = saved


def test_init_and_params_roundtrip_span_layout():
    params = _params()
    tx = functional.fused_adam(lr=1e-3)
    for dp in (2, 4):
        for rank in range(dp):
            st = tx.init(params, shard=("data", dp, rank), prefetch=3)
            assert st.spans and st.master.shape[0] == st.shard_len
        # global view: init on the full padded buffer, params() inverts
        # the rank-major permutation without a mesh
        from apex_tpu import train_step
        state, specs = train_step.init_zero_train_state(
            tx, params, "data", dp, prefetch=3)
        assert state.opt.spans
        out = state.params()
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), out, params)
        # spec tree still marks exactly the dp-shardable buffers
        padded = state.opt.padded_numel
        for leaf, spec in zip(jax.tree.leaves(state),
                              jax.tree.leaves(
                                  jax.tree.map(
                                      lambda s: s, specs,
                                      is_leaf=lambda x: isinstance(x, P)))):
            assert (spec == P("data")) == (
                leaf.ndim == 1 and leaf.shape[0] == padded)


def test_shard_flat_grads_span_layout_matches_block():
    """The ZeRO-2 grad reduce-scatter under the span layout lands each
    rank the same VALUES as the block layout, just permuted into the
    span windows — reassembling both through params()-style despan
    yields identical full gradients."""
    params = _params()
    n = int(tree_ravel(params)[0].size)
    tx = functional.fused_adam(lr=1e-3)
    rng = np.random.RandomState(7)
    g_ranks = [jnp.asarray(rng.randn(n), jnp.float32) for _ in range(2)]
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def run(prefetch):
        def body(gstack):
            st = tx.init(params, shard=("data", 2), prefetch=prefetch)
            rank = jax.lax.axis_index("data")
            gshard = functional.shard_flat_grads(gstack[rank], st)
            return gshard

        gstack = jnp.stack(g_ranks)
        return np.asarray(jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(),),
            out_specs=P("data")))(gstack))

    mean = (np.asarray(g_ranks[0]) + np.asarray(g_ranks[1])) / 2
    block = run(0)
    np.testing.assert_allclose(block[:n], mean, rtol=1e-6, atol=1e-7)
    spanned = run(3)
    # reassemble the span-layout result through _despan
    st = tx.init(params, shard=("data", 2, 0), prefetch=3)
    full = np.asarray(st.replace(
        master=jnp.asarray(spanned))._despan(jnp.asarray(spanned)))
    np.testing.assert_allclose(full, mean, rtol=1e-6, atol=1e-7)
