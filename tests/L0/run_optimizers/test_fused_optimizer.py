"""Fused optimizer vs oracle tests.

Mirrors ``tests/L0/run_optimizers/test_fused_optimizer.py`` in the reference:
every fused optimizer is stepped against a pure reference implementation
(torch.optim semantics) and must match within dtype tolerances.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.ops.fused_update import (
    adam_reference, fused_adam_flat, fused_axpby, fused_l2norm, fused_scale,
)
from apex_tpu.optimizers import (
    FusedAdagrad, FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD,
)


def _params(seed=0, sizes=((37,), (128, 129), (5, 7, 11), (1000,))):
    rng = np.random.RandomState(seed)
    return {f"p{i}": jnp.asarray(rng.randn(*s), jnp.float32)
            for i, s in enumerate(sizes)}


def _grads(seed=1, sizes=((37,), (128, 129), (5, 7, 11), (1000,))):
    return _params(seed, sizes)


class TestKernels:
    def test_scale(self):
        x = jnp.asarray(np.random.RandomState(0).randn(5000), jnp.float32)
        out, flag = jax.jit(fused_scale)(x, 0.25)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * 0.25,
                                   rtol=1e-6)
        assert float(flag) == 0.0

    def test_scale_detects_inf(self):
        x = jnp.asarray([1.0, jnp.inf, 3.0], jnp.float32)
        _, flag = jax.jit(fused_scale)(x, 1.0)
        assert float(flag) == 1.0

    def test_scale_detects_nan(self):
        x = jnp.asarray([1.0, jnp.nan, 3.0], jnp.float32)
        _, flag = jax.jit(fused_scale)(x, 1.0)
        assert float(flag) == 1.0

    def test_axpby(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3000), jnp.float32)
        y = jnp.asarray(rng.randn(3000), jnp.float32)
        out, flag = jax.jit(fused_axpby)(2.0, x, -0.5, y)
        np.testing.assert_allclose(np.asarray(out),
                                   2.0 * np.asarray(x) - 0.5 * np.asarray(y),
                                   rtol=1e-6)
        assert float(flag) == 0.0

    def test_l2norm(self):
        x = jnp.asarray(np.random.RandomState(0).randn(70001), jnp.float32)
        got = jax.jit(fused_l2norm)(x)
        np.testing.assert_allclose(float(got),
                                   float(np.linalg.norm(np.asarray(x))),
                                   rtol=1e-5)

    @pytest.mark.parametrize("adam_w", [True, False])
    def test_adam_kernel_vs_oracle(self, adam_w):
        rng = np.random.RandomState(0)
        n = 10_000
        p = jnp.asarray(rng.randn(n), jnp.float32)
        g = jnp.asarray(rng.randn(n), jnp.float32)
        m = jnp.asarray(rng.rand(n), jnp.float32)
        v = jnp.asarray(rng.rand(n), jnp.float32)
        kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                  weight_decay=0.01, step=3, adam_w_mode=adam_w)
        po, mo, vo = jax.jit(
            lambda *a: fused_adam_flat(*a, **kw))(p, g, m, v)
        pr, mr, vr = adam_reference(p, g, m, v, **kw)
        np.testing.assert_allclose(np.asarray(po), np.asarray(pr), atol=1e-6)
        np.testing.assert_allclose(np.asarray(mo), np.asarray(mr), atol=1e-6)
        np.testing.assert_allclose(np.asarray(vo), np.asarray(vr), atol=1e-6)

    def test_adam_noop_flag_skips(self):
        rng = np.random.RandomState(0)
        p = jnp.asarray(rng.randn(500), jnp.float32)
        g = jnp.asarray(rng.randn(500), jnp.float32)
        m = jnp.zeros(500, jnp.float32)
        v = jnp.zeros(500, jnp.float32)
        po, mo, vo = fused_adam_flat(
            p, g, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
            weight_decay=0.0, step=1, noop_flag=1.0)
        np.testing.assert_array_equal(np.asarray(po), np.asarray(p))
        np.testing.assert_array_equal(np.asarray(mo), np.asarray(m))


def _torch_steps(torch_opt_cls, params, grads_seq, **kw):
    tparams = [torch.nn.Parameter(torch.tensor(np.asarray(v)))
               for v in params.values()]
    opt = torch_opt_cls(tparams, **kw)
    for grads in grads_seq:
        for tp, gv in zip(tparams, grads.values()):
            tp.grad = torch.tensor(np.asarray(gv))
        opt.step()
    return [tp.detach().numpy() for tp in tparams]


class TestFusedAdam:
    def test_vs_torch_adamw(self):
        params = _params()
        opt = FusedAdam(params, lr=3e-3, weight_decay=0.05, adam_w_mode=True)
        grads_seq = [_grads(seed=s) for s in range(1, 6)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _torch_steps(torch.optim.AdamW, params, grads_seq,
                                lr=3e-3, weight_decay=0.05)
        for got, exp in zip(out.values(), expected):
            np.testing.assert_allclose(np.asarray(got).ravel(), exp.ravel(),
                                       atol=2e-5)

    def test_vs_torch_adam_l2(self):
        params = _params()
        opt = FusedAdam(params, lr=1e-2, weight_decay=0.1, adam_w_mode=False)
        grads_seq = [_grads(seed=s) for s in range(1, 4)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _torch_steps(torch.optim.Adam, params, grads_seq,
                                lr=1e-2, weight_decay=0.1)
        for got, exp in zip(out.values(), expected):
            np.testing.assert_allclose(np.asarray(got).ravel(), exp.ravel(),
                                       atol=2e-5)

    def test_param_groups(self):
        pa, pb = _params(0, ((64,),)), _params(1, ((32, 8),))
        opt = FusedAdam([{"params": pa, "lr": 1e-2},
                         {"params": pb, "lr": 1e-4}], lr=1e-3)
        ga, gb = _grads(2, ((64,),)), _grads(3, ((32, 8),))
        outa, outb = opt.step([ga, gb])
        assert not np.allclose(np.asarray(outa["p0"]), np.asarray(pa["p0"]))
        # smaller lr -> smaller step
        da = np.abs(np.asarray(outa["p0"]) - np.asarray(pa["p0"])).mean()
        db = np.abs(np.asarray(outb["p0"]) - np.asarray(pb["p0"])).mean()
        assert da > db

    def test_state_dict_roundtrip(self):
        params = _params()
        opt = FusedAdam(params, lr=1e-3)
        g = _grads()
        opt.step(g)
        sd = opt.state_dict()
        opt2 = FusedAdam(params, lr=1e-3)
        opt2.load_state_dict(sd)
        out1 = opt.step(g)
        out2 = opt2.step(g)
        for a, b in zip(out1.values(), out2.values()):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_grad_scale_matches_prescaled(self):
        params = _params()
        g = _grads()
        opt1 = FusedAdam(params, lr=1e-3)
        out1 = opt1.step(jax.tree.map(lambda x: x * 8.0, g), grad_scale=0.125)
        opt2 = FusedAdam(params, lr=1e-3)
        out2 = opt2.step(g)
        for a, b in zip(out1.values(), out2.values()):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)


class TestFusedSGD:
    @pytest.mark.parametrize("momentum,nesterov,wd",
                             [(0.0, False, 0.0), (0.9, False, 0.0),
                              (0.9, True, 0.0), (0.9, False, 0.01)])
    def test_vs_torch_sgd(self, momentum, nesterov, wd):
        params = _params()
        opt = FusedSGD(params, lr=0.05, momentum=momentum, nesterov=nesterov,
                       weight_decay=wd)
        grads_seq = [_grads(seed=s) for s in range(1, 5)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _torch_steps(torch.optim.SGD, params, grads_seq, lr=0.05,
                                momentum=momentum, nesterov=nesterov,
                                weight_decay=wd)
        for got, exp in zip(out.values(), expected):
            np.testing.assert_allclose(np.asarray(got).ravel(), exp.ravel(),
                                       atol=1e-5)


class TestFusedAdagrad:
    def test_vs_torch_adagrad(self):
        params = _params()
        opt = FusedAdagrad(params, lr=0.1, eps=1e-10, weight_decay=0.01)
        grads_seq = [_grads(seed=s) for s in range(1, 4)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _torch_steps(torch.optim.Adagrad, params, grads_seq,
                                lr=0.1, eps=1e-10, weight_decay=0.01)
        for got, exp in zip(out.values(), expected):
            np.testing.assert_allclose(np.asarray(got).ravel(), exp.ravel(),
                                       atol=1e-5)


def _lamb_reference_numpy(params, grads_seq, lr, betas, eps, wd,
                          max_grad_norm=1.0):
    """Pure-numpy LAMB oracle (mirrors the reference test's in-test Lamb)."""
    ps = {k: np.asarray(v, np.float64) for k, v in params.items()}
    ms = {k: np.zeros_like(v) for k, v in ps.items()}
    vs = {k: np.zeros_like(v) for k, v in ps.items()}
    b1, b2 = betas
    t = 0
    for grads in grads_seq:
        t += 1
        gs = {k: np.asarray(v, np.float64) for k, v in grads.items()}
        gnorm = np.sqrt(sum(float((g * g).sum()) for g in gs.values()))
        clip = max_grad_norm / (gnorm + 1e-6) \
            if (max_grad_norm > 0 and gnorm > max_grad_norm) else 1.0
        for k in ps:
            g = gs[k] * clip
            ms[k] = b1 * ms[k] + (1 - b1) * g
            vs[k] = b2 * vs[k] + (1 - b2) * g * g
            mhat = ms[k] / (1 - b1 ** t)
            vhat = vs[k] / (1 - b2 ** t)
            u = mhat / (np.sqrt(vhat) + eps) + wd * ps[k]
            wn = np.linalg.norm(ps[k])
            un = np.linalg.norm(u)
            ratio = wn / un if (wn > 0 and un > 0) else 1.0
            ps[k] = ps[k] - lr * ratio * u
    return ps


class TestFusedLAMB:
    def test_vs_numpy_lamb(self):
        params = _params()
        lr, betas, eps, wd = 1e-2, (0.9, 0.999), 1e-6, 0.01
        opt = FusedLAMB(params, lr=lr, betas=betas, eps=eps, weight_decay=wd,
                        max_grad_norm=1.0)
        grads_seq = [_grads(seed=s) for s in range(1, 4)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _lamb_reference_numpy(params, grads_seq, lr, betas, eps,
                                         wd)
        for k in out:
            np.testing.assert_allclose(np.asarray(out[k]), expected[k],
                                       atol=2e-5)


def _novograd_reference_numpy(params, grads_seq, lr, betas, eps, wd,
                              grad_averaging=True, bias_correction=True):
    ps = {k: np.asarray(v, np.float64) for k, v in params.items()}
    ms = {k: np.zeros_like(v) for k, v in ps.items()}
    vs = {k: 0.0 for k in ps}
    b1, b2 = betas
    t = 0
    for grads in grads_seq:
        t += 1
        for k in ps:
            g = np.asarray(grads[k], np.float64)
            gsq = float((g * g).sum())
            vs[k] = gsq if t == 1 else b2 * vs[k] + (1 - b2) * gsq
            ghat = g / (np.sqrt(vs[k]) + eps) + wd * ps[k]
            coef = (1 - b1) if grad_averaging else 1.0
            ms[k] = b1 * ms[k] + coef * ghat
            step_size = lr / (1 - b1 ** t) if bias_correction else lr
            ps[k] = ps[k] - step_size * ms[k]
    return ps


class TestFusedNovoGrad:
    def test_vs_numpy_novograd(self):
        params = _params()
        lr, betas, eps, wd = 1e-2, (0.95, 0.98), 1e-8, 0.01
        opt = FusedNovoGrad(params, lr=lr, betas=betas, eps=eps,
                            weight_decay=wd)
        grads_seq = [_grads(seed=s) for s in range(1, 4)]
        out = params
        for g in grads_seq:
            out = opt.step(g)
        expected = _novograd_reference_numpy(params, grads_seq, lr, betas,
                                             eps, wd)
        for k in out:
            np.testing.assert_allclose(np.asarray(out[k]), expected[k],
                                       atol=2e-5)


class TestMultiTensorApply:
    def test_applier_scale(self):
        from apex_tpu.multi_tensor_apply import (
            multi_tensor_applier, multi_tensor_scale)
        xs = [jnp.ones((16,)), jnp.full((4, 4), 2.0)]
        outs, flag = multi_tensor_applier(multi_tensor_scale, 0.0, [xs], 0.5)
        np.testing.assert_allclose(np.asarray(outs[0]), 0.5)
        np.testing.assert_allclose(np.asarray(outs[1]), 1.0)
        assert float(flag) == 0.0


class TestEmptyBuffers:
    """Zero-length flat buffers must not read uninitialized SMEM (the grid
    would be empty, skipping the flag/accumulator init)."""

    def test_fused_scale_empty(self):
        from apex_tpu.ops.fused_update import fused_scale
        out, flag = fused_scale(jnp.zeros((0,), jnp.float32), 2.0)
        assert out.shape == (0,)
        assert float(flag) == 0.0

    def test_fused_axpby_empty(self):
        from apex_tpu.ops.fused_update import fused_axpby
        out, flag = fused_axpby(1.0, jnp.zeros((0,), jnp.float32),
                                2.0, jnp.zeros((0,), jnp.float32))
        assert out.shape == (0,)
        assert float(flag) == 0.0

    def test_fused_l2norm_empty(self):
        from apex_tpu.ops.fused_update import fused_l2norm
        assert float(fused_l2norm(jnp.zeros((0,), jnp.float32))) == 0.0

    def test_odd_sizes_match_reference(self):
        from apex_tpu.ops.fused_update import fused_l2norm, fused_scale
        for n in (1, 127, 129, 65537):
            x = jnp.arange(n, dtype=jnp.float32) % 13 - 6.0
            np.testing.assert_allclose(
                float(fused_l2norm(x)), float(jnp.linalg.norm(x)),
                rtol=1e-5)
            out, flag = fused_scale(x, 3.0)
            np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
            assert float(flag) == 0.0


class TestBroadcastLeafScalars:
    """The repeat-free per-leaf broadcast (r5: jnp.repeat's gather
    lowering measured seconds per call on TPU; this helper replaced it
    in LAMB/NovoGrad and must stay exactly equivalent)."""

    def test_matches_jnp_repeat(self):
        from apex_tpu.optimizers.base import broadcast_leaf_scalars
        sizes = (1, 7, 128, 1000, 3)
        scal = jnp.arange(len(sizes), dtype=jnp.float32) * 0.5 - 1.0
        got = jax.jit(lambda s: broadcast_leaf_scalars(s, sizes))(scal)
        ref = jnp.repeat(scal, jnp.asarray(sizes),
                         total_repeat_length=sum(sizes))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_empty(self):
        from apex_tpu.optimizers.base import broadcast_leaf_scalars
        out = broadcast_leaf_scalars(jnp.zeros((0,), jnp.float32), ())
        assert out.shape == (0,)
