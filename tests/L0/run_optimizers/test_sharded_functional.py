"""ZeRO dp-sharding of the functional optimizer core (ISSUE 3).

Properties pinned here:

1. **Shard layout.** ``tx.init(params, shard=(axis, dp, rank))``
   materializes exactly ``ceil(P_padded / dp)`` elements per rank for
   the master and every master-sized slot, for every rank, and the
   concatenation of all ranks' shards reassembles the padded master.
2. **Dense equivalence.** For ALL FIVE rules (Adam, LAMB, SGD,
   NovoGrad, Adagrad) two sharded updates on a CPU mesh match the dense
   update bitwise-close — including LAMB's per-tensor trust ratios and
   NovoGrad's per-tensor moments, whose leaf spans straddle shard
   boundaries (the lax.switch static-span machinery in
   ``optimizers.base``).
3. **shard_flat_grads.** pad + psum_scatter + dp-mean equals slicing
   the mean of the per-rank full grads.
4. **Shard-aware checkpointing.** The contrib shells' ``state_dict``
   reassembles the full flat master from the global view, and
   ``load_state_dict`` + ``shard_state`` restore it at a DIFFERENT dp
   with identical continuation.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.optimizers import functional as fopt
from apex_tpu.utils import cdiv


def _params(seed=0):
    rng = np.random.RandomState(seed)
    # total 409 (odd): every dp in {2, 4} pads, and the leaf spans
    # straddle shard boundaries
    return {"w0": jnp.asarray(rng.randn(13, 15) * 0.3, jnp.float32),
            "b0": jnp.asarray(rng.randn(15) * 0.01, jnp.float32),
            "w1": jnp.asarray(rng.randn(15, 11) * 0.3, jnp.float32),
            "b1": jnp.asarray(rng.randn(11) * 0.01, jnp.float32),
            "head": jnp.asarray(rng.randn(3), jnp.float32)}


def _numel(tree):
    return sum(int(x.size) for x in jax.tree.leaves(tree))


ALL_TX = [
    ("adam", lambda: fopt.fused_adam(lr=1e-2, weight_decay=0.01)),
    ("lamb", lambda: fopt.fused_lamb(lr=1e-2, weight_decay=0.01,
                                     max_grad_norm=1.0)),
    ("sgd", lambda: fopt.fused_sgd(lr=1e-2, momentum=0.9)),
    ("novograd", lambda: fopt.fused_novograd(lr=1e-2)),
    ("adagrad", lambda: fopt.fused_adagrad(lr=1e-2)),
]


@pytest.mark.parametrize("dp", [2, 4])
def test_shard_lengths_exact(dp):
    params = _params()
    n = _numel(params)
    padded = cdiv(n, dp) * dp
    shard_len = cdiv(padded, dp)
    tx = fopt.fused_adam(lr=1e-3)
    shards = []
    for rank in range(dp):
        st = tx.init(params, shard=("data", dp, rank))
        assert st.master.shape == (shard_len,), (rank, st.master.shape)
        for k, slot in st.slots.items():
            assert slot.shape == (shard_len,), (rank, k, slot.shape)
        assert st.shard == ("data", dp)
        assert st.shard_len == shard_len
        assert st.global_numel == n and st.padded_numel == padded
        shards.append(np.asarray(st.master))
    # concatenated shards == padded full master (zeros in the tail)
    full = np.concatenate(shards)
    flat, _ = jax.flatten_util.ravel_pytree(params)
    np.testing.assert_array_equal(full[:n], np.asarray(flat))
    np.testing.assert_array_equal(full[n:], 0.0)


@pytest.mark.parametrize("txname,mk", ALL_TX)
def test_sharded_update_matches_dense(txname, mk):
    dp = 4
    tx = mk()
    params = _params()
    n = _numel(params)
    padded = cdiv(n, dp) * dp
    g = jnp.asarray(np.random.RandomState(7).randn(n), jnp.float32) * 0.1

    st = tx.init(params)
    st = tx.update(st, g)
    st = tx.update(st, g * 0.5, noop_flag=0.0, grad_scale=2.0)
    dense = np.asarray(st.master)

    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))
    gpad = jnp.concatenate([g, jnp.zeros((padded - n,), g.dtype)])

    def body(gfull):
        st = tx.init(params, shard=("data", dp))
        rank = jax.lax.axis_index("data")
        gsh = jax.lax.dynamic_slice_in_dim(
            gfull, rank * (padded // dp), padded // dp)
        st = tx.update(st, gsh)
        st = tx.update(st, gsh * 0.5, noop_flag=0.0, grad_scale=2.0)
        return st.master

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P(),), out_specs=P("data")))(gpad)
    np.testing.assert_allclose(np.asarray(out)[:n], dense,
                               rtol=1e-6, atol=1e-6)


def test_sharded_noop_skip_freezes_shard():
    dp = 2
    tx = fopt.fused_lamb(lr=1e-2)
    params = _params()
    n = _numel(params)
    padded = cdiv(n, dp) * dp
    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))

    def body():
        st = tx.init(params, shard=("data", dp))
        before = st.master
        st = tx.update(st, jnp.ones((padded // dp,), jnp.float32),
                       noop_flag=1.0)
        return before, st.master, st.slots["exp_avg"]

    before, after, m = jax.jit(
        functools.partial(jax.shard_map, check_vma=False)(
            body, mesh=mesh, in_specs=(),
            out_specs=(P("data"), P("data"), P("data"))))()
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    np.testing.assert_array_equal(np.asarray(m), 0.0)


def test_shard_flat_grads_reduce_scatter_mean():
    dp = 4
    tx = fopt.fused_adam(lr=1e-3)
    params = _params()
    n = _numel(params)
    padded = cdiv(n, dp) * dp
    per_rank = jnp.asarray(
        np.random.RandomState(9).randn(dp, n), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))

    def body(granks):
        st = tx.init(params, shard=("data", dp))
        return fopt.shard_flat_grads(granks[0], st)

    out = jax.jit(functools.partial(jax.shard_map, check_vma=False)(
        body, mesh=mesh, in_specs=(P("data"),), out_specs=P("data")))(
        per_rank)
    want = np.zeros(padded, np.float32)
    want[:n] = np.asarray(per_rank).mean(axis=0)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6,
                               atol=1e-7)


def test_contrib_state_dict_reassembles_and_reshards():
    """Checkpoint at dp=4, restore at dp=2: the continuation matches the
    uninterrupted dense FusedAdam trajectory."""
    from apex_tpu.contrib.optimizers import DistributedFusedAdam
    from apex_tpu.optimizers import FusedAdam

    params = _params(3)
    n = _numel(params)
    g = jax.tree.map(lambda x: jnp.full_like(x, 0.02), params)

    def run_steps(opt, dp, state_in, n_steps):
        mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))

        def body(state):
            # the P("data") in_specs already sliced my local shard out
            # of the padded global view load_state_dict rebuilt
            if state is None:
                state = opt.init_state(params)
            for _ in range(n_steps):
                p, state = opt.step(state, g)
            return p, state

        specs = {"step": P(), "master": P("data"), "exp_avg": P("data"),
                 "exp_avg_sq": P("data")}
        if state_in is not None:
            return jax.jit(functools.partial(
                jax.shard_map, check_vma=False)(
                body, mesh=mesh, in_specs=(specs,), out_specs=(P(), specs)
            ))(state_in)
        return jax.jit(functools.partial(
            jax.shard_map, check_vma=False)(
            lambda: body(None), mesh=mesh, in_specs=(),
            out_specs=(P(), specs)))()

    opt4 = DistributedFusedAdam(4, lr=1e-2, weight_decay=0.01)
    _, state4 = run_steps(opt4, 4, None, 2)
    sd = opt4.state_dict(state4)
    assert sd["master"].shape == (n,)        # unpadded full reassembly

    opt2 = DistributedFusedAdam(2, lr=1e-2, weight_decay=0.01)
    opt2._record_layout(params)
    full2 = opt2.load_state_dict(sd)
    p_final, _ = run_steps(opt2, 2, full2, 1)

    ref = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    for _ in range(3):
        ref_p = ref.step(g)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5,
                                                atol=1e-6),
        p_final, ref_p)


def test_state_dict_rejects_single_shard():
    from apex_tpu.contrib.optimizers import DistributedFusedAdam

    params = _params(4)
    opt = DistributedFusedAdam(4, lr=1e-3)
    # before the optimizer has seen the layout: the crafted error, not
    # a TypeError from int(None)
    with pytest.raises(ValueError, match="before init_state"):
        opt.state_dict({"step": jnp.zeros((), jnp.int32)})
    opt._record_layout(params)
    shard_len = cdiv(cdiv(_numel(params), 4) * 4, 4)
    bogus = {"step": jnp.zeros((), jnp.int32),
             "master": jnp.zeros((shard_len,)),
             "exp_avg": jnp.zeros((shard_len,)),
             "exp_avg_sq": jnp.zeros((shard_len,))}
    with pytest.raises(ValueError, match="GLOBAL view"):
        opt.state_dict(bogus)
