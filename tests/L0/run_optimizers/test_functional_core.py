"""Functional optimizer core vs the class API.

The class optimizers are thin stateful shells over
``apex_tpu.optimizers.functional`` — these tests pin the contract: N
steps through either entry point are BITWISE identical, the state
formats are interchangeable through ``state_dict``, and a FlatState is
donation-safe and scan-carryable.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.optimizers import (
    FusedAdagrad, FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD, functional,
)
from apex_tpu.utils import tree_ravel

SIZES = ((37,), (16, 24), (5, 7, 3), (200,), (1,))


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {f"p{i}": jnp.asarray(rng.randn(*s), jnp.float32)
            for i, s in enumerate(SIZES)}


def _grads_seq(n, seed0=1):
    return [_params(seed0 + i) for i in range(n)]


def _flat(tree):
    return tree_ravel(tree)[0]


# (name, class ctor, transform, traced-hyper dict): the class wrapper
# feeds its hyperparameters as traced scalars (so LR schedules don't
# recompile) — bitwise parity therefore drives update the same way;
# baked-constant hyperparameters let XLA fold 1-ulp differently.
_PAIRS = [
    ("adam",
     lambda p: FusedAdam(p, lr=3e-3, weight_decay=0.05, betas=(0.8, 0.95)),
     functional.fused_adam(lr=3e-3, weight_decay=0.05, betas=(0.8, 0.95)),
     dict(lr=3e-3, beta1=0.8, beta2=0.95, eps=1e-8, weight_decay=0.05)),
    ("lamb",
     lambda p: FusedLAMB(p, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0),
     functional.fused_lamb(lr=1e-2, weight_decay=0.01, max_grad_norm=1.0),
     dict(lr=1e-2, beta1=0.9, beta2=0.999, eps=1e-6, weight_decay=0.01,
          max_grad_norm=1.0)),
    ("sgd",
     lambda p: FusedSGD(p, lr=0.05, momentum=0.9, weight_decay=0.01),
     functional.fused_sgd(lr=0.05, momentum=0.9, weight_decay=0.01),
     dict(lr=0.05, momentum=0.9, dampening=0.0, weight_decay=0.01)),
    ("novograd",
     lambda p: FusedNovoGrad(p, lr=1e-2, betas=(0.95, 0.98),
                             weight_decay=0.01),
     functional.fused_novograd(lr=1e-2, betas=(0.95, 0.98),
                               weight_decay=0.01),
     dict(lr=1e-2, beta1=0.95, beta2=0.98, eps=1e-8, weight_decay=0.01)),
    ("adagrad",
     lambda p: FusedAdagrad(p, lr=0.1, weight_decay=0.01),
     functional.fused_adagrad(lr=0.1, weight_decay=0.01),
     dict(lr=0.1, eps=1e-10, weight_decay=0.01)),
]


def _traced(hyper):
    return {k: jnp.asarray(v, jnp.float32) for k, v in hyper.items()}


@pytest.mark.parametrize("name,make_cls,tx,hyper", _PAIRS,
                         ids=[p[0] for p in _PAIRS])
def test_functional_matches_class_bitwise(name, make_cls, tx, hyper):
    """N steps through tx.init/tx.update == N steps through the class
    API, bit for bit (same kernels, same program)."""
    params = _params()
    opt = make_cls(params)
    st = tx.init(params)
    # noop_flag/grad_scale traced too: baked 0.0/1.0 constants fold the
    # skip-select away and let XLA fuse the final subtract into an FMA,
    # a 1-ulp divergence from the class program on a few elements
    upd = jax.jit(lambda s, g, nf, gs, hp: tx.update(
        s, g, noop_flag=nf, grad_scale=gs, **hp))
    out = params
    for g in _grads_seq(4):
        out = opt.step(g)
        st = upd(st, _flat(g), jnp.float32(0.0), jnp.float32(1.0),
                 _traced(hyper))
    np.testing.assert_array_equal(
        np.asarray(st.master), np.asarray(opt.param_groups[0].master))
    for k, v in opt.param_groups[0].state.items():
        np.testing.assert_array_equal(np.asarray(st.slots[k]),
                                      np.asarray(v))
    # and the materialized params round-trip identically
    for a, b in zip(jax.tree.leaves(st.params()), jax.tree.leaves(out)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("name,make_cls,tx,hyper", _PAIRS[:2],
                         ids=[p[0] for p in _PAIRS[:2]])
def test_noop_flag_and_grad_scale_parity(name, make_cls, tx, hyper):
    params = _params()
    g = _params(9)
    opt = make_cls(params)
    st = tx.init(params)
    upd = jax.jit(lambda s, gf, nf, gs, hp: tx.update(
        s, gf, noop_flag=nf, grad_scale=gs, **hp))
    # a noop-skipped step then a scaled step
    opt.step(g, noop_flag=1.0)
    st = upd(st, _flat(g), 1.0, 1.0, _traced(hyper))
    np.testing.assert_array_equal(np.asarray(st.master),
                                  np.asarray(opt.param_groups[0].master))
    opt.step(g, grad_scale=0.125)
    st = upd(st, _flat(g), 0.0, 0.125, _traced(hyper))
    np.testing.assert_array_equal(np.asarray(st.master),
                                  np.asarray(opt.param_groups[0].master))


def test_state_dict_roundtrip_through_init_update():
    """Functional slots ARE the class checkpoint format: pack a
    FlatState into a ``state_dict``, load it into a fresh class
    optimizer, and both continuations stay bitwise identical — and the
    reverse direction (class state_dict -> FlatState) too."""
    params = _params()
    tx = functional.fused_adam(lr=3e-3, weight_decay=0.05)
    hyper = dict(lr=3e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                 weight_decay=0.05)
    upd = jax.jit(lambda s, g, hp: tx.update(s, g, **hp))
    st = tx.init(params)
    for g in _grads_seq(2):
        st = upd(st, _flat(g), _traced(hyper))

    # functional -> class
    opt = FusedAdam(params, lr=3e-3, weight_decay=0.05)
    opt.load_state_dict({
        "step": int(st.count),
        "groups": [{"master": st.master, "state": dict(st.slots),
                    "options": dict(opt.param_groups[0].options)}],
    })
    g3 = _params(7)
    opt.step(g3)
    st = upd(st, _flat(g3), _traced(hyper))
    np.testing.assert_array_equal(np.asarray(st.master),
                                  np.asarray(opt.param_groups[0].master))

    # class -> functional
    sd = opt.state_dict()
    st2 = tx.init(params)
    st2 = st2.replace(
        master=jnp.asarray(sd["groups"][0]["master"]),
        count=jnp.asarray(sd["step"], jnp.float32),
        slots={k: jnp.asarray(v)
               for k, v in sd["groups"][0]["state"].items()})
    g4 = _params(8)
    opt.step(g4)
    st2 = upd(st2, _flat(g4), _traced(hyper))
    np.testing.assert_array_equal(np.asarray(st2.master),
                                  np.asarray(opt.param_groups[0].master))


def test_update_is_donation_safe():
    """jit(update, donate_argnums=(0,)) must run repeatedly without
    'donated buffer reused' errors — nothing in the state may be needed
    after the update consumes it."""
    params = _params()
    tx = functional.fused_lamb(lr=1e-2)
    st = tx.init(params)
    upd = jax.jit(tx.update, donate_argnums=(0,))
    with warnings.catch_warnings():
        # CPU ignores donation with a warning; the contract under test
        # is that repeated donated calls stay correct
        warnings.simplefilter("ignore")
        for g in _grads_seq(3):
            st = upd(st, _flat(g))
    assert np.all(np.isfinite(np.asarray(st.master)))
    assert float(st.count) == 3.0


def test_flat_state_is_scan_carryable():
    """update preserves the treedef (static layout fields included), so
    a FlatState scans — and the scanned run equals the step-by-step
    run exactly."""
    params = _params()
    tx = functional.fused_adam(lr=1e-3, weight_decay=0.01)
    gs = jnp.stack([_flat(g) for g in _grads_seq(5)])

    @jax.jit
    def scanned(st, gs):
        return jax.lax.scan(lambda s, g: (tx.update(s, g), s.count),
                            st, gs)

    st_scan, counts = scanned(tx.init(params), gs)
    st_seq = tx.init(params)
    upd = jax.jit(tx.update)
    for g in gs:
        st_seq = upd(st_seq, g)
    np.testing.assert_array_equal(np.asarray(st_scan.master),
                                  np.asarray(st_seq.master))
    assert float(st_scan.count) == 5.0


def test_init_from_flat_buffer():
    """init accepts an already-flat 1-D buffer (the bench legs' entry):
    one implicit leaf, no unravel."""
    flat = jnp.arange(64, dtype=jnp.float32)
    tx = functional.fused_adam(lr=1e-3)
    st = tx.init(flat)
    assert st.sizes == (64,) and st.unravel is None
    st = jax.jit(tx.update)(st, jnp.ones(64, jnp.float32))
    assert not np.allclose(np.asarray(st.master), np.asarray(flat))
    with pytest.raises(ValueError):
        st.params()


def test_mid_training_static_option_mutation_takes_effect():
    """torch idiom: mutating a group's options between steps — static
    knobs included — must affect the next step (the class wrapper
    rebuilds its transform from the live options every step)."""
    params = _params()
    g = _params(5)
    opt_mut = FusedAdam(params, lr=1e-3)
    opt_ref = FusedAdam(params, lr=1e-3)
    opt_mut.step(g)
    opt_ref.step(g)
    opt_mut.param_groups[0].options["bias_correction"] = False
    out_mut = opt_mut.step(g)
    out_ref = opt_ref.step(g)
    assert not np.array_equal(np.asarray(out_mut["p3"]),
                              np.asarray(out_ref["p3"]))


def test_sgd_noop_step_does_not_seed_momentum():
    """The first EFFECTIVE step seeds the momentum buffer: an
    overflow-skipped step 1 must leave 'seeded' at 0 so step 2 still
    clones the grad (torch semantics), in class and functional alike."""
    params = _params()
    g = _params(3)
    tx = functional.fused_sgd(lr=0.1, momentum=0.9)
    st = tx.init(params)
    upd = jax.jit(lambda s, gf, nf: tx.update(s, gf, noop_flag=nf))
    st = upd(st, _flat(g), 1.0)
    assert float(st.slots["seeded"]) == 0.0
    st = upd(st, _flat(g), 0.0)
    assert float(st.slots["seeded"]) == 1.0
    # parity with the class path under the same skip pattern
    opt = FusedSGD(params, lr=0.1, momentum=0.9)
    opt.step(g, noop_flag=1.0)
    opt.step(g)
    np.testing.assert_array_equal(np.asarray(st.master),
                                  np.asarray(opt.param_groups[0].master))
