"""ISSUE 8 acceptance: runtime telemetry instruments serving and
training WITHOUT violating the two sacred invariants — every
instrumented path keeps ONE donated executable per step (zero compiles
after warmup, recompile counters pinned 0), and zero host syncs are
added (device scalars resolve one step late; the serving brackets close
only around host reads the loop performs anyway).

Integration-level: real engine + scheduler serving N requests, real
flat-native training steps, real sinks on disk."""
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import observability as obs
from apex_tpu import train_step
from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.observability import (JsonlSink, MetricsRegistry,
                                    PrometheusSink, ServeTelemetry,
                                    TrainTelemetry, schema)
from apex_tpu.optimizers import functional
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider

N_REQUESTS = 5


@pytest.fixture(scope="module")
def engine():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    eng = InferenceEngine("gpt", cfg, params, slots=2, max_seq=64)
    # warm every executable (prefill bucket + decode) through a
    # throwaway scheduler so the measured waves below see a warm engine
    warm = SlotScheduler(eng, telemetry=ServeTelemetry(MetricsRegistry()))
    for i in range(3):
        warm.submit([1 + i, 2, 3], max_new_tokens=3)
    warm.run()
    return eng


# -- serving ---------------------------------------------------------------

def test_serve_n_requests_metric_consistency(engine, tmp_path):
    """The headline acceptance: N requests through the REAL engine —
    TTFT histogram count == N, recompile counter == 0, and the serve
    adds ZERO compiles to the warm executables (compile count still 1
    per program)."""
    reg = MetricsRegistry()
    jsonl = tmp_path / "telemetry.jsonl"
    prom = tmp_path / "metrics.prom"
    reg.add_sink(JsonlSink(str(jsonl)))
    reg.add_sink(PrometheusSink(str(prom)))
    tel = ServeTelemetry(reg)

    c0 = obs.compile_count()
    sched = SlotScheduler(engine, telemetry=tel)
    uids = [sched.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(N_REQUESTS)]
    out = sched.run()
    assert obs.compile_count() == c0, \
        "serving a wave on a warm engine must compile NOTHING"

    assert sorted(out) == sorted(uids)
    # metric consistency
    assert tel.ttft.count() == N_REQUESTS
    assert int(tel.recompiles.total()) == 0
    assert int(tel.admitted.total()) == N_REQUESTS
    assert int(tel.finished.total()) == N_REQUESTS
    assert int(tel.tokens_generated.total()) == \
        sum(len(v) for v in out.values())
    assert tel.decode_token_seconds.count() == \
        int(tel.decode_steps.total()) > 0
    c = tel.conservation()
    assert c["submitted"] == c["finished"] + c["active"] + c["rejected"]
    assert c["active"] == 0

    # JSONL stream: every lifecycle event present, schema-shaped
    events = [json.loads(ln) for ln in
              jsonl.read_text().splitlines()]
    by_kind: dict = {}
    for e in events:
        by_kind.setdefault(e["kind"], []).append(e)
    for kind in ("request_submit", "request_admit",
                 "request_first_token", "request_finish"):
        assert len(by_kind[kind]) == N_REQUESTS, kind
    for e in events:
        declared = schema.EVENT_FIELDS[e["kind"]]
        assert set(e) == {"ts", "kind"} | set(declared), e["kind"]
        for field, ftype in declared.items():
            v = e[field]
            if ftype == "int":
                assert isinstance(v, int) and not isinstance(v, bool)
            elif ftype == "float":
                assert isinstance(v, (int, float))
            elif ftype == "str":
                assert isinstance(v, str)
            elif ftype == "int|null":
                assert v is None or isinstance(v, int)
            elif ftype == "float|null":
                assert v is None or isinstance(v, (int, float))
            elif ftype == "bool":
                assert isinstance(v, bool)
    # TTFT values are physical (the scrub rule bench enforces on
    # captures holds at the source)
    for e in by_kind["request_first_token"]:
        assert 0 < e["ttft_s"] < 3600

    # Prometheus exposition lands on export
    reg.export()
    text = prom.read_text()
    assert f"serve_ttft_seconds_count {N_REQUESTS}" in text
    assert "serve_recompiles_total 0" in text
    assert 'serve_requests_finished_total{reason="length"} 5' in text


def test_trace_and_slo_armed_add_zero_compiles(engine, tmp_path,
                                               monkeypatch):
    """ISSUE 13 acceptance: a warm engine serving a wave with
    APEX_TPU_TRACE=1 and both SLO knobs armed adds ZERO compiles and
    keeps the recompile counter at 0 — tracing and SLO accounting are
    pure host bookkeeping.  The trace_span stream is schema-shaped,
    every trace closes terminal, and the SLO window published burn
    rates off the live histograms."""
    monkeypatch.setenv("APEX_TPU_TRACE", "1")
    monkeypatch.setenv("APEX_TPU_SLO_TTFT_US", "3600000000")
    monkeypatch.setenv("APEX_TPU_SLO_DECODE_US", "1")
    reg = MetricsRegistry()
    jsonl = tmp_path / "telemetry.jsonl"
    reg.add_sink(JsonlSink(str(jsonl)))
    tel = ServeTelemetry(reg)              # trace armed from the env
    assert tel.tracer.sample == 1

    c0 = obs.compile_count()
    sched = SlotScheduler(engine, telemetry=tel)   # SLO specs from env
    uids = [sched.submit([1 + i, 2, 3], max_new_tokens=3)
            for i in range(N_REQUESTS)]
    out = sched.run()
    assert obs.compile_count() == c0, \
        "tracing/SLO accounting must compile NOTHING on a warm engine"
    assert int(tel.recompiles.total()) == 0
    assert sorted(out) == sorted(uids)

    # span conservation at the wave boundary
    sc = tel.tracer.conservation()
    assert sc["started"] == sc["closed"] == N_REQUESTS
    assert sc["dangling"] == [] and sc["orphan_terminals"] == []

    # the JSONL stream carries schema-shaped trace spans for every uid
    events = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    spans = [e for e in events if e["kind"] == "trace_span"]
    declared = schema.EVENT_FIELDS["trace_span"]
    assert {e["uid"] for e in spans} == set(uids)
    for e in spans:
        assert set(e) == {"ts", "kind"} | set(declared)
    for uid in uids:
        names = [e["span"] for e in spans if e["uid"] == uid]
        assert names[0] == "queued" and names[-1] == "retired"
        assert "first_token" in names and "decode" in names

    # the wave boundary closed an SLO window: a 1h TTFT target is
    # never violated, a 1µs decode target always is — burn rates off
    # the same histograms the lifecycle methods fed
    assert sched.slo.burn_rate.value(slo="ttft_p99") == 0.0
    assert sched.slo.burn_rate.value(slo="decode_token_p99") == \
        pytest.approx(100.0)
    assert sched.slo.budget_remaining.value(slo="ttft_p99") == 1.0
    assert any(e["kind"] == "slo_violation"
               and e["slo"] == "decode_token_p99" for e in events)


def test_serve_telemetry_summary_shape(engine):
    tel = ServeTelemetry(MetricsRegistry())
    sched = SlotScheduler(engine, telemetry=tel)
    sched.submit([1, 2, 3], max_new_tokens=2)
    sched.run()
    s = tel.summary()
    assert s["requests"] == 1 and s["recompiles"] == 0
    assert s["ttft_p50_s"] > 0 and s["decode_token_p50_s"] > 0


# -- training --------------------------------------------------------------

def _make_params(seed=0, n_layers=2):
    rng = np.random.RandomState(seed)
    return {k: jnp.asarray(v, jnp.float32)
            for i in range(n_layers)
            for k, v in ((f"w{i}", rng.randn(8, 8) * 0.3),
                         (f"b{i}", rng.randn(8) * 0.01))}


def _loss_fn(params, batch):
    h = batch["x"]
    for i in range(len(params) // 2):
        h = jnp.tanh(h @ params[f"w{i}"] + params[f"b{i}"])
    return jnp.mean((h - batch["y"]) ** 2)


def _batches(n, seed=1):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 16, 8).astype(np.float32)
    return {"x": jnp.asarray(x),
            "y": jnp.tanh(jnp.asarray(x) @ jnp.ones((8, 8)) * 0.1)}


def test_instrumented_train_loop_zero_recompiles_and_parity():
    """The instrumented loop: same math as train_loop, ONE donated
    executable (steps after the first add zero compiles), loss gauge
    fed one step late through the deferred collector."""
    n = 6
    params = _make_params()
    tx = functional.fused_adam(lr=1e-2)
    tel = TrainTelemetry(MetricsRegistry())
    run = train_step.instrumented_train_loop(
        _loss_fn, tx, telemetry=tel, tokens_per_batch=16)

    state = train_step.init_train_state(tx, params, loss_scale="dynamic")
    state, metrics = run(state, _batches(n))
    losses = [float(m[0] if isinstance(m, tuple) else m)
              for m in metrics]

    assert int(tel.steps.total()) == n
    assert int(tel.recompiles.total()) == 0, \
        "instrumentation must not break the ONE-executable property"
    assert tel.step_seconds.count() == n
    assert tel.tokens_per_s.value() > 0
    # flush() drained the deferred collector: the loss gauge holds the
    # FINAL step's loss, the scale gauge the live dynamic scale
    assert tel.loss.value() == pytest.approx(losses[-1])
    assert tel.loss_scale.value() == float(state.scaler.loss_scale)
    assert int(tel.overflow_skips.total()) == 0

    # numerical parity with the scanned (uninstrumented) loop
    ref_state = train_step.init_train_state(tx, _make_params(),
                                            loss_scale="dynamic")
    ref_state, ref_losses = train_step.train_loop(_loss_fn, tx)(
        ref_state, _batches(n))
    np.testing.assert_allclose(losses, np.asarray(ref_losses).ravel(),
                               rtol=1e-6)


def test_instrumented_loop_arms_mfu_from_compiled_flops():
    """mfu_from_compiled=True (ISSUE 10): the gauge is priced from the
    COMPILED step's cost_analysis() FLOPs, and the one AOT compile at
    run start lands outside every step bracket — the recompile counter
    still pins 0."""
    params = _make_params()
    tx = functional.fused_adam(lr=1e-2)
    tel = TrainTelemetry(MetricsRegistry())
    run = train_step.instrumented_train_loop(
        _loss_fn, tx, telemetry=tel, tokens_per_batch=16,
        mfu_from_compiled=True)
    state = train_step.init_train_state(tx, params, loss_scale="dynamic")
    run(state, _batches(4))
    assert int(tel.recompiles.total()) == 0
    flops = tel.model_flops_per_step.value()
    assert flops is not None and flops > 0
    assert tel.mfu.value() is not None and tel.mfu.value() > 0
    # the badput decomposition settled at flush: buckets conserve the
    # run's wall clock (everything productive here — no overflow, no
    # recompile)
    g = tel.goodput()
    assert g["overflow_s"] == 0.0 and g["recompile_s"] == 0.0
    assert g["productive_s"] > 0 and g["wall_s"] > 0
    assert g["goodput_fraction"] == pytest.approx(
        g["productive_s"] / g["wall_s"])


def test_instrumented_loop_counts_overflow_skips():
    """found_inf reaches the overflow-skip counter one step late,
    through the deferred collector — never through a blocking read."""
    params = _make_params()
    tx = functional.fused_adam(lr=1e-2)

    def loss_fn(p, b):
        # poison = 0 -> clean loss; huge -> inf grads -> found_inf
        return _loss_fn(p, b) + jnp.sum(p["w0"]) * b["poison"]

    tel = TrainTelemetry(MetricsRegistry())
    run = train_step.instrumented_train_loop(loss_fn, tx, telemetry=tel)
    batches = dict(_batches(3),
                   poison=jnp.asarray([1e38, 0.0, 0.0], jnp.float32))
    state = train_step.init_train_state(tx, params, loss_scale="dynamic")
    scale0 = float(state.scaler.loss_scale)
    state, _ = run(state, batches)
    assert int(tel.overflow_skips.total()) == 1
    assert float(state.scaler.loss_scale) == scale0 * 0.5
    assert tel.loss_scale.value() == float(state.scaler.loss_scale)


def test_gauges_populate_exactly_one_step_late_mid_run():
    """The documented deferral is ONE step: after step k's
    observe_device, the gauges hold step k-1's scalars — without
    waiting for flush()."""
    tel = TrainTelemetry(MetricsRegistry())
    with tel.step():
        pass
    tel.observe_device(loss=jnp.float32(1.0))
    assert tel.loss.value() is None        # nothing strictly older yet
    with tel.step():
        pass
    tel.observe_device(loss=jnp.float32(2.0))
    assert tel.loss.value() == 1.0         # previous step, live mid-run
    with tel.step():
        pass
    tel.observe_device(loss=jnp.float32(3.0))
    assert tel.loss.value() == 2.0


def test_flush_resets_step_interval_chain():
    """Reusing one telemetry across runs: the idle gap between runs is
    never a step sample, AND the boundary-less warm first step of run 2
    publishes no timing at all (its bracket would be pure dispatch —
    the async artifact the interval scheme exists to avoid)."""
    import time as _time
    tel = TrainTelemetry(MetricsRegistry())
    for _ in range(2):
        with tel.step():
            pass
    assert tel.step_seconds.count() == 2   # cold bracket + interval
    tel.flush()                            # run boundary
    _time.sleep(0.25)                      # eval/checkpoint idle gap
    with tel.step():
        pass                               # warm, boundary-less: no sample
    assert tel.step_seconds.count() == 2
    assert int(tel.steps.total()) == 3     # still counted as a step
    with tel.step():
        pass                               # boundary restored: interval
    assert tel.step_seconds.count() == 3
    assert tel.step_seconds.sum() < 0.25, \
        "the inter-run idle gap leaked into a step sample"


def test_train_jsonl_events(tmp_path):
    reg = MetricsRegistry()
    reg.add_sink(JsonlSink(str(tmp_path / "t.jsonl")))
    tel = TrainTelemetry(reg)
    tx = functional.fused_adam(lr=1e-2)
    run = train_step.instrumented_train_loop(_loss_fn, tx, telemetry=tel)
    state = train_step.init_train_state(tx, _make_params(),
                                        loss_scale="dynamic")
    run(state, _batches(3))
    events = [json.loads(ln) for ln in
              (tmp_path / "t.jsonl").read_text().splitlines()]
    steps = [e for e in events if e["kind"] == "train_step"]
    assert [e["step"] for e in steps] == [0, 1, 2]
    assert all(e["recompiled"] is False for e in steps)
    assert all(e["seconds"] > 0 for e in steps)


# -- env-knob configuration -------------------------------------------------

def test_configure_from_env_attaches_sinks(tmp_path, monkeypatch):
    monkeypatch.setenv("APEX_TPU_TELEMETRY", str(tmp_path / "obsdir"))
    reg = MetricsRegistry()
    obs.configure_from_env(reg)
    kinds = {type(s).__name__ for s in reg.sinks}
    assert kinds == {"JsonlSink", "PrometheusSink"}
    reg.declared("train_steps_total").inc()
    reg.export()
    assert (tmp_path / "obsdir" / "metrics.prom").exists()


def test_telemetry_knob_off_means_no_sinks(monkeypatch):
    monkeypatch.setenv("APEX_TPU_TELEMETRY", "0")
    reg = MetricsRegistry()
    obs.configure_from_env(reg)
    assert reg.sinks == ()
