"""Tier-1 guard (ISSUE 18): host-tier swap traffic is FIXED-WIDTH copy
dispatch, not a program change — machine-checked, not claimed.

1. A warm paged engine with the host tier armed, driven through
   evict-to-host -> swap-out -> hit -> swap-in churn, triggers ZERO
   new XLA compiles: both swap directions run ONE fixed-width
   executable each (shorter batches pad with the trash page / an OOB
   drop sentinel), so no page count, batch remainder, or tier state
   can mint a new program.
2. The refcount books balance through the churn: allocator page
   conservation, the host-tier mirror (prefix host_pages == store
   pages), and no page resident in both tiers at once.
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu.inference import InferenceEngine, SlotScheduler
from apex_tpu.observability import MetricsRegistry, ServeTelemetry
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.testing import GPTConfig, gpt_model_provider


def _engine():
    parallel_state.destroy_model_parallel()
    parallel_state.initialize_model_parallel(1)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_attention_heads=2, max_seq_length=64,
                    hidden_dropout=0.0, attention_dropout=0.0)
    model = gpt_model_provider(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))
    return InferenceEngine("gpt", cfg, params, slots=2, max_seq=64,
                           page_size=8, num_pages=16,
                           host_tier_bytes=1 << 20)


def test_warm_swap_churn_adds_zero_compiles():
    eng = _engine()
    sched = SlotScheduler(eng,
                          telemetry=ServeTelemetry(MetricsRegistry()))
    prefix = list((np.arange(16) * 5 + 2) % 64)

    def wave(prompts):
        for p in prompts:
            sched.submit(p, max_new_tokens=3)
        return sched.run()

    # warm EVERY program the measured churn uses: the cold full-prompt
    # bucket + decode, then evict (compiles the swap-out gather), then
    # a hit on the swapped-out prefix (compiles the swap-in scatter +
    # the suffix bucket), then evict again so the measured wave starts
    # from the same swapped-out state
    wave([prefix + [1, 2]])
    assert sched.prefix.evict_lru(eng.num_pages) > 0
    assert sched.host_store.pages > 0
    wave([prefix + [1, 2]])
    assert int(sched.telemetry.swap_in_pages.total()) > 0
    sched.prefix.evict_lru(eng.num_pages)
    assert sched.host_store.pages > 0

    events = []
    from jax._src import monitoring as _mon
    saved = {attr: list(getattr(_mon, attr))
             for attr in dir(_mon)
             if attr.endswith("_listeners")
             and isinstance(getattr(_mon, attr), list)}
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    try:
        # measured churn: hit the swapped-out prefix (swap-in), evict
        # it back out (swap-out), hit again — two full round trips,
        # different batch remainders than the warmup, all warm
        out1 = wave([prefix + [1, 2], prefix + [9]])
        sched.prefix.evict_lru(eng.num_pages)
        out2 = wave([prefix + [1, 2]])
    finally:
        for attr, listeners in saved.items():
            getattr(_mon, attr)[:] = listeners
    assert all(len(v) == 3 for v in out1.values())
    assert all(len(v) == 3 for v in out2.values())
    compiles = [e for e in events if "compile_requests" in e]
    assert not compiles, compiles

    tel = sched.telemetry
    assert int(tel.recompiles.total()) == 0
    assert int(tel.swap_in_pages.total()) >= 4
    assert int(tel.swap_out_pages.total()) >= 4
    assert int(tel.prefix_host_hits.total()) >= 3

    # books: allocator conservation + the host-tier mirror, and the
    # two tiers are disjoint (a page id pinned in HBM never doubles as
    # a host-resident slab)
    al = sched.alloc
    assert al.live_pages + al.free_pages == al.num_pages
    assert sched.prefix.host_pages == sched.host_store.pages
