"""Comm/compute overlap (ISSUE 7): overlap on/off is a SCHEDULING
change only, machine-checked from every side —

1. ZeRO layered prefetch == monolithic gather numerically (bitwise for
   Adam at any dp — per-span psum_scatter sums the same two/four
   operands elementwise; <= 2e-6 for LAMB at dp=4, whose per-leaf norm
   partials regroup across ranks), dp in {2, 4};
2. chunked TP row/column == fused psum (<= 2e-6; bitwise at tp=2 where
   two-term addition commutes) at 2 and 4 chunks;
3. comm BYTES are identical overlap on/off for all three hot paths
   (the APX215 zero-growth acceptance, asserted directly on
   ``comm_report`` so it holds at this test's shapes, not just the
   audit fixture's);
4. the overlapped zero step still compiles to ONE donated executable
   (compile-event counting — the overlap must not split the program);
5. DDP leaf-bucket overlap: bucketed == delayed bitwise, and no
   whole-tree ravel concatenate gates the bucket psums;
6. the registered overlapped executables audit clean (APX217 + the
   re-pinned ledger) — the acceptance criteria in one place.
"""
import functools
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

from apex_tpu import train_step
from apex_tpu.analysis.comm_model import comm_report
from apex_tpu.optimizers import functional
from apex_tpu.utils import tree_ravel

shard_map = functools.partial(jax.shard_map, check_vma=False)


@pytest.fixture(autouse=True)
def _restore_parallel_state():
    """The TP helpers initialize a tp=2 topology; leaving it behind
    poisons later suites' audits (they trace ops under the wrong
    world)."""
    yield
    from apex_tpu.transformer import parallel_state
    parallel_state.destroy_model_parallel()


def _params(n_layers=8, d=8, seed=0):
    rng = np.random.RandomState(seed)
    out = {}
    for i in range(n_layers):
        out[f"w{i}"] = jnp.asarray(rng.randn(d, d) * 0.3, jnp.float32)
        out[f"b{i}"] = jnp.asarray(rng.randn(d) * 0.01, jnp.float32)
    return out


def _loss(p, batch):
    h = batch["x"]
    for i in range(sum(1 for k in p if k.startswith("w"))):
        h = jnp.tanh(h @ p[f"w{i}"] + p[f"b{i}"])
    return jnp.mean((h - batch["y"]) ** 2)


def _batch(n=16, d=8, seed=1):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(n, d), jnp.float32)
    return {"x": x, "y": jnp.tanh(x @ jnp.ones((d, d)) * 0.1)}


def _zero_run(tx, params, batch, dp, prefetch, steps=3):
    """steps of the zero step; returns (losses, final params pytree)."""
    mesh = Mesh(np.array(jax.devices()[:dp]), ("data",))
    state, specs = train_step.init_zero_train_state(
        tx, params, "data", dp, loss_scale="dynamic", prefetch=prefetch)
    step = train_step.make_train_step(_loss, tx, zero=True)

    def body(st, b):
        losses = []
        for _ in range(steps):
            st, l = step(st, b)
            losses.append(l)
        return st, jnp.stack(losses)

    st, losses = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(specs, P()),
        out_specs=(specs, P())))(state, batch)
    return np.asarray(losses), st.params()


@pytest.mark.parametrize("dp", [2, 4])
def test_zero_prefetch_matches_monolithic_adam_bitwise(dp):
    params, batch = _params(), _batch()
    tx = functional.fused_adam(lr=1e-2, weight_decay=0.01)
    ref_losses, ref_params = _zero_run(tx, params, batch, dp, prefetch=0)
    for prefetch in (8, 5):          # per-layer spans + uneven grouping
        losses, out = _zero_run(tx, params, batch, dp, prefetch=prefetch)
        np.testing.assert_array_equal(losses, ref_losses)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), out, ref_params)


@pytest.mark.parametrize("dp", [2, 4])
def test_zero_prefetch_matches_monolithic_lamb(dp):
    """LAMB's per-leaf trust-ratio partial sums regroup across ranks
    under the span layout — bitwise at dp=2 (two-term adds commute),
    <= 2e-6 beyond."""
    params, batch = _params(), _batch()
    tx = functional.fused_lamb(lr=1e-2, weight_decay=0.01)
    ref_losses, ref_params = _zero_run(tx, params, batch, dp, prefetch=0)
    losses, out = _zero_run(tx, params, batch, dp, prefetch=8)
    tol = 0.0 if dp == 2 else 2e-6
    np.testing.assert_allclose(losses, ref_losses, rtol=0, atol=tol)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=0, atol=tol),
        out, ref_params)


def test_zero_prefetch_comm_bytes_identical():
    """APX215 zero-growth, asserted structurally: the per-span gathers
    move exactly the monolithic gather's bytes (and the per-span
    scatters the monolithic scatter's), here at a shape where every
    span pads."""
    params, batch = _params(n_layers=5), _batch()
    tx = functional.fused_adam(lr=1e-2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))

    def traced(prefetch):
        state, specs = train_step.init_zero_train_state(
            tx, params, "data", 2, loss_scale="dynamic",
            prefetch=prefetch)
        step = train_step.make_train_step(_loss, tx, zero=True)
        return comm_report(jax.make_jaxpr(shard_map(
            step, mesh=mesh, in_specs=(specs, P()),
            out_specs=(specs, P())))(state, batch), {"data": 2}), \
            len(state.opt.spans)

    (mono, _), (spans, n_spans) = traced(0), traced(5)
    assert spans["by_collective"]["all_gather@data"] == \
        mono["by_collective"]["all_gather@data"]
    assert spans["by_collective"]["reduce_scatter@data"] == \
        mono["by_collective"]["reduce_scatter@data"]
    assert spans["total_bytes"] == mono["total_bytes"]
    # and the pipeline is real: one gather per span, not one total
    assert n_spans > 1
    assert spans["counts"]["all_gather@data"] == n_spans
    assert mono["counts"]["all_gather@data"] == 1


def test_zero_prefetch_step_compiles_one_donated_executable():
    """Overlap must not split the ONE-donated-executable invariant:
    compile-event counting (auditor-independent, same probe as
    test_zero_train_step)."""
    params, batch = _params(), _batch()
    tx = functional.fused_adam(lr=1e-2)
    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    state, specs = train_step.init_zero_train_state(
        tx, params, "data", 2, loss_scale="dynamic", prefetch=8)
    zstep = train_step.make_train_step(_loss, tx, zero=True)
    sharded = shard_map(zstep, mesh=mesh, in_specs=(specs, P()),
                        out_specs=(specs, P()))
    from jax.sharding import NamedSharding
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        state, specs)
    step = jax.jit(sharded, donate_argnums=(0,))
    batch = jax.device_put(batch)

    events = []
    from jax._src import monitoring as _mon
    saved = {attr: list(getattr(_mon, attr))
             for attr in dir(_mon)
             if attr.endswith("_listeners")
             and isinstance(getattr(_mon, attr), list)}
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    try:
        jax.jit(lambda x: x * 2)(jnp.ones(3)).block_until_ready()
        jax.clear_caches()
        events.clear()
        jax.block_until_ready(step(state, batch))
        n = sum(1 for e in events if "compile_requests" in e)
        assert n == 1, n
    finally:
        for attr, listeners in saved.items():
            getattr(_mon, attr)[:] = listeners


# --- TP chunked ring pipelines ----------------------------------------------

def _tp_run(chunks, fused=False, tokens=8):
    from apex_tpu.transformer import parallel_state as ps
    from apex_tpu.transformer import tensor_parallel

    ps.destroy_model_parallel()
    ps.initialize_model_parallel(tensor_model_parallel_size_=2)
    mesh = ps.get_mesh()
    col = tensor_parallel.ColumnParallelLinear(
        8, 16, gather_output=False, bias=False, overlap_chunks=chunks,
        gradient_accumulation_fusion=fused)
    row = tensor_parallel.RowParallelLinear(
        16, 8, input_is_parallel=True, bias=False,
        overlap_chunks=chunks, gradient_accumulation_fusion=fused)

    def body(x):
        pc = col.init(jax.random.key(0), x)
        h, _ = col.apply(pc, x)
        pr = row.init(jax.random.key(1), h)

        def loss(x, pc, pr):
            h, _ = col.apply(pc, x)
            y, _ = row.apply(pr, h)
            return jnp.mean(y ** 2)

        return jax.value_and_grad(loss, argnums=(0, 1, 2))(x, pc, pr)

    fn = shard_map(body, mesh=mesh, in_specs=(P(),),
                   out_specs=(P(), (P(), P(), P())))
    x = jnp.asarray(np.linspace(-1, 1, tokens * 8,
                                dtype=np.float32).reshape(tokens, 8))
    return jax.jit(fn)(x), fn, x


@pytest.mark.parametrize("chunks", [2, 4])
@pytest.mark.parametrize("fused", [False, True])
def test_tp_chunked_matches_fused_psum(chunks, fused):
    (ref_l, ref_g), _, _ = _tp_run(1, fused=fused)
    (l, g), _, _ = _tp_run(chunks, fused=fused)
    # tp=2: every ring sum is two-term -> bitwise; keep the 2e-6
    # ceiling the reordering bound promises anyway
    np.testing.assert_allclose(np.asarray(l), np.asarray(ref_l),
                               rtol=0, atol=2e-6)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(ref_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=2e-6)


def test_tp_chunked_comm_bytes_equal_fused():
    """The ring decomposition moves exactly the fused psums' ring
    bytes: (chunks serialized hops of B/chunks) + the all-gather half
    == 2(n-1)/n * B per psum replaced."""
    _, fn1, x = _tp_run(1)
    rep1 = comm_report(jax.make_jaxpr(fn1)(x), {"tensor": 2})
    for chunks in (2, 4):
        _, fnc, x = _tp_run(chunks)
        repc = comm_report(jax.make_jaxpr(fnc)(x), {"tensor": 2})
        assert repc["total_bytes"] == rep1["total_bytes"], chunks
        assert "psum@tensor" not in repc["by_collective"]
        assert repc["by_collective"]["ppermute@tensor"] > 0
        assert repc["by_collective"]["all_gather@tensor"] > 0


# --- DDP leaf-bucket overlap ------------------------------------------------

def test_ddp_bucketed_matches_delayed_and_overlaps():
    from apex_tpu.parallel.distributed import DistributedDataParallel

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    rng = np.random.RandomState(0)
    grads = {f"w{i}": jnp.asarray(rng.randn(16, 16), jnp.float32)
             for i in range(6)}
    grads.update({f"b{i}": jnp.asarray(rng.randn(16), jnp.float32)
                  for i in range(6)})

    def run(ddp):
        return jax.jit(shard_map(
            lambda g: ddp.reduce_gradients(g), mesh=mesh,
            in_specs=(P(),), out_specs=P()))(grads)

    ref = run(DistributedDataParallel(axis_name="data",
                                      delay_allreduce=True))
    out = run(DistributedDataParallel(axis_name="data",
                                      message_size=4096))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), ref, out)

    # structural overlap property: the bucketed path has NO whole-tree
    # concatenate (each bucket's psum depends only on its own leaves)
    # and >= 2 psums, at the delayed path's exact byte total
    ddp = DistributedDataParallel(axis_name="data", message_size=4096)
    jaxpr = jax.make_jaxpr(shard_map(
        lambda g: ddp.reduce_gradients(g), mesh=mesh,
        in_specs=(P(),), out_specs=P()))(grads)
    n_total = sum(int(np.prod(v.shape)) for v in grads.values())

    def eqns(j):
        j = getattr(j, "jaxpr", j)
        for e in j.eqns:
            yield e
            for v in e.params.values():
                for s in (v if isinstance(v, (list, tuple)) else [v]):
                    if hasattr(s, "eqns") or hasattr(s, "jaxpr"):
                        yield from eqns(s)

    full_concat = [e for e in eqns(jaxpr)
                   if e.primitive.name == "concatenate"
                   and e.outvars[0].aval.size >= n_total]
    assert not full_concat, \
        "bucketed DDP still ravels the whole tree before any psum"
    rep = comm_report(jaxpr, {"data": 2})
    assert rep["counts"]["psum@data"] >= 2
    ddp_delay = DistributedDataParallel(axis_name="data",
                                        delay_allreduce=True)
    rep_delay = comm_report(jax.make_jaxpr(shard_map(
        lambda g: ddp_delay.reduce_gradients(g), mesh=mesh,
        in_specs=(P(),), out_specs=P()))(grads), {"data": 2})
    assert rep["total_bytes"] == rep_delay["total_bytes"]


# --- the registered overlapped executables (acceptance criteria) ------------

def test_registered_overlap_executables_audit_clean():
    """APX217 confirms overlap on the registered zero + TP executables
    (it runs as part of their audit and emits nothing), the ledger
    matches the committed budget bit-for-bit, and the ZeRO comm
    identity survives the span decomposition."""
    import json

    from apex_tpu.analysis.cli import repo_root
    from apex_tpu.analysis.spmd_audit import (BUDGET_NAME, exec_specs,
                                              run_spmd_audit)

    flagged = {s.name for s in exec_specs() if s.check_overlap}
    # PR 17 adds the tp-sharded fused decode step to the overlap set
    assert flagged == {"train_step_zero", "tp_column_row",
                       "inference_decode_fused_paged_tp2"}
    findings, report = run_spmd_audit(execs=sorted(flagged))
    assert findings == [], [(f.rule, f.message) for f in findings]
    committed = json.loads(
        (repo_root() / BUDGET_NAME).read_text())["executables"]
    for name in flagged:
        assert report["executables"][name] == committed[name], name
    zero = report["executables"]["train_step_zero"]
    assert zero["rs_ag_equals_ar"] is True
    assert zero["collective_counts"]["all_gather@data"] > 1
