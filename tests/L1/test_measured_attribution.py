"""ISSUE 14 acceptance: a CPU-driven leg with ``APEX_TPU_PROFILE_DIR``
armed stamps the MEASURED attribution into its capture — category
times summing to the window within the documented tolerance, the
measured-vs-``comm_model`` exposed-comm comparison under
``measured:trace`` provenance — and a run with no trace present stamps
the explicit ``unavailable:`` marker, never zeros."""
import os
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import bench
from apex_tpu.observability.attribution import COVERAGE_TOLERANCE
from apex_tpu.observability.tracing import profile_capture


@pytest.fixture
def captured_leg(tmp_path, monkeypatch):
    """A real (tiny) CPU-profiled leg: a few dispatches of a jitted
    matmul chain under profile_capture, exactly the bench bracket."""
    prof = tmp_path / "prof"
    monkeypatch.setenv("APEX_TPU_PROFILE_DIR", str(prof))

    @jax.jit
    def step(x, w):
        return jnp.tanh(x @ w) @ w.T

    x = jnp.ones((128, 128))
    w = jnp.ones((128, 128))
    with profile_capture(tag="bench_main_fused") as started:
        if not started:
            pytest.skip("profiler unavailable in this process")
        for _ in range(3):
            x = step(x, w)
        jax.block_until_ready(x)
    return str(prof)


def test_cpu_leg_stamps_measured_attribution(captured_leg):
    extras = {"chip": "cpu", "compiled_flops": 2 * 128 ** 3 * 2,
              "exposed_comm_model_us": 0.0}
    bench._stamp_measured_attribution(extras, captured_leg, steps=3)
    assert extras["measured_attribution_provenance"] == "measured:trace"
    assert extras["measured_window_us"] > 0
    assert extras["measured_step_us"] == pytest.approx(
        extras["measured_window_us"] / 3)
    assert extras["measured_compute_us"] > 0
    # single-chip CPU leg: no collectives observed -> no fabricated
    # zero-valued _us stamp (the hygiene scrub would drop it anyway)
    assert "measured_exposed_comm_us" not in extras
    # model prediction is 0 (no collectives in the jaxpr): the ratio is
    # undefined, so no drift stamp either — absence, not a made-up 1.0
    assert "exposed_comm_drift_ratio" not in extras
    # measured MFU landed from compiled FLOPs / measured compute time
    assert 0 < extras.get("measured_mfu", 0) <= 1.0

    # acceptance arithmetic: the attributed category times + host gap
    # sum to the measured window within the documented tolerance
    from apex_tpu.observability.attribution import attribute
    from apex_tpu.observability.trace_ingest import load_profile_dirs
    rec = attribute(load_profile_dirs([captured_leg]), steps=3)
    total = sum(rec["categories"].values()) + rec["host_gap_us"]
    assert total == pytest.approx(rec["window_us"],
                                  rel=COVERAGE_TOLERANCE)


def test_model_comparison_rides_measured_provenance(captured_leg):
    """When the comm model DID predict exposed comm (the ZeRO/TP
    legs), the measured-vs-model comparison lands in the attribution
    RECORD — but a 0.0 ratio is withheld from the capture stamp: it
    would become the watch's unbeatable best-prior (ratio vs 0 is
    None, so the series could never regress again)."""
    from apex_tpu.observability.attribution import attribute
    from apex_tpu.observability.trace_ingest import load_profile_dirs
    rec = attribute(load_profile_dirs([captured_leg]), steps=3,
                    model_exposed_comm_us=12.5)
    assert rec["provenance"] == "measured:trace"
    # measured exposure is 0 on one chip -> the honest 0.0 ratio is in
    # the record (and the attribution JSONL event)...
    assert rec["exposed_comm_drift_ratio"] == 0.0
    # ...but NOT in the capture stamp
    extras = {"chip": "cpu", "exposed_comm_model_us": 12.5}
    bench._stamp_measured_attribution(extras, captured_leg, steps=3)
    assert extras["measured_attribution_provenance"] == "measured:trace"
    assert "exposed_comm_drift_ratio" not in extras


def test_no_trace_stamps_unavailable_marker(tmp_path):
    """The degradation face of the acceptance criterion: an armed dir
    with no trace yields the explicit unavailable: marker in the
    capture stamp — and no numeric measured fields at all."""
    empty = tmp_path / "never_captured"
    empty.mkdir()
    extras = {"chip": "cpu", "compiled_flops": 1000}
    bench._stamp_measured_attribution(extras, str(empty), steps=3)
    assert extras["measured_attribution_provenance"] == \
        "unavailable:no-trace-files"
    for key in list(extras):
        assert not key.startswith("measured_w"), key
    assert "measured_step_us" not in extras
    assert "measured_mfu" not in extras
    assert "exposed_comm_drift_ratio" not in extras
